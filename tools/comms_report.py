"""Render a strategy's compile-time collective inventory as a table.

    python tools/comms_report.py --strategy dp
    python tools/comms_report.py --strategy zero3 --mesh 2x4
    python tools/comms_report.py --strategy dp,zero3 --check   # CI gate
    python tools/comms_report.py --all --json

No accelerator is involved anywhere: the strategy's train step is
lowered on a fake CPU mesh (``--xla_force_host_platform_device_count``)
and the inventory is read off the optimized HLO — see
``ddl25spring_tpu/obs/xla_analytics.py``.  With ``--check`` the exit
code is non-zero when any strategy's measured collectives violate its
declared analytic signature (the comms-regression pin CI runs), or when
a requested strategy fails to compile at all.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from ddl25spring_tpu.utils.metrics import fmt_bytes as _fmt_bytes  # noqa: E402
from ddl25spring_tpu.utils.platform import ensure_cpu_tools_env  # noqa: E402

# CPU-only with a multi-device fake host — must be decided before the
# first jax backend init (this image registers a TPU plugin at
# interpreter start, hence also the config route in main()).
ensure_cpu_tools_env()


def format_strategy_report(r: dict) -> str:
    """The human table for one strategy's compile report."""
    name = r.get("strategy", "?")
    mesh = r.get("mesh", {})
    mesh_s = ", ".join(f"{k}={v}" for k, v in mesh.items())
    lines = [f"strategy: {name}   mesh({mesh_s})   "
             f"lowered: {r.get('lowered', '?')}"]
    if "error" in r:
        lines.append(f"  FAILED to compile on this jax: {r['error']}")
        return "\n".join(lines)

    cols = (f"  {'collective':<20}{'sites':>6}{'execs':>7}"
            f"{'payload':>12}{'wire est':>12}  axes")
    lines.append(cols)
    lines.append("  " + "-" * (len(cols) - 2))
    ops = r["collectives"]["ops"]
    totals = r["collectives"]["totals"]
    for kind in sorted(totals):
        t = totals[kind]
        axes = sorted({
            ax for o in ops if o["kind"] == kind for ax in (o["axes"] or [])
        })
        unknown = any(not o["trip_known"] for o in ops if o["kind"] == kind)
        lines.append(
            f"  {kind:<20}{t['sites']:>6}{t['count']:>7}"
            f"{_fmt_bytes(t['result_bytes']):>12}"
            f"{_fmt_bytes(t['wire_bytes']):>12}  "
            + (",".join(axes) or "?")
            + ("  (loop trip unknown)" if unknown else "")
        )
    if not totals:
        lines.append("  (no collectives — single-shard program)")

    meta = r.get("meta") or {}
    if meta.get("n_buckets") is not None:
        lines.append(
            f"  flat-bucket packing: {meta['n_buckets']} bucket(s) over "
            f"{meta.get('n_param_leaves', '?')} param leaves"
        )
    mem = r.get("memory")
    if mem:
        lines.append(
            f"  peak HBM est/chip: {_fmt_bytes(mem['peak_hbm_bytes'])} "
            f"(args {_fmt_bytes(mem.get('argument_size_in_bytes', 0))}, "
            f"temps {_fmt_bytes(mem.get('temp_size_in_bytes', 0))}, "
            f"out {_fmt_bytes(mem.get('output_size_in_bytes', 0))})"
        )
    don = r.get("donation") or {}
    saved = don.get("hbm_saved_bytes", 0)
    if saved:
        lines.append(f"  donated (aliased in place): {_fmt_bytes(saved)}/chip")
    elif r.get("lowered") != "train_step":
        lines.append(f"  donated (aliased in place): n/a — lowers "
                     f"{r.get('lowered', '?')}, no aliasable outputs")
    elif not mem or "alias_size_in_bytes" not in mem:
        lines.append("  donated (aliased in place): unknown — no aliasing "
                     "stats on this backend")
    else:
        lines.append("  donated (aliased in place): none — step compiled "
                     "undonated")
    if r.get("flops"):
        lines.append(f"  flops/step (cost analysis): {r['flops']:.3e}")
    proj = r.get("projection") or {}
    for chip, p in proj.items():
        lines.append(
            f"  projected on {chip}: step {p['projected_step_s'] * 1e6:.1f} us "
            f"({p['bound']}-bound), MFU {p['projected_mfu']:.3f}"
        )
    lines.append("  " + _sched_cell(r))
    viols = r.get("signature_violations")
    if viols:
        lines.append("  SIGNATURE VIOLATIONS:")
        lines.extend(f"    - {v}" for v in viols)
    elif r.get("expected"):
        lines.append("  signature: OK (matches the declared analytic "
                     "collective signature)")
    lines.append("  " + _findings_cell(r))
    return "\n".join(lines)


def _sched_cell(r: dict) -> str:
    """The static-schedule column: the analytical overlap ceiling +
    window accounting from the sched verifier (analysis/sched.py) —
    the per-strategy slack the noise-bound wall-clock A/B cannot
    resolve."""
    s = r.get("sched")
    if not s:
        return "sched: not analyzed"
    if s.get("error"):
        return f"sched: analysis degraded ({s['error']})"
    bound = s.get("static_overlap_bound")
    scalar = s.get("scalar_bytes", 64)
    windows = [
        w for w in s.get("slack") or [] if w["result_bytes"] > scalar
    ]
    slack_flops = sum(w["slack_flops"] for w in windows)
    cell = (
        "sched: no non-scalar collectives to overlap" if not windows
        else (
            f"sched: static overlap bound "
            f"{bound:.4f} on {s.get('ref_chip', '?')} "
            f"({s.get('discipline')} issue, {len(windows)} window(s), "
            f"{slack_flops:.3g} independent FLOPs)"
        )
    )
    hz = s.get("hazards") or []
    if hz:
        cell += f"  DEADLOCK HAZARDS: {len(hz)} — see graft_lint H009"
    return cell


def _findings_cell(r: dict) -> str:
    """The hazard-findings column: count + worst severity, sourced from
    the static analyzer (``ddl25spring_tpu/analysis``; run per strategy
    by ``compile_strategy`` and in full by ``tools/graft_lint.py``)."""
    if r.get("lint_error"):
        return f"hazards: lint degraded ({r['lint_error']})"
    if "findings" not in r:
        return "hazards: not analyzed (lint=False)"
    from ddl25spring_tpu.analysis.engine import summarize

    s = summarize(r["findings"])
    if not s["findings"]:
        return "hazards: none"
    cell = f"hazards: {s['unwaived']} unwaived"
    if s["worst"]:
        cell += f" (worst {s['worst']})"
    if s["waived"]:
        cell += f", {s['waived']} waived"
    rules = ",".join(sorted(s["by_rule"]))
    return f"{cell} [{rules}] — see python -m tools.graft_lint"


def main(argv=None) -> int:
    import argparse
    import json

    import jax

    # env alone is too late on images whose sitecustomize registers a TPU
    # plugin at interpreter start; the config call forces CPU regardless
    jax.config.update("jax_platforms", "cpu")

    from ddl25spring_tpu.obs.compile_report import (
        DEFAULT_STRATEGIES,
        build_compile_report,
        parse_mesh_arg,
    )

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--strategy", default=None,
                    help="strategy name(s), comma-separated "
                         f"(known: {', '.join(DEFAULT_STRATEGIES)})")
    ap.add_argument("--all", action="store_true",
                    help="report every registered strategy")
    ap.add_argument("--mesh", default=None,
                    help="mesh sizes like 2x4 (positional onto the "
                         "strategy's axis names; extras fold into the "
                         "last axis)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw report JSON instead of the table")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on any signature violation or "
                         "compile failure (the CI comms-regression gate)")
    args = ap.parse_args(argv)

    if args.all or not args.strategy:
        names = list(DEFAULT_STRATEGIES) if args.all else ["dp"]
    else:
        names = [s.strip() for s in args.strategy.split(",") if s.strip()]
    mesh_sizes = parse_mesh_arg(args.mesh)

    report = build_compile_report(names, mesh_sizes)
    if args.json:
        print(json.dumps(report, indent=1, default=str))
    else:
        blocks = [
            format_strategy_report(r)
            for r in report["strategies"].values()
        ]
        print(f"compile-time collective inventory (jax "
              f"{report['jax_version']}, backend {report['backend']}; no "
              "accelerator required)\n")
        print("\n\n".join(blocks))

    if args.check:
        bad = 0
        for name, r in report["strategies"].items():
            if "error" in r:
                print(f"CHECK FAIL {name}: did not compile: {r['error']}",
                      file=sys.stderr)
                bad += 1
            for v in r.get("signature_violations", []):
                print(f"CHECK FAIL {name}: {v}", file=sys.stderr)
                bad += 1
        if bad:
            return 1
        print(f"\ncomms check OK: {len(report['strategies'])} strategy "
              "signature(s) hold", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
