#!/usr/bin/env python
"""Notebook scrubber: clear outputs, execution counts, and volatile metadata
from every ``*.ipynb`` under the given directories (default: repo root).

Parity: the reference ships ``lab/clear-metadata-notebooks.py`` (nbconvert
``ClearOutputPreprocessor`` + ``ClearMetadataPreprocessor`` over ``lab/``,
``clear-metadata-notebooks.py:10-22``).  This version is dependency-free —
plain JSON rewriting — because notebooks are an interchange artifact here,
not a dev dependency: the homework "notebooks" ship as runnable scripts in
``examples/`` (see ``examples/README.md``), and any notebook a user adds
gets scrubbed the same way before commit.

Usage: ``python tools/clear_notebook_metadata.py [dir ...]``
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

KEEP_METADATA = {"kernelspec", "language_info"}


def scrub(path: Path) -> bool:
    nb = json.loads(path.read_text())
    changed = False
    if set(nb.get("metadata", {})) - KEEP_METADATA:
        nb["metadata"] = {
            k: v for k, v in nb["metadata"].items() if k in KEEP_METADATA
        }
        changed = True
    for cell in nb.get("cells", []):
        if cell.get("outputs") or cell.get("execution_count") is not None:
            cell["outputs"] = []
            cell["execution_count"] = None
            changed = True
        if cell.get("metadata"):
            cell["metadata"] = {}
            changed = True
    if changed:
        path.write_text(json.dumps(nb, indent=1, ensure_ascii=False) + "\n")
    return changed


def main(argv: list[str]) -> int:
    roots = [Path(a) for a in argv] or [Path(".")]
    n = 0
    for root in roots:
        for p in sorted(root.rglob("*.ipynb")):
            if ".ipynb_checkpoints" in p.parts:
                continue
            if scrub(p):
                print(f"scrubbed {p}")
                n += 1
    print(f"{n} notebook(s) changed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
