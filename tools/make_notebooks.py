#!/usr/bin/env python
"""Generate the notebook-form homework deliverables.

The reference ships its solved homework as notebooks
(``lab/series01.ipynb``, 46 cells; blank assignment
``lab/homework-1.ipynb``) while this framework ships the same experiments
as executable scripts (``examples/``).  This tool closes the FORM gap: it
emits ``lab/series01_tpu.ipynb`` — markdown narration + code cells that
call the example entry points — mirroring the reference notebook's
A1/A2/A3/B1/B2 section structure.  Cells are committed UNEXECUTED, the
same convention the reference enforces with its metadata scrubber
(``lab/clear-metadata-notebooks.py``); run them top to bottom (or the
scripts directly) to reproduce RESULTS.md §1-§2.

Regenerate: ``python tools/make_notebooks.py``.
"""

from pathlib import Path

import nbformat as nbf

ROOT = Path(__file__).resolve().parent.parent


def md(text: str):
    return nbf.v4.new_markdown_cell(text.strip())


def code(src: str):
    c = nbf.v4.new_code_cell(src.strip())
    return c


CELLS = [
    md("""
# Series 01 — solved homework (TPU framework)

The reference's solved notebook (`lab/series01.ipynb`) runs homework 1 on
`torch` FL servers; this notebook runs the SAME experiments on the
vmapped TPU servers (`ddl25spring_tpu.fl`).  Every section names the
reference cells it mirrors.  Seeds follow the homework mandate
(`seed=10`).

On a zero-egress image the MNIST loader falls back to a deterministic
synthetic set and the golden accuracies shift; set `DDL25_MNIST_DIR` to a
directory holding the four raw IDX files to reproduce the notebook's
golden table (93.2% FedAvg at N=10, C=0.1 — cell 20).
"""),
    code("""
import sys, pathlib
sys.path.insert(0, str(pathlib.Path.cwd().parent))  # repo root
# Simulate an 8-device CPU mesh (reference analogue: gloo-on-localhost).
# Must run BEFORE the first jax backend init — so do NOT query
# jax.devices()/default_backend() first; on a real TPU VM comment this
# out instead.
from ddl25spring_tpu.utils.platform import force_cpu_devices
force_cpu_devices(8)
"""),
    md("""
## A1 — FedSGD-with-gradients ≡ FedSGD-with-weights
(reference cells 9-12; tolerance 0.02% per round)

One full-batch SGD step + weighted weight averaging is linear in the
gradients, so the two transports must produce identical rounds.
"""),
    code("""
from examples.homework1_a1_equivalence import main as a1
a1(["--rounds", "5", "--n-train", "4096"])
"""),
    md("""
## A2 — client count N and participation fraction C
(reference cells 13-24; golden table in cell 20)
"""),
    code("""
from examples.homework1_a2_a3_sweeps import main as sweeps
sweeps(["--rounds", "5", "--quick", "--only", "a2"])
"""),
    md("""
## A3 — local epochs E and IID vs non-IID splits
(reference cells 25-38)
"""),
    code("""
sweeps(["--rounds", "5", "--quick", "--only", "a3"])
"""),
    md("""
## Golden-table runner

Prints the framework's accuracies side-by-side with the reference's
golden values (and says which dataset actually ran).
"""),
    code("""
from examples.golden_tables import main as golden
golden(["--rounds", "5", "--quick"])
"""),
    md("""
## B1/B2 — microbatch pipeline and DP×PP

The pipeline halves of the homework are driver scripts (they manage
meshes and long-running training):

```
./lab/run-b1.sh        # B1: 3-stage microbatch pipeline (LLaMA)
./lab/run-b2.sh        # B2: DP x PP (+ the ResNet benchmark config)
```

Schedules: `--schedule {gpipe,1f1b,1f1b-stash,interleaved,interleaved-1f1b}`.
Equivalence with the serial model is pinned in `tests/test_pipeline.py`;
measured schedule memory/throughput tables live in `RESULTS.md` §4/§7b.
"""),
]


def main():
    nb = nbf.v4.new_notebook()
    nb.cells = CELLS
    nb.metadata["kernelspec"] = {
        "display_name": "Python 3", "language": "python", "name": "python3",
    }
    out = ROOT / "lab" / "series01_tpu.ipynb"
    nbf.write(nb, out)
    print(f"wrote {out} ({len(CELLS)} cells)")


if __name__ == "__main__":
    main()
