#!/usr/bin/env python
"""Regenerate the committed ``data/tinystories.model`` SentencePiece
artifact from the synthetic TinyStories corpus.

The artifact is what keeps the SentencePiece path live on images without
the sentencepiece package (``data/sp_model.py``); it is committed so CI
exercises the wrapper.  Re-run this only when the corpus generator or the
trainer changes: ``python tools/train_sp_tokenizer.py [--vocab 512]``.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--stories", type=int, default=400)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", default="data/tinystories.model")
    args = ap.parse_args(argv)

    import numpy as np

    from ddl25spring_tpu.data.sp_model import (
        PySentencePieceProcessor, train_sp_model,
    )
    from ddl25spring_tpu.data.tinystories import generate_story

    rng = np.random.default_rng(args.seed)
    texts = [generate_story(rng) for _ in range(args.stories)]
    train_sp_model(texts, vocab_size=args.vocab, path=args.out)
    sp = PySentencePieceProcessor(args.out)
    sample = texts[0][:60]
    ids = sp.encode(sample)
    print(f"{args.out}: vocab={sp.vocab_size()}, "
          f"{Path(args.out).stat().st_size} bytes; "
          f"'{sample}' -> {len(ids)} tokens "
          f"(bytes: {len(sample.encode())})")
    assert sp.decode(ids) == sample


if __name__ == "__main__":
    main()
