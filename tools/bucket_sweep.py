"""Ledger-driven bucket-size autotuner: sweep ``bucket_bytes`` over a
grid, measure each point with the perfscope machinery, and emit the best
size as a ``DDL25_BUCKET_BYTES`` recommendation.

    python tools/bucket_sweep.py --strategy dp
    python tools/bucket_sweep.py --strategy dp-overlap,zero3-overlap \
        --grid 65536,262144,1048576,4194304
    python tools/bucket_sweep.py --strategy zero3 --workload llama --reps 8

The 4 MiB default bucket threshold (PR 3) was a literature constant,
never measured on this framework's programs: too small and every launch
pays the fixed collective cost the bucketing exists to amortize, too
large and one transfer monopolizes the wire (and, in the overlapped
mode, the last bucket has nothing left to hide behind).  The sweet spot
is host- and strategy-specific, which is exactly what the perf ledger's
(strategy, mesh, host) trend identity models — so this tool reuses the
perfscope steady-state step timing + per-collective micro-costing per
grid point and appends one record per (strategy, bucket_bytes) to the
ledger.

Sweep records carry ``"record": "bucket_sweep"`` (not ``"perf"``), so
``tools/perf_report.py --check`` never mistakes a deliberately-detuned
grid point for a regression; the winning size is additionally recorded
as ``"bucket_sweep_best"``.  Apply a recommendation by exporting
``DDL25_BUCKET_BYTES=<bytes>`` — every train-step builder resolves it
at build time (``parallel/bucketing.default_bucket_bytes``), and BENCH
lines / perf records carry the effective value so before/after runs
stay comparable.

Caveats: fake CPU devices share this host's cores, so absolute
milliseconds are host-relative — compare grid points within one run,
and re-sweep on the deployment host before exporting the knob there.
Registry describe() workloads are deliberately tiny; sizes above the
whole tree collapse to one bucket (the table's ``n_buckets`` column
shows where the grid stops mattering).
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO_ROOT))

from ddl25spring_tpu.utils.platform import ensure_cpu_tools_env  # noqa: E402

ensure_cpu_tools_env()

DEFAULT_GRID = (
    4 * 1024, 16 * 1024, 64 * 1024, 256 * 1024,
    1024 * 1024, 4 * 1024 * 1024,
)


def sweep_strategy(
    name: str,
    grid: tuple[int, ...],
    mesh_sizes: tuple[int, ...] | None = None,
    *,
    reps: int = 6,
    warmup: int = 2,
    micro_reps: int = 3,
    **overrides,
) -> list[dict]:
    """One perfscope measurement per grid point (no 1-device
    counterfactual — compute is bucket-size-invariant, only the launch
    structure changes).  Returns the re-tagged sweep records, best
    (lowest step p50) first annotated via ``"best": True``."""
    from ddl25spring_tpu.obs.perfscope import measure_strategy

    records = []
    for bb in grid:
        try:
            rec = measure_strategy(
                name, mesh_sizes, reps=reps, warmup=warmup,
                micro_reps=micro_reps, rounds=1,
                compute_counterfactual=False,
                bucket_bytes=int(bb), **overrides,
            )[0]
        except Exception as e:  # noqa: BLE001 — one bad grid point
            records.append({
                "record": "bucket_sweep", "strategy": name,
                "bucket_bytes": int(bb),
                "error": f"{type(e).__name__}: {e}",
            })
            continue
        rec["record"] = "bucket_sweep"
        rec.pop("findings", None)  # per-point lint adds nothing here
        records.append(rec)
    timed = [r for r in records if r.get("step_s_p50")]
    if timed:
        min(timed, key=lambda r: r["step_s_p50"])["best"] = True
    return records


def render_table(name: str, records: list[dict]) -> str:
    from ddl25spring_tpu.utils.metrics import fmt_bytes

    lines = [f"strategy {name}"]
    head = (f"  {'bucket_bytes':>14}{'n_buckets':>11}{'step p50':>12}"
            f"{'p95':>12}{'micro total':>13}")
    lines += [head, "  " + "-" * (len(head) - 2)]
    for r in records:
        if "error" in r:
            lines.append(f"  {fmt_bytes(r['bucket_bytes']):>14}  "
                         f"FAILED: {r['error']}")
            continue
        mark = "  <- best" if r.get("best") else ""
        lines.append(
            f"  {fmt_bytes(r.get('bucket_bytes')):>14}"
            f"{r.get('n_buckets', '?'):>11}"
            f"{r['step_s_p50'] * 1e3:>10.3f} ms"
            f"{r['step_s_p95'] * 1e3:>10.3f} ms"
            f"{r.get('micro_total_s', 0.0) * 1e3:>10.3f} ms{mark}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    import json

    import jax

    # env alone is too late on images whose sitecustomize registers a
    # TPU plugin at interpreter start; the config call forces CPU
    jax.config.update("jax_platforms", "cpu")

    from ddl25spring_tpu.obs.compile_report import parse_mesh_arg
    from ddl25spring_tpu.obs.perfscope import DEFAULT_LEDGER, append_ledger

    ap = argparse.ArgumentParser(
        prog="bucket_sweep", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--strategy", default="dp",
                    help="comma-separated registered strategy names "
                         "(see obs/xla_analytics.STRATEGIES)")
    ap.add_argument("--grid", default=None,
                    help="comma-separated bucket_bytes values (default: "
                         + ",".join(str(g) for g in DEFAULT_GRID) + ")")
    ap.add_argument("--mesh", default=None,
                    help="mesh sizes like 2x4, positional onto each "
                         "strategy's axis names")
    ap.add_argument("--workload", default=None,
                    help="describe() workload override (e.g. llama for "
                         "the zero strategies' 12-leaf tree)")
    ap.add_argument("--reps", type=int, default=6)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--micro-reps", type=int, default=3)
    ap.add_argument("--ledger", default=DEFAULT_LEDGER, metavar="JSONL",
                    help=f"append sweep records here (default "
                         f"{DEFAULT_LEDGER}; '-' disables)")
    ap.add_argument("--json", action="store_true",
                    help="print the sweep records as JSON")
    args = ap.parse_args(argv)

    grid = tuple(
        int(x) for x in (args.grid or "").split(",") if x.strip()
    ) or DEFAULT_GRID
    overrides = {"workload": args.workload} if args.workload else {}
    names = [s.strip() for s in args.strategy.split(",") if s.strip()]

    rc = 0
    all_records: dict[str, list[dict]] = {}
    for name in names:
        records = sweep_strategy(
            name, grid, parse_mesh_arg(args.mesh),
            reps=args.reps, warmup=args.warmup,
            micro_reps=args.micro_reps, **overrides,
        )
        all_records[name] = records
        best = next((r for r in records if r.get("best")), None)
        if args.ledger != "-":
            for r in records:
                append_ledger(r, args.ledger)
            if best is not None:
                append_ledger(
                    {**best, "record": "bucket_sweep_best"}, args.ledger
                )
        if not args.json:
            print(render_table(name, records))
            if best is not None:
                print(f"  recommendation: export DDL25_BUCKET_BYTES="
                      f"{best['bucket_bytes']}\n")
            else:
                print(f"  no grid point measured for {name}\n")
                rc = 1
        elif best is None:
            rc = 1
    if args.json:
        print(json.dumps(all_records, indent=1, default=str))
    return rc


if __name__ == "__main__":
    sys.exit(main())
