"""Render the perf ledger as per-strategy trend tables and gate
regressions.

    python tools/perf_report.py                       # trend tables
    python tools/perf_report.py --strategy dp --last 10
    python tools/perf_report.py --check               # the CI gate
    python tools/perf_report.py --check --tolerance 1.0   # wide CI band
    python tools/perf_report.py --format json         # machine-readable

The ledger (``runs/perf_ledger.jsonl``, written by
``python -m ddl25spring_tpu.obs.perfscope`` and by ``bench.py``) holds
one measured perf record per (strategy, mesh, host) measurement: step
wall p50/p95, compute-only counterfactual, exposed-comms time, overlap
efficiency, and measured MFU — see ``ddl25spring_tpu/obs/perfscope.py``
for the semantics.

``--check`` mirrors the ``comms_report``/``graft_lint`` CLI contract:
exit non-zero when, within any (strategy, mesh, host) key, the LATEST
record regresses past the tolerance band against the median of up to
``--window`` prior records on the same key — step time growing by more
than ``tolerance`` (fractional, default 0.35), or measured MFU falling
by more than the same fraction.  Keys with a single record pass with a
"no baseline yet" note (a fresh ledger must not fail CI), and records
from different hosts never gate each other (fake-CPU wall clocks are
host-relative by construction).

Pure stdlib — no jax import, so the gate runs anywhere the JSON does.
"""

from __future__ import annotations

import json
import statistics
import sys
from datetime import datetime, timezone
from pathlib import Path

DEFAULT_LEDGER = "runs/perf_ledger.jsonl"
DEFAULT_TOLERANCE = 0.35
DEFAULT_WINDOW = 5


def read_ledger(path: str, kind: str = "perf") -> list[dict]:
    """Parseable ``record: kind`` rows in append order (torn lines
    skipped) — same contract as ``perfscope.read_ledger``, restated
    here so the gate never imports jax.  ``serve_report.py`` reads the
    same ledger with ``kind="serve"``."""
    out: list[dict] = []
    p = Path(path)
    if not p.exists():
        return out
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("record") == kind:
            out.append(rec)
    return out


def ledger_key(rec: dict) -> tuple[str, str, str]:
    """(strategy, mesh, host): the trend identity.  git sha is the
    variable under test, so it stays OUT of the key."""
    mesh = rec.get("mesh")
    mesh_s = (
        ",".join(f"{k}={v}" for k, v in mesh.items())
        if isinstance(mesh, dict) else str(mesh)
    )
    return (
        str(rec.get("strategy")), mesh_s, str(rec.get("host")),
    )


def group_records(records: list[dict], key=None) -> dict[tuple, list[dict]]:
    key = key or ledger_key
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        groups.setdefault(key(rec), []).append(rec)
    return groups


def _median(xs: list[float]) -> float | None:
    return statistics.median(xs) if xs else None


def check_overlap_floor(recs: list[dict], min_overlap_eff: float) -> list[str]:
    """The ``--min-overlap-eff`` gate: the latest record's measured
    overlap efficiency must not sit under the floor.  Unlike the
    relative bands of :func:`check_group` this is an absolute floor and
    needs no baseline — a single fresh record already gates.  Records
    whose ``overlap_eff`` is None (no costed collectives on this mesh,
    e.g. a 1-chip run) are skipped: an undefined efficiency is not a
    regressed one."""
    if not recs:
        return []
    eff = recs[-1].get("overlap_eff")
    if isinstance(eff, (int, float)) and eff < min_overlap_eff:
        return [
            f"overlap_eff {eff:.3f} fell under the --min-overlap-eff "
            f"{min_overlap_eff:.3f} floor (exposed comms "
            f"{_fmt(recs[-1].get('exposed_comms_s'), 3, 1e3, ' ms')} vs "
            f"micro total "
            f"{_fmt(recs[-1].get('micro_total_s'), 3, 1e3, ' ms')})"
        ]
    return []


def check_group(
    recs: list[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    window: int = DEFAULT_WINDOW,
) -> list[str]:
    """Regression verdicts for one key: [] = latest within band (or no
    baseline yet).  The baseline is the MEDIAN over up to ``window``
    prior records — one noisy historical rep must not move the gate."""
    if len(recs) < 2:
        return []
    latest = recs[-1]
    base = recs[:-1][-window:]
    fails: list[str] = []
    b_step = _median([
        r["step_s_p50"] for r in base
        if isinstance(r.get("step_s_p50"), (int, float))
    ])
    l_step = latest.get("step_s_p50")
    if b_step and isinstance(l_step, (int, float)):
        if l_step > b_step * (1.0 + tolerance):
            fails.append(
                f"step_s_p50 {l_step * 1e3:.3f} ms exceeds the "
                f"{(1 + tolerance):.2f}x band over the baseline "
                f"{b_step * 1e3:.3f} ms (median of {len(base)} prior "
                "record(s))"
            )
    b_mfu = _median([
        r["measured_mfu"] for r in base
        if isinstance(r.get("measured_mfu"), (int, float))
    ])
    l_mfu = latest.get("measured_mfu")
    if b_mfu and isinstance(l_mfu, (int, float)):
        if l_mfu < b_mfu * (1.0 - tolerance):
            fails.append(
                f"measured_mfu {l_mfu:.5f} fell below the "
                f"{(1 - tolerance):.2f}x band under the baseline "
                f"{b_mfu:.5f}"
            )
    return fails


def _fmt(v, nd=3, scale=1.0, suffix=""):
    if not isinstance(v, (int, float)):
        return "n/a"
    return f"{v * scale:.{nd}f}{suffix}"


def format_group(key: tuple, recs: list[dict], last: int) -> str:
    strategy, mesh_s, host = key
    chip = recs[-1].get("chip") or "?"
    lines = [
        f"strategy {strategy}  mesh({mesh_s})  host {host}  [chip {chip}]"
    ]
    cols = (
        f"  {'when (utc)':<20}{'sha':<9}{'step p50':>11}{'p95':>11}"
        f"{'compute':>11}{'exposed':>11}{'overlap':>9}{'MFU':>10}"
        f"{'proj err':>10}"
    )
    lines.append(cols)
    lines.append("  " + "-" * (len(cols) - 2))
    for rec in recs[-last:]:
        ts = rec.get("ts")
        when = (
            datetime.fromtimestamp(ts, tz=timezone.utc)
            .strftime("%Y-%m-%d %H:%M:%S")
            if isinstance(ts, (int, float)) else "?"
        )
        sha = (rec.get("git_sha") or "?")[:7]
        lines.append(
            f"  {when:<20}{sha:<9}"
            f"{_fmt(rec.get('step_s_p50'), 3, 1e3, ' ms'):>11}"
            f"{_fmt(rec.get('step_s_p95'), 3, 1e3, ' ms'):>11}"
            f"{_fmt(rec.get('compute_s_p50'), 3, 1e3, ' ms'):>11}"
            f"{_fmt(rec.get('exposed_comms_s'), 3, 1e3, ' ms'):>11}"
            f"{_fmt(rec.get('overlap_eff'), 3):>9}"
            f"{_fmt(rec.get('measured_mfu'), 5):>10}"
            f"{_fmt(rec.get('projection_err'), 2, 100.0, '%'):>10}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=DEFAULT_LEDGER, metavar="JSONL")
    ap.add_argument("--strategy", default=None,
                    help="comma-separated strategy filter")
    ap.add_argument("--last", type=int, default=8,
                    help="rows per key in the trend table")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="prior records per key the baseline medians over")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="fractional regression band (0.35 = step may "
                         "grow 35%%, MFU may drop 35%%); CI machines "
                         "want wide bands (e.g. 1.0)")
    ap.add_argument("--format", choices=("table", "json"), default="table",
                    help="json mirrors graft_lint --format json: one "
                         "structured document carrying the grouped "
                         "records AND every check verdict, so CI jobs "
                         "parse instead of grepping the table")
    ap.add_argument("--json", action="store_true",
                    help="deprecated alias for --format json")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero when any key's latest record "
                         "regresses past the band (the CI perf gate)")
    ap.add_argument("--min-overlap-eff", type=float, default=None,
                    metavar="F",
                    help="with --check: also fail when any key's latest "
                         "record has a measured overlap_eff below this "
                         "absolute floor (keys whose overlap_eff is "
                         "undefined — no costed collectives — are "
                         "skipped).  CI catches overlap regressions, "
                         "not just wall-clock ones")
    args = ap.parse_args(argv)

    records = read_ledger(args.ledger)
    if not records:
        print(f"no perf records in {args.ledger} (run "
              "python -m ddl25spring_tpu.obs.perfscope, or bench.py, "
              "to populate it)", file=sys.stderr)
        return 2 if args.check else 0
    if args.strategy:
        wanted = {s.strip() for s in args.strategy.split(",") if s.strip()}
        records = [r for r in records if r.get("strategy") in wanted]

    groups = group_records(records)
    # one verdict pass shared by the json document and the --check
    # gate: CI parses verdicts out of the JSON instead of grepping
    # "CHECK FAIL" lines off stderr
    verdicts: dict[tuple, dict] = {}
    for key, recs in groups.items():
        fails: list[str] = []
        note = None
        if args.min_overlap_eff is not None:
            # the absolute floor gates even a single fresh record
            fails += check_overlap_floor(recs, args.min_overlap_eff)
        if len(recs) < 2:
            if not fails:
                note = "no baseline yet (single record)"
        else:
            fails += check_group(recs, args.tolerance, args.window)
        verdicts[key] = {"fails": fails, "note": note}
    bad = sum(len(v["fails"]) for v in verdicts.values())

    if args.json or args.format == "json":
        doc = {
            "record": "perf_report",
            "ledger": args.ledger,
            "tolerance": args.tolerance,
            "window": args.window,
            "min_overlap_eff": args.min_overlap_eff,
            "groups": [
                {
                    "strategy": key[0],
                    "mesh": key[1],
                    "host": key[2],
                    "records": recs[-args.last:],
                    "fails": verdicts[key]["fails"],
                    "note": verdicts[key]["note"],
                }
                for key, recs in groups.items()
            ],
            "check": {"ok": bad == 0, "fails": bad},
        }
        print(json.dumps(doc, indent=1, default=str))
    else:
        print(f"perf ledger: {args.ledger}  ({len(records)} record(s), "
              f"{len(groups)} key(s))\n")
        print("\n\n".join(
            format_group(k, v, args.last) for k, v in groups.items()
        ))

    if args.check:
        for key, v in verdicts.items():
            label = f"{key[0]} mesh({key[1]})"
            if v["note"]:
                print(f"CHECK NOTE {label}: {v['note']}", file=sys.stderr)
            for fail in v["fails"]:
                print(f"CHECK FAIL {label}: {fail}", file=sys.stderr)
        if bad:
            return 1
        floor = (
            f", overlap_eff floor {args.min_overlap_eff:.2f}"
            if args.min_overlap_eff is not None else ""
        )
        print(f"\nperf check OK: {len(groups)} key(s) within the "
              f"{args.tolerance:.2f} tolerance band{floor}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
