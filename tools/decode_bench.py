#!/usr/bin/env python
"""KV-cache generation throughput on the chip.

The reference has no generation path at all (its LLaMA only trains —
``lab/s01_b1_microbatches.py``); this framework adds autoregressive
KV-cache decoding (``models/decode.py``), and this tool measures it: the
full jitted prefill+decode program at the reference workload constants
(dmodel 288, 6 heads, 6 layers), greedy decoding, across batch sizes.

Run: ``python tools/decode_bench.py [--ctx 256] [--new 224]``
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--prompt", type=int, default=32)
    ap.add_argument("--new", type=int, default=224)
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 8, 64])
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--tp", type=int, default=0, metavar="T",
                    help="TP-sharded decode over a (model=T) mesh "
                         "(head-sharded KV cache, vocab-sharded "
                         "embed/unembed; needs T devices — use "
                         "--force-cpu-devices via --cpu + "
                         "XLA_FLAGS for local smoke)")
    args = ap.parse_args(argv)

    import os

    if args.tp and args.cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.tp}"
            ).strip()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.models.decode import generate, make_tp_generate
    from ddl25spring_tpu.utils.config import LlamaConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = LlamaConfig(
        vocab_size=4096, dmodel=288, num_heads=6, n_layers=6,
        ctx_size=args.prompt + args.new,
        dtype="bfloat16" if on_tpu else "float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    print(f"device={jax.devices()[0].device_kind}  dmodel={cfg.dmodel} "
          f"L{cfg.n_layers}  prompt={args.prompt}  new={args.new}"
          + (f"  tp={args.tp}" if args.tp else ""))

    if args.tp:
        from ddl25spring_tpu.parallel.tp import shard_tp_params
        from ddl25spring_tpu.utils.mesh import make_mesh

        mesh = make_mesh(jax.devices()[: args.tp], model=args.tp)
        params = shard_tp_params(params, mesh)
        tp_gen = make_tp_generate(cfg, mesh, args.new)
        key0 = jax.random.PRNGKey(0)
        gen = lambda p, prompt: tp_gen(p, prompt, key0)
    else:
        gen = jax.jit(
            lambda p, prompt: generate(p, prompt, cfg, args.new),
        )
    for B in args.batches:
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (B, args.prompt), 0, cfg.vocab_size
        )
        toks = gen(params, prompt)  # compile
        jax.block_until_ready(toks)
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            toks = gen(params, prompt)
            # force completion through a host transfer (block_until_ready
            # does not block on this image's tunneled TPU platform)
            _ = int(toks[0, -1])
            best = min(best, time.perf_counter() - t0)
        total = B * args.new
        print(f"B={B:>3}: {total / best:,.0f} tok/s "
              f"({best * 1e3 / args.new:.2f} ms/token-step at batch {B})")


if __name__ == "__main__":
    main()
