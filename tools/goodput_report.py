"""Render goodput/badput decompositions and gate goodput regressions.

    python tools/goodput_report.py                     # ledger trend tables
    python tools/goodput_report.py --run runs/bench_smoke   # one run's doc
    python tools/goodput_report.py --check             # the CI trend gate
    python tools/goodput_report.py --check --slo-floor 0.9  # serve SLO gate
    python tools/goodput_report.py --check-elastic \\
        runs/elastic/goodput.json runs/relaunch/goodput.json
    python tools/goodput_report.py --format json       # machine-readable

The ledger (``runs/perf_ledger.jsonl``) holds one ``record:"goodput"``
row per run lineage, written by ``bench.py`` (training: the merged
all-attempts decomposition) and the serve driver (SLO attainment,
availability, goodput tokens/sec/chip) — semantics in
``ddl25spring_tpu/obs/goodput.py``.  Per-run ``goodput.json`` files
carry the full decomposition including the badput windows
``tools/trace_export.py`` renders.

Gates (all CI-facing, mirroring the ``perf_report`` contract — keys
with a single record pass with a "no baseline yet" note, different
hosts never gate each other):

- ``--check``: within each (strategy, mesh, host, scope) key, the
  latest row's ``fraction_useful`` must not fall more than
  ``--tolerance`` (fractional) below the median of up to ``--window``
  prior rows; serve rows apply the same band to ``slo_attainment``.
  Any row whose own ``sum_check`` failed (buckets over-attributed past
  the pinned tolerance) fails unconditionally — a decomposition that
  does not add up gates no trend.
- ``--slo-floor F``: the latest serve-scope row's ``slo_attainment``
  must be >= F (absolute; a single fresh record already gates — the
  serve-smoke SLO gate).
- ``--check-elastic ELASTIC RELAUNCH``: two run-dir ``goodput.json``
  paths measured on the SAME fault spec; the elastic run's
  ``fraction_useful`` must be STRICTLY higher than the relaunch run's
  — the PR-14 recovery A/B re-expressed in the production metric (an
  in-process reshape pays seconds where a relaunch pays process
  restart + restore + replayed steps).

Pure stdlib — no jax import, so the gate runs anywhere the JSON does.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
from datetime import datetime, timezone
from pathlib import Path

DEFAULT_LEDGER = "runs/perf_ledger.jsonl"
DEFAULT_TOLERANCE = 0.35
DEFAULT_WINDOW = 5

# restated from ddl25spring_tpu/obs/goodput.py (stdlib tools never
# import the package: its __init__ pulls jax)
GOODPUT_BASENAME = "goodput.json"
BUCKETS = (
    "useful_step",
    "warmup_compile",
    "checkpoint_save",
    "replayed_steps",
    "stall",
    "recovery",
    "reshape_window",
    "other",
)


def read_ledger(path: str, kind: str = "goodput") -> list[dict]:
    """Parseable ``record: kind`` rows in append order (torn trailing
    lines skipped, same contract as every ledger reader)."""
    out: list[dict] = []
    p = Path(path)
    if not p.exists():
        return out
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("record") == kind:
            out.append(rec)
    return out


def ledger_key(rec: dict) -> tuple[str, str, str, str]:
    """(strategy, mesh, host, scope): the trend identity.  The lineage
    id is IDENTITY on the row, never part of the key — every lineage
    is unique, so keying on it would orphan every trend group."""
    key = rec.get("key") if isinstance(rec.get("key"), dict) else {}
    mesh = key.get("mesh")
    mesh_s = (
        ",".join(f"{k}={v}" for k, v in sorted(mesh.items()))
        if isinstance(mesh, dict) else str(mesh)
    )
    return (
        str(key.get("strategy")), mesh_s, str(rec.get("host")),
        str(key.get("scope")),
    )


def group_records(records: list[dict]) -> dict[tuple, list[dict]]:
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        groups.setdefault(ledger_key(rec), []).append(rec)
    return groups


def _median(xs: list[float]) -> float | None:
    return statistics.median(xs) if xs else None


def _band_fail(latest, base: list[dict], field: str,
               tolerance: float) -> list[str]:
    b = _median([
        r[field] for r in base
        if isinstance(r.get(field), (int, float))
    ])
    v = latest.get(field)
    if b and isinstance(v, (int, float)) and v < b * (1.0 - tolerance):
        return [
            f"{field} {v:.4f} fell below the {(1 - tolerance):.2f}x "
            f"band under the baseline {b:.4f} (median of {len(base)} "
            "prior record(s))"
        ]
    return []


def check_group(recs: list[dict], tolerance: float = DEFAULT_TOLERANCE,
                window: int = DEFAULT_WINDOW) -> list[str]:
    """Regression verdicts for one key: [] = within band (or no
    baseline).  A latest row whose own decomposition failed its sum
    contract fails regardless of history."""
    fails: list[str] = []
    latest = recs[-1]
    sc = latest.get("sum_check")
    if isinstance(sc, dict) and sc.get("ok") is False:
        fails.append(
            f"decomposition sum_check failed: attributed "
            f"{sc.get('attributed_s')}s vs total "
            f"{sc.get('total_wall_s')}s exceeds the pinned "
            f"{sc.get('tolerance')} tolerance"
        )
    if len(recs) < 2:
        return fails
    base = recs[:-1][-window:]
    fails += _band_fail(latest, base, "fraction_useful", tolerance)
    if latest.get("key", {}).get("scope") == "serve":
        fails += _band_fail(latest, base, "slo_attainment", tolerance)
    return fails


def check_slo_floor(recs: list[dict], floor: float) -> list[str]:
    """Absolute SLO-attainment floor on the latest serve-scope row —
    needs no baseline (the serve-smoke gate).  Rows whose attainment
    is None (nothing completed to evaluate) FAIL: an engine that
    finished zero requests did not attain its SLO."""
    latest = recs[-1]
    if latest.get("key", {}).get("scope") != "serve":
        return []
    att = latest.get("slo_attainment")
    if att is None:
        return [
            "slo_attainment is null (no completed requests were "
            f"evaluated) — below the --slo-floor {floor:.3f}"
        ]
    if att < floor:
        return [
            f"slo_attainment {att:.4f} fell under the --slo-floor "
            f"{floor:.3f}"
        ]
    return []


def load_run_doc(path: str) -> dict:
    """A goodput doc from a run dir or a direct goodput.json path."""
    if os.path.isdir(path):
        path = os.path.join(path, GOODPUT_BASENAME)
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("record") != "goodput":
        raise ValueError(f"{path} is not a goodput doc")
    return doc


def check_elastic(elastic_path: str, relaunch_path: str) -> list[str]:
    """The elastic-vs-relaunch recovery A/B in goodput terms: on the
    same fault spec, the in-process reshape must waste strictly less
    of the lineage's wall than the kill->relaunch->restore->replay
    round-trip.  STRICT inequality — equal goodput means the reshape
    path bought nothing."""
    fails: list[str] = []
    e = load_run_doc(elastic_path)
    r = load_run_doc(relaunch_path)
    for name, doc in (("elastic", e), ("relaunch", r)):
        sc = doc.get("sum_check")
        if isinstance(sc, dict) and sc.get("ok") is False:
            fails.append(
                f"{name} decomposition sum_check failed "
                f"(attributed {sc.get('attributed_s')}s vs total "
                f"{sc.get('total_wall_s')}s)"
            )
    fe, fr = e.get("fraction_useful"), r.get("fraction_useful")
    if not isinstance(fe, (int, float)) or not isinstance(
        fr, (int, float)
    ):
        fails.append(
            f"fraction_useful missing (elastic={fe!r}, relaunch={fr!r})"
        )
    elif fe <= fr:
        fails.append(
            f"elastic goodput {fe:.4f} is not strictly above the "
            f"relaunch goodput {fr:.4f} on the same fault spec "
            f"(elastic wasted {1 - fe:.4f}, relaunch {1 - fr:.4f})"
        )
    return fails


def _fmt(v, nd=3, scale=1.0, suffix=""):
    if not isinstance(v, (int, float)):
        return "n/a"
    return f"{v * scale:.{nd}f}{suffix}"


def format_run(doc: dict) -> str:
    """One run's decomposition table (the --run view)."""
    total = doc.get("total_wall_s")
    lines = [
        f"goodput [{doc.get('scope')}]  lineage {doc.get('lineage_id')}"
        f"  attempts {doc.get('attempts')}  chips {doc.get('chips')}",
        f"  total wall {_fmt(total, 2, 1.0, ' s')}  fraction_useful "
        f"{_fmt(doc.get('fraction_useful'), 4)}",
    ]
    seconds = doc.get("seconds") or {}
    if seconds:
        lines.append(f"  {'bucket':<18}{'seconds':>12}{'share':>9}")
        lines.append("  " + "-" * 37)
        for b in BUCKETS:
            s = seconds.get(b)
            if not isinstance(s, (int, float)):
                continue
            share = s / total if total else None
            lines.append(
                f"  {b:<18}{_fmt(s, 3):>12}{_fmt(share, 3):>9}"
            )
    sc = doc.get("sum_check") or {}
    lines.append(
        f"  sum_check: attributed {_fmt(sc.get('attributed_s'), 3)} s "
        f"vs total {_fmt(sc.get('total_wall_s'), 3)} s -> "
        f"{'ok' if sc.get('ok') else 'FAIL'}"
    )
    if doc.get("slo_attainment") is not None or doc.get(
        "scope"
    ) == "serve":
        lines.append(
            f"  serve: slo_attainment "
            f"{_fmt(doc.get('slo_attainment'), 4)}  availability "
            f"{_fmt(doc.get('availability'), 4)}  goodput tok/s/chip "
            f"{_fmt(doc.get('goodput_tokens_per_sec_per_chip'), 1)}"
        )
    if doc.get("replayed_steps_count"):
        lines.append(
            f"  replayed steps: {doc['replayed_steps_count']}"
        )
    return "\n".join(lines)


def format_group(key: tuple, recs: list[dict], last: int) -> str:
    strategy, mesh_s, host, scope = key
    lines = [
        f"strategy {strategy}  mesh({mesh_s})  scope {scope}  host {host}"
    ]
    cols = (
        f"  {'when (utc)':<20}{'lineage':<14}{'att':>4}{'wall':>10}"
        f"{'useful':>9}{'replay':>8}{'slo':>8}{'avail':>8}"
    )
    lines.append(cols)
    lines.append("  " + "-" * (len(cols) - 2))
    for rec in recs[-last:]:
        ts = rec.get("ts")
        when = (
            datetime.fromtimestamp(ts, tz=timezone.utc)
            .strftime("%Y-%m-%d %H:%M:%S")
            if isinstance(ts, (int, float)) else "?"
        )
        lines.append(
            f"  {when:<20}{str(rec.get('lineage_id'))[:12]:<14}"
            f"{rec.get('attempts') or 1:>4}"
            f"{_fmt(rec.get('total_wall_s'), 1, 1.0, ' s'):>10}"
            f"{_fmt(rec.get('fraction_useful'), 3):>9}"
            f"{rec.get('replayed_steps_count') or 0:>8}"
            f"{_fmt(rec.get('slo_attainment'), 3):>8}"
            f"{_fmt(rec.get('availability'), 3):>8}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=DEFAULT_LEDGER, metavar="JSONL")
    ap.add_argument("--run", default=None, metavar="DIR",
                    help="render one run's goodput.json decomposition "
                         "(a run dir or a direct path) instead of the "
                         "ledger trend tables")
    ap.add_argument("--strategy", default=None,
                    help="comma-separated strategy filter")
    ap.add_argument("--last", type=int, default=8,
                    help="rows per key in the trend table")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="prior records per key the baseline medians over")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="fractional regression band on fraction_useful "
                         "/ slo_attainment (0.35 = may fall 35%%)")
    ap.add_argument("--format", choices=("table", "json"), default="table",
                    help="json: one structured document with the grouped "
                         "rows AND every check verdict (CI parses "
                         "instead of grepping)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero when any key's latest row "
                         "regresses past the band or fails its own "
                         "sum contract (the CI goodput gate)")
    ap.add_argument("--slo-floor", type=float, default=None, metavar="F",
                    help="with --check: the latest serve-scope row's "
                         "slo_attainment must be >= F (absolute floor, "
                         "no baseline needed — the serve-smoke gate)")
    ap.add_argument("--check-elastic", nargs=2, default=None,
                    metavar=("ELASTIC", "RELAUNCH"),
                    help="two goodput.json paths (run dirs or files) "
                         "from the SAME fault spec: elastic "
                         "fraction_useful must be STRICTLY above the "
                         "relaunch one (the PR-14 recovery A/B in "
                         "goodput terms); exits non-zero otherwise")
    args = ap.parse_args(argv)

    # --check-elastic is a self-contained two-artifact gate
    if args.check_elastic is not None:
        try:
            fails = check_elastic(*args.check_elastic)
        except (OSError, ValueError) as e:
            print(f"CHECK FAIL elastic-vs-relaunch: {e}", file=sys.stderr)
            return 2
        for f in fails:
            print(f"CHECK FAIL elastic-vs-relaunch: {f}", file=sys.stderr)
        if fails:
            return 1
        e_doc = load_run_doc(args.check_elastic[0])
        r_doc = load_run_doc(args.check_elastic[1])
        print(
            "elastic-vs-relaunch goodput OK: elastic "
            f"{e_doc.get('fraction_useful'):.4f} > relaunch "
            f"{r_doc.get('fraction_useful'):.4f}",
            file=sys.stderr,
        )
        if args.format == "json":
            print(json.dumps({
                "record": "goodput_elastic_check",
                "elastic": e_doc.get("fraction_useful"),
                "relaunch": r_doc.get("fraction_useful"),
                "ok": True,
            }, indent=1))
        return 0

    if args.run is not None:
        try:
            doc = load_run_doc(args.run)
        except (OSError, ValueError) as e:
            print(f"no goodput doc at {args.run}: {e}", file=sys.stderr)
            return 2
        if args.format == "json":
            print(json.dumps(doc, indent=1, default=str))
        else:
            print(format_run(doc))
        if args.check:
            fails = check_group([_doc_as_row(doc)], args.tolerance)
            if args.slo_floor is not None:
                fails += check_slo_floor(
                    [_doc_as_row(doc)], args.slo_floor
                )
            for f in fails:
                print(f"CHECK FAIL {args.run}: {f}", file=sys.stderr)
            return 1 if fails else 0
        return 0

    records = read_ledger(args.ledger)
    if not records:
        print(f"no goodput records in {args.ledger} (run bench.py with "
              "--obs-dir, or the serve bench, to populate it)",
              file=sys.stderr)
        return 2 if args.check else 0
    if args.strategy:
        wanted = {s.strip() for s in args.strategy.split(",") if s.strip()}
        records = [
            r for r in records
            if (r.get("key") or {}).get("strategy") in wanted
        ]

    groups = group_records(records)
    verdicts: dict[tuple, dict] = {}
    for key, recs in groups.items():
        fails = check_group(recs, args.tolerance, args.window)
        if args.slo_floor is not None:
            fails += check_slo_floor(recs, args.slo_floor)
        note = (
            "no baseline yet (single record)"
            if len(recs) < 2 and not fails else None
        )
        verdicts[key] = {"fails": fails, "note": note}
    bad = sum(len(v["fails"]) for v in verdicts.values())

    if args.format == "json":
        doc = {
            "record": "goodput_report",
            "ledger": args.ledger,
            "tolerance": args.tolerance,
            "window": args.window,
            "slo_floor": args.slo_floor,
            "groups": [
                {
                    "strategy": key[0],
                    "mesh": key[1],
                    "host": key[2],
                    "scope": key[3],
                    "records": recs[-args.last:],
                    "fails": verdicts[key]["fails"],
                    "note": verdicts[key]["note"],
                }
                for key, recs in groups.items()
            ],
            "check": {"ok": bad == 0, "fails": bad},
        }
        print(json.dumps(doc, indent=1, default=str))
    else:
        print(f"goodput ledger: {args.ledger}  ({len(records)} "
              f"record(s), {len(groups)} key(s))\n")
        print("\n\n".join(
            format_group(k, v, args.last) for k, v in groups.items()
        ))

    if args.check:
        for key, v in verdicts.items():
            label = f"{key[0]} mesh({key[1]}) scope {key[3]}"
            if v["note"]:
                print(f"CHECK NOTE {label}: {v['note']}", file=sys.stderr)
            for fail in v["fails"]:
                print(f"CHECK FAIL {label}: {fail}", file=sys.stderr)
        if bad:
            return 1
        floor = (
            f", slo floor {args.slo_floor:.2f}"
            if args.slo_floor is not None else ""
        )
        print(f"\ngoodput check OK: {len(groups)} key(s) within the "
              f"{args.tolerance:.2f} tolerance band{floor}",
              file=sys.stderr)
    return 0


def _doc_as_row(doc: dict) -> dict:
    """Adapt a run's goodput.json doc to the ledger-row shape the
    check helpers read (key.scope + the summary fields)."""
    return {
        **doc,
        "key": {
            "strategy": doc.get("strategy"),
            "scope": doc.get("scope"),
        },
    }


if __name__ == "__main__":
    sys.exit(main())
