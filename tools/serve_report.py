"""Render a serving run and gate cross-run serving regressions.

    python tools/serve_report.py runs/serve_smoke        # run report
    python tools/serve_report.py --ledger-only           # trend tables
    python tools/serve_report.py runs/serve_smoke --check   # the CI gate
    python tools/serve_report.py --check --check-ab      # + A/B verdict

The run directory holds the ``serve.json`` a ``bench.py --serve``
run dropped there (``ddl25spring_tpu/serve/driver.py``): the report
renders its throughput/admission table and ASCII latency histograms
(TTFT and per-decode-tick wall time).  The ledger
(``runs/perf_ledger.jsonl``) additionally holds one ``record: "serve"``
trend row per run, keyed (workload key, host) with git sha as the
variable under test — the same ledger the perfscope records live in,
different record kind.

``--check`` mirrors ``perf_report.py``: exit non-zero when, within any
(key, host) group, the LATEST row regresses past the tolerance band
against the median of up to ``--window`` priors — tokens/sec/chip
falling by more than ``--tolerance`` (fractional, default 0.5 — CPU CI
wall clocks are noisy) or p95 TTFT growing by more than it.  On
shared-prefix runs (``profile=shared`` in the key) ``prefix_hit_rate``
is a gated key too: deterministic on the seeded trace, so it gates at
the same band.  Groups with a single row pass with a "no baseline yet"
note, and rows from different hosts never gate each other.
``--check-ab`` adds the continuous-batching acceptance verdict: the
latest row's A/B cell must show continuous strictly ahead of static in
tokens delivered at the fixed budget (the deterministic virtual-clock
comparison the driver records).  ``--check-prefix-ab`` adds the radix
prefix cache's (PR 11): the latest row's cached-vs-cold cell must show
``prefill_tokens_saved > 0``, a strictly higher cached virtual-clock
tokens/sec/chip, tokens delivered strictly ahead at the fixed budget,
and bitwise-matching token streams.  ``--check-spec-ab`` adds the
speculative-decoding verdict (PR 13): the latest row's spec-on-vs-off
cell must show real accepted draft tokens, a strictly higher
speculative virtual-clock tokens/sec/chip at equal admission budget,
tokens delivered strictly ahead at the fixed budget, and
bitwise-matching token streams over >= 1 compared request (greedy
speculation IS the target's own output — an empty comparison would
pass the bitwise gate vacuously, so it fails instead).  On spec runs
(``spec`` in the key) ``acceptance_rate`` joins the banded trend keys:
deterministic on the seeded trace, it collapses when the drafter or
the acceptance walk regresses, long before the noisy wall clocks
notice.  ``--check-reshape`` adds the elastic-reshape verdict (PR 14):
the latest row's reshape cell must show >= 1 driven scale event, ZERO
dropped (accepted-then-lost) requests across the replica handoff, and
reshape-window p95 TTFT within ``--reshape-ttft-factor`` (default 3x)
of steady state over a non-empty window.

Pure stdlib — no jax import, so the gate runs anywhere the JSON does.
"""

from __future__ import annotations

import json
import sys
from datetime import datetime, timezone
from pathlib import Path

# the torn-tail ledger contract, grouping, and number formatting are
# perf_report's — one implementation for every stdlib gate over
# runs/perf_ledger.jsonl
try:  # imported as tools.serve_report (tests, package contexts)
    from tools import perf_report as _perf_report
except ImportError:  # run as a script: sys.path[0] is tools/
    import perf_report as _perf_report

_fmt = _perf_report._fmt
_median = _perf_report._median

DEFAULT_LEDGER = "runs/perf_ledger.jsonl"
DEFAULT_TOLERANCE = 0.5
DEFAULT_WINDOW = 5
# restated from ddl25spring_tpu.obs.report so the gate never imports
# the package (or numpy/jax behind it)
SERVE_BASENAME = "serve.json"


def read_serve_json(run_dir: str) -> dict:
    p = Path(run_dir) / SERVE_BASENAME
    with open(p) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("record") != "serve":
        raise ValueError(f"{p} is not a serve record")
    return doc


def read_ledger(path: str) -> list[dict]:
    """Parseable ``record: "serve"`` rows in append order (torn
    trailing lines skipped — ``perf_report.read_ledger``'s contract)."""
    return _perf_report.read_ledger(path, kind="serve")


def ledger_key(rec: dict) -> tuple[str, str]:
    """(workload key, host): the trend identity.  git sha is the
    variable under test, so it stays OUT of the key."""
    key = rec.get("key")
    key_s = (
        ",".join(f"{k}={key[k]}" for k in sorted(key))
        if isinstance(key, dict) else str(key)
    )
    return (key_s, str(rec.get("host")))


def group_records(records: list[dict]) -> dict[tuple, list[dict]]:
    return _perf_report.group_records(records, key=ledger_key)


def check_group(
    recs: list[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    window: int = DEFAULT_WINDOW,
) -> list[str]:
    """Regression verdicts for one (key, host) group: [] = latest within
    band (or no baseline yet).  Baseline = median of up to ``window``
    priors — one noisy historical run must not move the gate."""
    if len(recs) < 2:
        return []
    latest = recs[-1]
    base = recs[:-1][-window:]
    fails: list[str] = []
    b_tps = _median([
        r["tokens_per_sec_per_chip"] for r in base
        if isinstance(r.get("tokens_per_sec_per_chip"), (int, float))
    ])
    l_tps = latest.get("tokens_per_sec_per_chip")
    if b_tps and isinstance(l_tps, (int, float)):
        if l_tps < b_tps * (1.0 - tolerance):
            fails.append(
                f"tokens_per_sec_per_chip {l_tps:.2f} fell below the "
                f"{(1 - tolerance):.2f}x band under the baseline "
                f"{b_tps:.2f} (median of {len(base)} prior run(s))"
            )
    b_ttft = _median([
        r["ttft_s_p95"] for r in base
        if isinstance(r.get("ttft_s_p95"), (int, float))
    ])
    l_ttft = latest.get("ttft_s_p95")
    if b_ttft and isinstance(l_ttft, (int, float)):
        if l_ttft > b_ttft * (1.0 + tolerance):
            fails.append(
                f"ttft_s_p95 {l_ttft * 1e3:.2f} ms exceeds the "
                f"{(1 + tolerance):.2f}x band over the baseline "
                f"{b_ttft * 1e3:.2f} ms"
            )
    if _is_shared_prefix(latest):
        # prefix_hit_rate is DETERMINISTIC on the seeded shared-prefix
        # trace, so it gates like a perf key: a radix-tree or eviction
        # regression shows up as a hit-rate collapse long before the
        # noisy wall clocks notice
        b_hit = _median([
            r["prefix_hit_rate"] for r in base
            if isinstance(r.get("prefix_hit_rate"), (int, float))
        ])
        l_hit = latest.get("prefix_hit_rate")
        if b_hit and isinstance(l_hit, (int, float)):
            if l_hit < b_hit * (1.0 - tolerance):
                fails.append(
                    f"prefix_hit_rate {l_hit:.3f} fell below the "
                    f"{(1 - tolerance):.2f}x band under the baseline "
                    f"{b_hit:.3f} on a shared-prefix run"
                )
    if _is_spec(latest):
        # acceptance_rate is equally deterministic on a seeded trace
        # (greedy drafter vs greedy target): a collapse means the
        # drafter construction or the acceptance walk regressed
        b_acc = _median([
            r["acceptance_rate"] for r in base
            if isinstance(r.get("acceptance_rate"), (int, float))
        ])
        l_acc = latest.get("acceptance_rate")
        if b_acc and isinstance(l_acc, (int, float)):
            if l_acc < b_acc * (1.0 - tolerance):
                fails.append(
                    f"acceptance_rate {l_acc:.3f} fell below the "
                    f"{(1 - tolerance):.2f}x band under the baseline "
                    f"{b_acc:.3f} on a speculative run"
                )
    return fails


def _is_shared_prefix(rec: dict) -> bool:
    key = rec.get("key")
    return isinstance(key, dict) and key.get("profile") == "shared"


def _is_spec(rec: dict) -> bool:
    key = rec.get("key")
    return isinstance(key, dict) and bool(key.get("spec"))


def _ramp_or_top(rec: dict, name: str):
    """A gated counter: top-level on a ledger row, under ``ramp`` in a
    serve.json run doc — accept either, so the direct-doc fallback
    (custom --ledger paths) judges the same keys."""
    v = rec.get(name)
    if v is None:
        v = (rec.get("ramp") or {}).get(name)
    return v


def check_ab(recs: list[dict]) -> list[str]:
    """The continuous-batching acceptance verdict on the latest row:
    the A/B cell must exist and show continuous STRICTLY ahead."""
    if not recs:
        return []
    ab = recs[-1].get("ab")
    if not isinstance(ab, dict):
        return ["latest record carries no A/B cell (run without "
                "--no-serve-ab to record one)"]
    adv = ab.get("advantage_tokens")
    if not isinstance(adv, (int, float)) or adv <= 0:
        return [
            f"continuous batching did not beat static at the fixed "
            f"budget: continuous {ab.get('continuous_tokens_at_budget')} "
            f"vs static {ab.get('static_tokens_at_budget')} tokens "
            f"(budget {ab.get('budget_s')} s)"
        ]
    return []


def check_prefix_ab(recs: list[dict]) -> list[str]:
    """The radix-prefix-cache acceptance verdict on the latest row
    (PR 11): the cached-vs-cold cell must exist and show real skipped
    prefill work, a strict virtual-clock win at equal admission budget,
    and bitwise-matching token streams."""
    if not recs:
        return []
    latest = recs[-1]
    pab = latest.get("prefix_ab")
    if not isinstance(pab, dict):
        return ["latest record carries no prefix A/B cell (run with "
                "DDL25_SERVE_PREFIX=1 and without --no-serve-prefix-ab "
                "to record one)"]
    # a ledger row carries the flattened cell; a serve.json doc carries
    # the driver's full output with cached/cold sub-dicts — accept both
    cached_arm = pab.get("cached") or {}
    cold_arm = pab.get("cold") or {}
    pab = {
        **pab,
        "cached_tokens_per_sec_per_chip": pab.get(
            "cached_tokens_per_sec_per_chip",
            cached_arm.get("tokens_per_sec_per_chip"),
        ),
        "cold_tokens_per_sec_per_chip": pab.get(
            "cold_tokens_per_sec_per_chip",
            cold_arm.get("tokens_per_sec_per_chip"),
        ),
        "prefill_tokens_saved": pab.get(
            "prefill_tokens_saved", cached_arm.get("prefill_tokens_saved")
        ),
    }
    fails: list[str] = []
    saved = pab.get("prefill_tokens_saved")
    if not isinstance(saved, (int, float)) or saved <= 0:
        fails.append(
            f"prefix cache skipped no prefill work "
            f"(prefill_tokens_saved={saved}); on a shared-prefix trace "
            "the radix cache must hit"
        )
    cached_tps = pab.get("cached_tokens_per_sec_per_chip")
    cold_tps = pab.get("cold_tokens_per_sec_per_chip")
    if not (isinstance(cached_tps, (int, float))
            and isinstance(cold_tps, (int, float))
            and cached_tps > cold_tps):
        fails.append(
            f"cached engine not strictly faster on the virtual clock: "
            f"cached {cached_tps} vs cold {cold_tps} tokens/sec/chip "
            "at equal admission budget"
        )
    adv = pab.get("advantage_tokens")
    if not isinstance(adv, (int, float)) or adv <= 0:
        fails.append(
            f"cached engine not ahead at the fixed budget: cached "
            f"{pab.get('cached_tokens_at_budget')} vs cold "
            f"{pab.get('cold_tokens_at_budget')} tokens (budget "
            f"{pab.get('budget_s')} s)"
        )
    cmp_n = pab.get("compared_requests")
    if pab.get("tokens_match") is not True or not (
        isinstance(cmp_n, int) and cmp_n > 0
    ):
        # tokens_match is all() over the requests BOTH arms completed —
        # vacuously True over an empty intersection, so zero compared
        # requests is itself a gate failure, not a pass
        fails.append(
            "prefix-cached decode did not reproduce the cold path "
            f"token-for-token (tokens_match={pab.get('tokens_match')} "
            f"over {cmp_n} compared request(s); the comparison must "
            "cover at least one request)"
        )
    if _is_shared_prefix(latest):
        hit = _ramp_or_top(latest, "prefix_hit_rate")
        if not isinstance(hit, (int, float)) or hit <= 0:
            fails.append(
                f"prefix_hit_rate={hit} on a shared-prefix run (gated "
                "key: the seeded trace repeats its system prompts, so "
                "a zero hit rate is a cache defect, not workload noise)"
            )
    return fails


DEFAULT_RESHAPE_TTFT_FACTOR = 3.0


def check_reshape(
    recs: list[dict],
    ttft_factor: float = DEFAULT_RESHAPE_TTFT_FACTOR,
) -> list[str]:
    """The elastic-reshape acceptance verdict on the latest row
    (PR 14): the reshape cell must exist and show (a) at least one
    reshape event actually driven, (b) ZERO dropped requests across the
    handoff — a request accepted is a request served, the page-pool
    handoff's whole contract — and (c) p95 TTFT inside the reshape
    windows bounded at ``ttft_factor`` x the steady-state p95, over a
    non-empty window (an event nobody was waiting through proves
    nothing, the same vacuity hole the compared_requests guards
    close)."""
    if not recs:
        return []
    rsh = recs[-1].get("reshape")
    if not isinstance(rsh, dict):
        return ["latest record carries no reshape cell (arm elastic "
                "chaos — DDL25_CHAOS=traffic_spike@k / device_loss@k / "
                "capacity_change@k:N — on a bench.py --serve run to "
                "record one)"]
    fails: list[str] = []
    events = rsh.get("events") or []
    if not events:
        fails.append(
            "reshape cell carries no events: the armed chaos never "
            "drove a scale-up/down (wrong step index for the trace?)"
        )
    dropped = rsh.get("dropped_requests")
    if dropped != 0:
        fails.append(
            f"dropped_requests={dropped}: an admitted request was lost "
            f"across the handoff (admitted {rsh.get('admitted')} vs "
            f"completed {rsh.get('completed')}) — the drain/re-admit "
            "discipline must never lose accepted work"
        )
    steady = rsh.get("ttft_s_p95_steady")
    window = rsh.get("ttft_s_p95_reshape")
    n_window = rsh.get("reshape_window_requests")
    if not isinstance(n_window, int) or n_window < 1:
        fails.append(
            f"reshape_window_requests={n_window}: no request's first "
            "token landed inside a reshape window, so the TTFT bound "
            "is vacuous — fire the event while traffic is live"
        )
    elif not (isinstance(steady, (int, float))
              and isinstance(window, (int, float))):
        fails.append(
            f"reshape TTFT percentiles undefined (steady={steady}, "
            f"reshape={window}) with {n_window} window request(s)"
        )
    elif window > ttft_factor * steady:
        fails.append(
            f"p95 TTFT through the reshape window {window * 1e3:.2f} ms "
            f"exceeds {ttft_factor:.1f}x the steady-state p95 "
            f"{steady * 1e3:.2f} ms (over {n_window} window request(s))"
        )
    return fails


def check_spec_ab(recs: list[dict]) -> list[str]:
    """The speculative-decoding acceptance verdict on the latest row
    (PR 13): the spec-on-vs-off cell must exist and show real accepted
    draft work, a strict virtual-clock win at equal admission budget,
    and bitwise-matching token streams over at least one compared
    request (greedy speculation must BE the target's own output — an
    empty intersection would pass ``all()`` vacuously, the same hole
    the PR-11 ``compared_requests`` guard closed for the prefix gate).
    """
    if not recs:
        return []
    latest = recs[-1]
    sab = latest.get("spec_ab")
    if not isinstance(sab, dict):
        return ["latest record carries no spec A/B cell (run with "
                "DDL25_SERVE_SPEC=1 and without --no-serve-spec-ab "
                "to record one)"]
    # a ledger row carries the flattened cell; a serve.json doc carries
    # the driver's full output with spec/nospec sub-dicts — accept both
    spec_arm = sab.get("spec") or {}
    nospec_arm = sab.get("nospec") or {}
    sab = {
        **sab,
        "spec_tokens_per_sec_per_chip": sab.get(
            "spec_tokens_per_sec_per_chip",
            spec_arm.get("tokens_per_sec_per_chip"),
        ),
        "nospec_tokens_per_sec_per_chip": sab.get(
            "nospec_tokens_per_sec_per_chip",
            nospec_arm.get("tokens_per_sec_per_chip"),
        ),
        "draft_tokens_accepted": sab.get(
            "draft_tokens_accepted",
            spec_arm.get("draft_tokens_accepted"),
        ),
        "acceptance_rate": sab.get(
            "acceptance_rate", spec_arm.get("acceptance_rate")
        ),
    }
    fails: list[str] = []
    accepted = sab.get("draft_tokens_accepted")
    if not isinstance(accepted, (int, float)) or accepted <= 0:
        fails.append(
            f"the drafter contributed no accepted tokens "
            f"(draft_tokens_accepted={accepted}, acceptance_rate="
            f"{sab.get('acceptance_rate')}); speculation that never "
            "accepts only ever costs"
        )
    spec_tps = sab.get("spec_tokens_per_sec_per_chip")
    nospec_tps = sab.get("nospec_tokens_per_sec_per_chip")
    if not (isinstance(spec_tps, (int, float))
            and isinstance(nospec_tps, (int, float))
            and spec_tps > nospec_tps):
        fails.append(
            f"speculative engine not strictly faster on the virtual "
            f"clock: spec {spec_tps} vs non-spec {nospec_tps} "
            "tokens/sec/chip at equal admission budget"
        )
    adv = sab.get("advantage_tokens")
    if not isinstance(adv, (int, float)) or adv <= 0:
        fails.append(
            f"speculative engine not ahead at the fixed budget: spec "
            f"{sab.get('spec_tokens_at_budget')} vs non-spec "
            f"{sab.get('nospec_tokens_at_budget')} tokens (budget "
            f"{sab.get('budget_s')} s)"
        )
    cmp_n = sab.get("compared_requests")
    if sab.get("tokens_match") is not True or not (
        isinstance(cmp_n, int) and cmp_n > 0
    ):
        fails.append(
            "speculative decode did not reproduce the sequential "
            f"engine token-for-token (tokens_match="
            f"{sab.get('tokens_match')} over {cmp_n} compared "
            "request(s); the comparison must cover at least one "
            "request)"
        )
    return fails


def check_tp(recs: list[dict]) -> list[str]:
    """The TP-sharded serving acceptance verdict on the latest row
    (PR 18): the sharded-vs-dense cell must exist, the sharded arm's
    static per-chip residency must come in STRICTLY below the dense
    arm's (the whole point of dividing the KV head dim and the
    params), and the token streams must match bitwise over at least
    one compared request — vacuity-guarded exactly like the spec gate
    (an empty intersection passes ``all()`` for free)."""
    if not recs:
        return []
    latest = recs[-1]
    tab = latest.get("tp_ab")
    if not isinstance(tab, dict):
        return ["latest record carries no TP A/B cell (run with "
                "--serve-tp N / DDL25_SERVE_TP > 1 and without "
                "--no-serve-tp-ab to record one)"]
    # a ledger row carries the flattened cell; a serve.json doc carries
    # the driver's full output with sharded/dense sub-dicts — both work
    tp_arm = tab.get("sharded") or {}
    dense_arm = tab.get("dense") or {}
    tab = {
        **tab,
        "tp_mem_budget_bytes_per_chip": tab.get(
            "tp_mem_budget_bytes_per_chip",
            tp_arm.get("mem_budget_bytes_per_chip"),
        ),
        "dense_mem_budget_bytes_per_chip": tab.get(
            "dense_mem_budget_bytes_per_chip",
            dense_arm.get("mem_budget_bytes_per_chip"),
        ),
    }
    fails: list[str] = []
    shard = tab.get("tp_mem_budget_bytes_per_chip")
    dense = tab.get("dense_mem_budget_bytes_per_chip")
    if not (isinstance(shard, (int, float))
            and isinstance(dense, (int, float)) and shard < dense):
        fails.append(
            f"tp={tab.get('tp')} did not shrink the static per-chip "
            f"residency: sharded {shard} vs dense {dense} bytes "
            "(mem_budget_bytes must divide for the sharded engine to "
            "serve bigger models at all)"
        )
    if tab.get("budget_shrunk") is not True:
        fails.append(
            f"the driver's budget_shrunk verdict is "
            f"{tab.get('budget_shrunk')!r}, expected True"
        )
    cmp_n = tab.get("compared_requests")
    if tab.get("tokens_match") is not True or not (
        isinstance(cmp_n, int) and cmp_n > 0
    ):
        fails.append(
            "the sharded engine did not reproduce the dense oracle "
            f"token-for-token (tokens_match={tab.get('tokens_match')} "
            f"over {cmp_n} compared request(s); the comparison must "
            "cover at least one request)"
        )
    return fails


def histogram(xs: list[float], *, bins: int = 10, width: int = 40,
              scale: float = 1e3, unit: str = "ms") -> list[str]:
    """ASCII histogram lines (log-ish readable, linear bins)."""
    xs = [x for x in xs if isinstance(x, (int, float))]
    if not xs:
        return ["  (no samples)"]
    lo, hi = min(xs), max(xs)
    span = (hi - lo) or max(abs(hi), 1e-9)
    counts = [0] * bins
    for x in xs:
        i = min(int((x - lo) / span * bins), bins - 1)
        counts[i] += 1
    peak = max(counts)
    out = []
    for i, c in enumerate(counts):
        a = lo + span * i / bins
        b = lo + span * (i + 1) / bins
        bar = "#" * max(1 if c else 0, round(c / peak * width))
        out.append(
            f"  {a * scale:9.3f}-{b * scale:9.3f} {unit} "
            f"|{bar:<{width}}| {c}"
        )
    return out


def format_run(doc: dict) -> str:
    ramp = doc.get("ramp", {})
    key = doc.get("key", {})
    lines = [
        "serving run "
        + " ".join(f"{k}={key[k]}" for k in sorted(key))
        + f"  sha {(doc.get('git_sha') or '?')[:7]}",
        "",
        f"  requests {doc.get('requests')}  admitted {ramp.get('admitted')}"
        f"  rejected {ramp.get('rejected')} {ramp.get('rejected_by_reason')}"
        f"  completed {ramp.get('completed')}",
        f"  generated tokens {ramp.get('generated_tokens')}"
        f"  tokens/sec/chip "
        f"{_fmt(ramp.get('tokens_per_sec_per_chip'), 2)}"
        f"  (chips {ramp.get('n_chips')}, wall "
        f"{_fmt(ramp.get('wall_s'), 2)} s)",
        f"  TTFT p50 {_fmt(ramp.get('ttft_s_p50'), 2, 1e3, ' ms')}"
        f"  p95 {_fmt(ramp.get('ttft_s_p95'), 2, 1e3, ' ms')}"
        f"  |  per-token p50 "
        f"{_fmt(ramp.get('tok_latency_s_p50'), 2, 1e3, ' ms')}"
        f"  p95 {_fmt(ramp.get('tok_latency_s_p95'), 2, 1e3, ' ms')}",
        f"  queue depth max {ramp.get('queue_depth_max')}"
        f"  page pool peak {ramp.get('page_pool_peak_pages')}"
        f"/{ramp.get('page_pool_pages')} pages "
        f"({_fmt(ramp.get('page_pool_peak_occupancy'), 1, 100, '%')})"
        f"  pool-ok failures {ramp.get('pool_ok_failures')}",
    ]
    # PR 16: the per-request TTFT decomposition — "p95 regressed"
    # becomes "p95 regressed because queue-wait doubled"
    dec = ramp.get("ttft_decomp") or {}
    if dec.get("requests"):
        lines.append(
            f"  TTFT decomposition ({dec.get('clock')} clock, "
            f"{dec['requests']} req): queue-wait p50 "
            f"{_fmt(dec.get('queue_wait_s_p50'), 2, 1e3, ' ms')}"
            f" p95 {_fmt(dec.get('queue_wait_s_p95'), 2, 1e3, ' ms')}"
            f"  |  prefill p50 "
            f"{_fmt(dec.get('prefill_s_p50'), 2, 1e3, ' ms')}"
            f" p95 {_fmt(dec.get('prefill_s_p95'), 2, 1e3, ' ms')}"
            f"  |  first-decode p50 "
            f"{_fmt(dec.get('first_decode_s_p50'), 2, 1e3, ' ms')}"
            f" p95 {_fmt(dec.get('first_decode_s_p95'), 2, 1e3, ' ms')}"
        )
    prefix = ramp.get("prefix") or {}
    if prefix.get("enabled"):
        lines.append(
            f"  prefix cache: hit rate "
            f"{_fmt(ramp.get('prefix_hit_rate'), 1, 100, '%')} "
            f"({prefix.get('hits')}/{prefix.get('lookups')} admitted)  "
            f"prefill saved {ramp.get('prefill_tokens_saved')} tokens / "
            f"{_fmt(ramp.get('prefill_flops_saved'), 2, 1e-6, ' MFLOP')}"
            f"  cached pages {prefix.get('cached_pages')}  evictions "
            f"{prefix.get('evictions')}"
        )
    spec = ramp.get("spec") or {}
    if spec.get("enabled"):
        lines.append(
            f"  speculative decode: k={spec.get('k')} drafter "
            f"{spec.get('draft_layers')}L/{spec.get('draft_dim')}d "
            f"(flop ratio {_fmt(spec.get('flop_ratio'), 2)})  "
            f"acceptance "
            f"{_fmt(ramp.get('acceptance_rate'), 1, 100, '%')} "
            f"({ramp.get('draft_tokens_accepted')} accepted / "
            f"{ramp.get('draft_tokens_rejected')} rejected)  "
            f"rounds {spec.get('rounds')}  draft steps "
            f"{spec.get('draft_steps')}  accepts by prefix "
            f"{spec.get('accept_counts')}"
        )
    ab = doc.get("ab")
    if ab:
        lines += [
            "",
            "  continuous-vs-static A/B (virtual clock, tick "
            f"{_fmt(ab.get('tick_s'), 4)} s, budget "
            f"{_fmt(ab.get('budget_s'), 3)} s):",
            f"    continuous {ab.get('continuous_tokens_at_budget')} "
            f"tokens  static {ab.get('static_tokens_at_budget')} tokens  "
            f"advantage {ab.get('advantage_tokens')} "
            f"({_fmt(ab.get('advantage_frac'), 1, 100, '%')})",
        ]
    pab = doc.get("prefix_ab")
    if pab:
        cached = pab.get("cached") or {}
        cold = pab.get("cold") or {}
        lines += [
            "",
            "  cached-vs-cold prefix A/B (virtual clock, budget "
            f"{_fmt(pab.get('budget_s'), 3)} s, equal admission "
            "budget):",
            f"    cached {pab.get('cached_tokens_at_budget')} tokens  "
            f"cold {pab.get('cold_tokens_at_budget')} tokens  advantage "
            f"{pab.get('advantage_tokens')} "
            f"({_fmt(pab.get('advantage_frac'), 1, 100, '%')})",
            f"    tokens/sec/chip cached "
            f"{_fmt(cached.get('tokens_per_sec_per_chip'), 2)}"
            f" vs cold "
            f"{_fmt(cold.get('tokens_per_sec_per_chip'), 2)}"
            f"  hit rate {_fmt(cached.get('prefix_hit_rate'), 1, 100, '%')}"
            f"  saved {cached.get('prefill_tokens_saved')} tokens  "
            f"tokens match {pab.get('tokens_match')}",
        ]
    sab = doc.get("spec_ab")
    if sab:
        spec_arm = sab.get("spec") or {}
        nospec_arm = sab.get("nospec") or {}
        lines += [
            "",
            "  spec-on-vs-off A/B (virtual clock, budget "
            f"{_fmt(sab.get('budget_s'), 3)} s, equal admission "
            "budget; verify = 1 tick, drafter at its FLOP ratio):",
            f"    spec {sab.get('spec_tokens_at_budget')} tokens  "
            f"non-spec {sab.get('nospec_tokens_at_budget')} tokens  "
            f"advantage {sab.get('advantage_tokens')} "
            f"({_fmt(sab.get('advantage_frac'), 1, 100, '%')})",
            f"    tokens/sec/chip spec "
            f"{_fmt(spec_arm.get('tokens_per_sec_per_chip'), 2)}"
            f" vs non-spec "
            f"{_fmt(nospec_arm.get('tokens_per_sec_per_chip'), 2)}"
            f"  acceptance "
            f"{_fmt(spec_arm.get('acceptance_rate'), 1, 100, '%')}"
            f"  tokens match {sab.get('tokens_match')}",
        ]
    rsh = doc.get("reshape")
    if rsh:
        evs = rsh.get("events") or []
        lines += [
            "",
            f"  elastic reshape ({len(evs)} event(s), replicas "
            f"{rsh.get('replicas_start')} -> {rsh.get('replicas_end')}, "
            f"dropped {rsh.get('dropped_requests')}):",
        ]
        for ev in evs:
            lines.append(
                f"    {ev.get('reason')}: {ev.get('old')} -> "
                f"{ev.get('new')} at t={_fmt(ev.get('t'), 3)} s"
                f" (drained by {_fmt(ev.get('t_end'), 3)} s,"
                f" requeued {ev.get('requeued') or 0})"
            )
        lines.append(
            f"    TTFT p95 reshape window "
            f"{_fmt(rsh.get('ttft_s_p95_reshape'), 1, 1e3, ' ms')} "
            f"({rsh.get('reshape_window_requests')} req) vs steady "
            f"{_fmt(rsh.get('ttft_s_p95_steady'), 1, 1e3, ' ms')} "
            f"({rsh.get('steady_requests')} req)"
        )
    if doc.get("ttft_s"):
        lines += ["", "  TTFT histogram:"] + histogram(doc["ttft_s"])
    if doc.get("tick_wall_s"):
        lines += (
            ["", "  decode-tick wall histogram:"]
            + histogram(doc["tick_wall_s"])
        )
    return "\n".join(lines)


def format_group(key: tuple, recs: list[dict], last: int) -> str:
    key_s, host = key
    lines = [f"serve {key_s}  host {host}"]
    cols = (
        f"  {'when (utc)':<20}{'sha':<9}{'tok/s/chip':>11}"
        f"{'ttft p50':>11}{'ttft p95':>11}{'tok p95':>11}"
        f"{'adm':>5}{'rej':>5}{'pool%':>7}{'ab adv':>8}"
        f"{'hit%':>7}{'saved':>7}{'pfx adv':>8}"
        f"{'acc%':>7}{'dacc':>6}{'spec adv':>9}"
    )
    lines.append(cols)
    lines.append("  " + "-" * (len(cols) - 2))
    for rec in recs[-last:]:
        ts = rec.get("ts")
        when = (
            datetime.fromtimestamp(ts, tz=timezone.utc)
            .strftime("%Y-%m-%d %H:%M:%S")
            if isinstance(ts, (int, float)) else "?"
        )
        sha = (rec.get("git_sha") or "?")[:7]
        ab = rec.get("ab") or {}
        pab = rec.get("prefix_ab") or {}
        sab = rec.get("spec_ab") or {}
        lines.append(
            f"  {when:<20}{sha:<9}"
            f"{_fmt(rec.get('tokens_per_sec_per_chip'), 2):>11}"
            f"{_fmt(rec.get('ttft_s_p50'), 1, 1e3, 'ms'):>11}"
            f"{_fmt(rec.get('ttft_s_p95'), 1, 1e3, 'ms'):>11}"
            f"{_fmt(rec.get('tok_latency_s_p95'), 1, 1e3, 'ms'):>11}"
            f"{rec.get('admitted', '?'):>5}"
            f"{rec.get('rejected', '?'):>5}"
            f"{_fmt(rec.get('page_pool_peak_occupancy'), 0, 100, '%'):>7}"
            f"{_fmt(ab.get('advantage_tokens'), 0):>8}"
            f"{_fmt(rec.get('prefix_hit_rate'), 0, 100, '%'):>7}"
            f"{_fmt(rec.get('prefill_tokens_saved'), 0):>7}"
            f"{_fmt(pab.get('advantage_tokens'), 0):>8}"
            f"{_fmt(rec.get('acceptance_rate'), 0, 100, '%'):>7}"
            f"{_fmt(rec.get('draft_tokens_accepted'), 0):>6}"
            f"{_fmt(sab.get('advantage_tokens'), 0):>9}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir", nargs="?", default=None,
                    help="obs dir holding serve.json (omit with "
                         "--ledger-only for the trend tables alone)")
    ap.add_argument("--ledger", default=DEFAULT_LEDGER, metavar="JSONL")
    ap.add_argument("--ledger-only", action="store_true",
                    help="skip the run report; render/check the ledger")
    ap.add_argument("--last", type=int, default=8,
                    help="rows per key in the trend table")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="prior rows per key the baseline medians over")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="fractional regression band (0.5 = tokens/sec "
                         "may drop 50%%, p95 TTFT may grow 50%%); CPU CI "
                         "wall clocks want wide bands")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero when any (key, host) group's "
                         "latest row regresses past the band (the CI "
                         "serving gate)")
    ap.add_argument("--check-ab", action="store_true",
                    help="also fail when the latest row's "
                         "continuous-vs-static A/B does not show "
                         "continuous strictly ahead (implies --check)")
    ap.add_argument("--check-prefix-ab", action="store_true",
                    help="also fail when the latest row's cached-vs-"
                         "cold prefix A/B does not show skipped prefill "
                         "work, a strict virtual-clock win, and "
                         "matching token streams (implies --check)")
    ap.add_argument("--check-spec-ab", action="store_true",
                    help="also fail when the latest row's speculative "
                         "spec-on-vs-off A/B does not show accepted "
                         "draft tokens, a strict virtual-clock win, and "
                         "matching token streams over >= 1 compared "
                         "request (implies --check)")
    ap.add_argument("--check-tp", action="store_true",
                    help="also fail when the latest row's TP "
                         "sharded-vs-dense A/B does not show a strictly "
                         "smaller per-chip static residency and "
                         "matching token streams over >= 1 compared "
                         "request (implies --check)")
    ap.add_argument("--check-reshape", action="store_true",
                    help="also fail when the latest row's elastic "
                         "reshape cell does not show >= 1 driven event, "
                         "ZERO dropped (accepted-then-lost) requests "
                         "across the replica handoff, and reshape-"
                         "window p95 TTFT within --reshape-ttft-factor "
                         "of steady state (implies --check)")
    ap.add_argument("--reshape-ttft-factor", type=float,
                    default=DEFAULT_RESHAPE_TTFT_FACTOR,
                    help="allowed p95 TTFT inflation through a reshape "
                         "window vs steady state (default 3.0)")
    args = ap.parse_args(argv)
    if (args.check_ab or args.check_prefix_ab or args.check_spec_ab
            or args.check_tp or args.check_reshape):
        args.check = True  # a verdict nobody reads is not a gate

    if args.run_dir is None and not args.ledger_only:
        ap.error("pass a run_dir, or --ledger-only")

    doc = None
    if args.run_dir is not None:
        try:
            doc = read_serve_json(args.run_dir)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"no serving record at {args.run_dir}: {e}",
                  file=sys.stderr)
            return 2
        print(format_run(doc))
        print()

    records = read_ledger(args.ledger)
    if not records:
        print(f"no serve records in {args.ledger} (run "
              "bench.py --serve to populate it)", file=sys.stderr)
        return 2 if args.check else 0

    groups = group_records(records)
    # with a run_dir the A/B acceptance verdict gates THAT run's
    # (key, host) group ONLY; other groups' rows may legitimately have
    # been recorded with --no-serve-ab or hold a documented tie (an
    # unloaded engine serves both policies identically), and a stale
    # unrelated key must not wedge the gate forever.  Ledger-only mode
    # has no run to scope to and stays strict across every group.
    ab_scope = ledger_key(doc) if doc is not None else None
    verdicts: dict[tuple, dict] = {}
    for key, recs in groups.items():
        fails: list[str] = []
        note = None
        if ab_scope is None or key == ab_scope:
            # the A/B verdicts need no baseline: a single row gates
            if args.check_ab:
                fails += check_ab(recs)
            if args.check_prefix_ab:
                fails += check_prefix_ab(recs)
            if args.check_spec_ab:
                fails += check_spec_ab(recs)
            if args.check_tp:
                fails += check_tp(recs)
            if args.check_reshape:
                fails += check_reshape(recs, args.reshape_ttft_factor)
        if len(recs) < 2:
            if not fails:
                note = "no baseline yet (single record)"
        else:
            fails += check_group(recs, args.tolerance, args.window)
        verdicts[key] = {"fails": fails, "note": note}
    if ((args.check_ab or args.check_prefix_ab or args.check_spec_ab
            or args.check_tp or args.check_reshape)
            and ab_scope is not None and ab_scope not in groups):
        # the run under test never landed in this ledger (custom
        # --ledger path): judge its serve.json directly
        fails = check_ab([doc]) if args.check_ab else []
        if args.check_prefix_ab:
            fails += check_prefix_ab([doc])
        if args.check_spec_ab:
            fails += check_spec_ab([doc])
        if args.check_tp:
            fails += check_tp([doc])
        if args.check_reshape:
            fails += check_reshape([doc], args.reshape_ttft_factor)
        verdicts[ab_scope] = {"fails": fails, "note": None}
    bad = sum(len(v["fails"]) for v in verdicts.values())

    print(f"serve ledger: {args.ledger}  ({len(records)} record(s), "
          f"{len(groups)} key(s))\n")
    print("\n\n".join(
        format_group(k, v, args.last) for k, v in groups.items()
    ))

    if args.check:
        for key, v in verdicts.items():
            label = f"serve({key[0][:60]})"
            if v["note"]:
                print(f"CHECK NOTE {label}: {v['note']}", file=sys.stderr)
            for fail in v["fails"]:
                print(f"CHECK FAIL {label}: {fail}", file=sys.stderr)
        if bad:
            return 1
        ab_note = ", A/B advantage verified" if args.check_ab else ""
        if args.check_prefix_ab:
            ab_note += ", prefix A/B advantage verified"
        if args.check_spec_ab:
            ab_note += ", spec A/B advantage verified"
        if args.check_tp:
            ab_note += ", tp shrink + token equality verified"
        if args.check_reshape:
            ab_note += ", reshape handoff verified"
        print(f"\nserve check OK: {len(groups)} key(s) within the "
              f"{args.tolerance:.2f} tolerance band{ab_note}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
