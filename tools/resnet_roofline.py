#!/usr/bin/env python
"""Analytic per-layer roofline of the bench ResNet-18/CIFAR step on TPU v5e.

Why this exists: op-level `jax.profiler` traces hang over this image's
tunneled TPU transport (RESULTS §6a), so the "where does the other half of
the MXU go" question is answered with a model instead: for every conv in
the ResNet-18 CIFAR variant, compute

- FLOPs (fwd; bwd counted as 2x fwd: dgrad + wgrad);
- an MXU efficiency bound from systolic-array tiling: the contraction dim
  (Cin*kh*kw) pads up to a multiple of 128 lanes and the output-channel
  dim to the 128-wide MXU tile, so layers with Cin*9 or Cout below/not a
  multiple of 128 cannot use the full array (e.g. the 3->64 stem runs at
  27/128 = 21% contraction occupancy at best);
- an HBM-bandwidth bound from activation + weight traffic (bf16, fwd
  read+write, bwd read of saved activations + cotangents, GroupNorm's
  extra normalize pass);

and take per-layer time = max(compute_bound, bandwidth_bound) — which is
exactly the shared roofline the compile-time analytics project whole
programs onto, so each layer rides
``xla_analytics.roofline_projection`` with the chip's peak derated by
its MXU occupancy.  Chip numbers come from the one
``utils/flops.CHIP_SPECS`` table (nothing duplicated here; a drift test
in ``tests/test_flops_tools.py`` pins the fold).  The sum is the best
achievable step time for THIS architecture at THIS batch — the
structural ceiling — to compare against the measured step.

Run: ``python tools/resnet_roofline.py [--batch 1024]``.  Pure math, no
accelerator needed.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from ddl25spring_tpu.obs.xla_analytics import roofline_projection  # noqa: E402
from ddl25spring_tpu.utils.flops import CHIP_SPECS  # noqa: E402

CHIP = "TPU v5e"
# module constants kept as *views* of the shared spec table (the drift
# test asserts they are the same object's numbers, not fresh literals)
PEAK_BF16 = CHIP_SPECS[CHIP]["peak_bf16_flops"]
HBM_BW = CHIP_SPECS[CHIP]["hbm_bytes_per_s"]
MXU_LANE = 128           # systolic array width (contraction + out tiles)

# (name, H, W, Cin, Cout, k, stride, count) — ResNet-18 CIFAR variant
# (ddl25spring_tpu/models/resnet.py block_plan): stem + 4 groups of 2
# blocks; 1x1 projections at each stride-2 group entry
LAYERS = [
    ("stem 3x3/1", 32, 32, 3, 64, 3, 1, 1),
    ("g1 3x3", 32, 32, 64, 64, 3, 1, 4),
    ("g2 entry 3x3/2", 32, 32, 64, 128, 3, 2, 1),
    ("g2 1x1/2 proj", 32, 32, 64, 128, 1, 2, 1),
    ("g2 3x3", 16, 16, 128, 128, 3, 1, 3),
    ("g3 entry 3x3/2", 16, 16, 128, 256, 3, 2, 1),
    ("g3 1x1/2 proj", 16, 16, 128, 256, 1, 2, 1),
    ("g3 3x3", 8, 8, 256, 256, 3, 1, 3),
    ("g4 entry 3x3/2", 8, 8, 256, 512, 3, 2, 1),
    ("g4 1x1/2 proj", 8, 8, 256, 512, 1, 2, 1),
    ("g4 3x3", 4, 4, 512, 512, 3, 1, 3),
]


def ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def conv_cost(B, H, W, Cin, Cout, k, stride, bytes_per=2):
    """Return (flops_fwd, mxu_eff, bytes_fwd) for one conv."""
    Ho, Wo = H // stride, W // stride
    flops = 2.0 * B * Ho * Wo * Cin * Cout * k * k
    # MXU occupancy: contraction dim Cin*k*k and output dim Cout both pad
    # to 128; spatial*batch rows are abundant (>= thousands) so row
    # occupancy ~1
    red = Cin * k * k
    eff = (red / ceil_to(red, MXU_LANE)) * (Cout / ceil_to(Cout, MXU_LANE))
    bytes_ = bytes_per * (B * H * W * Cin + B * Ho * Wo * Cout
                          + Cin * Cout * k * k)
    return flops, eff, bytes_


def layer_rooflines(batch: int, chip: str = CHIP) -> list[dict]:
    """Per-layer roofline rows through the shared projection: each conv
    is one ``roofline_projection`` call with the chip's peak derated by
    the layer's MXU occupancy (fwd+bwd = 3x fwd for both FLOPs and
    traffic, as before the fold)."""
    spec = CHIP_SPECS[chip]
    rows = []
    for name, H, W, Cin, Cout, k, s, cnt in LAYERS:
        f, eff, by = conv_cost(batch, H, W, Cin, Cout, k, s)
        proj = roofline_projection(
            3 * f, 3 * by, 0.0, chips=[chip],
            specs={chip: {**spec, "peak_bf16_flops":
                          spec["peak_bf16_flops"] * eff}},
        )[chip]
        rows.append({
            "name": name,
            "count": cnt,
            "flops_fwd": f,
            "mxu_eff": eff,
            "bytes_fwd": by,
            "t_comp_s": proj["t_compute_s"],
            "t_bw_s": proj["t_hbm_s"],
            "t_s": proj["projected_step_s"] * cnt,
            "bound": proj["bound"],
        })
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=1024)
    args = ap.parse_args(argv)
    B = args.batch

    print(f"{'layer':18s} {'GF(fwd)':>8s} {'MXU eff':>8s} "
          f"{'t_comp':>8s} {'t_bw':>8s} {'t(ms,f+b)':>9s}")
    rows = layer_rooflines(B)
    tot_t = sum(r["t_s"] for r in rows)
    tot_f = sum(3 * r["flops_fwd"] * r["count"] for r in rows)
    for r in rows:
        print(f"{r['name']:18s} {r['flops_fwd'] / 1e9:8.1f} "
              f"{r['mxu_eff'] * 100:7.0f}% "
              f"{r['t_comp_s'] * 1e3:8.2f} {r['t_bw_s'] * 1e3:8.2f} "
              f"{r['t_s'] * 1e3:9.2f}")

    # GroupNorm + relu + residual adds: elementwise/reduction passes over
    # the activation footprint, bandwidth-bound.  How many full passes
    # survive depends on XLA fusion: ~12 unfused (stats, normalize,
    # relu, add and their grads all separate) down to ~4 when everything
    # fusable rides a conv epilogue and only the GroupNorm reductions
    # force extra sweeps.  Report both ends of the range.
    act_bytes = 2 * B * sum(
        (H // s) * (W // s) * Cout * cnt
        for _, H, W, _, Cout, _, s, cnt in LAYERS
    )
    opt_bytes = 2 * 11.2e6 * 3 * 4  # params+grad+momentum fp32 r/w
    t_opt = opt_bytes / HBM_BW
    print(f"{'sgd+momentum':18s} {'':8s} {'':8s} {'':8s} "
          f"{t_opt*1e3:8.2f} {t_opt*1e3:9.2f}")

    xla_flops = 2.98e12 * (B / 1024)  # bench-reported cost-model FLOPs
    print(f"\nconv FLOPs counted: {tot_f/1e12:.2f} TF "
          f"-> naive 100%-MXU time {tot_f/PEAK_BF16*1e3:.2f} ms")
    for passes, label in ((4, "well-fused"), (12, "unfused")):
        t_elem = passes * act_bytes / HBM_BW
        t = tot_t + t_elem + t_opt
        print(f"{label:>10s} ({passes:2d} elementwise passes): "
              f"step >= {t*1e3:6.2f} ms -> ceiling "
              f"{tot_f / PEAK_BF16 / t * 100:5.1f}% (this count) / "
              f"{xla_flops / PEAK_BF16 / t * 100:5.1f}% (bench's XLA count)")
    print(
        "\nReading: in the bench's own MFU accounting (XLA cost-model\n"
        "FLOPs), the well-fused bound is ~48% — and the measured 32.2 ms\n"
        "step (47.0%, RESULTS §6a) already sits AT it.  The headroom to\n"
        "55%+ MFU does not exist for THIS model at THIS batch on v5e:\n"
        "the stem runs at ~11% MXU occupancy (27/128 contraction lanes\n"
        "x 64/128 output lanes), group-1 convs at ~45%, and the\n"
        "GroupNorm reductions are irreducibly bandwidth-bound.  The\n"
        "recoverable inefficiency was per-dispatch overhead, which the\n"
        "scan-fused primary removes."
    )


if __name__ == "__main__":
    main()
