#!/usr/bin/env python
"""On-TPU microbenchmark: Pallas flash attention vs dense attention.

Times forward+backward of causal attention at growing context lengths and
prints a table (ms/iter, speedup, attention TFLOP/s).  The dense path is
``models.llama.causal_attention`` (fp32 softmax, the exact fallback the
model uses off-TPU); the flash path is ``ops.flash_attention`` (the
default on TPU).  Rationale: the reference fixes ctx at 256
(`lab/s01_b1_microbatches.py:24`) where dense is fine; flash is what makes
"ctx >> 256" viable — this records the crossover and the win.

Run on the real chip: ``python tools/flash_attention_bench.py``.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ctxs", type=int, nargs="+",
                    default=[512, 1024, 2048, 4096])
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=6)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from ddl25spring_tpu.models.llama import causal_attention
    from ddl25spring_tpu.ops.flash_attention import flash_attention

    dev = jax.devices()[0]
    dtype = jnp.bfloat16 if dev.platform == "tpu" else jnp.float32
    print(f"device: {dev.device_kind or dev.platform}, dtype: {dtype.__name__}, "
          f"B={args.batch} H={args.heads} hd={args.head_dim}, "
          f"fwd+bwd, {args.iters} iters")
    print(f"{'ctx':>6} {'dense ms':>9} {'flash ms':>9} {'speedup':>8} "
          f"{'flash TF/s':>10}")

    for L in args.ctxs:
        key = jax.random.PRNGKey(0)
        shape = (args.batch, L, args.heads, args.head_dim)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                     dtype) for i in range(3))

        def loss_dense(q, k, v):
            return causal_attention(q, k, v, dtype).astype(jnp.float32).sum()

        def loss_flash(q, k, v):
            return flash_attention(q, k, v).astype(jnp.float32).sum()

        def timeit(f):
            g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
            r = g(q, k, v)  # compile
            jax.block_until_ready(r)
            float(r[0].astype(jnp.float32).sum())
            t0 = time.perf_counter()
            for _ in range(args.iters):
                r = g(q, k, v)
            float(r[0].astype(jnp.float32).sum())
            return (time.perf_counter() - t0) / args.iters

        try:
            td = timeit(loss_dense)
        except Exception as e:  # noqa: BLE001
            if "memory" not in str(e).lower() and "hbm" not in str(e).lower():
                raise  # only OOM is an expected dense failure
            td = None
        tf_ = timeit(loss_flash)
        # causal attention FLOPs (fwd 2*2, bwd ~2x fwd): ~3.5 * 4 * B*H*L^2*hd
        # halved for causal masking
        flops = 3.5 * 4 * args.batch * args.heads * L * L * args.head_dim / 2
        dense_s = f"{td * 1e3:>9.2f}" if td else "  OOM(hbm)"
        speed_s = f"{td / tf_:>7.2f}x" if td else "       -"
        print(f"{L:>6} {dense_s} {tf_ * 1e3:>9.2f} {speed_s} "
              f"{flops / tf_ / 1e12:>10.1f}")


if __name__ == "__main__":
    main()
