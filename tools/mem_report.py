"""Render and gate the graft-mem runtime memory record (PR 17).

    python tools/mem_report.py --run runs/serve_smoke            # render
    python tools/mem_report.py --run runs/serve_smoke --check    # CI gate
    python tools/mem_report.py --run runs/serve_elastic --check \
        --require-step-down                      # + elastic memory proof
    python tools/mem_report.py --ledger runs/perf_ledger.jsonl --check

Two sources, same record schema (``ddl25spring_tpu/obs/memscope.py``):

- ``--run RUN_DIR`` reads the run's ``mem.json`` — the single
  ``record: "mem"`` document the serve/train driver wrote at exit:
  measured live-bytes / host-RSS peaks, the budget-vs-measured verdict,
  the KV-pool occupancy/fragmentation snapshot, and the drain-time leak
  check.  ``--check`` fails when the budget band is breached, any KV
  page leaked (each leak names its page + holder — page-table slot with
  the seated request's rid, or an orphan refcount), or the windowed
  monotone-growth detector fired during the run.
  ``--require-step-down`` additionally demands at least one elastic
  reshape step-down whose live bytes went DOWN — the proof a retired
  replica's pools were actually freed, not leaked into the retired
  roster.

- ``--ledger PATH`` trends ``record: "mem"`` rows the same way
  ``perf_report.py`` trends perf rows: within each (strategy, mesh,
  host) key the LATEST record's live/RSS peaks must sit within the
  ``--tolerance`` band over the median of up to ``--window`` priors.
  Single-record keys pass with a "no baseline yet" note; different
  hosts never gate each other.

Exit codes: 0 ok, 1 check failed, 2 no data.  Pure stdlib — no jax
import, so the gate runs anywhere the JSON does.
"""

from __future__ import annotations

import json
import statistics
import sys
from pathlib import Path

MEM_BASENAME = "mem.json"            # restated from obs/memscope.py
DEFAULT_LEDGER = "runs/perf_ledger.jsonl"
DEFAULT_TOLERANCE = 0.5
DEFAULT_WINDOW = 5


def read_ledger(path: str) -> list[dict]:
    """Parseable ``record: "mem"`` rows in append order (torn lines
    skipped) — the perf_report.py contract, filtered to the mem kind."""
    out: list[dict] = []
    p = Path(path)
    if not p.exists():
        return out
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("record") == "mem":
            out.append(rec)
    return out


def ledger_key(rec: dict) -> tuple[str, str, str]:
    mesh = rec.get("mesh")
    mesh_s = (
        ",".join(f"{k}={v}" for k, v in mesh.items())
        if isinstance(mesh, dict) else str(mesh)
    )
    return (str(rec.get("strategy")), mesh_s, str(rec.get("host")))


def _mib(v) -> str:
    if not isinstance(v, (int, float)):
        return "n/a"
    return f"{v / (1 << 20):.1f} MiB"


def check_record(rec: dict, require_step_down: bool = False) -> list[str]:
    """The --run gate: [] means the record passes."""
    fails: list[str] = []
    b = rec.get("budget") or {}
    if b.get("available") and b.get("within_band") is False:
        fails.append(
            f"budget band breached: measured {_mib(b.get('measured_peak_bytes'))} "
            f"is {b.get('ratio')}x the accounted "
            f"{_mib(b.get('budget_bytes'))} budget "
            f"({b.get('source')}; tolerance {b.get('tolerance')})"
        )
    leaked = rec.get("leaked_pages", 0)
    if leaked:
        names = []
        for chk in rec.get("leaks") or []:
            for leak in (chk.get("leaks") or [])[:8]:
                if leak.get("held_by") == "page_table":
                    who = f"slot {leak.get('slot')}"
                    if leak.get("rid") is not None:
                        who += f" (rid {leak['rid']})"
                else:
                    who = "orphan refcount"
                names.append(
                    f"page {leak.get('page')} held by {who} "
                    f"(refcount {leak.get('refcount')})"
                )
        fails.append(
            f"{leaked} KV page(s) leaked at drain: "
            + ("; ".join(names) if names else "no attribution recorded")
        )
    growth = rec.get("growth_violations", 0)
    if growth:
        srcs = [
            f"{v.get('source')} grew {_mib(v.get('growth_bytes'))} over "
            f"{v.get('window')} consecutive samples"
            for v in (rec.get("memscope") or {}).get(
                "growth_violations", [])[:4]
        ]
        fails.append(
            f"{growth} monotone-growth violation(s): "
            + ("; ".join(srcs) if srcs else "see memscope cell")
        )
    if require_step_down:
        steps = rec.get("reshape_steps") or []
        downs = [
            s for s in steps
            if isinstance(s.get("step_down_bytes"), (int, float))
            and s["step_down_bytes"] > 0
        ]
        if not downs:
            fails.append(
                "--require-step-down: no elastic reshape step-down with "
                f"live bytes going DOWN recorded ({len(steps)} reshape "
                "step(s) present) — a retired replica's pools were "
                "never freed"
            )
        bad_leaks = [s for s in steps if s.get("leak_ok") is False]
        if bad_leaks:
            fails.append(
                f"{len(bad_leaks)} reshape step-down(s) retired a "
                "replica with a leaking pool"
            )
    return fails


def check_group(
    recs: list[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    window: int = DEFAULT_WINDOW,
) -> list[str]:
    """Trend verdicts for one ledger key: latest live/RSS peak within
    the band over the median of up to ``window`` priors."""
    if len(recs) < 2:
        return []
    latest = recs[-1]
    base = recs[:-1][-window:]
    fails: list[str] = []
    for field in ("live_bytes_peak", "rss_bytes_peak"):
        b = statistics.median([
            (r.get("memscope") or {}).get(field) for r in base
            if isinstance((r.get("memscope") or {}).get(field),
                          (int, float))
        ] or [0])
        lv = (latest.get("memscope") or {}).get(field)
        if b and isinstance(lv, (int, float)):
            if lv > b * (1.0 + tolerance):
                fails.append(
                    f"{field} {_mib(lv)} exceeds the "
                    f"{(1 + tolerance):.2f}x band over the baseline "
                    f"{_mib(b)} (median of {len(base)} prior record(s))"
                )
    return fails


def format_record(rec: dict) -> str:
    lines = [
        f"strategy {rec.get('strategy')}  mesh {rec.get('mesh')}  "
        f"host {rec.get('host')}  sha "
        f"{(rec.get('git_sha') or '?')[:7]}"
    ]
    scope = rec.get("memscope") or {}
    lines.append(
        f"  live bytes peak {_mib(scope.get('live_bytes_peak'))}  "
        f"host RSS peak {_mib(scope.get('rss_bytes_peak'))}  "
        f"samples {scope.get('samples')} "
        f"(every {scope.get('every')} tick(s))"
    )
    if scope.get("live_bytes_baseline") is not None:
        lines.append(
            f"  live-bytes baseline (post-build) "
            f"{_mib(scope['live_bytes_baseline'])}"
        )
    b = rec.get("budget") or {}
    if b.get("available"):
        verdict = "WITHIN BAND" if b.get("within_band") else "BREACHED"
        lines.append(
            f"  budget ({b.get('source')}): accounted "
            f"{_mib(b.get('budget_bytes'))}, measured/budget "
            f"{b.get('ratio')}, tolerance {b.get('tolerance')} -> "
            f"{verdict}"
        )
    else:
        lines.append(
            f"  budget: unavailable ({b.get('source', '?')})"
        )
    pool = rec.get("pool")
    if pool:
        fr = pool.get("free_runs") or {}
        lines.append(
            f"  kv pool: {pool.get('used_pages')}/{pool.get('n_pages')} "
            f"pages used (occupancy {pool.get('occupancy')}) — "
            f"cache-held {pool.get('cache_held_pages')}, table-held "
            f"{pool.get('table_held_pages')}"
        )
        lines.append(
            f"  free runs: {fr.get('count')} run(s), max "
            f"{fr.get('max')}, fragmentation {pool.get('fragmentation')}"
        )
    lines.append(
        f"  leaked pages {rec.get('leaked_pages', 0)}  "
        f"growth violations {rec.get('growth_violations', 0)}"
    )
    steps = rec.get("reshape_steps")
    if steps:
        for s in steps:
            lines.append(
                f"  reshape step-down [{s.get('scope')}:"
                f"{s.get('reason')}]: {_mib(s.get('live_bytes_before'))}"
                f" -> {_mib(s.get('live_bytes_after'))} "
                f"(freed {_mib(s.get('step_down_bytes'))}"
                + (
                    f", leak check "
                    f"{'ok' if s.get('leak_ok') else 'FAILED'}"
                    if "leak_ok" in s else ""
                )
                + ")"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--run", default=None, metavar="RUN_DIR",
                    help=f"run directory holding {MEM_BASENAME} "
                         "(written by bench.py when graft-mem is on)")
    ap.add_argument("--ledger", default=None, metavar="JSONL",
                    help="trend record:\"mem\" rows in this ledger "
                         f"instead (default {DEFAULT_LEDGER} when "
                         "--run is absent)")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="prior records per key the trend baseline "
                         "medians over")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="fractional trend band on live/RSS peaks "
                         "(0.5 = may grow 50%%)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on budget breach / leaked "
                         "pages / growth violations (--run) or a "
                         "trend regression (--ledger) — the CI gate")
    ap.add_argument("--require-step-down", action="store_true",
                    help="with --run --check: also fail unless at "
                         "least one elastic reshape step-down freed "
                         "live bytes (and none leaked)")
    ap.add_argument("--format", choices=("table", "json"),
                    default="table")
    args = ap.parse_args(argv)

    if args.run is not None:
        path = Path(args.run) / MEM_BASENAME
        if not path.exists():
            print(f"no {MEM_BASENAME} at {args.run} (graft-mem off? "
                  "check DDL25_OBS / DDL25_MEMSCOPE)", file=sys.stderr)
            return 2
        try:
            rec = json.loads(path.read_text())
        except (json.JSONDecodeError, OSError) as e:
            print(f"unreadable {path}: {e}", file=sys.stderr)
            return 2
        fails = check_record(rec, args.require_step_down)
        if args.format == "json":
            print(json.dumps({
                "record": "mem_report", "run": args.run, "mem": rec,
                "check": {"ok": not fails, "fails": fails},
            }, indent=1, default=str))
        else:
            print(f"mem record: {path}\n")
            print(format_record(rec))
        if args.check:
            for fail in fails:
                print(f"CHECK FAIL: {fail}", file=sys.stderr)
            if fails:
                return 1
            print(f"\nmem check OK for {args.run}: budget within band, "
                  "zero leaked pages, zero growth violations"
                  + (", elastic step-down present"
                     if args.require_step_down else ""),
                  file=sys.stderr)
        return 0

    ledger = args.ledger or DEFAULT_LEDGER
    records = read_ledger(ledger)
    if not records:
        print(f"no mem records in {ledger} (run bench.py with obs on "
              "to populate it)", file=sys.stderr)
        return 2 if args.check else 0
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        groups.setdefault(ledger_key(rec), []).append(rec)
    verdicts = {
        key: {
            "fails": check_group(recs, args.tolerance, args.window),
            "note": ("no baseline yet (single record)"
                     if len(recs) < 2 else None),
        }
        for key, recs in groups.items()
    }
    bad = sum(len(v["fails"]) for v in verdicts.values())
    if args.format == "json":
        print(json.dumps({
            "record": "mem_report", "ledger": ledger,
            "tolerance": args.tolerance, "window": args.window,
            "groups": [
                {"strategy": k[0], "mesh": k[1], "host": k[2],
                 "records": len(v), "fails": verdicts[k]["fails"],
                 "note": verdicts[k]["note"]}
                for k, v in groups.items()
            ],
            "check": {"ok": bad == 0, "fails": bad},
        }, indent=1, default=str))
    else:
        print(f"mem ledger: {ledger}  ({len(records)} record(s), "
              f"{len(groups)} key(s))\n")
        print("\n\n".join(
            format_record(recs[-1]) for recs in groups.values()
        ))
    if args.check:
        for key, v in verdicts.items():
            label = f"{key[0]} mesh({key[1]})"
            if v["note"]:
                print(f"CHECK NOTE {label}: {v['note']}",
                      file=sys.stderr)
            for fail in v["fails"]:
                print(f"CHECK FAIL {label}: {fail}", file=sys.stderr)
        if bad:
            return 1
        print(f"\nmem trend check OK: {len(groups)} key(s) within the "
              f"{args.tolerance:.2f} band", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
