"""Merge a run's telemetry into ONE Perfetto/Chrome trace (PR 16).

    python tools/trace_export.py <run_dir>            # write trace_merged.json
    python tools/trace_export.py <run_dir> --check    # + span-chain gate

Inputs (all from the run directory; only the timeline is required):

- ``timeline.jsonl``  — the graft-trace event log (``obs/timeline.py``):
  serve request lifecycles, reshape windows, mirrored chaos / autosave /
  watchdog / sentinel fires.  Its header's ``time_origin_unix_s``
  anchors the merged trace's time axis.
- ``trace.json``      — the ``obs/spans.py`` host spans (already Chrome
  format); shifted onto the common axis via its own
  ``otherData.time_origin_unix_s``.
- ``flight.json``     — the flight-recorder ring; records become
  instants on a "flight ring" track (needs the recorder's
  ``time_origin_unix_s``, present from PR 16 on — older dumps are
  skipped with a note).
- ``goodput.json``    — the graft-goodput decomposition
  (``obs/goodput.py``): each badput window becomes a complete ("X")
  span on a per-lineage goodput track, one thread row per bucket, so
  warmup / checkpoint / replay / reshape time lines up under the
  request spans and subsystem tracks it explains.

Output: ``trace_merged.json`` (Chrome JSON object format — open in
https://ui.perfetto.dev or ``chrome://tracing``) with

- one process ("track") per serve engine replica, one thread row per
  request, each request a flow-arrow-linked span chain
  ``queue -> prefill -> decode`` with ``first_token`` / ``spec_round`` /
  ``reject`` / ``drain-handoff`` instants riding the rows;
- a "subsystems" process: chaos / reshape / autosave / watchdog /
  sentinel tracks, with each elastic reshape window rendered as a
  track-level span (paired ``reshape`` -> ``reshape_end`` events);
- the host spans and the flight ring alongside, on the same clock;
- a "resources" process of Perfetto counter tracks (``"ph":"C"``) built
  from graft-mem ``mem_sample`` events: pool occupancy, queue depth,
  live bytes, host RSS, tokens/sec — same ``t_wall_s`` base, so memory
  lines up under the request spans (``--min-counter-tracks`` gates it).

``--check`` is the CI gate: every admitted request's span chain must be
complete — no orphan ``serve_admit`` without a terminal ``serve_done``
(a drain-handoff is an intermediate leg: the request must still admit
and finish on a survivor).  Submitted-but-never-seated requests (run
ended mid-queue under a wall budget) are reported, not failed.  When
the run carries a ``goodput.json``, ``--check`` also refuses a goodput
section whose windows overlap one another, run past the lineage's
total wall, or whose bucket seconds sum past total wall beyond the
pinned tolerance — an overlapping decomposition double-bills chip
time, which is exactly the lie goodput exists to prevent.

Everything here is stdlib-only, like the other report tools: the gate
must run anywhere CI can run python.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

TIMELINE_BASENAME = "timeline.jsonl"  # restated from obs/timeline.py
TRACE_BASENAME = "trace.json"         # restated from obs/spans.py usage
FLIGHT_BASENAME = "flight.json"       # restated from obs/recorder.py
GOODPUT_BASENAME = "goodput.json"     # restated from obs/goodput.py
MERGED_BASENAME = "trace_merged.json"

# restated from obs/goodput.py SUM_TOLERANCE: bucket seconds may sum
# past total wall by at most this fraction before --check refuses
GOODPUT_SUM_TOLERANCE = 0.02
# two goodput windows may touch within this slack (float accumulation
# across a multi-attempt lineage) without counting as an overlap
GOODPUT_OVERLAP_SLACK_S = 1e-3

# synthetic pids, far above any real os.getpid() the span recorder
# stamped, so the merged view never interleaves two unrelated tracks
PID_SUBSYS = 1_000_000
PID_FLIGHT = 1_000_001
PID_COUNTERS = 1_000_002  # graft-mem resource counter tracks (ph=C)
PID_GOODPUT = 1_000_003   # graft-goodput per-lineage badput windows
PID_REPLICA0 = 1_000_100  # + stable replica ordinal per serve track

# mem_sample fields that become Perfetto counter tracks ("ph":"C"),
# one track per (field, engine/replica source), all on the shared
# t_wall_s time base so they line up under the request spans
_COUNTER_FIELDS = (
    "live_bytes", "rss_bytes", "pool_used", "queue_depth",
    "tokens_per_s",
)

_SUBSYS_TIDS = {
    "chaos": (1, "chaos"),
    "reshape": (2, "reshape"),
    "reshape_end": (2, "reshape"),
    "save": (3, "autosave"),
    "save_skipped": (3, "autosave"),
    "restore": (3, "autosave"),
    "stall": (4, "watchdog"),
    "violation": (5, "sentinels"),
}

_REQUEST_KINDS = {
    "serve_submit", "serve_reject", "serve_admit", "serve_prefill",
    "serve_first_token", "serve_spec_round", "serve_done",
    "serve_drain", "serve_drain_handoff",
}


def read_timeline(run_dir: str) -> tuple[dict, list[dict]]:
    """(header, events) from timeline.jsonl — strict JSON, like the
    writer (a NaN that sneaks in is a bug, not data)."""

    def _reject(_):
        raise ValueError("non-finite constant in timeline.jsonl")

    header: dict = {}
    events: list[dict] = []
    with open(os.path.join(run_dir, TIMELINE_BASENAME)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line, parse_constant=_reject)
            if rec.get("record") == "timeline_header":
                header = rec
            else:
                events.append(rec)
    if "time_origin_unix_s" not in header:
        raise ValueError(
            f"{TIMELINE_BASENAME} carries no header — configure() the "
            "timeline at a run dir before emitting"
        )
    return header, events


def _args_of(ev: dict) -> dict:
    return {
        k: v for k, v in ev.items()
        if k not in ("record", "seq", "kind", "t_wall_s")
    }


def _group_requests(events: list[dict]) -> dict[tuple, dict]:
    """Fold request-lifecycle events into per-(engine, rid) chains.
    rids are only unique within an engine label (the ramp engine and
    the elastic driver each count from their own 0)."""
    chains: dict[tuple, dict] = {}
    for ev in events:
        if ev.get("kind") not in _REQUEST_KINDS or "rid" not in ev:
            continue
        key = (ev.get("engine", "serve"), ev["rid"])
        c = chains.setdefault(key, {"events": [], "replica": None})
        c["events"].append(ev)
        # the chain renders on the replica that SEATED the request
        # (falls back to the submitting replica for rejected/queued)
        if ev["kind"] == "serve_admit":
            c["replica"] = ev.get("replica", 0)
        elif c["replica"] is None and "replica" in ev:
            c["replica"] = ev["replica"]
    return chains


def _first(chain: list[dict], kind: str) -> dict | None:
    for ev in chain:
        if ev["kind"] == kind:
            return ev
    return None


def _last(chain: list[dict], kind: str) -> dict | None:
    out = None
    for ev in chain:
        if ev["kind"] == kind:
            out = ev
    return out


def check_chains(events: list[dict]) -> tuple[list[str], dict]:
    """The --check gate.  Returns (failures, stats)."""
    chains = _group_requests(events)
    fails: list[str] = []
    admitted = done = rejected = pending = handoffs = 0
    for (engine, rid), c in sorted(chains.items(), key=lambda kv: (
            kv[0][0], kv[0][1])):
        evs = c["events"]
        kinds = [e["kind"] for e in evs]
        handoffs += kinds.count("serve_drain_handoff")
        if "serve_admit" in kinds:
            admitted += 1
            if "serve_first_token" not in kinds:
                fails.append(
                    f"{engine}:rid={rid} admitted without a first_token"
                )
            if "serve_done" in kinds:
                done += 1
            else:
                fails.append(
                    f"{engine}:rid={rid} orphan admit — no terminal "
                    f"serve_done (kinds: {kinds})"
                )
        elif "serve_reject" in kinds:
            rejected += 1
        else:
            pending += 1  # never seated: ended the run still queued
    stats = {
        "requests": len(chains),
        "admitted": admitted,
        "complete": done,
        "rejected": rejected,
        "pending": pending,
        "drain_handoffs": handoffs,
    }
    return fails, stats


def read_goodput(run_dir: str) -> dict | None:
    """The run's goodput.json decomposition, or None when absent /
    unparseable (older runs predate graft-goodput; that is a note,
    not a failure)."""
    path = os.path.join(run_dir, GOODPUT_BASENAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("record") != "goodput":
        return None
    return doc


def check_goodput(doc: dict) -> list[str]:
    """The goodput leg of --check.  A decomposition is a partition of
    the lineage's wall clock: windows must not overlap each other, must
    not run past total wall, and bucket seconds must not sum past total
    wall beyond GOODPUT_SUM_TOLERANCE."""
    fails: list[str] = []
    total = doc.get("total_wall_s")
    windows = [
        w for w in (doc.get("windows") or [])
        if isinstance(w, dict)
        and isinstance(w.get("t0_s"), (int, float))
        and isinstance(w.get("t1_s"), (int, float))
    ]
    by_start = sorted(windows, key=lambda w: (w["t0_s"], w["t1_s"]))
    for prev, cur in zip(by_start, by_start[1:]):
        if cur["t0_s"] < prev["t1_s"] - GOODPUT_OVERLAP_SLACK_S:
            fails.append(
                f"windows overlap: {prev.get('bucket')}"
                f"[{prev['t0_s']:.3f},{prev['t1_s']:.3f}] vs "
                f"{cur.get('bucket')}"
                f"[{cur['t0_s']:.3f},{cur['t1_s']:.3f}] — the "
                "decomposition double-bills that interval"
            )
    if isinstance(total, (int, float)):
        for w in by_start:
            if w["t1_s"] > total + GOODPUT_OVERLAP_SLACK_S:
                fails.append(
                    f"window {w.get('bucket')}"
                    f"[{w['t0_s']:.3f},{w['t1_s']:.3f}] runs past "
                    f"total wall {total:.3f}s"
                )
            if w["t0_s"] < -GOODPUT_OVERLAP_SLACK_S:
                fails.append(
                    f"window {w.get('bucket')} starts before the "
                    f"lineage origin (t0={w['t0_s']:.3f}s)"
                )
        seconds = doc.get("seconds") or {}
        attributed = sum(
            v for v in seconds.values() if isinstance(v, (int, float)))
        if attributed > total * (1.0 + GOODPUT_SUM_TOLERANCE) + 1e-9:
            fails.append(
                f"bucket seconds sum to {attributed:.3f}s > total wall "
                f"{total:.3f}s beyond the {GOODPUT_SUM_TOLERANCE:.0%} "
                "tolerance"
            )
    sc = doc.get("sum_check")
    if isinstance(sc, dict) and sc.get("ok") is False:
        fails.append(
            f"goodput's own sum_check is marked failed: {sc}")
    return fails


def merge(run_dir: str) -> tuple[dict, dict]:
    """Build the merged Chrome trace; returns (trace_doc, notes)."""
    header, events = read_timeline(run_dir)
    t0_unix = header["time_origin_unix_s"]
    out: list[dict] = []
    notes: dict = {"timeline_events": len(events)}

    def ts(ev: dict) -> float:  # event -> merged-axis microseconds
        return ev["t_wall_s"] * 1e6

    def meta(pid, name, tid=None, tname=None):
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0, "args": {"name": name}})
        if tid is not None:
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": tname}})

    # ---- serve request chains: track per replica, row per request --
    chains = _group_requests(events)
    replica_pids: dict[tuple, int] = {}
    for (engine, rid), c in sorted(chains.items(), key=lambda kv: (
            kv[0][0], kv[0][1])):
        rep = c["replica"] or 0
        rkey = (engine, rep)
        if rkey not in replica_pids:
            pid = PID_REPLICA0 + len(replica_pids)
            replica_pids[rkey] = pid
            meta(pid, f"serve:{engine} replica {rep}")
        pid = replica_pids[rkey]
        tid = rid + 1  # tid 0 is the metadata row
        meta(pid, f"serve:{engine} replica {rep}", tid, f"req {rid}")
        evs = c["events"]
        flow_id = f"{engine}:{rid}"
        sub = _first(evs, "serve_submit")
        adm = _first(evs, "serve_admit")
        rej = _first(evs, "serve_reject")
        ftk = _first(evs, "serve_first_token")
        dne = _last(evs, "serve_done")
        base = {"pid": pid, "tid": tid, "cat": "serve_request"}
        if sub is not None and adm is not None:
            out.append({**base, "ph": "X", "name": "queue",
                        "ts": ts(sub), "dur": max(ts(adm) - ts(sub), 1),
                        "args": _args_of(sub)})
            out.append({**base, "ph": "s", "id": flow_id, "name": "req",
                        "ts": ts(sub)})
        if sub is not None and rej is not None:
            out.append({**base, "ph": "i", "s": "t", "name":
                        f"reject:{rej.get('reason')}", "ts": ts(rej),
                        "args": _args_of(rej)})
        for ho in (e for e in evs if e["kind"] == "serve_drain_handoff"):
            out.append({**base, "ph": "i", "s": "t",
                        "name": "drain-handoff", "ts": ts(ho),
                        "args": _args_of(ho)})
        if adm is not None and ftk is not None:
            out.append({**base, "ph": "X", "name": "prefill",
                        "ts": ts(adm), "dur": max(ts(ftk) - ts(adm), 1),
                        "args": _args_of(
                            _first(evs, "serve_prefill") or adm)})
            out.append({**base, "ph": "t", "id": flow_id, "name": "req",
                        "ts": ts(adm) + 1})
        if ftk is not None:
            out.append({**base, "ph": "i", "s": "t", "name":
                        "first_token", "ts": ts(ftk),
                        "args": _args_of(ftk)})
        if ftk is not None and dne is not None:
            out.append({**base, "ph": "X", "name": "decode",
                        "ts": ts(ftk), "dur": max(ts(dne) - ts(ftk), 1),
                        "args": _args_of(dne)})
            out.append({**base, "ph": "f", "bp": "e", "id": flow_id,
                        "name": "req", "ts": ts(ftk) + 1})
        for sr in (e for e in evs if e["kind"] == "serve_spec_round"):
            out.append({**base, "ph": "i", "s": "t",
                        "name": f"spec_round[{sr.get('accepted')}/"
                                f"{sr.get('accepted', 0) + sr.get('rejected', 0)}]",
                        "ts": ts(sr), "args": _args_of(sr)})

    # ---- subsystem tracks (+ reshape windows as track spans) -------
    meta(PID_SUBSYS, "subsystems")
    seen_tids = set()
    reshape_starts: list[dict] = []
    for ev in events:
        kind = ev.get("kind")
        if kind not in _SUBSYS_TIDS:
            continue
        tid, tname = _SUBSYS_TIDS[kind]
        if tid not in seen_tids:
            seen_tids.add(tid)
            meta(PID_SUBSYS, "subsystems", tid, tname)
        if kind == "reshape":
            reshape_starts.append(ev)
        if kind == "reshape_end":
            # pair with the matching start (same virtual t + reason)
            start = next(
                (s for s in reshape_starts
                 if s.get("t") == ev.get("t")
                 and s.get("reason") == ev.get("reason")), None)
            ts0 = ts(start) if start is not None else ts(ev)
            out.append({"pid": PID_SUBSYS, "tid": tid, "ph": "X",
                        "cat": "reshape_window",
                        "name": f"reshape:{ev.get('reason')}",
                        "ts": ts0, "dur": max(ts(ev) - ts0, 1),
                        "args": _args_of(ev)})
            continue
        out.append({"pid": PID_SUBSYS, "tid": tid, "ph": "i", "s": "t",
                    "cat": "subsystem", "name": kind, "ts": ts(ev),
                    "args": _args_of(ev)})
    # serve_drain markers ride the reshape track too (replica roster)
    for ev in events:
        if ev.get("kind") != "serve_drain":
            continue
        tid, tname = _SUBSYS_TIDS["reshape"]
        if tid not in seen_tids:
            seen_tids.add(tid)
            meta(PID_SUBSYS, "subsystems", tid, tname)
        out.append({"pid": PID_SUBSYS, "tid": tid, "ph": "i", "s": "t",
                    "cat": "subsystem",
                    "name": f"drain:replica{ev.get('replica')}",
                    "ts": ts(ev), "args": _args_of(ev)})

    # ---- resource counter tracks (graft-mem mem_sample events) -----
    notes["counter_tracks"] = 0
    counter_names: set[tuple] = set()
    for ev in events:
        if ev.get("kind") != "mem_sample":
            continue
        src = ev.get("engine", "run")
        if ev.get("replica") is not None:
            src = f"{src}/r{ev['replica']}"
        for field in _COUNTER_FIELDS:
            if field not in ev:
                continue
            name = f"{field} [{src}]"
            key = (PID_COUNTERS, name)
            if key not in counter_names:
                counter_names.add(key)
                if len(counter_names) == 1:
                    meta(PID_COUNTERS, "resources")
            out.append({"pid": PID_COUNTERS, "tid": 0, "ph": "C",
                        "cat": "resource", "name": name, "ts": ts(ev),
                        "args": {field: ev[field]}})
    notes["counter_tracks"] = len(counter_names)

    # ---- goodput badput windows (obs/goodput.py goodput.json) ------
    notes["goodput_windows"] = 0
    gdoc = read_goodput(run_dir)
    if gdoc is not None:
        gp_origin = gdoc.get("time_origin_unix_s")
        if gp_origin is None:
            notes["goodput_note"] = (
                f"{GOODPUT_BASENAME} carries no time_origin_unix_s; "
                "windows not merged")
        else:
            shift = (gp_origin - t0_unix) * 1e6
            lineage = gdoc.get("lineage_id") or "?"
            title = f"goodput [lineage {lineage}]"
            meta(PID_GOODPUT, title)
            gp_tids: dict[str, int] = {}
            for w in gdoc.get("windows") or []:
                if not (isinstance(w, dict)
                        and isinstance(w.get("t0_s"), (int, float))
                        and isinstance(w.get("t1_s"), (int, float))):
                    continue
                bucket = str(w.get("bucket", "other"))
                if bucket not in gp_tids:
                    gp_tids[bucket] = len(gp_tids) + 1
                    meta(PID_GOODPUT, title, gp_tids[bucket], bucket)
                out.append({
                    "pid": PID_GOODPUT, "tid": gp_tids[bucket],
                    "ph": "X", "cat": "goodput", "name": bucket,
                    "ts": w["t0_s"] * 1e6 + shift,
                    "dur": max((w["t1_s"] - w["t0_s"]) * 1e6, 1),
                    "args": {k: v for k, v in w.items()
                             if k not in ("t0_s", "t1_s")},
                })
                notes["goodput_windows"] += 1

    # ---- host spans (obs/spans.py trace.json) ----------------------
    span_path = os.path.join(run_dir, TRACE_BASENAME)
    notes["host_spans"] = 0
    if os.path.exists(span_path):
        with open(span_path) as f:
            doc = json.load(f)
        span_origin = (doc.get("otherData") or {}).get(
            "time_origin_unix_s")
        if span_origin is None:
            notes["host_spans_note"] = (
                f"{TRACE_BASENAME} has no time_origin_unix_s; skipped")
        else:
            shift = (span_origin - t0_unix) * 1e6
            for ev in doc.get("traceEvents", []):
                ev = dict(ev)
                if "ts" in ev:
                    ev["ts"] = ev["ts"] + shift
                out.append(ev)
                notes["host_spans"] += 1

    # ---- the flight ring -------------------------------------------
    flight_path = os.path.join(run_dir, FLIGHT_BASENAME)
    notes["flight_records"] = 0
    if os.path.exists(flight_path):
        with open(flight_path) as f:
            fdoc = json.load(f)
        f_origin = fdoc.get("time_origin_unix_s")
        if f_origin is None:
            notes["flight_note"] = (
                f"{FLIGHT_BASENAME} predates time_origin_unix_s; "
                "ring not merged")
        else:
            meta(PID_FLIGHT, "flight ring", 1, "records")
            shift = (f_origin - t0_unix) * 1e6
            for rec in fdoc.get("records", []):
                out.append({
                    "pid": PID_FLIGHT, "tid": 1, "ph": "i", "s": "t",
                    "cat": "flight", "name": rec.get("kind", "?"),
                    "ts": rec.get("t_s", 0.0) * 1e6 + shift,
                    "args": {k: v for k, v in rec.items()
                             if k not in ("kind",)},
                })
                notes["flight_records"] += 1

    trace_doc = {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "tools/trace_export.py",
            "run_dir": os.path.abspath(run_dir),
            "time_origin_unix_s": t0_unix,
            **notes,
        },
    }
    return trace_doc, notes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir", help="run directory holding "
                                    f"{TIMELINE_BASENAME} (+ trace.json"
                                    " / flight.json)")
    ap.add_argument("--out", default=None,
                    help=f"output path (default <run_dir>/{MERGED_BASENAME})")
    ap.add_argument("--check", action="store_true",
                    help="fail when any admitted request's span chain "
                         "is incomplete (the CI gate)")
    ap.add_argument("--min-counter-tracks", type=int, default=0,
                    metavar="N",
                    help="with --check: also fail unless the merged "
                         "trace carries at least N resource counter "
                         "tracks (graft-mem mem_sample events)")
    args = ap.parse_args(argv)

    try:
        doc, notes = merge(args.run_dir)
        _, events = read_timeline(args.run_dir)
    except FileNotFoundError as e:
        print(f"no timeline at {args.run_dir}: {e}", file=sys.stderr)
        return 2
    out_path = args.out or os.path.join(args.run_dir, MERGED_BASENAME)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)

    fails, stats = check_chains(events)
    print(
        f"merged {notes['timeline_events']} timeline event(s), "
        f"{notes['host_spans']} host span event(s), "
        f"{notes['flight_records']} flight record(s), "
        f"{notes['counter_tracks']} counter track(s), "
        f"{notes['goodput_windows']} goodput window(s) -> {out_path}"
    )
    print(
        f"requests: {stats['requests']} traced, {stats['admitted']} "
        f"admitted, {stats['complete']} complete, {stats['rejected']} "
        f"rejected, {stats['pending']} pending, "
        f"{stats['drain_handoffs']} drain-handoff(s)"
    )
    for note in ("host_spans_note", "flight_note", "goodput_note"):
        if notes.get(note):
            print(f"note: {notes[note]}", file=sys.stderr)
    if args.check:
        if fails:
            for f_ in fails:
                print(f"span-chain check FAILED: {f_}", file=sys.stderr)
            return 1
        gdoc = read_goodput(args.run_dir)
        if gdoc is not None:
            gp_fails = check_goodput(gdoc)
            if gp_fails:
                for f_ in gp_fails:
                    print(f"goodput check FAILED: {f_}",
                          file=sys.stderr)
                return 1
        if notes["counter_tracks"] < args.min_counter_tracks:
            print(
                f"counter-track check FAILED: {notes['counter_tracks']}"
                f" counter track(s) < required "
                f"{args.min_counter_tracks} (no mem_sample telemetry? "
                f"check DDL25_MEMSCOPE)", file=sys.stderr)
            return 1
        print("span-chain check ok: every admitted request reached "
              "a terminal serve_done; goodput windows "
              + ("partition total wall" if gdoc is not None
                 else "absent (no goodput.json)"), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
