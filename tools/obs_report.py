"""Summarize a telemetry run directory written by ``ddl25spring_tpu.obs``.

    python tools/obs_report.py <run_dir>          # aligned table
    python tools/obs_report.py <run_dir> --json   # machine-readable

The run directory comes from any obs-instrumented driver — e.g.
``python bench.py --smoke`` (CPU) or ``python bench.py --obs-dir DIR``
(TPU).  Besides the perf table, the report renders a "health" section
from ``flight.json`` and a "recovery" section from the flight meta +
the autosave ``ckpt/manifest.json`` (last durable step, resume count,
steps replayed, saves the poisoned-checkpoint gate refused) — so a
post-mortem answers "what survived" as well as "what died".
Everything reported derives from host-side artifacts
(``metrics.jsonl``, ``counters.json``, ``trace.json``); no
``jax.profiler`` capture is involved anywhere on this path, so it works
on tunneled TPU transports where device tracing hangs (RESULTS §6a).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from ddl25spring_tpu.obs.report import format_report, summarize_run  # noqa: E402


EXIT_CODES = """\
exit codes:
  0  report printed; with --check-health, the run is healthy
  2  no telemetry at run_dir (missing metrics.jsonl / artifacts)
  3  --check-health: sentinel violation(s), stall, or flight error
  4  --check-health: memory violation — mem.json records leaked KV
     pages, windowed monotone live-bytes growth, or a budget-band
     breach (graft-mem; see tools/mem_report.py for the full gate)
  5  --check-health: goodput/SLO violation — goodput.json's bucket
     decomposition breaks its sum-to-wall contract, or (with
     --slo-floor) a serve-scope record's SLO attainment sits below
     the floor (graft-goodput; see tools/goodput_report.py for the
     cross-run trend gate)
"""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        epilog=EXIT_CODES,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("run_dir", help="directory holding metrics.jsonl (+ "
                                    "counters.json / trace.json)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw summary dict as JSON")
    ap.add_argument("--check-health", action="store_true",
                    help="exit non-zero when the run's flight.json "
                         "records sentinel violations or a stall (the "
                         "CI health gate)")
    ap.add_argument("--slo-floor", type=float, default=None,
                    metavar="FRACTION",
                    help="with --check-health: also fail (exit 5) when "
                         "a serve-scope goodput.json reports SLO "
                         "attainment below this fraction (0..1)")
    args = ap.parse_args(argv)

    try:
        summary = summarize_run(args.run_dir)
    except FileNotFoundError as e:
        print(f"no telemetry at {args.run_dir}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(format_report(summary))
    if args.check_health:
        h = summary.get("health") or {}
        problems = []
        # an elastic in-run reshape (ft/elastic.py, flight kind=
        # "reshape") is RECOVERY, not damage: the gate names it so the
        # log is explicit, and never fails on it
        reshapes = (summary.get("recovery") or {}).get("reshapes")
        if reshapes:
            print(
                f"note: {reshapes} elastic reshape(s) recorded for "
                f"{args.run_dir} — recovery events, not violations",
                file=sys.stderr,
            )
        if h.get("violations"):
            problems.append(f"{h['violations']} sentinel violation(s)")
        if h.get("stall"):
            problems.append(
                f"stall (watchdog {h['stall'].get('watchdog')})"
            )
        if h.get("error"):
            problems.append(h["error"])
        if problems:
            print(
                f"health check FAILED for {args.run_dir}: "
                + "; ".join(problems),
                file=sys.stderr,
            )
            return 3
        mem = summary.get("mem") or {}
        mem_problems = []
        if not mem.get("error"):
            if mem.get("leaked_pages"):
                mem_problems.append(
                    f"{mem['leaked_pages']} leaked KV page(s)"
                )
            if mem.get("growth_violations"):
                mem_problems.append(
                    f"{mem['growth_violations']} live-bytes growth "
                    f"violation(s)"
                )
            b = mem.get("budget") or {}
            if b.get("available") and b.get("within_band") is False:
                mem_problems.append(
                    f"budget band breach (measured/budget "
                    f"{b.get('ratio')}, tol {b.get('tolerance')})"
                )
        if mem_problems:
            print(
                f"memory check FAILED for {args.run_dir}: "
                + "; ".join(mem_problems),
                file=sys.stderr,
            )
            return 4
        gp = summary.get("goodput") or {}
        gp_problems = []
        if gp and not gp.get("error"):
            sc = gp.get("sum_check") or {}
            if sc.get("ok") is False:
                gp_problems.append(
                    f"decomposition breaks the sum-to-wall contract "
                    f"(attributed {sc.get('attributed_s')} s vs wall "
                    f"{sc.get('total_wall_s')} s, tol "
                    f"{sc.get('tolerance')})"
                )
            att = gp.get("slo_attainment")
            if (args.slo_floor is not None
                    and gp.get("scope") == "serve"
                    and (not isinstance(att, (int, float))
                         or att < args.slo_floor)):
                gp_problems.append(
                    f"SLO attainment {att} below floor "
                    f"{args.slo_floor}"
                )
        if gp_problems:
            print(
                f"goodput check FAILED for {args.run_dir}: "
                + "; ".join(gp_problems),
                file=sys.stderr,
            )
            return 5
        print(f"health check ok for {args.run_dir}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
