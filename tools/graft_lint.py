"""graft-lint: static hazard analysis over the repo and its compiled HLO.

    python -m tools.graft_lint                      # source rules only
    python -m tools.graft_lint --strategy all       # + HLO rules, every strategy
    python -m tools.graft_lint --strategy zero3,ep --mesh 2x4
    python -m tools.graft_lint --strategy all --format json
    python -m tools.graft_lint --strategy all --shard-flow --check  # the CI gate

Two passes share one findings model and one waiver file
(``analysis/waivers.toml``):

- **HLO pass** — every requested parallel strategy's train step is
  compiled on a fake CPU mesh (no accelerator anywhere) and the hazard
  rule pack H001-H013 runs over its optimized HLO: missed async
  overlap, inverse-collective resharding, unaccountable/hoistable
  loop collectives, bf16->f32 upcasts on the wire, donation misses,
  host round-trips, deadlock-shaped permutes and axis leaks, plus the
  sharding-flow family (implicit reshards, partition-rule coverage,
  saved-layout contracts).  ``--shard-flow`` additionally renders the
  per-strategy flow table and runs the cross-program layout contracts
  (serve KV-pool pair agreement).  See
  ``ddl25spring_tpu/analysis/rules.py`` for the pack.
- **source pass** — AST rules S101-S103 over the installable package:
  env reads in traced-code modules, jit call sites without a donation
  decision, raw numpy inside traced functions.
- **host-safety pass** (``--host-safety``) — graft-race S201-S205 over
  the host surfaces (``obs/``, ``ft/``, ``serve/``, ``bench.py``,
  ``tools/``): cross-context attribute races, lock-order inversions,
  signal-handler-unsafe operations, host<->device mirror drift against
  the declared MIRRORS contract, and unbounded blocking on shutdown
  paths (``ddl25spring_tpu/analysis/host_safety.py``).

``--check`` exits non-zero on any *unwaived* finding (or any strategy
that fails to compile when strategies were requested) — the
``graft-lint`` CI job runs ``--strategy all --check`` on every PR, with
per-strategy clean baselines pinned in ``tests/test_hlo_lint.py``.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO_ROOT))

from ddl25spring_tpu.utils.platform import ensure_cpu_tools_env  # noqa: E402

# CPU-only with a multi-device fake host — decided before the first jax
# backend init (this image registers a TPU plugin at interpreter start,
# hence also the config call in main()).
ensure_cpu_tools_env()


def _fmt_finding(f: dict) -> str:
    where = f.get("strategy") or ""
    anchor = f.get("op") or ""
    src = f.get("source") or ""
    loc = " ".join(x for x in (where, anchor, src) if x)
    line = f"  {f['rule']} [{f['severity']:<5}] {loc}\n      {f['message']}"
    if f.get("fix_hint"):
        line += f"\n      fix: {f['fix_hint']}"
    if f.get("waived"):
        line += f"\n      WAIVED: {f['waived_reason']}"
    m = f.get("measured")
    if m:
        # perfscope cross-reference (--perf-ledger): the overlap
        # complaint priced by the measured cost of the very op it flags
        bits = []
        if m.get("t_s_per_exec") is not None:
            bits.append(f"~{m['t_s_per_exec'] * 1e3:.3f} ms/exec standalone")
        if m.get("exposed_comms_s") is not None:
            bits.append(
                f"strategy exposed-comms {m['exposed_comms_s'] * 1e3:.3f} ms"
            )
        if m.get("overlap_eff") is not None:
            bits.append(f"overlap eff {m['overlap_eff']:.3f}")
        if bits:
            line += f"\n      measured: {'; '.join(bits)}"
    return line


def _fmt_sched(r: dict) -> list[str]:
    """The --sched block for one strategy: per-window slack + the
    static overlap bound (analysis/sched.py)."""
    s = r.get("sched")
    if not s:
        return ["  sched: not analyzed"]
    if s.get("error"):
        return [f"  sched: analysis degraded ({s['error']})"]
    bound = s.get("static_overlap_bound")
    lines = [
        "  sched: "
        + (
            f"static overlap bound {bound:.4f}" if bound is not None
            else "no non-scalar collectives"
        )
        + f"  [{s.get('discipline')} issue discipline, "
        f"ref {s.get('ref_chip', '?')}, "
        f"{s.get('async_pairs', 0)} async pair(s), "
        f"{len(s.get('hazards') or [])} deadlock hazard(s)]"
    ]
    for w in s.get("slack") or []:
        if w["result_bytes"] <= s.get("scalar_bytes", 64):
            continue  # scalar bookkeeping: never judged
        lines.append(
            f"    {w['op']} {w['kind']} x{w['count']} "
            f"[{w['window']} window] slack {w['slack_flops']:.3g} FLOPs "
            f"/ {w['slack_bytes']} B over "
            f"{w['independent_instructions']} instr(s), "
            f"wire {w['wire_bytes']} B"
        )
    return lines


def _fmt_shard_flow(summary: dict) -> list[str]:
    """The --shard-flow block for one strategy: entry-parameter layout
    table + the per-collective source walk (analysis/shard_flow.py)."""
    lines = []
    entry = summary.get("entry_params") or []
    sharded = [p for p in entry if p["sharding"] not in ("-", "replicated")]
    lines.append(
        f"  shard-flow: {len(entry)} entry param(s), "
        f"{len(sharded)} sharded"
    )
    for p in entry:
        lines.append(
            f"    {p['arg']:<28} {p['sharding']:<12} "
            f"({p['bytes']} B)"
        )
    for fl in summary.get("flows") or []:
        srcs = ", ".join(
            f"{s['arg']}[{s['sharding']}]" for s in fl["sources"]
        ) or ("<loop-internal>" if fl["internal"] else "<constants>")
        if fl.get("truncated"):
            srcs += "  (walk truncated: sources are a lower bound)"
        lines.append(f"    {fl['op']} {fl['kind']} <- {srcs}")
    return lines


def _fmt_host_safety(inv, findings) -> list[str]:
    """The --host-safety block: the execution-context inventory one-
    liner + every S201-S205 finding (analysis/host_safety.py)."""
    from ddl25spring_tpu.analysis.engine import summarize

    s = summarize(findings)
    inv_s = inv.summary()
    entries = ", ".join(
        f"{k}={v}" for k, v in sorted(inv_s["entry_points"].items())
    ) or "none"
    lines = [
        f"host-safety (graft-race): {s['findings']} finding(s), "
        f"{s['unwaived']} unwaived  "
        f"[{inv_s['files']} files, {inv_s['functions']} functions, "
        f"{len(inv_s['locks'])} declared lock(s), entries: {entries}, "
        f"{inv_s['mirror_contracts']} mirror contract(s)]"
    ]
    lines.extend(_fmt_finding(f.to_dict()) for f in findings)
    return lines


def _render_table(
    src_findings, hlo_reports, sched: bool = False,
    shard_flow: dict | None = None,
    host_inv=None, host_findings=None,
) -> str:
    from ddl25spring_tpu.analysis.engine import summarize

    blocks = []
    if src_findings is not None:
        s = summarize(src_findings)
        blocks.append(
            f"source lint: {s['findings']} finding(s), "
            f"{s['unwaived']} unwaived"
        )
        blocks.extend(_fmt_finding(f.to_dict()) for f in src_findings)
    if host_findings is not None:
        blocks.extend(_fmt_host_safety(host_inv, host_findings))
    for name, r in (hlo_reports or {}).items():
        if "error" in r:
            blocks.append(f"strategy {name}: FAILED to compile: {r['error']}")
            continue
        fs = r.get("findings", [])
        s = summarize(fs)
        mesh = ", ".join(f"{k}={v}" for k, v in r.get("mesh", {}).items())
        head = (
            f"strategy {name} mesh({mesh}) lowered={r.get('lowered', '?')}: "
            f"{s['findings']} finding(s), {s['unwaived']} unwaived"
        )
        if r.get("lint_error"):
            head += f"  [lint degraded: {r['lint_error']}]"
        blocks.append(head)
        if sched:
            blocks.extend(_fmt_sched(r))
        if shard_flow and name in shard_flow.get("strategies", {}):
            blocks.extend(
                _fmt_shard_flow(shard_flow["strategies"][name])
            )
        blocks.extend(_fmt_finding(f) for f in fs)
    if shard_flow is not None:
        by_rule = ", ".join(
            f"{k}={v}" for k, v in sorted(shard_flow["by_rule"].items())
        ) or "none"
        blocks.append(
            "shard-flow cross-program contracts: "
            f"{len(shard_flow['findings'])} finding(s)  "
            f"[H011-H013 totals: {by_rule}]"
        )
        blocks.extend(_fmt_finding(f) for f in shard_flow["findings"])
    return "\n".join(blocks)


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="graft_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--strategy", default=None,
                    help="comma-separated strategy names, or 'all' for "
                         "every registered strategy; omit to skip the "
                         "HLO pass")
    ap.add_argument("--mesh", default=None,
                    help="mesh sizes like 2x4, positional onto each "
                         "strategy's axis names")
    ap.add_argument("--format", choices=("table", "json"), default="table")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on any unwaived finding or "
                         "compile failure (the CI gate; implies --sched)")
    ap.add_argument("--sched", action="store_true",
                    help="render the whole-program schedule report per "
                         "strategy: overlap-slack windows, the static "
                         "overlap bound, and deadlock-hazard counts "
                         "(analysis/sched.py).  The H008-H010 rules run "
                         "regardless; this flag controls the report "
                         "detail.  On by default under --check")
    ap.add_argument("--shard-flow", action="store_true",
                    help="render the sharding-flow section per strategy "
                         "(entry-parameter layouts + per-collective "
                         "source walk) and run the cross-program layout "
                         "contracts — serve prefill/decode KV-pool "
                         "agreement, on top of the per-strategy "
                         "H011-H013 the rule pass always runs "
                         "(analysis/shard_flow.py)")
    ap.add_argument("--host-safety", action="store_true",
                    help="run the graft-race pass (S201-S205): the "
                         "execution-context inventory + concurrency/"
                         "signal-safety/mirror rules over obs/, ft/, "
                         "serve/, bench.py and tools/ "
                         "(analysis/host_safety.py)")
    ap.add_argument("--no-src", action="store_true",
                    help="skip the source (AST) pass")
    ap.add_argument("--waivers", default=None, metavar="TOML",
                    help="waiver file (default: analysis/waivers.toml)")
    ap.add_argument("--perf-ledger", default=None, metavar="JSONL",
                    help="cross-reference each strategy's latest "
                         "measured perf record (obs/perfscope ledger) "
                         "onto its H001 findings, so overlap "
                         "complaints carry a measured cost")
    ap.add_argument("--root", default=str(_REPO_ROOT),
                    help="repo root for the source pass")
    args = ap.parse_args(argv)

    from ddl25spring_tpu.analysis import engine, source_lint
    from ddl25spring_tpu.analysis.waivers import apply_waivers, load_waivers

    waivers = load_waivers(args.waivers)

    src_findings = None
    if not args.no_src:
        src_findings = apply_waivers(
            source_lint.lint_repo(args.root), waivers
        )

    host_inv = None
    host_findings = None
    if args.host_safety:
        from ddl25spring_tpu.analysis import host_safety

        host_inv, host_findings = host_safety.lint_repo(args.root)
        host_findings = apply_waivers(host_findings, waivers)

    hlo_reports: dict = {}
    if args.strategy:
        import jax

        # env alone is too late on images whose sitecustomize registers
        # a TPU plugin at interpreter start; force CPU regardless
        jax.config.update("jax_platforms", "cpu")

        from ddl25spring_tpu.obs.compile_report import (
            DEFAULT_STRATEGIES,
            parse_mesh_arg,
        )

        names = (
            list(DEFAULT_STRATEGIES)
            if args.strategy.strip().lower() == "all"
            else [s.strip() for s in args.strategy.split(",") if s.strip()]
        )
        mesh_sizes = parse_mesh_arg(args.mesh)
        for name in names:
            # --shard-flow's per-collective source walk needs the HLO
            # text of the same compile the lint pass already paid for
            r = engine.lint_strategy(
                name, mesh_sizes, keep_hlo=args.shard_flow
            )
            if args.waivers and "findings" in r:
                # a custom waiver file overrides the default one the
                # strategy report already resolved against: re-apply
                fresh = [
                    engine.Finding(
                        **{**f, "waived": False, "waived_reason": None}
                    )
                    for f in r["findings"]
                ]
                r["findings"] = [
                    f.to_dict() for f in apply_waivers(fresh, waivers)
                ]
            hlo_reports[name] = r

        if args.perf_ledger:
            from ddl25spring_tpu.analysis.engine import attach_measured_costs
            from ddl25spring_tpu.obs.perfscope import (
                host_fingerprint,
                read_ledger,
            )

            # the ledger's trend identity is (strategy, mesh, host) —
            # a record measured on another machine or mesh must not
            # print its milliseconds onto THIS compile's findings
            # (HLO op names are stable across compiles, so a
            # strategy-only match would silently look plausible)
            here = host_fingerprint()
            latest: dict = {}
            for rec in read_ledger(args.perf_ledger):
                if rec.get("host") == here:
                    latest[(rec.get("strategy"), str(rec.get("mesh")))] = rec
            for name, r in hlo_reports.items():
                rec = latest.get((name, str(r.get("mesh"))))
                if rec and r.get("findings") is not None:
                    # prices H001 findings AND the schedule's overlap
                    # windows — windows that cannot hide their own
                    # measured transfer surface as H010 findings here
                    attach_measured_costs(
                        r["findings"], rec, sched=r.get("sched"),
                        strategy=name, waivers=waivers,
                    )

    shard_flow_doc = None
    if args.shard_flow and hlo_reports:
        from ddl25spring_tpu.analysis import shard_flow as sf

        shard_flow_doc = sf.flow_report(hlo_reports, waivers=waivers)
    elif args.shard_flow:
        # a silent no-op would read as "layout contracts checked and
        # passed" — say loudly that nothing ran
        print("graft-lint: --shard-flow needs the HLO pass; pass "
              "--strategy all (or a list) to run the sharding-flow "
              "section — NOTHING was checked", file=sys.stderr)

    if args.format == "json":
        # per-rule finding counts across every pass, so CI artifacts
        # diff mechanically (mirrors perf_report --format json's
        # verdicts-in-document shape)
        by_rule: dict = {}
        for f in src_findings or []:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        for f in host_findings or []:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        for r in hlo_reports.values():
            for f in r.get("findings") or []:
                by_rule[f["rule"]] = by_rule.get(f["rule"], 0) + 1
        for f in (shard_flow_doc or {}).get("findings", []):
            by_rule[f["rule"]] = by_rule.get(f["rule"], 0) + 1
        doc = {
            "record": "graft_lint",
            "source": [f.to_dict() for f in src_findings or []],
            "strategies": {
                # keep_hlo text serves the flow walk above; megabytes of
                # HLO never belong in a JSON artifact
                name: {k: v for k, v in r.items() if k != "hlo_text"}
                for name, r in hlo_reports.items()
            },
            "by_rule": by_rule,
        }
        if shard_flow_doc is not None:
            doc["shard_flow"] = shard_flow_doc
        if host_findings is not None:
            doc["host_safety"] = {
                "inventory": host_inv.summary(),
                "findings": [f.to_dict() for f in host_findings],
            }
        print(json.dumps(doc, indent=1, default=str))
    else:
        print(_render_table(
            src_findings, hlo_reports, sched=args.sched or args.check,
            shard_flow=shard_flow_doc,
            host_inv=host_inv, host_findings=host_findings,
        ))

    if args.check:
        bad = 0
        for f in src_findings or []:
            if not f.waived:
                print(f"CHECK FAIL source: {f.rule} {f.source} {f.op}",
                      file=sys.stderr)
                bad += 1
        for f in host_findings or []:
            if not f.waived:
                print(f"CHECK FAIL host-safety: {f.rule} {f.source} "
                      f"{f.op}", file=sys.stderr)
                bad += 1
        for name, r in hlo_reports.items():
            if "error" in r:
                print(f"CHECK FAIL {name}: did not compile: {r['error']}",
                      file=sys.stderr)
                bad += 1
                continue
            if r.get("lint_error"):
                print(f"CHECK FAIL {name}: lint degraded: "
                      f"{r['lint_error']}", file=sys.stderr)
                bad += 1
            for f in r.get("findings", []):
                if not f.get("waived"):
                    print(f"CHECK FAIL {name}: {f['rule']} {f.get('op')}: "
                          f"{f['message']}", file=sys.stderr)
                    bad += 1
        for f in (shard_flow_doc or {}).get("findings", []):
            if not f.get("waived"):
                print(f"CHECK FAIL shard-flow {f.get('strategy')}: "
                      f"{f['rule']} {f.get('op')}: {f['message']}",
                      file=sys.stderr)
                bad += 1
        if bad:
            print(f"\ngraft-lint: {bad} unwaived finding(s)/failure(s)",
                  file=sys.stderr)
            return 1
        src_msg = (
            "source pass clean" if src_findings is not None
            else "source pass SKIPPED (--no-src)"
        )
        if host_findings is not None:
            src_msg += ", host-safety pass clean"
        print(f"graft-lint OK: {src_msg}, {len(hlo_reports)} strategy "
              "HLO pass(es) clean (waivers applied)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
