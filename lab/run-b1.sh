#!/bin/bash

# Part B1: the GPipe microbatch pipeline (reference: 3 gloo processes,
# lab/run-b1.sh:8-15). TPU-native: ONE single-controller process — the
# pipeline stages are mesh devices inside one jitted SPMD program, so there
# is no per-rank spawn loop, no out<rank>.txt fan-out, and no rendezvous.

cd "$(dirname "$0")" || exit 1
START_TIME=$SECONDS

python -u s01_b1_microbatches.py "$@"

echo "Elapsed time (s): $((SECONDS - START_TIME))"
