#!/usr/bin/env python
"""Homework B1 — GPipe microbatch pipeline, TPU-native.

The reference runs this as THREE OS processes (``python s01_b1_microbatches.py
<rank>``, ``lab/run-b1.sh:8-15``), each holding one LLaMA stage and chaining
``isend/irecv`` with per-microbatch tags (``lab/s01_b1_microbatches.py:66-178``).
Here the same workload — the reference constants dmodel=288, 6 heads, 6 layers,
ctx 256, batch 3 split into 3 microbatches, Adam — is ONE jitted SPMD program:
stages live on a mesh ``stage`` axis, the microbatch schedule is a ``lax.scan``
of ``ppermute`` hops, and backward/grad-accumulation fall out of ``jax.grad``.

Single-controller launch: no rank argv, no MASTER_ADDR/PORT rendezvous.  On a
host without 3 accelerator devices, ``--force-cpu-devices N`` simulates the
mesh on CPU (the TPU-world analogue of the reference's gloo-on-localhost runs).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=200,
                    help="outer iterations (reference: 5000)")
    ap.add_argument("--batch", type=int, default=3,
                    help="global batch size (reference: 3)")
    ap.add_argument("--microbatches", type=int, default=3,
                    help="microbatches per batch (reference: 3)")
    ap.add_argument("--stages", type=int, default=0,
                    help="pipeline stages; 0 = largest divisor of n_layers "
                         "that fits the device count (reference: 3)")
    ap.add_argument("--lr", type=float, default=8e-4)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--log-every", type=int, default=10)
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N",
                    help="simulate an N-device mesh on CPU")
    ap.add_argument("--schedule",
                    choices=("gpipe", "1f1b", "1f1b-stash", "interleaved",
                             "interleaved-1f1b"),
                    default="gpipe",
                    help="pipeline schedule: gpipe (homework B1 parity), "
                         "1f1b (memory-bounded, remat backward; activation "
                         "stash O(S) not O(M)), 1f1b-stash (non-remat "
                         "1F1B: pullback residuals stashed, no forward "
                         "recompute), interleaved (virtual-stage "
                         "chunking, --chunks per device; bubble ~/V), or "
                         "interleaved-1f1b (Megatron production schedule: "
                         "chunked AND memory-bounded)")
    ap.add_argument("--chunks", type=int, default=2, metavar="V",
                    help="interleaved schedule: layer chunks per device "
                         "(needs microbatches %% stages == 0 and "
                         "n_layers %% (stages*V) == 0)")
    ap.add_argument("--scan-steps", type=int, default=0,
                    help="fuse K train steps per dispatched program "
                         "(lax.scan over K stacked batches); 0 = auto "
                         "(16 on TPU, 1 on CPU).  Amortizes the ~4 ms "
                         "tunneled-dispatch cost that dominates at the "
                         "reference-parity batch size")
    ap.add_argument("--no-flash", action="store_true",
                    help="disable the Pallas flash-attention kernel (on TPU "
                         "it is ON by default; CPU always runs dense)")
    ap.add_argument("--trace-dir", default="",
                    help="capture a jax.profiler trace of the timed loop "
                         "(Perfetto/TensorBoard-loadable)")
    return ap.parse_args(argv)


def main(argv=None) -> None:
    args = parse_args(argv)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

    from ddl25spring_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.force_cpu_devices)

    import jax
    import jax.numpy as jnp
    import optax
    from ddl25spring_tpu.data.tinystories import TinyStories
    from ddl25spring_tpu.data.tokenizer import get_tokenizer
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_staged_params,
    )
    from ddl25spring_tpu.utils.config import LlamaConfig
    from ddl25spring_tpu.utils.mesh import make_mesh

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    tokenizer = get_tokenizer()
    # fastest correct path by default: the Pallas flash kernel on TPU
    # (measured 1.8x at ctx 4096), dense attention on CPU where Pallas
    # would run interpreted
    cfg = LlamaConfig(
        vocab_size=tokenizer.vocab_size, dmodel=288, num_heads=6,
        n_layers=6, ctx_size=args.seq_len,
        dtype="bfloat16" if on_tpu else "float32",
        use_flash=on_tpu and not args.no_flash,
    )
    S = args.stages or max(
        s for s in (6, 3, 2, 1) if s <= len(devices) and cfg.n_layers % s == 0
    )
    mesh = make_mesh(devices[:S], stage=S)
    print(f"devices={len(devices)} ({devices[0].platform}) -> "
          f"pipeline stages={S}, microbatches={args.microbatches}, "
          f"batch={args.batch}, schedule={args.schedule}, "
          f"attention={'flash' if cfg.use_flash else 'dense'}")

    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    chunked = args.schedule.startswith("interleaved")
    if chunked:
        split = lambda p: llama.split_blocks_interleaved(p, S, args.chunks)
    else:
        split = lambda p: llama.split_blocks_for_stages(p, S)
    staged = shard_staged_params(split(params), mesh)
    tx = optax.adam(args.lr)
    opt_state = tx.init(staged)

    def build_step(c):
        return make_pipeline_train_step(
            c, tx, mesh, args.microbatches, schedule=args.schedule,
            num_chunks=args.chunks if chunked else 1,
        )

    step = build_step(cfg)

    ds = iter(TinyStories(tokenizer, batch_size=args.batch, seq_l=args.seq_len))
    # warmup outside the timer: jit compile dominates the first step
    from ddl25spring_tpu.parallel.pipeline import warmup_with_flash_fallback

    tokens = jnp.asarray(next(ds))
    (staged, opt_state, loss), step, cfg = warmup_with_flash_fallback(
        cfg, build_step, step, staged, opt_state, tokens,
    )
    float(loss)

    import contextlib

    from ddl25spring_tpu.utils.flops import compiled_flops, mfu
    from ddl25spring_tpu.utils.tracing import trace

    K = args.scan_steps or (16 if on_tpu else 1)
    if K > 1:
        from ddl25spring_tpu.parallel.pipeline import fuse_train_steps

        import numpy as np

        multi = fuse_train_steps(step, K)
        iters = max(1, args.iters // K)
        if iters * K != args.iters:
            print(f"note: --iters {args.iters} adjusted to {iters * K} "
                  f"(a dispatch runs {K} fused steps; use --scan-steps to "
                  "change the granularity)")
        print(f"fusing {K} steps per dispatch ({iters} dispatches)")
        # warmup compile of the fused program outside the timer
        window = jnp.asarray(np.stack([next(ds) for _ in range(K)]))
        staged, opt_state, losses = multi(staged, opt_state, window)
        float(losses[-1])
    else:
        multi, iters = None, args.iters

    ctx = trace(args.trace_dir) if args.trace_dir else contextlib.nullcontext()
    t0 = time.perf_counter()
    with ctx:
        for it in range(iters):
            if multi is None:
                tokens = jnp.asarray(next(ds))
                staged, opt_state, loss = step(staged, opt_state, tokens)
            else:
                window = jnp.asarray(np.stack([next(ds) for _ in range(K)]))
                staged, opt_state, losses = multi(staged, opt_state, window)
                loss = losses[-1]
            if it % args.log_every == 0 or it == iters - 1:
                # host transfer forces completion of the async dispatch
                # chain; fused windows label the loss with the step it
                # belongs to (the window's LAST step)
                step_no = it if multi is None else it * K + K - 1
                print(f"iter {step_no:5d}  loss {float(loss):.4f}",
                      flush=True)
    dt = time.perf_counter() - t0
    n_chips = len(mesh.devices.flat)
    n_steps = iters * K if multi is not None else args.iters
    tok_s = n_steps * args.batch * args.seq_len / dt
    print(f"done: {n_steps} steps in {dt:.1f}s "
          f"({tok_s:,.0f} tok/s, {tok_s / n_chips:,.0f} tok/s/chip)")
    fl = compiled_flops(step, staged, opt_state, tokens)
    tf, frac = mfu(fl, dt / n_steps, n_chips, devices[0])
    if tf is not None:
        print(f"achieved {tf:.2f} TFLOP/s/chip"
              + (f" (MFU {frac:.2%})" if frac is not None else ""))
    if args.trace_dir:
        print(f"profiler trace written to {args.trace_dir}")


if __name__ == "__main__":
    main()
