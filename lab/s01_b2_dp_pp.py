#!/usr/bin/env python
"""Homework B2 — DP x PP hybrid, TPU-native.

The reference runs SIX processes — two 3-stage pipelines {0,1,2}/{3,4,5} with
per-stage DP groups {0,3},{1,4},{2,5} built via ``dist.new_group``, microbatch
``isend/irecv`` chains, then barrier + flatten + per-group ``all_reduce(SUM)``
+ unflatten/2 + Adam step (``lab/s01_b2_dp_pp.py``).  Here the whole topology
is ONE jitted program over a 2-D mesh ``(data, stage)``: the per-stage DP
groups ARE the ``data`` axis, the pipelines ARE the ``stage`` axis, and the
flatten/all_reduce dance is the automatic cotangent psum.

Two workloads:

- ``--workload llama``  — the reference's capability: the 288-d LLaMA on
  TinyStories, 2 pipelines x 3 stages (collapses gracefully to the devices
  available);
- ``--workload resnet`` (default) — the BASELINE.json benchmark config:
  ResNet-18/CIFAR-10 DP(+PP) with microbatches, printing samples/sec/chip
  against the >= 5k north star.  With ``--pp`` the heterogeneous 2-stage
  pipeline is used; default is pure DP (the fastest layout when the model
  fits on one chip — pipelining a chip-resident ResNet only adds bubble).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workload", choices=("resnet", "llama"), default="resnet")
    ap.add_argument("--iters", type=int, default=0,
                    help="0 = workload default (resnet 30, llama 200)")
    ap.add_argument("--batch", type=int, default=0,
                    help="global batch; 0 = workload default "
                         "(resnet 1024/chip, llama 6)")
    ap.add_argument("--microbatches", type=int, default=0,
                    help="0 = workload default (resnet 2 when --pp, llama 3)")
    ap.add_argument("--pp", action="store_true",
                    help="resnet: use the 2-stage heterogeneous pipeline")
    ap.add_argument("--lr", type=float, default=0.0,
                    help="0 = workload default")
    ap.add_argument("--log-every", type=int, default=10)
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N")
    ap.add_argument("--ckpt-dir", default="",
                    help="llama workload: checkpoint/resume directory; a "
                         "relaunched run continues from the latest step")
    ap.add_argument("--ckpt-every", type=int, default=100)
    ap.add_argument("--input",
                    choices=("auto", "hbm-scan", "hbm", "stream", "fixed"),
                    default="auto",
                    help="resnet input pipeline: 'hbm' = whole train split "
                         "resident in device memory with on-device epoch "
                         "shuffle (zero steady-state host->device traffic — "
                         "the TPU-native path for datasets that fit HBM); "
                         "'stream' = native C++ prefetching loader pushing a "
                         "fresh uint8 batch over the host link every step; "
                         "'fixed' = one device-resident batch re-fed (pure "
                         "compute).  'auto' = hbm (CIFAR-10 is 147 MiB)")
    ap.add_argument("--stream", dest="input", action="store_const",
                    const="stream", help="alias for --input stream")
    ap.add_argument("--no-stream", dest="input", action="store_const",
                    const="fixed", help="alias for --input fixed")
    ap.add_argument("--schedule",
                    choices=("gpipe", "1f1b", "1f1b-stash", "interleaved",
                             "interleaved-1f1b"),
                    default="gpipe",
                    help="llama: pipeline schedule (1f1b bounds activation "
                         "memory at O(S) instead of O(M); 1f1b-stash is the "
                         "non-remat variant; interleaved chunks each stage "
                         "into --chunks virtual stages, bubble ~/V; "
                         "interleaved-1f1b composes chunking with the "
                         "bounded 1F1B backward — the Megatron production "
                         "schedule)")
    ap.add_argument("--chunks", type=int, default=2, metavar="V",
                    help="llama interleaved schedule: layer chunks per "
                         "device (needs microbatches %% stages == 0 and "
                         "n_layers %% (stages*V) == 0)")
    ap.add_argument("--no-flash", action="store_true",
                    help="llama: disable the Pallas flash-attention kernel "
                         "(ON by default on TPU; CPU always runs dense)")
    ap.add_argument("--trace-dir", default="",
                    help="capture a jax.profiler trace of the timed loop")
    return ap.parse_args(argv)


def run_llama(args, jax, jnp):
    import optax

    from ddl25spring_tpu.data.tinystories import TinyStories
    from ddl25spring_tpu.data.tokenizer import get_tokenizer
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_staged_params,
    )
    from ddl25spring_tpu.utils.config import LlamaConfig
    from ddl25spring_tpu.utils.mesh import make_mesh

    devices = jax.devices()
    n = len(devices)
    # reference topology 2x3 when possible, else collapse (SURVEY §3.1)
    if n >= 6:
        dp, S = 2, 3
    elif n >= 4:
        dp, S = 2, 2
    elif n >= 2:
        dp, S = 1, 2
    else:
        dp, S = 1, 1
    mesh = make_mesh(devices[: dp * S], data=dp, stage=S)

    on_tpu = devices[0].platform == "tpu"
    tokenizer = get_tokenizer()
    # fastest correct path by default: Pallas flash attention on TPU,
    # dense on CPU (where Pallas would run interpreted)
    cfg = LlamaConfig(
        vocab_size=tokenizer.vocab_size, dmodel=288, num_heads=6,
        n_layers=6, ctx_size=256,
        dtype="bfloat16" if on_tpu else "float32",
        use_flash=on_tpu and not args.no_flash,
    )
    M = args.microbatches or 3
    batch = args.batch or 3 * dp  # reference: batch 3 per pipeline
    iters = args.iters or 200
    print(f"llama DPxPP: mesh(data={dp}, stage={S}), batch={batch}, "
          f"microbatches={M}, schedule={args.schedule}, "
          f"attention={'flash' if cfg.use_flash else 'dense'}")

    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    chunked = args.schedule.startswith("interleaved")
    if chunked:
        split = lambda p: llama.split_blocks_interleaved(p, S, args.chunks)
    else:
        split = lambda p: llama.split_blocks_for_stages(p, S)
    staged = shard_staged_params(split(params), mesh)
    tx = optax.adam(args.lr or 8e-4)
    opt_state = tx.init(staged)

    def build_step(c):
        return make_pipeline_train_step(
            c, tx, mesh, M, data_axis="data" if dp > 1 else None,
            schedule=args.schedule,
            num_chunks=args.chunks if chunked else 1,
        )

    step = build_step(cfg)

    start_it = 0
    ckpt = None
    if args.ckpt_dir:
        from ddl25spring_tpu.utils.checkpoint import (
            Checkpointer, with_mesh_placement,
        )

        ckpt = Checkpointer(args.ckpt_dir)
        state, start_it = ckpt.restore_or_init(
            with_mesh_placement({"params": staged, "opt_state": opt_state}, mesh)
        )
        staged, opt_state = state["params"], state["opt_state"]
        if start_it:
            print(f"resumed from step {start_it - 1} in {args.ckpt_dir}")

    # disjoint per-replica data like the reference's skip=rank*N: one global
    # stream here, sharded over the data axis by the step's in_spec
    ds = iter(TinyStories(
        tokenizer, batch_size=batch, seq_l=cfg.ctx_size,
        skip=start_it * batch,
    ))
    # warmup outside the timer: jit compile dominates the first step.  The
    # outputs are DISCARDED — a warmup that stepped the optimizer would give
    # every resumed run one extra update and break kill-and-resume
    # equivalence with an uninterrupted run
    from ddl25spring_tpu.parallel.pipeline import warmup_with_flash_fallback

    tokens_w = jnp.asarray(next(ds))
    _, step, cfg = warmup_with_flash_fallback(
        cfg, build_step, step, staged, opt_state, tokens_w,
    )
    float(_[2])

    import contextlib

    from ddl25spring_tpu.utils.tracing import trace

    ctx = trace(args.trace_dir) if args.trace_dir else contextlib.nullcontext()
    t0 = time.perf_counter()
    last_it = start_it - 1
    with ctx:
        for it in range(start_it, start_it + iters):
            staged, opt_state, loss = step(
                staged, opt_state, jnp.asarray(next(ds))
            )
            if (args.log_every and it % args.log_every == 0) \
                    or it == start_it + iters - 1:
                print(f"iter {it:5d}  loss {float(loss):.4f}", flush=True)
            if ckpt is not None and args.ckpt_every > 0 \
                    and (it + 1) % args.ckpt_every == 0:
                ckpt.save(it, {"params": staged, "opt_state": opt_state})
            last_it = it
    dt = time.perf_counter() - t0
    if ckpt is not None and last_it >= start_it:
        # persist the tail: without this, up to ckpt_every-1 trailing steps
        # would be redone on relaunch.  Skip if the loop's periodic save
        # already covered last_it (orbax refuses duplicate steps).
        if args.ckpt_every <= 0 or (last_it + 1) % args.ckpt_every != 0:
            ckpt.save(last_it, {"params": staged, "opt_state": opt_state},
                      force=True)
        ckpt.close()
    tok_s = iters * batch * cfg.ctx_size / dt
    print(f"done: {iters} iters in {dt:.1f}s ({tok_s:,.0f} tok/s, "
          f"{tok_s / (dp * S):,.0f} tok/s/chip)")

    from ddl25spring_tpu.utils.flops import compiled_flops, mfu

    fl = compiled_flops(step, staged, opt_state, tokens_w)
    tf, frac = mfu(fl, dt / iters, dp * S, devices[0])
    if tf is not None:
        print(f"achieved {tf:.2f} TFLOP/s/chip"
              + (f" (MFU {frac:.2%})" if frac is not None else ""))
    if args.trace_dir:
        print(f"profiler trace written to {args.trace_dir}")


def run_resnet(args, jax, jnp):
    from ddl25spring_tpu.benchmarks import (
        DeviceDataset, InputFeed, build_resnet_scan_step, build_resnet_step,
        report_line,
    )

    devices = jax.devices()
    n = len(devices)
    on_tpu = devices[0].platform == "tpu"
    iters = args.iters or 30

    if args.pp and n >= 2:
        dp, S = n // 2, 2
    else:
        dp, S = n, 1
    n_used = dp * S  # odd counts strand a device in the --pp layout
    M = (args.microbatches or 2) if S == 2 else 1
    # CPU simulation can't sustain the TPU-sized default batch: a --pp tick
    # slower than XLA's ~40s collective-rendezvous deadline aborts the
    # process, and full-width conv ticks on fake CPU devices hit that at
    # microbatches of ~16; default to microbatches of ~4
    batch = args.batch or (1024 if on_tpu else 4) * n_used
    batch = batch // (dp * M) * (dp * M)

    if args.input == "auto":
        # hbm needs batch <= dataset size (50k CIFAR rows); on a slice big
        # enough to exceed that, auto degrades to the streaming loader.
        # The scan-fused hbm mode is the bench primary (amortized dispatch)
        # but TPU-only: lax.scan over a conv body is ~55x slower on the
        # XLA CPU backend (see build_resnet_scan_step)
        if batch > 50_000:
            mode = "stream"
        else:
            mode = "hbm-scan" if on_tpu else "hbm"
    else:
        mode = args.input

    # the SAME builders + input pipelines bench.py uses (benchmarks.py):
    # raw uint8 batches in, normalization fused into the jitted step
    if mode == "hbm-scan":
        feed = DeviceDataset(batch)
        K = max(k for k in range(1, 17) if feed.batches_per_epoch % k == 0)
        multi, step, params, opt_state, meta = build_resnet_scan_step(
            devices, dp, S, M, batch, K, feed.n, lr=args.lr or 0.1
        )
    else:
        K = 1
        step, params, opt_state, meta = build_resnet_step(
            devices, dp, S, M, batch, lr=args.lr or 0.1
        )
        feed = (
            DeviceDataset(batch) if mode == "hbm"
            else InputFeed(batch, stream=(mode == "stream"))
        )

    input_mode = (
        f"{feed.input_mode}-scan{K}" if mode == "hbm-scan" else feed.input_mode
    )
    print(f"resnet18/cifar10: {meta['topology']}, global batch={batch}, "
          f"{n_used}/{n} device(s) in mesh, input={input_mode}")

    import contextlib

    from ddl25spring_tpu.utils.tracing import trace

    def one_iter(params, opt_state):
        if mode == "hbm-scan":
            return multi(params, opt_state, feed.x, feed.y,
                         *feed.scan_window(K))
        return step(params, opt_state, feed.feed())

    n_disp = max(2, iters // K)
    # warmup (compile) happens before the timer; wrap the timed loop only
    ctx = trace(args.trace_dir) if args.trace_dir else contextlib.nullcontext()
    with ctx:
        for _ in range(3):  # warmup / compile
            params, opt_state, loss = one_iter(params, opt_state)
        float(loss)
        t0 = time.perf_counter()
        for it in range(n_disp):
            params, opt_state, loss = one_iter(params, opt_state)
            if args.log_every and (it % args.log_every == 0):
                # the dispatch returns the loss of its LAST fused step
                print(f"iter {(it + 1) * K - 1:4d}  loss {float(loss):.4f}",
                      flush=True)
        float(loss)
        dt = time.perf_counter() - t0
    sps_chip = n_disp * K * batch / dt / n_used

    from ddl25spring_tpu.utils.flops import compiled_flops, mfu

    fixed = getattr(feed, "fixed", None)
    fl = compiled_flops(step, params, opt_state, fixed)
    tf, frac = mfu(fl, dt / (n_disp * K), n_used, devices[0])
    if tf is not None:
        print(f"achieved {tf:.2f} TFLOP/s/chip"
              + (f" (MFU {frac:.2%})" if frac is not None else ""))
    if args.trace_dir:
        print(f"profiler trace written to {args.trace_dir}")
    print(report_line(meta["layout"], sps_chip, input_mode, frac, tf))
    feed.close()


def main(argv=None) -> None:
    args = parse_args(argv)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

    from ddl25spring_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.force_cpu_devices)

    import jax
    import jax.numpy as jnp
    if args.workload == "llama":
        run_llama(args, jax, jnp)
    else:
        run_resnet(args, jax, jnp)


if __name__ == "__main__":
    main()
