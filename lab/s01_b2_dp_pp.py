#!/usr/bin/env python
"""Homework B2 — DP x PP hybrid, TPU-native.

The reference runs SIX processes — two 3-stage pipelines {0,1,2}/{3,4,5} with
per-stage DP groups {0,3},{1,4},{2,5} built via ``dist.new_group``, microbatch
``isend/irecv`` chains, then barrier + flatten + per-group ``all_reduce(SUM)``
+ unflatten/2 + Adam step (``lab/s01_b2_dp_pp.py``).  Here the whole topology
is ONE jitted program over a 2-D mesh ``(data, stage)``: the per-stage DP
groups ARE the ``data`` axis, the pipelines ARE the ``stage`` axis, and the
flatten/all_reduce dance is the automatic cotangent psum.

Two workloads:

- ``--workload llama``  — the reference's capability: the 288-d LLaMA on
  TinyStories, 2 pipelines x 3 stages (collapses gracefully to the devices
  available);
- ``--workload resnet`` (default) — the BASELINE.json benchmark config:
  ResNet-18/CIFAR-10 DP(+PP) with microbatches, printing samples/sec/chip
  against the >= 5k north star.  With ``--pp`` the heterogeneous 2-stage
  pipeline is used; default is pure DP (the fastest layout when the model
  fits on one chip — pipelining a chip-resident ResNet only adds bubble).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workload", choices=("resnet", "llama"), default="resnet")
    ap.add_argument("--iters", type=int, default=0,
                    help="0 = workload default (resnet 30, llama 200)")
    ap.add_argument("--batch", type=int, default=0,
                    help="global batch; 0 = workload default "
                         "(resnet 1024/chip, llama 6)")
    ap.add_argument("--microbatches", type=int, default=0,
                    help="0 = workload default (resnet 2 when --pp, llama 3)")
    ap.add_argument("--pp", action="store_true",
                    help="resnet: use the 2-stage heterogeneous pipeline")
    ap.add_argument("--lr", type=float, default=0.0,
                    help="0 = workload default")
    ap.add_argument("--log-every", type=int, default=10)
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N")
    ap.add_argument("--ckpt-dir", default="",
                    help="llama workload: checkpoint/resume directory; a "
                         "relaunched run continues from the latest step")
    ap.add_argument("--ckpt-every", type=int, default=100)
    ap.add_argument("--stream", action="store_true",
                    help="resnet: stream a fresh batch per step through the "
                         "native C++ prefetching loader (needs real CIFAR-10 "
                         "binaries via DDL25_CIFAR10_DIR) instead of reusing "
                         "one device-resident batch")
    return ap.parse_args(argv)


def run_llama(args, jax, jnp):
    import optax

    from ddl25spring_tpu.data.tinystories import TinyStories
    from ddl25spring_tpu.data.tokenizer import get_tokenizer
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_staged_params,
    )
    from ddl25spring_tpu.utils.config import LlamaConfig
    from ddl25spring_tpu.utils.mesh import make_mesh

    devices = jax.devices()
    n = len(devices)
    # reference topology 2x3 when possible, else collapse (SURVEY §3.1)
    if n >= 6:
        dp, S = 2, 3
    elif n >= 4:
        dp, S = 2, 2
    elif n >= 2:
        dp, S = 1, 2
    else:
        dp, S = 1, 1
    mesh = make_mesh(devices[: dp * S], data=dp, stage=S)

    tokenizer = get_tokenizer()
    cfg = LlamaConfig(
        vocab_size=tokenizer.vocab_size, dmodel=288, num_heads=6,
        n_layers=6, ctx_size=256,
        dtype="bfloat16" if devices[0].platform == "tpu" else "float32",
    )
    M = args.microbatches or 3
    batch = args.batch or 3 * dp  # reference: batch 3 per pipeline
    iters = args.iters or 200
    print(f"llama DPxPP: mesh(data={dp}, stage={S}), batch={batch}, "
          f"microbatches={M}")

    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    staged = shard_staged_params(llama.split_blocks_for_stages(params, S), mesh)
    tx = optax.adam(args.lr or 8e-4)
    opt_state = tx.init(staged)
    step = make_pipeline_train_step(
        cfg, tx, mesh, M, data_axis="data" if dp > 1 else None
    )

    start_it = 0
    ckpt = None
    if args.ckpt_dir:
        from ddl25spring_tpu.utils.checkpoint import (
            Checkpointer, with_mesh_placement,
        )

        ckpt = Checkpointer(args.ckpt_dir)
        state, start_it = ckpt.restore_or_init(
            with_mesh_placement({"params": staged, "opt_state": opt_state}, mesh)
        )
        staged, opt_state = state["params"], state["opt_state"]
        if start_it:
            print(f"resumed from step {start_it - 1} in {args.ckpt_dir}")

    # disjoint per-replica data like the reference's skip=rank*N: one global
    # stream here, sharded over the data axis by the step's in_spec
    ds = iter(TinyStories(
        tokenizer, batch_size=batch, seq_l=cfg.ctx_size,
        skip=start_it * batch,
    ))
    # warmup outside the timer: jit compile dominates the first step.  The
    # outputs are DISCARDED — a warmup that stepped the optimizer would give
    # every resumed run one extra update and break kill-and-resume
    # equivalence with an uninterrupted run
    _ = step(staged, opt_state, jnp.asarray(next(ds)))
    float(_[2])
    t0 = time.perf_counter()
    last_it = start_it - 1
    for it in range(start_it, start_it + iters):
        staged, opt_state, loss = step(staged, opt_state, jnp.asarray(next(ds)))
        if (args.log_every and it % args.log_every == 0) \
                or it == start_it + iters - 1:
            print(f"iter {it:5d}  loss {float(loss):.4f}", flush=True)
        if ckpt is not None and args.ckpt_every > 0 \
                and (it + 1) % args.ckpt_every == 0:
            ckpt.save(it, {"params": staged, "opt_state": opt_state})
        last_it = it
    dt = time.perf_counter() - t0
    if ckpt is not None and last_it >= start_it:
        # persist the tail: without this, up to ckpt_every-1 trailing steps
        # would be redone on relaunch.  Skip if the loop's periodic save
        # already covered last_it (orbax refuses duplicate steps).
        if args.ckpt_every <= 0 or (last_it + 1) % args.ckpt_every != 0:
            ckpt.save(last_it, {"params": staged, "opt_state": opt_state},
                      force=True)
        ckpt.close()
    tok_s = iters * batch * cfg.ctx_size / dt
    print(f"done: {iters} iters in {dt:.1f}s ({tok_s:,.0f} tok/s, "
          f"{tok_s / (dp * S):,.0f} tok/s/chip)")


def run_resnet(args, jax, jnp):
    import optax

    from ddl25spring_tpu.data.cifar10 import load_cifar10
    from ddl25spring_tpu.models.resnet import (
        ResNet18, ResNet18Stage0, ResNet18Stage1,
    )
    from ddl25spring_tpu.ops.losses import cross_entropy_logits
    from ddl25spring_tpu.parallel.dp import make_dp_train_step
    from ddl25spring_tpu.parallel.het_pipeline import (
        make_het_pipeline_train_step,
    )
    from ddl25spring_tpu.utils.mesh import make_mesh

    devices = jax.devices()
    n = len(devices)
    on_tpu = devices[0].platform == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    iters = args.iters or 30
    warmup = 3

    if args.pp and n >= 2:
        dp, S = n // 2, 2
    else:
        dp, S = n, 1
    n_used = dp * S  # odd counts strand a device in the --pp layout
    # CPU simulation can't sustain the TPU-sized default batch: a --pp tick
    # slower than XLA's ~40s collective-rendezvous deadline aborts the
    # process, and full-width conv ticks on fake CPU devices hit that at
    # microbatches of ~16; default to microbatches of ~4
    batch = args.batch or (1024 if on_tpu else 4) * n_used
    data = load_cifar10(n_train=batch, n_test=8)
    batch = (min(batch, len(data["x_train"])) // (dp * (args.microbatches or 2))) \
        * dp * (args.microbatches or 2)
    x_host = data["x_train"][:batch]
    y_host = data["y_train"][:batch]
    # init below only touches x[:8]; the full fixed batch goes to the device
    # only when it IS the feed (no --stream), so streaming runs don't pin
    # ~12 MB/1024-batch of dead fp32 in HBM
    x = jnp.asarray(x_host[:8])
    tx = optax.sgd(args.lr or 0.1, momentum=0.9)

    if S == 2:
        M = args.microbatches or 2
        mesh = make_mesh(devices, data=dp, stage=S) if dp > 1 else \
            make_mesh(devices[:2], stage=2)
        s0, s1 = ResNet18Stage0(dtype=dtype), ResNet18Stage1(dtype=dtype)
        p0 = s0.init(jax.random.PRNGKey(0), x[:8])["params"]
        mid = s0.apply({"params": p0}, x[:8])
        p1 = s1.init(jax.random.PRNGKey(1), mid)["params"]
        params = (p0, p1)
        mb = batch // M // dp
        step_pp = make_het_pipeline_train_step(
            [lambda p, h: s0.apply({"params": p}, h),
             lambda p, h: s1.apply({"params": p}, h)],
            lambda logits, b: cross_entropy_logits(logits, b["y"]),
            (mb, 32, 32, 3), [(mb,) + mid.shape[1:], (mb, 10)],
            tx, mesh, M, data_axis="data" if dp > 1 else None,
            compute_dtype=dtype,
        )
        opt_state = tx.init(params)
        topo = f"mesh(data={dp}, stage=2), microbatches={M}"

        def step(params, opt_state, bat, key):
            return step_pp(params, opt_state, bat)

        def fixed_batch():
            return {"x": jnp.asarray(x_host), "y": jnp.asarray(y_host)}
    else:
        mesh = make_mesh(devices, data=dp)
        model = ResNet18(norm="group", dtype=dtype)
        params = model.init(jax.random.PRNGKey(0), x[:8])["params"]

        def loss_fn(p, bat, key):
            xb, yb = bat
            logits = model.apply({"params": p}, xb.astype(dtype), train=True)
            return cross_entropy_logits(logits, yb)

        step = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
        opt_state = tx.init(params)
        topo = f"mesh(data={dp})"

        def fixed_batch():
            return (jnp.asarray(x_host), jnp.asarray(y_host))

    stream = None
    if args.stream:
        from ddl25spring_tpu.data.native_loader import (
            NativeCifar10Loader, NativeLoaderUnavailable, normalize_on_device,
        )

        cdir = os.environ.get("DDL25_CIFAR10_DIR", "data/cifar-10-batches-bin")
        try:
            # raw uint8 over the host->device link (4x less traffic than
            # fp32); normalization happens device-side
            stream = iter(
                NativeCifar10Loader(cdir, batch_size=batch, normalize=False)
            )
        except NativeLoaderUnavailable as e:
            print(f"native loader unavailable ({e}); using fixed batch")

    batch_pytree = fixed_batch() if stream is None else None

    def feed():
        if stream is None:
            return batch_pytree
        xs, ys = next(stream)
        xd = normalize_on_device(jnp.asarray(xs))
        if S == 2:
            return {"x": xd, "y": jnp.asarray(ys)}
        return (xd, jnp.asarray(ys))

    print(f"resnet18/cifar10: {topo}, global batch={batch}, "
          f"{n_used}/{n} device(s) in mesh"
          + (", native streaming input" if stream is not None else ""))
    key = jax.random.PRNGKey(2)
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, feed(), key)
    float(loss)  # force completion (async dispatch)

    t0 = time.perf_counter()
    for it in range(iters):
        params, opt_state, loss = step(params, opt_state, feed(), key)
        if args.log_every and (it % args.log_every == 0):
            print(f"iter {it:4d}  loss {float(loss):.4f}", flush=True)
    float(loss)
    dt = time.perf_counter() - t0
    sps_chip = iters * batch / dt / n_used
    print(json.dumps({
        "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
        "value": round(sps_chip, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps_chip / 5000.0, 3),
    }))


def main(argv=None) -> None:
    args = parse_args(argv)
    if args.force_cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.force_cpu_devices}"
        ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    if args.workload == "llama":
        run_llama(args, jax, jnp)
    else:
        run_resnet(args, jax, jnp)


if __name__ == "__main__":
    main()
