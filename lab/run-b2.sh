#!/bin/bash

# Part B2: DP + PP with micro-batches (reference: 6 gloo processes — two
# 3-stage pipelines with per-stage DP groups, lab/run-b2.sh:8-15). TPU-native:
# ONE single-controller process over a 2-D (data, stage) device mesh.
#
# Default workload is the BASELINE.json benchmark config (ResNet-18/CIFAR-10,
# samples/sec/chip vs the >=5k north star); pass "--workload llama" for the
# reference's original LLaMA-on-TinyStories DPxPP run.

cd "$(dirname "$0")" || exit 1
START_TIME=$SECONDS

python -u s01_b2_dp_pp.py "$@"

echo "Elapsed time (s): $((SECONDS - START_TIME))"
