# Root conftest: makes the in-tree package importable when running
# `python -m pytest tests/` without an editable install.
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
