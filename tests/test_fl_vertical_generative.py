"""VFL (split-NN) + generative-FL (VAE/TSTR) tests on the heart workload."""

import numpy as np
import pytest

from ddl25spring_tpu.data.heart import load_heart, partition_features
from ddl25spring_tpu.fl.generative import TabularVAE, train_evaluator, tstr
from ddl25spring_tpu.fl.vertical import VFLNetwork


@pytest.fixture(scope="module")
def heart():
    return load_heart(n_synthetic=600, seed=42)


def test_heart_loader_schema(heart):
    assert heart["x"].shape[1] == len(heart["feature_names"])
    assert heart["x"].shape[1] >= 26  # 5 numericals + one-hot categoricals
    assert set(np.unique(heart["y"])) <= {0, 1}
    # slices cover the matrix disjointly
    spans = sorted(heart["feature_slices"].values())
    assert spans[0][0] == 0 and spans[-1][1] == heart["x"].shape[1]
    for (_, b), (c, _) in zip(spans, spans[1:]):
        assert b == c


def test_partition_features_disjoint_covering(heart):
    parts = partition_features(heart["feature_slices"], 4)
    assert len(parts) == 4
    allidx = np.concatenate(parts)
    assert len(allidx) == heart["x"].shape[1]
    assert len(np.unique(allidx)) == len(allidx)


def test_vfl_trains_above_chance(heart):
    x, y = heart["x"], heart["y"]
    n = int(0.8 * len(x))
    parts = partition_features(heart["feature_slices"], 4)
    net = VFLNetwork(parts, lr=1e-3, seed=42)
    losses = net.train_with_settings(30, 64, x[:n], y[:n])
    assert losses[-1] < losses[0]
    acc, loss = net.test(x[n:], y[n:])
    base = max(np.mean(y[n:]), 1 - np.mean(y[n:]))
    assert acc > base - 0.05  # beats/approaches majority class


def test_vae_loss_decreases_and_samples(heart):
    x, y = heart["x"], heart["y"]
    real = np.concatenate([x, y[:, None].astype(np.float32)], axis=1)
    vae = TabularVAE(d_in=real.shape[1], seed=42)
    losses = vae.train_with_settings(20, 64, real)
    assert losses[-1] < losses[0]
    mu, logvar = vae.encode_stats(real)
    synth = vae.sample(100, mu, logvar)
    assert synth.shape == (100, real.shape[1])
    assert set(np.unique(synth[:, -1])) <= {0.0, 1.0}  # label clipped+rounded


def test_tstr_harness(heart):
    x, y = heart["x"], heart["y"]
    n = int(0.8 * len(x))
    vae = TabularVAE(d_in=x.shape[1] + 1, seed=42)
    vae.train_with_settings(10, 64, np.concatenate(
        [x[:n], y[:n, None].astype(np.float32)], axis=1))
    res = tstr(vae, x[:n], y[:n], x[n:], y[n:])
    assert 0.0 <= res["synthetic"] <= 1.0
    assert res["real"] > 0.6  # evaluator learns the real data