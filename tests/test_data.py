"""Data pipeline tests: determinism and the non-IID splitter invariants
(reference splitter semantics at ``lab/tutorial_1a/hfl_complete.py:91-104``)."""

import numpy as np

from ddl25spring_tpu.data.mnist import load_mnist
from ddl25spring_tpu.data.splitter import split_indices, stack_client_data


def test_mnist_deterministic_and_normalized():
    load_mnist.cache_clear()
    a = load_mnist(n_train=256, n_test=64)
    load_mnist.cache_clear()
    b = load_mnist(n_train=256, n_test=64)
    np.testing.assert_array_equal(a["x_train"], b["x_train"])
    assert a["x_train"].shape == (256, 28, 28, 1)
    assert a["y_train"].dtype == np.int32
    assert set(np.unique(a["y_train"])) <= set(range(10))


def test_split_iid_partitions_everything():
    labels = np.repeat(np.arange(10), 100)
    splits = split_indices(labels, nr_clients=7, iid=True, seed=10)
    allidx = np.concatenate(splits)
    assert len(allidx) == 1000
    assert len(np.unique(allidx)) == 1000


def test_split_noniid_limits_labels_per_client():
    labels = np.repeat(np.arange(10), 100)
    splits = split_indices(labels, nr_clients=10, iid=False, seed=10)
    allidx = np.concatenate(splits)
    assert len(np.unique(allidx)) == 1000
    for s in splits:
        # each client gets 2 shards of a label-sorted array => <= ~3 labels
        assert len(np.unique(labels[s])) <= 4
    # non-IID must be skewed: some client sees fewer labels than the full set
    assert min(len(np.unique(labels[s])) for s in splits) <= 2


def test_split_seed_determinism():
    labels = np.repeat(np.arange(10), 50)
    a = split_indices(labels, 5, False, seed=10)
    b = split_indices(labels, 5, False, seed=10)
    c = split_indices(labels, 5, False, seed=11)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_stack_client_data_pads_and_counts():
    x = np.arange(10, dtype=np.float32)[:, None]
    y = np.arange(10, dtype=np.int32)
    splits = [np.array([0, 1, 2]), np.array([3, 4, 5, 6, 7, 8, 9])]
    xs, ys, counts = stack_client_data(x, y, splits)
    assert xs.shape == (2, 7, 1)
    np.testing.assert_array_equal(counts, [3, 7])
    # padding repeats the client's own data
    assert set(ys[0].tolist()) == {0, 1, 2}
