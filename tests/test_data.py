"""Data pipeline tests: determinism and the non-IID splitter invariants
(reference splitter semantics at ``lab/tutorial_1a/hfl_complete.py:91-104``)."""

import numpy as np
import pytest

from ddl25spring_tpu.data.mnist import load_digits_28x28, load_mnist
from ddl25spring_tpu.data.splitter import split_indices, stack_client_data


def test_digits_real_data_mnist_shaped():
    """The sklearn-bundled UCI digits (REAL handwritten data on the
    zero-egress image) must drop into every MNIST consumer: same shapes,
    dtypes, normalization constants; train/test disjoint and
    deterministic."""
    pytest.importorskip("sklearn")  # optional dep: ships the real digits
    load_digits_28x28.cache_clear()
    d = load_digits_28x28()
    assert d["x_train"].shape == (1437, 28, 28, 1)
    assert d["x_test"].shape == (360, 28, 28, 1)
    assert d["y_train"].dtype == np.int32
    assert set(np.unique(d["y_train"])) == set(range(10))
    # normalized like load_mnist: background pixels sit at (0-MEAN)/STD
    from ddl25spring_tpu.data.mnist import MEAN, STD

    assert np.isclose(d["x_train"].min(), (0.0 - MEAN) / STD, atol=1e-6)
    load_digits_28x28.cache_clear()
    d2 = load_digits_28x28()
    np.testing.assert_array_equal(d["x_train"], d2["x_train"])
    # real data: images within a class differ (no synthetic prototype)
    zeros = d["x_train"][d["y_train"] == 0]
    assert not np.allclose(zeros[0], zeros[1])


def test_mnist_deterministic_and_normalized():
    load_mnist.cache_clear()
    a = load_mnist(n_train=256, n_test=64)
    load_mnist.cache_clear()
    b = load_mnist(n_train=256, n_test=64)
    np.testing.assert_array_equal(a["x_train"], b["x_train"])
    assert a["x_train"].shape == (256, 28, 28, 1)
    assert a["y_train"].dtype == np.int32
    assert set(np.unique(a["y_train"])) <= set(range(10))


def test_split_iid_partitions_everything():
    labels = np.repeat(np.arange(10), 100)
    splits = split_indices(labels, nr_clients=7, iid=True, seed=10)
    allidx = np.concatenate(splits)
    assert len(allidx) == 1000
    assert len(np.unique(allidx)) == 1000


def test_split_noniid_limits_labels_per_client():
    labels = np.repeat(np.arange(10), 100)
    splits = split_indices(labels, nr_clients=10, iid=False, seed=10)
    allidx = np.concatenate(splits)
    assert len(np.unique(allidx)) == 1000
    for s in splits:
        # each client gets 2 shards of a label-sorted array => <= ~3 labels
        assert len(np.unique(labels[s])) <= 4
    # non-IID must be skewed: some client sees fewer labels than the full set
    assert min(len(np.unique(labels[s])) for s in splits) <= 2


def test_split_seed_determinism():
    labels = np.repeat(np.arange(10), 50)
    a = split_indices(labels, 5, False, seed=10)
    b = split_indices(labels, 5, False, seed=10)
    c = split_indices(labels, 5, False, seed=11)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_stack_client_data_pads_and_counts():
    x = np.arange(10, dtype=np.float32)[:, None]
    y = np.arange(10, dtype=np.int32)
    splits = [np.array([0, 1, 2]), np.array([3, 4, 5, 6, 7, 8, 9])]
    xs, ys, counts = stack_client_data(x, y, splits)
    assert xs.shape == (2, 7, 1)
    np.testing.assert_array_equal(counts, [3, 7])
    # padding repeats the client's own data
    assert set(ys[0].tolist()) == {0, 1, 2}
