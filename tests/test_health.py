"""The runtime health layer: in-step numerics sentinels, the
crash-surviving flight recorder, and the stall watchdog.

The contract pins, in order:

1. **HLO identity** — with sentinels disabled, every instrumented
   train-step builder lowers to HLO byte-identical to a build with the
   guard explicitly off (the PR-1 zero-cost pattern, per strategy); with
   sentinels enabled the guard actually lands in the program.  Builders
   whose grad path needs VMA-typed shard_map gate on ``HAS_VMA`` exactly
   like ``tests/test_pipeline.py`` (their forward-only paths carry no
   update to guard).  Lowerings are cached per (builder, mode) — the
   ``tests/test_xla_analytics.py`` compile-once pattern.
2. **Detection** — a NaN injected into a DP and a ZeRO-3 step is caught
   within that step, recorded in the flight ring, and identified down to
   the violating gradient leaf; ``flight.json`` dump contents pinned.
3. **Policies** — ``skip`` suppresses the poisoned update on device,
   ``halt`` raises with flight-record context (strategy, step, leaf),
   not a bare FloatingPointError.
4. **Watchdog** — an artificial stall produces a dump carrying every
   host thread's stack, including the wedged thread's blocking frame.
"""

import contextlib
import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.obs import flight, sentinels
from ddl25spring_tpu.obs.watchdog import StallWatchdog, thread_stacks
from ddl25spring_tpu.utils.compat import HAS_VMA
from ddl25spring_tpu.utils.mesh import make_mesh


@pytest.fixture(autouse=True)
def _health_clean():
    """Sentinels off, flight ring empty, before and after every test —
    the module flags must never leak (same discipline as test_obs)."""
    sentinels.enable(False)
    sentinels.set_policy("log")
    sentinels.reset()
    flight.reset()
    flight.configure(run_dir=None)
    yield
    sentinels.enable(False)
    sentinels.set_policy("log")
    sentinels.reset()
    flight.reset()
    flight.configure(run_dir=None)


# --------------------------------------------------- tiny builder setups


def _mlp_loss(p, batch, key):
    del key
    x, y = batch
    return jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2)


def _mlp_params():
    return {
        "w1": jnp.full((8, 16), 0.5, jnp.float32),
        "w2": jnp.full((16, 4), 0.5, jnp.float32),
    }


def _mlp_batch(bad: bool = False, n: int = 8):
    x = jnp.ones((n, 8), jnp.float32)
    if bad:
        x = x.at[0, 0].set(jnp.nan)
    return x, jnp.ones((n, 4), jnp.float32)


def _builder_setups(devices8):
    """name -> (build() -> (lowerable, args)) for every sentinel-wired
    train-step builder.  build() is called under the desired sentinel
    scope; tiny workloads keep ~20 lowerings cheap."""
    from ddl25spring_tpu.parallel import dp, ep, het_pipeline, sp, tp, zero
    from ddl25spring_tpu.utils.config import LlamaConfig

    tx = optax.sgd(0.1)
    p = _mlp_params()
    batch = _mlp_batch()
    key = jax.random.PRNGKey(0)
    mesh2 = make_mesh(devices8[:2], data=2)
    cfg = LlamaConfig(
        vocab_size=32, dmodel=8, num_heads=2, n_layers=2, ctx_size=8,
        dtype="float32",
    )
    toks = jnp.zeros((4, cfg.ctx_size), jnp.int32)

    def serial():
        step = dp.make_train_step(_mlp_loss, tx)
        return step, (p, tx.init(p), batch, key)

    def dp_grad():
        step = dp.make_dp_train_step(
            _mlp_loss, tx, mesh2, per_shard_rng=False
        )
        return step, (p, tx.init(p), batch, key)

    def dp_overlap():
        step = dp.make_dp_train_step(
            _mlp_loss, tx, mesh2, per_shard_rng=False, overlap=True
        )
        return step, (p, tx.init(p), batch, key)

    def dp_wavg():
        step = dp.make_dp_weight_avg_step(
            _mlp_loss, tx, mesh2, per_shard_rng=False
        )
        return step, (p, dp.stack_opt_state(tx.init(p), 2), batch, key)

    def zero3_overlap():
        step = zero.make_zero_dp_train_step(
            _mlp_loss, tx, mesh2, p, per_shard_rng=False, overlap=True
        )
        shards = zero.zero_shard_params(p, mesh2)
        return step, (shards, tx.init(shards), batch, key)

    def zero_stage(stage):
        def build():
            if stage == 3:
                step = zero.make_zero_dp_train_step(
                    _mlp_loss, tx, mesh2, p, per_shard_rng=False
                )
            else:
                step = zero.make_zero_partitioned_train_step(
                    _mlp_loss, tx, mesh2, p, stage=stage,
                    per_shard_rng=False,
                )
            shards = zero.zero_shard_params(p, mesh2)
            args = (
                (shards if stage == 3 else p),
                tx.init(shards), batch, key,
            )
            return step, args
        return build

    def zero3_llama():
        step = zero.make_zero3_llama_train_step(
            cfg, tx, mesh2, per_shard_rng=False
        )
        shards = zero_shard_llama(cfg, mesh2)
        return step, (shards, tx.init(shards), toks, key)

    def zero_shard_llama(cfg, mesh):
        from ddl25spring_tpu.models import llama

        return zero.zero_shard_llama_params(
            llama.init_llama_params(jax.random.PRNGKey(0), cfg), mesh
        )

    def tp_step():
        from ddl25spring_tpu.models import llama

        mesh = make_mesh(devices8[:2], model=2)
        params = tp.shard_tp_params(
            llama.init_llama_params(jax.random.PRNGKey(0), cfg), mesh,
            "model",
        )
        step = tp.make_tp_train_step(cfg, tx, mesh)
        return step, (params, tx.init(params), toks)

    def sp_step():
        from ddl25spring_tpu.models import llama

        mesh = make_mesh(devices8[:2], seq=2)
        params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
        step = sp.make_sp_train_step(cfg, tx, mesh)
        return step, (params, tx.init(params), toks)

    def ep_step():
        mesh = make_mesh(devices8[:2], expert=2)
        params = ep.shard_moe_params(
            ep.init_moe_params(jax.random.PRNGKey(0), 8, 16, 2), mesh
        )
        step = ep.make_ep_train_step(tx, mesh)
        x = jnp.ones((8, 8), jnp.float32)
        return step, (params, tx.init(params), (x, jnp.zeros_like(x)))

    def pipeline_step():
        from ddl25spring_tpu.models import llama
        from ddl25spring_tpu.parallel.pipeline import (
            make_pipeline_train_step,
            shard_staged_params,
        )

        mesh = make_mesh(devices8[:2], stage=2)
        step = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=2)
        params = shard_staged_params(
            llama.split_blocks_for_stages(
                llama.init_llama_params(jax.random.PRNGKey(0), cfg), 2
            ),
            mesh,
        )
        return step, (params, tx.init(params), toks)

    def het_step():
        mesh = make_mesh(devices8[:2], stage=2)
        params = (
            {"w": jnp.full((8, 16), 0.5)},
            {"w": jnp.full((16, 4), 0.5)},
        )
        step = het_pipeline.make_het_pipeline_train_step(
            [lambda p, x: jnp.tanh(x @ p["w"]),
             lambda p, x: x @ p["w"]],
            lambda out, b: jnp.mean((out - b["y"]) ** 2),
            (2, 8), [(2, 16), (2, 4)], tx, mesh, 2,
        )
        batch = {
            "x": jnp.ones((4, 8), jnp.float32),
            "y": jnp.ones((4, 4), jnp.float32),
        }
        return step, (params, tx.init(params), batch)

    setups = {
        "serial": serial,
        "dp": dp_grad,
        "dp-overlap": dp_overlap,
        "dp-weight-avg": dp_wavg,
        "zero1": zero_stage(1),
        "zero2": zero_stage(2),
        "zero3": zero_stage(3),
        "zero3-prefetch": zero3_llama,
        "zero3-overlap": zero3_overlap,
        "tp": tp_step,
        "sp": sp_step,
        "ep": ep_step,
    }
    if HAS_VMA:
        # the scan-over-ppermute schedules transpose only under
        # VMA-typed shard_map (same gating as tests/test_pipeline.py);
        # pre-VMA these builders cannot trace a grad path at all
        setups["pipeline"] = pipeline_step
        setups["het_pipeline"] = het_step
    return setups


def _lowered(devices8, name: str, mode: str) -> str:
    """Lower-once cache over (builder, sentinel-mode) — the shared
    tests/conftest.py memo (one cache for the whole session), applied
    to lowerings."""
    from conftest import cached_lowering

    def build_text():
        build = _builder_setups(devices8)[name]
        ctx = {
            "off": sentinels.scoped(False),
            "default": contextlib.nullcontext(),
            "on": sentinels.scoped(True),
        }[mode]
        with ctx:
            fn, args = build()
        return fn.lower(*args).as_text()

    return cached_lowering(("health-lowered", name, mode), build_text)


def test_every_builder_hlo_identical_when_disabled(devices8):
    """The acceptance pin: sentinels disabled -> byte-identical HLO to a
    sentinel-free build, for EVERY wired builder; enabled -> the guard
    demonstrably lands (catches a builder that forgot to call it)."""
    assert sentinels.enabled() is False
    for name in _builder_setups(devices8):
        off = _lowered(devices8, name, "off")
        on = _lowered(devices8, name, "on")
        assert on != off, f"{name}: enabling sentinels changed nothing"


@pytest.mark.parametrize("name", ["dp", "zero3"])
def test_default_follows_global_flag(devices8, name):
    assert _lowered(devices8, name, "default") == _lowered(
        devices8, name, "off"
    )


def test_sentinels_do_not_serialize_overlapped_collectives(devices8):
    """The PR-8 interaction pin: enabling sentinels on the overlapped
    DP step must not add (or force) any non-scalar collective — the
    guard's facts ride scalar reductions + one host callback, so the
    backward-issued bucket all-reduces keep their overlap structure.
    Compares the OPTIMIZED HLO collective inventories of the on/off
    builds: identical non-scalar sites, and everything the guard added
    is scalar-sized."""
    from ddl25spring_tpu.obs.xla_analytics import parse_hlo_collectives
    from ddl25spring_tpu.parallel import dp

    tx = optax.sgd(0.1)
    p = _mlp_params()
    batch = _mlp_batch()
    key = jax.random.PRNGKey(0)
    mesh2 = make_mesh(devices8[:2], data=2)

    def compiled_ops(on: bool):
        with sentinels.scoped(on):
            step = dp.make_dp_train_step(
                _mlp_loss, tx, mesh2, per_shard_rng=False, overlap=True
            )
        hlo = step.lower(p, tx.init(p), batch, key).compile().as_text()
        return parse_hlo_collectives(hlo)

    def big(ops):
        return sorted(
            (o["kind"], o["result_bytes"], o["count"])
            for o in ops if o["result_bytes"] > 64
        )

    off_ops, on_ops = compiled_ops(False), compiled_ops(True)
    assert big(on_ops) == big(off_ops), (
        "sentinels changed the overlapped step's non-scalar collective "
        "structure — the guard is serializing the bucket all-reduces"
    )


def test_guard_disabled_returns_results_unchanged():
    """Zero-cost by construction: the disabled guard is Python identity
    — the exact object, no tracing, nothing inserted."""
    results = ({"w": jnp.ones(2)}, None)
    out = sentinels.guard("x", results, loss=jnp.float32(1.0),
                          enabled=False)
    assert out is results


# ------------------------------------------------------------- detection


def _run(step, *args):
    out = step(*args)
    jax.block_until_ready(out)
    jax.effects_barrier()
    return out


def test_dp_nan_detected_within_one_step_and_dumped(devices8, tmp_path):
    from ddl25spring_tpu.parallel.dp import make_dp_train_step

    flight.configure(run_dir=str(tmp_path))
    mesh = make_mesh(devices8[:2], data=2)
    tx = optax.sgd(0.1)
    p = _mlp_params()
    with sentinels.scoped(True, policy="log"):
        step = make_dp_train_step(_mlp_loss, tx, mesh, per_shard_rng=False)

    # healthy step: a step record, no violation
    _run(step, p, tx.init(p), _mlp_batch(), jax.random.PRNGKey(0))
    recs = flight.last()
    assert recs and recs[-1]["kind"] == "step"
    assert recs[-1]["strategy"] == "dp"
    assert np.isfinite(recs[-1]["loss"]) and recs[-1]["grad_norm"] > 0
    assert 0 < recs[-1]["update_ratio"] < 1

    # poisoned step: detected in THAT step, leaf named
    _run(step, p, tx.init(p), _mlp_batch(bad=True), jax.random.PRNGKey(0))
    v = [r for r in flight.last() if r["kind"] == "violation"]
    assert len(v) == 1
    v = v[0]
    assert v["strategy"] == "dp" and v["step"] == 1
    assert v["violating_metric"].startswith("grads")
    assert any("w1" in leaf for leaf in v["nonfinite_leaves"])
    assert sentinels.last_violation()["step"] == 1

    # the dump identifies strategy, step index, violating metric
    path = flight.dump(reason="test")
    doc = json.load(open(path))
    assert doc["violations"] == 1
    last = doc["last_violation"]
    assert last["strategy"] == "dp"
    assert last["step"] == 1
    assert last["violating_metric"] == v["violating_metric"]
    assert last["loss"] == "nan"  # JSON-safe encoding of the NaN loss
    assert json.dumps(doc)  # strict JSON round-trips


def test_zero3_nan_detected_once_across_shards(devices8, tmp_path):
    """ZeRO-3's guard sits INSIDE shard_map: facts must arrive globally
    reduced and be recorded once (shard 0), not once per device."""
    from ddl25spring_tpu.parallel import zero

    flight.configure(run_dir=str(tmp_path))
    mesh = make_mesh(devices8[:4], data=4)
    tx = optax.adam(1e-3)
    p = _mlp_params()
    shards = zero.zero_shard_params(p, mesh)
    with sentinels.scoped(True, policy="log"):
        step = zero.make_zero_dp_train_step(
            _mlp_loss, tx, mesh, p, per_shard_rng=False
        )
    _run(step, shards, tx.init(shards), _mlp_batch(bad=True),
         jax.random.PRNGKey(0))
    recs = [r for r in flight.last() if r.get("strategy") == "zero3"]
    assert len(recs) == 1, "per-shard callbacks must collapse to one record"
    assert recs[0]["kind"] == "violation"
    assert recs[0]["nonfinite_leaves"]
    doc = json.load(open(flight.dump()))
    assert doc["last_violation"]["strategy"] == "zero3"


def test_optimizer_nan_detected_in_same_step(devices8):
    """A NaN born in the OPTIMIZER (poisoned Adam moment, finite grads)
    must trip the sentinel in the step that applies it — checking grads
    alone would see it one step late, after skip's fallback is already
    poisoned."""
    from ddl25spring_tpu.parallel.dp import make_dp_train_step

    mesh = make_mesh(devices8[:2], data=2)
    tx = optax.adam(1e-3)
    p = _mlp_params()
    with sentinels.scoped(True, policy="skip"):
        step = make_dp_train_step(_mlp_loss, tx, mesh, per_shard_rng=False)
    o = tx.init(p)
    adam = o[0]
    o = (
        adam._replace(
            mu=dict(adam.mu, w1=adam.mu["w1"].at[0, 0].set(jnp.nan))
        ),
    ) + tuple(o[1:])
    new_p, _, _ = _run(step, p, o, _mlp_batch(), jax.random.PRNGKey(0))
    v = [r for r in flight.last() if r["kind"] == "violation"]
    assert v, "optimizer-made NaN escaped the sentinel"
    assert v[-1]["violating_metric"].startswith("updates")
    assert any("w1" in leaf for leaf in v[-1]["nonfinite_leaves"])
    # skip still protected the params in the SAME step
    assert all(
        np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(new_p)
    )


# -------------------------------------------------------------- policies


def test_skip_policy_suppresses_update_on_device(devices8):
    from ddl25spring_tpu.parallel.dp import make_dp_train_step

    mesh = make_mesh(devices8[:2], data=2)
    tx = optax.sgd(0.1)
    p = _mlp_params()
    with sentinels.scoped(True, policy="skip"):
        step = make_dp_train_step(_mlp_loss, tx, mesh, per_shard_rng=False)
    bad_p, _, _ = _run(
        step, p, tx.init(p), _mlp_batch(bad=True), jax.random.PRNGKey(0)
    )
    np.testing.assert_array_equal(np.asarray(bad_p["w1"]),
                                  np.asarray(p["w1"]))
    good_p, _, _ = _run(
        step, p, tx.init(p), _mlp_batch(), jax.random.PRNGKey(0)
    )
    assert not np.array_equal(np.asarray(good_p["w1"]),
                              np.asarray(p["w1"]))


_HALT_SCRIPT = r"""
import os, sys
os.environ["DDL25_DONATE"] = "0"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp, optax
from ddl25spring_tpu.obs import flight, sentinels
from ddl25spring_tpu.parallel.dp import make_dp_train_step
from ddl25spring_tpu.utils.mesh import make_mesh

flight.configure(run_dir=sys.argv[1])
mesh = make_mesh(jax.devices()[:2], data=2)
tx = optax.sgd(0.1)
p = {"w1": jnp.full((8, 16), 0.5), "w2": jnp.full((16, 4), 0.5)}
def loss_fn(pp, batch, key):
    x, y = batch
    return jnp.mean((jnp.tanh(x @ pp["w1"]) @ pp["w2"] - y) ** 2)
with sentinels.scoped(True, policy="halt"):
    step = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
x = jnp.ones((8, 8)).at[0, 0].set(jnp.nan)
try:
    out = step(p, tx.init(p), (x, jnp.ones((8, 4))), jax.random.PRNGKey(0))
    jax.block_until_ready(out)
    jax.effects_barrier()
    print("MARKER:no-raise")
except Exception as e:
    print("MARKER:raised", type(e).__name__)
    print("MARKER:msg", str(e).replace("\n", " "))
ctx = sentinels.last_violation()
print("MARKER:ctx", ctx["strategy"], ctx["step"], ctx["violating_metric"])
os._exit(0)  # the poisoned dispatch stream would trip atexit otherwise
"""


def test_halt_policy_raises_with_flight_context(tmp_path):
    """Halt must surface the flight-record context — strategy, step,
    offending leaf, dump path — not a bare FloatingPointError.  The
    runtime may wrap the raise in its own error type (async dispatch:
    the exception surfaces at the next blocking point, see the
    sentinels module docstring).  Run in a SUBPROCESS: halt is a
    terminal policy — the raise leaves the backend's dispatch stream
    errored (observed on the CPU runtime: every later multi-device
    dispatch in the process inherits the failure), which is fine for a
    run that is dying on purpose but must not poison this suite."""
    import subprocess

    r = subprocess.run(
        [sys.executable, "-c", _HALT_SCRIPT, str(tmp_path)],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    out = r.stdout
    assert "MARKER:raised" in out, (out, r.stderr[-2000:])
    assert "MARKER:no-raise" not in out
    assert "sentinel violation" in out
    assert "MARKER:ctx dp 0 grads" in out
    # the dump happened BEFORE the raise
    doc = json.load(open(os.path.join(str(tmp_path), "flight.json")))
    assert doc["reason"] == "sentinel_halt"
    assert doc["last_violation"]["strategy"] == "dp"
    assert doc["last_violation"]["violating_metric"].startswith("grads")


def test_policy_resolution_and_env_choice():
    with sentinels.scoped(True, policy="skip"):
        assert sentinels.resolve(None) == (True, "skip")
        assert sentinels.resolve(False) == (False, "skip")
        assert sentinels.resolve(None, "halt") == (True, "halt")
    assert sentinels.resolve(None) == (False, "log")
    with pytest.raises(ValueError, match="not one of"):
        sentinels.set_policy("explode")
    from ddl25spring_tpu.utils.config import env_choice

    os.environ["DDL25_TEST_CHOICE"] = "bogus"
    try:
        with pytest.raises(ValueError, match="bogus"):
            env_choice("DDL25_TEST_CHOICE", ("a", "b"), "a")
        os.environ["DDL25_TEST_CHOICE"] = "b"
        assert env_choice("DDL25_TEST_CHOICE", ("a", "b"), "a") == "b"
    finally:
        del os.environ["DDL25_TEST_CHOICE"]


# ------------------------------------------------------- flight recorder


def test_flight_ring_truncates_and_snapshot_counts(tmp_path):
    flight.configure(capacity=8)
    try:
        # one violation FIRST, then enough steps to evict it: the
        # cumulative count (and the --check-health gate riding on it)
        # must survive ring eviction
        flight.record(kind="violation", strategy="dp", step=0,
                      violating_metric="loss", violation=True)
        for i in range(20):
            flight.record(kind="step", step=i)
        snap = flight.snapshot()
        assert snap["recorded"] == 21
        assert len(snap["records"]) == 8
        assert [r["step"] for r in snap["records"]] == list(range(12, 20))
        assert all(r["kind"] == "step" for r in snap["records"])
        assert snap["violations"] == 1
        doc = json.load(open(flight.dump(path=str(tmp_path / "f.json"))))
        assert doc["violations"] == 1
        assert doc["last_violation"]["violating_metric"] == "loss"
    finally:
        flight.configure(capacity=256)


def test_flight_dump_is_atomic_and_json_safe(tmp_path):
    # foreign scalar types land in records/meta in practice (numpy
    # losses, jax ints) — a CRASH dump must encode them, never raise
    flight.annotate(layout="dp", rng_seed=20,
                    h2d=np.float32(3.5), weird=object())
    flight.record(kind="step", loss=float("nan"),
                  grad_norm=float("inf"), npnan=np.float32("nan"), step=0)
    path = flight.dump(path=str(tmp_path / "flight.json"), reason="manual")
    raw = open(path).read()
    doc = json.loads(raw)  # strict: would reject bare NaN tokens
    assert doc["meta"]["layout"] == "dp" and doc["meta"]["rng_seed"] == 20
    assert doc["meta"]["h2d"] == 3.5
    assert isinstance(doc["meta"]["weird"], str)
    assert doc["records"][0]["loss"] == "nan"
    assert doc["records"][0]["grad_norm"] == "inf"
    assert doc["records"][0]["npnan"] == "nan"
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]


def test_configure_none_clears_run_dir(tmp_path, monkeypatch):
    """run_dir=None must CLEAR a previously-set dir (back to the env
    default) — or a stale test/run dir leaks into every later dump."""
    flight.configure(run_dir=str(tmp_path / "a"))
    flight.record(kind="step", step=0)
    monkeypatch.setenv("DDL25_FLIGHT_DIR", str(tmp_path / "dflt"))
    flight.configure(run_dir=None)
    p = flight.dump(reason="manual")
    assert p == os.path.join(str(tmp_path / "dflt"), "flight.json")
    flight.configure()  # no args: leaves the (cleared) dir untouched
    assert flight.dump(reason="manual") == p


def test_sigterm_handler_preserves_sig_ign(tmp_path, monkeypatch):
    """A process that chose to IGNORE SIGTERM must keep ignoring it
    after install(): the handler dumps and returns, never exits."""
    import signal

    exits = []
    monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
    prev = signal.signal(signal.SIGTERM, signal.SIG_IGN)
    try:
        flight.configure(run_dir=str(tmp_path))
        flight.install()
        flight.record(kind="step", step=0)
        handler = signal.getsignal(signal.SIGTERM)
        assert callable(handler)
        handler(signal.SIGTERM, None)  # simulated delivery
        assert exits == [], "SIG_IGN process must not be killed"
        doc = json.load(open(tmp_path / "flight.json"))
        assert doc["reason"] == "sigterm"
    finally:
        flight.uninstall()
        signal.signal(signal.SIGTERM, prev)


def test_flight_excepthook_dumps_and_chains(tmp_path):
    seen = []
    prev_hook = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    try:
        flight.configure(run_dir=str(tmp_path))
        flight.install()
        assert sys.excepthook is not prev_hook
        flight.record(kind="step", step=0)
        try:
            raise RuntimeError("boom")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
        doc = json.load(open(tmp_path / "flight.json"))
        assert doc["reason"] == "unhandled_exception"
        assert "boom" in doc["exception"]
        assert seen, "previous excepthook must still run"
    finally:
        flight.uninstall()
        sys.excepthook = prev_hook


# --------------------------------------------------------------- watchdog


def test_watchdog_dump_carries_thread_stacks(tmp_path):
    """The r01–r05 acceptance pin: a stalled step fires the watchdog,
    whose dump names every host thread's blocking frame — including the
    artificially wedged worker's sleep."""
    release = threading.Event()

    def wedged_worker():
        release.wait(10.0)

    t = threading.Thread(
        target=wedged_worker, name="wedged-worker", daemon=True
    )
    t.start()
    wd = StallWatchdog(
        deadline_s=0.25, run_dir=str(tmp_path), name="unit", source="self"
    )
    with wd:
        deadline = time.monotonic() + 5.0
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.05)
    release.set()
    assert wd.fired and wd.dump_path
    doc = json.load(open(wd.dump_path))
    assert doc["reason"] == "stall"
    assert doc["stall"]["watchdog"] == "unit"
    assert doc["stall"]["deadline_s"] == 0.25
    stacks = doc["thread_stacks"]
    wedged = [v for k, v in stacks.items() if "wedged-worker" in k]
    assert wedged, f"wedged thread missing from {sorted(stacks)}"
    assert any("wedged_worker" in frame for frame in wedged[0])
    # a LATER dump (end_of_run / atexit) must not erase the stall fact:
    # the ring-derived summary keeps the --check-health gate honest
    doc2 = json.load(open(flight.dump(reason="end_of_run")))
    assert doc2["reason"] == "end_of_run"
    assert doc2["stalls"] == 1
    assert doc2["stall"]["watchdog"] == "unit"


def test_watchdog_beat_rearms_and_flight_source():
    wd = StallWatchdog(deadline_s=0.2, name="beaten", poll_s=0.05)
    with wd:
        for _ in range(8):  # flight activity counts as progress
            flight.beat()
            time.sleep(0.05)
        assert not wd.fired
        time.sleep(0.6)
        assert wd.fired
        flight.beat()
        wd.beat()
        assert not wd.fired  # re-armed


def test_watchdog_restartable_after_stop(tmp_path):
    """stop() then start() must yield a LIVE monitor — a silently dead
    watchdog is the one failure mode this class may never have."""
    wd = StallWatchdog(deadline_s=0.2, run_dir=str(tmp_path),
                       name="restart", source="self", poll_s=0.05)
    with wd:
        time.sleep(0.05)
    assert not wd.fired
    with wd:  # second use of the same instance
        deadline = time.monotonic() + 5.0
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.05)
    assert wd.fired, "restarted watchdog never fired"


def test_thread_stacks_sees_this_thread():
    stacks = thread_stacks()
    mine = [v for k, v in stacks.items() if "MainThread" in k]
    assert mine and any("test_thread_stacks" in f for f in mine[0])


# --------------------------------- bench driver + report integration


def test_bench_classify_failure_reason_codes():
    import bench

    assert bench.classify_failure(
        "accelerator unreachable: device init timed out after 240s"
    ) == "device_unreachable"
    assert bench.classify_failure(
        "RuntimeError: UNAVAILABLE: tunnel closed"
    ) == "device_unreachable"
    assert bench.classify_failure(
        "attempt 2: bench subprocess exceeded 2400s and was killed"
    ) == "stalled"
    assert bench.classify_failure(
        "XlaRuntimeError: INTERNAL: Mosaic compilation failed"
    ) == "compile_error"
    assert bench.classify_failure("ValueError: batch 7 not divisible") \
        == "runtime_error"
    assert bench.classify_failure(None) == "runtime_error"


def test_bench_health_rides_the_dead_line():
    import bench

    rec = {"metric": "m", "value": 0.0,
           "error": "accelerator unreachable: device init timed out",
           "flight_dump": "runs/x/flight.json"}
    failures = [{"record": "bench_retry_failure", "attempt": 1,
                 "error": "device init timed out",
                 "reason": "device_unreachable",
                 "flight_dump": "runs/x/flight.json",
                 "backoff_s": 0.0, "wall_s": 1.0, "rc": None}]
    out = bench.attach_parent_telemetry(rec, failures, None)
    h = out["telemetry"]["health"]
    assert h["flight_dump"] == "runs/x/flight.json"
    assert h["reason"] == "device_unreachable"
    assert out["telemetry"]["retry_failures"][0]["reason"] == (
        "device_unreachable"
    )


def _mini_run_dir(tmp_path, with_violation: bool):
    run = tmp_path / "run"
    run.mkdir(parents=True)
    with open(run / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"record": "header", "n_chips": 1}) + "\n")
        f.write(json.dumps(
            {"record": "step", "step": 0, "wall_s": 0.1, "label": "x"}
        ) + "\n")
    flight.reset()
    flight.record(kind="step", strategy="dp", step=0, loss=1.0)
    if with_violation:
        flight.record(kind="violation", strategy="dp", step=1,
                      violating_metric="loss", violation=True)
    flight.dump(path=str(run / "flight.json"), reason="test")
    return str(run)


def test_report_health_section_and_check_health(tmp_path):
    from ddl25spring_tpu.obs.report import format_report, summarize_run
    from tools.obs_report import main as report_main

    run = _mini_run_dir(tmp_path, with_violation=True)
    s = summarize_run(run)
    assert s["health"]["violations"] == 1
    assert s["health"]["last_violation"]["strategy"] == "dp"
    text = format_report(s)
    assert "health (flight.json" in text
    assert "sentinel violations: 1" in text
    assert "last violation: strategy=dp" in text

    # --check-health: violations -> rc 3; clean run -> rc 0
    assert report_main([run, "--check-health"]) == 3
    clean = _mini_run_dir(tmp_path / "c", with_violation=False)
    assert report_main([clean, "--check-health"]) == 0
    assert report_main([clean]) == 0  # no flag: report only


def test_tools_import_path_for_obs_report(tmp_path):
    """tools/obs_report.py is also runnable as a script; its module
    import above must not have shadowed the package."""
    import tools.obs_report as m

    assert callable(m.main)
