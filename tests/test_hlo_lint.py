"""The hazard linter: per-rule synthetic positives + clean baselines.

Two contracts pinned here:

1. **Every rule fires** — each H-rule gets one deliberately-hazardous
   synthetic HLO module (and each S-rule one pitfall Python snippet)
   proving the rule detects what it claims, plus a near-miss showing it
   stays quiet when the hazard is absent.
2. **Every strategy is clean** — every registered strategy (all
   twenty-one, the rule-table and speculative-serving variants
   included) compiles with ZERO
   unwaived findings on this jax, the same
   way PR 2 pinned their collective signatures.  A refactor that
   introduces a sync-collective pileup, a donation miss, an axis leak,
   or a participant-stream mismatch fails here (and the ``graft-lint``
   CI job) before it ever reaches a TPU.

The strategy compiles ride the shared session cache in
``tests/conftest.py`` — one compile per strategy per test session,
shared with test_xla_analytics's signature pins and test_sched's
overlap-bound pins.
"""

import json

import pytest

from ddl25spring_tpu.analysis import engine, source_lint
from ddl25spring_tpu.analysis.rules import (
    DEFAULT_THRESHOLDS,
    Finding,
    severity_rank,
    worst_severity,
)
from ddl25spring_tpu.analysis.waivers import apply_waivers, load_waivers
from ddl25spring_tpu.obs.compile_report import DEFAULT_STRATEGIES
from ddl25spring_tpu.utils.mesh import make_mesh
from conftest import cached_strategy_report as _report  # lower-once cache


def _rules_fired(findings):
    return {f.rule for f in findings}


def _lint(hlo, **kw):
    kw.setdefault("obs_enabled", False)
    kw.setdefault("waivers", [])
    return engine.lint_hlo_text(hlo, **kw)


# --------------------------------------------------------- rule positives

_ADD = """\
%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}
"""

H001_SYNC = f"""\
HloModule h001
{_ADD}
ENTRY %main (x: f32[1048576]) -> f32[1048576] {{
  %x = f32[1048576]{{0}} parameter(0)
  ROOT %ar = f32[1048576]{{0}} all-reduce(f32[1048576]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
}}
"""


def test_h001_sync_collective_fires_and_async_is_exempt():
    fs = _lint(H001_SYNC)
    assert "H001" in _rules_fired(fs)
    f = next(f for f in fs if f.rule == "H001")
    assert f.severity == "warn"
    # ring all-reduce over 4 devices: 2*(n-1)/n x the 4 MiB payload
    assert f.bytes == int(2 * 4 * 1048576 * 3 / 4)
    # the async spelling of the same op is the fix, not a finding
    fs2 = _lint(H001_SYNC.replace("all-reduce(", "all-reduce-start("))
    assert "H001" not in _rules_fired(fs2)
    # below the byte threshold: scalar loss pmeans must never fire
    small = H001_SYNC.replace("1048576]", "8]")
    assert "H001" not in _rules_fired(_lint(small))


# a PROPERLY paired async all-reduce: -start issues, independent
# compute runs (the overlap), -done collects — the exact shape the
# overlapped strategies must lower to on hardware with async-collective
# support, and the fix H001's hint prescribes
H001_ASYNC_PAIRED = f"""\
HloModule h001async
{_ADD}
ENTRY %main (x: f32[1048576], y: f32[1048576]) -> f32[1048576] {{
  %x = f32[1048576]{{0}} parameter(0)
  %y = f32[1048576]{{0}} parameter(1)
  %ars = f32[1048576]{{0}} all-reduce-start(f32[1048576]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
  %w = f32[1048576]{{0}} multiply(f32[1048576]{{0}} %y, f32[1048576]{{0}} %y)
  %ard = f32[1048576]{{0}} all-reduce-done(f32[1048576]{{0}} %ars)
  ROOT %out = f32[1048576]{{0}} add(f32[1048576]{{0}} %ard, f32[1048576]{{0}} %w)
}}
"""


def test_h001_paired_async_collective_passes():
    """The negative the overlap work pins: a 4 MiB all-reduce lowered
    as a start/done pair with intervening compute is the OVERLAPPED
    form — H001 must stay quiet, and the parser must count the pair as
    ONE async op site (the -done op never double-counts)."""
    fs = _lint(H001_ASYNC_PAIRED)
    assert "H001" not in _rules_fired(fs)
    from ddl25spring_tpu.obs.xla_analytics import parse_hlo_collectives

    ops = parse_hlo_collectives(H001_ASYNC_PAIRED)
    ars = [o for o in ops if o["kind"] == "all-reduce"]
    assert len(ars) == 1
    assert ars[0]["async"] is True
    assert ars[0]["result_bytes"] == 4 * 1048576


def test_h001_judges_wire_bytes_not_result_shape():
    """A reduce-scatter's RESULT is payload/n, but (n-1) result-sized
    shards cross the wire — the rule must catch it despite the small
    result shape."""
    rs = f"""\
HloModule h001rs
{_ADD}
ENTRY %main (x: f32[524288]) -> f32[131072] {{
  %x = f32[524288]{{0}} parameter(0)
  ROOT %rs = f32[131072]{{0}} reduce-scatter(f32[524288]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, dimensions={{0}}, to_apply=%add
}}
"""
    fs = _lint(rs)
    f = next(f for f in fs if f.rule == "H001")
    # result = 512 KiB (under the 1 MiB threshold), wire = (n-1) x result
    # = 1.5 MiB (over it): only the wire measure catches this one
    assert 131072 * 4 < DEFAULT_THRESHOLDS["h001_sync_bytes"] <= f.bytes


H002_INVERSE = f"""\
HloModule h002
{_ADD}
ENTRY %main (x: f32[8,64]) -> f32[8,64] {{
  %x = f32[8,64]{{1,0}} parameter(0)
  %ag = f32[32,64]{{1,0}} all-gather(f32[8,64]{{1,0}} %x), replica_groups={{{{0,1,2,3}}}}, dimensions={{0}}
  ROOT %rs = f32[8,64]{{1,0}} reduce-scatter(f32[32,64]{{1,0}} %ag), replica_groups={{{{0,1,2,3}}}}, dimensions={{0}}, to_apply=%add
}}
"""

H002_GATHER_SLICE = """\
HloModule h002b
ENTRY %main (x: f32[8,64], i: s32[]) -> f32[2,64] {
  %x = f32[8,64]{1,0} parameter(0)
  %i = s32[] parameter(1)
  %zero = s32[] constant(0)
  %ag = f32[32,64]{1,0} all-gather(f32[8,64]{1,0} %x), replica_groups={{0,1,2,3}}, dimensions={0}
  %r = f32[32,64]{1,0} reshape(f32[32,64]{1,0} %ag)
  ROOT %ds = f32[2,64]{1,0} dynamic-slice(f32[32,64]{1,0} %r, s32[] %i, s32[] %zero), dynamic_slice_sizes={2,64}
}
"""


def test_h002_inverse_pair_and_gather_then_slice():
    assert "H002" in _rules_fired(_lint(H002_INVERSE))
    # the walk crosses pass-through ops (reshape) to find the gather
    fs = _lint(H002_GATHER_SLICE)
    assert any(
        f.rule == "H002" and "dynamic-sliced" in f.message for f in fs
    )
    # gather NOT feeding its inverse (or a slice) is quiet
    solo = H002_GATHER_SLICE.replace(
        "f32[32,64]{1,0} %r, s32[] %i", "f32[32,64]{1,0} %x2, s32[] %i"
    ).replace(
        "%r = f32[32,64]{1,0} reshape(f32[32,64]{1,0} %ag)",
        "%x2 = f32[32,64]{1,0} broadcast(f32[8,64]{1,0} %x), dimensions={0,1}",
    )
    assert "H002" not in _rules_fired(_lint(solo))


# optimized HLO routinely fuses the consumer: the dynamic-slice lives in
# a fused computation whose parameter 0 is the caller's all-gather
H002_FUSED_SLICE = """\
HloModule h002c
%fused_slice (p0: f32[32,64], p1: s32[]) -> f32[2,64] {
  %p0 = f32[32,64]{1,0} parameter(0)
  %p1 = s32[] parameter(1)
  %z = s32[] constant(0)
  ROOT %ds = f32[2,64]{1,0} dynamic-slice(f32[32,64]{1,0} %p0, s32[] %p1, s32[] %z), dynamic_slice_sizes={2,64}
}
ENTRY %main (x: f32[8,64], i: s32[]) -> f32[2,64] {
  %x = f32[8,64]{1,0} parameter(0)
  %i = s32[] parameter(1)
  %ag = f32[32,64]{1,0} all-gather(f32[8,64]{1,0} %x), replica_groups={{0,1,2,3}}, dimensions={0}
  ROOT %f = f32[2,64]{1,0} fusion(f32[32,64]{1,0} %ag, s32[] %i), kind=kLoop, calls=%fused_slice
}
"""


def test_h002_sees_through_fusion_computations():
    """Fusion bodies are reachable (the multiplier walk only follows
    control flow) and the producer walk climbs from a fused parameter
    back to the caller's operand — the fused form of gather-then-slice
    must not hide the hazard."""
    fs = _lint(H002_FUSED_SLICE)
    assert any(
        f.rule == "H002" and "dynamic-sliced" in f.message for f in fs
    )


H003_UNKNOWN_TRIP = """\
HloModule h003a
%body (p: (s32[], f32[4,8])) -> (s32[], f32[4,8]) {
  %p = (s32[], f32[4,8]{1,0}) parameter(0)
  %c = s32[] get-tuple-element((s32[], f32[4,8]{1,0}) %p), index=0
  %g = f32[4,8]{1,0} get-tuple-element((s32[], f32[4,8]{1,0}) %p), index=1
  %cp = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %g), source_target_pairs={{0,1},{1,0}}
  ROOT %t = (s32[], f32[4,8]{1,0}) tuple(%c, %cp)
}
%cond (p: (s32[], f32[4,8])) -> pred[] {
  %p = (s32[], f32[4,8]{1,0}) parameter(0)
  ROOT %lt = pred[] constant(false)
}
ENTRY %main (x: f32[4,8]) -> f32[4,8] {
  %x = f32[4,8]{1,0} parameter(0)
  %c0 = s32[] constant(0)
  %t = (s32[], f32[4,8]{1,0}) tuple(%c0, %x)
  %w = (s32[], f32[4,8]{1,0}) while((s32[], f32[4,8]{1,0}) %t), condition=%cond, body=%body
  ROOT %out = f32[4,8]{1,0} get-tuple-element((s32[], f32[4,8]{1,0}) %w), index=1
}
"""


def test_h003_unknown_trip_count_fires_and_known_is_quiet():
    fs = _lint(H003_UNKNOWN_TRIP)
    assert any(
        f.rule == "H003" and "unknown trip" in f.message for f in fs
    )
    known = H003_UNKNOWN_TRIP.replace(
        "condition=%cond, body=%body",
        'condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"7"}}',
    )
    # trip known AND the permute's operand changes each iteration (the
    # carry slot holds the permute result): nothing to report
    assert "H003" not in _rules_fired(_lint(known))


# carry slot 1 is returned untouched (ROOT passes gte 1 through) yet the
# all-gather re-sends it every one of the 7 annotated iterations
H003_HOISTABLE = """\
HloModule h003b
%body (p: (s32[], f32[128])) -> (s32[], f32[128]) {
  %p = (s32[], f32[128]{0}) parameter(0)
  %c = s32[] get-tuple-element((s32[], f32[128]{0}) %p), index=0
  %inv = f32[128]{0} get-tuple-element((s32[], f32[128]{0}) %p), index=1
  %ag = f32[512]{0} all-gather(f32[128]{0} %inv), replica_groups={{0,1,2,3}}, dimensions={0}
  %one = s32[] constant(1)
  %c2 = s32[] add(s32[] %c, s32[] %one)
  ROOT %t = (s32[], f32[128]{0}) tuple(%c2, %inv)
}
%cond (p: (s32[], f32[128])) -> pred[] {
  %p = (s32[], f32[128]{0}) parameter(0)
  ROOT %lt = pred[] constant(false)
}
ENTRY %main (x: f32[128]) -> f32[128] {
  %x = f32[128]{0} parameter(0)
  %c0 = s32[] constant(0)
  %t = (s32[], f32[128]{0}) tuple(%c0, %x)
  %w = (s32[], f32[128]{0}) while((s32[], f32[128]{0}) %t), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"7"}}
  ROOT %out = f32[128]{0} get-tuple-element((s32[], f32[128]{0}) %w), index=1
}
"""


def test_h003_loop_invariant_collective_is_hoistable():
    fs = _lint(H003_HOISTABLE)
    assert any(
        f.rule == "H003" and "loop-invariant" in f.message for f in fs
    )


H004_UPCAST = f"""\
HloModule h004
{_ADD}
ENTRY %main (x: bf16[1024]) -> f32[1024] {{
  %x = bf16[1024]{{0}} parameter(0)
  %cv = f32[1024]{{0}} convert(bf16[1024]{{0}} %x)
  ROOT %ar = f32[1024]{{0}} all-reduce(f32[1024]{{0}} %cv), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
}}
"""


def test_h004_upcast_before_collective():
    fs = _lint(H004_UPCAST)
    f = next(f for f in fs if f.rule == "H004")
    assert "bf16" in f.message and "2x" in f.message
    # down-casting before the wire is the FIX, never a finding
    down = H004_UPCAST.replace(
        "%cv = f32[1024]{0} convert(bf16[1024]{0} %x)",
        "%cv = f32[1024]{0} convert(f64[1024]{0} %y)",
    )
    assert "H004" not in _rules_fired(_lint(down))


H005_MISS = """\
HloModule h005, input_output_alias={ {1}: (1, {}, may-alias) }
ENTRY %main (p0: f32[262144], p1: f32[262144], b: f32[64]) -> (f32[262144], f32[262144]) {
  %p0 = f32[262144]{0} parameter(0), metadata={op_name="params[\'w\']"}
  %p1 = f32[262144]{0} parameter(1), metadata={op_name="opt_state[0]"}
  %b = f32[64]{0} parameter(2), metadata={op_name="batch"}
  ROOT %t = (f32[262144]{0}, f32[262144]{0}) tuple(%p0, %p1)
}
"""


def test_h005_donation_miss_only_for_donatable_params():
    report = {"donation": {"donatable_leaves": 2}, "lowered": "train_step"}
    fs = _lint(H005_MISS, report=report)
    missed = [f for f in fs if f.rule == "H005"]
    # param 0 (1 MiB, donatable, unaliased) fires; param 1 is aliased;
    # the batch input (#2) is beyond donatable_leaves and exempt
    assert len(missed) == 1
    assert missed[0].op == "params['w']"
    assert missed[0].severity == "error"
    assert missed[0].bytes == 4 * 262144
    # without donatable info (forward-only lowering) the rule claims
    # nothing
    assert "H005" not in _rules_fired(_lint(H005_MISS, report=None))


H006_CALLBACK = """\
HloModule h006
ENTRY %main (x: f32[8]) -> f32[8] {
  %x = f32[8]{0} parameter(0)
  %c = s64[] constant(7)
  %cc = () custom-call(s64[] %c, f32[8]{0} %x), custom_call_target="xla_python_cpu_callback", custom_call_has_side_effect=true
  ROOT %y = f32[8]{0} add(f32[8]{0} %x, f32[8]{0} %x)
}
"""


def test_h006_host_roundtrip_gated_on_obs():
    fs = _lint(H006_CALLBACK, obs_enabled=False)
    assert any(f.rule == "H006" and f.severity == "error" for f in fs)
    # instrumentation ON means the host cost was requested
    assert "H006" not in _rules_fired(
        _lint(H006_CALLBACK, obs_enabled=True)
    )
    outfeed = H006_CALLBACK.replace(
        'custom-call(s64[] %c, f32[8]{0} %x), custom_call_target='
        '"xla_python_cpu_callback", custom_call_has_side_effect=true',
        "outfeed(f32[8]{0} %x, token[] %tok)",
    ).replace(
        "%c = s64[] constant(7)", "%tok = token[] after-all()"
    )
    assert "H006" in _rules_fired(_lint(outfeed, obs_enabled=False))


H007_DUP_TARGET = """\
HloModule h007
ENTRY %main (x: f32[4,8]) -> f32[4,8] {
  %x = f32[4,8]{1,0} parameter(0)
  ROOT %cp = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %x), source_target_pairs={{0,1},{2,1},{1,3}}
}
"""


def test_h007_mismatched_permute_cycle():
    fs = _lint(H007_DUP_TARGET)
    f = next(f for f in fs if f.rule == "H007")
    assert "repeats a target" in f.message
    ok = H007_DUP_TARGET.replace("{0,1},{2,1},{1,3}", "{0,1},{1,2},{2,0}")
    assert "H007" not in _rules_fired(_lint(ok))
    # duplicate SOURCES are legal one-to-many multicast, never a finding
    multicast = H007_DUP_TARGET.replace(
        "{0,1},{2,1},{1,3}", "{0,1},{0,2},{1,3}"
    )
    assert "H007" not in _rules_fired(_lint(multicast))


H007_AXIS_LEAK = f"""\
HloModule h007b
{_ADD}
ENTRY %main (x: f32[4,8]) -> f32[4,8] {{
  %x = f32[4,8]{{1,0}} parameter(0)
  ROOT %ar = f32[4,8]{{1,0}} all-reduce(f32[4,8]{{1,0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
}}
"""


@pytest.fixture(scope="module")
def mesh22(devices8):
    return make_mesh(devices8[:4], outer=2, inner=2)


def test_h007_axis_leak_against_declared_signature(mesh22):
    # groups {0,1,2,3} span BOTH axes of the 2x2 mesh; the signature
    # only declares traffic on "inner"
    report = {"expected": {"all-reduce": {"axes": ["inner"]}}}
    fs = _lint(H007_AXIS_LEAK, mesh=mesh22, report=report)
    assert any(f.rule == "H007" and "axis leak" in f.message for f in fs)
    # declaring both axes clears it
    report2 = {"expected": {"all-reduce": {"axes": ["inner", "outer"]}}}
    fs2 = _lint(H007_AXIS_LEAK, mesh=mesh22, report=report2)
    assert "H007" not in _rules_fired(fs2)
    # no declaration at all -> the rule has no baseline to judge against
    assert "H007" not in _rules_fired(_lint(H007_AXIS_LEAK, mesh=mesh22))


# ------------------------------------------ sched rule pack (H008-H010)

# 4 MiB async pair closed immediately: the cosmetic-overlap shape the
# PR-9 motivation names — H001's has-a-pair test passes it trivially,
# H008 must not
H008_ZERO_SLACK_PAIR = f"""\
HloModule h008
{_ADD}
ENTRY %main (x: f32[1048576], a: f32[512,512], b: f32[512,512]) -> f32[1048576] {{
  %x = f32[1048576]{{0}} parameter(0)
  %a = f32[512,512]{{1,0}} parameter(1)
  %b = f32[512,512]{{1,0}} parameter(2)
  %ars = f32[1048576]{{0}} all-reduce-start(f32[1048576]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
  %ard = f32[1048576]{{0}} all-reduce-done(f32[1048576]{{0}} %ars)
  %d = f32[512,512]{{1,0}} dot(f32[512,512]{{1,0}} %a, f32[512,512]{{1,0}} %b), lhs_contracting_dims={{1}}, rhs_contracting_dims={{0}}
  ROOT %out = f32[1048576]{{0}} add(f32[1048576]{{0}} %ard, f32[1048576]{{0}} %ard)
}}
"""


def test_h008_zero_slack_async_pair_fires():
    fs = _lint(H008_ZERO_SLACK_PAIR)
    f = next(f for f in fs if f.rule == "H008")
    assert f.severity == "warn"
    assert "cosmetic" in f.message
    # H001 is satisfied by the pair — exactly the blind spot H008 covers
    assert "H001" not in _rules_fired(fs)


def test_h008_near_miss_pair_with_real_window_is_quiet():
    # the same pair with the 2*512^3-FLOP dot INSIDE the window (above
    # 1% of the transfer's wire time on the reference chip): overlapped
    # for real, H008 stays quiet
    moved = H008_ZERO_SLACK_PAIR.replace(
        "  %ard = f32[1048576]{0} all-reduce-done(f32[1048576]{0} %ars)\n"
        "  %d = f32[512,512]{1,0} dot(f32[512,512]{1,0} %a, "
        "f32[512,512]{1,0} %b), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}\n",
        "  %d = f32[512,512]{1,0} dot(f32[512,512]{1,0} %a, "
        "f32[512,512]{1,0} %b), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}\n"
        "  %ard = f32[1048576]{0} all-reduce-done(f32[1048576]{0} %ars)\n",
    )
    assert "H008" not in _rules_fired(_lint(moved))
    # below the byte threshold nothing fires either way
    small = H008_ZERO_SLACK_PAIR.replace("1048576]", "1024]")
    assert "H008" not in _rules_fired(_lint(small))


def test_h008_judges_overlap_declared_sync_collectives_too():
    """An overlap-DECLARED strategy (describe meta overlap=True) whose
    big sync collective has no dataflow-independent work is the same
    cosmetic claim without the async spelling — H008 fires; give the
    window real independent compute and it clears."""
    sync_big = f"""\
HloModule h008b
{_ADD}
ENTRY %main (x: f32[1048576], a: f32[512,512], b: f32[512,512]) -> f32[1048576] {{
  %x = f32[1048576]{{0}} parameter(0)
  %a = f32[512,512]{{1,0}} parameter(1)
  %b = f32[512,512]{{1,0}} parameter(2)
  %ar = f32[1048576]{{0}} all-reduce(f32[1048576]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
  ROOT %out = f32[1048576]{{0}} negate(f32[1048576]{{0}} %ar)
}}
"""
    report = {"meta": {"overlap": True}}
    fs = _lint(sync_big, report=report)
    assert any(f.rule == "H008" and "no dataflow-independent" in f.message
               for f in fs)
    # the dot is independent of the all-reduce: a real dataflow window
    with_dot = sync_big.replace(
        "ROOT %out = f32[1048576]{0} negate(f32[1048576]{0} %ar)",
        "%d = f32[512,512]{1,0} dot(f32[512,512]{1,0} %a, "
        "f32[512,512]{1,0} %b), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}\n"
        "  ROOT %out = f32[1048576]{0} negate(f32[1048576]{0} %ar)",
    )
    assert "H008" not in _rules_fired(_lint(with_dot, report=report))
    # without the overlap declaration the sync op is H001's department
    assert "H008" not in _rules_fired(_lint(sync_big))


# two sites share channel 7 but group the mesh differently: every
# participant waits on a peer set that never assembles — the
# mismatched-participant deadlock H007 (shape-local: duplicate permute
# targets, axis leaks) cannot catch
H009_CHANNEL_MISMATCH = f"""\
HloModule h009, num_partitions=4
{_ADD}
ENTRY %main (x: f32[1024], y: f32[1024]) -> f32[1024] {{
  %x = f32[1024]{{0}} parameter(0)
  %y = f32[1024]{{0}} parameter(1)
  %ar1 = f32[1024]{{0}} all-reduce(f32[1024]{{0}} %x), channel_id=7, replica_groups={{{{0,1}},{{2,3}}}}, use_global_device_ids=true, to_apply=%add
  %ar2 = f32[1024]{{0}} all-reduce(f32[1024]{{0}} %y), channel_id=7, replica_groups={{{{0,2}},{{1,3}}}}, use_global_device_ids=true, to_apply=%add
  ROOT %s = f32[1024]{{0}} add(f32[1024]{{0}} %ar1, f32[1024]{{0}} %ar2)
}}
"""


def test_h009_mismatched_participants_deadlock_h007_cannot_catch():
    fs = _lint(H009_CHANNEL_MISMATCH)
    f = next(f for f in fs if f.rule == "H009")
    assert f.severity == "error"
    assert "channel-group-mismatch" in f.message
    # H007's shape-local checks see nothing wrong with either site
    assert "H007" not in _rules_fired(fs)
    # near miss: same channel, same groups — two instances of one
    # rendezvous shape, perfectly legal
    ok = H009_CHANNEL_MISMATCH.replace("{{0,2},{1,3}}", "{{0,1},{2,3}}")
    assert "H009" not in _rules_fired(_lint(ok))


def test_h009_divergent_conditional_sequences():
    hlo = f"""\
HloModule h009b
{_ADD}
%true_b (t: f32[256]) -> f32[256] {{
  %t = f32[256]{{0}} parameter(0)
  ROOT %ar = f32[256]{{0}} all-reduce(f32[256]{{0}} %t), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
}}
%false_b (f: f32[256]) -> f32[256] {{
  %f = f32[256]{{0}} parameter(0)
  ROOT %n = f32[256]{{0}} negate(f32[256]{{0}} %f)
}}
ENTRY %main (p: pred[], x: f32[256]) -> f32[256] {{
  %p = pred[] parameter(0)
  %x = f32[256]{{0}} parameter(1)
  ROOT %c = f32[256]{{0}} conditional(pred[] %p, f32[256]{{0}} %x, f32[256]{{0}} %x), true_computation=%true_b, false_computation=%false_b
}}
"""
    fs = _lint(hlo)
    assert any(f.rule == "H009" and "divergent-branches" in f.message
               for f in fs)
    same = hlo.replace(
        "ROOT %n = f32[256]{0} negate(f32[256]{0} %f)",
        "ROOT %n = f32[256]{0} all-reduce(f32[256]{0} %f), "
        "replica_groups={{0,1,2,3}}, to_apply=%add",
    )
    assert "H009" not in _rules_fired(_lint(same))


def test_h010_prices_windows_against_measured_micro_costs():
    """H010 rides attach_measured_costs (the only place a static window
    and a live measurement meet): a window whose compute cannot cover
    the op's measured standalone cost fires; a window that can stays
    quiet."""
    from ddl25spring_tpu.analysis import sched as sched_mod

    zero = sched_mod.analyze_schedule(H008_ZERO_SLACK_PAIR)
    record = {
        "peak_flops_per_chip": 1e12,
        "micro": [{"op": "ars", "t_s": 1e-3}],
    }
    findings: list = []
    n = engine.attach_measured_costs(
        findings, record, sched=zero, strategy="synthetic", waivers=[]
    )
    assert n == 1
    (f,) = findings
    assert f["rule"] == "H010" and f["severity"] == "warn"
    assert "even in principle" in f["message"]
    assert not f["waived"]
    # near miss: the paired-with-dot window holds ~268 us of compute at
    # this peak — a 100 us measured transfer hides, no finding
    hlo_ok = H008_ZERO_SLACK_PAIR.replace(
        "  %ard = f32[1048576]{0} all-reduce-done(f32[1048576]{0} %ars)\n"
        "  %d = f32[512,512]{1,0} dot(f32[512,512]{1,0} %a, "
        "f32[512,512]{1,0} %b), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}\n",
        "  %d = f32[512,512]{1,0} dot(f32[512,512]{1,0} %a, "
        "f32[512,512]{1,0} %b), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}\n"
        "  %ard = f32[1048576]{0} all-reduce-done(f32[1048576]{0} %ars)\n",
    )
    ok = sched_mod.analyze_schedule(hlo_ok)
    fs2: list = []
    engine.attach_measured_costs(
        fs2, {"peak_flops_per_chip": 1e12,
              "micro": [{"op": "ars", "t_s": 100e-6}]},
        sched=ok, strategy="synthetic", waivers=[],
    )
    assert [f["rule"] for f in fs2] == []


def test_h010_findings_resolve_against_waivers():
    from ddl25spring_tpu.analysis import sched as sched_mod
    from ddl25spring_tpu.analysis.waivers import Waiver

    zero = sched_mod.analyze_schedule(H008_ZERO_SLACK_PAIR)
    record = {"peak_flops_per_chip": 1e12,
              "micro": [{"op": "ars", "t_s": 1e-3}]}
    findings: list = []
    engine.attach_measured_costs(
        findings, record, sched=zero, strategy="dp-overlap",
        waivers=[Waiver(rule="H010", strategy="dp-*",
                        reason="fake mesh: micro costs are dispatch-bound")],
    )
    assert findings and findings[0]["waived"]
    assert "dispatch-bound" in findings[0]["waived_reason"]


# ------------------------------------------------------- source rule pack

S101_SRC = """\
import os

def donation_default():
    return os.environ.get("DDL25_DONATE", "1") not in ("", "0")

TRACE_FLAG = os.environ.get("AT_IMPORT_IS_FINE")
"""


def test_s101_env_read_in_traced_module_function():
    fs = source_lint.lint_source(
        S101_SRC, "ddl25spring_tpu/parallel/bucketing.py"
    )
    assert [f.rule for f in fs] == ["S101"]  # module-level read exempt
    assert fs[0].op == "donation_default"
    # outside the traced-code scope (data loaders) env reads are fine
    assert source_lint.lint_source(
        S101_SRC, "ddl25spring_tpu/data/cifar10.py"
    ) == []


S102_SRC = """\
import jax
from functools import partial

def make_step_bad(fn):
    return jax.jit(fn)

def make_step_good(fn):
    return jax.jit(fn, donate_argnums=(0, 1))

@partial(jax.jit, donate_argnums=(0,))
def decorated_good(x):
    return x

@jax.jit
def decorated_bad(x):
    return x
"""


def test_s102_jit_without_donation_decision():
    fs = source_lint.lint_source(
        S102_SRC, "ddl25spring_tpu/parallel/newthing.py"
    )
    assert sorted(f.op for f in fs if f.rule == "S102") == [
        "decorated_bad", "make_step_bad",
    ]
    # out of the donation scope (models/) the rule does not apply
    assert source_lint.lint_source(
        S102_SRC, "ddl25spring_tpu/models/llama.py"
    ) == []


S103_SRC = """\
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

SIZES = np.arange(4)  # module level: static metadata, fine

def plain_helper(x):
    return np.prod(x.shape)  # undecorated helper: fine

@partial(jax.jit, donate_argnums=())
def step(x):
    def inner(y):
        return np.sum(y)  # traced context (nested): fires
    return jnp.sum(x) + np.mean(x)  # traced context: fires
"""


def test_s103_numpy_inside_traced_functions():
    fs = source_lint.lint_source(S103_SRC, "ddl25spring_tpu/anywhere.py")
    hits = [f for f in fs if f.rule == "S103"]
    assert len(hits) == 2
    assert {f.severity for f in hits} == {"error"}
    assert any("np.sum" in f.message for f in hits)
    assert any("np.mean" in f.message for f in hits)


# ----------------------------------------------------- waivers + summary


def test_waiver_file_roundtrip(tmp_path):
    p = tmp_path / "waivers.toml"
    p.write_text(
        '# test waivers\n'
        '[[waiver]]\n'
        'rule = "H001"\n'
        'strategy = "zero*"\n'
        'match = "sync"\n'
        'reason = "tiny mesh, overlap not worth it"\n'
    )
    ws = load_waivers(str(p))
    assert len(ws) == 1 and ws[0].rule == "H001"
    f_covered = Finding(rule="H001", severity="warn", strategy="zero3",
                        message="sync all-reduce ...")
    f_other = Finding(rule="H001", severity="warn", strategy="dp",
                      message="sync all-reduce ...")
    apply_waivers([f_covered, f_other], ws)
    assert f_covered.waived and f_covered.waived_reason
    assert not f_other.waived


def test_waiver_path_matches_absolute_hlo_sources(tmp_path):
    """H-rule findings carry ABSOLUTE paths (HLO source_file metadata);
    a repo-relative waiver path must still cover them."""
    p = tmp_path / "w.toml"
    p.write_text(
        '[[waiver]]\n'
        'rule = "H001"\n'
        'path = "ddl25spring_tpu/parallel/zero.py"\n'
        'reason = "tiny mesh"\n'
    )
    ws = load_waivers(str(p))
    f_abs = Finding(rule="H001", severity="warn", message="m",
                    source="/root/repo/ddl25spring_tpu/parallel/zero.py:55")
    f_rel = Finding(rule="H001", severity="warn", message="m",
                    source="ddl25spring_tpu/parallel/zero.py:55")
    f_other = Finding(rule="H001", severity="warn", message="m",
                      source="/root/repo/ddl25spring_tpu/parallel/dp.py:9")
    apply_waivers([f_abs, f_rel, f_other], ws)
    assert f_abs.waived and f_rel.waived and not f_other.waived


def test_waiver_without_reason_is_rejected(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text('[[waiver]]\nrule = "H001"\n')
    with pytest.raises(ValueError, match="reason"):
        load_waivers(str(p))
    p.write_text('[[waiver]]\nrule = "H001"\nreason = "r"\ntypo = "x"\n')
    with pytest.raises(ValueError, match="unknown keys"):
        load_waivers(str(p))


def test_mini_parser_rejects_trailing_junk_but_takes_comments():
    """A malformed line must not silently drop its tail (which would
    WIDEN the waiver); a trailing comment is fine — matching what
    tomllib does on 3.11, so the two parsers never diverge."""
    from ddl25spring_tpu.analysis.waivers import _parse_mini

    ok = _parse_mini(
        '[[waiver]]\nrule = "H001"  # the overlap rule\nreason = "r"\n'
    )
    assert ok["waiver"][0] == {"rule": "H001", "reason": "r"}
    with pytest.raises(ValueError, match="after string value"):
        _parse_mini('[[waiver]]\nrule = "H001" strategy = "dp"\n')


def _tomllib():
    try:
        import tomllib

        return tomllib
    except ModuleNotFoundError:  # the 3.10 image: fallback only
        return None


def test_mini_parser_matches_tomllib_on_escaped_quotes():
    """The fallback parser is load-bearing on the 3.10 build image —
    every construct the schema allows must parse IDENTICALLY to
    tomllib (checked directly on 3.11 CI, pinned by value here)."""
    from ddl25spring_tpu.analysis.waivers import _parse_mini

    text = (
        '[[waiver]]\n'
        'rule = "H001"\n'
        'match = "say \\"sync\\" twice"\n'
        'reason = "quoted \\"reason\\" with a # inside"\n'
    )
    mini = _parse_mini(text)
    assert mini["waiver"][0]["match"] == 'say "sync" twice'
    assert mini["waiver"][0]["reason"] == 'quoted "reason" with a # inside'
    tl = _tomllib()
    if tl is not None:
        assert mini == tl.loads(text)


def test_mini_parser_matches_tomllib_on_crlf_line_endings():
    """A waivers.toml saved with CRLF endings (Windows checkout, or a
    heredoc through a CR-preserving pipe) must parse identically —
    the \\r must never leak into a rule id or reason string."""
    from ddl25spring_tpu.analysis.waivers import _parse_mini

    text = (
        '[[waiver]]\r\n'
        'rule = "H005"\r\n'
        'reason = "crlf file"\r\n'
        '\r\n'
        '[[waiver]]\r\n'
        'rule = "H001"\r\n'
        'reason = "second entry"\r\n'
    )
    mini = _parse_mini(text)
    assert [w["rule"] for w in mini["waiver"]] == ["H005", "H001"]
    assert mini["waiver"][0]["reason"] == "crlf file"
    tl = _tomllib()
    if tl is not None:
        assert mini == tl.loads(text)


def test_mini_parser_matches_tomllib_on_escaped_hash_and_backslash_tail():
    """PR-12 satellite: the one-char-lookbehind quote scanner mis-read
    a string ending in an ESCAPED BACKSLASH (``"...\\\\"``) — the
    closing quote looked escaped, so the scanner hunted past it and,
    with a ``#`` comment on the line, swallowed the comment while
    looking for a closing quote that never came (a loud failure on a
    VALID file).  And ``\\#`` — not a TOML escape — parsed silently
    where tomllib rejects it: a waiver that loads on the 3.10 build
    image and crashes 3.11 CI.  Both halves pinned against tomllib."""
    from ddl25spring_tpu.analysis.waivers import _parse_mini

    # a reason ending in a literal backslash, with a trailing comment
    text = (
        '[[waiver]]\n'
        'rule = "H001"\n'
        'reason = "win path C:\\\\temp\\\\" # checkout note\n'
    )
    mini = _parse_mini(text)
    assert mini["waiver"][0]["reason"] == "win path C:\\temp\\"
    tl = _tomllib()
    if tl is not None:
        assert mini == tl.loads(text)

    # an escaped '#' inside the reason string: INVALID TOML — both
    # parsers must refuse (silent acceptance here is the divergence)
    bad = '[[waiver]]\nrule = "H001"\nreason = "keep the \\# literal"\n'
    with pytest.raises(ValueError, match="invalid escape"):
        _parse_mini(bad)
    if tl is not None:
        with pytest.raises(Exception):
            tl.loads(bad)

    # a PLAIN '#' inside the string (no escape) stays legal, comment
    # detection untouched
    ok = _parse_mini(
        '[[waiver]]\nrule = "H001"\nreason = "a # inside" # real comment\n'
    )
    assert ok["waiver"][0]["reason"] == "a # inside"

    # \uXXXX / \UXXXXXXXX are VALID TOML — the mini parser must accept
    # them exactly as tomllib does (review fix: rejecting them crashed
    # the 3.10 image on a file 3.11 CI accepts)
    uni = (
        '[[waiver]]\nrule = "H001"\n'
        'reason = "caf\\u00e9 \\U0001F600"\n'
    )
    mini = _parse_mini(uni)
    assert mini["waiver"][0]["reason"] == "caf\u00e9 \U0001F600"
    if tl is not None:
        assert mini == tl.loads(uni)
    with pytest.raises(ValueError, match="truncated"):
        _parse_mini('[[waiver]]\nrule = "H001"\nreason = "x\\u00"\n')
    # int(_, 16) would silently take '00_4' — strict hex digits only,
    # and lone surrogates are not scalar values (tomllib rejects both)
    with pytest.raises(ValueError, match="non-hex"):
        _parse_mini('[[waiver]]\nrule = "H001"\nreason = "x\\u00_4y"\n')
    with pytest.raises(ValueError, match="scalar"):
        _parse_mini('[[waiver]]\nrule = "H001"\nreason = "x\\uD800y"\n')


def test_mini_parser_rejects_table_of_tables_loudly():
    """tomllib accepts plain/nested tables (``[waiver]``,
    ``[waiver.meta]``); the mini parser supports exactly the
    array-of-tables schema and must REJECT anything else loudly —
    silently ignoring a section tomllib would honor is how the two
    parsers diverge into a waiver that works on CI (3.11) and not on
    the build image (3.10)."""
    from ddl25spring_tpu.analysis.waivers import _parse_mini

    for text in (
        '[waiver]\nrule = "H001"\nreason = "r"\n',
        '[[waiver]]\nrule = "H001"\nreason = "r"\n[waiver.meta]\nx = "y"\n',
    ):
        tl = _tomllib()
        if tl is not None:
            tl.loads(text)  # tomllib is fine with it — the divergence
        with pytest.raises(ValueError, match="only \\[\\[table\\]\\]"):
            _parse_mini(text)


def test_load_waivers_reads_crlf_and_escaped_quotes_from_disk(tmp_path):
    """End-to-end through load_waivers: binary-written CRLF bytes and
    escaped quotes survive the open()/parse path on any Python."""
    p = tmp_path / "w.toml"
    p.write_bytes(
        b'[[waiver]]\r\n'
        b'rule = "S102"\r\n'
        b'symbol = "make_\\"odd\\"_step"\r\n'
        b'reason = "windows checkout"\r\n'
    )
    (w,) = load_waivers(str(p))
    assert w.rule == "S102"
    assert w.symbol == 'make_"odd"_step'
    assert w.reason == "windows checkout"


def test_repo_waiver_file_loads_and_every_entry_has_reason():
    ws = load_waivers()
    assert ws, "analysis/waivers.toml should carry the in-repo waivers"
    assert all(w.reason for w in ws)


def test_severity_order_and_summary():
    assert severity_rank("error") > severity_rank("warn") > severity_rank(
        "info"
    ) > severity_rank(None)
    assert worst_severity(["info", "error", "warn"]) == "error"
    fs = [
        Finding(rule="H001", severity="warn", message="a"),
        Finding(rule="H005", severity="error", message="b", waived=True,
                waived_reason="ok"),
    ]
    s = engine.summarize(fs)
    assert s == {
        "findings": 2, "unwaived": 1, "waived": 1, "worst": "warn",
        "by_rule": {"H001": 1, "H005": 1},
    }


# ------------------------------------------------ per-strategy baselines


@pytest.mark.parametrize("name", DEFAULT_STRATEGIES)
def test_strategy_hlo_lints_clean(name):
    """The pinned clean baselines: every registered strategy's compiled
    train step carries ZERO unwaived hazard findings on this jax."""
    r = _report(name)
    assert "lint_error" not in r, r.get("lint_error")
    assert "findings" in r
    unwaived = [f for f in r["findings"] if not f["waived"]]
    assert unwaived == [], (
        f"{name} regressed: {[(f['rule'], f['message']) for f in unwaived]}"
    )


def test_strategy_reports_carry_donation_walk_fields():
    r = _report("dp")
    assert r["donation"]["donatable_leaves"] == 3
    # every donatable input is in the alias table (donate=True describe)
    assert set(range(3)) <= set(r["donation"]["aliased_params"])
    assert [p["number"] for p in r["entry_params"]] == sorted(
        p["number"] for p in r["entry_params"]
    )
    args = {p["arg"] for p in r["entry_params"] if p["arg"]}
    assert any(a.startswith("params[") for a in args)


def test_repo_source_lints_clean():
    """Dogfood pin: the repo's own Python has no unwaived findings (the
    PR-3 trace-time env read in bucketing.donation_default is fixed, the
    three justified jit sites are waived in analysis/waivers.toml)."""
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = apply_waivers(
        source_lint.lint_repo(repo_root), load_waivers()
    )
    unwaived = [f for f in findings if not f.waived]
    assert unwaived == [], [
        (f.rule, f.source, f.op) for f in unwaived
    ]
    # the waivers are live, not dead entries
    assert any(f.waived for f in findings)


# --------------------------------------------------------- CLI + consumers


def test_graft_lint_cli_check_is_green(capsys):
    from tools import graft_lint

    assert graft_lint.main(["--check"]) == 0
    assert "graft-lint OK" in capsys.readouterr().err


def test_graft_lint_cli_json_format(capsys):
    from tools import graft_lint

    assert graft_lint.main(["--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["record"] == "graft_lint"
    assert {f["rule"] for f in doc["source"]} == {"S102"}
    assert all(f["waived"] for f in doc["source"])


def test_bench_lint_summary_condenses_compile_report():
    import bench

    cr = {"strategies": {
        "dp": {"findings": [
            {"rule": "H001", "severity": "warn", "waived": False},
            {"rule": "H005", "severity": "error", "waived": True},
        ]},
        "ep": {"findings": []},
        "dead": {"error": "no compile"},
    }}
    s = bench.lint_summary(cr)
    assert s["findings"] == 2 and s["unwaived"] == 1
    assert s["worst"] == "warn"
    assert s["per_strategy"]["dp"]["unwaived"] == 1
    assert s["per_strategy"]["ep"]["findings"] == 0
    # an unjudged strategy is an ERROR in the summary, never "clean"
    assert s["errors"] == 1
    assert s["per_strategy"]["dead"] == {"error": "no compile"}
    rec = bench.attach_parent_telemetry({}, None, cr)
    assert rec["telemetry"]["lint"]["unwaived"] == 1


def test_comms_report_findings_cell():
    from tools.comms_report import _findings_cell

    assert _findings_cell({}) == "hazards: not analyzed (lint=False)"
    assert _findings_cell({"findings": []}) == "hazards: none"
    cell = _findings_cell({"findings": [
        {"rule": "H001", "severity": "warn", "waived": False},
        {"rule": "H001", "severity": "warn", "waived": True},
    ]})
    assert "1 unwaived" in cell and "worst warn" in cell
    assert "1 waived" in cell and "H001" in cell
    assert "lint degraded" in _findings_cell({"lint_error": "boom"})


def test_lint_threshold_defaults_are_sane():
    assert DEFAULT_THRESHOLDS["h001_sync_bytes"] == 1024 * 1024
    assert DEFAULT_THRESHOLDS["h005_donation_bytes"] == 64 * 1024
    assert DEFAULT_THRESHOLDS["scalar_bytes"] == 64
