"""The serving engine (``ddl25spring_tpu/serve``): paged-KV
equivalence pins, continuous batching, admission control, and the
report/gate tooling.

The load-bearing pins:

- **paged == dense, bitwise** — greedy fp32 decode through the page
  pool reproduces ``models/decode.generate`` token for token, including
  a sequence spanning a page boundary and one admitted mid-batch (the
  whole correctness contract of ``kv_pages``).
- **continuous beats static** — on a seeded capacity-bound trace, slots
  refilling mid-flight deliver strictly more tokens by the fixed budget
  than drain-the-whole-batch admission (the reason ``serve/`` exists).
- **compile signatures** — serve-decode/serve-prefill pin all-reduce-
  ONLY collectives over the model axis, riding the session's
  lower-once strategy cache (``tests/conftest.py``) like every
  training strategy.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.models import decode as dm, llama
from ddl25spring_tpu.serve import kv_pages
from ddl25spring_tpu.serve.engine import (
    REJECT_BAD_REQUEST,
    REJECT_POOL_EXHAUSTED,
    REJECT_QUEUE_FULL,
    REJECT_TOKEN_BUDGET,
    REJECT_TOO_LONG,
    ServeEngine,
)
from ddl25spring_tpu.serve.traffic import (
    TrafficSpec,
    synth_trace,
    trace_tokens,
)
from ddl25spring_tpu.utils.config import LlamaConfig

from conftest import cached_lowering

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


def dense_greedy(params, prompt: list[int], max_new: int) -> list[int]:
    """The dense-cache oracle, compiled once per (|prompt|, max_new)."""

    def build():
        toks = dm.generate(
            params, jnp.asarray([prompt], jnp.int32), CFG,
            max_new_tokens=max_new, temperature=0.0,
        )
        return [int(t) for t in np.asarray(toks)[0]]

    return cached_lowering(("serve-dense", tuple(prompt), max_new), build)


def make_engine(params, **kw):
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


# ------------------------------------------------- equivalence pins


def test_paged_reproduces_dense_across_a_page_boundary(params):
    """fp32 greedy decode through the page-table cache == the dense
    cache, token for token — with prompt 4 + 9 generated crossing the
    page_len=4 boundary twice (pages 4..7 and 8..12)."""
    prompt = [5, 9, 11, 3]
    max_new = 9
    dense = dense_greedy(params, prompt, max_new)

    eng = make_engine(params)
    eng.warmup()  # also pins: warmup leaves no state behind
    assert eng.generated_tokens == 0 and eng.admitted == 0
    assert not eng.ttft_s and not eng.done
    req = eng.make_request(prompt, max_new)
    assert eng.submit(req) is None
    drain(eng)
    assert req.tokens == dense
    assert eng.pool_ok_failures == 0


def test_mid_batch_admission_is_token_exact(params):
    """A request admitted into a slot WHILE another decodes produces
    exactly its own dense generation — the cross-sequence isolation of
    the shared page pool (and the continuous-batching admission path)."""
    a_prompt, a_new = [5, 9, 11, 3], 9
    b_prompt, b_new = [7, 2, 8], 6
    dense_a = dense_greedy(params, a_prompt, a_new)
    dense_b = dense_greedy(params, b_prompt, b_new)

    eng = make_engine(params)
    ra = eng.make_request(a_prompt, a_new)
    assert eng.submit(ra) is None
    eng.step()  # prefill A, first decode tick
    eng.step()  # A decoding
    assert eng.slots[0] is ra and len(ra.tokens) >= 2
    rb = eng.make_request(b_prompt, b_new)
    assert eng.submit(rb) is None
    eng.step()  # admits B mid-flight while A stays resident
    assert rb.admitted_t is not None and ra.done_t is None
    drain(eng)
    assert ra.tokens == dense_a
    assert rb.tokens == dense_b
    assert eng.pool_ok_failures == 0


def test_eos_stops_a_sequence_and_frees_its_slot(params):
    """EOS mid-generation completes the request at the EOS token and
    releases its slot + pages — the capacity-return event continuous
    batching admits into."""
    prompt = [5, 9, 11, 3]
    dense = dense_greedy(params, prompt, 9)
    eos = dense[3]  # 4th generated token
    eng = make_engine(params, eos_id=eos)
    req = eng.make_request(prompt, 9)
    eng.submit(req)
    drain(eng)
    assert req.tokens == dense[:4]
    assert req.tokens[-1] == eos
    # every page returned: the device free mask is full again
    eng.step()  # flush the release mask
    assert int(jnp.sum(eng.pool["free"])) == eng.n_pages
    assert not any(eng.pool["active"].tolist())


def test_pages_freed_on_completion_and_host_mirror(params):
    eng = make_engine(params)
    req = eng.make_request([5, 9, 11, 3], 5)
    eng.submit(req)
    eng.step()
    assert eng._host_pages_used() > 0
    drain(eng)
    eng.step()  # flush release
    assert eng._host_pages_used() == 0
    assert int(jnp.sum(~eng.pool["free"])) == 0
    # 4 prompt + 4 appended generated tokens = 8 written positions ->
    # 2 pages of 4 at peak (the final sampled token is never written
    # back: its KV would only feed a token past the stop)
    assert eng.peak_pages == 2
    assert eng.metrics()["page_pool_peak_pages"] == 2


# ------------------------------------------------- continuous batching


def test_continuous_beats_static_on_the_seeded_trace(params):
    """THE acceptance pin: same trace, same engine knobs, virtual
    clock — admission into mid-flight freed slots delivers strictly
    more tokens by the fixed budget than static drain-then-refill."""
    from ddl25spring_tpu.serve.driver import ab_compare

    spec = TrafficSpec(
        seed=3, duration_s=0.2, rate_rps=120.0, profile="flat",
        vocab_size=CFG.vocab_size,
    )
    trace = synth_trace(spec)
    assert len(trace) >= 10
    knobs = dict(
        page_len=4, n_pages=16, max_slots=2, prefill_batch=2,
        max_prompt_len=8, max_queue=64, token_budget=None, eos_id=None,
    )
    ab = ab_compare(params, CFG, trace, knobs)
    assert ab["continuous_tokens_at_budget"] > ab["static_tokens_at_budget"]
    assert ab["advantage_tokens"] > 0
    # both drained the identical workload in full
    assert (ab["continuous"]["generated_tokens"]
            == ab["static"]["generated_tokens"])
    # and continuous took strictly fewer virtual seconds to do it
    assert (ab["continuous"]["drain_wall_s"]
            < ab["static"]["drain_wall_s"])


def test_ab_compare_equalizes_prefill_width(params):
    """The A/B must isolate admission policy: with prefill_batch <
    max_slots the static arm could never fill the batch (it only
    admits into an all-idle engine), so ab_compare forces
    ``prefill_batch=max_slots`` on BOTH arms.  Four simultaneous
    arrivals at width 2 -> static runs exactly 2 full-width prefills."""
    from ddl25spring_tpu.serve.driver import ab_compare

    trace = [
        {"t": 0.0, "prompt": [1 + i, 2 + i], "max_new": 3}
        for i in range(4)
    ]
    knobs = dict(
        page_len=4, n_pages=16, max_slots=2, prefill_batch=1,
        max_prompt_len=8, max_queue=64, token_budget=None, eos_id=None,
    )
    ab = ab_compare(params, CFG, trace, knobs)
    assert ab["static"]["prefills"] == 2
    assert ab["static"]["completed"] == 4
    assert ab["advantage_tokens"] >= 0


def test_token_timeline_readout(params):
    eng = make_engine(params)
    req = eng.make_request([5, 9], 4)
    eng.submit(req)
    drain(eng)
    assert eng.tokens_at(0.0) == 0
    assert eng.tokens_at(float("inf")) == eng.generated_tokens == 4
    counts = [n for _, n in eng.token_log]
    assert counts == sorted(counts)


# ------------------------------------------------- admission control


def test_rejection_reasons(params):
    eng = make_engine(params, max_queue=1, token_budget=16)
    # over the prefill program's STATIC prompt capacity: malformed for
    # this build (no compiled program can run it) — bad_request at the
    # door, NOT the policy-capacity too_long it was conflated with
    # before PR 11 (too_long should mean "well-formed but over the
    # context budget", so the admission counters stay truthful)
    r = eng.make_request(list(range(1, 10)), 2)
    assert eng.submit(r) == REJECT_BAD_REQUEST
    # too long: prompt + new over pages_per_seq * page_len
    r = eng.make_request([1, 2, 3], 30)
    assert eng.submit(r) == REJECT_TOO_LONG
    # worst-case pages over the whole pool
    small = make_engine(params, n_pages=2, pages_per_seq=4)
    r = small.make_request([1, 2, 3, 4], 8)  # 12 positions -> 3 pages > 2
    assert small.submit(r) == REJECT_POOL_EXHAUSTED
    # queue full
    assert eng.submit(eng.make_request([1], 2)) is None
    assert eng.submit(eng.make_request([1], 2)) == REJECT_QUEUE_FULL
    # token budget (fresh engine: queue holds 3+2 of 16, next 12+2 over)
    eng2 = make_engine(params, token_budget=16)
    assert eng2.submit(eng2.make_request([1, 2, 3], 2)) is None
    assert (eng2.submit(eng2.make_request([1, 2, 3, 4], 10))
            == REJECT_TOKEN_BUDGET)
    # malformed: an empty prompt would decode from the zero-initialized
    # logits buffer (a token the model never produced); non-positive
    # max_new would still emit one token the caller never asked for
    assert eng2.submit(eng2.make_request([], 3)) == REJECT_BAD_REQUEST
    assert eng2.submit(eng2.make_request([1, 2], 0)) == REJECT_BAD_REQUEST
    counts = eng.metrics()["rejected_by_reason"]
    assert counts[REJECT_TOO_LONG] == 1
    assert counts[REJECT_BAD_REQUEST] == 1
    assert counts[REJECT_QUEUE_FULL] == 1
    assert eng2.metrics()["rejected_by_reason"][REJECT_BAD_REQUEST] == 2


def test_head_of_line_backpressure_until_pages_free(params):
    """A request whose worst-case pages exceed the UNRESERVED pool
    waits at the head of the queue (no admission) until a completion
    frees capacity — then admits, and the device-side ok flag never
    fired (host accounting covered the pool exactly)."""
    eng = make_engine(params, n_pages=3, max_slots=2, prefill_batch=2)
    ra = eng.make_request([1, 2, 3, 4], 8)   # 12 pos -> 3 pages
    rb = eng.make_request([5, 6, 7, 8], 8)   # 3 more pages: must wait
    assert eng.submit(ra) is None
    assert eng.submit(rb) is None
    eng.step()
    assert ra.admitted_t is not None and rb.admitted_t is None
    drain(eng)
    assert rb.admitted_t is not None and rb.admitted_t > ra.done_t - 1e-9
    assert len(ra.tokens) == 8 and len(rb.tokens) == 8
    assert eng.pool_ok_failures == 0


def test_static_admission_waits_for_the_batch_to_drain(params):
    eng = make_engine(params, admission="static", prefill_batch=1)
    ra = eng.make_request([1, 2], 6)
    rb = eng.make_request([3, 4], 2)
    eng.submit(ra)
    eng.submit(rb)
    eng.step()
    assert ra.admitted_t is not None and rb.admitted_t is None
    # a free slot exists the whole time, but static admission refuses
    # to use it until EVERY slot is idle
    for _ in range(3):
        eng.step()
        if ra.done_t is None:
            assert rb.admitted_t is None
    drain(eng)
    assert rb.tokens and rb.admitted_t >= ra.done_t - 1e-9


# ------------------------------------------------- kv_pages units


def test_resolve_heads_validates_explicit_zero():
    assert kv_pages.resolve_heads(CFG, None) == CFG.num_heads
    assert kv_pages.resolve_heads(CFG, 1) == 1
    with pytest.raises(ValueError, match="num_heads=0"):
        kv_pages.resolve_heads(CFG, 0)
    with pytest.raises(ValueError, match="num_heads=-2"):
        kv_pages.resolve_heads(CFG, -2)


def test_init_kv_cache_rejects_zero_heads():
    """The ISSUE-10 satellite fix: the old ``num_heads or
    cfg.num_heads`` idiom treated an explicit 0 as unset and silently
    built a full-head cache."""
    with pytest.raises(ValueError, match="num_heads=0"):
        dm.init_kv_cache(CFG, batch=1, max_len=8, num_heads=0)
    k, v = dm.init_kv_cache(CFG, batch=1, max_len=8, num_heads=1)
    assert k.shape == (CFG.n_layers, 1, 8, 1, CFG.head_dim)


def test_page_pool_reserve_write_release_roundtrip():
    pool = kv_pages.init_page_pool(
        CFG, n_pages=4, page_len=2, max_slots=2, pages_per_seq=2,
    )
    slots = jnp.arange(2, dtype=jnp.int32)
    pos = jnp.zeros((2,), jnp.int32)
    pool, ok = kv_pages.reserve_pages(
        pool, slots, pos, jnp.asarray([True, True])
    )
    assert bool(ok)
    assert int(kv_pages.used_pages(pool)) == 2
    table = np.asarray(pool["page_table"])
    assert (table[:, 0] >= 0).all() and (table[:, 1] == -1).all()
    assert table[0, 0] != table[1, 0]  # distinct pages
    # masked writes land in the trash page, never a live one
    pages, offs = kv_pages.write_page_ids(
        pool, slots, pos, jnp.asarray([True, False])
    )
    assert int(pages[1]) == 4  # the trash row (n_pages)
    pool = kv_pages.release_slots(pool, jnp.asarray([True, False]))
    assert int(kv_pages.used_pages(pool)) == 1
    assert (np.asarray(pool["page_table"])[0] == -1).all()


def test_reserve_pages_refuses_past_table_position_atomically():
    """A needed row whose position falls past the page table must fail
    the WHOLE call with nothing allocated: consuming the page from the
    free mask while the table write drop-routes would leak it forever
    (in no table, so release_slots could never return it)."""
    pool = kv_pages.init_page_pool(
        CFG, n_pages=4, page_len=4, max_slots=2, pages_per_seq=2,
    )
    pool2, ok = kv_pages.reserve_pages(
        pool,
        jnp.asarray([0]),
        jnp.asarray([2 * 4]),  # entry 2 >= pages_per_seq
        jnp.asarray([True]),
    )
    assert not bool(ok)
    assert int(jnp.sum(pool2["free"])) == 4  # nothing consumed
    assert int(kv_pages.used_pages(pool2)) == 0
    assert (pool2["page_table"] == pool["page_table"]).all()


def test_reserve_pages_refuses_overcommit_atomically():
    pool = kv_pages.init_page_pool(
        CFG, n_pages=1, page_len=2, max_slots=2, pages_per_seq=2,
    )
    pool, ok = kv_pages.reserve_pages(
        pool,
        jnp.arange(2, dtype=jnp.int32),
        jnp.zeros((2,), jnp.int32),
        jnp.asarray([True, True]),
    )
    assert not bool(ok)
    # NOTHING allocated: the flag is all-or-nothing
    assert int(kv_pages.used_pages(pool)) == 0


def test_init_page_pool_validates_geometry():
    with pytest.raises(ValueError, match="n_pages=0"):
        kv_pages.init_page_pool(
            CFG, n_pages=0, page_len=2, max_slots=1, pages_per_seq=1,
        )


def test_engine_rejects_explicit_zero_pages_per_seq(params):
    """``pages_per_seq=0`` must fail loudly in the pool, not silently
    fall back to the ctx_size-derived default (the same falsy-zero
    class as the ``init_kv_cache`` ``num_heads=0`` fix)."""
    with pytest.raises(ValueError, match="pages_per_seq=0"):
        make_engine(params, pages_per_seq=0)


def test_engine_rejects_zero_prefill_batch(params):
    """``prefill_batch=0`` admits nothing and never advances the
    virtual clock — run() would spin to max_steps with admitted=0.
    It must fail at construction like the other geometry knobs."""
    with pytest.raises(ValueError, match="prefill_batch=0"):
        make_engine(params, prefill_batch=0)


def test_prefill_completed_request_skips_the_decode_tick(params):
    """A request that completes DURING prefill (max_new=1) must have
    its device slot released before the same step's decode tick: the
    tick would otherwise write KV for a dead sequence and could lazily
    allocate a page neither admission nor the host peak mirror sees."""
    prompt = [5, 9, 11, 3]
    dense_b = dense_greedy(params, [7, 2], 1)
    eng = make_engine(params, prefill_batch=2)
    ra = eng.make_request(prompt, 6)
    rb = eng.make_request([7, 2], 1)
    assert eng.submit(ra) is None and eng.submit(rb) is None
    eng.step()  # prefill admits both; rb completes at its first token
    assert rb.done_t is not None and rb.tokens == dense_b
    assert ra.done_t is None
    # rb's slot (1) is inactive on device and its pages are back in
    # the pool BEFORE the decode tick that ran for ra in this step
    assert not bool(eng.pool["active"][1])
    assert int(jnp.sum(~eng.pool["free"])) == eng._host_pages_used()
    drain(eng)
    eng.step()  # flush ra's release
    assert int(jnp.sum(~eng.pool["free"])) == 0
    assert eng.pool_ok_failures == 0


# ------------------------------------------------- traffic


def test_trace_is_seed_deterministic():
    spec = TrafficSpec(seed=7, duration_s=1.0, rate_rps=10.0)
    a, b = synth_trace(spec), synth_trace(spec)
    assert a == b and len(a) > 0
    c = synth_trace(TrafficSpec(seed=8, duration_s=1.0, rate_rps=10.0))
    assert a != c
    assert all(0.0 <= r["t"] < 1.0 for r in a)
    assert trace_tokens(a) == sum(
        len(r["prompt"]) + r["max_new"] for r in a
    )


def test_ramp_and_spike_profiles_shape_the_rate():
    ramp = TrafficSpec(profile="ramp", rate_rps=10.0, duration_s=10.0)
    assert ramp.rate_at(0.0) == pytest.approx(1.0)
    assert ramp.rate_at(10.0) == pytest.approx(10.0)
    spike = TrafficSpec(profile="spike", rate_rps=10.0, duration_s=9.0)
    assert spike.rate_at(1.0) == pytest.approx(3.0)
    assert spike.rate_at(4.5) == pytest.approx(10.0)
    assert spike.rate_at(8.0) == pytest.approx(3.0)
    with pytest.raises(ValueError, match="profile"):
        TrafficSpec(profile="bogus").rate_at(1.0)
    assert synth_trace(TrafficSpec(rate_rps=0.0)) == []


# ------------------------------------------------- compile signatures


@pytest.mark.parametrize("name,ar_count", [
    ("serve-decode", 2 * 2),          # 2 psums/block x 2 layers
    ("serve-prefill", 2 * 2 * 8),     # x max_prompt_len scan
    # the start-offset variant scans max_prompt_len - start = 4
    # positions: HALF serve-prefill's collectives — the compile-time
    # proof of the prefill work a radix prefix hit skips
    ("serve-prefill-cached", 2 * 2 * 4),
])
def test_serve_signature_pins(strategy_report, name, ar_count):
    """TP serving traffic is the row-parallel all-reduce ONLY: exact
    count over the model axis, every other collective forbidden, HBM
    inside the registered budget — pinned through the same registry
    gates as every training strategy (lower-once session cache)."""
    r = strategy_report(name)
    assert r["signature_violations"] == []
    assert [f for f in r["findings"] if not f["waived"]] == []
    totals = r["collectives"]["totals"]
    assert set(totals) == {"all-reduce"}
    assert totals["all-reduce"]["count"] == ar_count
    assert r["sched"]["hazards"] == []
    assert r["lowered"] in ("decode_step", "prefill_step")


# ------------------------------------------------- driver + tooling


@pytest.fixture(scope="module")
def smoke_record(params, tmp_path_factory):
    """One tiny end-to-end driver run shared by the contract tests
    (compiles ride the per-engine jit caches; keep it single)."""
    from ddl25spring_tpu.serve import driver

    out = tmp_path_factory.mktemp("serve_run")
    led = str(out / "ledger.jsonl")
    rec = driver.run_serve_bench(
        smoke=True, obs_dir=str(out), duration_s=0.5, rate_rps=40.0,
        profile="ramp", seed=0, ledger_path=led,
    )
    return rec, out, led


SERVE_CONTRACT_KEYS = (
    "tokens_per_sec_per_chip", "ttft_s_p50", "ttft_s_p95",
    "tok_latency_s_p50", "tok_latency_s_p95", "admitted", "rejected",
    "completed", "page_pool_peak_occupancy", "page_pool_peak_pages",
)


def test_driver_emits_the_telemetry_serve_contract(smoke_record):
    from ddl25spring_tpu.serve import driver

    rec, out, led = smoke_record
    cell = driver.serve_cell(rec)
    for k in SERVE_CONTRACT_KEYS:
        assert cell.get(k) is not None, k
    assert cell["ab"]["advantage_tokens"] > 0
    assert json.loads(json.dumps(cell))  # BENCH-line serializable
    # artifacts: serve.json + one ledger row
    doc = json.loads((out / "serve.json").read_text())
    assert doc["record"] == "serve" and doc["ramp"]["admitted"] > 0
    rows = [json.loads(line)
            for line in open(led) if line.strip()]
    # PR 20: the driver also appends a record:"goodput" ledger row —
    # exactly one serve row and one goodput row per run
    by_rec = {}
    for r in rows:
        by_rec.setdefault(r["record"], []).append(r)
    assert sorted(by_rec) == ["goodput", "serve"]
    assert len(by_rec["serve"]) == 1 and len(by_rec["goodput"]) == 1
    serve_row = by_rec["serve"][0]
    assert serve_row["ab"]["advantage_tokens"] > 0
    assert by_rec["goodput"][0]["key"]["scope"] == "serve"
    # raw sample lists stay OUT of the ledger (stdlib tool, 1 line/run)
    assert "ttft_s" not in serve_row and "tick_wall_s" not in serve_row


def test_serve_report_renders_and_checks(smoke_record, capsys):
    import tools.serve_report as serve_report

    rec, out, led = smoke_record
    # run report + single-row ledger: passes with "no baseline yet"
    assert serve_report.main(
        [str(out), "--ledger", led, "--check", "--check-ab"]
    ) == 0
    cap = capsys.readouterr()
    assert "TTFT histogram" in cap.out
    assert "no baseline yet" in cap.err

    # a regressed latest row trips the gate
    row = json.loads((out / "serve.json").read_text())
    good = serve_report.read_ledger(led)[0]
    bad = dict(good)
    bad["tokens_per_sec_per_chip"] = (
        good["tokens_per_sec_per_chip"] * 0.1
    )
    bad["ttft_s_p95"] = good["ttft_s_p95"] * 10
    led2 = str(out / "regressed.jsonl")
    with open(led2, "w") as f:
        for r in (good, good, bad):
            f.write(json.dumps(r) + "\n")
    assert serve_report.main(
        ["--ledger-only", "--ledger", led2, "--check"]
    ) == 1
    cap = capsys.readouterr()
    assert "tokens_per_sec_per_chip" in cap.err
    assert "ttft_s_p95" in cap.err

    # hosts never gate each other: the regressed row on another host
    other = dict(bad, host="elsewhere/64cpu/tpu")
    led3 = str(out / "otherhost.jsonl")
    with open(led3, "w") as f:
        for r in (good, good, other):
            f.write(json.dumps(r) + "\n")
    assert serve_report.main(
        ["--ledger-only", "--ledger", led3, "--check"]
    ) == 0

    # --check-ab trips when continuous failed to beat static
    tied = dict(good)
    tied["ab"] = dict(good["ab"], advantage_tokens=0)
    led4 = str(out / "tied.jsonl")
    with open(led4, "w") as f:
        f.write(json.dumps(tied) + "\n")
    assert serve_report.main(
        ["--ledger-only", "--ledger", led4, "--check", "--check-ab"]
    ) == 1
    # --check-ab alone implies --check: the verdict must gate, not
    # print-and-exit-0
    assert serve_report.main(
        ["--ledger-only", "--ledger", led4, "--check-ab"]
    ) == 1
    assert row["record"] == "serve"  # sanity on the artifact we mutated


def test_check_ab_is_scoped_to_the_run_under_test(smoke_record):
    """A historical row recorded with --no-serve-ab on an UNRELATED
    key must not wedge ``--check-ab`` for the run under test forever;
    the run's OWN group still gates strictly, and ledger-only mode
    (no run dir to scope to) keeps the strict behavior."""
    import tools.serve_report as serve_report

    rec, out, led = smoke_record
    good = serve_report.read_ledger(led)[0]
    stale = {k: v for k, v in good.items() if k != "ab"}
    stale["key"] = dict(good["key"], profile="spike")  # foreign group
    # a foreign key may also hold a documented TIE (unloaded engine)
    tied = dict(good, key=dict(good["key"], rate_rps=0.5))
    tied["ab"] = dict(good["ab"], advantage_tokens=0)
    led2 = str(out / "stale_foreign_ab.jsonl")
    with open(led2, "w") as f:
        for r in (stale, tied, good):
            f.write(json.dumps(r) + "\n")
    assert serve_report.main(
        [str(out), "--ledger", led2, "--check", "--check-ab"]
    ) == 0
    # ledger-only mode has no run to scope to: still strict
    assert serve_report.main(
        ["--ledger-only", "--ledger", led2, "--check", "--check-ab"]
    ) == 1
    # the run's own group missing its ab cell DOES gate
    own = {k: v for k, v in good.items() if k != "ab"}
    led3 = str(out / "own_missing_ab.jsonl")
    with open(led3, "w") as f:
        for r in (stale, own):
            f.write(json.dumps(r) + "\n")
    assert serve_report.main(
        [str(out), "--ledger", led3, "--check", "--check-ab"]
    ) == 1


def test_serve_report_missing_inputs(tmp_path):
    import tools.serve_report as serve_report

    assert serve_report.main(
        [str(tmp_path), "--ledger", str(tmp_path / "none.jsonl")]
    ) == 2  # no serve.json
    assert serve_report.main(
        ["--ledger-only", "--ledger", str(tmp_path / "none.jsonl"),
         "--check"]
    ) == 2  # --check with no ledger


def test_obs_report_renders_the_serving_section(smoke_record):
    from ddl25spring_tpu.obs.report import format_report, summarize_run

    rec, out, led = smoke_record
    s = summarize_run(str(out))
    assert s["serve"]["ramp"]["admitted"] == rec["ramp"]["admitted"]
    text = format_report(s)
    assert "serving (serve.json" in text
    assert "tokens/sec/chip" in text
    assert "A/B continuous" in text
