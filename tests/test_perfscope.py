"""Perf observatory: measurement decomposition invariants, the
regression ledger + gate, and the bench telemetry.perf contract.

The measurement layer's contract (``ddl25spring_tpu/obs/perfscope.py``):

- the step-wall decomposition is internally consistent — exposed comms
  is never negative, overlap efficiency lives in [0, 1], and the
  micro-cost table covers the compile-time collective inventory
  EXACTLY (every op site appears, costed or explicitly not);
- measured MFU is *defined* on this CPU image (the calibrated
  ``cpu-host`` pseudo-spec), with a projection error against the PR-2
  roofline on the same spec;
- records append to a JSONL ledger keyed by (strategy, mesh, host),
  and ``tools/perf_report.py --check`` trips on a genuinely slowed
  step (host-callback sleep) while a clean re-run passes.

Budget note (ROADMAP 870 s): the one dp measurement is compiled ONCE at
module scope and shared by every invariant test; the full
``bench.py --smoke`` subprocess pin is ``slow``-marked (CI's tier-1 job
asserts the same telemetry.perf contract on its own smoke run).
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.obs import perfscope

# ----------------------------------------------------- shared measurement

_CACHE: dict = {}


def _dp_record() -> dict:
    """Measure-once cache: the dp strategy's perf record (compiles the
    4-way step, the 1-device counterfactual, and the micro benches one
    time for the whole module)."""
    if "dp" not in _CACHE:
        _CACHE["dp"] = perfscope.measure_strategy(
            "dp", reps=4, warmup=2, micro_reps=3
        )[0]
    return _CACHE["dp"]


# ------------------------------------------------ decomposition invariants


def test_decomposition_invariants():
    rec = _dp_record()
    assert rec["step_s_p50"] > 0
    assert rec["step_s_p95"] >= rec["step_s_p50"] >= rec["step_s_min"]
    # the 1-device counterfactual exists for dp and is *compute*: with
    # the per-device workload held fixed (describe() scales its batch
    # with the mesh) it cannot exceed the contended 4-fake-device step
    # by more than scheduling noise (factor-2 slack: fake CPU devices
    # share this host's cores)
    assert rec["compute_s_p50"] is not None
    assert rec["compute_s_p50"] <= rec["step_s_p50"] * 2
    # exposed comms is clamped non-negative by construction
    assert rec["exposed_comms_s"] >= 0
    # dp's grad all-reduce is real traffic on this mesh: the micro cost
    # model must have priced it
    assert rec["micro_total_s"] > 0
    # capped at 1.0, deliberately NOT floored at 0: negative efficiency
    # is the contended-fake-mesh signal (exposure beyond the comms
    # bill) that before/after comparisons and --min-overlap-eff need
    assert rec["overlap_eff"] is None or rec["overlap_eff"] <= 1.0


def test_micro_costs_cover_inventory_exactly():
    """Every op site in the PR-2 collective inventory appears in the
    micro table — costed, or carrying an explicit why-not note."""
    from ddl25spring_tpu.obs import xla_analytics as xa

    rec = _dp_record()
    mesh = xa.strategy_mesh("dp")
    d = xa.describe_strategy("dp", mesh)
    compiled = d["fn"].lower(*d["args"]).compile()
    ops = xa.parse_hlo_collectives(compiled.as_text(), mesh)
    assert [m["op"] for m in rec["micro"]] == [o["name"] for o in ops]
    assert [m["count"] for m in rec["micro"]] == [o["count"] for o in ops]
    for m in rec["micro"]:
        assert (m["t_s"] is not None) or m.get("note")
    # the non-scalar grad-bucket all-reduce is costed (group of 4 over
    # the data axis — real wire traffic)
    big = [m for m in rec["micro"] if m["result_bytes"] > 64]
    assert big and all(m["t_s"] is not None and m["t_s"] > 0 for m in big)


def test_measured_mfu_defined_on_cpu_host():
    rec = _dp_record()
    assert rec["chip"] == "cpu-host"
    assert rec["peak_source"] == "calibrated-host"
    assert rec["peak_flops_per_chip"] and rec["peak_flops_per_chip"] > 0
    assert rec["measured_mfu"] and rec["measured_mfu"] > 0
    assert rec["projected_mfu"] and rec["projection_err"] is not None


def test_record_schema_and_ledger_key_fields():
    rec = _dp_record()
    required = {
        "record", "schema", "ts", "strategy", "mesh", "n_chips", "host",
        "git_sha", "jax_version", "backend", "chip",
        "peak_flops_per_chip", "peak_source", "reps", "warmup",
        "step_s_p50", "step_s_p95", "step_s_min", "compute_s_p50",
        "exposed_comms_s", "micro_total_s", "overlap_eff", "flops",
        "bytes_accessed", "wire_bytes", "measured_mfu", "projected_mfu",
        "projected_bound", "projection_err", "micro", "findings",
    }
    assert required <= set(rec)
    assert rec["record"] == "perf"
    assert rec["mesh"] == {"data": 4} and rec["n_chips"] == 4
    # the record is JSON-serializable as-is (the ledger contract)
    json.dumps(rec)


def test_perf_cell_carries_the_bench_contract_keys():
    cell = perfscope.perf_cell(_dp_record())
    assert {
        "measured_mfu", "overlap_eff", "exposed_comms_ms",
        "projection_err",
    } <= set(cell)
    assert cell["exposed_comms_ms"] is not None
    assert cell["exposed_comms_ms"] >= 0
    assert cell["measured_mfu"] > 0


def test_calibrated_host_peak_cached():
    from ddl25spring_tpu.utils.flops import calibrated_host_peak_flops

    p1 = calibrated_host_peak_flops()
    assert p1 and p1 > 0
    t0 = time.perf_counter()
    assert calibrated_host_peak_flops() == p1  # cache hit, no re-run
    assert time.perf_counter() - t0 < 0.05


# -------------------------------------------- ledger + regression gate


def _toy_step():
    """A step heavy enough (512x512 matmul chain, ~tens of ms on a CI
    core) that scheduling jitter is small RELATIVE to the wall time —
    light steps flake the tolerance band on shared CI machines."""
    a = jnp.full((512, 512), 0.5, jnp.float32)

    @jax.jit
    def f(x):
        for _ in range(8):
            x = x @ a
        return x

    return f, (a,)


def _slowed_step(sleep_s: float = 0.3):
    """The same toy step with a deliberate host-callback sleep inside
    the dispatch — the 'someone added a host round-trip to the hot
    path' regression the gate exists to catch."""
    f, (a,) = _toy_step()

    def cb(y):
        time.sleep(sleep_s)
        return np.asarray(y)

    @jax.jit
    def slow(x):
        y = f(x)
        return jax.pure_callback(
            cb, jax.ShapeDtypeStruct(y.shape, y.dtype), y
        )

    return slow, (a,)


def test_ledger_roundtrip_and_torn_tail(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    rec = perfscope.measure_callable(
        *_toy_step(), strategy="toy", reps=3, warmup=1
    )
    perfscope.append_ledger(rec, led)
    with open(led, "a") as f:
        f.write('{"record": "perf", "torn')  # killed mid-write
    out = perfscope.read_ledger(led)
    assert len(out) == 1
    assert out[0]["strategy"] == "toy"


def test_slowed_step_trips_the_gate_and_clean_rerun_passes(tmp_path):
    """The acceptance loop: clean baseline -> injected slowdown fails
    ``perf_report --check`` -> clean re-run passes again."""
    import tools.perf_report as perf_report

    # tolerance 1.0 (the wide CI-machine band the perf-smoke job uses):
    # clean re-measurements sit well inside 2x, while the 0.3 s
    # injected sleep is a ~10x step regression — unambiguous both ways
    band = ["--check", "--tolerance", "1.0"]
    led = str(tmp_path / "ledger.jsonl")
    fast_fn, fast_args = _toy_step()
    for _ in range(2):
        perfscope.append_ledger(perfscope.measure_callable(
            fast_fn, fast_args, strategy="toy", reps=6, warmup=2
        ), led)
    assert perf_report.main(["--ledger", led, *band]) == 0

    perfscope.append_ledger(perfscope.measure_callable(
        *_slowed_step(), strategy="toy", reps=4, warmup=1
    ), led)
    assert perf_report.main(["--ledger", led, *band]) == 1

    perfscope.append_ledger(perfscope.measure_callable(
        fast_fn, fast_args, strategy="toy", reps=6, warmup=2
    ), led)
    assert perf_report.main(["--ledger", led, *band]) == 0


def test_check_is_per_host_and_needs_a_baseline(tmp_path, capsys):
    import tools.perf_report as perf_report

    led = str(tmp_path / "ledger.jsonl")
    rec = perfscope.measure_callable(
        *_toy_step(), strategy="toy", reps=3, warmup=1
    )
    perfscope.append_ledger(rec, led)
    # single record: no baseline, check passes with a note
    assert perf_report.main(["--ledger", led, "--check"]) == 0
    assert "no baseline" in capsys.readouterr().err
    # a 100x slower record from a DIFFERENT host never gates this one
    other = dict(rec, host="elsewhere/64cpu/tpu",
                 step_s_p50=rec["step_s_p50"] * 100)
    perfscope.append_ledger(other, led)
    assert perf_report.main(["--ledger", led, "--check"]) == 0
    # missing ledger: rc 2 under --check (CI misconfiguration must not
    # read as a pass), rc 0 without
    assert perf_report.main(
        ["--ledger", str(tmp_path / "absent.jsonl"), "--check"]
    ) == 2
    assert perf_report.main(
        ["--ledger", str(tmp_path / "absent.jsonl")]
    ) == 0


def test_min_overlap_eff_floor_gates_and_skips_undefined(tmp_path, capsys):
    """The --min-overlap-eff satellite: an absolute floor on the latest
    record's measured overlap efficiency — gates even a single fresh
    record, skips keys whose efficiency is undefined, and stays out of
    the way when the flag is absent."""
    import tools.perf_report as perf_report

    led = str(tmp_path / "ledger.jsonl")
    base = perfscope.measure_callable(
        *_toy_step(), strategy="toy", reps=2, warmup=1
    )
    low = dict(base, strategy="ov-low", overlap_eff=0.2,
               exposed_comms_s=0.008, micro_total_s=0.01)
    high = dict(base, strategy="ov-high", overlap_eff=0.9,
                exposed_comms_s=0.001, micro_total_s=0.01)
    undefined = dict(base, strategy="ov-none", overlap_eff=None)
    for r in (low, high, undefined):
        perfscope.append_ledger(r, led)
    # floor above the low record's 0.2: exactly one key fails
    assert perf_report.main(
        ["--ledger", led, "--check", "--min-overlap-eff", "0.5"]
    ) == 1
    err = capsys.readouterr().err
    fails = [l for l in err.splitlines() if l.startswith("CHECK FAIL")]
    assert len(fails) == 1
    assert "ov-low" in fails[0] and "overlap_eff 0.200" in fails[0]
    # floor below every defined record: passes (undefined key skipped)
    assert perf_report.main(
        ["--ledger", led, "--check", "--min-overlap-eff", "0.1"]
    ) == 0
    # no flag: the floor never engages
    assert perf_report.main(["--ledger", led, "--check"]) == 0


def test_dp_record_carries_bucket_knob_fields():
    """Sweep comparability: every strategy record names the bucket
    threshold + plan it measured (the DDL25_BUCKET_BYTES knob's value
    at build time) so grid points and env-knob runs never mix
    silently.  Since PR 9 the describe() default is the multi-bucket
    DESCRIBE_BUCKET_BYTES (the sched verifier's overlap windows need
    >= 2 launches to exist), not the 4 MiB runtime default."""
    from ddl25spring_tpu.parallel import dp

    rec = _dp_record()
    assert rec["bucket_bytes"] == dp.DESCRIBE_BUCKET_BYTES
    assert rec["n_buckets"] == 3


def test_record_carries_static_overlap_bound():
    """PR-9 wiring: every measured record ships the schedule verifier's
    analytical overlap ceiling next to the measured overlap_eff — dp is
    a sync-issue strategy, so its committed schedule provably allows
    (essentially) nothing, and the bound says so deterministically."""
    rec = _dp_record()
    assert "static_overlap_bound" in rec
    assert rec["static_overlap_bound"] == 0.0
    assert "static_overlap_bound" in perfscope.perf_cell(rec)


def test_bucket_sweep_measures_grid_and_recommends(tmp_path):
    """tools/bucket_sweep.py: one re-tagged record per grid point (the
    perf gate never sees them), exactly one marked best, and the best
    is the measured-fastest."""
    from tools.bucket_sweep import render_table, sweep_strategy

    records = sweep_strategy(
        "dp", (1024, 4 * 1024 * 1024), reps=2, warmup=1, micro_reps=1
    )
    assert len(records) == 2
    assert all(r["record"] == "bucket_sweep" for r in records)
    assert [r["bucket_bytes"] for r in records] == [1024, 4 * 1024 * 1024]
    # the 1 KiB grid point splits the 2.6 KiB MLP tree; 4 MiB holds it
    assert records[0]["n_buckets"] > records[1]["n_buckets"] == 1
    best = [r for r in records if r.get("best")]
    assert len(best) == 1
    assert best[0]["step_s_p50"] == min(r["step_s_p50"] for r in records)
    table = render_table("dp", records)
    assert "best" in table and "bucket_bytes" in table
    # sweep records are invisible to the perf regression gate
    led = str(tmp_path / "ledger.jsonl")
    for r in records:
        perfscope.append_ledger(r, led)
    assert perfscope.read_ledger(led) == []


# ------------------------------------------------ H001 cross-referencing


def test_attach_measured_costs_prices_h001():
    from ddl25spring_tpu.analysis.engine import attach_measured_costs

    findings = [
        {"rule": "H001", "op": "all-reduce.7", "severity": "warn"},
        {"rule": "H001", "op": "all-reduce.9", "severity": "warn"},
        {"rule": "H005", "op": "params['w1']", "severity": "error"},
    ]
    record = {
        "exposed_comms_s": 0.004,
        "overlap_eff": 0.25,
        "micro": [
            {"op": "all-reduce.7", "t_s": 0.003, "t_total_s": 0.003},
            {"op": "other.1", "t_s": 0.001, "t_total_s": 0.001},
        ],
    }
    n = attach_measured_costs(findings, record)
    assert n == 2  # both H001s annotated; H005 untouched
    assert findings[0]["measured"]["t_s_per_exec"] == 0.003
    assert findings[0]["measured"]["exposed_comms_s"] == 0.004
    # op not in the micro table still gains the strategy-level context
    assert findings[1]["measured"]["exposed_comms_s"] == 0.004
    assert "t_s_per_exec" not in findings[1]["measured"]
    assert "measured" not in findings[2]
    # the bench parent hands over the ms-denominated telemetry cell
    cell_findings = [{"rule": "H001", "op": "x", "severity": "warn"}]
    attach_measured_costs(cell_findings, {"exposed_comms_ms": 12.0})
    assert cell_findings[0]["measured"]["exposed_comms_s"] == (
        pytest.approx(0.012)
    )


def test_strategy_record_findings_ride_with_measured_slot():
    rec = _dp_record()
    # dp is pinned lint-clean, so no H001 here — but the findings slot
    # exists and is trimmed to the ledger schema
    assert isinstance(rec["findings"], list)
    for f in rec["findings"]:
        assert set(f) <= {
            "rule", "severity", "op", "bytes", "source", "waived",
            "measured",
        }


# ----------------------------------------------------- report rendering


def test_perf_report_table_renders(tmp_path, capsys):
    import tools.perf_report as perf_report

    led = str(tmp_path / "ledger.jsonl")
    perfscope.append_ledger(_dp_record(), led)
    assert perf_report.main(["--ledger", led]) == 0
    out = capsys.readouterr().out
    assert "strategy dp" in out and "step p50" in out and "MFU" in out


def test_perf_report_format_json_is_machine_readable(tmp_path, capsys):
    """PR-9 satellite: --format json mirrors graft_lint --format json —
    one structured document carrying the grouped records AND every
    check verdict, so CI parses instead of grepping stderr tables."""
    import tools.perf_report as perf_report

    led = str(tmp_path / "ledger.jsonl")
    base = _dp_record()
    perfscope.append_ledger(base, led)
    slow = dict(base, step_s_p50=base["step_s_p50"] * 50, ts=base["ts"] + 1)
    perfscope.append_ledger(slow, led)

    assert perf_report.main(["--ledger", led, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["record"] == "perf_report"
    (g,) = doc["groups"]
    assert g["strategy"] == "dp" and len(g["records"]) == 2
    # the 50x regression verdict rides the document
    assert doc["check"]["ok"] is False and doc["check"]["fails"] == 1
    assert any("step_s_p50" in f for f in g["fails"])

    # --check still gates on the same shared verdicts
    assert perf_report.main(
        ["--ledger", led, "--format", "json", "--check"]
    ) == 1
    out = capsys.readouterr()
    assert json.loads(out.out)["check"]["fails"] == 1
    assert "CHECK FAIL" in out.err
    # legacy --json spelling stays an alias
    assert perf_report.main(["--ledger", led, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["record"] == "perf_report"


def test_obs_report_renders_performance_section(tmp_path, capsys):
    from ddl25spring_tpu.obs.report import format_report, summarize_run

    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    with open(os.path.join(run_dir, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"record": "header", "n_chips": 1}) + "\n")
        f.write(json.dumps(
            {"record": "step", "step": 0, "wall_s": 0.1, "label": "x"}
        ) + "\n")
    perfscope.write_run_perf(_dp_record(), run_dir)
    text = format_report(summarize_run(run_dir))
    assert "performance (perf.json" in text
    assert "measured MFU" in text
    assert "overlap efficiency" in text
    assert "cpu-host" in text


# --------------------------------------------- bench --smoke contract pin


@pytest.mark.slow
def test_bench_smoke_emits_perf_cell(tmp_path):
    """The acceptance pin: a --smoke BENCH line carries a full
    telemetry.perf cell and appends a ledger record.  slow-marked (one
    extra ResNet CPU compile); the tier-1 CI job asserts the same
    contract on its own bench --smoke run."""
    led = str(tmp_path / "ledger.jsonl")
    obs_dir = str(tmp_path / "run")
    # the CI smoke environment: single CPU device (the suite's 8-device
    # XLA_FLAGS would build the DPxPP pipeline, whose grad path cannot
    # trace on pre-VMA jax), production donation defaults
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "DDL25_DONATE", "DDL25_CHAOS")
    }
    env.update(JAX_PLATFORMS="cpu", DDL25_BENCH_NTRAIN="256")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--smoke",
         "--steps", "2", "--per-chip-batch", "16",
         "--obs-dir", obs_dir, "--perf-ledger", led],
        capture_output=True, text=True, timeout=900, env=env, cwd=root,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = [ln for ln in r.stdout.splitlines() if ln.strip()][-1]
    d = json.loads(line)
    perf = d["telemetry"]["perf"]
    for k in ("measured_mfu", "overlap_eff", "exposed_comms_ms",
              "projection_err"):
        assert k in perf, (k, perf)
    assert perf["measured_mfu"] > 0
    assert perf["exposed_comms_ms"] >= 0
    assert perf["chip"] == "cpu-host"
    # the record landed in the ledger and in the run dir
    recs = perfscope.read_ledger(led)
    assert recs and recs[-1]["strategy"] == "bench-dp"
    assert os.path.exists(os.path.join(obs_dir, perfscope.PERF_BASENAME))
