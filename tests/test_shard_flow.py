"""graft-shard: the sharding-flow verifier + the partition-rule engine.

Four contracts pinned here:

1. **The rules fire** — synthetic positives and near-miss negatives for
   H011 (implicit reshard), H012 (rule-coverage defect), and H013
   (cross-program layout mismatch), like every rule before them.
2. **Strategy-as-data is exact** — the ``dp-rules`` / ``zero3-rules``
   registry strategies lower to optimized HLO **bitwise identical** to
   their bespoke builders, with their tables proven covered (every
   param leaf matched exactly once, every rule reachable).
3. **The layout contracts hold on the real programs** — ZeRO-family
   entry-parameter shardings match ``ft/reshard``'s ``[n, k]`` /
   ``[L, n, k]`` checkpoint contract, and the serve prefill/decode
   programs agree on the paged-KV pool split.
4. **The flow walk attributes collectives** — zero3's gathers trace
   back to the ``dim0/n``-sharded param shards that feed them.

Every registered-strategy fact rides the shared lower-once compile
cache (``tests/conftest.py``, now ``keep_hlo=True``) — this module
pays for ZERO extra strategy compiles.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from conftest import cached_strategy_report as _report  # lower-once cache
from ddl25spring_tpu.analysis import engine, shard_flow
from ddl25spring_tpu.obs import xla_analytics as xa
from ddl25spring_tpu.parallel import rules as prules
from ddl25spring_tpu.utils.mesh import make_mesh


def _lint(hlo, **kw):
    kw.setdefault("obs_enabled", False)
    kw.setdefault("waivers", [])
    return engine.lint_hlo_text(hlo, **kw)


def _rules_fired(findings):
    return {f.rule for f in findings}


# ------------------------------------------------- sharding-attr parsing


def test_parse_sharding_forms():
    ps = xa.parse_sharding
    assert ps(None) is None
    assert ps("replicated")["replicated"] is True
    assert ps("maximal device=0")["maximal"] is True

    d = ps("devices=[4,1]<=[4]")
    assert d["partitioned_dims"] == [0] and d["partitions"] == {0: 4}
    assert not d["replicated"]

    d = ps("devices=[1,4]<=[4]")
    assert d["partitioned_dims"] == [1] and d["partitions"] == {1: 4}

    # stacked [L, n, k]: the layer dim replicated, rows on dim 1
    d = ps("devices=[1,4,1]<=[4]")
    assert d["partitioned_dims"] == [1]

    # a trailing replicated tile dim is a subgroup, not a data split
    d = ps("devices=[2,1,2]<=[4] last_tile_dim_replicate")
    assert d["partitioned_dims"] == [0] and d["trailing_subgroups"] == 1
    d = ps("devices=[2,1,2]<=[4] last_tile_dims={replicated}")
    assert d["partitioned_dims"] == [0] and d["trailing_subgroups"] == 1


def test_sharding_attr_of_line_balances_braces():
    line = ('%p = f32[4]{0} parameter(0), sharding={devices=[2,2]<=[4] '
            'last_tile_dims={manual}}, metadata={op_name="x"}')
    attr = xa._sharding_attr_of_line(line)
    assert attr == "devices=[2,2]<=[4] last_tile_dims={manual}"
    assert xa._sharding_attr_of_line("%p = f32[4]{0} parameter(0)") is None


def test_sharding_summary_tokens():
    assert shard_flow.sharding_summary(None) == "-"
    assert shard_flow.sharding_summary({"replicated": True}) == "replicated"
    assert shard_flow.sharding_summary(
        {"partitioned_dims": [0], "partitions": {0: 4}}
    ) == "dim0/4"


def test_h013_proof_survives_a_json_roundtrip():
    """Stored reports are the re-run substrate (compile_report.json):
    JSON coerces the partitions dict's int keys to strings, and the
    walk must still judge them — no spurious 'matching no mesh axis'
    error, no KeyError in the summary."""
    report = {
        "strategy": "zero3", "meta": {"zero_stage": 3},
        "mesh": {"data": 4}, "donation": {"donatable_leaves": 1},
        "entry_params": [{
            "number": 0, "name": "p0", "bytes": 2048,
            "type": "f32[1,128]{1,0}", "arg": "param_shards['w1']",
            "sharding": {"replicated": False, "maximal": False,
                         "manual": False, "tile": [4, 1],
                         "trailing_subgroups": 0,
                         "partitioned_dims": [0], "partitions": {0: 4}},
        }],
    }
    rt = json.loads(json.dumps(report))
    assert rt["entry_params"][0]["sharding"]["partitions"] == {"0": 4}
    assert shard_flow.saved_layout_findings(rt) == []
    assert shard_flow.sharding_summary(
        rt["entry_params"][0]["sharding"]
    ) == "dim0/4"
    # a real violation still fires on the round-tripped shape
    rt["entry_params"][0]["sharding"]["partitions"] = {"0": 2}
    fs = shard_flow.saved_layout_findings(rt)
    assert [f.rule for f in fs] == ["H013"]


# -------------------------------------------------- partition-rule engine


def test_match_partition_rules_first_match_wins_and_raises_unmatched():
    tree = {"w1": jnp.zeros((2, 2)), "b1": jnp.zeros((2,))}
    atoms = prules.match_partition_rules(prules.TABLES["zero3"], tree)
    assert atoms == {"w1": "rows", "b1": "rows"}
    # first match wins: a catch-all AFTER a specific rule never fires
    atoms = prules.match_partition_rules(
        [("^w1$", "rows"), (".*", "replicated")], tree
    )
    assert atoms == {"w1": "rows", "b1": "replicated"}
    with pytest.raises(ValueError, match="no partition rule matches"):
        prules.match_partition_rules([("^w", "rows")], tree)


def test_partition_rule_validates_atom_and_regex():
    with pytest.raises(ValueError, match="unknown layout"):
        prules.PartitionRule("^w", "diagonal")
    import re as _re

    with pytest.raises(_re.error):
        prules.PartitionRule("[", "rows")
    # a typo'd discipline must fail at table construction, not fall
    # through discipline_of()'s legacy flags into wrong sched verdicts
    with pytest.raises(ValueError, match="discipline"):
        prules.RuleTable(
            name="t", axes=("data",),
            rules=(prules.PartitionRule(".*", "rows"),),
            discipline="overlpa",
        )


def test_rule_coverage_matrix():
    cov = prules.rule_coverage(
        [("^w", "rows"), ("^w1$", "rows"), ("^b", "rows")],
        ["w1", "w2", "b1"],
    )
    by_path = {r["path"]: r for r in cov["leaves"]}
    assert by_path["w1"]["matches"] == [0, 1]  # ambiguous
    assert by_path["w2"]["matches"] == [0]
    assert cov["rules"][1]["first_matches"] == 0  # shadowed by rule 0
    assert cov["rules"][1]["matches"] == 1
    assert cov["rules"][2]["first_matches"] == 1


def test_leaf_paths_join_nested_names():
    tree = {"blocks": {"wq": jnp.zeros(2)}, "w1": jnp.zeros(2)}
    assert set(prules.leaf_paths(tree)) == {"blocks/wq", "w1"}


def test_rule_table_meta_roundtrips_through_json():
    meta = prules.TABLES["zero3"].to_meta()
    again = json.loads(json.dumps(meta))
    assert again == meta
    assert shard_flow.coverage_defects(again, ["w1", "b1", "w2"]) == []


@pytest.fixture(scope="module")
def mesh4(devices8):
    return make_mesh(devices8[:4], data=4)


def test_rule_partitioner_rejects_mixed_and_layers_tables(mesh4):
    mixed = prules.RuleTable(
        name="mixed", axes=("data",),
        rules=(
            prules.PartitionRule("^w", "rows"),
            prules.PartitionRule("^b", "replicated"),
        ),
    )
    tree = {"w1": jnp.zeros((2, 2)), "b1": jnp.zeros((2,))}
    with pytest.raises(NotImplementedError, match="mixes layouts"):
        prules.RulePartitioner(mesh4, mixed).layout_of(tree)
    layered = prules.RuleTable(
        name="layered", axes=("data",),
        rules=(prules.PartitionRule(".*", "layers"),),
    )
    with pytest.raises(NotImplementedError, match="layers"):
        prules.RulePartitioner(mesh4, layered).layout_of(tree)
    wrong_axis = prules.RuleTable(
        name="w", axes=("model",),
        rules=(prules.PartitionRule(".*", "rows"),),
    )
    with pytest.raises(ValueError, match="mesh axes"):
        prules.RulePartitioner(mesh4, wrong_axis)


def test_rule_partitioner_shard_params_matches_zero_layout(mesh4):
    from ddl25spring_tpu.parallel.zero import zero_shard_params

    params = {"w1": jnp.arange(12.0).reshape(3, 4), "b1": jnp.ones((3,))}
    part = prules.RulePartitioner(mesh4, prules.TABLES["zero3"])
    a = part.shard_params(params)
    b = zero_shard_params(params, mesh4, "data")
    assert jax.tree.all(jax.tree.map(
        lambda x, y: bool((x == y).all()) and x.sharding == y.sharding,
        a, b,
    ))
    # the replicated table passes params through untouched
    part_dp = prules.RulePartitioner(mesh4, prules.TABLES["dp"])
    assert part_dp.shard_params(params) is params


def test_discipline_rides_the_table_as_data():
    from ddl25spring_tpu.analysis import sched

    assert sched.discipline_of({"discipline": "sync"}) == "sync"
    assert sched.discipline_of({"discipline": "overlap"}) == "overlap"
    # the legacy flags still decide when no table discipline is present
    assert sched.discipline_of({"overlap": True}) == "overlap"
    assert sched.discipline_of({}) == "sync"


# --------------------------------------------------------- H011 synthetic

_H011_UNDECLARED_GATHER = """\
HloModule h011
ENTRY %main (x: f32[128]) -> f32[512] {
  %x = f32[128]{0} parameter(0)
  ROOT %ag = f32[512]{0} all-gather(f32[128]{0} %x), replica_groups={{0,1,2,3}}, dimensions={0}
}
"""


def test_h011_undeclared_collective_fires_and_declared_is_quiet():
    report = {"expected": {"scalar_bytes": 64, "all-reduce": {"count": 1}}}
    fs = _lint(_H011_UNDECLARED_GATHER, report=report)
    f = next(f for f in fs if f.rule == "H011")
    assert f.severity == "error"
    assert "never declared" in f.message
    assert f.bytes == 512 * 4
    # declaring the kind (with any bounds) clears it
    report2 = {"expected": {"scalar_bytes": 64,
                            "all-gather": {"max_bytes": 4096}}}
    assert "H011" not in _rules_fired(
        _lint(_H011_UNDECLARED_GATHER, report=report2)
    )
    # FORBIDDING it also clears H011 — the violation is then the
    # signature gate's department, not an undeclared-traffic claim
    report3 = {"expected": {"scalar_bytes": 64,
                            "forbidden": ["all-gather"]}}
    assert "H011" not in _rules_fired(
        _lint(_H011_UNDECLARED_GATHER, report=report3)
    )
    # no declared signature at all: no claim to hold the HLO to
    assert "H011" not in _rules_fired(_lint(_H011_UNDECLARED_GATHER))


def test_h011_scalar_bookkeeping_is_exempt():
    small = _H011_UNDECLARED_GATHER.replace("512", "8").replace("128", "2")
    report = {"expected": {"scalar_bytes": 64, "all-reduce": {"count": 1}}}
    assert "H011" not in _rules_fired(_lint(small, report=report))


# --------------------------------------------------------- H012 synthetic

_NO_COLLECTIVES = """\
HloModule h012
ENTRY %main (x: f32[4]) -> f32[4] {
  ROOT %x = f32[4]{0} parameter(0)
}
"""


def _h012(table_rules, paths):
    report = {"meta": {
        "rule_table": {"name": "t", "rules": table_rules},
        "param_paths": paths,
    }}
    return _lint(_NO_COLLECTIVES, report=report)


def test_h012_unmatched_leaf_is_an_error():
    fs = _h012([["^w", "rows"]], ["w1", "b1"])
    f = next(f for f in fs if f.rule == "H012")
    assert f.severity == "error" and "unmatched" in f.message
    assert f.op == "b1"


def test_h012_shadowed_rule_can_never_fire():
    # rule #1 matches only w1, which rule #0 already takes: shadowed
    fs = _h012([["^w", "rows"], ["^w1$", "rows"]], ["w1", "w2"])
    kinds = {f.message.split("[")[1].split("]")[0]
             for f in fs if f.rule == "H012"}
    assert "shadowed" in kinds
    assert "ambiguous" in kinds  # w1 matched twice: order load-bearing
    assert all(
        f.severity == "warn" for f in fs if f.rule == "H012"
    )


def test_h012_bad_table_is_loud_not_a_crash():
    fs = _h012([["[", "rows"]], ["w1"])
    f = next(f for f in fs if f.rule == "H012")
    assert f.severity == "error" and "bad-table" in f.message
    fs = _h012([["^w", "diagonal"]], ["w1"])
    assert any("bad-table" in f.message for f in fs if f.rule == "H012")


def test_h012_clean_table_and_non_table_strategies_are_quiet():
    fs = _h012([["^w", "rows"], ["^b", "rows"]], ["w1", "b1", "w2"])
    assert "H012" not in _rules_fired(fs)
    assert "H012" not in _rules_fired(_lint(_NO_COLLECTIVES))


# --------------------------------------------------------- H013 synthetic

_H013_TRANSPOSED = """\
HloModule h013
ENTRY %main (p0: f32[128,4]) -> f32[128,4] {
  ROOT %p0 = f32[128,4]{1,0} parameter(0), sharding={devices=[1,4]<=[4]}, metadata={op_name="param_shards['w']"}
}
"""


def test_h013_transposed_save_layout_fires_through_the_engine():
    """The satellite case: a [k, n] save layout — rows on dim 1 instead
    of ft/reshard's dim-0 contract — caught from the compiled program's
    own entry-parameter sharding."""
    report = {"meta": {"zero_stage": 3}, "mesh": {"data": 4},
              "donation": {"donatable_leaves": 1}}
    fs = _lint(_H013_TRANSPOSED, report=report)
    f = next(f for f in fs if f.rule == "H013")
    assert f.severity == "error"
    assert "param_shards['w']" in (f.op or "")
    assert "dim" in f.message and "reshard" in f.message
    # the near-miss: the contract layout [n, k] (rows on dim 0) passes
    ok = _H013_TRANSPOSED.replace("devices=[1,4]", "devices=[4,1]")
    assert "H013" not in _rules_fired(_lint(ok, report=report))
    # replicated leaves (zero1/2 params) make no sharded-save claim
    rep = _H013_TRANSPOSED.replace(
        "sharding={devices=[1,4]<=[4]}", "sharding={replicated}"
    )
    assert "H013" not in _rules_fired(_lint(rep, report=report))
    # a non-ZeRO-family strategy makes no claim at all
    assert "H013" not in _rules_fired(
        _lint(_H013_TRANSPOSED, report={"meta": {}, "mesh": {"data": 4}})
    )


def test_h013_row_count_must_match_a_mesh_axis():
    # [n, k] on dim 0 but split 2 ways on a 4-way mesh: the row refit
    # cannot be exact
    hlo = _H013_TRANSPOSED.replace("devices=[1,4]", "devices=[2,1]")
    report = {"meta": {"zero_stage": 3}, "mesh": {"data": 4},
              "donation": {"donatable_leaves": 1}}
    fs = _lint(hlo, report=report)
    f = next(f for f in fs if f.rule == "H013")
    assert "matching no mesh axis" in f.message


def test_h013_serve_pair_mismatch_and_declared_dim():
    mk = lambda dims, parts: {  # noqa: E731 — tiny local factory
        "meta": {"program": "decode", "kv_sharded_dim": 3, "tp": 2},
        "entry_params": [{
            "number": 0, "name": "p0", "bytes": 4096,
            "type": "f32[17,2,4,2,8]",
            "arg": "pool['k']",
            "sharding": {"partitioned_dims": dims,
                         "partitions": parts},
        }],
    }
    good = mk([3], {3: 2})
    bad_dim = mk([0], {0: 2})
    # declared-dim half: pages split off the head dim flag immediately
    fs = shard_flow.serve_pair_findings({"serve-x": bad_dim})
    assert [f.rule for f in fs] == ["H013"]
    assert "head dim" in fs[0].message
    # a pool that silently fell back to REPLICATED under tp>1 is as
    # much a contract break as a wrong dim (exact match, not subset)
    fs = shard_flow.serve_pair_findings({"serve-x": mk([], {})})
    assert [f.rule for f in fs] == ["H013"]
    # at tp=1 a replicated pool is the legitimate compile
    solo = mk([], {})
    solo["meta"]["tp"] = 1
    assert shard_flow.serve_pair_findings({"serve-x": solo}) == []
    # pair half: two programs disagreeing on the same pool buffer
    fs = shard_flow.serve_pair_findings(
        {"serve-a": good, "serve-b": bad_dim}
    )
    pair = [f for f in fs if "cross-program layout mismatch" in f.message]
    assert pair
    # the finding carries a REAL strategy name (waiver globs must
    # match it), with both pair members named in the message
    assert pair[0].strategy == "serve-a"
    assert "serve-b" in pair[0].message
    # agreement is quiet
    assert shard_flow.serve_pair_findings(
        {"serve-a": good, "serve-b": mk([3], {3: 2})}
    ) == []


# ----------------------------------------- pinned real-strategy contracts


@pytest.mark.parametrize("bespoke,ruled", [
    ("dp", "dp-rules"), ("zero3", "zero3-rules"),
])
def test_rule_table_strategy_is_bitwise_identical_to_bespoke(
    bespoke, ruled
):
    """The tentpole acceptance pin: the strategy-as-data variants lower
    to byte-for-byte the SAME optimized HLO as the builders they will
    eventually replace — the rule engine changes where the strategy is
    written down, not what XLA compiles."""
    a, b = _report(bespoke), _report(ruled)
    assert a["hlo_text"] == b["hlo_text"]
    assert a["signature_violations"] == [] == b["signature_violations"]


@pytest.mark.parametrize("name", ["dp-rules", "zero3-rules"])
def test_rule_table_coverage_proof_holds(name):
    """Every param leaf matched exactly once, every rule fires — the
    H012 proof, re-derived from the serialized meta exactly as the lint
    pass does (no import of the strategy module)."""
    meta = _report(name)["meta"]
    table, paths = meta["rule_table"], meta["param_paths"]
    assert shard_flow.coverage_defects(table, paths) == []
    cov = prules.rule_coverage(
        [tuple(r) for r in table["rules"]], paths
    )
    assert all(len(leaf["matches"]) == 1 for leaf in cov["leaves"])
    assert all(r["first_matches"] >= 1 for r in cov["rules"])
    assert meta["discipline"] == "sync"


def test_zero_family_entry_layouts_satisfy_the_reshard_contract():
    """The per-program H013 walk on the real compiled programs: every
    saved sharded leaf sits on the checkpoint contract's dim (rows on
    dim 0; the stacked LLaMA blocks on dim 1), with the row count equal
    to the shard axis."""
    for name in ("zero3", "zero3-rules"):
        r = _report(name)
        shards = [
            p for p in r["entry_params"]
            if p["number"] < r["donation"]["donatable_leaves"]
            and (p.get("sharding") or {}).get("partitioned_dims")
        ]
        assert shards, f"{name}: no sharded saved leaves?"
        for p in shards:
            assert p["sharding"]["partitioned_dims"] == [0], p
            assert p["sharding"]["partitions"][0] == 4, p
    r = _report("zero3-prefetch")
    stacked = [
        p for p in r["entry_params"]
        if shard_flow._type_rank(p["type"]) == 3
        and (p.get("sharding") or {}).get("partitioned_dims")
    ]
    assert stacked, "prefetch step lost its [L, n, k] stacked leaves?"
    for p in stacked:
        assert p["sharding"]["partitioned_dims"] == [1], p
    assert shard_flow.saved_layout_findings(r) == []


def test_serve_programs_agree_on_the_kv_pool_split():
    """The cross-program half on the real serve programs: prefill,
    decode, the cached-prefill variant, AND the PR-18 trio (per-chip
    budget entries + the ZeRO-3 streaming decode) shard every pool
    buffer identically, k/v on the engine's declared head dim."""
    reports = {
        n: _report(n)
        for n in (
            "serve-decode", "serve-prefill", "serve-prefill-cached",
            "serve-decode-tp", "serve-prefill-tp",
            "serve-decode-zero3stream",
        )
    }
    assert shard_flow.check_layout_contracts(reports, waivers=[]) == []
    for n, r in reports.items():
        pool = shard_flow._pool_params(r)
        assert set(pool) >= {"pool['k']", "pool['v']"}, (n, sorted(pool))
        for arg in ("pool['k']", "pool['v']"):
            sh = pool[arg]["sharding"]
            assert sh["partitioned_dims"] == [
                r["meta"]["kv_sharded_dim"]
            ], (n, arg, sh)


def test_flow_walk_attributes_zero3_gathers_to_sharded_params():
    """The per-tensor propagation walk on the real program: each
    forward all-gather's sources are exactly dim0/4-sharded
    param_shards leaves (the batch never feeds a gather)."""
    r = _report("zero3")
    flows = shard_flow.collective_flows(r["hlo_text"], report=r)
    gathers = [f for f in flows if f["kind"] == "all-gather"]
    assert gathers
    for g in gathers:
        assert g["sources"], g
        assert g["truncated"] is False, g  # complete walk on this program
        for s in g["sources"]:
            assert "param_shards" in s["arg"], g
            assert s["sharding"] == "dim0/4", g
    # the backward's scatters depend on the whole loss: batch included
    scatters = [f for f in flows if f["kind"] == "reduce-scatter"]
    assert scatters
    assert any(
        any("batch" in s["arg"] for s in f["sources"]) for f in scatters
    )


def test_flow_report_counts_rules_and_strips_nothing_it_needs():
    reports = {"zero3": _report("zero3"), "dp": _report("dp")}
    doc = shard_flow.flow_report(reports, waivers=[])
    assert set(doc) == {"strategies", "findings", "by_rule"}
    assert doc["findings"] == []
    entry = doc["strategies"]["zero3"]["entry_params"]
    assert any(p["sharding"] == "dim0/4" for p in entry)
    # dict is JSON-serializable (the CI artifact contract)
    json.dumps(doc)


def test_h011_dogfood_declarations_survive():
    """The two real finds from H011's first run stay declared: tp's
    partitioner-inserted loss-assembly resharding and sp's replicated-
    params grad sync are signature facts now — removing them would
    resurrect the undeclared traffic this rule exists to catch."""
    tp = _report("tp")["expected"]
    for kind in ("all-gather", "reduce-scatter", "all-to-all"):
        assert kind in tp, kind
    sp = _report("sp")["expected"]
    assert "all-reduce" in sp
    assert sp["all-reduce"]["min_bytes"] > 0


def test_graft_lint_shard_flow_renderer():
    from tools.graft_lint import _fmt_shard_flow

    lines = _fmt_shard_flow({
        "entry_params": [
            {"arg": "param_shards['w1']", "bytes": 512,
             "sharding": "dim0/4"},
            {"arg": "batch[0]", "bytes": 128, "sharding": "replicated"},
        ],
        "flows": [
            {"op": "ag.1", "kind": "all-gather",
             "sources": [{"arg": "param_shards['w1']",
                          "sharding": "dim0/4"}],
             "internal": False},
            {"op": "ar.2", "kind": "all-reduce", "sources": [],
             "internal": True},
            {"op": "ag.3", "kind": "all-gather",
             "sources": [{"arg": "params['a']", "sharding": "dim0/4"}],
             "internal": False, "truncated": True},
        ],
    })
    text = "\n".join(lines)
    assert "1 sharded" in text
    assert "param_shards['w1'][dim0/4]" in text
    assert "<loop-internal>" in text
    # a budget-truncated walk must say so, not present as complete
    assert "walk truncated" in text


@pytest.mark.slow
def test_graft_lint_cli_shard_flow_check_is_green(capsys):
    """End-to-end: the CI gate's exact invocation shape over the two
    rule-table strategies (slow: pays its own compiles)."""
    from tools import graft_lint

    rc = graft_lint.main([
        "--strategy", "dp-rules,zero3-rules", "--shard-flow", "--check",
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "graft-lint OK" in err
