"""Flat-bucket collectives + universal buffer donation: the equality and
memory contracts behind the bucketed/donated defaults.

Three pin families:

- **plan/pack units**: dtype-homogeneous greedy packing under the byte
  threshold, order preservation, pack/unpack round-trip;
- **path equality**: the bucketed DP/ZeRO-1/2/3 steps land exactly where
  the per-leaf paths land — DP *bitwise* (psum is elementwise, packing
  commutes with it), ZeRO within the suite's grad tolerance — and the
  scanned-LLaMA gather-prefetch ZeRO-3 step trains identically to
  replicated DP;
- **donation**: a donated step's compile-time peak HBM sits strictly
  below the undonated build of the same program (the aliased
  params+opt-state bytes), on the fake CPU mesh via ``memory_analysis``.

Collective-count shapes (O(n_buckets) vs O(n_leaves), the prefetch
while-loop) are pinned next to the other signatures in
``tests/test_xla_analytics.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.parallel import bucketing
from ddl25spring_tpu.parallel.dp import _tiny_mlp_workload, make_dp_train_step
from ddl25spring_tpu.parallel.zero import (
    _llama_workload,
    make_zero3_llama_train_step,
    make_zero_dp_train_step,
    make_zero_partitioned_train_step,
    zero_shard_llama_params,
    zero_shard_params,
    zero_unshard_llama_params,
    zero_unshard_params,
)
from ddl25spring_tpu.utils.compat import compiled_memory_stats
from ddl25spring_tpu.utils.mesh import make_mesh

# ------------------------------------------------------------- plan units


def test_plan_groups_by_dtype_and_threshold():
    tree = {
        "a": jnp.zeros((256,), jnp.float32),   # 1 KiB
        "b": jnp.zeros((256,), jnp.float32),   # 1 KiB
        "c": jnp.zeros((256,), jnp.int32),     # different dtype
        "d": jnp.zeros((512,), jnp.float32),   # 2 KiB - overflows 2 KiB cap
    }
    plan = bucketing.plan_buckets(tree, bucket_bytes=2 * 1024)
    # a+b fill the first f32 bucket exactly; d overflows into its own;
    # c buckets alone (dtype-homogeneous)
    assert plan.n_buckets == 3
    kinds = {
        tuple(sorted(plan.buckets[b])): str(plan.bucket_dtype(b))
        for b in range(plan.n_buckets)
    }
    leaves = sorted(tree)  # flatten order: a, b, c, d
    assert kinds[(leaves.index("a"), leaves.index("b"))] == "float32"
    assert kinds[(leaves.index("c"),)] == "int32"
    assert kinds[(leaves.index("d"),)] == "float32"


def test_plan_single_bucket_under_threshold_and_oversize_leaf():
    small = {"a": jnp.zeros((4, 4)), "b": jnp.zeros((8,))}
    assert bucketing.plan_buckets(small).n_buckets == 1
    big = {"x": jnp.zeros((64,)), "y": jnp.zeros((2048,))}  # y alone > cap
    plan = bucketing.plan_buckets(big, bucket_bytes=1024)
    assert plan.n_buckets == 2  # an oversize leaf still lands somewhere


def test_plan_backward_order_groups_by_readiness():
    """order="backward" walks the leaves in reversed flatten order —
    bucket 0 holds the LAST leaves (the first cotangents the backward
    produces) — and still round-trips pack/unpack exactly."""
    tree = {f"l{i}": jnp.zeros((256,), jnp.float32) for i in range(4)}
    fwd = bucketing.plan_buckets(tree, bucket_bytes=2 * 1024)
    bwd = bucketing.plan_buckets(tree, bucket_bytes=2 * 1024,
                                 order="backward")
    assert fwd.buckets == ((0, 1), (2, 3))
    assert bwd.buckets == ((3, 2), (1, 0))
    vals = {f"l{i}": jnp.arange(256.0) + i for i in range(4)}
    back = bwd.unpack(bwd.pack(vals))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        vals, back,
    )
    with pytest.raises(ValueError, match="order"):
        bucketing.plan_buckets(tree, order="sideways")


def test_bucket_bytes_env_knob(monkeypatch):
    """DDL25_BUCKET_BYTES resolves through the sanctioned env boundary:
    AUTO -> the knob (0 = per-leaf), explicit values pass through, and
    None keeps meaning per-leaf as it has since PR 3."""
    monkeypatch.delenv("DDL25_BUCKET_BYTES", raising=False)
    assert bucketing.resolve_bucket_bytes(bucketing.AUTO) == (
        bucketing.DEFAULT_BUCKET_BYTES
    )
    monkeypatch.setenv("DDL25_BUCKET_BYTES", str(1 << 20))
    assert bucketing.resolve_bucket_bytes(bucketing.AUTO) == 1 << 20
    monkeypatch.setenv("DDL25_BUCKET_BYTES", "0")
    assert bucketing.resolve_bucket_bytes(bucketing.AUTO) is None
    assert bucketing.resolve_bucket_bytes(None) is None
    assert bucketing.resolve_bucket_bytes(0) is None
    assert bucketing.resolve_bucket_bytes(2048) == 2048
    monkeypatch.setenv("DDL25_BUCKET_BYTES", "not-bytes")
    with pytest.raises(ValueError):
        bucketing.resolve_bucket_bytes(bucketing.AUTO)


def test_pack_unpack_roundtrip_mixed_dtypes():
    key = jax.random.PRNGKey(0)
    tree = {
        "w": jax.random.normal(key, (3, 5)),
        "b": jnp.arange(7, dtype=jnp.int32),
        "s": jnp.float32(3.5).reshape(()),
    }
    plan = bucketing.plan_buckets(tree, bucket_bytes=64)
    back = plan.unpack(plan.pack(tree))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        tree, back,
    )
    assert back["b"].dtype == jnp.int32
    assert back["s"].shape == ()


def test_bucketed_pmean_matches_per_leaf_bitwise(devices8):
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from ddl25spring_tpu.utils.compat import shard_map

    mesh = make_mesh(devices8[:4], data=4)
    tree = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (4, 33, 7)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (4, 11)),
    }

    @partial(shard_map, mesh=mesh, in_specs=(P("data"),), out_specs=P())
    def both(t):
        local = jax.tree.map(lambda x: x[0], t)
        per_leaf = jax.tree.map(
            lambda x: jax.lax.pmean(x, "data"), local
        )
        bucketed = bucketing.bucketed_pmean(local, "data")
        return per_leaf, bucketed

    per_leaf, bucketed = jax.jit(both)(tree)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        per_leaf, bucketed,
    )


# ---------------------------------------------------------- path equality


@pytest.fixture(scope="module")
def mlp4(devices8):
    n = 4
    mesh = make_mesh(devices8[:n], data=n)
    params, loss_fn, batch, _ = _tiny_mlp_workload(n)
    key0 = jax.random.PRNGKey(7)
    params = jax.tree.map(
        lambda x: 0.1 * jax.random.normal(key0, x.shape, x.dtype), params
    )
    batch = (
        jax.random.normal(jax.random.PRNGKey(8), batch[0].shape),
        jax.random.normal(jax.random.PRNGKey(9), batch[1].shape),
    )
    return mesh, params, loss_fn, batch


def test_dp_bucketed_equals_per_leaf_bitwise(mlp4):
    """The acceptance pin: DP's bucketed gradient path is BITWISE equal
    to the per-leaf path — packing commutes with the elementwise psum."""
    mesh, params, loss_fn, batch = mlp4
    tx = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)
    per_leaf = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False, bucket_bytes=None
    )
    bucketed = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False
    )
    p1, o1, l1 = params, tx.init(params), None
    p2, o2 = params, tx.init(params)
    for _ in range(3):
        p1, o1, l1 = per_leaf(p1, o1, batch, key)
        p2, o2, l2 = bucketed(p2, o2, batch, key)
        assert float(l1) == float(l2)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        jax.device_get(p1), jax.device_get(p2),
    )


def test_zero3_bucketed_equals_per_leaf(mlp4):
    mesh, params, loss_fn, batch = mlp4
    tx = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)
    per_leaf = make_zero_dp_train_step(
        loss_fn, tx, mesh, params, per_shard_rng=False, bucket_bytes=None
    )
    bucketed = make_zero_dp_train_step(
        loss_fn, tx, mesh, params, per_shard_rng=False
    )
    s1, s2 = zero_shard_params(params, mesh), zero_shard_params(params, mesh)
    o1, o2 = tx.init(s1), tx.init(s2)
    for _ in range(3):
        s1, o1, l1 = per_leaf(s1, o1, batch, key)
        s2, o2, l2 = bucketed(s2, o2, batch, key)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=2e-6
        ),
        zero_unshard_params(jax.device_get(s1), params),
        zero_unshard_params(jax.device_get(s2), params),
    )


@pytest.mark.parametrize("stage", [1, 2])
def test_zero_stage12_bucketed_equals_per_leaf(stage, mlp4):
    mesh, params, loss_fn, batch = mlp4
    tx = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)
    per_leaf = make_zero_partitioned_train_step(
        loss_fn, tx, mesh, params, stage=stage, per_shard_rng=False,
        bucket_bytes=None,
    )
    bucketed = make_zero_partitioned_train_step(
        loss_fn, tx, mesh, params, stage=stage, per_shard_rng=False
    )
    p1 = p2 = params
    o1 = tx.init(zero_shard_params(params, mesh))
    o2 = tx.init(zero_shard_params(params, mesh))
    for _ in range(3):
        p1, o1, l1 = per_leaf(p1, o1, batch, key)
        p2, o2, l2 = bucketed(p2, o2, batch, key)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=2e-6
        ),
        jax.device_get(p1), jax.device_get(p2),
    )


@pytest.mark.parametrize("prefetch", [True, False])
def test_zero3_llama_prefetch_equals_plain_dp(prefetch, devices8):
    """The scanned-LLaMA gather-prefetch ZeRO-3 step (double-buffered
    carry, layer i+1's all-gather issued before layer i's compute — and
    the prefetch=False remat variant that re-gathers in the backward)
    trains identically to replicated DP + the same Adam chain."""
    n = 4
    mesh = make_mesh(devices8[:n], data=n)
    cfg, params, loss_fn, tokens, _ = _llama_workload(n)
    tokens = jnp.asarray(
        jax.random.randint(jax.random.PRNGKey(1), tokens.shape, 0,
                           cfg.vocab_size)
    )
    tx = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)

    dp = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
    zp = make_zero3_llama_train_step(
        cfg, tx, mesh, prefetch=prefetch, per_shard_rng=False
    )

    p_ref, o_ref = params, tx.init(params)
    shards = zero_shard_llama_params(params, mesh)
    o_z = tx.init(shards)
    for _ in range(3):
        p_ref, o_ref, l_ref = dp(p_ref, o_ref, tokens, key)
        shards, o_z, l_z = zp(shards, o_z, tokens, key)
        np.testing.assert_allclose(float(l_ref), float(l_z), rtol=1e-5)
    restored = zero_unshard_llama_params(jax.device_get(shards), params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        ),
        jax.device_get(p_ref), restored,
    )


def test_zero3_llama_prefetch_holds_sharded_state(devices8):
    """The point of the layout: block params and Adam moments live in the
    per-layer [L, n, k] layout with 1/n per device."""
    n = 4
    mesh = make_mesh(devices8[:n], data=n)
    cfg, params, _, tokens, _ = _llama_workload(n)
    tx = optax.adam(1e-2)
    zp = make_zero3_llama_train_step(
        cfg, tx, mesh, per_shard_rng=False
    )
    shards = zero_shard_llama_params(params, mesh)
    o_z = tx.init(shards)
    shards, o_z, _ = zp(shards, o_z, tokens, jax.random.PRNGKey(0))
    wq = shards["blocks"]["wq"]
    assert wq.shape[:2] == (cfg.n_layers, n)
    local = [s for s in wq.addressable_shards if s.device == devices8[0]]
    assert sum(s.data.shape[1] for s in local) == 1  # one row of each layer
    mu = o_z[0].mu["blocks"]["wq"]
    assert mu.shape == wq.shape


# ------------------------------------------------------- overlapped backward


def test_dp_overlap_equals_per_leaf_bitwise(mlp4):
    """The PR-8 acceptance pin: the backward-overlapped DP step — each
    bucket's all-reduce emitted by its custom_vjp bwd rule, buckets in
    backward-readiness order — lands BITWISE where per-leaf sync DP
    lands (psum is elementwise; packing and issue order commute with
    it)."""
    mesh, params, loss_fn, batch = mlp4
    tx = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)
    per_leaf = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False, bucket_bytes=None
    )
    overlapped = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False, overlap=True
    )
    p1, o1 = params, tx.init(params)
    p2, o2 = params, tx.init(params)
    for _ in range(3):
        p1, o1, l1 = per_leaf(p1, o1, batch, key)
        p2, o2, l2 = overlapped(p2, o2, batch, key)
        assert float(l1) == float(l2)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        jax.device_get(p1), jax.device_get(p2),
    )


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_overlap_equals_sync(stage, mlp4):
    """Every ZeRO overlap variant — stage 1's bwd-issued all-reduce,
    stage 2's bwd-issued reduce-scatter (re-seated at row i of the
    padded layout), stage 3's backward-ordered gather plan — trains
    within the suite grad tolerance of its sync twin."""
    mesh, params, loss_fn, batch = mlp4
    tx = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)
    if stage == 3:
        mk = lambda ov: make_zero_dp_train_step(  # noqa: E731
            loss_fn, tx, mesh, params, per_shard_rng=False, overlap=ov
        )
        s1, s2 = (
            zero_shard_params(params, mesh), zero_shard_params(params, mesh)
        )
        a1, a2 = s1, s2
    else:
        mk = lambda ov: make_zero_partitioned_train_step(  # noqa: E731
            loss_fn, tx, mesh, params, stage=stage, per_shard_rng=False,
            overlap=ov,
        )
        a1 = a2 = params
    o1 = tx.init(zero_shard_params(params, mesh))
    o2 = tx.init(zero_shard_params(params, mesh))
    sync, overlapped = mk(False), mk(True)
    for _ in range(3):
        a1, o1, l1 = sync(a1, o1, batch, key)
        a2, o2, l2 = overlapped(a2, o2, batch, key)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    unshard = (
        (lambda t: zero_unshard_params(jax.device_get(t), params))
        if stage == 3 else jax.device_get
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=2e-6
        ),
        unshard(a1), unshard(a2),
    )


def test_weight_avg_bucketed_sync_equals_per_leaf(mlp4):
    """The third DP variant: weight-aggregation DP's params-pmean rides
    the flat-bucket path now (it had stayed per-leaf through PR 3) —
    bitwise-equal, same oracle as the gradient path."""
    from ddl25spring_tpu.parallel.dp import (
        make_dp_weight_avg_step,
        stack_opt_state,
    )

    mesh, params, loss_fn, batch = mlp4
    tx = optax.sgd(0.1)
    key = jax.random.PRNGKey(0)
    per_leaf = make_dp_weight_avg_step(
        loss_fn, tx, mesh, per_shard_rng=False, bucket_bytes=None
    )
    bucketed = make_dp_weight_avg_step(
        loss_fn, tx, mesh, per_shard_rng=False
    )
    o1 = stack_opt_state(tx.init(params), 4)
    o2 = stack_opt_state(tx.init(params), 4)
    p1, p2 = params, params
    for _ in range(2):
        p1, o1, l1 = per_leaf(p1, o1, batch, key)
        p2, o2, l2 = bucketed(p2, o2, batch, key)
        assert float(l1) == float(l2)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        jax.device_get(p1), jax.device_get(p2),
    )


def test_overlap_requires_bucketing(mlp4):
    mesh, params, loss_fn, _ = mlp4
    tx = optax.sgd(0.1)
    with pytest.raises(ValueError, match="bucketed path"):
        make_dp_train_step(
            loss_fn, tx, mesh, bucket_bytes=None, overlap=True
        )
    with pytest.raises(ValueError, match="bucketed path"):
        make_zero_dp_train_step(
            loss_fn, tx, mesh, params, bucket_bytes=0, overlap=True
        )


# --------------------------------------------------------------- donation


def _peak(jitted, *args):
    stats = compiled_memory_stats(jitted.lower(*args).compile())
    assert stats is not None
    return stats["peak_hbm_bytes"], stats.get("alias_size_in_bytes", 0)


def test_dp_donated_peak_hbm_strictly_below_undonated(mlp4):
    """The acceptance pin: with params+opt-state donated, the compiled
    DP step's peak HBM drops strictly below the undonated build — by at
    least the aliased bytes' worth of double-residency."""
    mesh, params, loss_fn, batch = mlp4
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    args = (params, opt_state, batch, jax.random.PRNGKey(0))
    undonated = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False, donate=False
    )
    donated = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False, donate=True
    )
    peak_u, alias_u = _peak(undonated, *args)
    peak_d, alias_d = _peak(donated, *args)
    assert alias_u == 0
    tree_bytes = sum(
        np.size(l) * np.asarray(l).dtype.itemsize
        for l in jax.tree.leaves((params, opt_state))
    )
    # params + both Adam moments alias in place...
    assert alias_d >= tree_bytes
    # ...and the peak drops by most of it (XLA keeps a small live-range
    # remainder, so "strictly below by >= half the aliased bytes" is the
    # robust form of the claim)
    assert peak_u - peak_d >= alias_d // 2
    assert peak_d < peak_u


@pytest.mark.parametrize("builder", ["zero3", "zero12", "llama-prefetch"])
def test_sharded_steps_donate_their_shards(builder, mlp4, devices8):
    """Every ZeRO variant's donated build aliases a nonzero byte count
    (the per-device shard of params/opt state) and never exceeds the
    undonated build's peak."""
    mesh, params, loss_fn, batch = mlp4
    tx = optax.adam(1e-2)
    if builder == "zero3":
        mk = lambda donate: make_zero_dp_train_step(  # noqa: E731
            loss_fn, tx, mesh, params, per_shard_rng=False, donate=donate
        )
        shards = zero_shard_params(params, mesh)
        args = (shards, tx.init(shards), batch, jax.random.PRNGKey(0))
    elif builder == "zero12":
        mk = lambda donate: make_zero_partitioned_train_step(  # noqa: E731
            loss_fn, tx, mesh, params, stage=2, per_shard_rng=False,
            donate=donate,
        )
        args = (
            params, tx.init(zero_shard_params(params, mesh)), batch,
            jax.random.PRNGKey(0),
        )
    else:
        cfg, lp, _, tokens, _ = _llama_workload(4)
        mk = lambda donate: make_zero3_llama_train_step(  # noqa: E731
            cfg, tx, mesh, per_shard_rng=False, donate=donate
        )
        shards = zero_shard_llama_params(lp, mesh)
        args = (shards, tx.init(shards), tokens, jax.random.PRNGKey(0))
    peak_u, _ = _peak(mk(False), *args)
    peak_d, alias_d = _peak(mk(True), *args)
    assert alias_d > 0
    assert peak_d < peak_u


def test_donation_invalidates_inputs_and_env_default(mlp4, monkeypatch):
    """Runtime contract: a donated call consumes its input buffers (the
    caller must rebind), and the builders' donate=None default follows
    DDL25_DONATE (the conftest sets 0 so oracle tests can re-use trees)."""
    mesh, params, loss_fn, batch = mlp4
    tx = optax.sgd(0.1)
    assert bucketing.donation_default() is False  # conftest opt-out
    monkeypatch.delenv("DDL25_DONATE", raising=False)
    assert bucketing.donation_default() is True
    step = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False, donate=True
    )
    p = jax.tree.map(jnp.array, params)
    o = tx.init(p)
    p2, o2, _ = step(p, o, batch, jax.random.PRNGKey(0))
    with pytest.raises(RuntimeError):
        _ = np.asarray(jax.tree.leaves(p)[0]) + 0
    # the returned trees are live and feed the next step
    p3, _, _ = step(p2, o2, batch, jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(jax.tree.leaves(p3)[0])).all()


@pytest.mark.slow
def test_resnet_dp_donation_saves_param_and_momentum_bytes(devices8):
    """The bench workload's donation claim: ResNet-18 DP's donated build
    aliases ~params+momentum in place (the 44.7 MB HBM headroom at the
    real batch; scaled-down compile here)."""
    from ddl25spring_tpu.benchmarks import build_resnet_step

    step_d, params, opt_state, _ = build_resnet_step(
        devices8[:2], 2, 1, 1, 64, donate=True
    )
    step_u, _, _, _ = build_resnet_step(
        devices8[:2], 2, 1, 1, 64, donate=False
    )
    raw = (
        jnp.zeros((64, 32, 32, 3), jnp.uint8),
        jnp.zeros((64,), jnp.int32),
    )
    peak_u, _ = _peak(step_u, params, opt_state, raw)
    peak_d, alias_d = _peak(step_d, params, opt_state, raw)
    tree_bytes = sum(
        np.size(l) * np.asarray(l).dtype.itemsize
        for l in jax.tree.leaves((params, opt_state))
    )
    assert alias_d >= tree_bytes  # fp32 params + SGD momentum
    assert peak_d < peak_u
