"""Sequence-parallel (ring attention) correctness.

Oracle: the seq-sharded model with ring attention must match the unsharded
``llama_forward`` + causal-LM loss — values AND gradients — for any ring
size (SURVEY §4 equivalence discipline applied to the long-context axis)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops.losses import causal_lm_loss
from ddl25spring_tpu.parallel.sp import make_sp_loss, make_sp_train_step
from ddl25spring_tpu.utils.config import LlamaConfig
from ddl25spring_tpu.utils.mesh import make_mesh

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params_and_tokens():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
    return params, tokens


def serial_loss(params, tokens):
    return causal_lm_loss(llama.llama_forward(params, tokens, CFG), tokens)


@pytest.mark.parametrize("ring", [2, 4, 8])
def test_sp_loss_equals_serial(params_and_tokens, ring, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:ring], seq=ring)
    loss = make_sp_loss(CFG, mesh)
    np.testing.assert_allclose(
        float(jax.jit(loss)(params, tokens)),
        float(serial_loss(params, tokens)),
        rtol=1e-5,
    )


def test_sp_grads_equal_serial(params_and_tokens, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:4], seq=4)
    loss = make_sp_loss(CFG, mesh)
    g_sp = jax.jit(jax.grad(loss))(params, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_sp,
        g_serial,
    )


def test_sp_dp_train_step(params_and_tokens, devices8):
    """(data=2, seq=4): one step matches the serial step on the same batch."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8, data=2, seq=4)
    tx = optax.adam(1e-3)
    step = make_sp_train_step(CFG, tx, mesh, data_axis="data")
    new_params, _, loss = step(params, tx.init(params), tokens)

    sloss, g = jax.value_and_grad(serial_loss)(params, tokens)
    updates, _ = tx.update(g, tx.init(params), params)
    expect = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        new_params,
        expect,
    )
