"""Sequence-parallel (ring attention) correctness.

Oracle: the seq-sharded model with ring attention must match the unsharded
``llama_forward`` + causal-LM loss — values AND gradients — for any ring
size (SURVEY §4 equivalence discipline applied to the long-context axis)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops.losses import causal_lm_loss
from ddl25spring_tpu.parallel.sp import make_sp_loss, make_sp_train_step
from ddl25spring_tpu.utils.config import LlamaConfig
from ddl25spring_tpu.utils.mesh import make_mesh

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params_and_tokens():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
    return params, tokens


def serial_loss(params, tokens):
    return causal_lm_loss(llama.llama_forward(params, tokens, CFG), tokens)


@pytest.mark.parametrize("ring", [2, 4, 8])
def test_sp_loss_equals_serial(params_and_tokens, ring, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:ring], seq=ring)
    loss = make_sp_loss(CFG, mesh)
    np.testing.assert_allclose(
        float(jax.jit(loss)(params, tokens)),
        float(serial_loss(params, tokens)),
        rtol=1e-5,
    )


def test_sp_grads_equal_serial(params_and_tokens, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:4], seq=4)
    loss = make_sp_loss(CFG, mesh)
    g_sp = jax.jit(jax.grad(loss))(params, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_sp,
        g_serial,
    )


FLASH_CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32", use_flash=True,
)


@pytest.mark.parametrize("ring", [2, 4])
def test_ring_flash_loss_and_grads_equal_serial(
    params_and_tokens, ring, devices8
):
    """SP x flash composition (VERDICT r3 #2): the flash-local-step ring
    (lse merge, structural visibility) must match the dense ring AND the
    serial model — values and grads.  Off-TPU the local step is the
    dense-with-lse fallback, so this pins the ring/merge math and its
    backward; the Pallas (o, lse) kernel itself is pinned in
    test_flash_attention.py."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:ring], seq=ring)
    loss_flash = make_sp_loss(FLASH_CFG, mesh)
    loss_dense = make_sp_loss(CFG, mesh)

    lf = float(jax.jit(loss_flash)(params, tokens))
    np.testing.assert_allclose(lf, float(serial_loss(params, tokens)), rtol=1e-5)
    np.testing.assert_allclose(
        lf, float(jax.jit(loss_dense)(params, tokens)), rtol=1e-5
    )

    g_flash = jax.jit(jax.grad(loss_flash))(params, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        g_flash,
        g_serial,
    )


def test_sp_moe_aux_reaches_loss(devices8):
    """MoE under SP: the per-shard switch aux must appear in the loss (no
    silent drop) — with one shard the dispatch group is the full batch, so
    the value matches the serial composite exactly."""
    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=32,
        dtype="float32", n_experts=4, capacity_factor=2.0,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(3), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, 64)

    mesh1 = make_mesh(devices8[:1], seq=1)
    l_sp = float(jax.jit(make_sp_loss(cfg, mesh1))(params, tokens))
    logits, aux = llama.llama_forward_with_aux(params, tokens, cfg)
    l_serial = float(
        causal_lm_loss(logits, tokens) + cfg.moe_aux_weight * aux
    )
    np.testing.assert_allclose(l_sp, l_serial, rtol=1e-5)
    assert float(aux) > 0.0  # the aux term is genuinely nonzero

    # 2-shard ring: per-shard dispatch estimator — runs, finite, and close
    # to serial (estimator, not bitwise; see module docstring)
    mesh2 = make_mesh(devices8[:2], seq=2)
    l_sp2 = float(jax.jit(make_sp_loss(cfg, mesh2))(params, tokens))
    assert np.isfinite(l_sp2)
    np.testing.assert_allclose(l_sp2, l_serial, rtol=0.05)


def test_ulysses_loss_and_grads_equal_serial(params_and_tokens, devices8):
    """All-to-all (Ulysses) SP ≡ serial — values and grads.  Off-TPU the
    local full-length step is dense causal attention; the two tiled
    all_to_alls (seq -> heads -> seq) are what this pins."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:2], seq=2)  # num_heads=2 -> 1 head/device
    loss = make_sp_loss(CFG, mesh, mode="ulysses")
    np.testing.assert_allclose(
        float(jax.jit(loss)(params, tokens)),
        float(serial_loss(params, tokens)),
        rtol=1e-5,
    )
    g_sp = jax.jit(jax.grad(loss))(params, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_sp,
        g_serial,
    )


def test_ulysses_rejects_indivisible_heads(devices8):
    mesh = make_mesh(devices8[:4], seq=4)  # 2 heads over 4 shards: no
    with pytest.raises(ValueError, match="divisible"):
        make_sp_loss(CFG, mesh, mode="ulysses")


def test_ulysses_dp_train_step(params_and_tokens, devices8):
    """(data=2, seq=2) Ulysses: one step matches the serial step."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:4], data=2, seq=2)
    tx = optax.adam(1e-3)
    step = make_sp_train_step(CFG, tx, mesh, data_axis="data", mode="ulysses")
    new_params, _, loss = step(params, tx.init(params), tokens)

    sloss, g = jax.value_and_grad(serial_loss)(params, tokens)
    updates, _ = tx.update(g, tx.init(params), params)
    expect = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        new_params,
        expect,
    )


def test_ulysses_moe_equals_serial_composite(devices8):
    """Ulysses SP x switch-MoE: attention re-shards seq -> heads while the
    FFN dispatches per-shard token groups; at 2 shards the composite loss
    must stay close to the serial oracle (per-shard dispatch estimator,
    same caveat as the ring MoE test)."""
    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=32,
        dtype="float32", n_experts=4, capacity_factor=2.0,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(3), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, 64)
    logits, aux = llama.llama_forward_with_aux(params, tokens, cfg)
    l_serial = float(causal_lm_loss(logits, tokens)
                     + cfg.moe_aux_weight * aux)
    mesh = make_mesh(devices8[:2], seq=2)
    l_u = float(jax.jit(make_sp_loss(cfg, mesh, mode="ulysses"))(
        params, tokens))
    assert np.isfinite(l_u)
    np.testing.assert_allclose(l_u, l_serial, rtol=0.05)


def test_sp_dp_train_step(params_and_tokens, devices8):
    """(data=2, seq=4): one step matches the serial step on the same batch."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8, data=2, seq=4)
    tx = optax.adam(1e-3)
    step = make_sp_train_step(CFG, tx, mesh, data_axis="data")
    new_params, _, loss = step(params, tx.init(params), tokens)

    sloss, g = jax.value_and_grad(serial_loss)(params, tokens)
    updates, _ = tx.update(g, tx.init(params), params)
    expect = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        new_params,
        expect,
    )
