"""The obs telemetry subsystem: span JSON against the Chrome-trace schema,
JSONL round-trips, ``jax.debug.callback`` counters under CPU jit, and the
zero-cost-when-disabled contract — instrumented step functions must lower
to HLO *identical* to uninstrumented ones when telemetry is off."""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu import obs
from ddl25spring_tpu.obs.report import format_report, summarize_run
from ddl25spring_tpu.utils.mesh import make_mesh


@pytest.fixture(autouse=True)
def _obs_off():
    """Every test starts (and leaves) with telemetry disabled and a clean
    counter set — the global flag must never leak between tests."""
    obs.enable(False)
    obs.counters.reset()
    yield
    obs.enable(False)
    obs.counters.reset()


# ---------------------------------------------------------------- spans


def test_span_json_validates_against_chrome_trace_schema(tmp_path):
    rec = obs.SpanRecorder(process_name="test-proc")
    with rec.span("outer", cat="host", k=1), rec.span("inner"):
        time.sleep(0.002)
    rec.instant("marker", note="x")

    out = rec.to_chrome_trace()
    # JSON Object Format: traceEvents array + optional metadata
    assert isinstance(out["traceEvents"], list)
    assert out["displayTimeUnit"] in ("ms", "ns")
    json.dumps(out)  # must be serializable as-is

    phs = {e["ph"] for e in out["traceEvents"]}
    assert "X" in phs and "M" in phs and "i" in phs
    for e in out["traceEvents"]:
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["pid"], int)
        assert "tid" in e
        if e["ph"] == "X":  # complete events: ts + dur in microseconds
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["cat"], str)
        if e["ph"] == "i":
            assert e["s"] in ("g", "p", "t")
    # the inner span nests inside the outer one on the same thread
    spans = {e["name"]: e for e in out["traceEvents"] if e["ph"] == "X"}
    o, i = spans["outer"], spans["inner"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e3
    # process_name metadata event carries the recorder's name
    meta = [e for e in out["traceEvents"] if e["ph"] == "M"]
    assert any(e["args"].get("name") == "test-proc" for e in meta)

    p = rec.save(str(tmp_path / "trace.json"))
    with open(p) as f:
        assert json.load(f)["traceEvents"]


def test_spans_threadsafe_and_disabled_is_noop():
    rec = obs.SpanRecorder()

    def worker(i):
        with rec.span(f"w{i}"):
            time.sleep(0.001)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    names = {e["name"] for e in rec.to_chrome_trace()["traceEvents"]}
    assert {f"w{i}" for i in range(8)} <= names

    # module-level span() with telemetry disabled records nothing
    before = len(obs.get_recorder())
    with obs.span("ignored"):
        pass
    assert len(obs.get_recorder()) == before


# --------------------------------------------------------------- logger


def test_metrics_jsonl_roundtrip(tmp_path):
    run = str(tmp_path / "run")
    meta = obs.run_metadata(
        mesh={"data": 2, "stage": 2}, layout="dppp", n_chips=4
    )
    assert meta["jax_version"] == jax.__version__
    with obs.MetricsLogger(run, meta=meta) as lg:
        for i in range(3):
            lg.log(
                step=i,
                wall_s=0.1 * (i + 1),
                samples=64,
                loss=jnp.float32(2.5 - i),  # jax scalar -> plain float
                label="primary",
            )
    recs = obs.read_jsonl(lg.path)
    assert len(recs) == 4
    assert recs[0]["record"] == "header"
    assert recs[0]["mesh"] == {"data": 2, "stage": 2}
    assert recs[0]["layout"] == "dppp"
    assert "git_sha" in recs[0] and "device" in recs[0]
    for i, r in enumerate(recs[1:]):
        assert r["record"] == "step" and r["step"] == i
        assert isinstance(r["loss"], float)  # coerced, not repr'd
    # appending reopens cleanly (crash-resume semantics)
    with obs.MetricsLogger(run) as lg2:
        lg2.log(step=3, wall_s=0.4)
    assert len(obs.read_jsonl(lg.path)) == 5
    # a FRESH run (meta given) truncates: re-running into a fixed run dir
    # must not pool two runs' records into one summary
    with obs.MetricsLogger(run, meta=meta) as lg3:
        lg3.log(step=0, wall_s=0.2)
    assert len(obs.read_jsonl(lg3.path)) == 2


# -------------------------------------------------------------- counters


def test_debug_callback_counters_fire_under_cpu_jit():
    obs.enable()

    @jax.jit
    def f(x):
        obs.counters.emit("t.loss", jnp.sum(x))
        return x * 2

    f(jnp.ones(4)).block_until_ready()
    f(jnp.full(4, 2.0)).block_until_ready()
    s = obs.counters.snapshot()["scalars"]["t.loss"]
    assert s["count"] == 2
    np.testing.assert_allclose(s["sum"], 4.0 + 8.0)
    np.testing.assert_allclose(s["last"], 8.0)
    assert s["min"] == 4.0 and s["max"] == 8.0


def test_mark_series_fire_inside_lax_scan():
    obs.enable()

    @jax.jit
    def f(x):
        def body(c, t):
            obs.counters.mark("t.tick", t)
            return c + 1.0, None

        out, _ = jax.lax.scan(body, x, jnp.arange(5))
        return out

    f(jnp.float32(0.0)).block_until_ready()
    series = obs.counters.snapshot()["series"]["t.tick"]
    assert [int(i) for i, _ in series] == [0, 1, 2, 3, 4]
    # host arrival times are monotone
    times = [t for _, t in series]
    assert times == sorted(times)


def test_counters_insert_nothing_when_disabled():
    def make(instrumented):
        def f(x):
            if instrumented:
                obs.counters.emit("t.x", jnp.sum(x))
                obs.counters.mark("t.m", jnp.int32(0))
            return x * 2

        return f

    x = jnp.ones(4)
    assert obs.enabled() is False
    # instrumentation helpers are trace-time no-ops when disabled, so the
    # two programs must be byte-identical: truly zero-cost
    text_instr = jax.jit(make(True)).lower(x).as_text()
    text_plain = jax.jit(make(False)).lower(x).as_text()
    assert text_instr == text_plain
    jax.jit(make(True))(x)
    assert obs.counters.snapshot()["scalars"] == {}

    with obs.scoped(True):
        assert jax.jit(make(True)).lower(x).as_text() != text_plain


# --------------------------------------- hot-path HLO equality (the pin)


def _dp_setup(devices8, instrument):
    from ddl25spring_tpu.parallel.dp import make_dp_train_step

    def loss_fn(p, batch, key):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    mesh = make_mesh(devices8[:2], data=2)
    tx = optax.sgd(0.1)
    step = make_dp_train_step(
        loss_fn, tx, mesh, per_shard_rng=False, instrument=instrument
    )
    p = {"w": jnp.ones((4, 2))}
    args = (
        p,
        tx.init(p),
        (jnp.ones((8, 4)), jnp.ones((8, 2))),
        jax.random.PRNGKey(0),
    )
    return step, args


def test_dp_step_hlo_identical_when_disabled(devices8):
    step_off, args = _dp_setup(devices8, instrument=False)
    plain = step_off.lower(*args).as_text()

    # default instrumentation, telemetry disabled -> identical HLO
    step_def, args = _dp_setup(devices8, instrument=None)
    assert step_def.lower(*args).as_text() == plain

    # telemetry enabled -> the callbacks actually land in the program
    with obs.scoped(True):
        step_on, args = _dp_setup(devices8, instrument=None)
        assert step_on.lower(*args).as_text() != plain


def test_instrument_true_overrides_disabled_flag(devices8):
    """Explicit ``instrument=True`` hard-enables: the counters land in the
    program even though the global flag is off (build AND trace time)."""
    assert obs.enabled() is False
    step_off, args = _dp_setup(devices8, instrument=False)
    step_on, args_on = _dp_setup(devices8, instrument=True)
    assert step_on.lower(*args_on).as_text() != step_off.lower(*args).as_text()
    jax.block_until_ready(step_on(*args_on))
    jax.effects_barrier()  # debug callbacks flush asynchronously
    assert "dp.loss" in obs.counters.snapshot()["scalars"]


def _het_setup(devices8, instrument):
    from ddl25spring_tpu.parallel.het_pipeline import make_het_pipeline_loss

    mesh = make_mesh(devices8[:2], stage=2)
    loss = make_het_pipeline_loss(
        [lambda p, x: x * p, lambda p, x: x + p],
        lambda out, b: jnp.mean((out - b["y"]) ** 2),
        (4, 8),
        [(4, 8), (4, 8)],
        mesh,
        num_microbatches=2,
        instrument=instrument,
    )
    params = (jnp.float32(2.0), jnp.float32(1.0))
    batch = {"x": jnp.ones((8, 8)), "y": jnp.zeros((8, 8))}
    return loss, (params, batch)


def test_pipeline_loss_hlo_identical_when_disabled(devices8):
    loss_off, args = _het_setup(devices8, instrument=False)
    plain = jax.jit(loss_off).lower(*args).as_text()

    loss_def, args = _het_setup(devices8, instrument=None)
    assert jax.jit(loss_def).lower(*args).as_text() == plain

    with obs.scoped(True):
        loss_on, args = _het_setup(devices8, instrument=None)
        assert jax.jit(loss_on).lower(*args).as_text() != plain


def test_pipeline_tick_counters_and_schedule_statics(devices8):
    obs.enable()
    loss, args = _het_setup(devices8, instrument=None)
    v = jax.jit(loss)(*args)
    assert np.isfinite(float(v))
    snap = obs.counters.snapshot()
    # T = M + S - 1 = 3 ticks, once per stage device
    assert len(snap["series"]["pipeline.tick"]) == 3 * 2
    assert snap["static"]["pipeline.num_stages"] == 2
    assert snap["static"]["pipeline.num_microbatches"] == 2
    np.testing.assert_allclose(
        snap["static"]["pipeline.bubble_fraction_gpipe"], 1 / 3
    )


# ---------------------------------------------------------------- report


def test_gpipe_bubble_fraction_math():
    assert obs.gpipe_bubble_fraction(1, 8) == 0.0
    np.testing.assert_allclose(obs.gpipe_bubble_fraction(2, 2), 1 / 3)
    np.testing.assert_allclose(obs.gpipe_bubble_fraction(4, 12), 0.2)


def test_summarize_run_and_format(tmp_path):
    run = str(tmp_path / "run")
    with obs.MetricsLogger(
        run,
        meta=obs.run_metadata(
            mesh={"data": 1},
            layout="dp",
            n_chips=1,
            num_stages=2,
            num_microbatches=4,
        ),
    ) as lg:
        walls = [0.10, 0.10, 0.10, 0.10, 0.10, 0.10, 0.10, 0.10, 0.10, 1.0]
        for i, w in enumerate(walls):
            lg.log(step=i, wall_s=w, samples=100, loss=1.0, label="primary")
        # flops arrive in a late supplementary header — must merge
        lg.log(record="header", flops_per_step=1e9, peak_flops_per_chip=1e10)
    obs.counters.save(run)

    s = summarize_run(run)
    ph = s["phases"]["primary"]
    assert ph["steps"] == 10
    # p50 must shrug off the one 1.0 s outlier (the GC-pause scenario)
    np.testing.assert_allclose(ph["step_s_p50"], 0.10)
    assert ph["step_s_p95"] > 0.5
    np.testing.assert_allclose(ph["steps_per_sec_p50"], 10.0)
    np.testing.assert_allclose(ph["samples_per_sec_per_chip_p50"], 1000.0)
    np.testing.assert_allclose(ph["mfu"], 1e9 / 0.10 / 1e10)
    np.testing.assert_allclose(s["bubble_fraction"], 0.2)

    text = format_report(s)
    for token in ("step p50", "step p95", "MFU", "bubble fraction", "0.2000"):
        assert token in text, f"report is missing {token!r}"


def test_summarize_run_normalizes_fused_steps(tmp_path):
    """Scan-fused phases log one record per DISPATCH of k train steps;
    the summary must report per-train-step units (steps/sec, MFU) or the
    fused phase reads k times slower than it is."""
    run = str(tmp_path / "run")
    with obs.MetricsLogger(
        run, meta=obs.run_metadata(n_chips=1)
    ) as lg:
        for i in range(6):
            # 0.4 s per dispatch of 4 fused steps = 0.1 s/step
            lg.log(step=i, wall_s=0.4, samples=400, fused_steps=4,
                   label="hbm-scan")
        lg.log(record="header", flops_per_step=1e9, peak_flops_per_chip=1e10)

    ph = summarize_run(run)["phases"]["hbm-scan"]
    assert ph["steps"] == 24 and ph["fused_steps"] == 4
    assert ph["dispatches"] == 6
    np.testing.assert_allclose(ph["step_s_p50"], 0.10)
    np.testing.assert_allclose(ph["steps_per_sec_p50"], 10.0)
    np.testing.assert_allclose(ph["samples_per_sec_per_chip_p50"], 1000.0)
    np.testing.assert_allclose(ph["mfu"], 1e9 / 0.10 / 1e10)


def test_tick_interval_collapses_shards_and_scan_restarts(tmp_path):
    """The tick series holds one arrival PER SHARD per tick, and the tick
    index restarts each scan invocation; the cadence estimate must use
    first-arrival-per-index consecutive transitions only."""
    import json as _json
    import os as _os

    run = str(tmp_path / "run")
    with obs.MetricsLogger(run, meta=obs.run_metadata()) as lg:
        lg.log(step=0, wall_s=1.0)
    # 2 shards x 3 ticks x 2 scan invocations, 0.1 s real tick interval,
    # shard echoes ~1 ms apart, 5 s between invocations
    series = []
    for t0 in (0.0, 5.0):
        for idx in range(3):
            series.append([idx, t0 + 0.1 * idx])
            series.append([idx, t0 + 0.1 * idx + 0.001])
    with open(_os.path.join(run, "counters.json"), "w") as f:
        _json.dump(
            {"scalars": {}, "series": {"pipeline.tick": series}, "static": {}},
            f,
        )
    s = summarize_run(run)
    np.testing.assert_allclose(s["tick_interval_s_p50"], 0.1, rtol=0.05)


# ---------------------------------------- satellite: StepTimer percentiles


def test_steptimer_percentiles_and_p50_rate():
    from ddl25spring_tpu.utils.tracing import StepTimer

    st = StepTimer(warmup=0)
    st.times = [0.1] * 9 + [1.0]  # one GC-pause outlier
    np.testing.assert_allclose(st.p50_step_s, 0.1)
    assert st.p95_step_s > 0.5
    np.testing.assert_allclose(st.min_step_s, 0.1)
    np.testing.assert_allclose(st.mean_step_s, 0.19)
    # the headline rate uses p50: the outlier must not skew it
    np.testing.assert_allclose(st.steps_per_sec(), 10.0)

    with pytest.raises(ValueError, match="no timed steps"):
        StepTimer().p50_step_s


# ------------------------------------- satellite: flops warning, not raise


def test_compiled_flops_warns_and_returns_none_when_unavailable(caplog):
    from ddl25spring_tpu.utils.flops import compiled_flops, mfu

    class Broken:
        def lower(self, *a, **k):
            raise RuntimeError("no cost model on this backend")

    with caplog.at_level("WARNING", logger="ddl25spring_tpu.utils.flops"):
        assert compiled_flops(Broken()) is None
    assert any("cost analysis" in r.message for r in caplog.records)
    # and the mfu path degrades to (None, None) instead of raising
    assert mfu(None, 0.1) == (None, None)
    assert mfu(1e9, 0.0) == (None, None)
