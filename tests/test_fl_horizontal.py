"""Horizontal FL tests.

Centerpiece: the homework-A1 equivalence oracle — FedSGD-with-gradients must
equal FedAvg-with-weights at ``B=-1, E=1`` (``lab/series01.ipynb`` cells 9-12;
tolerance 0.02% there, exact up to fp32 here with dropout disabled, since
weight-averaging after one full-batch SGD step is linear in the gradients).
"""

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.data.mnist import load_mnist
from ddl25spring_tpu.fl import CentralizedServer, FedAvgServer, FedSgdGradientServer


class TinyMlp(nn.Module):
    """Dropout-free model for exact-equivalence tests (full MnistCnn under
    vmapped scans compiles for minutes on the CPU test backend)."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(32)(x))
        return nn.log_softmax(nn.Dense(10)(x))


class TinyDropoutMlp(nn.Module):
    """Small model WITH dropout: exercises per-client rng plumbing."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(32)(x))
        x = nn.Dropout(0.3, deterministic=not train)(x)
        return nn.log_softmax(nn.Dense(10)(x))


@pytest.fixture(scope="module")
def small_data():
    return load_mnist(n_train=1000, n_test=500)


def test_a1_fedsgd_equals_fedavg_fullbatch(small_data):
    kw = dict(
        nr_clients=5,
        client_fraction=0.4,
        lr=0.05,
        seed=10,
        model=TinyMlp(),
        data=small_data,
    )
    sgd = FedSgdGradientServer(batch_size=-1, nr_local_epochs=1, **kw)
    avg = FedAvgServer(batch_size=-1, nr_local_epochs=1, **kw)
    r_sgd = sgd.run(3)
    r_avg = avg.run(3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4
        ),
        sgd.params,
        avg.params,
    )
    # the reference's tolerance on the metric itself
    np.testing.assert_allclose(
        r_sgd.test_accuracy, r_avg.test_accuracy, atol=2e-4
    )


def test_fedavg_learns_and_counts_messages(small_data):
    server = FedAvgServer(
        nr_clients=10,
        client_fraction=0.5,
        batch_size=50,
        nr_local_epochs=2,
        lr=0.05,
        seed=10,
        model=TinyDropoutMlp(),
        data=small_data,
    )
    res = server.run(3)
    assert res.test_accuracy[-1] > 0.6  # synthetic data is easy
    assert res.message_count == [10, 20, 30]  # 2*(r+1)*5
    df = res.as_df()
    assert list(df["Round"]) == [1, 2, 3]
    assert df["Algorithm"].iloc[0] == "FedAvg"


def test_fedavg_noniid_runs(small_data):
    server = FedAvgServer(
        nr_clients=5,
        client_fraction=0.6,
        batch_size=20,
        nr_local_epochs=1,
        lr=0.05,
        iid=False,
        seed=10,
        model=TinyMlp(),
        data=small_data,
    )
    res = server.run(2)
    assert len(res.test_accuracy) == 2


def test_fedavg_seed_determinism(small_data):
    mk = lambda: FedAvgServer(
        nr_clients=5,
        client_fraction=0.4,
        batch_size=50,
        nr_local_epochs=1,
        lr=0.05,
        seed=10,
        model=TinyDropoutMlp(),
        data=small_data,
    )
    a, b = mk(), mk()
    a.run(2)
    b.run(2)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            jax.device_get(x), jax.device_get(y)
        ),
        a.params,
        b.params,
    )


def test_centralized_learns(small_data):
    server = CentralizedServer(lr=0.05, batch_size=50, seed=10, data=small_data)
    res = server.run(2)
    assert res.test_accuracy[-1] > 0.8


def test_local_update_invariant_to_pad_rows(small_data):
    """Pad rows (positions >= count) must not influence local training:
    the same client padded with repeats vs. garbage must produce identical
    weights (the round-1 FedAvg oversampling bug trained on the repeats)."""
    from ddl25spring_tpu.fl.horizontal import _make_local_epochs_fn

    model = TinyMlp()
    x = np.asarray(small_data["x_train"][:40], np.float32)
    y = np.asarray(small_data["y_train"][:40], np.int32)
    count, max_n = 25, 40
    params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
    key = jax.random.PRNGKey(3)

    x_repeat = x.copy()
    x_repeat[count:] = x[:max_n - count]  # stack_client_data-style repeats
    x_junk = x.copy()
    x_junk[count:] = 1e3  # adversarial pad contents
    y_junk = y.copy()
    y_junk[count:] = 0

    for bs in (-1, 8):  # full-batch path and minibatch path
        local = _make_local_epochs_fn(model, lr=0.05, batch_size=bs, nr_epochs=2)
        run = jax.jit(local)
        p_rep = run(params, jnp.asarray(x_repeat), jnp.asarray(y), key,
                    jnp.int32(count))
        p_junk = run(params, jnp.asarray(x_junk), jnp.asarray(y_junk), key,
                     jnp.int32(count))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                jax.device_get(a), jax.device_get(b)
            ),
            p_rep, p_junk,
        )


def test_fedavg_vmapped_round_equals_python_loop(small_data):
    """One vmapped FedAvg round == a plain per-client Python-loop round
    under a non-IID split (VERDICT r1 item 4): same padded shards and keys,
    clients trained one by one, then weighted-averaged by true counts."""
    from ddl25spring_tpu.data.splitter import split_indices, stack_client_data
    from ddl25spring_tpu.fl.horizontal import _make_local_epochs_fn
    from ddl25spring_tpu.utils.prng import client_round_key

    model = TinyMlp()
    x = np.asarray(small_data["x_train"][:300], np.float32)
    y = np.asarray(small_data["y_train"][:300], np.int32)
    splits = split_indices(y, nr_clients=4, iid=False, seed=10)
    cx, cy, counts = stack_client_data(x, y, splits)
    assert len(set(counts.tolist())) > 1, "want unequal client sizes"

    server = FedAvgServer(
        nr_clients=4, client_fraction=1.0, batch_size=16, nr_local_epochs=2,
        lr=0.05, iid=False, seed=10, model=model,
        data={**small_data, "x_train": x, "y_train": y},
    )
    params0 = jax.tree.map(jnp.copy, server.params)
    server.round(0)
    vmapped = server.params

    local = _make_local_epochs_fn(model, lr=0.05, batch_size=16, nr_epochs=2)
    # server.sample_clients used rng(seed=10).choice too; with C=1.0 every
    # client is chosen, so order only affects key assignment by index
    per_client = []
    for i in server_chosen_order(seed=10, n=4):
        k = client_round_key(jax.random.PRNGKey(10), 0, int(i))
        per_client.append(
            jax.jit(local)(
                params0, jnp.asarray(cx[i]), jnp.asarray(cy[i]), k,
                jnp.int32(counts[i]),
            )
        )
    w = np.asarray([counts[i] for i in server_chosen_order(seed=10, n=4)],
                   np.float32)
    w = w / w.sum()
    looped = jax.tree.map(
        lambda *leaves: sum(wi * l for wi, l in zip(w, leaves)), *per_client
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-6, rtol=1e-5
        ),
        vmapped, looped,
    )


def server_chosen_order(seed: int, n: int) -> np.ndarray:
    """Replicate _HflBase.sample_clients for round 0: rng(seed).choice."""
    return np.random.default_rng(seed).choice(n, n, replace=False)

# ---------------------------------------------------------------- golden / A1


@pytest.mark.skipif(
    __import__("ddl25spring_tpu.data.mnist", fromlist=["_find_idx_dir"])
    ._find_idx_dir() is None,
    reason="golden accuracy targets need real MNIST "
           "(series01.ipynb cell 20; point DDL25_MNIST_DIR at IDX files)",
)
@pytest.mark.parametrize(
    "server_cls,golden",
    [(FedAvgServer, 0.932), (FedSgdGradientServer, 0.4287)],
)
def test_golden_accuracy_n10_c01(server_cls, golden):
    """The solved homework's recorded targets at N=10, C=0.1, 10 rounds,
    tutorial defaults lr=0.01 E=1 B=100 seed=10 (BASELINE.md; reference
    ``lab/series01.ipynb`` cell 20: FedAvg 93.2%, FedSGD 42.87%)."""
    server = server_cls(
        nr_clients=10, client_fraction=0.1,
        batch_size=-1 if server_cls is FedSgdGradientServer else 100,
        nr_local_epochs=1, lr=0.01, seed=10,
    )
    res = server.run(10)
    np.testing.assert_allclose(res.test_accuracy[-1], golden, atol=0.02)


@pytest.mark.skipif(
    not os.environ.get("DDL25_RUN_SLOW"),
    reason="full MnistCnn under vmapped scans compiles for minutes on the "
           "CPU backend (set DDL25_RUN_SLOW=1; runs in seconds on TPU). "
           "The same oracle is exercised continuously by "
           "examples/homework1_a1_equivalence.py — see RESULTS.md",
)
def test_a1_oracle_shipped_mnist_cnn():
    """A1 on the SHIPPED model: FedSGD-with-gradients == FedSGD-with-weights
    (FedAvg at B=-1, E=1) on MnistCnn with dropout + conv — the exact
    configuration the reference tests (``hfl_complete.py:39-64``,
    ``series01.ipynb`` cells 9-12; tolerance 0.02% per round)."""
    data = load_mnist(n_train=1000, n_test=500)
    common = dict(nr_clients=4, client_fraction=0.5, lr=0.01, seed=10,
                  data=data, batch_size=-1, nr_local_epochs=1)
    grad_server = FedSgdGradientServer(**common)
    weight_server = FedAvgServer(**common)
    for r in range(2):
        grad_server.round(r)
        weight_server.round(r)
        ga, wa = grad_server.test_accuracy(), weight_server.test_accuracy()
        assert abs(ga - wa) <= 2e-4, (r, ga, wa)
