"""Horizontal FL tests.

Centerpiece: the homework-A1 equivalence oracle — FedSGD-with-gradients must
equal FedAvg-with-weights at ``B=-1, E=1`` (``lab/series01.ipynb`` cells 9-12;
tolerance 0.02% there, exact up to fp32 here with dropout disabled, since
weight-averaging after one full-batch SGD step is linear in the gradients).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.data.mnist import load_mnist
from ddl25spring_tpu.fl import CentralizedServer, FedAvgServer, FedSgdGradientServer


class TinyMlp(nn.Module):
    """Dropout-free model for exact-equivalence tests (full MnistCnn under
    vmapped scans compiles for minutes on the CPU test backend)."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(32)(x))
        return nn.log_softmax(nn.Dense(10)(x))


class TinyDropoutMlp(nn.Module):
    """Small model WITH dropout: exercises per-client rng plumbing."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(32)(x))
        x = nn.Dropout(0.3, deterministic=not train)(x)
        return nn.log_softmax(nn.Dense(10)(x))


@pytest.fixture(scope="module")
def small_data():
    return load_mnist(n_train=1000, n_test=500)


def test_a1_fedsgd_equals_fedavg_fullbatch(small_data):
    kw = dict(
        nr_clients=5,
        client_fraction=0.4,
        lr=0.05,
        seed=10,
        model=TinyMlp(),
        data=small_data,
    )
    sgd = FedSgdGradientServer(batch_size=-1, nr_local_epochs=1, **kw)
    avg = FedAvgServer(batch_size=-1, nr_local_epochs=1, **kw)
    r_sgd = sgd.run(3)
    r_avg = avg.run(3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4
        ),
        sgd.params,
        avg.params,
    )
    # the reference's tolerance on the metric itself
    np.testing.assert_allclose(
        r_sgd.test_accuracy, r_avg.test_accuracy, atol=2e-4
    )


def test_fedavg_learns_and_counts_messages(small_data):
    server = FedAvgServer(
        nr_clients=10,
        client_fraction=0.5,
        batch_size=50,
        nr_local_epochs=2,
        lr=0.05,
        seed=10,
        model=TinyDropoutMlp(),
        data=small_data,
    )
    res = server.run(3)
    assert res.test_accuracy[-1] > 0.6  # synthetic data is easy
    assert res.message_count == [10, 20, 30]  # 2*(r+1)*5
    df = res.as_df()
    assert list(df["Round"]) == [1, 2, 3]
    assert df["Algorithm"].iloc[0] == "FedAvg"


def test_fedavg_noniid_runs(small_data):
    server = FedAvgServer(
        nr_clients=5,
        client_fraction=0.6,
        batch_size=20,
        nr_local_epochs=1,
        lr=0.05,
        iid=False,
        seed=10,
        model=TinyMlp(),
        data=small_data,
    )
    res = server.run(2)
    assert len(res.test_accuracy) == 2


def test_fedavg_seed_determinism(small_data):
    mk = lambda: FedAvgServer(
        nr_clients=5,
        client_fraction=0.4,
        batch_size=50,
        nr_local_epochs=1,
        lr=0.05,
        seed=10,
        model=TinyDropoutMlp(),
        data=small_data,
    )
    a, b = mk(), mk()
    a.run(2)
    b.run(2)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            jax.device_get(x), jax.device_get(y)
        ),
        a.params,
        b.params,
    )


def test_centralized_learns(small_data):
    server = CentralizedServer(lr=0.05, batch_size=50, seed=10, data=small_data)
    res = server.run(2)
    assert res.test_accuracy[-1] > 0.8