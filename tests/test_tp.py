"""Tensor-parallel correctness: TP(xDP) loss and grads must match the
unpartitioned model (the SURVEY §4 equivalence oracle, applied to the
layer-internal sharding axis)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops.losses import causal_lm_loss
from ddl25spring_tpu.parallel.tp import (
    make_tp_loss,
    make_tp_train_step,
    shard_tp_params,
)
from ddl25spring_tpu.utils.config import LlamaConfig
from ddl25spring_tpu.utils.mesh import make_mesh

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=4, n_layers=2, ctx_size=16,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params_and_tokens():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    return params, tokens


def serial_loss(params, tokens):
    return causal_lm_loss(llama.llama_forward(params, tokens, CFG), tokens)


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_loss_equals_serial(params_and_tokens, tp, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:tp], model=tp)
    loss = make_tp_loss(CFG, mesh)
    l_tp = float(jax.jit(loss)(shard_tp_params(params, mesh), tokens))
    np.testing.assert_allclose(l_tp, float(serial_loss(params, tokens)), rtol=1e-5)


def test_tp_grads_equal_serial(params_and_tokens, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:2], model=2)
    loss = make_tp_loss(CFG, mesh)
    g_tp = jax.jit(jax.grad(loss))(shard_tp_params(params, mesh), tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_tp,
        g_serial,
    )


def test_tp_vocab_params_actually_sharded(params_and_tokens, devices8):
    """The point of shard_vocab: each device holds V/n rows of embed and
    V/n columns of unembed, not full replicas."""
    params, _ = params_and_tokens
    mesh = make_mesh(devices8[:2], model=2)
    sharded = shard_tp_params(params, mesh)
    for leaf, dim in ((sharded["embed"], 0), (sharded["unembed"], 1)):
        s0 = [s for s in leaf.addressable_shards if s.device == devices8[0]]
        assert s0[0].data.shape[dim] == leaf.shape[dim] // 2, (
            leaf.shape, s0[0].data.shape, dim,
        )


def test_tp_dp_train_step(params_and_tokens, devices8):
    """2-D (data=2, model=2): one step matches the serial step."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:4], data=2, model=2)
    tx = optax.adam(1e-3)
    step = make_tp_train_step(CFG, tx, mesh, data_axis="data")
    sharded = shard_tp_params(params, mesh)
    new_params, _, loss = step(sharded, tx.init(sharded), tokens)

    sstep_loss, g = jax.value_and_grad(serial_loss)(params, tokens)
    updates, _ = tx.update(g, tx.init(params), params)
    expect = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(loss), float(sstep_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        new_params,
        expect,
    )
