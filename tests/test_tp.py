"""Tensor-parallel correctness: TP(xDP) loss and grads must match the
unpartitioned model (the SURVEY §4 equivalence oracle, applied to the
layer-internal sharding axis)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops.losses import causal_lm_loss
from ddl25spring_tpu.parallel.tp import (
    make_tp_loss,
    make_tp_train_step,
    shard_tp_params,
)
from ddl25spring_tpu.utils.config import LlamaConfig
from ddl25spring_tpu.utils.mesh import make_mesh

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=4, n_layers=2, ctx_size=16,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params_and_tokens():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    return params, tokens


def serial_loss(params, tokens):
    return causal_lm_loss(llama.llama_forward(params, tokens, CFG), tokens)


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_loss_equals_serial(params_and_tokens, tp, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:tp], model=tp)
    loss = make_tp_loss(CFG, mesh)
    l_tp = float(jax.jit(loss)(shard_tp_params(params, mesh), tokens))
    np.testing.assert_allclose(l_tp, float(serial_loss(params, tokens)), rtol=1e-5)


def test_tp_grads_equal_serial(params_and_tokens, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:2], model=2)
    loss = make_tp_loss(CFG, mesh)
    g_tp = jax.jit(jax.grad(loss))(shard_tp_params(params, mesh), tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_tp,
        g_serial,
    )


def test_tp_vocab_params_actually_sharded(params_and_tokens, devices8):
    """The point of shard_vocab: each device holds V/n rows of embed and
    V/n columns of unembed, not full replicas."""
    params, _ = params_and_tokens
    mesh = make_mesh(devices8[:2], model=2)
    sharded = shard_tp_params(params, mesh)
    for leaf, dim in ((sharded["embed"], 0), (sharded["unembed"], 1)):
        s0 = [s for s in leaf.addressable_shards if s.device == devices8[0]]
        assert s0[0].data.shape[dim] == leaf.shape[dim] // 2, (
            leaf.shape, s0[0].data.shape, dim,
        )


MOE_CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=4, n_layers=2, ctx_size=16,
    dtype="float32", n_experts=4, capacity_factor=1.0,
)


def serial_moe_loss(params, tokens):
    logits, aux = llama.llama_forward_with_aux(params, tokens, MOE_CFG)
    return causal_lm_loss(logits, tokens) + MOE_CFG.moe_aux_weight * aux


@pytest.fixture(scope="module")
def moe_params_and_tokens():
    params = llama.init_llama_params(jax.random.PRNGKey(2), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, 64)
    return params, tokens


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_moe_loss_equals_serial(moe_params_and_tokens, tp, devices8):
    """Expert-sharded TP MoE ≡ serial moe_ffn EXACTLY — global routing and
    the tight capacity_factor=1.0 overflow drops are computed identically
    on every shard (unlike EP's per-shard capacity)."""
    params, tokens = moe_params_and_tokens
    mesh = make_mesh(devices8[:tp], model=tp)
    loss = make_tp_loss(MOE_CFG, mesh)
    l_tp = float(jax.jit(loss)(shard_tp_params(params, mesh), tokens))
    np.testing.assert_allclose(
        l_tp, float(serial_moe_loss(params, tokens)), rtol=1e-5
    )


def test_tp_moe_grads_equal_serial(moe_params_and_tokens, devices8):
    params, tokens = moe_params_and_tokens
    mesh = make_mesh(devices8[:2], model=2)
    loss = make_tp_loss(MOE_CFG, mesh)
    g_tp = jax.jit(jax.grad(loss))(shard_tp_params(params, mesh), tokens)
    g_serial = jax.grad(serial_moe_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        g_tp,
        g_serial,
    )


def test_tp_moe_expert_stacks_actually_sharded(moe_params_and_tokens, devices8):
    params, _ = moe_params_and_tokens
    mesh = make_mesh(devices8[:2], model=2)
    sharded = shard_tp_params(params, mesh)
    moe = sharded["blocks"]["moe"]
    for k in ("w_gate", "w_up", "w_down"):
        s0 = [s for s in moe[k].addressable_shards if s.device == devices8[0]]
        assert s0[0].data.shape[1] == MOE_CFG.n_experts // 2, (
            k, s0[0].data.shape,
        )
    # router replicated: every shard holds the full [L, D, E]
    r0 = moe["router"].addressable_shards[0]
    assert r0.data.shape == moe["router"].shape


def test_tp_dp_moe_train_step(moe_params_and_tokens, devices8):
    """2-D (data=2, model=2) with MoE blocks: one step matches the serial
    per-data-shard oracle.  Each data row routes its own half-batch (its
    own aux estimate — the standard sharded-MoE mean-of-shard-losses), so
    the oracle is the mean of serial losses over the two halves."""
    params, tokens = moe_params_and_tokens
    cfg = dataclasses.replace(MOE_CFG, capacity_factor=4.0)
    mesh = make_mesh(devices8[:4], data=2, model=2)
    tx = optax.adam(1e-3)
    step = make_tp_train_step(cfg, tx, mesh, data_axis="data")
    sharded = shard_tp_params(params, mesh)
    new_params, _, loss = step(sharded, tx.init(sharded), tokens)

    def serial(params, tokens):
        def one(tk):
            logits, aux = llama.llama_forward_with_aux(params, tk, cfg)
            return causal_lm_loss(logits, tk) + cfg.moe_aux_weight * aux

        return 0.5 * (one(tokens[:2]) + one(tokens[2:]))

    sstep_loss, g = jax.value_and_grad(serial)(params, tokens)
    updates, _ = tx.update(g, tx.init(params), params)
    expect = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(loss), float(sstep_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        ),
        new_params,
        expect,
    )


def test_tp_dp_train_step(params_and_tokens, devices8):
    """2-D (data=2, model=2): one step matches the serial step."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:4], data=2, model=2)
    tx = optax.adam(1e-3)
    step = make_tp_train_step(CFG, tx, mesh, data_axis="data")
    sharded = shard_tp_params(params, mesh)
    new_params, _, loss = step(sharded, tx.init(sharded), tokens)

    sstep_loss, g = jax.value_and_grad(serial_loss)(params, tokens)
    updates, _ = tx.update(g, tx.init(params), params)
    expect = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(loss), float(sstep_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        new_params,
        expect,
    )
