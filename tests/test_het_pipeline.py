"""Heterogeneous-stage (ResNet) pipeline correctness.

Same oracle as the LLaMA pipeline tests: the 2-stage microbatched SPMD
program must reproduce the unpartitioned model's loss and gradients
(SURVEY §4 equivalence-testing discipline), here for the benchmark
topology — ResNet stages with *different* param structures and boundary
shapes (BASELINE.json "2-stage pipeline x 2-way DP with microbatches").
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models.resnet import ResNet18Stage0, ResNet18Stage1
from ddl25spring_tpu.ops.losses import cross_entropy_logits
from ddl25spring_tpu.parallel.het_pipeline import (
    make_het_pipeline_loss,
    make_het_pipeline_train_step,
)
from ddl25spring_tpu.utils.compat import HAS_VMA
from ddl25spring_tpu.utils.mesh import make_mesh

# Forward passes through the het pipeline run on any jax (pinned by the
# loss-equality test below and by tests/test_obs.py).  The GRAD path does
# not: pre-VMA jax's experimental shard_map mis-stages the transposed
# program (_SpecError on a scalar cotangent) for the scan-over-ppermute
# schedule, so gradient/train tests need the VMA-typed shard_map.
needs_vma_grad = pytest.mark.skipif(
    not HAS_VMA,
    reason="pipeline grad path needs VMA-typed shard_map (lax.pcast); "
    "this jax's experimental shard_map mis-transposes the schedule",
)

W = 8  # narrow net: CPU-fast, same structure
S0 = ResNet18Stage0(width=W)
S1 = ResNet18Stage1(width=W, num_classes=10)


@pytest.fixture(scope="module")
def setup():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
    p0 = S0.init(key, x[:1])["params"]
    mid = S0.apply({"params": p0}, x[:1])
    p1 = S1.init(jax.random.PRNGKey(3), mid)["params"]
    return (p0, p1), x, y


def serial_loss(params, batch):
    p0, p1 = params
    logits = S1.apply({"params": p1}, S0.apply({"params": p0}, batch["x"]))
    return cross_entropy_logits(logits, batch["y"])


def _stage_fns():
    return [
        lambda p, x: S0.apply({"params": p}, x),
        lambda p, x: S1.apply({"params": p}, x),
    ]


def _shapes(mb):
    return (mb, 32, 32, 3), [(mb, 16, 16, 2 * W), (mb, 10)]


@pytest.mark.parametrize("microbatches", [2, 4])
def test_het_pipeline_loss_equals_serial(setup, microbatches, devices8):
    params, x, y = setup
    mesh = make_mesh(devices8[:2], stage=2)
    mb = x.shape[0] // microbatches
    in_shape, bounds = _shapes(mb)
    loss = make_het_pipeline_loss(
        _stage_fns(), lambda logits, b: cross_entropy_logits(logits, b["y"]),
        in_shape, bounds, mesh, microbatches,
    )
    l_pipe = float(jax.jit(loss)(params, {"x": x, "y": y}))
    l_serial = float(serial_loss(params, {"x": x, "y": y}))
    np.testing.assert_allclose(l_pipe, l_serial, rtol=1e-5)


@needs_vma_grad
def test_het_pipeline_grads_equal_serial(setup, devices8):
    params, x, y = setup
    mesh = make_mesh(devices8[:2], stage=2)
    M = 2
    in_shape, bounds = _shapes(x.shape[0] // M)
    loss = make_het_pipeline_loss(
        _stage_fns(), lambda logits, b: cross_entropy_logits(logits, b["y"]),
        in_shape, bounds, mesh, M,
    )
    g_pipe = jax.jit(jax.grad(loss))(params, {"x": x, "y": y})
    g_serial = jax.grad(serial_loss)(params, {"x": x, "y": y})
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_serial)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


@needs_vma_grad
def test_het_pipeline_dp_pp_trains(setup, devices8):
    """DPxPP: 2-way data x 2-stage pipeline on 4 devices; loss decreases."""
    params, x, y = setup
    mesh = make_mesh(devices8[:4], data=2, stage=2)
    M = 2
    mb = x.shape[0] // M // 2  # per-DP-shard microbatch
    in_shape, bounds = _shapes(mb)
    tx = optax.sgd(0.05)
    step = make_het_pipeline_train_step(
        _stage_fns(), lambda logits, b: cross_entropy_logits(logits, b["y"]),
        in_shape, bounds, tx, mesh, M, data_axis="data",
    )
    opt_state = tx.init(params)
    batch = {"x": x, "y": y}
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------- sharded params


@needs_vma_grad
def test_sharded_het_pipeline_equals_replicated(setup, devices8):
    """The stage-SHARDED variant (params packed [S, maxP] over the stage
    axis, each device materializing only its branch) must match the
    replicated path — loss and the params after one optimizer step."""
    from ddl25spring_tpu.parallel.het_pipeline import (
        make_sharded_het_pipeline_train_step,
        pack_stage_params,
        unpack_stage_params,
    )

    params, x, y = setup
    mesh = make_mesh(devices8[:4], data=2, stage=2)
    M, mb = 2, 2
    batch = {"x": x, "y": y}
    tx = optax.sgd(0.1)

    step_rep = make_het_pipeline_train_step(
        _stage_fns(), lambda lg, b: cross_entropy_logits(lg, b["y"]),
        *_shapes(mb), tx, mesh, M, data_axis="data",
    )
    p_rep, _, l_rep = step_rep(params, tx.init(params), batch)

    step_sh, stacked, opt_sh = make_sharded_het_pipeline_train_step(
        _stage_fns(), params,
        lambda lg, b: cross_entropy_logits(lg, b["y"]),
        *_shapes(mb), tx, mesh, M, data_axis="data",
    )
    stacked, _, l_sh = step_sh(stacked, opt_sh, batch)

    np.testing.assert_allclose(float(l_rep), float(l_sh), rtol=1e-6)
    _, metas = pack_stage_params(params)
    for i in range(2):
        p_i = unpack_stage_params(jax.device_get(stacked)[i], metas[i])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-5
            ),
            p_rep[i],
            p_i,
        )


@needs_vma_grad
def test_sharded_het_pipeline_param_memory(setup, devices8):
    """The point of sharding: per-device param bytes are max_s|p_s| (plus
    padding), not sum_s|p_s|.  Check the compiled argument footprint of the
    sharded step is strictly below the replicated step's."""
    from ddl25spring_tpu.parallel.het_pipeline import (
        make_sharded_het_pipeline_train_step,
        pack_stage_params,
    )

    params, x, y = setup
    mesh = make_mesh(devices8[:2], stage=2)
    M, mb = 2, 4
    batch = {"x": x, "y": y}
    tx = optax.sgd(0.1)

    step_rep = make_het_pipeline_train_step(
        _stage_fns(), lambda lg, b: cross_entropy_logits(lg, b["y"]),
        *_shapes(mb), tx, mesh, M,
    )
    rep_stats = step_rep.lower(
        params, tx.init(params), batch
    ).compile().memory_analysis()

    step_sh, stacked, opt_sh = make_sharded_het_pipeline_train_step(
        _stage_fns(), params,
        lambda lg, b: cross_entropy_logits(lg, b["y"]),
        *_shapes(mb), tx, mesh, M,
    )
    sh_stats = step_sh.lower(stacked, opt_sh, batch).compile().memory_analysis()

    # replicated: every device holds p0+p1 (+opt twin). sharded: [S, maxP]
    # total across devices = 2*maxP, i.e. per-device maxP < p0+p1
    assert sh_stats.argument_size_in_bytes < rep_stats.argument_size_in_bytes, (
        sh_stats.argument_size_in_bytes, rep_stats.argument_size_in_bytes,
    )


@pytest.mark.parametrize("stages", [3, 4])
@needs_vma_grad
def test_het_pipeline_s3_s4_equals_serial(stages, devices8):
    """The S-generic ResNet stage split (round-5 lift of the S<=2 cap):
    the S-stage pipelined loss and grads equal the serial composition of
    the same stages — the reference's flagship 3-stage topology
    (lab/s01_b2_dp_pp.py:22-29) is now expressible on the benchmark
    workload."""
    from ddl25spring_tpu.models.resnet import make_resnet_stages

    S = stages
    mods = make_resnet_stages(S, width=W)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
    params, shapes, h = [], [], x[:1]
    for i, sm in enumerate(mods):
        p = sm.init(jax.random.PRNGKey(i), h)["params"]
        h = sm.apply({"params": p}, h)
        params.append(p)
        shapes.append(h.shape)
    params = tuple(params)

    def serial(ps, batch):
        h = batch["x"]
        for sm, p in zip(mods, ps):
            h = sm.apply({"params": p}, h)
        return cross_entropy_logits(h, batch["y"])

    mesh = make_mesh(devices8[:S], stage=S)
    M, mb = 2, 4
    fns = [
        (lambda sm: lambda p, h: sm.apply({"params": p}, h))(sm)
        for sm in mods
    ]
    pipe = make_het_pipeline_loss(
        fns, lambda logits, b: cross_entropy_logits(logits, b["y"]),
        (mb, 32, 32, 3), [(mb,) + s[1:] for s in shapes], mesh, M,
    )
    batch = {"x": x, "y": y}
    np.testing.assert_allclose(
        float(jax.jit(pipe)(params, batch)),
        float(serial(params, batch)),
        rtol=1e-5,
    )
    g_pipe = jax.jit(jax.grad(pipe))(params, batch)
    g_serial = jax.grad(serial)(params, batch)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=5e-4, rtol=5e-3
        ),
        g_serial,
        g_pipe,
    )


@needs_vma_grad
def test_build_resnet_step_s3(devices8):
    """build_resnet_step at the reference flagship topology (dp=2, S=3):
    one step runs on a (data=2, stage=3) mesh and the loss is finite."""
    from ddl25spring_tpu.benchmarks import build_resnet_step

    step, params, opt_state, meta = build_resnet_step(
        devices8[:6], dp=2, S=3, num_microbatches=2, batch=8,
        dtype=jnp.float32,
    )
    assert meta["n_chips"] == 6
    assert "stage=3" in meta["topology"]
    x = np.zeros((8, 32, 32, 3), np.uint8)
    y = np.zeros((8,), np.int32)
    _, _, loss = step(params, opt_state, (jnp.asarray(x), jnp.asarray(y)))
    assert np.isfinite(float(loss))
