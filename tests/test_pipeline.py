"""Pipeline-parallel correctness.

The oracle (SURVEY §4): the pipelined, microbatched, stage-sharded program
must match the unpartitioned model — loss AND gradients — under the same
params and batch.  This subsumes the reference's eyeball-the-loss-files
verification of ``s01_b1_microbatches.py`` / ``s01_b2_dp_pp.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops.losses import causal_lm_loss
from ddl25spring_tpu.parallel.pipeline import (
    make_grad_accum_step,
    make_pipeline_loss,
    make_pipeline_train_step,
    shard_staged_params,
)
from ddl25spring_tpu.utils.config import LlamaConfig
from ddl25spring_tpu.utils.mesh import make_mesh

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=4, ctx_size=16, dtype="float32"
)


def serial_loss(params, tokens):
    return causal_lm_loss(llama.llama_forward(params, tokens, CFG), tokens)


@pytest.fixture(scope="module")
def params_and_tokens():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    return params, tokens


@pytest.mark.parametrize("stages,microbatches", [(2, 3), (4, 2), (2, 6)])
def test_pipeline_loss_equals_serial(params_and_tokens, stages, microbatches, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:stages], stage=stages)
    staged = llama.split_blocks_for_stages(params, stages)
    pipe_loss = make_pipeline_loss(CFG, mesh, microbatches)
    l_pipe = float(jax.jit(pipe_loss)(staged, tokens))
    l_serial = float(serial_loss(params, tokens))
    np.testing.assert_allclose(l_pipe, l_serial, rtol=1e-5)


def test_pipeline_grads_equal_serial(params_and_tokens, devices8):
    params, tokens = params_and_tokens
    S, M = 2, 3
    mesh = make_mesh(devices8[:S], stage=S)
    staged = llama.split_blocks_for_stages(params, S)
    pipe_loss = make_pipeline_loss(CFG, mesh, M)

    g_pipe = jax.jit(jax.grad(pipe_loss))(staged, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)

    g_pipe_merged = llama.merge_blocks_from_stages(g_pipe)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        g_pipe_merged,
    )


def test_dp_pp_2d_mesh_equals_serial(params_and_tokens, devices8):
    """The flagship topology: 2 pipelines x 2 stages on a 2-D mesh
    (reference shape: ``s01_b2_dp_pp.py:22-34`` with world=6; here 2x2)."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:4], data=2, stage=2)
    staged = llama.split_blocks_for_stages(params, 2)
    pipe_loss = make_pipeline_loss(CFG, mesh, 3, data_axis="data")

    l_pipe = float(jax.jit(pipe_loss)(staged, tokens))
    l_serial = float(serial_loss(params, tokens))
    np.testing.assert_allclose(l_pipe, l_serial, rtol=1e-5)

    g_pipe = llama.merge_blocks_from_stages(
        jax.jit(jax.grad(pipe_loss))(staged, tokens)
    )
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        g_pipe,
    )


def test_pipeline_train_step_loss_decreases(devices8):
    mesh = make_mesh(devices8[:2], stage=2)
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    staged = shard_staged_params(
        llama.split_blocks_for_stages(params, 2), mesh
    )
    tx = optax.adam(1e-3)
    opt_state = tx.init(staged)
    step = make_pipeline_train_step(CFG, tx, mesh, num_microbatches=3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    losses = []
    for _ in range(15):
        staged, opt_state, loss = step(staged, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_grad_accum_equals_full_batch():
    """Microbatch grad accumulation == full-batch step (linearity), the
    standalone capability of s01_b1 without the stage split."""
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    tx = optax.sgd(0.1)

    def loss_fn(p, batch, key):
        return causal_lm_loss(llama.llama_forward(p, batch, CFG), batch)

    accum = make_grad_accum_step(loss_fn, tx, num_microbatches=3)
    p_a, _, l_a = accum(params, tx.init(params), tokens, jax.random.PRNGKey(2))

    g_full = jax.grad(lambda p: loss_fn(p, tokens, None))(params)
    p_f = jax.tree.map(lambda p, g: p - 0.1 * g, params, g_full)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4
        ),
        p_a,
        p_f,
    )
