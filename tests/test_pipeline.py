"""Pipeline-parallel correctness.

The oracle (SURVEY §4): the pipelined, microbatched, stage-sharded program
must match the unpartitioned model — loss AND gradients — under the same
params and batch.  This subsumes the reference's eyeball-the-loss-files
verification of ``s01_b1_microbatches.py`` / ``s01_b2_dp_pp.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops.losses import causal_lm_loss
from ddl25spring_tpu.parallel.pipeline import (
    make_1f1b_value_and_grad,
    make_grad_accum_step,
    make_interleaved_pipeline_loss,
    make_pipeline_loss,
    make_pipeline_train_step,
    shard_staged_params,
)
from ddl25spring_tpu.utils.compat import HAS_VMA
from ddl25spring_tpu.utils.config import LlamaConfig
from ddl25spring_tpu.utils.mesh import make_mesh

# The homogeneous pipeline schedules lean on VMA-typed shard_map autodiff
# (pcast-varying carries, collectives under lax.cond); pre-VMA jax traces
# them into _SpecError / wrong-transpose territory — not worth 6 minutes
# of CI to confirm on every run.  DP, ZeRO, TP, SP, EP, and het-pipeline
# FORWARD suites run on both; het-pipeline grad tests carry their own
# per-test skip (tests/test_het_pipeline.py::needs_vma_grad).
pytestmark = pytest.mark.skipif(
    not HAS_VMA,
    reason="homogeneous pipeline schedules need VMA-typed shard_map "
    "(lax.pcast); this jax predates it",
)

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=4, ctx_size=16, dtype="float32"
)


def serial_loss(params, tokens):
    return causal_lm_loss(llama.llama_forward(params, tokens, CFG), tokens)


@pytest.fixture(scope="module")
def params_and_tokens():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    return params, tokens


@pytest.mark.parametrize("stages,microbatches", [(2, 3), (4, 2), (2, 6)])
def test_pipeline_loss_equals_serial(params_and_tokens, stages, microbatches, devices8):
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:stages], stage=stages)
    staged = llama.split_blocks_for_stages(params, stages)
    pipe_loss = make_pipeline_loss(CFG, mesh, microbatches)
    l_pipe = float(jax.jit(pipe_loss)(staged, tokens))
    l_serial = float(serial_loss(params, tokens))
    np.testing.assert_allclose(l_pipe, l_serial, rtol=1e-5)


def test_pipeline_grads_equal_serial(params_and_tokens, devices8):
    params, tokens = params_and_tokens
    S, M = 2, 3
    mesh = make_mesh(devices8[:S], stage=S)
    staged = llama.split_blocks_for_stages(params, S)
    pipe_loss = make_pipeline_loss(CFG, mesh, M)

    g_pipe = jax.jit(jax.grad(pipe_loss))(staged, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)

    g_pipe_merged = llama.merge_blocks_from_stages(g_pipe)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        g_pipe_merged,
    )


def test_dp_pp_2d_mesh_equals_serial(params_and_tokens, devices8):
    """The flagship topology: 2 pipelines x 2 stages on a 2-D mesh
    (reference shape: ``s01_b2_dp_pp.py:22-34`` with world=6; here 2x2)."""
    params, tokens = params_and_tokens
    mesh = make_mesh(devices8[:4], data=2, stage=2)
    staged = llama.split_blocks_for_stages(params, 2)
    pipe_loss = make_pipeline_loss(CFG, mesh, 3, data_axis="data")

    l_pipe = float(jax.jit(pipe_loss)(staged, tokens))
    l_serial = float(serial_loss(params, tokens))
    np.testing.assert_allclose(l_pipe, l_serial, rtol=1e-5)

    g_pipe = llama.merge_blocks_from_stages(
        jax.jit(jax.grad(pipe_loss))(staged, tokens)
    )
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        g_pipe,
    )


def test_pipeline_train_step_loss_decreases(devices8):
    mesh = make_mesh(devices8[:2], stage=2)
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    staged = shard_staged_params(
        llama.split_blocks_for_stages(params, 2), mesh
    )
    tx = optax.adam(1e-3)
    opt_state = tx.init(staged)
    step = make_pipeline_train_step(CFG, tx, mesh, num_microbatches=3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    losses = []
    for _ in range(15):
        staged, opt_state, loss = step(staged, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("stages,microbatches,dp", [(2, 3, 1), (4, 2, 1), (2, 4, 2)])
def test_1f1b_equals_gpipe_and_serial(
    params_and_tokens, stages, microbatches, dp, devices8
):
    """The 1F1B schedule (hand-rolled backward, bounded activation stash)
    must produce the same loss and gradients as GPipe and the serial model
    (the reference's 1F1B chain generalized: ``intro_PP_1F1B.py:50-95``)."""
    params, tokens = params_and_tokens
    B = 2 * microbatches * dp  # divisible by M, with M-chunks divisible by dp
    tokens = jnp.tile(tokens, (-(-B // tokens.shape[0]), 1))[:B]
    devs = devices8[: stages * dp]
    data_axis = "data" if dp > 1 else None
    mesh = (
        make_mesh(devs, data=dp, stage=stages)
        if dp > 1
        else make_mesh(devs, stage=stages)
    )
    staged = llama.split_blocks_for_stages(params, stages)

    l_1f1b, g_1f1b = jax.jit(
        make_1f1b_value_and_grad(CFG, mesh, microbatches, data_axis=data_axis)
    )(staged, tokens)
    l_gpipe, g_gpipe = jax.jit(
        jax.value_and_grad(
            make_pipeline_loss(CFG, mesh, microbatches, data_axis=data_axis)
        )
    )(staged, tokens)

    np.testing.assert_allclose(float(l_1f1b), float(l_gpipe), rtol=1e-5)
    np.testing.assert_allclose(
        float(l_1f1b), float(serial_loss(params, tokens)), rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-5, rtol=2e-4
        ),
        g_gpipe,
        g_1f1b,
    )
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_from_stages(g_1f1b),
    )


@pytest.mark.parametrize("moe", [False, True])
def test_1f1b_residual_stash_equals_remat_and_serial(
    params_and_tokens, moe, devices8
):
    """The non-remat 1F1B (stash='residuals': pullback residuals ring-
    stashed via closure_convert, no forward recompute) must match the
    remat schedule and the serial model exactly — VERDICT r3 #5."""
    S, M = 2, 3
    cfg = MOE_CFG if moe else CFG
    mesh = make_mesh(devices8[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    l_res, g_res = jax.jit(
        make_1f1b_value_and_grad(cfg, mesh, M, stash="residuals")
    )(staged, tokens)
    l_in, g_in = jax.jit(
        make_1f1b_value_and_grad(cfg, mesh, M, stash="input")
    )(staged, tokens)

    np.testing.assert_allclose(float(l_res), float(l_in), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-5, rtol=2e-4
        ),
        g_in,
        g_res,
    )
    if moe:
        l_serial = float(serial_moe_loss(params, tokens, M))
    else:
        l_serial = float(
            causal_lm_loss(llama.llama_forward(params, tokens, cfg), tokens)
        )
    np.testing.assert_allclose(float(l_res), l_serial, rtol=1e-5)


def test_1f1b_train_step_loss_decreases(devices8):
    mesh = make_mesh(devices8[:2], stage=2)
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    staged = shard_staged_params(llama.split_blocks_for_stages(params, 2), mesh)
    tx = optax.adam(1e-3)
    opt_state = tx.init(staged)
    step = make_pipeline_train_step(
        CFG, tx, mesh, num_microbatches=3, schedule="1f1b"
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    losses = []
    for _ in range(15):
        staged, opt_state, loss = step(staged, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpipe_remat_equals_plain_and_saves_memory(devices8):
    """``remat=True`` GPipe: same loss/grads, less compiled temp memory
    (scan saves carries only, recomputes block internals)."""
    cfg = LlamaConfig(
        vocab_size=128, dmodel=32, num_heads=2, n_layers=4, ctx_size=128,
        dtype="float32",
    )
    S, M = 2, 6
    mesh = make_mesh(devices8[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    staged = llama.split_blocks_for_stages(params, S)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (M, cfg.ctx_size), 0, 128)

    vg_plain = jax.jit(jax.value_and_grad(make_pipeline_loss(cfg, mesh, M)))
    vg_remat = jax.jit(
        jax.value_and_grad(make_pipeline_loss(cfg, mesh, M, remat=True))
    )
    (l0, g0), (l1, g1) = vg_plain(staged, tokens), vg_remat(staged, tokens)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4
        ),
        g0, g1,
    )
    m_plain = vg_plain.lower(staged, tokens).compile().memory_analysis()
    m_remat = vg_remat.lower(staged, tokens).compile().memory_analysis()
    assert m_remat.temp_size_in_bytes < m_plain.temp_size_in_bytes, (
        m_remat.temp_size_in_bytes, m_plain.temp_size_in_bytes,
    )


def test_1f1b_bounds_activation_memory(devices8):
    """The point of 1F1B: compiled temp memory is bounded in M.  GPipe's
    scan-transpose saves every tick's residuals (O(M) activations + block
    internals); 1F1B stashes only ``2S-1`` stage inputs and rematerializes.
    At ctx 256 / M=8 the compiled temp footprint must be several times
    smaller (measured 6.9x at ctx 1024 — RESULTS.md)."""
    cfg = LlamaConfig(
        vocab_size=128, dmodel=32, num_heads=2, n_layers=4, ctx_size=256,
        dtype="float32",
    )
    S, M = 2, 8
    mesh = make_mesh(devices8[:S], stage=S)
    staged = shard_staged_params(
        llama.split_blocks_for_stages(
            llama.init_llama_params(jax.random.PRNGKey(0), cfg), S
        ),
        mesh,
    )
    tx = optax.adam(1e-3)
    opt = tx.init(staged)
    tokens = jnp.zeros((M, cfg.ctx_size), jnp.int32)

    temps = {}
    for sched in ("gpipe", "1f1b"):
        step = make_pipeline_train_step(cfg, tx, mesh, M, schedule=sched)
        stats = step.lower(staged, opt, tokens).compile().memory_analysis()
        temps[sched] = stats.temp_size_in_bytes
    assert temps["1f1b"] * 2 < temps["gpipe"], temps


MOE_CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=4, ctx_size=16,
    dtype="float32", n_experts=4, capacity_factor=2.0,
)

# 4-head variant for TP tests (heads must divide the model axis)
CFG4H = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=4, n_layers=4, ctx_size=16,
    dtype="float32",
)


def serial_moe_loss(params, tokens, M):
    """Per-microbatch oracle: the pipeline's MoE dispatch groups are the
    ``[mb*L]`` token groups each stage sees, so the reference composite
    loss is the mean over microbatches of ``ce + w * aux`` from
    ``llama_forward_with_aux`` — routing (and any capacity drops) is then
    IDENTICAL on both sides, so equality is exact, not just ample-capacity."""
    B, L = tokens.shape
    mbs = tokens.reshape(M, B // M, L)

    def per_mb(mb):
        logits, aux = llama.llama_forward_with_aux(params, mb, MOE_CFG)
        return causal_lm_loss(logits, mb) + MOE_CFG.moe_aux_weight * aux

    return jnp.mean(jax.vmap(per_mb)(mbs))


def test_gpipe_moe_loss_and_grads_equal_serial(devices8):
    """Switch-MoE rides GPipe: aux loss accumulates through the scan carry
    (VERDICT r3 #3 — the flagship MoE-LLaMA x PP composition)."""
    S, M = 2, 3
    mesh = make_mesh(devices8[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    pipe_loss = make_pipeline_loss(MOE_CFG, mesh, M)
    l_pipe = float(jax.jit(pipe_loss)(staged, tokens))
    l_serial = float(serial_moe_loss(params, tokens, M))
    np.testing.assert_allclose(l_pipe, l_serial, rtol=1e-5)

    g_pipe = llama.merge_blocks_from_stages(
        jax.jit(jax.grad(pipe_loss))(staged, tokens)
    )
    g_serial = jax.grad(lambda p: serial_moe_loss(p, tokens, M))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        g_pipe,
    )


def test_1f1b_moe_equals_gpipe_and_serial(devices8):
    """The memory-bounded schedule carries the per-stage aux term too
    (uniform 1.0 loss-cotangent seed across stages)."""
    S, M = 2, 3
    mesh = make_mesh(devices8[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    l_1f1b, g_1f1b = jax.jit(
        make_1f1b_value_and_grad(MOE_CFG, mesh, M)
    )(staged, tokens)
    l_gpipe, g_gpipe = jax.jit(
        jax.value_and_grad(make_pipeline_loss(MOE_CFG, mesh, M))
    )(staged, tokens)

    np.testing.assert_allclose(float(l_1f1b), float(l_gpipe), rtol=1e-5)
    np.testing.assert_allclose(
        float(l_1f1b), float(serial_moe_loss(params, tokens, M)), rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-5, rtol=2e-4
        ),
        g_gpipe,
        g_1f1b,
    )
    g_serial = jax.grad(lambda p: serial_moe_loss(p, tokens, M))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_from_stages(g_1f1b),
    )


def test_moe_dp_pp_2d_mesh_equals_serial(devices8):
    """MoE x the flagship DP x PP topology on a 2-D mesh."""
    S, M = 2, 2
    mesh = make_mesh(devices8[:4], data=2, stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    pipe_loss = make_pipeline_loss(MOE_CFG, mesh, M, data_axis="data")
    l_pipe = float(jax.jit(pipe_loss)(staged, tokens))
    # DP shards the microbatch dim: each replica sees its own [mb] rows, so
    # the oracle groups are the M*dp per-replica microbatches
    l_serial = float(serial_moe_loss(params, tokens, M * 2))
    np.testing.assert_allclose(l_pipe, l_serial, rtol=1e-5)


@pytest.mark.parametrize("cf", [2.0, 0.5])
def test_ep_dp_pp_expert_sharded_equals_dense(cf, devices8):
    """EP x DP x PP: expert stacks sharded over the data axis, capacity
    buckets moved between data rows by all_to_all each tick.  Routing and
    capacity are decided per data shard BEFORE the a2a, so loss and grads
    are EXACTLY the replicated-expert pipeline's — at ample capacity
    (cf=2.0) and under heavy drops (cf=0.5) alike — while each device
    holds only E/n experts per stage."""
    import dataclasses

    cfg = dataclasses.replace(MOE_CFG, capacity_factor=cf)
    S, M = 2, 2
    mesh = make_mesh(devices8[:4], data=2, stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    dense_loss = make_pipeline_loss(cfg, mesh, M, data_axis="data")
    l_dense, g_dense = jax.jit(jax.value_and_grad(dense_loss))(staged, tokens)

    sharded = shard_staged_params(staged, mesh, ep_axis="data")
    w = sharded["blocks"]["moe"]["w_gate"]
    assert w.addressable_shards[0].data.shape[2] == cfg.n_experts // 2, (
        "expert stacks not sharded over the data axis"
    )
    ep_loss = make_pipeline_loss(
        cfg, mesh, M, data_axis="data", ep_axis="data"
    )
    l_ep, g_ep = jax.jit(jax.value_and_grad(ep_loss))(sharded, tokens)

    np.testing.assert_allclose(float(l_ep), float(l_dense), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-5, rtol=2e-4
        ),
        g_dense,
        g_ep,
    )


def test_ep_pipeline_train_step_and_guards(devices8):
    """The EP x DP x PP train step runs (loss falls over steps); EP and
    TP remain mutually exclusive in the staged specs."""
    S, M = 2, 2
    mesh = make_mesh(devices8[:4], data=2, stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    staged = shard_staged_params(
        llama.split_blocks_for_stages(params, S), mesh, ep_axis="data"
    )
    tx = optax.adam(1e-2)
    step = make_pipeline_train_step(
        MOE_CFG, tx, mesh, M, data_axis="data", ep_axis="data"
    )
    opt = tx.init(staged)
    losses = []
    toks = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)
    for _ in range(5):
        staged, opt, loss = step(staged, opt, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    with pytest.raises(NotImplementedError, match="exclusive"):
        make_pipeline_train_step(
            MOE_CFG, tx, mesh, M, data_axis="data", ep_axis="data",
            tp_axis="data",
        )


@pytest.mark.parametrize("schedule", ["interleaved", "interleaved-1f1b"])
def test_ep_interleaved_expert_sharded_equals_dense(schedule, devices8):
    """EP rides BOTH interleaved schedules (round-5 closure of the
    chunked-EP guard): the 5-d expert stacks shard their expert dim over
    the data axis, the per-tick a2a sits in uniform control flow (the
    interleaved tick runs its chunk unconditionally under EP), and loss
    + grads equal the dense replicated-expert run exactly — heavy drops
    included."""
    import dataclasses

    cfg = dataclasses.replace(MOE_CFG, capacity_factor=0.5)
    S, V, M, dp = 2, 2, 2, 2
    mesh = make_mesh(devices8[:4], data=dp, stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    staged = llama.split_blocks_interleaved(params, S, V)

    if schedule == "interleaved":
        def vag(ep_axis, p):
            return jax.jit(jax.value_and_grad(make_interleaved_pipeline_loss(
                cfg, mesh, M, V, data_axis="data", ep_axis=ep_axis
            )))(p, tokens)
    else:
        def vag(ep_axis, p):
            return jax.jit(make_1f1b_value_and_grad(
                cfg, mesh, M, data_axis="data", num_chunks=V,
                ep_axis=ep_axis,
            ))(p, tokens)

    l_dense, g_dense = vag(None, staged)
    sharded = shard_staged_params(staged, mesh, ep_axis="data", chunked=True)
    w = sharded["blocks"]["moe"]["w_gate"]
    assert w.addressable_shards[0].data.shape[3] == cfg.n_experts // dp
    l_ep, g_ep = vag("data", sharded)

    np.testing.assert_allclose(float(l_ep), float(l_dense), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-5, rtol=2e-4
        ),
        g_dense,
        g_ep,
    )


@pytest.mark.parametrize("cf,stash", [
    (2.0, "input"), (0.5, "input"), (2.0, "residuals"),
])
def test_ep_1f1b_expert_sharded_equals_dense(cf, stash, devices8):
    """EP x DP x PP under the 1F1B schedules: the forward slot runs the
    stage body unconditionally (output masked) so the EP all_to_all sits
    in uniform control flow, and expert-slice grads take the 1/n
    normalization.  Loss and grads must equal the dense replicated-expert
    1F1B run EXACTLY — ample capacity and heavy drops alike (routing and
    capacity are per data shard, decided before the a2a)."""
    import dataclasses

    cfg = dataclasses.replace(MOE_CFG, capacity_factor=cf)
    S, M = 2, 2
    mesh = make_mesh(devices8[:4], data=2, stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    l_dense, g_dense = jax.jit(
        make_1f1b_value_and_grad(
            cfg, mesh, M, data_axis="data", stash=stash
        )
    )(staged, tokens)

    sharded = shard_staged_params(staged, mesh, ep_axis="data")
    l_ep, g_ep = jax.jit(
        make_1f1b_value_and_grad(
            cfg, mesh, M, data_axis="data", stash=stash, ep_axis="data"
        )
    )(sharded, tokens)

    np.testing.assert_allclose(float(l_ep), float(l_dense), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-5, rtol=2e-4
        ),
        g_dense,
        g_ep,
    )
    # and the dense 1F1B itself is pinned to GPipe elsewhere; close the
    # loop cheaply against the serial per-microbatch oracle on the loss
    def oracle(p):
        mbs = tokens.reshape(M * 2, tokens.shape[0] // (M * 2), -1)

        def per_mb(mb):
            logits, aux = llama.llama_forward_with_aux(p, mb, cfg)
            return causal_lm_loss(logits, mb) + cfg.moe_aux_weight * aux

        return jnp.mean(jax.vmap(per_mb)(mbs))

    np.testing.assert_allclose(float(l_ep), float(oracle(params)), rtol=1e-5)


def test_grad_accum_equals_full_batch():
    """Microbatch grad accumulation == full-batch step (linearity), the
    standalone capability of s01_b1 without the stage split."""
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (6, 16), 0, 64)
    tx = optax.sgd(0.1)

    def loss_fn(p, batch, key):
        return causal_lm_loss(llama.llama_forward(p, batch, CFG), batch)

    accum = make_grad_accum_step(loss_fn, tx, num_microbatches=3)
    p_a, _, l_a = accum(params, tx.init(params), tokens, jax.random.PRNGKey(2))

    g_full = jax.grad(lambda p: loss_fn(p, tokens, None))(params)
    p_f = jax.tree.map(lambda p, g: p - 0.1 * g, params, g_full)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4
        ),
        p_a,
        p_f,
    )


@pytest.mark.parametrize("schedule", ["gpipe", "interleaved", "interleaved-1f1b"])
def test_fused_steps_equal_sequential(schedule, devices8):
    """fuse_train_steps(step, K) on [K, B, L] stacked batches must land on
    the same params/losses as K sequential dispatches of the same step
    (dispatch-amortization must not change semantics) — the fusion wraps
    ANY schedule, so both splitters/schedules share this harness."""
    from ddl25spring_tpu.parallel.pipeline import fuse_train_steps

    S, M, K = 2, 2, 3
    mesh = make_mesh(devices8[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(5), CFG)
    chunked = schedule.startswith("interleaved")
    if chunked:
        staged = llama.split_blocks_interleaved(params, S, 2)
    else:
        staged = llama.split_blocks_for_stages(params, S)
    tx = optax.sgd(0.05)
    # num_chunks only rides the interleaved schedules — passing it with
    # gpipe now raises (the round-4 advisor's silent-fallback finding)
    step = make_pipeline_train_step(
        CFG, tx, mesh, M, schedule=schedule,
        num_chunks=2 if chunked else 1,
    )
    tokens_k = jax.random.randint(jax.random.PRNGKey(6), (K, 4, 16), 0, 64)

    p_seq, o_seq = staged, tx.init(staged)
    seq_losses = []
    for i in range(K):
        p_seq, o_seq, loss = step(p_seq, o_seq, tokens_k[i])
        seq_losses.append(float(loss))

    multi = fuse_train_steps(step, K)
    p_fused, _, losses = multi(staged, tx.init(staged), tokens_k)

    np.testing.assert_allclose(np.asarray(losses), seq_losses, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4
        ),
        p_fused,
        p_seq,
    )


# ---------------------------------------------------------------- interleaved


def test_interleaved_split_merge_roundtrip():
    params = llama.init_llama_params(jax.random.PRNGKey(2), CFG)
    split = llama.split_blocks_interleaved(params, 2, 2)
    leaf = jax.tree.leaves(split["blocks"])[0]
    assert leaf.shape[:3] == (2, 2, 1)  # [S, V, Lc]
    back = llama.merge_blocks_interleaved(split)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), params, back
    )
    # chunk mapping: blocks[s][v] is global chunk v*S + s
    l0 = params["blocks"]["wq"]
    np.testing.assert_array_equal(split["blocks"]["wq"][1, 0, 0], l0[1])
    np.testing.assert_array_equal(split["blocks"]["wq"][0, 1, 0], l0[2])


@pytest.mark.parametrize("mbs", [2, 4])
def test_interleaved_loss_and_grads_equal_serial(
    params_and_tokens, mbs, devices8
):
    """The virtual-stage schedule (V=2 chunks/device) must match the
    serial model exactly — the tick algebra (slot -> (chunk, microbatch)
    map, single-ring delay-1 transfers, wrap-to-chunk-v+1) is all pinned
    by this equality."""
    params, tokens = params_and_tokens
    tokens = tokens[:4]  # B=4: divisible by both M values
    S, V = 2, 2
    mesh = make_mesh(devices8[:S], stage=S)
    staged = llama.split_blocks_interleaved(params, S, V)
    loss = make_interleaved_pipeline_loss(CFG, mesh, mbs, V)
    np.testing.assert_allclose(
        float(jax.jit(loss)(staged, tokens)),
        float(serial_loss(params, tokens)),
        rtol=1e-5,
    )
    g = jax.jit(jax.grad(loss))(staged, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_interleaved(g),
    )


def test_interleaved_rejects_indivisible_microbatches(devices8):
    mesh = make_mesh(devices8[:2], stage=2)
    with pytest.raises(ValueError, match="divisible"):
        make_interleaved_pipeline_loss(CFG, mesh, 3, 2)


def test_interleaved_dp_pp_train_step(params_and_tokens, devices8):
    """schedule='interleaved' on the 2-D (data, stage) mesh: one step
    equals the serial step."""
    params, tokens = params_and_tokens
    tokens = tokens[:4]
    S, V, M = 2, 2, 2
    mesh = make_mesh(devices8[:4], data=2, stage=S)
    staged = shard_staged_params(
        llama.split_blocks_interleaved(params, S, V), mesh
    )
    tx = optax.adam(1e-3)
    step = make_pipeline_train_step(
        CFG, tx, mesh, M, data_axis="data", schedule="interleaved",
        num_chunks=V,
    )
    new_params, _, loss = step(staged, tx.init(staged), tokens)

    sloss, g = jax.value_and_grad(serial_loss)(params, tokens)
    updates, _ = tx.update(g, tx.init(params), params)
    expect = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4
        ),
        llama.merge_blocks_interleaved(jax.device_get(new_params)),
        expect,
    )


def test_interleaved_moe_equals_serial(devices8):
    """Switch-MoE rides the interleaved schedule: per-(chunk, microbatch)
    dispatch groups are the per-layer-per-microbatch groups of the serial
    oracle, so equality is exact."""
    S, V, M = 2, 2, 2
    mesh = make_mesh(devices8[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    staged = llama.split_blocks_interleaved(params, S, V)
    loss = make_interleaved_pipeline_loss(MOE_CFG, mesh, M, V)
    np.testing.assert_allclose(
        float(jax.jit(loss)(staged, tokens)),
        float(serial_moe_loss(params, tokens, M)),
        rtol=1e-5,
    )
    g = jax.jit(jax.grad(loss))(staged, tokens)
    g_serial = jax.grad(lambda p: serial_moe_loss(p, tokens, M))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_interleaved(g),
    )


# ---------------------------------------------------------------- DPxPPxTP


@pytest.mark.parametrize("dp", [1, 2])
def test_pipeline_tp_equals_serial(params_and_tokens, dp, devices8):
    """Full 3-D parallelism (data, stage, model): Megatron TP inside each
    pipeline stage.  Loss AND sharded-weight grads must equal the serial
    model — the pmean-over-TP transpose and the in-block psums are what
    this pins."""
    params, tokens = params_and_tokens
    S, T = 2, 2
    tokens = tokens[:4]
    if dp > 1:
        mesh = make_mesh(devices8[: dp * S * T], data=dp, stage=S, model=T)
    else:
        mesh = make_mesh(devices8[: S * T], stage=S, model=T)
    staged = llama.split_blocks_for_stages(params, S)
    loss = make_pipeline_loss(
        CFG, mesh, 2, data_axis="data" if dp > 1 else None, tp_axis="model"
    )
    np.testing.assert_allclose(
        float(jax.jit(loss)(staged, tokens)),
        float(serial_loss(params, tokens)),
        rtol=1e-5,
    )
    g = jax.jit(jax.grad(loss))(staged, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_from_stages(g),
    )


def test_pipeline_tp_train_step_sharded_placement(params_and_tokens, devices8):
    """The 3-D train step with actually-sharded param placement: one step
    runs, block weights are placed over (stage, model), loss is finite."""
    import optax as _optax

    params, tokens = params_and_tokens
    tokens = tokens[:4]
    mesh = make_mesh(devices8, data=2, stage=2, model=2)
    staged = shard_staged_params(
        llama.split_blocks_for_stages(params, 2), mesh, tp_axis="model"
    )
    shard = staged["blocks"]["wq"].sharding.spec
    assert shard == jax.sharding.PartitionSpec("stage", None, None, "model")
    tx = _optax.adam(1e-3)
    step = make_pipeline_train_step(
        CFG, tx, mesh, 2, data_axis="data", tp_axis="model"
    )
    new_params, _, loss = step(staged, tx.init(staged), tokens)
    sloss = float(serial_loss(params, tokens))
    np.testing.assert_allclose(float(loss), sloss, rtol=1e-5)
    # the TP placement must SURVIVE the step — a train step that silently
    # drops tp_axis would return P('stage', ...) params (regression guard:
    # the first wiring of this feature did exactly that)
    out_spec = new_params["blocks"]["wq"].sharding.spec
    assert out_spec == jax.sharding.PartitionSpec(
        "stage", None, None, "model"
    ), out_spec
    # the 1F1B schedule accepts tp_axis through the SAME train-step
    # builder (regression guard on the pass-through at the vag dispatch):
    # loss == serial and the TP placement survives the optimizer step
    step1f = make_pipeline_train_step(
        CFG, tx, mesh, 2, data_axis="data", tp_axis="model",
        schedule="1f1b",
    )
    p1f, _, loss1f = step1f(staged, tx.init(staged), tokens)
    np.testing.assert_allclose(float(loss1f), sloss, rtol=1e-5)
    assert p1f["blocks"]["wq"].sharding.spec == jax.sharding.PartitionSpec(
        "stage", None, None, "model"
    )

    # the interleaved schedule composes with TP too: 5-d chunked specs
    # (chunked=True), loss == serial, placement survives the step
    staged_il = shard_staged_params(
        llama.split_blocks_interleaved(params, 2, 2), mesh,
        tp_axis="model", chunked=True,
    )
    assert staged_il["blocks"]["wq"].sharding.spec == (
        jax.sharding.PartitionSpec("stage", None, None, None, "model")
    )
    step_il = make_pipeline_train_step(
        CFG, tx, mesh, 2, data_axis="data", tp_axis="model",
        schedule="interleaved", num_chunks=2,
    )
    p_il, _, loss_il = step_il(staged_il, tx.init(staged_il), tokens)
    np.testing.assert_allclose(float(loss_il), sloss, rtol=1e-5)
    assert p_il["blocks"]["wq"].sharding.spec == (
        jax.sharding.PartitionSpec("stage", None, None, None, "model")
    )


def test_interleaved_tp_grads_equal_serial(params_and_tokens, devices8):
    """Interleaved virtual stages x Megatron TP: grads ≡ serial through
    the chunk-indexed TP blocks (the chunked 5-d specs must shard the
    OUTPUT dim of column weights, not the input dim)."""
    params, tokens = params_and_tokens
    tokens = tokens[:4]
    mesh = make_mesh(devices8[:4], stage=2, model=2)
    staged = llama.split_blocks_interleaved(params, 2, 2)
    loss = make_interleaved_pipeline_loss(CFG, mesh, 2, 2, tp_axis="model")
    np.testing.assert_allclose(
        float(jax.jit(loss)(staged, tokens)),
        float(serial_loss(params, tokens)),
        rtol=1e-5,
    )
    g = jax.jit(jax.grad(loss))(staged, tokens)
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_interleaved(g),
    )


@pytest.mark.parametrize("stash", ["input", "residuals"])
def test_1f1b_tp_equals_serial(params_and_tokens, stash, devices8):
    """TP inside the hand-rolled 1F1B backward: the cooperative vjp runs
    the in-block psum transposes across TP members, and the final 1/t
    normalization (see make_1f1b_value_and_grad) makes loss AND grads
    equal the serial model — both stash variants, on the 3-D mesh."""
    params, tokens = params_and_tokens
    tokens = tokens[:4]
    mesh = make_mesh(devices8, data=2, stage=2, model=2)
    staged = llama.split_blocks_for_stages(params, 2)
    l, g = jax.jit(
        make_1f1b_value_and_grad(
            CFG, mesh, 2, data_axis="data", stash=stash, tp_axis="model"
        )
    )(staged, tokens)
    np.testing.assert_allclose(
        float(l), float(serial_loss(params, tokens)), rtol=1e-5
    )
    g_serial = jax.grad(serial_loss)(params, tokens)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_from_stages(g),
    )


@pytest.mark.parametrize("cf", [2.0, 0.5])
def test_pipeline_tp_moe_equals_serial(cf, devices8):
    """Switch-MoE under pipeline TP on the full (data, stage, model) mesh:
    expert stacks shard their expert dim over the tp axis
    (staged_param_specs n_experts schema), routing stays global per
    (data-shard, stage, microbatch) group via make_tp_moe_fn, and the
    block's row-parallel psum completes the partial combine — so loss and
    grads equal the serial per-microbatch oracle EXACTLY, at ample
    capacity (cf=2.0) and under heavy drops (cf=0.5) alike."""
    import dataclasses

    cfg = dataclasses.replace(MOE_CFG, capacity_factor=cf)
    S, T, dp, M = 2, 2, 2, 2
    mesh = make_mesh(devices8[: dp * S * T], data=dp, stage=S, model=T)
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    # sharpen router margins: TP's psum reorders fp summation by ulps,
    # and with the near-uniform init logits a ulp can flip a near-tie
    # routing decision under tight capacity — the test pins the drop
    # MECHANISM (global capacity, identical bucketing on every shard),
    # not fp tie-breaking, so give the router decisive margins
    params["blocks"]["moe"]["router"] = (
        30.0 * params["blocks"]["moe"]["router"]
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    sharded = shard_staged_params(staged, mesh, tp_axis="model")
    w = sharded["blocks"]["moe"]["w_gate"]
    assert w.addressable_shards[0].data.shape[2] == cfg.n_experts // T, (
        "expert stacks not sharded over the model axis"
    )

    loss = make_pipeline_loss(
        cfg, mesh, M, data_axis="data", tp_axis="model"
    )
    l_pipe, g_pipe = jax.jit(jax.value_and_grad(loss))(sharded, tokens)

    # per-microbatch oracle at THIS cf (serial_moe_loss is pinned to
    # MOE_CFG's ample capacity): dp shards the microbatch dim -> M*dp
    # per-replica dispatch groups
    def oracle(p):
        mbs = tokens.reshape(M * dp, tokens.shape[0] // (M * dp), -1)

        def per_mb(mb):
            logits, aux = llama.llama_forward_with_aux(p, mb, cfg)
            return causal_lm_loss(logits, mb) + cfg.moe_aux_weight * aux

        return jnp.mean(jax.vmap(per_mb)(mbs))

    l_serial = float(oracle(params))
    np.testing.assert_allclose(float(l_pipe), l_serial, rtol=1e-5)

    g_serial = jax.grad(oracle)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_from_stages(g_pipe),
    )


@pytest.mark.parametrize("stash", ["input", "residuals"])
def test_1f1b_tp_moe_equals_serial(stash, devices8):
    """MoE x TP inside the hand-rolled 1F1B backward: the router grad is
    replicated across tp (pmean re-typing) while the expert slices follow
    the 1/t matmul normalization — pinned against the serial oracle, for
    both the remat and residual-stash backward variants."""
    S, T, M = 2, 2, 2
    mesh = make_mesh(devices8[: S * T], stage=S, model=T)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    staged = llama.split_blocks_for_stages(params, S)

    l, g = jax.jit(
        make_1f1b_value_and_grad(
            MOE_CFG, mesh, M, tp_axis="model", stash=stash
        )
    )(staged, tokens)
    l_serial = float(serial_moe_loss(params, tokens, M))
    np.testing.assert_allclose(float(l), l_serial, rtol=1e-5)
    g_serial = jax.grad(lambda p: serial_moe_loss(p, tokens, M))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_from_stages(g),
    )


def test_interleaved_tp_moe_equals_serial(devices8):
    """MoE x TP x the interleaved virtual-stage schedule: the chunked
    5-d expert stacks shard their expert dim over tp."""
    S, V, M, T = 2, 2, 2, 2
    mesh = make_mesh(devices8[: S * T], stage=S, model=T)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    staged = llama.split_blocks_interleaved(params, S, V)
    loss = make_interleaved_pipeline_loss(
        MOE_CFG, mesh, M, V, tp_axis="model"
    )
    np.testing.assert_allclose(
        float(jax.jit(loss)(staged, tokens)),
        float(serial_moe_loss(params, tokens, M)),
        rtol=1e-5,
    )
    g = jax.jit(jax.grad(loss))(staged, tokens)
    g_serial = jax.grad(lambda p: serial_moe_loss(p, tokens, M))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_interleaved(g),
    )


# ------------------------------------------------------- interleaved 1F1B


@pytest.mark.parametrize("stages,chunks,microbatches,dp,tp", [
    (2, 2, 2, 1, 1),
    (2, 3, 4, 1, 1),
    (4, 2, 4, 1, 1),
    (2, 2, 4, 2, 2),
])
def test_interleaved_1f1b_equals_serial(
    stages, chunks, microbatches, dp, tp, devices8
):
    """The production Megatron schedule — interleaved virtual stages WITH
    the memory-bounded hand-rolled 1F1B backward: loss and grads must
    equal the serial model across chunk counts, stage counts, and the
    full DP x PP x TP composition (the backward stream's reversed slot
    map and ring indexing are what this pins)."""
    S, V, M = stages, chunks, microbatches
    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=S * V, ctx_size=16,
        dtype="float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (M * dp * 2, 16), 0, 64
    )

    def serial(p):
        return causal_lm_loss(llama.llama_forward(p, tokens, cfg), tokens)

    kw = {}
    names = {"stage": S}
    if dp > 1:
        names = {"data": dp, "stage": S}
        kw["data_axis"] = "data"
    if tp > 1:
        names["model"] = tp
        kw["tp_axis"] = "model"
    mesh = make_mesh(devices8[: S * dp * tp], **names)
    staged = llama.split_blocks_interleaved(params, S, V)
    l, g = jax.jit(
        make_1f1b_value_and_grad(cfg, mesh, M, num_chunks=V, **kw)
    )(staged, tokens)
    np.testing.assert_allclose(float(l), float(serial(params)), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        jax.grad(serial)(params),
        llama.merge_blocks_interleaved(g),
    )


def test_interleaved_1f1b_moe_equals_serial(devices8):
    """Switch-MoE rides interleaved 1F1B: every (chunk, microbatch)
    backward slot banks its chunk's weighted aux term."""
    S, V, M = 2, 2, 2
    mesh = make_mesh(devices8[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    staged = llama.split_blocks_interleaved(params, S, V)
    l, g = jax.jit(
        make_1f1b_value_and_grad(MOE_CFG, mesh, M, num_chunks=V)
    )(staged, tokens)
    np.testing.assert_allclose(
        float(l), float(serial_moe_loss(params, tokens, M)), rtol=1e-5
    )
    g_serial = jax.grad(lambda p: serial_moe_loss(p, tokens, M))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        g_serial,
        llama.merge_blocks_interleaved(g),
    )


def test_interleaved_1f1b_bounds_activation_memory(devices8):
    """The point of composing the two schedules: at V=2 the interleaved
    scan-transpose saves every chunk-tick's residuals (O(M·V)); the
    interleaved 1F1B ring-stashes 2VS-1 chunk inputs and rematerializes —
    compiled temp memory must be several times smaller at M=8."""
    cfg = LlamaConfig(
        vocab_size=128, dmodel=32, num_heads=2, n_layers=4, ctx_size=256,
        dtype="float32",
    )
    S, V, M = 2, 2, 8
    mesh = make_mesh(devices8[:S], stage=S)
    staged = shard_staged_params(
        llama.split_blocks_interleaved(
            llama.init_llama_params(jax.random.PRNGKey(0), cfg), S, V
        ),
        mesh, chunked=True,
    )
    tx = optax.adam(1e-3)
    opt = tx.init(staged)
    tokens = jnp.zeros((M, cfg.ctx_size), jnp.int32)

    temps = {}
    for sched in ("interleaved", "interleaved-1f1b"):
        step = make_pipeline_train_step(
            cfg, tx, mesh, M, schedule=sched, num_chunks=V
        )
        stats = step.lower(staged, opt, tokens).compile().memory_analysis()
        temps[sched] = stats.temp_size_in_bytes
    assert temps["interleaved-1f1b"] * 2 < temps["interleaved"], temps


def test_interleaved_1f1b_train_step_and_guards(devices8):
    """The train-step builder dispatches the interleaved-1f1b schedule
    (loss falls over steps) and the guards hold: residual stash and EP
    are not wired for chunked stacks, num_chunks >= 2 required."""
    S, V, M = 2, 2, 2
    mesh = make_mesh(devices8[:S], stage=S)
    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=S * V, ctx_size=16,
        dtype="float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    staged = shard_staged_params(
        llama.split_blocks_interleaved(params, S, V), mesh, chunked=True
    )
    tx = optax.adam(1e-2)
    step = make_pipeline_train_step(
        cfg, tx, mesh, M, schedule="interleaved-1f1b", num_chunks=V
    )
    opt = tx.init(staged)
    toks = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)
    losses = []
    for _ in range(5):
        staged, opt, loss = step(staged, opt, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    with pytest.raises(NotImplementedError, match="residual"):
        make_1f1b_value_and_grad(
            cfg, mesh, M, stash="residuals", num_chunks=V
        )
    with pytest.raises(ValueError, match="num_chunks"):
        make_pipeline_train_step(
            cfg, tx, mesh, M, schedule="interleaved-1f1b", num_chunks=1
        )
    with pytest.raises(ValueError, match="divisible"):
        make_1f1b_value_and_grad(cfg, mesh, 3, num_chunks=V)


# ------------------------------------------------------- SP inside the pipe


@pytest.mark.parametrize("mode,dp,flash", [
    ("ring", 1, False),
    ("ring", 2, True),
    ("ulysses", 1, False),
    ("ulysses", 2, False),
])
def test_pipeline_sp_equals_serial(mode, dp, flash, devices8):
    """Sequence parallelism INSIDE pipeline stages (round-5 closure of
    the SP x PP hole): tokens shard their length dim over a seq axis,
    every stage runs ring/Ulysses attention at global positions, targets
    come from one pre-scan boundary ppermute, and loss + grads equal the
    serial model on the (data, stage, seq) mesh."""
    import dataclasses

    cfg = dataclasses.replace(CFG, use_flash=flash)
    S, sq, M = 2, 2, 2
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)

    def serial(p):
        return causal_lm_loss(llama.llama_forward(p, tokens, cfg), tokens)

    names = (
        {"data": dp, "stage": S, "seq": sq} if dp > 1
        else {"stage": S, "seq": sq}
    )
    mesh = make_mesh(devices8[: S * sq * dp], **names)
    staged = llama.split_blocks_for_stages(params, S)
    loss = make_pipeline_loss(
        cfg, mesh, M, data_axis="data" if dp > 1 else None,
        seq_axis="seq", sp_mode=mode,
    )
    l, g = jax.jit(jax.value_and_grad(loss))(staged, tokens)
    np.testing.assert_allclose(float(l), float(serial(params)), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        jax.grad(serial)(params),
        llama.merge_blocks_from_stages(g),
    )


def test_pipeline_sp_train_step_and_guards(devices8):
    """The train-step builder threads seq_axis (gpipe only); the guarded
    compositions raise instead of silently deadlocking or mis-training."""
    S, sq, M = 2, 2, 2
    mesh = make_mesh(devices8[: S * sq], stage=S, seq=sq)
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    staged = shard_staged_params(
        llama.split_blocks_for_stages(params, S), mesh
    )
    tx = optax.adam(1e-2)
    step = make_pipeline_train_step(CFG, tx, mesh, M, seq_axis="seq")
    opt = tx.init(staged)
    toks = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)
    losses = []
    for _ in range(5):
        staged, opt, loss = step(staged, opt, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    with pytest.raises(NotImplementedError, match="residual"):
        make_pipeline_train_step(
            CFG, tx, mesh, M, seq_axis="seq", schedule="1f1b-stash"
        )
    with pytest.raises(NotImplementedError, match="dense"):
        make_1f1b_value_and_grad(MOE_CFG, mesh, M, seq_axis="seq")


@pytest.mark.parametrize("tp", [1, 2])
def test_pipeline_sp_moe_equals_sp_oracle(tp, devices8):
    """Switch-MoE under SP x PP (round 5), with and without TP inside
    the stages: per-(seq-shard, layer, microbatch) dispatch groups with
    the aux term on its OWN scan carry (the CE slot holds
    token-count-normalized sums under seq — one denominator cannot
    serve both).  The oracle is make_sp_loss itself, per microbatch on
    a seq-only mesh: identical routing groups and the identical
    sharded-MoE aux estimator, so equality is exact (TP members compute
    identical global routing, so the same oracle serves tp > 1)."""
    from ddl25spring_tpu.parallel.sp import make_sp_loss

    S, sq, M = 2, 2, 2
    cfg = (
        LlamaConfig(
            vocab_size=64, dmodel=32, num_heads=4, n_layers=4,
            ctx_size=16, dtype="float32", n_experts=4,
            capacity_factor=2.0,
        )
        if tp > 1 else MOE_CFG
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    names = {"stage": S, "seq": sq}
    kw = {}
    if tp > 1:
        names["model"] = tp
        kw["tp_axis"] = "model"
    mesh = make_mesh(devices8[: S * sq * tp], **names)
    staged = llama.split_blocks_for_stages(params, S)
    loss = make_pipeline_loss(cfg, mesh, M, seq_axis="seq", **kw)
    l, g = jax.jit(jax.value_and_grad(loss))(staged, tokens)

    mesh_sq = make_mesh(devices8[:sq], seq=sq)
    sp_loss = make_sp_loss(cfg, mesh_sq, seq_axis="seq")

    def oracle(p):
        mbs = tokens.reshape(M, tokens.shape[0] // M, -1)
        return jnp.mean(
            jnp.stack([sp_loss(p, mbs[m]) for m in range(M)])
        )

    np.testing.assert_allclose(float(l), float(oracle(params)), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        jax.device_get(jax.grad(oracle)(params)),
        jax.device_get(llama.merge_blocks_from_stages(g)),
    )


@pytest.mark.parametrize("mode,num_chunks,tp", [
    ("ring", 1, 1), ("ulysses", 1, 1), ("ring", 2, 1),
    ("ring", 1, 2), ("ulysses", 1, 2), ("ring", 2, 2),
])
def test_sp_1f1b_equals_serial(mode, num_chunks, tp, devices8):
    """SP under the hand-rolled 1F1B backwards (plain AND interleaved
    chunks, AND composed with TP): sequence-sharded stages with
    ring/Ulysses attention, the forward slot running unconditionally
    (masked) so the seq collectives stay uniform, blocks pcast varying
    over seq so the final psum-over-seq assembles each shard's local
    grad paths exactly once (the TP 1/t normalization then composes
    unchanged) — loss and grads equal the serial model."""
    S, sq, M, V = 2, 2, 2, num_chunks
    cfg = CFG4H if tp > 1 else CFG
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)

    def serial(p):
        return causal_lm_loss(llama.llama_forward(p, tokens, cfg), tokens)

    names = {"stage": S, "seq": sq}
    kw = {}
    if tp > 1:
        names["model"] = tp
        kw["tp_axis"] = "model"
    mesh = make_mesh(devices8[: S * sq * tp], **names)
    staged = (
        llama.split_blocks_interleaved(params, S, V) if V > 1
        else llama.split_blocks_for_stages(params, S)
    )
    l, g = jax.jit(
        make_1f1b_value_and_grad(
            cfg, mesh, M, seq_axis="seq", sp_mode=mode, num_chunks=V, **kw
        )
    )(staged, tokens)
    np.testing.assert_allclose(float(l), float(serial(params)), rtol=1e-5)
    merged = (
        llama.merge_blocks_interleaved(g) if V > 1
        else llama.merge_blocks_from_stages(g)
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        jax.grad(serial)(params),
        merged,
    )


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_pipeline_sp_tp_equals_serial(mode, devices8):
    """The full PP x SP x TP composition on a (stage, seq, model) mesh:
    Megatron-split matmuls operate on the per-shard head subset, ring /
    Ulysses attention runs over the seq axis within each stage, and loss
    + grads equal the serial model."""
    cfg = CFG4H
    S, sq, T, M = 2, 2, 2, 2
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)

    def serial(p):
        return causal_lm_loss(llama.llama_forward(p, tokens, cfg), tokens)

    mesh = make_mesh(devices8[:8], stage=S, seq=sq, model=T)
    staged = llama.split_blocks_for_stages(params, S)
    loss = make_pipeline_loss(
        cfg, mesh, M, seq_axis="seq", sp_mode=mode, tp_axis="model"
    )
    l, g = jax.jit(jax.value_and_grad(loss))(staged, tokens)
    np.testing.assert_allclose(float(l), float(serial(params)), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=2e-4, rtol=2e-3
        ),
        jax.grad(serial)(params),
        llama.merge_blocks_from_stages(g),
    )
