"""Test harness: simulate an 8-device TPU mesh on CPU.

The TPU-world analogue of the reference's gloo-on-localhost fake cluster
(SURVEY §4): ``--xla_force_host_platform_device_count=8`` gives every test a
multi-device mesh without hardware.

XLA_FLAGS must be set before the CPU backend initializes; the platform
selection must be forced through ``jax.config`` because this image's
sitecustomize registers a TPU plugin at interpreter start (before conftest),
so the ``JAX_PLATFORMS`` env var alone is too late.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Buffer donation is ON by default in every train-step builder
# (parallel/dp.donate_argnums), which (correctly) invalidates the input
# trees after a call.  The equivalence-oracle tests feed one params tree
# through several independent steps, so the suite opts out here; the
# donation contract itself is pinned explicitly (donate=True) in
# tests/test_bucketing.py and through every describe() hook in
# tests/test_xla_analytics.py.
os.environ.setdefault("DDL25_DONATE", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest failed to fake 8 CPU devices"
    return devs[:8]


# ----------------------------------------------- lower-once compile caches
#
# Compiles are the suite's wall-clock budget (ROADMAP: ~770 s against an
# 870 s ceiling on the 2-core CI host).  Every test that needs a
# registered strategy's compile-time report MUST ride this session cache
# — one compile per strategy per test session, shared across
# test_xla_analytics (signature pins), test_hlo_lint (clean baselines),
# and test_sched (overlap-bound pins).  The generic `lower_once` memo is
# the same pattern for ad-hoc lowerings (test_health's sentinel-mode
# HLO texts).

_strategy_reports: dict = {}
_lowered_once: dict = {}


def cached_strategy_report(name: str) -> dict:
    """Compile + analyze one registered strategy, once per session.
    ``keep_hlo=True``: the report carries the optimized-HLO text, so the
    bitwise rule-table pins and the sharding-flow walks
    (test_shard_flow.py) reuse this one compile instead of paying their
    own."""
    from ddl25spring_tpu.obs import xla_analytics as xa

    if name not in _strategy_reports:
        _strategy_reports[name] = xa.compile_strategy(name, keep_hlo=True)
    r = _strategy_reports[name]
    assert "error" not in r, f"{name} failed to compile: {r.get('error')}"
    return r


@pytest.fixture(scope="session")
def strategy_report():
    """The shared compile-once cache, as a fixture: tests call
    ``strategy_report(name)`` and share one ``compile_strategy`` result
    per strategy across every test module in the session."""
    return cached_strategy_report


def cached_lowering(key, build):
    """Generic memoized-lowering cache: runs ``build()`` on first use of
    ``key`` and replays the result after — for expensive lowerings that
    aren't registry strategies (e.g. the sentinel-mode HLO texts in
    test_health)."""
    if key not in _lowered_once:
        _lowered_once[key] = build()
    return _lowered_once[key]


@pytest.fixture(scope="session")
def lower_once():
    """:func:`cached_lowering`, as a fixture."""
    return cached_lowering
