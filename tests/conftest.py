"""Test harness: simulate an 8-device TPU mesh on CPU.

The TPU-world analogue of the reference's gloo-on-localhost fake cluster
(SURVEY §4): ``--xla_force_host_platform_device_count=8`` gives every test a
multi-device mesh without hardware.

XLA_FLAGS must be set before the CPU backend initializes; the platform
selection must be forced through ``jax.config`` because this image's
sitecustomize registers a TPU plugin at interpreter start (before conftest),
so the ``JAX_PLATFORMS`` env var alone is too late.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Buffer donation is ON by default in every train-step builder
# (parallel/dp.donate_argnums), which (correctly) invalidates the input
# trees after a call.  The equivalence-oracle tests feed one params tree
# through several independent steps, so the suite opts out here; the
# donation contract itself is pinned explicitly (donate=True) in
# tests/test_bucketing.py and through every describe() hook in
# tests/test_xla_analytics.py.
os.environ.setdefault("DDL25_DONATE", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest failed to fake 8 CPU devices"
    return devs[:8]
