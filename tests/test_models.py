"""Model shape/behavior checks (the reference ships no tests — SURVEY §4 —
so shapes are pinned here against the reference architectures)."""

import jax
import jax.numpy as jnp
import numpy as np

from ddl25spring_tpu.models.heart_mlp import HeartDiseaseNN
from ddl25spring_tpu.models.mnist_cnn import MnistCnn


def test_mnist_cnn_shapes_and_logprobs():
    model = MnistCnn()
    x = jnp.zeros((4, 28, 28, 1))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (4, 10)
    # log_softmax rows sum to 1 in prob space
    np.testing.assert_allclose(np.exp(out).sum(-1), np.ones(4), rtol=1e-5)
    # flatten feeds 9216 features into fc1, per hfl_complete.py:47
    assert variables["params"]["Dense_0"]["kernel"].shape == (9216, 128)


def test_mnist_cnn_dropout_needs_rng_and_differs():
    model = MnistCnn()
    x = jnp.ones((2, 28, 28, 1))
    variables = model.init(jax.random.PRNGKey(0), x)
    a = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)})
    b = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
    assert not np.allclose(a, b)


def test_heart_mlp_shapes():
    model = HeartDiseaseNN()
    x = jnp.zeros((8, 30))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (8, 2)
    shapes = [
        variables["params"][f"Dense_{i}"]["kernel"].shape for i in range(4)
    ]
    assert shapes == [(30, 64), (64, 128), (128, 256), (256, 2)]
