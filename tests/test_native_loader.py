"""Native C++ loader: build, parse-equivalence vs the numpy path,
determinism, and epoch coverage — on a generated CIFAR-10 binary fixture."""

import numpy as np
import pytest

from ddl25spring_tpu.data.cifar10 import MEAN, STD
from ddl25spring_tpu.data.native_loader import (
    NativeCifar10Loader,
    NativeLoaderUnavailable,
)

N = 64  # records in the fixture file


@pytest.fixture(scope="module")
def bin_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cifar_bin")
    rng = np.random.default_rng(7)
    recs = []
    for i in range(N):
        label = np.array([i % 10], np.uint8)
        pixels = rng.integers(0, 256, 3072, dtype=np.uint8)  # CHW bytes
        recs.append(np.concatenate([label, pixels]))
    (d / "data_batch_1.bin").write_bytes(np.concatenate(recs).tobytes())
    return d


def _numpy_reference(path):
    raw = np.frombuffer(
        (path / "data_batch_1.bin").read_bytes(), np.uint8
    ).reshape(-1, 3073)
    y = raw[:, 0].astype(np.int32)
    x = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    x = (x.astype(np.float32) / 255.0 - MEAN) / STD
    return x, y


def test_native_matches_numpy_normalization(bin_dir):
    try:
        loader = NativeCifar10Loader(bin_dir, batch_size=N, seed=0, workers=1)
    except NativeLoaderUnavailable as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    x_ref, y_ref = _numpy_reference(bin_dir)
    x, y = next(iter(loader))
    assert loader.num_samples == N
    assert sorted(y.tolist()) == sorted(y_ref.tolist())

    # batch 0 is a permutation of the file: denormalizing recovers the exact
    # uint8 pixels, which identify each record unambiguously
    def debytes(arr):  # [32,32,3] normalized -> raw byte tuple
        px = np.rint((arr * STD + MEAN) * 255.0).clip(0, 255).astype(np.uint8)
        return px.tobytes()

    ref_by_key = {
        (int(y_ref[i]), debytes(x_ref[i])): x_ref[i] for i in range(N)
    }
    assert len(ref_by_key) == N
    for i in range(N):
        key = (int(y[i]), debytes(x[i]))
        assert key in ref_by_key, f"record {i} not found in reference"
        np.testing.assert_allclose(x[i], ref_by_key[key], atol=2e-5)
    loader.close()


def test_native_deterministic_and_epochs(bin_dir):
    try:
        a = NativeCifar10Loader(bin_dir, batch_size=16, seed=3, workers=2)
        b = NativeCifar10Loader(bin_dir, batch_size=16, seed=3, workers=1)
    except NativeLoaderUnavailable as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    ita, itb = iter(a), iter(b)
    seen = []
    for _ in range(N // 16 + 2):  # crosses an epoch boundary
        xa, ya = next(ita)
        xb, yb = next(itb)
        np.testing.assert_array_equal(ya, yb)
        np.testing.assert_allclose(xa, xb, atol=0)
        seen.append(ya)
    # first epoch covered every record exactly once
    first_epoch = np.concatenate(seen[: N // 16])
    assert len(first_epoch) == N
    counts = np.bincount(first_epoch, minlength=10)
    assert counts.sum() == N and counts.max() == N // 10 + (N % 10 > 0)
    a.close()
    b.close()


def test_raw_mode_matches_device_normalization(bin_dir):
    try:
        raw = NativeCifar10Loader(
            bin_dir, batch_size=16, seed=5, workers=1, normalize=False
        )
        ref = NativeCifar10Loader(bin_dir, batch_size=16, seed=5, workers=1)
    except NativeLoaderUnavailable as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    from ddl25spring_tpu.data.native_loader import normalize_on_device

    xr, yr = next(iter(raw))
    xf, yf = next(iter(ref))
    assert xr.dtype == np.uint8
    np.testing.assert_array_equal(yr, yf)
    np.testing.assert_allclose(
        np.asarray(normalize_on_device(xr)), xf, atol=1e-5
    )
    raw.close()
    ref.close()


def test_missing_dir_raises(tmp_path):
    with pytest.raises(NativeLoaderUnavailable):
        NativeCifar10Loader(tmp_path / "nope", batch_size=8)
