"""graft-trace (``ddl25spring_tpu/obs/timeline.py`` + serve wiring +
``tools/trace_export.py``): the unified run timeline.

The load-bearing pins:

- **schema** — every declared event kind round-trips strict JSON
  through ``timeline.jsonl`` with its required payload fields, and the
  envelope ``seq`` is strictly monotone (the contract ROADMAP-5's
  FL/RL workloads emit into).
- **TTFT decomposition sums to TTFT** — ``queue_wait + prefill +
  first_decode == ttft`` exactly on the virtual clock (float-exact by
  construction), within float tolerance on the wall clock.
- **zero cost when off** — with ``DDL25_OBS=0`` the engine's token
  streams and virtual clock are BITWISE identical to an instrumented
  run, and the serve decode tick lowers to byte-identical HLO.
- **the elastic handoff narrates completely** — device_loss emits
  drain / per-request handoff / reshape / reshape_end events, and
  ``trace_export --check`` proves no admitted request's span chain is
  left without a terminal.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.obs import state
from ddl25spring_tpu.obs.recorder import flight
from ddl25spring_tpu.obs.timeline import (
    EVENT_KINDS,
    MIRRORED_FLIGHT_KINDS,
    read_timeline,
    timeline,
)
from ddl25spring_tpu.serve.engine import Reservoir, ServeEngine
from ddl25spring_tpu.utils.config import LlamaConfig

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


def make_engine(params, **kw):
    # the test_serve smoke geometry: every compiled program rides the
    # session-wide _PROGRAM_CACHE shared with tests/test_serve.py
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


@pytest.fixture()
def tl(tmp_path):
    """The module-singleton timeline, configured at a fresh dir and
    handed back reset afterwards (other tests share the singleton)."""
    timeline.configure(str(tmp_path))
    try:
        yield timeline
    finally:
        timeline.configure(None)


# ------------------------------------------------------- schema pins


def _fill(fields):
    return {
        f: ("device_loss" if f == "reason" else 1) for f in fields
    }


def test_every_event_kind_round_trips_strict_json(tl, tmp_path):
    with state.scoped(True):
        for kind, req in EVENT_KINDS.items():
            tl.emit(kind, vt=0.5, engine="t", replica=0, **_fill(req))
        tl.flush()
    header, events = read_timeline(str(tmp_path))
    assert header["time_origin_unix_s"] > 0
    assert header["capacity"] == tl._ring.maxlen
    assert len(events) == len(EVENT_KINDS)
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    by_kind = {e["kind"]: e for e in events}
    assert set(by_kind) == set(EVENT_KINDS)
    for kind, req in EVENT_KINDS.items():
        e = by_kind[kind]
        for f in req:
            assert f in e, f"{kind} lost required field {f}"
        # the envelope every event carries
        assert e["record"] == "event"
        assert isinstance(e["t_wall_s"], float)
        assert e["vt_s"] == 0.5 and e["engine"] == "t"
        assert e["replica"] == 0
    assert tl.counts() == {k: 1 for k in EVENT_KINDS}


def test_emit_is_gated_and_typed(tl):
    # disabled -> no-op before any validation (zero cost when off)
    assert state.enabled() is False
    assert tl.emit("serve_submit", rid=1) is None
    assert tl.emit("no_such_kind") is None
    assert tl.events() == []
    with state.scoped(True):
        with pytest.raises(ValueError, match="unknown timeline event"):
            tl.emit("no_such_kind")
        with pytest.raises(ValueError, match="missing required"):
            tl.emit("serve_submit", rid=1)  # prompt_len/max_new absent


def test_non_finite_payloads_stay_strict_json(tl, tmp_path):
    """A NaN in a payload is stringified (the flight `_json_safe`
    idiom), never written as a bare NaN literal — the strict reader
    must always be able to load the file."""
    with state.scoped(True):
        tl.emit("serve_submit", rid=1, prompt_len=4,
                max_new=float("nan"))
        tl.flush()
    _, events = read_timeline(str(tmp_path))
    assert events[0]["max_new"] == "nan"


def test_flight_tap_mirrors_only_narrating_kinds(tl):
    assert "chaos" in MIRRORED_FLIGHT_KINDS
    assert "serve_tick" not in MIRRORED_FLIGHT_KINDS
    with state.scoped(True):
        flight.record(kind="chaos", fault="device_loss", step=2)
        flight.record(kind="serve_tick", step=3)
    mirrored = tl.events("chaos")
    assert len(mirrored) == 1
    assert mirrored[0]["fault"] == "device_loss"
    # the flight envelope is renamed so the timeline's own wins
    assert "flight_seq" in mirrored[0]
    assert tl.events("serve_tick") == []
    # disabled -> the tap emits nothing
    flight.record(kind="chaos", fault="bit_flip", step=4)
    assert len(tl.events("chaos")) == 1


# ------------------------------------------------- Reservoir (sat. 2)


def test_reservoir_below_cap_is_exact_ordered_list():
    r = Reservoir(cap=8)
    for x in [3.0, 1.0, 2.0]:
        r.append(x)
    assert list(r) == [3.0, 1.0, 2.0]
    assert len(r) == 3 and bool(r)
    assert r[0] == 3.0 and r[-1] == 2.0 and r[:2] == [3.0, 1.0]
    s = r.summary()
    assert s["count"] == 3 and s["sampled"] == 3
    assert s["max"] == 3.0 and s["min"] == 1.0 and s["mean"] == 2.0


def test_reservoir_caps_memory_but_keeps_exact_extremes():
    r = Reservoir(cap=16)
    n = 10_000
    for i in range(n):
        r.append(float(i))
    assert len(r) == 16          # host memory bounded
    assert r.count == n          # exact count over the full series
    assert r.max == float(n - 1) and r.min == 0.0
    assert r.summary()["mean"] == pytest.approx((n - 1) / 2)
    assert not r or all(0.0 <= x <= n - 1 for x in r)


def test_reservoir_clear_restores_deterministic_sampling():
    a, b = Reservoir(cap=4), Reservoir(cap=4)
    for x in range(100):
        a.append(float(x))
        b.append(float(x))
    assert list(a) == list(b)  # seeded: same series, same sample
    kept = list(a)
    a.clear()
    assert len(a) == 0 and a.count == 0 and not a
    for x in range(100):
        a.append(float(x))
    assert list(a) == kept  # clear() re-arms the same RNG stream


def test_reservoir_tolerates_non_numeric_entries():
    r = Reservoir(cap=4)
    r.append((0.1, 0.2, 0.3))  # the ttft_decomp triple
    r.append((0.4, 0.5, 0.6))
    assert r.count == 2 and r.max is None and r.total == 0.0


# ------------------------------------- serve lifecycle + decomposition


def _run_traced(params, *, clock, n_req=4):
    eng = make_engine(params, clock=clock, prefill_batch=2)
    eng.warmup()
    with state.scoped(True):
        for i in range(n_req):
            req = eng.make_request([5 + i, 9, 11, 3], 6)
            assert eng.submit(req) is None
        drain(eng)
    return eng


def test_ttft_decomposition_sums_exactly_on_virtual_clock(params):
    timeline.configure(None)
    eng = _run_traced(params, clock="virtual")
    assert eng.ttft_decomp.count == len(eng.ttft_s) == 4
    for ttft, (q, p, f) in zip(eng.ttft_s, eng.ttft_decomp):
        assert q >= 0 and p >= 0
        # virtual clock: the parts re-assemble the whole EXACTLY
        assert q + p + f == pytest.approx(ttft, abs=1e-12)
    cell = eng.ttft_decomp_cell()
    assert cell["clock"] == "virtual" and cell["requests"] == 4
    for k in ("queue_wait_s_p50", "queue_wait_s_p95", "prefill_s_p50",
              "prefill_s_p95", "first_decode_s_p50",
              "first_decode_s_p95"):
        assert isinstance(cell[k], float)


def test_ttft_decomposition_sums_on_wall_clock_within_tolerance(params):
    timeline.configure(None)
    eng = _run_traced(params, clock="wall")
    assert eng.ttft_decomp.count == len(eng.ttft_s) == 4
    for ttft, (q, p, f) in zip(eng.ttft_s, eng.ttft_decomp):
        assert q + p + f == pytest.approx(ttft, abs=1e-6)


def test_request_lifecycle_events_ordered_and_vt_monotone(params):
    timeline.configure(None)
    eng = _run_traced(params, clock="virtual")
    # request-lifecycle events only: mem_sample shares the engine tag
    # but is resource telemetry, not request-scoped (no rid)
    evs = [e for e in timeline.events()
           if e.get("engine") == "serve" and e["kind"] != "mem_sample"]
    counts = {}
    for e in evs:
        counts[e["kind"]] = counts.get(e["kind"], 0) + 1
    assert counts["serve_submit"] == 4
    assert counts["serve_admit"] == 4
    assert counts["serve_prefill"] == 4
    assert counts["serve_first_token"] == 4
    assert counts["serve_done"] == 4
    # the virtual clock never runs backwards within a replica
    vts = [e["vt_s"] for e in evs if e.get("replica") == 0]
    assert vts == sorted(vts)
    # per-request ordering: submit < admit <= prefill <= first < done
    for rid in {e["rid"] for e in evs}:
        kinds = [e["kind"] for e in evs if e["rid"] == rid]
        assert kinds.index("serve_submit") < kinds.index("serve_admit")
        assert kinds.index("serve_admit") <= kinds.index("serve_prefill")
        assert kinds.index("serve_prefill") <= kinds.index(
            "serve_first_token")
        assert kinds.index("serve_first_token") < kinds.index(
            "serve_done")
    # the first_token event carries the decomposition, re-summing
    for e in evs:
        if e["kind"] == "serve_first_token":
            assert e["ttft_s"] == pytest.approx(
                e["queue_wait_s"] + e["prefill_s"]
                + e["first_decode_s"], abs=2e-6)
    assert eng.generated_tokens > 0


def test_reject_event_carries_reason(params):
    timeline.configure(None)
    eng = make_engine(params)
    with state.scoped(True):
        req = eng.make_request([1] * 9, 4)  # > max_prompt_len=8
        assert eng.submit(req) is not None
    (ev,) = timeline.events("serve_reject")
    assert ev["rid"] == req.rid and ev["reason"] == "bad_request"


def test_trace_label_none_keeps_engine_off_the_timeline(params):
    timeline.configure(None)
    eng = make_engine(params, trace_label=None)
    with state.scoped(True):
        req = eng.make_request([5, 9, 11, 3], 4)
        assert eng.submit(req) is None
        drain(eng)
    assert timeline.events() == []  # the A/B-arm discipline
    assert len(req.tokens) == 4


# ------------------------------------------------ zero cost when off


def test_disabled_run_is_bitwise_identical(params):
    """DDL25_OBS=0 leaves token streams AND the virtual clock bitwise
    unchanged — emission is host-only and consumes no RNG."""

    def run(on: bool, run_dir=None):
        eng = make_engine(params, prefill_batch=2)
        with state.scoped(on):
            if on:
                timeline.configure(run_dir)
            reqs = [
                eng.make_request([5 + i, 9, 11, 3], 6) for i in range(3)
            ]
            for r in reqs:
                assert eng.submit(r) is None
            drain(eng)
        return [r.tokens for r in reqs], eng.now(), eng._vtime

    base_tokens, base_now, base_vt = run(False)
    timeline.configure(None)
    on_tokens, on_now, on_vt = run(True)
    timeline.configure(None)
    assert on_tokens == base_tokens
    assert on_now == base_now and on_vt == base_vt


def test_decode_tick_hlo_identical_when_disabled(params):
    """The serve decode tick — the newly span-instrumented dispatch —
    lowers to byte-identical HLO whether telemetry is on or off: all
    PR 16 instrumentation is host-side."""
    from ddl25spring_tpu.serve import kv_pages
    from ddl25spring_tpu.serve.engine import make_decode_tick

    pool = kv_pages.init_page_pool(
        CFG, n_pages=16, page_len=4, max_slots=2, pages_per_seq=4,
    )
    args = (
        params, pool, jnp.zeros((2,), jnp.int32), jax.random.PRNGKey(0),
    )

    def lower():
        tick = make_decode_tick(CFG, temperature=0.0, sentinel=False)
        return jax.jit(tick).lower(*args).as_text()

    with state.scoped(False):
        off = lower()
    with state.scoped(True):
        on = lower()
    assert on == off


# --------------------------------------- elastic handoff + exporter


def test_elastic_handoff_narrates_drain_reshape_and_chains(
    params, tmp_path
):
    """device_loss mid-traffic: the timeline carries the drain, every
    per-request handoff leg, the (mirrored) reshape and its window-end
    — and the exporter's chain check proves no admitted request was
    left without a terminal serve_done."""
    from ddl25spring_tpu.ft.chaos import ChaosInjector, parse_chaos
    from ddl25spring_tpu.serve.driver import elastic_serve_run
    from tools.trace_export import check_chains, merge

    knobs = dict(
        page_len=4, n_pages=16, max_slots=2, prefill_batch=2,
        max_prompt_len=8, max_queue=32, token_budget=None, eos_id=None,
        prefix_cache=False, spec_k=0, draft_layers=1,
    )
    prompt_a, new_a = [5, 9, 11, 3], 9
    prompt_b, new_b = [7, 2, 8], 6
    trace = [
        {"t": 0.0, "prompt": prompt_a, "max_new": new_a},
        {"t": 0.0, "prompt": prompt_b, "max_new": new_b},
        {"t": 0.001, "prompt": prompt_a, "max_new": new_a},
        {"t": 0.001, "prompt": prompt_b, "max_new": new_b},
        {"t": 0.002, "prompt": prompt_a, "max_new": new_a},
        {"t": 0.002, "prompt": prompt_b, "max_new": new_b},
    ]
    chaos = ChaosInjector(
        parse_chaos("device_loss@2"), state_dir=tmp_path / "chaos"
    )
    run_dir = tmp_path / "run"
    with state.scoped(True):
        timeline.configure(str(run_dir))
        try:
            cell = elastic_serve_run(
                params, CFG, trace, knobs, chaos=chaos, replicas=2,
            )
            timeline.flush()
        finally:
            timeline.configure(None)
    assert cell["dropped_requests"] == 0

    _, events = read_timeline(str(run_dir))
    counts = {}
    for e in events:
        counts[e["kind"]] = counts.get(e["kind"], 0) + 1
    assert counts.get("serve_submit") == 6
    assert counts.get("serve_drain", 0) >= 1
    # every requeued request got its own handoff leg, stamped with the
    # victim's stable replica id
    (drain_ev,) = [e for e in events if e["kind"] == "serve_drain"]
    assert counts.get("serve_drain_handoff", 0) == drain_ev["requeued"]
    for e in events:
        if e["kind"] == "serve_drain_handoff":
            assert e["from_replica"] == drain_ev["replica"]
    # the reshape arrives mirrored off the flight ring; its window end
    # is emitted directly when the victim finishes draining
    assert counts.get("reshape", 0) >= 1
    (end_ev,) = [e for e in events if e["kind"] == "reshape_end"]
    assert end_ev["reason"] == "device_loss"
    assert end_ev["t_end"] >= end_ev["t"]

    fails, stats = check_chains(events)
    assert fails == []
    assert stats["admitted"] == stats["complete"] > 0

    # the merged trace renders the window as a track-level span
    doc, _ = merge(str(run_dir))
    windows = [
        e for e in doc["traceEvents"]
        if e.get("cat") == "reshape_window" and e.get("ph") == "X"
    ]
    assert len(windows) == 1 and windows[0]["dur"] >= 1


def test_trace_export_merges_and_checks(params, tmp_path):
    """One obs-enabled engine run -> timeline.jsonl + trace.json ->
    trace_export writes one merged Perfetto doc whose request chains
    are complete (queue/prefill/decode X-slices + s/t/f flow arrows),
    and --check passes."""
    from ddl25spring_tpu.obs import spans
    from tools.trace_export import main as export_main

    run_dir = tmp_path / "run"
    with state.scoped(True):
        timeline.configure(str(run_dir))
        old_rec = spans.set_recorder(spans.SpanRecorder(
            process_name="test-serve"))
        try:
            eng = make_engine(params, prefill_batch=2)
            for i in range(3):
                assert eng.submit(
                    eng.make_request([5 + i, 9, 11, 3], 5)) is None
            drain(eng)
            timeline.flush()
            spans.get_recorder().save(str(run_dir / "trace.json"))
        finally:
            spans.set_recorder(old_rec)
            timeline.configure(None)

    assert export_main([str(run_dir), "--check"]) == 0
    with open(run_dir / "trace_merged.json") as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    x_names = [e["name"] for e in evs if e["ph"] == "X"]
    for name in ("queue", "prefill", "decode"):
        assert x_names.count(name) == 3
    # the host spans landed in the same doc, on the same axis
    assert "serve.decode_tick" in x_names
    assert "serve.prefill" in x_names
    # each request chain is flow-linked start/step/end
    for ph in ("s", "t", "f"):
        assert sum(1 for e in evs if e["ph"] == ph) == 3
    assert all(e.get("ts", 0) >= 0 for e in evs if e["ph"] != "M")


def test_trace_export_check_fails_on_orphan_admit(tmp_path):
    from tools.trace_export import main as export_main

    run_dir = tmp_path / "orphan"
    run_dir.mkdir()
    lines = [
        {"record": "timeline_header", "time_origin_unix_s": 1000.0,
         "capacity": 16, "pid": 1},
        {"record": "event", "seq": 0, "kind": "serve_submit",
         "t_wall_s": 0.0, "rid": 1, "prompt_len": 4, "max_new": 4,
         "engine": "serve", "replica": 0},
        {"record": "event", "seq": 1, "kind": "serve_admit",
         "t_wall_s": 0.1, "rid": 1, "slot": 0, "engine": "serve",
         "replica": 0},
        # no first_token, no terminal serve_done -> orphan
    ]
    with open(run_dir / "timeline.jsonl", "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    assert export_main([str(run_dir), "--check"]) == 1
    # without --check the same dir still exports (triage a torn run)
    assert export_main([str(run_dir)]) == 0


# ------------------------------------------------- report plumbing


def test_obs_report_folds_timeline_section(params, tmp_path):
    from ddl25spring_tpu.obs.report import format_report, summarize_run

    run_dir = tmp_path / "run"
    with state.scoped(True):
        timeline.configure(str(run_dir))
        try:
            eng = make_engine(params, prefill_batch=2)
            for i in range(3):
                assert eng.submit(
                    eng.make_request([5 + i, 9, 11, 3], 5)) is None
            drain(eng)
            timeline.flush()
        finally:
            timeline.configure(None)
    flight.dump(str(run_dir / "flight.json"), reason="test")
    summary = summarize_run(str(run_dir))
    tl_sum = summary["timeline"]
    assert tl_sum["counts"]["serve_first_token"] == 3
    assert 1 <= len(tl_sum["slowest_requests"]) <= 5
    slowest = tl_sum["slowest_requests"][0]
    assert slowest["ttft_s"] == max(
        r["ttft_s"] for r in tl_sum["slowest_requests"])
    for k in ("queue_wait_s", "prefill_s", "first_decode_s"):
        assert k in slowest
    text = format_report(summary)
    assert "timeline (timeline.jsonl" in text
    assert "slowest requests" in text
