"""Fault-tolerance layer (``ddl25spring_tpu/ft``): chaos injection,
resilient checkpointing, auto-resume, cross-mesh restore.

The central pins, per the recovery contract:

- **kill-and-resume equivalence**: a run SIGKILL'd mid-step by the
  chaos injector and relaunched lands BITWISE on the params of a run
  that never died (DP is deterministic; the restored data/rng cursors
  are load-bearing — a broken cursor would replay different batches);
- **SIGTERM drains the in-flight save**: the flight recorder's
  shutdown chain barriers the async checkpoint, so preemption never
  truncates the last save;
- **poisoned-checkpoint prevention**: a step the sentinels flagged
  non-finite is provably never persisted;
- **cross-mesh restore**: ZeRO-3 state saved on 8 devices restores and
  trains on 4, equivalent to the uninterrupted 8-way run, and the
  resumed step's collective signature re-pins via compile analytics.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.ft import (
    AutoSaver,
    ChaosInjector,
    DeviceLossError,
    Fault,
    latest_durable_step,
    parse_chaos,
    read_manifest,
    reshard_leaf,
    reshard_state,
    resume_bundle,
    write_manifest,
)
from ddl25spring_tpu.obs import flight, sentinels
from ddl25spring_tpu.utils.checkpoint import Checkpointer
from ddl25spring_tpu.utils.mesh import make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ chaos spec


def test_parse_chaos_grammar():
    assert parse_chaos(None) == ()
    assert parse_chaos("") == ()
    assert parse_chaos("sigterm@12") == (Fault("sigterm", 12),)
    assert parse_chaos("kill@7, nan_grad@5") == (
        Fault("kill", 7), Fault("nan_grad", 5),
    )
    # the PR-14 signal kinds (full matrix in tests/test_elastic.py)
    assert parse_chaos("traffic_spike@8:16,capacity_change@5:4") == (
        Fault("traffic_spike", 8, 16), Fault("capacity_change", 5, 4),
    )
    for bad in ("boom@3", "sigterm", "sigterm@", "sigterm@x", "sigterm@-1",
                "sigterm@5:2", "capacity_change@5:0"):
        with pytest.raises(ValueError):
            parse_chaos(bad)


def test_chaos_poison_device_loss_and_one_shot_journal(tmp_path):
    ci = ChaosInjector(
        parse_chaos("nan_grad@2,device_loss@3"), state_dir=tmp_path
    )
    batch = (jnp.ones((4, 3)), jnp.arange(4))
    x1, _ = ci.poison_batch(batch, 1)
    assert not np.isnan(np.asarray(x1)).any()  # wrong step: untouched
    x2, y2 = ci.poison_batch(batch, 2)
    assert np.isnan(np.asarray(x2)).all()
    np.testing.assert_array_equal(np.asarray(y2), np.arange(4))  # int leaf
    ci.on_step(1)  # nothing armed at 1
    with pytest.raises(DeviceLossError, match="device unreachable"):
        ci.on_step(3)
    # one-shot across relaunches: a new injector reading the same
    # journal must not re-fire either fault (a resumed run replays the
    # armed step index — re-firing would preempt forever)
    ci2 = ChaosInjector(
        parse_chaos("nan_grad@2,device_loss@3"), state_dir=tmp_path
    )
    ci2.on_step(3)  # no raise
    x3, _ = ci2.poison_batch(batch, 2)
    assert not np.isnan(np.asarray(x3)).any()
    # integer-only batches cannot carry the poison: skipped, still armed
    ci3 = ChaosInjector(parse_chaos("nan_grad@0"), state_dir=tmp_path / "b")
    (out,) = ci3.poison_batch((jnp.arange(4),), 0)
    np.testing.assert_array_equal(np.asarray(out), np.arange(4))
    assert ci3.pending("nan_grad")


def test_chaos_journal_tolerates_torn_line(tmp_path):
    """A SIGKILL mid-journal leaves a partial trailing line; every later
    relaunch must still arm (skipping the torn record) instead of
    crash-looping before training starts."""
    (tmp_path / "chaos_fired.jsonl").write_text(
        '{"fault": "sigterm@5"}\n{"fault": "ki'
    )
    ci = ChaosInjector(parse_chaos("sigterm@5,kill@7"), state_dir=tmp_path)
    assert [f.key for f in ci.pending()] == ["kill@7"]  # torn line skipped


def test_classify_failure_preempted_and_device_loss():
    import bench

    assert bench.classify_failure("whatever", rc=143) == "preempted"
    assert bench.classify_failure("whatever", rc=-15) == "preempted"
    assert bench.classify_failure("whatever", rc=-9) == "preempted"
    assert bench.classify_failure(
        "chaos: simulated device loss after step 9 — device unreachable"
    ) == "device_unreachable"
    assert bench.classify_failure("ValueError: nope", rc=1) == "runtime_error"
    # the parent's own timeout kill stays `stalled`, not preempted
    assert bench.classify_failure(
        "attempt 2: bench subprocess exceeded 2400s and was killed"
    ) == "stalled"


def test_flight_last_step_reader(tmp_path):
    import bench

    assert bench._flight_last_step(None) is None
    assert bench._flight_last_step(str(tmp_path / "missing.json")) is None
    p = tmp_path / "flight.json"
    p.write_text(json.dumps({"dumped_at_unix": 123.5, "records": [
        {"kind": "step", "step": 4, "wall_s": 0.1, "resumable": True},
        {"kind": "violation", "step": 9},    # not a step record
        {"kind": "step", "step": 11},        # sentinel record: no marker
        {"kind": "step", "step": 30, "wall_s": 0.1},  # secondary phase:
        # single-step units, no checkpoint alignment — must not count
        {"kind": "step", "step": 7, "wall_s": 0.1, "resumable": True},
    ]}))
    assert bench._flight_last_step(str(p)) == 7
    assert bench._flight_dump_facts(str(p)) == (123.5, 7)
    assert bench._flight_dump_facts(None) == (None, None)


# ---------------------------------------------------- manifest + durability


def test_manifest_atomicity_and_tmp_dirs_invisible(tmp_path):
    d = tmp_path / "ck"
    write_manifest(d, {"last_durable_step": 3})
    assert read_manifest(d)["last_durable_step"] == 3
    # a torn temp file from an interrupted writer is not the manifest
    (d / "manifest.json.tmp.999.1").write_text('{"last_durable')
    assert read_manifest(d)["last_durable_step"] == 3
    # a truncated manifest degrades to None, never an exception
    (d / "manifest.json").write_text('{"last_durable')
    assert read_manifest(d) is None
    # orbax commits by rename: only digit-named dirs are durable steps —
    # a save interrupted mid-write (still on its tmp name) is invisible
    (d / "3").mkdir()
    (d / "7.orbax-checkpoint-tmp-123").mkdir()
    assert latest_durable_step(d) == 3
    assert latest_durable_step(tmp_path / "nope") is None
    ck = Checkpointer(tmp_path / "ck2", async_save=False)
    ck.save(0, {"w": jnp.arange(2.0)})
    (tmp_path / "ck2" / "9.orbax-checkpoint-tmp-1").mkdir()
    assert ck.latest_step() == 0
    assert latest_durable_step(tmp_path / "ck2") == 0
    ck.close()


def test_checkpointer_wait_timeout_bounds_a_wedged_barrier(
    tmp_path, monkeypatch
):
    import time

    ck = Checkpointer(tmp_path / "c", async_save=True)
    ck.save(0, {"w": jnp.arange(4.0)})
    assert ck.wait_until_finished(timeout_s=120.0) is True
    # a wedged orbax thread must not outlive the watchdog: the bounded
    # wait reports failure instead of hanging the shutdown path
    monkeypatch.setattr(
        ck._mgr, "wait_until_finished", lambda: time.sleep(30)
    )
    assert ck.wait_until_finished(timeout_s=0.2) is False
    assert ck.close(timeout_s=0.2) is False
    # a barrier that RAISES (failed async save) is not "drained" either
    def _boom():
        raise OSError("disk full")

    monkeypatch.setattr(ck._mgr, "wait_until_finished", _boom)
    assert ck.wait_until_finished(timeout_s=5.0) is False


def test_close_without_save_preserves_prior_manifest(tmp_path):
    """A resumed process preempted again before its first save must not
    clobber the lineage's manifest — leaf_shapes is what the NEXT
    resume's cross-mesh path keys on."""
    a = AutoSaver(tmp_path / "ck", save_every=1, async_save=False)
    a.save(0, resume_bundle({"w": jnp.ones((4, 2))}, {}, data_cursor=1))
    a.close()
    man = read_manifest(tmp_path / "ck")
    assert man["leaf_shapes"] is not None
    saves_before = man["saves"]

    b = AutoSaver(tmp_path / "ck", save_every=1)
    b.close()  # the second preemption: shutdown hook, zero new saves
    man2 = read_manifest(tmp_path / "ck")
    assert man2["leaf_shapes"] == man["leaf_shapes"]
    assert man2["saves"] == saves_before
    assert man2["last_requested_step"] == 0
    assert man2["last_durable_step"] == 0


def test_flight_shutdown_hooks_run_before_dump(tmp_path):
    from ddl25spring_tpu.obs.recorder import FlightRecorder

    fr = FlightRecorder()
    fr.configure(run_dir=str(tmp_path))
    calls = []
    name = fr.register_shutdown(lambda: calls.append("hook"))
    fr.record(kind="step", step=0)
    fr._atexit_dump()
    assert calls == ["hook"]
    assert (tmp_path / "flight.json").exists()
    fr.unregister_shutdown(name)
    fr._atexit_dump()
    assert calls == ["hook"]  # unregistered: not run again


def test_restore_or_init_fresh_start(tmp_path):
    saver = AutoSaver(tmp_path / "ck", save_every=2)
    init = resume_bundle({"w": jnp.ones((2, 2))}, {"m": jnp.zeros((2, 2))},
                         data_cursor=0, rng_seed=1)
    state, start = saver.restore_or_init(init)
    assert start == 0
    assert state is init
    saver.close()


def test_device_dataset_cursor_roundtrip():
    from ddl25spring_tpu.benchmarks import DeviceDataset

    ds = DeviceDataset(16, n_train=64)
    ds.feed()
    ds.feed()
    c = ds.cursor
    x1, _ = ds.feed()
    ds.cursor = c  # the restore path: replay from the checkpointed cursor
    x2, _ = ds.feed()
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))
    assert ds.cursor == c + 1


# ----------------------------------------------------- sentinel-gated save


def test_sentinel_flagged_step_is_never_persisted(devices8, tmp_path):
    """The poisoned-checkpoint gate: step 2's batch is NaN-poisoned, the
    sentinels flag it (skip policy recovers the params on device), and
    the autosave layer provably never writes that step — while every
    clean neighbor IS on disk."""
    from ddl25spring_tpu.parallel.dp import make_dp_train_step

    sentinels.reset()
    skipped_before = flight.counts().get("save_skipped", 0)
    mesh = make_mesh(devices8[:2], data=2)
    tx = optax.sgd(0.1)
    params = {"w": jnp.full((8, 4), 0.5)}

    def loss_fn(p, batch, key):
        del key
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    x = jnp.ones((8, 8))
    y = jnp.ones((8, 4))
    key = jax.random.PRNGKey(0)
    saver = AutoSaver(
        tmp_path / "ck", save_every=1, max_to_keep=10, async_save=False
    )
    with sentinels.scoped(True, policy="skip"):
        step = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
        p, o = params, tx.init(params)
        for i in range(6):
            xb = x.at[0, 0].set(jnp.nan) if i == 2 else x
            p, o, loss = step(p, o, (xb, y), key)
            saver.maybe_save(
                i, resume_bundle(p, o, data_cursor=i + 1), loss=float(loss)
            )
    saver.close()
    steps = Checkpointer(tmp_path / "ck").steps()
    assert 2 not in steps, steps
    assert {0, 1, 3, 4, 5} <= set(steps)
    # skip policy: the poisoned update never reached the params either
    assert np.isfinite(np.asarray(p["w"])).all()
    assert flight.counts().get("save_skipped", 0) >= skipped_before + 1
    man = read_manifest(tmp_path / "ck")
    assert man["save_skipped"] >= 1
    assert man["last_durable_step"] == 5


# -------------------------------------------------- kill-and-resume (demo)


def _run_demo(tmp_path, ckpt, name, chaos=None, sync=True, steps=8):
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("DDL25_CHAOS", "XLA_FLAGS", "DDL25_SENTINELS")
    }
    if chaos:
        env["DDL25_CHAOS"] = chaos
    out = tmp_path / f"{name}.npz"
    cmd = [
        sys.executable, "-m", "ddl25spring_tpu.ft.demo",
        "--steps", str(steps), "--save-every", "2",
        "--ckpt-dir", str(tmp_path / ckpt),
        "--run-dir", str(tmp_path / f"run_{name}"),
        "--out", str(out),
    ]
    if sync:
        cmd.append("--sync-saves")
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=300, cwd=REPO, env=env
    )
    return r, out


def test_kill_and_resume_equivalence(tmp_path):
    """The headline pin: chaos SIGKILLs the run after step 6 (of 8); the
    relaunch restores step 5's checkpoint — params, opt state, data
    cursor, rng seed — replays 6..7, and lands BITWISE on the
    uninterrupted run's params.  Sensitive to every piece of the resume
    bundle: a dropped cursor or seed changes the replayed batches."""
    ref, ref_out = _run_demo(tmp_path, "ck_ref", "ref")
    assert ref.returncode == 0, ref.stderr[-2000:]

    killed, _ = _run_demo(tmp_path, "ck", "killed", chaos="kill@6")
    assert killed.returncode in (-9, 137), (
        killed.returncode, killed.stderr[-2000:]
    )
    # sync saves at steps 1, 3, 5 — all durable despite the SIGKILL
    assert latest_durable_step(tmp_path / "ck") == 5

    resumed, res_out = _run_demo(tmp_path, "ck", "resumed")
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    assert "FT-DEMO start=6" in resumed.stdout, resumed.stdout

    a, b = np.load(ref_out), np.load(res_out)
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


def test_sigterm_drains_the_inflight_checkpoint(tmp_path):
    """Lifecycle satellite: with ASYNC saves, SIGTERM at step 5 arrives
    while step 3's save may still be in flight.  The flight recorder's
    shutdown chain runs AutoSaver.close() — the bounded barrier — so
    the checkpoint commits instead of truncating, the manifest names it
    durable, and the flight dump records both the preemption and the
    durable step."""
    r, _ = _run_demo(
        tmp_path, "ck", "sigterm", chaos="sigterm@5", sync=False
    )
    assert r.returncode in (143, -15), (r.returncode, r.stderr[-2000:])
    man = read_manifest(tmp_path / "ck")
    assert man is not None
    assert man["last_durable_step"] == 3
    assert latest_durable_step(tmp_path / "ck") == 3
    fl = json.loads((tmp_path / "run_sigterm" / "flight.json").read_text())
    assert fl["reason"] == "sigterm"
    assert fl["meta"]["ckpt_last_durable_step"] == 3
    assert fl["counts"].get("chaos") == 1


# ------------------------------------------------------- cross-mesh restore


def test_reshard_refit_and_truncation_guard():
    true = np.arange(1, 38, dtype=np.float32)  # 37 nonzero elements
    saved = np.zeros(40, np.float32)
    saved[:37] = true
    saved = saved.reshape(8, 5)  # the n=8 shard layout (3 pad zeros)
    out = reshard_leaf(saved, jnp.zeros((4, 10), jnp.float32), "w")
    flat = np.asarray(out).reshape(-1)
    np.testing.assert_array_equal(flat[:37], true)
    assert flat[37:].sum() == 0
    # growing back onto the larger mesh round-trips exactly
    back = reshard_leaf(np.asarray(out), jnp.zeros((8, 5)), "w")
    np.testing.assert_array_equal(np.asarray(back), saved)
    # layer-stacked [L, n, k]: per-layer refit
    stacked = np.stack([saved, 2 * saved])
    out3 = reshard_leaf(stacked, jnp.zeros((2, 4, 10)), "blocks")
    np.testing.assert_array_equal(
        np.asarray(out3)[1].reshape(-1)[:37], 2 * true
    )
    # a template too small for the true data must refuse, loudly
    with pytest.raises(ValueError, match="nonzero"):
        reshard_leaf(saved, jnp.zeros((2, 10)), "w")  # 20 slots < 37
    with pytest.raises(ValueError, match="cannot reshard"):
        reshard_leaf(saved, jnp.zeros((40,)), "w")  # rank change
    out_t = reshard_state(
        {"a": saved, "c": np.int64(5)},
        {"a": jnp.zeros((4, 10)), "c": np.int64(0)},
    )
    assert int(out_t["c"]) == 5
    assert np.asarray(out_t["a"]).shape == (4, 10)


def test_cross_mesh_zero3_restore_8_to_4(devices8, tmp_path):
    """ZeRO-3 state saved on an 8-way mesh restores onto the surviving
    4-way mesh via the template-sharding path and trains on: the
    resumed trajectory is equivalent (suite tolerance) to the
    uninterrupted 8-way run — ZeRO's math is mesh-size-independent, so
    any divergence is a reshard bug.  The resumed step's collective
    signature is re-pinned through the compile analytics."""
    from ddl25spring_tpu.obs import xla_analytics as xa
    from ddl25spring_tpu.parallel import bucketing, zero

    k0 = jax.random.PRNGKey(0)
    params = {
        "w1": jax.random.normal(jax.random.fold_in(k0, 0), (12, 20)) * 0.1,
        "b1": jnp.zeros((20,)),
        "w2": jax.random.normal(jax.random.fold_in(k0, 1), (20, 4)) * 0.1,
    }

    def loss_fn(p, batch, key):
        del key
        x, yb = batch
        return jnp.mean((jnp.tanh(x @ p["w1"] + p["b1"]) @ p["w2"] - yb) ** 2)

    tx = optax.adam(1e-2)
    mesh8 = make_mesh(devices8, data=8)
    mesh4 = make_mesh(devices8[:4], data=4)
    step8 = zero.make_zero_dp_train_step(
        loss_fn, tx, mesh8, params, per_shard_rng=False
    )
    step4 = zero.make_zero_dp_train_step(
        loss_fn, tx, mesh4, params, per_shard_rng=False
    )
    key = jax.random.PRNGKey(1)
    batches = [
        (
            jax.random.normal(jax.random.fold_in(k0, 10 + i), (16, 12)),
            jax.random.normal(jax.random.fold_in(k0, 20 + i), (16, 4)),
        )
        for i in range(4)
    ]

    # uninterrupted: 4 steps on the 8-way mesh
    s_ref = zero.zero_shard_params(params, mesh8)
    o_ref = tx.init(s_ref)
    for b in batches:
        s_ref, o_ref, _ = step8(s_ref, o_ref, b, key)
    p_ref = zero.zero_unshard_params(s_ref, params)

    # interrupted: 2 steps on 8 devices, autosaved, then "the pod
    # shrinks" — restore on 4 and run the remaining 2 steps
    saver = AutoSaver(tmp_path / "ck", save_every=1, async_save=False)
    s, o = zero.zero_shard_params(params, mesh8), None
    o = tx.init(s)
    for i, b in enumerate(batches[:2]):
        s, o, _ = step8(s, o, b, key)
        assert saver.maybe_save(
            i, resume_bundle(s, o, data_cursor=i + 1, rng_seed=0)
        )
    saver.close()

    saver2 = AutoSaver(tmp_path / "ck", save_every=1)
    tmpl = zero.zero_resume_template(params, tx, mesh4)
    state, nxt = saver2.restore_or_init(resume_bundle(
        tmpl["params"], tmpl["opt_state"], data_cursor=0, rng_seed=0
    ))
    assert nxt == 2
    assert int(state["data_cursor"]) == 2  # the cursor crossed meshes too
    s4, o4 = state["params"], state["opt_state"]
    w1 = s4["w1"]
    assert w1.shape[0] == 4  # re-sharded [8, k] -> [4, k']
    assert (
        w1.sharding.spec == jax.tree.leaves(tmpl["params"])[0].sharding.spec
    )
    for b in batches[2:]:
        s4, o4, _ = step4(s4, o4, b, key)
    saver2.close()
    p_res = zero.zero_unshard_params(s4, params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        ),
        p_res, p_ref,
    )

    # re-pin the RESUMED step's collective signature (the acceptance
    # contract: cross-mesh restore must not change what the compiled
    # step launches) — same expected shape as zero.describe(stage=3)
    n = 4
    padded = sum(
        n * (-(-int(np.prod(l.shape) or 1) // n)) * 4
        for l in jax.tree.leaves(params)
    )
    launches = zero._row_plan(
        params, n, bucketing.DEFAULT_BUCKET_BYTES
    ).n_buckets
    compiled = step4.lower(s4, o4, batches[-1], key).compile()
    rep = xa.analyze_compiled(compiled, mesh4)
    expected = {
        "scalar_bytes": 64,
        "all-gather": {
            "min_bytes": padded, "max_bytes": 2 * padded + 256,
            "axes": ["data"],
            "min_count": launches, "max_count": 2 * launches,
        },
        "reduce-scatter": {
            "min_bytes": padded // n, "max_bytes": padded // n + 256,
            "axes": ["data"],
            "min_count": launches, "max_count": launches,
        },
        "all-reduce": {"max_bytes": 64},
        "forbidden": ["collective-permute", "all-to-all"],
    }
    assert xa.check_signature(rep, expected) == []
