"""DeviceDataset — the HBM-resident input pipeline that is the bench
primary and the lab driver's default input mode.  Pins the properties that
distinguish it from the rounds-1-2 "one re-fed batch" flaw: per-step batch
variation, per-epoch disjointness (drop-last), and epoch reshuffling."""

import numpy as np
import pytest

from ddl25spring_tpu.benchmarks import DeviceDataset


@pytest.fixture(scope="module")
def ds():
    # synthetic CIFAR (zero-egress image); n=100, B=32 -> 3 batches/epoch,
    # 4-row drop-last tail
    return DeviceDataset(32, n_train=100)


def test_epoch_batches_disjoint_and_drop_last(ds):
    ds._i = 0
    nb = ds.batches_per_epoch
    assert nb == 3
    seen = []
    for _ in range(nb):
        x, y = ds.feed()
        assert x.shape == (32, 32, 32, 3) and y.shape == (32,)
        # recover row identities by matching against the device dataset
        flat = np.asarray(x).reshape(32, -1)
        ref = np.asarray(ds.x).reshape(ds.n, -1)
        idx = [int(np.argmax((ref == r).all(1))) for r in flat]
        seen.append(idx)
    all_idx = [i for b in seen for i in b]
    assert len(set(all_idx)) == 96, "epoch batches must be disjoint"


def test_epochs_reshuffle(ds):
    ds._i = 0
    first_epoch = [np.asarray(ds.feed()[1]) for _ in range(ds.batches_per_epoch)]
    second_epoch = [np.asarray(ds.feed()[1]) for _ in range(ds.batches_per_epoch)]
    # same label multiset is not guaranteed (drop-last differs per perm),
    # but identical batch sequences would mean the shuffle is not keyed
    # by epoch
    assert any(
        not np.array_equal(a, b) for a, b in zip(first_epoch, second_epoch)
    )


def test_step_counter_survives_many_epochs(ds):
    # int32-overflow regression guard: epoch math is host-side Python ints
    ds._i = (2**31 // 32) + 7  # would overflow a traced i*B int32 product
    x, y = ds.feed()
    assert x.shape[0] == 32 and np.asarray(y).shape == (32,)


def test_batch_larger_than_dataset_rejected():
    with pytest.raises(ValueError, match="exceeds dataset size"):
        DeviceDataset(256, n_train=100)


def test_scan_step_equals_sequential_steps(devices8):
    """The K-steps-per-dispatch primary (build_resnet_scan_step) must be
    the same training as K sequential single-step dispatches on the same
    DeviceDataset stream (same batches, same updates, up to fp32
    reassociation across the two compilations) — the scan fuses dispatch
    overhead away, it must not change semantics."""
    import jax

    from ddl25spring_tpu.benchmarks import build_resnet_scan_step

    B, K = 16, 2
    ds = DeviceDataset(B, n_train=64)
    assert ds.batches_per_epoch % K == 0
    multi, step1, p0, o0, meta = build_resnet_scan_step(
        devices8[:1], 1, 1, 1, B, K, ds.n
    )
    assert meta["scan_steps"] == K

    ds._i = 0
    p_ref, o_ref = p0, o0
    for _ in range(K):
        p_ref, o_ref, loss_ref = step1(p_ref, o_ref, ds.feed())

    ds._i = 0
    p_s, o_s, loss_s = multi(p0, o0, ds.x, ds.y, *ds.scan_window(K))

    np.testing.assert_allclose(float(loss_ref), float(loss_s), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4
        ),
        jax.device_get(p_ref),
        jax.device_get(p_s),
    )

    with pytest.raises(ValueError, match="must divide"):
        ds.scan_window(3)
