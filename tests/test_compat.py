"""Direct unit coverage for the ``utils/compat.py`` shims.

The shims are the single import point that lets the whole stack (written
against current jax: top-level ``shard_map``, VMA ``pcast``, one-dict
``cost_analysis``, peak-carrying ``memory_analysis``) import and run on
jax 0.4.x.  They were previously exercised only through the modules that
use them; these tests pin each shim's contract on BOTH API vintages —
every assertion here is phrased so it passes on the legacy runtime this
image ships AND on a current one.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from ddl25spring_tpu.utils import compat
from ddl25spring_tpu.utils.compat import (
    HAS_VMA,
    compiled_cost_analysis,
    compiled_memory_stats,
    pcast,
    shard_map,
    typeof,
)
from ddl25spring_tpu.utils.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh4(devices8):
    return make_mesh(devices8[:4], data=4)


# ------------------------------------------------------------- shard_map


def test_shard_map_direct_call_runs_psum(mesh4):
    @functools.partial(
        shard_map, mesh=mesh4, in_specs=(P("data"),), out_specs=P()
    )
    def total(x):
        return lax.psum(jnp.sum(x), "data")

    out = total(jnp.arange(8.0))
    assert float(out) == pytest.approx(28.0)


def test_shard_map_partial_decorator_form(mesh4):
    """The ``shard_map(f=None, **kw)`` curry: usable exactly like the
    real API's decorator spelling."""
    deco = shard_map(mesh=mesh4, in_specs=(P("data"),), out_specs=P("data"))
    assert callable(deco)
    doubled = deco(lambda x: x * 2)
    np.testing.assert_array_equal(
        np.asarray(doubled(jnp.arange(4.0))), [0.0, 2.0, 4.0, 6.0]
    )


def test_shard_map_legacy_flag_matches_runtime():
    """On pre-VMA jax the shim must route through the experimental API
    with check_rep defaulted off; on current jax it must NOT inject the
    (removed) kwarg.  _LEGACY is the single switch for both."""
    legacy_runtime = not hasattr(jax, "shard_map")
    assert compat._LEGACY == legacy_runtime


# ----------------------------------------------------------------- pcast


def test_pcast_is_identity_semantics(mesh4):
    """pcast never changes VALUES — on VMA jax it only retypes the aval,
    pre-VMA it is literally identity (nothing to cast between)."""
    @functools.partial(
        shard_map, mesh=mesh4, in_specs=(P("data"),), out_specs=P("data")
    )
    def body(x):
        return pcast(x, "data", to="varying") + 1.0

    np.testing.assert_array_equal(
        np.asarray(body(jnp.zeros(4))), np.ones(4)
    )


def test_pcast_binding_tracks_vma():
    if HAS_VMA:
        assert pcast is lax.pcast
    else:
        x = jnp.arange(3.0)
        assert pcast(x, "data", to="varying") is x


def test_typeof_exposes_shape_dtype():
    t = typeof(jnp.zeros((2, 3), jnp.float32))
    assert tuple(t.shape) == (2, 3) and t.dtype == jnp.float32
    # the callers' probe pattern: vma is a set on VMA jax, absent before
    vma = getattr(t, "vma", None)
    assert vma is None or isinstance(vma, (set, frozenset, tuple))


# -------------------------------------------------- cost analysis shapes


class _CostList:
    """jax <= 0.4.x: per-module list; entry module first."""

    def cost_analysis(self):
        return [{"flops": 12.0, "bytes accessed": 3.0}, {"flops": 99.0}]


class _CostDict:
    def cost_analysis(self):
        return {"flops": 7.5}


class _CostEmptyList:
    def cost_analysis(self):
        return []


class _CostNone:
    def cost_analysis(self):
        return None


class _CostRaises:
    def cost_analysis(self):
        raise NotImplementedError("no cost model on this backend")


def test_cost_analysis_normalizes_every_api_shape():
    assert compiled_cost_analysis(_CostList()) == {
        "flops": 12.0, "bytes accessed": 3.0,
    }
    assert compiled_cost_analysis(_CostDict()) == {"flops": 7.5}
    assert compiled_cost_analysis(_CostEmptyList()) is None
    assert compiled_cost_analysis(_CostNone()) is None
    assert compiled_cost_analysis(_CostRaises()) is None


def test_cost_analysis_returns_a_fresh_dict():
    """Mutating the normalized dict must not corrupt a cached analysis."""
    src = _CostDict()
    d = compiled_cost_analysis(src)
    d["flops"] = -1
    assert compiled_cost_analysis(src) == {"flops": 7.5}


# ------------------------------------------------- memory analysis shapes


class _MemOld:
    """CompiledMemoryStats as 0.4.x ships it: no peak field."""

    argument_size_in_bytes = 1000
    output_size_in_bytes = 300
    temp_size_in_bytes = 700
    alias_size_in_bytes = 100
    generated_code_size_in_bytes = 50


class _MemNew(_MemOld):
    peak_memory_in_bytes = 4242


def _compiled_with(stats):
    class C:
        def memory_analysis(self):
            return stats

    return C()


def test_memory_stats_assembles_peak_on_legacy_fields():
    out = compiled_memory_stats(_compiled_with(_MemOld()))
    assert out["peak_hbm_bytes"] == 1000 + 300 + 700 + 50 - 100
    assert out["alias_size_in_bytes"] == 100


def test_memory_stats_prefers_backend_peak():
    out = compiled_memory_stats(_compiled_with(_MemNew()))
    assert out["peak_hbm_bytes"] == 4242


def test_memory_stats_dict_shaped_future_api():
    out = compiled_memory_stats(_compiled_with({
        "argument_size_in_bytes": 10,
        "temp_size_in_bytes": 5,
        "not_a_known_field": 77,
        "generated_code_size_in_bytes": "not-a-number",
    }))
    assert out == {
        "argument_size_in_bytes": 10,
        "temp_size_in_bytes": 5,
        "peak_hbm_bytes": 15,
    }


def test_memory_stats_degrades_to_none():
    class NoApi:
        pass

    class Raises:
        def memory_analysis(self):
            raise NotImplementedError

    assert compiled_memory_stats(NoApi()) is None
    assert compiled_memory_stats(_compiled_with(None)) is None
    assert compiled_memory_stats(Raises()) is None
    # an object with none of the known fields: no stats, not zeros
    class Alien:
        irrelevant = 1

    assert compiled_memory_stats(_compiled_with(Alien())) is None


# --------------------------------------------- end-to-end on this jax


def test_both_probes_work_on_a_real_compiled_program():
    compiled = (
        jax.jit(lambda a: (a @ a).sum()).lower(jnp.ones((64, 64))).compile()
    )
    cost = compiled_cost_analysis(compiled)
    assert cost and cost.get("flops", 0) >= 2 * 64**3
    mem = compiled_memory_stats(compiled)
    if mem is not None:  # some backends expose no memory stats at all
        assert mem["peak_hbm_bytes"] > 0
