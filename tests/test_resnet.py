"""ResNet-18 benchmark-model tests."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddl25spring_tpu.data.cifar10 import load_cifar10
from ddl25spring_tpu.models.resnet import ResNet18
from ddl25spring_tpu.ops.losses import cross_entropy_logits
from ddl25spring_tpu.parallel.dp import make_dp_train_step
from ddl25spring_tpu.utils.mesh import make_mesh


def test_resnet_group_norm_shapes():
    model = ResNet18(norm="group")
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 10)
    n_params = sum(p.size for p in jax.tree.leaves(variables["params"]))
    assert 10e6 < n_params < 13e6  # ResNet-18 ~11.2M params


def test_resnet_batch_norm_updates_stats():
    model = ResNet18(norm="batch")
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    out, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    assert out.shape == (4, 10)
    before = jax.tree.leaves(variables["batch_stats"])[0]
    after = jax.tree.leaves(mutated["batch_stats"])[0]
    assert not np.allclose(before, after)


def test_cifar10_loader_shapes_and_determinism():
    load_cifar10.cache_clear()
    a = load_cifar10(n_train=64, n_test=32)
    load_cifar10.cache_clear()
    b = load_cifar10(n_train=64, n_test=32)
    assert a["x_train"].shape == (64, 32, 32, 3)
    np.testing.assert_array_equal(a["x_train"], b["x_train"])


def test_resnet_dp_trains(devices8):
    model = ResNet18(norm="group", width=16)  # narrow for CPU speed
    data = load_cifar10(n_train=64, n_test=8)
    x = jnp.asarray(data["x_train"][:32])
    y = jnp.asarray(data["y_train"][:32])
    params = model.init(jax.random.PRNGKey(0), x[:2])["params"]

    def loss_fn(p, batch, key):
        xb, yb = batch
        return cross_entropy_logits(model.apply({"params": p}, xb, train=True), yb)

    tx = optax.sgd(0.05, momentum=0.9)
    opt_state = tx.init(params)
    mesh = make_mesh(devices8[:4], data=4)
    step = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
    losses = []
    for i in range(8):
        params, opt_state, loss = step(params, opt_state, (x, y), jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
