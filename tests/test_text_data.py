"""Tokenizer + TinyStories stream tests."""

import numpy as np
import pytest

from ddl25spring_tpu.data.tinystories import TinyStories, generate_story
from ddl25spring_tpu.data.tokenizer import (
    BpeTokenizer,
    ByteTokenizer,
    get_tokenizer,
)


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "One day Tom went to the park. Ünïcòde too."
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert max(ids) < tok.vocab_size and min(ids) >= 0
    assert tok.decode(ids) == text


def test_story_generator_deterministic():
    rng_a = np.random.default_rng(5)
    rng_b = np.random.default_rng(5)
    assert generate_story(rng_a) == generate_story(rng_b)


def test_tinystories_batch_shape_and_determinism():
    tok = ByteTokenizer()
    ds_a = iter(TinyStories(tok, batch_size=3, seq_l=64, min_chars=20_000))
    ds_b = iter(TinyStories(tok, batch_size=3, seq_l=64, min_chars=20_000))
    a, b = next(ds_a), next(ds_b)
    assert a.shape == (3, 64) and a.dtype == np.int32
    np.testing.assert_array_equal(a, b)


def _train_corpus(n_stories=400, seed=7):
    rng = np.random.default_rng(seed)
    return " ".join(generate_story(rng) for _ in range(n_stories))


def test_bpe_trains_compresses_roundtrips(tmp_path):
    """The trained-subword path end-to-end (VERDICT r3 #6, adapted: the
    sentencepiece package is absent on this image, so the in-tree BPE
    covers the capability): train on the corpus -> merges actually learned
    -> encoding is SHORTER than bytes -> artifact save/load preserves
    behavior -> exact round-trip incl. unicode."""
    corpus = _train_corpus()
    tok = BpeTokenizer.train(corpus, n_merges=256)
    assert len(tok.merges) > 50  # the corpus supports real merges
    assert tok.vocab_size == 259 + len(tok.merges)

    text = "One day Tom went to the park. The cat found a red ball."
    ids = tok.encode(text)
    byte_len = len(ByteTokenizer().encode(text))
    assert len(ids) < 0.7 * byte_len  # genuine subword compression
    assert tok.decode(ids) == text

    weird = "Tabs\tand  spaces Ünïcòde \n newlines"
    assert tok.decode(tok.encode(weird)) == weird

    path = tmp_path / "bpe.json"
    tok.save(str(path))
    tok2 = BpeTokenizer.load(str(path))
    assert tok2.encode(text) == ids
    assert tok2.vocab_size == tok.vocab_size


def test_native_bpe_encode_matches_python():
    """The C++ encode loop (native/bpe.cc, the in-tree analogue of the
    reference's native SentencePiece tokenizer) must be byte-identical to
    the Python reference implementation — chunking (Python-str \\s
    semantics incl. Unicode whitespace), leftmost-lowest-rank merges, bos
    handling.  Skipped only where the toolchain can't build the lib."""
    tok = BpeTokenizer.train(_train_corpus(), n_merges=128)
    if tok._native is None:
        pytest.skip("native BPE lib unavailable (no toolchain)")
    py = BpeTokenizer(tok.merges, native=False)
    assert py._native is None
    cases = [
        "", "   ", "a", " a", "a ", "trailing ws   ", "\n\nleading",
        "One day Tom went to the park. The cat found a red ball.",
        "Tabs\tand  spaces Ünïcòde \n newlines",
        "nbsp\xa0thin ideo　sep done",  # unicode \s chunking
        "café naïve 你好世界",
    ]
    for text in cases:
        for bos in (True, False):
            assert tok.encode(text, add_bos=bos) == py.encode(
                text, add_bos=bos
            ), repr(text)
        assert tok.decode(tok.encode(text)) == text


def test_get_tokenizer_discovers_bpe_artifact(tmp_path, monkeypatch):
    """get_tokenizer() artifact discovery mirrors the reference's fetched
    SPTokenizer model file (s01_b1_microbatches.py:31)."""
    tok = BpeTokenizer.train(_train_corpus(100), n_merges=64)
    path = tmp_path / "bpe.json"
    tok.save(str(path))
    monkeypatch.setenv("DDL25_BPE_MODEL", str(path))
    found = get_tokenizer()
    assert isinstance(found, BpeTokenizer)
    assert found.vocab_size == tok.vocab_size
    monkeypatch.delenv("DDL25_BPE_MODEL")
    monkeypatch.setenv("DDL25_BPE_MODEL", "")
    assert isinstance(get_tokenizer(), ByteTokenizer)
    # explicit .json path routes to the BPE loader
    assert isinstance(get_tokenizer(str(path)), BpeTokenizer)


def test_bpe_feeds_tinystories_and_trainstep(tmp_path):
    """The full b1 mechanism on the trained tokenizer: TinyStories batches
    under the BPE vocab -> one LLaMA train step, loss finite and falling
    over a few steps (the reference's convergence-by-eyeball check)."""
    import jax
    import optax

    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.ops.losses import causal_lm_loss
    from ddl25spring_tpu.utils.config import LlamaConfig

    tok = BpeTokenizer.train(_train_corpus(), n_merges=128)
    ds = iter(TinyStories(tok, batch_size=4, seq_l=32, min_chars=50_000))
    batch = next(ds)
    assert batch.max() < tok.vocab_size

    cfg = LlamaConfig(
        vocab_size=tok.vocab_size, dmodel=32, num_heads=2, n_layers=2,
        ctx_size=32, dtype="float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o, toks):
        def loss_fn(p):
            return causal_lm_loss(llama.llama_forward(p, toks, cfg), toks)

        loss, g = jax.value_and_grad(loss_fn)(p)
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), o, loss

    losses = []
    for _ in range(10):
        params, opt, loss = step(params, opt, jax.numpy.asarray(next(ds)))
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_tinystories_skip_disjoint_and_oversized_skip():
    """skip= gives DP replicas disjoint heads (reference: skip=rank*N,
    intro_DP_GA.py:29); a skip beyond the corpus must still yield full
    batches (modular wrap)."""
    tok = ByteTokenizer()
    kw = dict(batch_size=2, seq_l=64, min_chars=20_000)
    a = next(iter(TinyStories(tok, **kw, skip=0)))
    b = next(iter(TinyStories(tok, **kw, skip=2)))
    assert not np.array_equal(a, b)
    huge = next(iter(TinyStories(tok, **kw, skip=10**9)))
    assert huge.shape == (2, 64)

# ------------------------------------------------- SentencePiece (in-tree)


def test_sp_model_wire_roundtrip(tmp_path):
    """The hand-rolled ModelProto writer/reader are exact inverses —
    the compatibility contract with real SentencePiece artifacts."""
    from ddl25spring_tpu.data.sp_model import (
        CONTROL, NORMAL, UNKNOWN, read_sp_model, write_sp_model,
    )

    pieces = [
        ("<pad>", 0.0, CONTROL), ("<s>", 0.0, CONTROL),
        ("</s>", 0.0, CONTROL), ("<unk>", 0.0, UNKNOWN),
        ("▁the", -1.5, NORMAL), ("▁", -2.25, NORMAL), ("e", -3.0, NORMAL),
    ]
    p = tmp_path / "t.model"
    write_sp_model(pieces, p)
    got = read_sp_model(p)
    assert [(a, c) for a, _, c in got] == [(a, c) for a, _, c in pieces]
    for (_, s1, _), (_, s2, _) in zip(pieces, got):
        assert abs(s1 - s2) < 1e-6


def test_sp_tokenizer_runs_on_in_tree_artifact():
    """The SentencePiece wrapper is live on this image (round-5 closure):
    without the sentencepiece package it loads the committed
    ``data/tinystories.model`` through the pure-Python unigram-Viterbi
    processor — encode compresses vs bytes and decode round-trips."""
    from ddl25spring_tpu.data.tokenizer import SentencePieceTokenizer

    tok = SentencePieceTokenizer("data/tinystories.model")
    assert tok.vocab_size == 512
    text = "One day Zoe went to the school. The mouse came to play."
    ids = tok.encode(text, add_bos=True)
    assert ids[0] == tok.bos_id
    body = ids[1:]
    # trained subwords must beat byte-level length
    assert len(body) < len(text.encode()) // 2
    assert tok.decode(body) == text


def test_sp_tokenizer_warns_once_on_pure_python_fallback():
    """Without the sentencepiece package the wrapper must SAY it swapped
    in the approximate pure-Python processor (no NFKC, no byte-fallback
    — see data/sp_model.py's divergence notes), not swap silently."""
    import contextlib
    import warnings

    from ddl25spring_tpu.data.tokenizer import SentencePieceTokenizer

    with contextlib.suppress(ImportError):
        import sentencepiece  # noqa: F401

        pytest.skip("real sentencepiece installed; no fallback to warn on")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        SentencePieceTokenizer("data/tinystories.model")
    msgs = [str(w.message) for w in caught]
    assert any("PySentencePieceProcessor" in m and "approximate" in m.lower()
               for m in msgs), msgs


def test_sp_tokenizer_via_env_discovery(monkeypatch):
    from ddl25spring_tpu.data.tokenizer import (
        SentencePieceTokenizer, get_tokenizer,
    )

    monkeypatch.setenv("DDL25_SP_MODEL", "data/tinystories.model")
    tok = get_tokenizer()
    assert isinstance(tok, SentencePieceTokenizer)
    assert tok.encode("the cat", add_bos=False)


def test_sp_viterbi_prefers_trained_pieces_and_handles_unknowns():
    from ddl25spring_tpu.data.sp_model import (
        CONTROL, NORMAL, UNKNOWN, PySentencePieceProcessor, write_sp_model,
    )
    import tempfile, os

    pieces = [
        ("<pad>", 0.0, CONTROL), ("<s>", 0.0, CONTROL),
        ("</s>", 0.0, CONTROL), ("<unk>", 0.0, UNKNOWN),
        ("▁ab", -1.0, NORMAL), ("▁a", -2.0, NORMAL), ("b", -2.0, NORMAL),
        ("▁", -3.0, NORMAL), ("a", -3.0, NORMAL),
    ]
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "m.model")
        write_sp_model(pieces, p)
        sp = PySentencePieceProcessor(p)
    # one merged piece (score -1) beats ▁a + b (-4): Viterbi max-sum
    assert sp.encode("ab") == [4]
    # an uncovered character falls back to <unk>, neighbors unaffected
    ids = sp.encode("aXb")
    assert sp._unk in ids and ids[0] == 5  # ▁a, <unk>, b
    # decode renders <unk> as " ⁇ " like real SentencePiece (silently
    # dropping it would lose characters on out-of-vocab input)
    assert sp.decode(ids) == "a ⁇ b"
