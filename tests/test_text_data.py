"""Tokenizer + TinyStories stream tests."""

import numpy as np

from ddl25spring_tpu.data.tinystories import TinyStories, generate_story
from ddl25spring_tpu.data.tokenizer import ByteTokenizer


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "One day Tom went to the park. Ünïcòde too."
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert max(ids) < tok.vocab_size and min(ids) >= 0
    assert tok.decode(ids) == text


def test_story_generator_deterministic():
    rng_a = np.random.default_rng(5)
    rng_b = np.random.default_rng(5)
    assert generate_story(rng_a) == generate_story(rng_b)


def test_tinystories_batch_shape_and_determinism():
    tok = ByteTokenizer()
    ds_a = iter(TinyStories(tok, batch_size=3, seq_l=64, min_chars=20_000))
    ds_b = iter(TinyStories(tok, batch_size=3, seq_l=64, min_chars=20_000))
    a, b = next(ds_a), next(ds_b)
    assert a.shape == (3, 64) and a.dtype == np.int32
    np.testing.assert_array_equal(a, b)


def test_tinystories_skip_disjoint_and_oversized_skip():
    """skip= gives DP replicas disjoint heads (reference: skip=rank*N,
    intro_DP_GA.py:29); a skip beyond the corpus must still yield full
    batches (modular wrap)."""
    tok = ByteTokenizer()
    kw = dict(batch_size=2, seq_l=64, min_chars=20_000)
    a = next(iter(TinyStories(tok, **kw, skip=0)))
    b = next(iter(TinyStories(tok, **kw, skip=2)))
    assert not np.array_equal(a, b)
    huge = next(iter(TinyStories(tok, **kw, skip=10**9)))
    assert huge.shape == (2, 64)