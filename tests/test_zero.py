"""ZeRO/FSDP-sharded DP: equivalence oracle vs plain (replicated) DP, and
the memory claim — per-device param/opt bytes shrink by ~n.

The reference's DP holds a full replica per rank
(`lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:35-39`); the sharded
variant must train identically while each device stores 1/n of the state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.data.mnist import load_mnist
from ddl25spring_tpu.models.mnist_cnn import MnistCnn
from ddl25spring_tpu.ops.losses import nll_loss
from ddl25spring_tpu.parallel.dp import make_dp_train_step
from ddl25spring_tpu.parallel.zero import (
    make_zero_dp_train_step,
    zero_clip_by_global_norm,
    zero_shard_params,
    zero_unshard_params,
)
from ddl25spring_tpu.utils.mesh import make_mesh


@pytest.fixture(scope="module")
def setup():
    model = MnistCnn()
    data = load_mnist(n_train=512, n_test=256)
    params = model.init(jax.random.PRNGKey(0), data["x_train"][:1])["params"]

    def loss_fn(params, batch, key):
        x, y = batch
        out = model.apply({"params": params}, x, train=False)
        return nll_loss(out, y)

    return data, params, loss_fn


@pytest.mark.parametrize("n_dev", [2, 4])
@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_zero_equals_plain_dp(setup, n_dev, opt, devices8):
    data, params, loss_fn = setup
    tx = optax.sgd(0.1, momentum=0.9) if opt == "sgd" else optax.adam(1e-3)
    mesh = make_mesh(devices8[:n_dev], data=n_dev)

    dp = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
    zero = make_zero_dp_train_step(
        loss_fn, tx, mesh, params, per_shard_rng=False
    )

    batch = (
        jnp.asarray(data["x_train"][:64]),
        jnp.asarray(data["y_train"][:64]),
    )
    key = jax.random.PRNGKey(1)

    p_d, o_d, loss_d = dp(params, tx.init(params), batch, key)

    shards = zero_shard_params(params, mesh)
    o_z = tx.init(shards)
    for i in range(3):
        shards, o_z, loss_z = zero(shards, o_z, batch, key)
        if i == 0:
            np.testing.assert_allclose(
                float(loss_d), float(loss_z), rtol=1e-5
            )
    # re-run plain DP for the same 3 steps to compare end states
    p_ref, o_ref = params, tx.init(params)
    for _ in range(3):
        p_ref, o_ref, _ = dp(p_ref, o_ref, batch, key)

    restored = zero_unshard_params(jax.device_get(shards), params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        ),
        jax.device_get(p_ref),
        restored,
    )


def test_zero_shard_roundtrip(setup, devices8):
    _, params, _ = setup
    mesh = make_mesh(devices8[:4], data=4)
    shards = zero_shard_params(params, mesh)
    back = zero_unshard_params(jax.device_get(shards), params)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(params),
        back,
    )


def test_zero_per_device_memory(setup, devices8):
    """Each device holds ~1/n of the parameter bytes (the FSDP point)."""
    _, params, _ = setup
    n = 8
    mesh = make_mesh(devices8[:n], data=n)
    shards = zero_shard_params(params, mesh)

    total = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(shards))
    per_dev = 0
    for leaf in jax.tree.leaves(shards):
        shard0 = [s for s in leaf.addressable_shards if s.device == devices8[0]]
        per_dev += sum(s.data.size * s.data.dtype.itemsize for s in shard0)
    assert per_dev <= total / n + 1024  # 1/n plus padding slack


@pytest.mark.parametrize("M", [2, 4])
def test_zero_grad_accum_equals_full_batch(setup, M, devices8):
    """FSDP-style microbatch accumulation (num_microbatches=M) must equal
    the single-shot step on the same total batch (deterministic loss, no
    dropout) — the reference's .grad-accumulation semantics
    (s01_b1_microbatches.py) transplanted to sharded DP."""
    data, params, loss_fn = setup
    tx = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(devices8[:2], data=2)

    batch = (
        jnp.asarray(data["x_train"][:64]),
        jnp.asarray(data["y_train"][:64]),
    )
    key = jax.random.PRNGKey(2)

    one = make_zero_dp_train_step(
        loss_fn, tx, mesh, params, per_shard_rng=False
    )
    acc = make_zero_dp_train_step(
        loss_fn, tx, mesh, params, per_shard_rng=False, num_microbatches=M
    )

    s1 = zero_shard_params(params, mesh)
    p1, _, l1 = one(s1, tx.init(s1), batch, key)
    s2 = zero_shard_params(params, mesh)
    p2, _, l2 = acc(s2, tx.init(s2), batch, key)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        ),
        jax.device_get(p1),
        jax.device_get(p2),
    )


@pytest.mark.parametrize("max_norm", [0.05, 1e4])
def test_zero_global_norm_clip_equals_replicated(setup, max_norm, devices8):
    """ZeRO + zero_clip_by_global_norm == replicated DP +
    optax.clip_by_global_norm, in both regimes (clip triggered with the
    tiny max_norm; pass-through with the huge one) — VERDICT r3 #4.
    Three steps so the clipped updates feed back through Adam state."""
    data, params, loss_fn = setup
    mesh = make_mesh(devices8[:4], data=4)

    tx_ref = optax.chain(optax.clip_by_global_norm(max_norm), optax.adam(1e-2))
    tx_z = optax.chain(zero_clip_by_global_norm(max_norm), optax.adam(1e-2))

    dp = make_dp_train_step(loss_fn, tx_ref, mesh, per_shard_rng=False)
    zero = make_zero_dp_train_step(
        loss_fn, tx_z, mesh, params, per_shard_rng=False
    )

    batch = (
        jnp.asarray(data["x_train"][:64]),
        jnp.asarray(data["y_train"][:64]),
    )
    key = jax.random.PRNGKey(3)

    p_ref, o_ref = params, tx_ref.init(params)
    for _ in range(3):
        p_ref, o_ref, _ = dp(p_ref, o_ref, batch, key)

    shards = zero_shard_params(params, mesh)
    o_z = tx_z.init(shards)
    for _ in range(3):
        shards, o_z, _ = zero(shards, o_z, batch, key)

    restored = zero_unshard_params(jax.device_get(shards), params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        ),
        jax.device_get(p_ref),
        restored,
    )


def test_zero_rejects_mismatched_2d_state(setup, devices8):
    """A transform whose 2-D state leaf is not in the [n, k] shard layout
    must be rejected loudly, not silently mis-sharded (ADVICE r3)."""
    _, params, loss_fn = setup
    mesh = make_mesh(devices8[:2], data=2)

    def bad_init(params):
        return {"mat": jnp.zeros((3, 7))}

    def bad_update(updates, state, params=None):
        return updates, state

    tx = optax.GradientTransformation(bad_init, bad_update)
    step = make_zero_dp_train_step(loss_fn, tx, mesh, params)
    shards = zero_shard_params(params, mesh)
    with pytest.raises(ValueError, match="2-D leaf"):
        step(shards, tx.init(shards),
             (jnp.zeros((8, 28, 28, 1)), jnp.zeros((8,), jnp.int32)),
             jax.random.PRNGKey(0))


def test_zero_moe_llama_composition(devices8):
    """Capstone composition: a switch-MoE LLaMA trained under ZeRO/FSDP
    sharding with microbatch accumulation — params (incl. expert stacks)
    and opt state sharded over the data axis, aux-weighted LM loss, loss
    falls.  Exercises zero.py's gather/scatter on the MoE pytree."""

    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.ops.losses import causal_lm_loss
    from ddl25spring_tpu.utils.config import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=16,
        dtype="float32", n_experts=4, capacity_factor=2.0,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    )

    def loss_fn(p, batch, key):
        logits, aux = llama.llama_forward_with_aux(p, batch, cfg)
        return causal_lm_loss(logits, batch) + cfg.moe_aux_weight * aux

    mesh = make_mesh(devices8[:4], data=4)
    tx = optax.adam(1e-2)
    step = make_zero_dp_train_step(
        loss_fn, tx, mesh, params, per_shard_rng=False, num_microbatches=2
    )
    shards = zero_shard_params(params, mesh)
    ost = tx.init(shards)
    losses = []
    for _ in range(15):
        shards, ost, loss = step(
            shards, ost, tokens, jax.random.PRNGKey(2)
        )
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses[::5]
    assert all(np.isfinite(losses))


@pytest.mark.parametrize("stage", [1, 2])
def test_zero_stage12_equals_plain_dp(stage, devices8):
    """ZeRO-1/2 (optimizer-state sharding, replicated params) must train
    bitwise-equivalently to replicated DP + the same optax chain — the
    stages only repartition WHERE the update runs, never what it computes.
    Tiny-MLP workload (the compile-analytics one) with Adam, whose moments
    live sharded [n, k]."""
    from ddl25spring_tpu.parallel.dp import _tiny_mlp_workload
    from ddl25spring_tpu.parallel.zero import make_zero_partitioned_train_step

    n = 4
    mesh = make_mesh(devices8[:n], data=n)
    params, loss_fn, batch, _ = _tiny_mlp_workload(n)
    key0 = jax.random.PRNGKey(7)
    params = jax.tree.map(
        lambda x: 0.1 * jax.random.normal(key0, x.shape, x.dtype), params
    )
    batch = (
        jax.random.normal(jax.random.PRNGKey(8), batch[0].shape),
        jax.random.normal(jax.random.PRNGKey(9), batch[1].shape),
    )
    tx = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)

    dp = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
    z = make_zero_partitioned_train_step(
        loss_fn, tx, mesh, params, stage=stage, per_shard_rng=False
    )

    p_ref, o_ref = params, tx.init(params)
    p_z, o_z = params, tx.init(zero_shard_params(params, mesh))
    for _ in range(3):
        p_ref, o_ref, loss_ref = dp(p_ref, o_ref, batch, key)
        p_z, o_z, loss_z = z(p_z, o_z, batch, key)
        np.testing.assert_allclose(float(loss_ref), float(loss_z), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=2e-6
        ),
        jax.device_get(p_ref),
        jax.device_get(p_z),
    )


def test_zero_stage12_opt_state_stays_sharded(devices8):
    """The point of ZeRO-1/2: Adam moments live in the [n, k] sharded
    layout (1/n per device), while params come back replicated."""
    from ddl25spring_tpu.parallel.dp import _tiny_mlp_workload
    from ddl25spring_tpu.parallel.zero import make_zero_partitioned_train_step

    n = 4
    mesh = make_mesh(devices8[:n], data=n)
    params, loss_fn, batch, _ = _tiny_mlp_workload(n)
    tx = optax.adam(1e-2)
    z = make_zero_partitioned_train_step(
        loss_fn, tx, mesh, params, stage=2, per_shard_rng=False
    )
    o_z = tx.init(zero_shard_params(params, mesh))
    p, o_z, _ = z(params, o_z, batch, jax.random.PRNGKey(0))
    mu = o_z[0].mu["w1"]
    assert mu.shape[0] == n
    shard0 = [s for s in mu.addressable_shards if s.device == devices8[0]]
    assert sum(s.data.shape[0] for s in shard0) == 1  # one row per device
    # params returned replicated with original shapes
    assert jax.tree.structure(p) == jax.tree.structure(params)
    assert p["w1"].shape == params["w1"].shape
