"""KV-cache generation correctness.

Oracle (SURVEY §4 discipline applied to inference): the cached
incremental decode must reproduce the full forward — greedy generation
token-for-token equals argmax of ``llama_forward`` over the growing
sequence (teacher forcing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.models.decode import (
    decode_step,
    generate,
    init_kv_cache,
    sample_logits,
)
from ddl25spring_tpu.utils.config import LlamaConfig

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params_and_prompt():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 1, 64)
    return params, prompt


def _teacher_forced(params, prompt, cfg, n):
    """Reference: grow the sequence with argmax of the FULL forward."""
    seq = np.asarray(prompt)
    out = []
    for _ in range(n):
        logits = llama.llama_forward(params, jnp.asarray(seq), cfg)
        nxt = np.asarray(logits[:, -1].argmax(-1).astype(jnp.int32))
        out.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


def test_greedy_generate_equals_full_forward(params_and_prompt):
    params, prompt = params_and_prompt
    n = 8
    got = np.asarray(jax.jit(
        lambda p, t: generate(p, t, CFG, n)
    )(params, prompt))
    want = _teacher_forced(params, prompt, CFG, n)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("top_k", [1, 2])
def test_greedy_generate_moe(params_and_prompt, top_k):
    """MoE blocks decode too: with ample capacity the per-token routing
    (top-1 switch AND top-2) is group-independent, so the oracle still
    holds exactly — a decode path that dropped ``moe_top_k`` would route
    top-1 and silently diverge from the trained forward."""
    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=32,
        dtype="float32", n_experts=4, capacity_factor=4.0, moe_top_k=top_k,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(2), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 1, 64)
    n = 6

    def fwd_with_aux_argmax(seq):
        logits, _ = llama.llama_forward_with_aux(params, seq, cfg)
        return logits

    seq = np.asarray(prompt)
    want = []
    for _ in range(n):
        logits = fwd_with_aux_argmax(jnp.asarray(seq))
        nxt = np.asarray(logits[:, -1].argmax(-1).astype(jnp.int32))
        want.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    got = np.asarray(generate(params, prompt, cfg, n))
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_decode_step_matches_forward_slice(params_and_prompt):
    """One incremental step after a prefilled cache == the last-position
    logits of the full forward."""
    params, prompt = params_and_prompt
    B, P = prompt.shape
    cache = init_kv_cache(CFG, B, P + 1)
    for i in range(P):
        logits, cache = decode_step(
            params, cache, prompt[:, i], jnp.int32(i), CFG
        )
    full = llama.llama_forward(params, prompt, CFG)[:, -1]
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full), atol=1e-4, rtol=1e-4
    )


def test_temperature_sampling_deterministic_and_in_range(params_and_prompt):
    params, prompt = params_and_prompt
    k = jax.random.PRNGKey(7)
    a = np.asarray(generate(params, prompt, CFG, 6, temperature=0.8, key=k))
    b = np.asarray(generate(params, prompt, CFG, 6, temperature=0.8, key=k))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 6)
    assert a.min() >= 0 and a.max() < CFG.vocab_size
    c = np.asarray(
        generate(params, prompt, CFG, 6, temperature=0.8,
                 key=jax.random.PRNGKey(8))
    )
    assert not np.array_equal(a, c)  # different key, different sample


def test_top_k_restricts_support():
    """Every top-k sample must land in the k highest logits; k=1 is
    greedy regardless of key."""
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 32))
    topk_sets = np.asarray(jax.lax.top_k(logits, 5)[1])
    for seed in range(20):
        tok = np.asarray(
            sample_logits(logits, jax.random.PRNGKey(seed),
                          temperature=1.0, top_k=5)
        )
        for b in range(4):
            assert tok[b] in topk_sets[b]
    greedy = np.asarray(logits.argmax(-1))
    for seed in range(5):
        np.testing.assert_array_equal(
            np.asarray(sample_logits(logits, jax.random.PRNGKey(seed),
                                     temperature=1.0, top_k=1)),
            greedy,
        )


def test_top_p_nucleus_restricts_support():
    """Nucleus sampling keeps exactly the smallest prefix of the sorted
    vocab reaching mass p — verified against a numpy reconstruction of
    the nucleus, plus the always-keep-best edge case at tiny p."""
    logits = jax.random.normal(jax.random.PRNGKey(1), (3, 16)) * 3.0
    p = 0.7
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    order = np.argsort(-probs, axis=-1)
    nucleus = []
    for b in range(3):
        mass, keep = 0.0, set()
        for idx in order[b]:
            keep.add(int(idx))
            mass += probs[b, idx]
            if mass >= p:
                break
        nucleus.append(keep)
    for seed in range(30):
        tok = np.asarray(
            sample_logits(logits, jax.random.PRNGKey(seed),
                          temperature=1.0, top_p=p)
        )
        for b in range(3):
            assert int(tok[b]) in nucleus[b]
    # p -> 0 degenerates to greedy (the best token is always kept),
    # including the exact p=0.0 boundary (cutoff clamp)
    greedy = np.asarray(logits.argmax(-1))
    for p_edge in (1e-6, 0.0):
        for seed in range(5):
            np.testing.assert_array_equal(
                np.asarray(sample_logits(logits, jax.random.PRNGKey(seed),
                                         temperature=1.0, top_p=p_edge)),
                greedy,
            )


def test_generate_with_top_k_p_jits(params_and_prompt):
    """The filtered samplers thread through the jitted generate loop."""
    params, prompt = params_and_prompt
    out = np.asarray(jax.jit(
        lambda p, t: generate(p, t, CFG, 5, temperature=0.9,
                              key=jax.random.PRNGKey(3), top_k=8,
                              top_p=0.9)
    )(params, prompt))
    assert out.shape == (2, 5)
    assert out.min() >= 0 and out.max() < CFG.vocab_size


# ---------------------------------------------------------------- TP decode


@pytest.mark.parametrize("shard_vocab", [True, False])
def test_tp_generate_equals_single_device(shard_vocab, devices8):
    """TP-sharded generation (round-5 serving closure): head-sharded
    attention + KV cache, row-parallel psums, and (with shard_vocab) the
    vocab-sharded embed/unembed with one logits all_gather — greedy
    output must equal the single-device generate token for token."""
    from ddl25spring_tpu.models.decode import make_tp_generate
    from ddl25spring_tpu.parallel.tp import shard_tp_params
    from ddl25spring_tpu.utils.mesh import make_mesh

    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=4, n_layers=2, ctx_size=32,
        dtype="float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 1, 64)
    ref = np.asarray(generate(params, prompt, cfg, 8))

    mesh = make_mesh(devices8[:2], model=2)
    gen = make_tp_generate(cfg, mesh, 8, shard_vocab=shard_vocab)
    got = np.asarray(gen(
        shard_tp_params(params, mesh, shard_vocab=shard_vocab),
        prompt, jax.random.PRNGKey(0),
    ))
    np.testing.assert_array_equal(got, ref)


def test_tp_generate_moe_and_sampled(devices8):
    """TP decode with switch-MoE blocks (global routing, expert slices,
    psum-completed combine) and a sampled (non-greedy) chain: every shard
    draws the identical stream, so TP output == single-device output
    under the same key."""
    from ddl25spring_tpu.models.decode import make_tp_generate
    from ddl25spring_tpu.parallel.tp import shard_tp_params
    from ddl25spring_tpu.utils.mesh import make_mesh

    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=4, n_layers=2, ctx_size=32,
        dtype="float32", n_experts=4, capacity_factor=4.0,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(2), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 1, 64)
    key = jax.random.PRNGKey(7)
    ref = np.asarray(generate(
        params, prompt, cfg, 6, temperature=0.8, top_k=8, key=key
    ))

    mesh = make_mesh(devices8[:2], model=2)
    gen = make_tp_generate(
        cfg, mesh, 6, temperature=0.8, top_k=8
    )
    got = np.asarray(gen(shard_tp_params(params, mesh), prompt, key))
    np.testing.assert_array_equal(got, ref)
