"""Elastic mesh reshaping (``ddl25spring_tpu/ft/elastic``): survive
device loss and capacity change without a restart.

The central pins, per the PR-14 acceptance contract:

- **kill-free reshape equivalence**: an 8-way ZeRO-3 run reshaped LIVE
  onto 4 devices mid-run (no subprocess, no checkpoint round-trip) and
  continued matches the uninterrupted 4-way run from the same seed
  (tolerance-pinned like the PR-6 cross-mesh restore test), and the
  4 -> 8 grow-back cycle holds too;
- **live fast path == copy path**: :func:`ft.reshard.reshard_leaf` on
  live ``jax.Array`` leaves (device refit, no per-leaf host copy) is
  BITWISE the numpy checkpoint path, including the nonzero-truncation
  refusal;
- **signature re-pin**: the post-reshape step's collective signature
  re-pins clean via the compile analytics on the surviving mesh, and
  the rule-engine strategy stays graft-lint/graft-shard clean there
  (the ``with_mesh`` re-lower carries the table unchanged);
- **serve handoff**: replica scale-down drains through the ordinary
  release discipline with ZERO accepted-then-lost requests and
  token-exact output; the traffic-spike autoscaler answers a burst
  with a scale-up; ``serve_report --check-reshape`` gates it all.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.ft import (
    ChaosInjector,
    Fault,
    elastic,
    parse_chaos,
    reshard,
)
from ddl25spring_tpu.parallel import zero
from ddl25spring_tpu.parallel.rules import TABLES, RulePartitioner
from ddl25spring_tpu.utils.mesh import make_mesh


# ------------------------------------------------------------ chaos grammar


def test_signal_kind_grammar_matrix():
    """The PR-14 grammar extension: signal kinds parse with and without
    the ``:<arg>`` suffix, key round-trips, and every malformed shape
    refuses loudly (same matrix discipline as the PR-6 kinds)."""
    assert parse_chaos("traffic_spike@8") == (Fault("traffic_spike", 8),)
    assert parse_chaos("traffic_spike@8:16") == (
        Fault("traffic_spike", 8, 16),
    )
    assert parse_chaos("capacity_change@5:4") == (
        Fault("capacity_change", 5, 4),
    )
    assert parse_chaos("device_loss@3,capacity_change@5:2") == (
        Fault("device_loss", 3), Fault("capacity_change", 5, 2),
    )
    # key round-trip: the one-shot journal stores exactly this string
    assert Fault("capacity_change", 5, 4).key == "capacity_change@5:4"
    assert Fault("traffic_spike", 8).key == "traffic_spike@8"
    for bad in (
        "sigterm@5:2",        # arg on a kill kind
        "capacity_change@5:", # empty arg
        "capacity_change@5:x",
        "capacity_change@5:0",  # arg must be >= 1
        "traffic_spike",        # no step
        "traffic_spike@:4",
    ):
        with pytest.raises(ValueError):
            parse_chaos(bad)


def test_take_journals_one_shot_and_on_step_skips_signals(tmp_path):
    """Signal kinds never execute through on_step (a non-elastic driver
    must not die on them); take() consumes them with the same one-shot
    journal semantics as a fired kill, and the skip= filter lets an
    elastic driver claim device_loss away from the raise-and-die
    default."""
    spec = "traffic_spike@2:8,capacity_change@2:4,device_loss@2"
    ci = ChaosInjector(parse_chaos(spec), state_dir=tmp_path)
    ci.on_step(2, skip=("device_loss",))  # signals skipped, loss claimed
    assert len(ci.pending()) == 3  # nothing fired
    taken = ci.take(2)  # default: the two signal kinds
    assert sorted(f.kind for f in taken) == [
        "capacity_change", "traffic_spike",
    ]
    assert taken[0].arg in (8, 4)
    (loss,) = ci.take(2, kinds=("device_loss",))
    assert loss.kind == "device_loss"
    assert not ci.pending()
    # one-shot across relaunches: a fresh injector on the same journal
    ci2 = ChaosInjector(parse_chaos(spec), state_dir=tmp_path)
    assert not ci2.pending()
    assert ci2.take(2) == ()


# ------------------------------------------------- live fast path == copy


def test_live_fast_path_equals_copy_path():
    """reshard_leaf on live jax arrays (device refit) lands BITWISE on
    the numpy checkpoint path's output — shrink, grow, and the
    layer-stacked [L, n, k] layout — and refuses nonzero truncation
    with the same story."""
    true = np.arange(1, 38, dtype=np.float32)
    saved = np.zeros(40, np.float32)
    saved[:37] = true
    saved = saved.reshape(8, 5)
    stacked = np.stack([saved, 2 * saved])
    for src, tmpl in (
        (saved, jnp.zeros((4, 10), jnp.float32)),    # shrink 8 -> 4
        (saved, jnp.zeros((16, 3), jnp.float32)),    # grow 8 -> 16
        (stacked, jnp.zeros((2, 4, 10), jnp.float32)),  # [L, n, k]
        (saved, jnp.zeros((8, 5), jnp.float32)),     # same shape
    ):
        via_np = reshard.reshard_leaf(src, tmpl, "w")
        via_dev = reshard.reshard_leaf(jnp.asarray(src), tmpl, "w")
        assert isinstance(via_dev, jax.Array)
        np.testing.assert_array_equal(
            np.asarray(via_np), np.asarray(via_dev)
        )
    # the truncation refusal holds on the device path (the dropped tail
    # is host-read and judged exactly like the copy path's)
    with pytest.raises(ValueError, match="nonzero"):
        reshard.reshard_leaf(jnp.asarray(saved), jnp.zeros((2, 10)), "w")
    with pytest.raises(ValueError, match="nonzero"):
        reshard.reshard_leaf(
            jnp.asarray(stacked), jnp.zeros((2, 2, 10)), "b"
        )
    with pytest.raises(ValueError, match="cannot reshard"):
        reshard.reshard_leaf(jnp.asarray(saved), jnp.zeros((40,)), "w")


def test_zero_resume_template_abstract_matches_concrete(devices8):
    """The allocation-free template (``abstract=True``) carries exactly
    the concrete template's shapes, dtypes, and shardings — flat and
    layer-stacked layouts both — so the elastic reshape can target it
    without materializing a throwaway state."""
    mesh4 = make_mesh(devices8[:4], data=4)
    tx = optax.adam(1e-2)
    for params, llama in (
        ({"w1": jnp.ones((12, 20)), "b1": jnp.zeros((20,)),
          "w2": jnp.ones((20, 4))}, False),
        ({"blocks": {"wq": jnp.ones((3, 6, 5))},
          "embed": jnp.ones((7, 4))}, True),
    ):
        t_abs = zero.zero_resume_template(
            params, tx, mesh4, llama=llama, abstract=True
        )
        t_con = zero.zero_resume_template(params, tx, mesh4, llama=llama)
        flat_a = jax.tree_util.tree_flatten_with_path(t_abs)[0]
        flat_c = jax.tree_util.tree_flatten_with_path(t_con)[0]
        assert len(flat_a) == len(flat_c)
        for (pa, la), (_pc, lc) in zip(flat_a, flat_c):
            assert isinstance(la, jax.ShapeDtypeStruct), pa
            assert la.shape == lc.shape, pa
            assert la.dtype == lc.dtype, pa
            assert la.sharding.spec == lc.sharding.spec, pa


# ------------------------------------------- kill-free reshape equivalence


@pytest.fixture(scope="module")
def zero_world(devices8):
    """One compile each of the 8-way and 4-way ZeRO-3 steps plus the
    shared batch stream — both reshape-equivalence tests and the
    signature re-pin ride these two compiles."""
    k0 = jax.random.PRNGKey(0)
    params = {
        "w1": jax.random.normal(jax.random.fold_in(k0, 0), (12, 20)) * 0.1,
        "b1": jnp.zeros((20,)),
        "w2": jax.random.normal(jax.random.fold_in(k0, 1), (20, 4)) * 0.1,
    }

    def loss_fn(p, batch, key):
        del key
        x, yb = batch
        return jnp.mean(
            (jnp.tanh(x @ p["w1"] + p["b1"]) @ p["w2"] - yb) ** 2
        )

    tx = optax.adam(1e-2)
    mesh8 = make_mesh(devices8, data=8)
    mesh4 = make_mesh(devices8[:4], data=4)
    batches = [
        (
            jax.random.normal(jax.random.fold_in(k0, 10 + i), (16, 12)),
            jax.random.normal(jax.random.fold_in(k0, 20 + i), (16, 4)),
        )
        for i in range(4)
    ]
    world = {
        "params": params, "loss_fn": loss_fn, "tx": tx,
        "mesh8": mesh8, "mesh4": mesh4, "batches": batches,
        "key": jax.random.PRNGKey(1),
        "step8": zero.make_zero_dp_train_step(
            loss_fn, tx, mesh8, params, per_shard_rng=False
        ),
        "step4": zero.make_zero_dp_train_step(
            loss_fn, tx, mesh4, params, per_shard_rng=False
        ),
    }
    # the oracle: 4 uninterrupted steps on the 4-way mesh (ZeRO's math
    # is mesh-size-independent, so every elastic trajectory must land
    # here no matter which meshes it visited in between)
    s, o = zero.zero_shard_params(params, mesh4), None
    o = tx.init(s)
    for b in batches:
        s, o, _ = world["step4"](s, o, b, world["key"])
    world["p_ref"] = zero.zero_unshard_params(s, params)
    return world


def _run_elastic(world, first_mesh, first_step, second_mesh, second_step):
    """Two steps on one mesh, a LIVE in-run reshape (no checkpoint, no
    subprocess), two steps on the other; returns unsharded params."""
    w = world
    s = zero.zero_shard_params(w["params"], first_mesh)
    o = w["tx"].init(s)
    for b in w["batches"][:2]:
        s, o, _ = first_step(s, o, b, w["key"])
    tmpl = zero.zero_resume_template(
        w["params"], w["tx"], second_mesh, abstract=True
    )
    state = elastic.reshape_state(
        {"params": s, "opt_state": o},
        {"params": tmpl["params"], "opt_state": tmpl["opt_state"]},
    )
    s, o = state["params"], state["opt_state"]
    # the reshaped leaves carry the target mesh's layout exactly
    lead = second_mesh.shape["data"]
    assert s["w1"].shape[0] == lead
    assert s["w1"].sharding.spec == jax.tree.leaves(
        tmpl["params"]
    )[0].sharding.spec
    for b in w["batches"][2:]:
        s, o, _ = second_step(s, o, b, w["key"])
    return zero.zero_unshard_params(s, w["params"]), (s, o)


def test_reshape_8_to_4_matches_uninterrupted(zero_world, devices8):
    """The kill-free half of the PR-6 cross-mesh pin: 8 -> 4 mid-run
    via the LIVE device-to-device path (abstract template, no orbax)
    followed by the remaining steps matches the uninterrupted 4-way
    run — same tolerance as the checkpointed twin, with a reshape
    flight event recorded."""
    from ddl25spring_tpu.obs import flight

    w = zero_world
    before = flight.counts().get("reshape", 0)
    p_res, (s4, o4) = _run_elastic(
        w, w["mesh8"], w["step8"], w["mesh4"], w["step4"]
    )
    ev = elastic.record_reshape(
        old=w["mesh8"], new=w["mesh4"], wall_s=0.01, steps_lost=0,
        reason="device_loss",
    )
    assert ev["old"] == {"data": 8} and ev["new"] == {"data": 4}
    assert flight.counts().get("reshape", 0) == before + 1
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        ),
        p_res, w["p_ref"],
    )

    # the acceptance contract's last clause: the post-reshape step's
    # collective signature re-pins clean on the surviving mesh (same
    # expected shape as zero.describe(stage=3), like the PR-6 test)
    from ddl25spring_tpu.obs import xla_analytics as xa
    from ddl25spring_tpu.parallel import bucketing

    n = 4
    padded = sum(
        n * (-(-int(np.prod(leaf.shape) or 1) // n)) * 4
        for leaf in jax.tree.leaves(w["params"])
    )
    launches = zero._row_plan(
        w["params"], n, bucketing.DEFAULT_BUCKET_BYTES
    ).n_buckets
    compiled = w["step4"].lower(
        s4, o4, w["batches"][-1], w["key"]
    ).compile()
    rep = xa.analyze_compiled(compiled, w["mesh4"])
    expected = {
        "scalar_bytes": 64,
        "all-gather": {
            "min_bytes": padded, "max_bytes": 2 * padded + 256,
            "axes": ["data"],
            "min_count": launches, "max_count": 2 * launches,
        },
        "reduce-scatter": {
            "min_bytes": padded // n, "max_bytes": padded // n + 256,
            "axes": ["data"],
            "min_count": launches, "max_count": launches,
        },
        "all-reduce": {"max_bytes": 64},
        "forbidden": ["collective-permute", "all-to-all"],
    }
    assert xa.check_signature(rep, expected) == []


def test_grow_back_4_to_8_matches_uninterrupted(zero_world):
    """The grow-back cycle: capacity returns mid-run (4 -> 8) and the
    run re-expands onto it — same oracle, same tolerance.  Growth is
    the direction the checkpoint-relaunch path never exercised."""
    w = zero_world
    p_res, _ = _run_elastic(
        w, w["mesh4"], w["step4"], w["mesh8"], w["step8"]
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        ),
        p_res, w["p_ref"],
    )


def test_rules_relower_with_mesh_and_lint_clean(
    devices8, strategy_report
):
    """The rule-engine re-lower seam: with_mesh carries the SAME table
    onto the survivor mesh (strategy-as-data — no new module, no
    builder fork), elastic.relower routes a table through it, and the
    zero3-rules strategy pins graft-lint/graft-shard CLEAN on the
    4-way survivor mesh (the session cache's default mesh IS the
    surviving size of the 8 -> 4 pin above)."""
    mesh8 = make_mesh(devices8, data=8)
    mesh4 = make_mesh(devices8[:4], data=4)
    part8 = RulePartitioner(mesh8, TABLES["zero3"])
    part4 = part8.with_mesh(mesh4)
    assert part4.table is part8.table
    assert part4.mesh is mesh4
    assert part4.axis == part8.axis

    # relower() builds a runnable step on the survivor without tracing
    params = {"w1": jnp.ones((8, 4)), "b1": jnp.zeros((4,))}

    def loss_fn(p, batch, key):
        del key
        x, y = batch
        return jnp.mean((x @ p["w1"] + p["b1"] - y) ** 2)

    step = elastic.relower(
        part8, mesh4, loss_fn=loss_fn, tx=optax.sgd(0.1),
        params_template=params, per_shard_rng=False, donate=False,
    )
    assert callable(step)

    # graft-lint + graft-shard clean on the surviving mesh: compile
    # analytics' registered zero3-rules entry (default mesh = 4) via
    # the session's lower-once cache — zero extra compiles here
    rep = strategy_report("zero3-rules")
    assert rep["mesh"] == {"data": 4}
    unwaived = [
        f for f in rep.get("findings", []) if not f.get("waived")
    ]
    assert unwaived == [], unwaived
    assert rep.get("signature_violations") == []
    assert rep["meta"]["rule_table"]["name"] == "zero3-rules"


def test_autosaver_note_reshape_refreshes_leaf_shapes(tmp_path):
    """After a reshape the manifest's recorded leaf_shapes are the OLD
    mesh's — stale for the next cross-mesh resume.  note_reshape drops
    the cache (and the prior lineage's copy) so the next save records
    the survivor layout."""
    from ddl25spring_tpu.ft import AutoSaver, read_manifest, resume_bundle

    saver = AutoSaver(tmp_path / "ck", save_every=1, async_save=False)
    saver.save(0, resume_bundle({"w": jnp.ones((8, 4))}, {}))
    man = read_manifest(tmp_path / "ck")
    shapes = [tuple(s) for s, _ in man["leaf_shapes"]]
    assert (8, 4) in shapes
    saver.note_reshape(old={"data": 8}, new={"data": 4}, step=1)
    saver.save(1, resume_bundle({"w": jnp.ones((4, 8))}, {}))
    saver.close()
    man = read_manifest(tmp_path / "ck")
    shapes = [tuple(s) for s, _ in man["leaf_shapes"]]
    assert (4, 8) in shapes and (8, 4) not in shapes
    assert man["meta"]["reshape"]["new"] == {"data": 4}


def test_surviving_devices_bounds(devices8):
    assert len(elastic.surviving_devices(devices8, lose=4)) == 4
    assert len(elastic.surviving_devices(devices8, size=2)) == 2
    with pytest.raises(ValueError):
        elastic.surviving_devices(devices8, lose=8)
    with pytest.raises(ValueError):
        elastic.surviving_devices(devices8, size=9)


# ----------------------------------------------------- serve: handoff


@pytest.fixture(scope="module")
def serve_world():
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.utils.config import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
        dtype="float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    # the test_serve smoke geometry — every compiled program rides the
    # session-wide _PROGRAM_CACHE shared with tests/test_serve.py
    knobs = dict(
        page_len=4, n_pages=16, max_slots=2, prefill_batch=2,
        max_prompt_len=8, max_queue=32, token_budget=None, eos_id=None,
        prefix_cache=False, spec_k=0, draft_layers=1,
    )
    return cfg, params, knobs


def _dense_oracle(params, cfg, prompt, max_new):
    from conftest import cached_lowering
    from ddl25spring_tpu.models import decode as dm

    def build():
        toks = dm.generate(
            params, jnp.asarray([prompt], jnp.int32), cfg,
            max_new_tokens=max_new, temperature=0.0,
        )
        return [int(t) for t in np.asarray(toks)[0]]

    return cached_lowering(
        ("serve-dense", tuple(prompt), max_new), build
    )


def test_scale_down_handoff_zero_drops_token_exact(
    serve_world, tmp_path
):
    """device_loss mid-traffic: the victim replica drains its live
    slots through the ordinary release discipline, its queued requests
    re-admit on the survivor, NOTHING accepted is lost, and every
    completed stream is token-for-token the dense oracle's — the
    handoff moved scheduling, never tokens."""
    from ddl25spring_tpu.serve.driver import elastic_serve_run

    cfg, params, knobs = serve_world
    prompt_a, new_a = [5, 9, 11, 3], 9
    prompt_b, new_b = [7, 2, 8], 6
    trace = [
        {"t": 0.0, "prompt": prompt_a, "max_new": new_a},
        {"t": 0.0, "prompt": prompt_b, "max_new": new_b},
        {"t": 0.001, "prompt": prompt_a, "max_new": new_a},
        {"t": 0.001, "prompt": prompt_b, "max_new": new_b},
        {"t": 0.002, "prompt": prompt_a, "max_new": new_a},
        {"t": 0.002, "prompt": prompt_b, "max_new": new_b},
    ]
    chaos = ChaosInjector(
        parse_chaos("device_loss@2"), state_dir=tmp_path
    )
    cell = elastic_serve_run(
        params, cfg, trace, knobs, chaos=chaos, replicas=2,
        keep_requests=True,
    )
    assert cell["dropped_requests"] == 0
    assert cell["submitted"] == 6
    assert cell["completed"] + cell["rejected"] == 6
    assert cell["completed"] >= 4  # the tiny queue bound may reject
    (ev,) = cell["events"]
    assert ev["reason"] == "device_loss"
    assert ev["old"] == 2 and ev["new"] == 1
    assert ev["t_end"] >= ev["t"]  # the drain ran to completion
    assert cell["replicas_end"] == 1
    # token-exactness across the handoff: whichever replica served a
    # request — including those re-admitted from the victim's queue —
    # the stream is the dense oracle's
    oracle = {
        (tuple(prompt_a), new_a): _dense_oracle(
            params, cfg, prompt_a, new_a
        ),
        (tuple(prompt_b), new_b): _dense_oracle(
            params, cfg, prompt_b, new_b
        ),
    }
    for req in cell["_requests"]:
        assert req.tokens == oracle[
            (tuple(req.prompt), req.max_new_tokens)
        ], req.rid


def test_handoff_forces_past_full_survivor_queue(serve_world, tmp_path):
    """Regression: the victim's queued (already-accepted) requests must
    re-admit even when every survivor queue sits AT max_queue — the
    zero-drop contract outranks the door bound, so the handoff seats
    them directly instead of bouncing queue_full into a silent loss
    (which the dropped_requests counter could not see: they were never
    'admitted')."""
    from ddl25spring_tpu.serve.driver import elastic_serve_run

    cfg, params, knobs = serve_world
    knobs = dict(knobs, max_queue=2)
    # 4 arrivals fill both replicas' slots at t=0; 4 more land on the
    # next tick and fill both queues to the max_queue bound; the loss
    # at iteration 3 then hands the victim's 2 queued requests to a
    # survivor whose queue is already full
    trace = [
        {"t": 0.0, "prompt": [5, 9, 11, 3], "max_new": 6}
        for _ in range(4)
    ] + [
        {"t": 0.005, "prompt": [5, 9, 11, 3], "max_new": 6}
        for _ in range(4)
    ]
    chaos = ChaosInjector(
        parse_chaos("device_loss@3"), state_dir=tmp_path
    )
    cell = elastic_serve_run(
        params, cfg, trace, knobs, chaos=chaos, replicas=2,
        tick_s=0.01,
    )
    (ev,) = cell["events"]
    assert ev["requeued"] == 2, cell["events"]
    assert cell["submitted"] == 8
    assert cell["rejected"] == 0
    assert cell["completed"] == 8  # every accepted request served
    assert cell["dropped_requests"] == 0


def test_traffic_spike_autoscales_and_windows_defined(
    serve_world, tmp_path
):
    """A deterministic traffic_spike burst drives the queue-depth
    autoscaler into a scale-up, the reshape cell splits TTFT into
    window vs steady, and the --check-reshape gate passes the cell."""
    from tools.serve_report import check_reshape

    from ddl25spring_tpu.serve.driver import elastic_serve_run

    cfg, params, knobs = serve_world
    trace = [
        {"t": 0.001 * i, "prompt": [5, 9, 11, 3], "max_new": 6}
        for i in range(8)
    ]
    chaos = ChaosInjector(
        parse_chaos("traffic_spike@1:12,device_loss@8"),
        state_dir=tmp_path,
    )
    cell = elastic_serve_run(
        params, cfg, trace, knobs, chaos=chaos, replicas=2,
        max_replicas=3,
    )
    reasons = [e["reason"] for e in cell["events"]]
    assert "traffic_spike_scale_up" in reasons, cell["events"]
    assert "device_loss" in reasons
    assert cell["dropped_requests"] == 0
    assert cell["reshape_window_requests"] >= 1
    assert cell["ttft_s_p95_reshape"] is not None
    # the gate's verdict on this cell (ledger-row shaped): clean
    assert check_reshape([{"reshape": cell}], ttft_factor=50.0) == []


def test_check_reshape_gate_refuses_bad_cells():
    """Every failure mode the gate exists for: no cell, no events,
    dropped requests, a vacuous (empty) window, and an unbounded
    TTFT blowup."""
    from tools.serve_report import check_reshape

    good = {
        "events": [{"reason": "device_loss", "old": 2, "new": 1,
                    "t": 0.1, "t_end": 0.2}],
        "dropped_requests": 0,
        "admitted": 10, "completed": 10,
        "ttft_s_p95_steady": 0.1, "ttft_s_p95_reshape": 0.2,
        "reshape_window_requests": 3, "steady_requests": 7,
    }
    assert check_reshape([{"reshape": good}]) == []
    assert check_reshape([{}])  # no cell at all
    assert any(
        "no events" in f
        for f in check_reshape([{"reshape": {**good, "events": []}}])
    )
    assert any(
        "dropped_requests=2" in f
        for f in check_reshape(
            [{"reshape": {**good, "dropped_requests": 2,
                          "completed": 8}}]
        )
    )
    assert any(
        "vacuous" in f
        for f in check_reshape(
            [{"reshape": {**good, "reshape_window_requests": 0}}]
        )
    )
    assert any(
        "exceeds" in f
        for f in check_reshape(
            [{"reshape": {**good, "ttft_s_p95_reshape": 0.5}}]
        )
    )
    # and the factor knob moves the bound
    assert check_reshape(
        [{"reshape": {**good, "ttft_s_p95_reshape": 0.5}}],
        ttft_factor=10.0,
    ) == []


def test_engine_begin_drain_blocks_admission_and_hands_off(serve_world):
    """The engine-level handoff contract directly: a draining engine
    admits nothing, returns its queued (never-admitted) requests, and
    reports drained exactly when its live slots have released."""
    from ddl25spring_tpu.serve.engine import ServeEngine

    cfg, params, knobs = serve_world
    eng = ServeEngine(params, cfg, clock="virtual", **knobs)
    r1 = eng.make_request([5, 9, 11, 3], 3)
    r2 = eng.make_request([7, 2, 8], 3)
    r3 = eng.make_request([7, 2, 8, 1], 3)
    for r in (r1, r2, r3):
        assert eng.submit(r) is None
    eng.step()  # admits r1+r2 (prefill width 2), r3 still queued
    assert eng.admitted == 2
    handoff = eng.begin_drain()
    assert [r.rid for r in handoff] == [r3.rid]
    assert not eng.drained  # r1/r2 still decoding
    steps = 0
    while not eng.drained:
        eng.step()
        steps += 1
        assert steps < 50, "draining engine failed to finish live work"
    assert eng.completed == 2
    assert eng.admitted == 2  # r3 was never admitted here
    # a draining replica bounces direct submits with its own reason —
    # it must never accumulate work it will not admit
    from ddl25spring_tpu.serve.engine import REJECT_DRAINING

    assert eng.submit(eng.make_request([5], 2)) == REJECT_DRAINING
    assert eng.drained  # still empty: the bounce never queued


def test_flight_record_and_recovery_report_carry_reshape(tmp_path):
    """The observability half: a reshape flight record lands in the
    dump, summarize_run surfaces it under recovery, and the health
    gate stays green (a reshape is recovery, not a violation)."""
    from ddl25spring_tpu.obs.recorder import FlightRecorder
    from ddl25spring_tpu.obs.report import summarize_run

    fr = FlightRecorder()
    fr.configure(run_dir=str(tmp_path))
    fr.record(
        kind="reshape", scope="train", reason="device_loss",
        old={"data": 2}, new={"data": 1}, wall_s=0.5, steps_lost=0,
    )
    path = fr.dump(reason="end_of_run")
    doc = json.loads(open(path).read())
    assert doc["counts"]["reshape"] == 1
    s = summarize_run(str(tmp_path))
    assert s["recovery"]["reshapes"] == 1
    assert s["recovery"]["last_reshape"]["reason"] == "device_loss"
    assert s["health"].get("violations", 0) == 0
