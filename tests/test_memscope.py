"""graft-mem (``ddl25spring_tpu/obs/memscope.py`` + serve/bench wiring
+ ``tools/mem_report.py``): the runtime memory observatory.

The load-bearing pins:

- **leak injection fires, near-miss stays quiet** — a page seated in a
  page-table row across drain is named slot-and-rid by the detector; a
  pool whose only residue is prefix-cache-held pages passes.  A host
  list growing monotonically across a training window fires the growth
  detector ONCE naming the watch; a plateauing series never fires.
- **budget-vs-measured** — the serve engine's static bill covers its
  measured live-bytes high-water within the band, and
  ``mem_report --check`` turns the record's verdicts into exit codes.
- **zero cost when off** — with ``DDL25_MEMSCOPE=0`` token streams are
  bitwise identical and the decode tick lowers to byte-identical HLO
  (all sampling is host-side observation).
- **counter tracks** — ``trace_export`` renders ``mem_sample`` events
  as Perfetto ``"ph":"C"`` counters on the PR-16 time base, and
  ``--min-counter-tracks`` gates their presence.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.obs import memscope, state
from ddl25spring_tpu.obs.memscope import (
    GrowthDetector,
    MemScope,
    Series,
    budget_cell,
    host_rss_bytes,
    live_array_summary,
    mem_cell,
    mem_record,
    pool_leak_check,
    pool_snapshot,
    write_run_mem,
)
from ddl25spring_tpu.obs.recorder import flight
from ddl25spring_tpu.obs.timeline import timeline
from ddl25spring_tpu.serve.engine import ServeEngine
from ddl25spring_tpu.utils.config import LlamaConfig

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


def make_engine(params, **kw):
    # the test_serve smoke geometry — every compiled program rides the
    # session-wide program cache shared with tests/test_serve.py
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


# ------------------------------------------------ series + detector


def test_series_below_cap_is_exact_and_summarized():
    s = Series(cap=8)
    for v in (3, 1, 9, 4):
        s.append(v)
    assert list(s) == [3, 1, 9, 4]
    d = s.summary()
    assert d["count"] == 4 and d["sampled"] == 4
    assert d["max"] == 9 and d["min"] == 1


def test_series_caps_memory_but_keeps_exact_extremes():
    s = Series(cap=4)
    for v in range(100):
        s.append(v)
    assert len(s) == 4
    d = s.summary()
    assert d["count"] == 100 and d["sampled"] == 4
    assert d["max"] == 99 and d["min"] == 0


def test_growth_detector_fires_once_naming_the_source():
    det = GrowthDetector(window=4, min_growth_bytes=100)
    v = None
    for i in range(6):
        got = det.observe("replay_buffer", 1000 + i * 50, step=i)
        if got is not None:
            assert v is None, "detector fired twice"
            v = got
    assert v is not None
    assert v["kind"] == "mem" and v["source"] == "replay_buffer"
    assert v["growth_bytes"] >= 100 and v["window"] == 4
    # latched: the same still-growing series never re-fires
    assert det.observe("replay_buffer", 10_000, step=9) is None


def test_growth_detector_near_miss_plateau_stays_quiet():
    det = GrowthDetector(window=4, min_growth_bytes=100)
    # grows, but plateaus once inside every window -> not monotone
    series = [100, 200, 300, 300, 400, 500, 500, 600, 700, 700]
    assert all(
        det.observe("spiky", v, step=i) is None
        for i, v in enumerate(series)
    )


def test_growth_detector_below_floor_stays_quiet():
    det = GrowthDetector(window=4, min_growth_bytes=1 << 20)
    # strictly increasing, but by allocator-noise amounts
    assert all(
        det.observe("noise", 1000 + i, step=i) is None
        for i in range(10)
    )


# ------------------------------------------------- host-side probes


def test_host_rss_and_live_array_summary_sanity():
    rss = host_rss_bytes()
    assert rss is not None and rss > (1 << 20)
    x = jnp.ones((64, 64), jnp.float32)
    s = live_array_summary(top=5)
    assert s["count"] >= 1
    assert s["total_bytes"] >= x.size * 4
    assert s["largest"], s
    top = s["largest"][0]
    for k in ("shape", "dtype", "bytes", "sharding"):
        assert k in top, (k, top)
    assert sum(v["bytes"] for v in s["by_sharding"].values()) == (
        s["total_bytes"]
    )
    del x


def test_flight_dump_carries_live_array_summary(tmp_path):
    """Satellite 1: a crash dump answers 'what was resident' — the
    live-array census rides every flight.json."""
    keep = jnp.arange(4096, dtype=jnp.int32)  # resident at dump time
    path = flight.dump(str(tmp_path / "flight.json"), reason="test")
    doc = json.load(open(path))
    la = doc["live_arrays"]
    assert la["count"] >= 1
    assert la["total_bytes"] >= keep.nbytes
    assert doc["host_rss_bytes"] > (1 << 20)
    assert any(
        v["bytes"] == keep.nbytes for v in la["largest"]
    ), la["largest"]


# -------------------------------------------- scope sampling + gating


def test_memscope_sample_is_gated_and_thinned():
    resident = jnp.ones((32, 32), jnp.float32)  # noqa: F841
    scope = MemScope(label="t", every=2)
    assert scope.sample(0) is None  # obs off -> no-op
    with state.scoped(True):
        s0 = scope.sample(0)
        s1 = scope.sample(1)  # off-cadence (every=2)
        s2 = scope.sample(2)
    assert s0 is not None and s2 is not None and s1 is None
    assert scope.live_bytes_peak >= s0["live_bytes"] > 0
    assert scope.live_bytes_baseline == s0["live_bytes"]
    cell = scope.cell()
    assert cell["samples"] == 2 and cell["every"] == 2


def test_memscope_flag_gates_without_obs_state():
    scope = MemScope(label="t")
    with state.scoped(True), memscope.scoped(False):
        assert memscope.enabled() is False
        assert scope.sample(0) is None
    assert len(scope.live_bytes) == 0


def test_memscope_watch_growth_fires_into_flight(tmp_path):
    """Satellite 2: a host-side list growing monotonically across the
    window fires ONE violation naming the watch, mirrored to the
    flight ring as kind="mem"."""
    buf: list[bytes] = []
    scope = MemScope(label="train", window=4, min_growth_bytes=64)
    scope.watch("replay_buffer", lambda: len(buf) * 1024)
    with state.scoped(True):
        for i in range(8):
            buf.append(b"x")
            scope.sample(i)
    assert len(scope.violations) == 1
    v = scope.violations[0]
    assert v["source"] == "replay_buffer" and v["scope"] == "train"
    assert scope.cell()["growth_violations"] == [v]
    recs = [
        r for r in flight.snapshot()["records"]
        if r.get("kind") == "mem"
        and r.get("source") == "replay_buffer"
    ]
    assert recs, "growth violation never reached the flight ring"


def test_memscope_near_miss_watch_stays_quiet():
    sizes = [100, 200, 300, 300, 400, 500, 500, 600]  # plateaus
    it = iter(sizes)
    scope = MemScope(label="train", window=4, min_growth_bytes=64)
    scope.watch("steady_cache", lambda: next(it))
    with state.scoped(True):
        for i in range(len(sizes)):
            scope.sample(i)
    assert scope.violations == []


# ----------------------------------------------- pool telemetry


def test_pool_snapshot_and_clean_drain_leak_check(params):
    timeline.configure(None)
    eng = make_engine(params)
    with state.scoped(True):
        for i in range(3):
            assert eng.submit(
                eng.make_request([5 + i, 9, 11, 3], 4)) is None
        drain(eng)
    # the leak check first: it flushes the batched releases the drain
    # left pending, settling the device tables the snapshot reads
    leak = eng.mem_leak_check()
    assert leak["ok"] is True and leak["leaked_pages"] == 0
    assert leak["leaks"] == []
    snap = eng.mem_pool_snapshot()
    assert snap["n_pages"] == 16
    assert snap["used_pages"] == (
        snap["cache_held_pages"] + snap["table_held_pages"]
    )
    assert snap["table_held_pages"] == 0  # drained + flushed
    assert 0.0 <= snap["fragmentation"] <= 1.0
    # the histogram covers exactly the held pages (ref > 0)
    assert sum(snap["refcount_hist"].values()) == snap["used_pages"]
    # the sampler rode every tick: peak within the static bill's band
    assert eng.memscope.live_bytes_peak > 0
    budget = eng.mem_budget_bytes()
    assert budget > 0
    assert budget_cell(
        eng.memscope.live_bytes_peak, budget
    )["within_band"] is True


def test_injected_page_table_leak_is_named_by_slot_and_rid(params):
    """Satellite 2: seat a page back into a page-table row after drain
    — the detector must fail naming the slot and the last rid that
    occupied it, and the verdict must reach the flight ring."""
    timeline.configure(None)
    eng = make_engine(params)
    with state.scoped(True):
        req = eng.make_request([5, 9, 11, 3], 4)
        assert eng.submit(req) is None
        drain(eng)
        # the injection: page 7 held by slot 1's table row + refcount
        pool = dict(eng.pool)
        pool["page_table"] = pool["page_table"].at[1, 0].set(7)
        pool["refcount"] = pool["refcount"].at[7].add(1)
        pool["free"] = pool["free"].at[7].set(False)
        eng.pool = pool
        eng._slot_last_rid[1] = req.rid
        leak = eng.mem_leak_check()
    assert leak["ok"] is False
    assert leak["leaked_pages"] == 1
    (entry,) = [x for x in leak["leaks"] if x["held_by"] == "page_table"]
    assert entry["page"] == 7 and entry["slot"] == 1
    assert entry["rid"] == req.rid
    recs = [
        r for r in flight.snapshot()["records"]
        if r.get("kind") == "mem" and r.get("source") == "kv_pool_leak"
    ]
    assert recs and recs[-1]["leaked_pages"] == 1


def test_orphan_refcount_beyond_cache_budget_is_a_leak():
    import numpy as np

    pool = {
        "free": np.array([False, False, True, True]),
        "refcount": np.array([1, 1, 0, 0]),
        "page_table": np.full((2, 2), -1),
    }
    # both held pages accounted to the cache -> clean
    ok = pool_leak_check(pool, cache_held_pages=2)
    assert ok["ok"] is True and ok["leaks"] == []
    # only one accounted -> one orphan leak
    bad = pool_leak_check(pool, cache_held_pages=1)
    assert bad["ok"] is False and bad["leaked_pages"] == 1
    (entry,) = bad["leaks"]
    assert entry["held_by"] == "orphan_refcount"


def test_pool_snapshot_fragmentation_of_interleaved_free_pages():
    import numpy as np

    pool = {
        "free": np.array([True, False, True, False, True, True]),
        "refcount": np.array([0, 1, 0, 1, 0, 0]),
        "page_table": np.full((2, 2), -1),
    }
    snap = pool_snapshot(pool)
    assert snap["used_pages"] == 2 and snap["free_pages"] == 4
    assert snap["free_runs"]["count"] == 3
    assert snap["free_runs"]["max"] == 2
    assert snap["fragmentation"] == pytest.approx(1 - 2 / 4)


# ------------------------------------------------ zero cost when off


def test_tokens_bitwise_identical_with_memscope_off(params):
    """Satellite 3: DDL25_MEMSCOPE=0 under obs-on leaves token streams
    and the virtual clock bitwise unchanged — sampling is host-only."""
    timeline.configure(None)

    def run(mem_on: bool):
        eng = make_engine(params, prefill_batch=2)
        with state.scoped(True), memscope.scoped(mem_on):
            reqs = [
                eng.make_request([5 + i, 9, 11, 3], 6) for i in range(3)
            ]
            for r in reqs:
                assert eng.submit(r) is None
            drain(eng)
        return [r.tokens for r in reqs], eng.now(), eng._vtime

    off_tokens, off_now, off_vt = run(False)
    on_tokens, on_now, on_vt = run(True)
    assert on_tokens == off_tokens
    assert on_now == off_now and on_vt == off_vt


def test_decode_tick_hlo_identical_with_memscope_toggled(params):
    """Satellite 3: the decode tick lowers to byte-identical HLO with
    the scope on or off — graft-mem never touches a compiled program."""
    from ddl25spring_tpu.serve import kv_pages
    from ddl25spring_tpu.serve.engine import make_decode_tick

    pool = kv_pages.init_page_pool(
        CFG, n_pages=16, page_len=4, max_slots=2, pages_per_seq=4,
    )
    args = (
        params, pool, jnp.zeros((2,), jnp.int32), jax.random.PRNGKey(0),
    )

    def lower():
        tick = make_decode_tick(CFG, temperature=0.0, sentinel=False)
        return jax.jit(tick).lower(*args).as_text()

    with state.scoped(True), memscope.scoped(False):
        off = lower()
    with state.scoped(True), memscope.scoped(True):
        on = lower()
    assert on == off


def test_mem_sample_timeline_events_present_iff_scope_on(
    params, tmp_path
):
    from ddl25spring_tpu.obs.timeline import read_timeline

    def run(mem_on: bool, sub: str):
        run_dir = tmp_path / sub
        timeline.configure(str(run_dir))
        try:
            with state.scoped(True), memscope.scoped(mem_on):
                eng = make_engine(params)
                assert eng.submit(
                    eng.make_request([5, 9, 11, 3], 4)) is None
                drain(eng)
                timeline.flush()
        finally:
            timeline.configure(None)
        _, events = read_timeline(str(run_dir))
        return [e for e in events if e["kind"] == "mem_sample"]

    on = run(True, "on")
    assert on, "no mem_sample events with the scope on"
    for e in on:
        assert e["live_bytes"] > 0
        assert e["engine"] == "serve"
        assert e["pool_pages"] == 16
        assert "pool_used" in e and "queue_depth" in e
    assert run(False, "off") == []


# -------------------------------------- record envelope + the gates


def _good_record(**over):
    scope = MemScope(label="t")
    with state.scoped(True):
        scope.sample(0)
    rec = mem_record(
        strategy="serve/tiny",
        mesh={"replicas": 1},
        scope_cell=scope.cell(),
        budget=budget_cell(100, 100),
        pool=None,
        leaks=[{"ok": True, "leaked_pages": 0, "leaks": []}],
    )
    rec.update(over)
    return rec


def test_mem_record_round_trips_through_mem_json_and_cell(tmp_path):
    rec = _good_record()
    assert rec["record"] == "mem" and rec["leaked_pages"] == 0
    path = write_run_mem(rec, str(tmp_path))
    assert json.load(open(path)) == json.loads(json.dumps(rec))
    cell = mem_cell(rec)
    assert cell["enabled"] is True
    assert cell["live_bytes_peak"] > 0
    assert cell["budget"]["within_band"] is True
    assert cell["leaked_pages"] == 0
    assert cell["growth_violations"] == 0


def test_budget_cell_band_semantics():
    assert budget_cell(149, 100, tol=0.5)["within_band"] is True
    assert budget_cell(151, 100, tol=0.5)["within_band"] is False
    assert budget_cell(100, None)["available"] is False
    assert budget_cell(100, 0)["available"] is False


def test_mem_report_check_passes_clean_and_fails_injected_leak(
    tmp_path,
):
    from tools.mem_report import main as mem_main

    good = tmp_path / "good"
    good.mkdir()
    write_run_mem(_good_record(), str(good))
    assert mem_main(["--run", str(good), "--check"]) == 0

    leaky = tmp_path / "leaky"
    leaky.mkdir()
    write_run_mem(_good_record(
        leaked_pages=2,
        leaks=[{"ok": False, "leaked_pages": 2, "leaks": [
            {"page": 7, "refcount": 1, "held_by": "page_table",
             "slot": 1, "rid": 3},
            {"page": 9, "refcount": 2, "held_by": "orphan_refcount"},
        ]}],
    ), str(leaky))
    assert mem_main(["--run", str(leaky), "--check"]) == 1

    breach = tmp_path / "breach"
    breach.mkdir()
    write_run_mem(
        _good_record(budget=budget_cell(200, 100, tol=0.5)),
        str(breach),
    )
    assert mem_main(["--run", str(breach), "--check"]) == 1
    # no mem.json at all -> no-data exit, distinct from a failure
    assert mem_main(["--run", str(tmp_path / "void"), "--check"]) == 2


def test_mem_report_require_step_down(tmp_path):
    from tools.mem_report import main as mem_main

    flat = tmp_path / "flat"
    flat.mkdir()
    write_run_mem(_good_record(reshape_steps=[]), str(flat))
    assert mem_main(
        ["--run", str(flat), "--check", "--require-step-down"]) == 1

    stepped = tmp_path / "stepped"
    stepped.mkdir()
    write_run_mem(_good_record(reshape_steps=[{
        "scope": "serve", "reason": "device_loss",
        "live_bytes_before": 1000, "live_bytes_after": 400,
        "step_down_bytes": 600, "leak_ok": True, "leaked_pages": 0,
    }]), str(stepped))
    assert mem_main(
        ["--run", str(stepped), "--check", "--require-step-down"]) == 0


def test_obs_report_exit_code_4_on_mem_violation(tmp_path):
    """Satellite 6: the documented exit-code matrix — a leaky mem.json
    under --check-health exits 4, distinct from health's 3."""
    from tools.obs_report import main as obs_main

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "metrics.jsonl").write_text("")
    write_run_mem(_good_record(leaked_pages=1), str(run_dir))
    assert obs_main([str(run_dir), "--check-health"]) == 4
    write_run_mem(_good_record(), str(run_dir))
    assert obs_main([str(run_dir), "--check-health"]) == 0


# ------------------------------------------------- counter tracks


def test_trace_export_renders_counter_tracks_and_gates(tmp_path):
    from tools.trace_export import main as export_main, merge

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    lines = [
        {"record": "timeline_header", "time_origin_unix_s": 1000.0,
         "capacity": 16, "pid": 1},
    ]
    for i in range(4):
        lines.append({
            "record": "event", "seq": i, "kind": "mem_sample",
            "t_wall_s": 0.1 * i, "engine": "serve", "replica": 0,
            "live_bytes": 1000 + i, "rss_bytes": 5000 + i,
            "pool_used": i, "queue_depth": 4 - i,
            "tokens_per_s": 10.0 * i,
        })
    with open(run_dir / "timeline.jsonl", "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")

    doc, notes = merge(str(run_dir))
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    names = {e["name"] for e in counters}
    assert notes["counter_tracks"] == len(names) == 5
    # every counter rides the shared time base (t_wall_s * 1e6)
    assert sorted({e["ts"] for e in counters}) == pytest.approx(
        [0.1 * i * 1e6 for i in range(4)]
    )
    for e in counters:
        assert e["pid"] == 1_000_002
        (field,) = e["args"].keys()
        assert e["name"].startswith(f"{field} [serve/r0]")

    assert export_main(
        [str(run_dir), "--check", "--min-counter-tracks", "3"]) == 0
    assert export_main(
        [str(run_dir), "--check", "--min-counter-tracks", "6"]) == 1


def test_trace_export_counter_gate_fails_without_mem_samples(tmp_path):
    from tools.trace_export import main as export_main

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    with open(run_dir / "timeline.jsonl", "w") as f:
        f.write(json.dumps({
            "record": "timeline_header", "time_origin_unix_s": 1000.0,
            "capacity": 16, "pid": 1,
        }) + "\n")
    assert export_main([str(run_dir), "--check"]) == 0
    assert export_main(
        [str(run_dir), "--check", "--min-counter-tracks", "1"]) == 1
