"""DP correctness: the reference's strongest testing idea is equivalence as a
correctness oracle (homework A1, ``lab/series01.ipynb`` cell 9; SURVEY §4).
Here: DP-sharded trainstep == single-device trainstep on the same global
batch, to fp32 tolerance."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.data.mnist import load_mnist
from ddl25spring_tpu.models.mnist_cnn import MnistCnn
from ddl25spring_tpu.ops.losses import nll_loss
from ddl25spring_tpu.parallel.dp import (
    make_dp_train_step,
    make_dp_weight_avg_step,
    make_train_step,
    stack_opt_state,
)
from ddl25spring_tpu.utils.mesh import make_mesh


@pytest.fixture(scope="module")
def setup():
    model = MnistCnn()
    data = load_mnist(n_train=512, n_test=256)
    key = jax.random.PRNGKey(0)
    params = model.init(key, data["x_train"][:1])["params"]

    def loss_fn(params, batch, key):
        x, y = batch
        out = model.apply({"params": params}, x, train=False)
        return nll_loss(out, y)

    return model, data, params, loss_fn


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_dp_equals_serial(setup, n_dev, devices8):
    _, data, params, loss_fn = setup
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)
    mesh = make_mesh(devices8[:n_dev], data=n_dev)

    serial = make_train_step(loss_fn, tx)
    dp = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)

    batch = (jnp.asarray(data["x_train"][:64]), jnp.asarray(data["y_train"][:64]))
    key = jax.random.PRNGKey(1)

    p_s, o_s, loss_s = serial(params, opt_state, batch, key)
    p_d, o_d, loss_d = dp(params, opt_state, batch, key)

    np.testing.assert_allclose(float(loss_s), float(loss_d), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5),
        p_s,
        jax.device_get(p_d),
    )


def test_dp_loss_decreases(setup, devices8):
    _, data, params, loss_fn = setup
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    mesh = make_mesh(devices8[:4], data=4)
    dp = make_dp_train_step(loss_fn, tx, mesh)

    key = jax.random.PRNGKey(2)
    batch = (
        jnp.asarray(data["x_train"][:64]),
        jnp.asarray(data["y_train"][:64]),
    )
    losses = []
    for i in range(20):
        params, opt_state, loss = dp(params, opt_state, batch, jax.random.fold_in(key, i))
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0]


def test_weight_avg_equals_grad_avg_for_sgd(setup, devices8):
    """With plain SGD and sync-every-step, averaging weights after local
    steps == averaging gradients (linearity) — the equivalence the homework
    A1 oracle is built on, transplanted to DP."""
    _, data, params, loss_fn = setup
    tx = optax.sgd(0.05)
    mesh = make_mesh(devices8[:4], data=4)

    ga = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
    wa = make_dp_weight_avg_step(loss_fn, tx, mesh, per_shard_rng=False)

    batch = (jnp.asarray(data["x_train"][:64]), jnp.asarray(data["y_train"][:64]))
    key = jax.random.PRNGKey(3)

    p_g, _, _ = ga(params, tx.init(params), batch, key)
    p_w, _, _ = wa(params, stack_opt_state(tx.init(params), 4), batch, key)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-5
        ),
        p_g,
        p_w,
    )
