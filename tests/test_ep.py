"""Expert-parallel MoE correctness.

Oracle (SURVEY §4 discipline): the EP-sharded MoE — tokens and experts
sharded over the ``expert`` axis with two all_to_all hops — must equal the
single-device reference when capacity is ample (no token drops; with drops
the two differ only in per-shard vs global bucket cutoffs, which is the
documented switch behavior)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.parallel.ep import (
    init_moe_params,
    make_ep_moe_fn,
    moe_ffn,
    shard_moe_params,
)
from ddl25spring_tpu.utils.mesh import make_mesh

D, F, E, T = 16, 32, 4, 64


@pytest.fixture(scope="module")
def setup():
    p = init_moe_params(jax.random.PRNGKey(0), D, F, E)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, D), jnp.float32)
    return p, x


def test_moe_routes_to_multiple_experts(setup):
    p, x = setup
    logits = x @ p["router"]
    assert len(set(np.asarray(jnp.argmax(logits, -1)).tolist())) > 1


@pytest.mark.parametrize("ep", [2, 4])
def test_ep_equals_dense_with_ample_capacity(setup, ep, devices8):
    p, x = setup
    mesh = make_mesh(devices8[:ep], expert=ep)
    # capacity_factor E: every token fits even if all pick one expert
    y_ref, aux_ref = jax.jit(lambda p, x: moe_ffn(p, x, float(E)))(p, x)
    f = make_ep_moe_fn(mesh, capacity_factor=float(E))
    y_ep, aux_ep = jax.jit(f)(shard_moe_params(p, mesh), x)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_ep), atol=1e-6, rtol=1e-5
    )
    # aux is a mean of per-shard estimators (see make_ep_moe_fn) — close,
    # not bitwise
    np.testing.assert_allclose(float(aux_ref), float(aux_ep), rtol=5e-3)


def test_ep_grads_equal_dense(setup, devices8):
    p, x = setup
    ep = 2
    mesh = make_mesh(devices8[:ep], expert=ep)
    f = make_ep_moe_fn(mesh, capacity_factor=float(E))

    # output-path grads only: the aux estimators differ per-shard vs global
    # (see make_ep_moe_fn), so exact grad equality holds for y alone
    def loss_ref(p):
        y, _ = moe_ffn(p, x, float(E))
        return (y ** 2).mean()

    def loss_ep(p):
        y, _ = f(p, x)
        return (y ** 2).mean()

    g_ref = jax.grad(loss_ref)(p)
    g_ep = jax.jit(jax.grad(loss_ep))(shard_moe_params(p, mesh))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=1e-4
        ),
        g_ref,
        g_ep,
    )


def test_capacity_overflow_drops_tokens(setup):
    p, x = setup
    # capacity 1/E of ample -> overflow tokens pass through as zeros in y
    y_tight, _ = moe_ffn(p, x, capacity_factor=0.25)
    y_ample, _ = moe_ffn(p, x, capacity_factor=float(E))
    dropped = np.asarray(jnp.all(y_tight == 0.0, axis=-1))
    assert dropped.any(), "tight capacity should drop some tokens"
    kept = ~dropped
    np.testing.assert_allclose(
        np.asarray(y_tight)[kept], np.asarray(y_ample)[kept],
        atol=1e-6, rtol=1e-5,
    )


@pytest.mark.parametrize("ep", [2, 4])
def test_ep_drop_accounting_matches_dense(setup, ep, devices8):
    """Under OVERFLOW with an unequal routing load, the EP path's
    kept/dropped accounting must equal the dense reference applied to each
    shard's token group (per-shard buckets are the documented EP
    semantics) — VERDICT r3 #10."""
    p, x = setup
    cf = 0.5  # tight capacity: forces drops
    # the natural routing load is unequal (precondition of the test)
    counts = np.bincount(
        np.asarray((x @ p["router"]).argmax(-1)), minlength=E
    )
    assert counts.max() > counts.min()

    mesh = make_mesh(devices8[:ep], expert=ep)
    f = make_ep_moe_fn(mesh, capacity_factor=cf, return_stats=True)
    y_ep, _, stats = jax.jit(f)(shard_moe_params(p, mesh), x)
    kept_ep = np.asarray(stats["kept"])
    assert float(stats["assigned"]) == T

    kept_ref = np.zeros(E, np.float32)
    for sx in x.reshape(ep, T // ep, D):  # P(axis) shards contiguously
        _, _, st = moe_ffn(p, sx, capacity_factor=cf, return_stats=True)
        kept_ref += np.asarray(st["kept"])
    np.testing.assert_allclose(kept_ep, kept_ref)

    dropped = T - kept_ep.sum()
    assert dropped > 0, "tight capacity must actually overflow"
    # dropped tokens pass through as zero rows of y — the counts agree
    zero_rows = np.asarray(jnp.all(y_ep == 0.0, axis=-1)).sum()
    assert zero_rows == dropped


def test_ep_dp_2d_mesh_equals_dense(setup, devices8):
    """EP x DP on a 2-D (data, expert) mesh: tokens shard over both axes,
    expert stacks shard over expert and replicate over data; with ample
    capacity output and grads equal the dense reference."""
    p, x = setup
    mesh = make_mesh(devices8[:4], data=2, expert=2)
    f = make_ep_moe_fn(
        mesh, capacity_factor=float(E), data_axis="data", return_stats=True
    )
    ps = shard_moe_params(p, mesh)
    y_ep, aux_ep, stats = jax.jit(f)(ps, x)
    y_ref, _ = jax.jit(lambda p, x: moe_ffn(p, x, float(E)))(p, x)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_ep), atol=1e-6, rtol=1e-5
    )
    assert float(stats["assigned"]) == T
    # ample capacity: every token kept, across all 4 shard groups
    np.testing.assert_allclose(float(np.asarray(stats["kept"]).sum()), T)

    def loss_ep(ps):
        y, _, _ = f(ps, x)
        return (y ** 2).mean()

    def loss_ref(p):
        y, _ = moe_ffn(p, x, float(E))
        return (y ** 2).mean()

    g_ep = jax.jit(jax.grad(loss_ep))(ps)
    g_ref = jax.grad(loss_ref)(p)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=1e-4
        ),
        g_ref,
        g_ep,
    )


def test_moe_trains(setup, devices8):
    p, x = setup
    mesh = make_mesh(devices8[:2], expert=2)
    f = make_ep_moe_fn(mesh, capacity_factor=2.0)
    tgt = jax.random.normal(jax.random.PRNGKey(2), (T, D))
    tx = optax.adam(1e-2)
    ps = shard_moe_params(p, mesh)
    opt = tx.init(ps)

    @jax.jit
    def step(ps, opt):
        def loss(ps):
            y, aux = f(ps, x)
            return ((y - tgt) ** 2).mean() + 0.01 * aux

        l, g = jax.value_and_grad(loss)(ps)
        up, opt = tx.update(g, opt, ps)
        return optax.apply_updates(ps, up), opt, l

    losses = []
    for _ in range(20):
        ps, opt, l = step(ps, opt)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def test_moe_llama_with_ep_moe_fn(devices8):
    """Model-level EP composition: the llama moe_fn hook routed through
    make_ep_moe_fn equals the single-device moe_ffn path at ample capacity
    (same tokens, same params, expert axis = 2)."""

    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel.ep import make_ep_moe_fn
    from ddl25spring_tpu.utils.config import LlamaConfig
    from ddl25spring_tpu.utils.mesh import make_mesh

    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=16,
        dtype="float32", n_experts=4, capacity_factor=4.0,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)

    ref_logits, ref_aux = llama.llama_forward_with_aux(params, tokens, cfg)

    mesh = make_mesh(devices8[:2], expert=2)
    ep_fn = make_ep_moe_fn(mesh, capacity_factor=cfg.capacity_factor)

    def fwd_ep(p, toks):
        x = llama.embed(p, toks, cfg)
        x, aux = llama.apply_blocks(
            p["blocks"], x, cfg, with_aux=True, moe_fn=ep_fn
        )
        return llama.unembed(p, x, cfg), aux

    ep_logits, ep_aux = jax.jit(fwd_ep)(params, tokens)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(ep_logits), atol=2e-4, rtol=2e-4
    )
    # aux estimators differ (per-shard vs global buckets) but must stay
    # finite and in the same ballpark as the reference
    assert np.isfinite(float(ep_aux))
    np.testing.assert_allclose(float(ref_aux), float(ep_aux), rtol=0.25)


# ---------------------------------------------------------------- top-k


def test_top2_matches_explicit_expert_sum(setup):
    """top_k=2 with ample capacity ≡ the literal definition: for every
    token, the renormalized-gate-weighted sum of its two highest-prob
    experts' FFN outputs."""
    p, x = setup
    y, aux = jax.jit(
        lambda p, x: moe_ffn(p, x, capacity_factor=float(E), top_k=2)
    )(p, x)

    probs = jax.nn.softmax(x @ p["router"], axis=-1)
    gates, experts = jax.lax.top_k(probs, 2)
    gates = gates / gates.sum(-1, keepdims=True)
    per_expert = jnp.stack([
        jax.nn.silu(x @ p["w_gate"][e]) * (x @ p["w_up"][e]) @ p["w_down"][e]
        for e in range(E)
    ])  # [E, T, D]
    expect = jnp.zeros_like(x)
    for j in range(2):
        expect = expect + gates[:, j:j + 1] * jnp.take_along_axis(
            per_expert, experts[:, j][None, :, None], axis=0
        )[0]
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(expect), atol=1e-5, rtol=1e-4
    )
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_top1_unchanged_by_topk_plumbing(setup):
    """top_k=1 must remain the exact switch path."""
    p, x = setup
    y1, aux1 = jax.jit(lambda p, x: moe_ffn(p, x, 2.0))(p, x)
    y2, aux2 = jax.jit(lambda p, x: moe_ffn(p, x, 2.0, top_k=1))(p, x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    assert float(aux1) == float(aux2)


@pytest.mark.parametrize("ep", [2, 4])
def test_ep_top2_equals_dense(setup, ep, devices8):
    """EP-sharded top-2 ≡ dense top-2 at ample capacity (the a2a dispatch
    carries two bucket slots per token now)."""
    p, x = setup
    mesh = make_mesh(devices8[:ep], expert=ep)
    y_ref, aux_ref = jax.jit(
        lambda p, x: moe_ffn(p, x, float(E), top_k=2)
    )(p, x)
    f = make_ep_moe_fn(mesh, capacity_factor=float(E), top_k=2)
    y_ep, aux_ep = jax.jit(f)(shard_moe_params(p, mesh), x)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_ep), atol=1e-6, rtol=1e-5
    )
    np.testing.assert_allclose(float(aux_ref), float(aux_ep), rtol=5e-3)


def test_top2_overflow_drops_second_choices_first(setup):
    """Choice-major bucket filling (GShard discipline): EVERY first
    choice outranks every second choice for bucket slots.  The oracle is
    a crafted 4-token, 2-expert, C=2 case where choice-major and
    token-major filling disagree: t0 arrives first but wants expert A
    only as its SECOND choice, while t1..t3 want A first — so A's two
    slots must go to t1, t2 (first-choicers, arrival order), NOT t0."""
    from ddl25spring_tpu.parallel.ep import _dispatch_tensors

    A, B = 0, 1
    logits = jnp.array([
        [2.0, 5.0],   # t0: first B, second A
        [5.0, 2.0],   # t1: first A, second B
        [5.0, 2.0],   # t2: first A, second B
        [5.0, 2.0],   # t3: first A, second B
    ])
    disp, combine, aux, kept = _dispatch_tensors(logits, 2, top_k=2)
    disp = np.asarray(disp)
    # expert A slots: t1, t2 (first choices beat t0's earlier-arriving
    # second choice); t3's first choice overflows
    assert disp[0, A].sum() == 0  # token-major filling would make this 1
    assert disp[1, A].sum() == 1 and disp[2, A].sum() == 1
    assert disp[3, A].sum() == 0
    # expert B slots: t0 (first choice) + t1's second choice; t2/t3 drop
    assert disp[0, B].sum() == 1 and disp[1, B].sum() == 1
    assert disp[2, B].sum() == 0 and disp[3, B].sum() == 0
    np.testing.assert_array_equal(np.asarray(kept), [2.0, 2.0])

    # and the slot accounting stays non-negative under overflow at the
    # moe_ffn level: assigned = T*k slots, dropped = assigned - kept
    p, x = setup
    y, aux2, stats = jax.jit(
        lambda p, x: moe_ffn(p, x, 0.5, return_stats=True, top_k=2)
    )(p, x)
    C = max(1, int(T * 0.5 * 2 / E))
    kept2 = np.asarray(stats["kept"])
    assert (kept2 <= C).all()
    assert float(stats["assigned"]) == 2 * T
    assert float(stats["assigned"]) - kept2.sum() > 0  # genuine drops
    assert np.isfinite(np.asarray(y)).all()


def test_top2_llama_trains(devices8):
    """A top-2 MoE LLaMA config trains end-to-end through the aux-weighted
    composite loss."""
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.ops.losses import causal_lm_loss
    from ddl25spring_tpu.utils.config import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=16,
        dtype="float32", n_experts=4, capacity_factor=2.0, moe_top_k=2,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)

    def loss_fn(p):
        logits, aux = llama.llama_forward_with_aux(p, tokens, cfg)
        return causal_lm_loss(logits, tokens) + cfg.moe_aux_weight * aux

    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return loss, optax.apply_updates(p, updates), o

    losses = []
    for _ in range(20):
        loss, params, opt = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
