"""The radix prefix cache (PR 11): refcounted page sharing, COW, LRU
eviction, and the bitwise cached==cold contract.

The load-bearing pins:

- **prefix-cached == cold, bitwise** — fp32 greedy decode through a
  radix hit (shared full pages + a copy-on-write partial page)
  reproduces the dense oracle token for token, including across an
  eviction-then-readmit of the same prefix.
- **pool invariant under interleavings** — a seeded fuzz of
  allocate/adopt(COW)/release/evict keeps ``free == (refcount == 0)``,
  ``used + free == n_pages``, and ``refcount[p] == table references +
  cache reference`` exactly (no double-free, no leak, the COW copy
  reachable from exactly one page table).
- **the saved work is countable** — prefill_tokens_saved /
  prefill_flops_saved / prefix_hit_rate are deterministic on the seeded
  shared-prefix trace, and the cached engine strictly beats the cold
  one on the virtual clock at equal admission budget.
"""

from __future__ import annotations

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.models import decode as dm, llama
from ddl25spring_tpu.serve import kv_pages
from ddl25spring_tpu.serve.engine import ServeEngine
from ddl25spring_tpu.serve.prefix import PrefixCache
from ddl25spring_tpu.serve.traffic import (
    PROFILES,
    TrafficSpec,
    synth_trace,
)
from ddl25spring_tpu.utils.config import LlamaConfig

from conftest import cached_lowering

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


def dense_greedy(params, prompt: list[int], max_new: int) -> list[int]:
    """The dense-cache oracle, compiled once per (|prompt|, max_new)."""

    def build():
        toks = dm.generate(
            params, jnp.asarray([prompt], jnp.int32), CFG,
            max_new_tokens=max_new, temperature=0.0,
        )
        return [int(t) for t in np.asarray(toks)[0]]

    return cached_lowering(("serve-dense", tuple(prompt), max_new), build)


def make_engine(params, **kw):
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    kw.setdefault("prefix_cache", True)
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


def serve_tokens(eng, requests: list[tuple[list[int], int]]) -> list[list]:
    """Submit sequentially (each drains before the next arrives — the
    shape that makes every later request a clean cache-hit candidate)
    and return per-request token lists."""
    out = []
    for prompt, max_new in requests:
        r = eng.make_request(prompt, max_new)
        assert eng.submit(r) is None
        drain(eng)
        out.append(list(r.tokens))
    return out


def assert_pool_invariants(eng):
    """The PR-11 pool contract, reconciled between device state and the
    host radix tree: ``free`` is exactly the zero-refcount set, and
    every reference is accounted — one per page-table entry holding the
    page (live or pending release) plus one iff the cache holds a node
    on it.  Equality rules out double-frees, leaks, and a COW copy
    reachable from two tables at once."""
    refcount = np.asarray(jax.device_get(eng.pool["refcount"]))
    free = np.asarray(jax.device_get(eng.pool["free"]))
    table = np.asarray(jax.device_get(eng.pool["page_table"]))
    n_pages = free.shape[0]
    assert (free == (refcount == 0)).all()
    assert int(free.sum()) + int((refcount > 0).sum()) == n_pages
    assert (refcount >= 0).all()
    table_refs = np.bincount(
        table[table >= 0].ravel(), minlength=n_pages
    )[:n_pages]
    cache_pages = eng.prefix.pages()
    assert len(cache_pages) == len(set(cache_pages))  # one node per page
    cache_refs = np.zeros((n_pages,), np.int64)
    for p in cache_pages:
        cache_refs[p] = 1
    assert (refcount == table_refs + cache_refs).all(), (
        refcount.tolist(), table_refs.tolist(), cache_pages,
    )


# ------------------------------------------------ kv_pages refcount ops


def _tiny_pool(n_pages=6, page_len=4, max_slots=3, pages_per_seq=4):
    return kv_pages.init_page_pool(
        CFG, n_pages=n_pages, page_len=page_len, max_slots=max_slots,
        pages_per_seq=pages_per_seq,
    )


def test_adopt_prefix_shares_by_reference_and_cow_copies_bitwise():
    pool = _tiny_pool()
    # slot 0 allocates page for its position-0 page and fills the pool
    # rows with recognizable values
    pool, ok = kv_pages.reserve_pages(
        pool, jnp.arange(3), jnp.zeros((3,), jnp.int32),
        jnp.asarray([True, False, False]),
    )
    assert bool(ok)
    src = int(np.asarray(pool["page_table"])[0, 0])
    k = pool["k"].at[src].set(
        jax.random.normal(jax.random.PRNGKey(7), pool["k"].shape[1:])
    )
    pool = {**pool, "k": k, "v": k + 1.0}
    # rows 1 and 2 both adopt slot 0's page as a COW source
    pool, ok = kv_pages.adopt_prefix(
        pool,
        jnp.asarray([1, 2, -1]),
        jnp.full((3, 4), -1, jnp.int32),
        jnp.asarray([src, src, -1]),
    )
    assert bool(ok)
    table = np.asarray(pool["page_table"])
    c1, c2 = int(table[1, 0]), int(table[2, 0])
    # two adopters of the same source each get their OWN copy — the COW
    # page is reachable from exactly one table
    assert len({src, c1, c2}) == 3
    kp = np.asarray(pool["k"])
    np.testing.assert_array_equal(kp[c1], kp[src])
    np.testing.assert_array_equal(kp[c2], kp[src])
    np.testing.assert_array_equal(
        np.asarray(pool["v"])[c1], np.asarray(pool["v"])[src]
    )
    rc = np.asarray(pool["refcount"])
    assert rc[src] == 1 and rc[c1] == 1 and rc[c2] == 1


def test_adopt_prefix_by_reference_bumps_refcount():
    pool = _tiny_pool()
    pool, ok = kv_pages.reserve_pages(
        pool, jnp.arange(3), jnp.zeros((3,), jnp.int32),
        jnp.asarray([True, False, False]),
    )
    page = int(np.asarray(pool["page_table"])[0, 0])
    adopt = np.full((3, 4), -1, np.int32)
    adopt[1, 0] = page
    pool, ok = kv_pages.adopt_prefix(
        pool, jnp.asarray([-1, 1, -1]), jnp.asarray(adopt),
        jnp.full((3,), -1, jnp.int32),
    )
    assert bool(ok)
    rc = np.asarray(pool["refcount"])
    assert rc[page] == 2
    # releasing ONE owner keeps the page resident; the second frees it
    pool = kv_pages.release_slots(
        pool, jnp.asarray([True, False, False])
    )
    assert np.asarray(pool["refcount"])[page] == 1
    assert not bool(np.asarray(pool["free"])[page])
    pool = kv_pages.release_slots(
        pool, jnp.asarray([False, True, False])
    )
    assert np.asarray(pool["refcount"])[page] == 0
    assert bool(np.asarray(pool["free"])[page])


def test_adopt_prefix_all_or_nothing_when_cow_cannot_fit():
    pool = _tiny_pool(n_pages=2)
    # exhaust the pool: two slots take one page each
    pool, ok = kv_pages.reserve_pages(
        pool, jnp.arange(3), jnp.zeros((3,), jnp.int32),
        jnp.asarray([True, True, False]),
    )
    assert bool(ok) and int(np.asarray(pool["free"]).sum()) == 0
    before_rc = np.asarray(pool["refcount"]).copy()
    before_tb = np.asarray(pool["page_table"]).copy()
    src = int(before_tb[0, 0])
    adopt = np.full((3, 4), -1, np.int32)
    adopt[2, 0] = src
    pool, ok = kv_pages.adopt_prefix(
        pool, jnp.asarray([-1, -1, 2]), jnp.asarray(adopt),
        jnp.asarray([-1, -1, src]),
    )
    # the COW copy cannot fit: NOTHING adopted, not even the
    # by-reference entry of the same row
    assert not bool(ok)
    np.testing.assert_array_equal(np.asarray(pool["refcount"]), before_rc)
    np.testing.assert_array_equal(
        np.asarray(pool["page_table"]), before_tb
    )


def test_ref_unref_roundtrip_and_pad_rows():
    pool = _tiny_pool()
    pool, _ = kv_pages.reserve_pages(
        pool, jnp.arange(3), jnp.zeros((3,), jnp.int32),
        jnp.asarray([True, False, False]),
    )
    page = int(np.asarray(pool["page_table"])[0, 0])
    pool = kv_pages.ref_pages(pool, jnp.asarray([page, -1, -1]))
    assert np.asarray(pool["refcount"])[page] == 2
    pool = kv_pages.release_slots(
        pool, jnp.asarray([True, False, False])
    )
    # the cache reference keeps the page out of the free set
    assert not bool(np.asarray(pool["free"])[page])
    pool = kv_pages.unref_pages(pool, jnp.asarray([page, -1, -1]))
    assert bool(np.asarray(pool["free"])[page])
    assert int(np.asarray(pool["refcount"]).sum()) == 0


# ------------------------------------------------------- radix tree


def test_radix_match_always_leaves_a_suffix_token():
    c = PrefixCache(page_len=4)
    prompt = [1, 2, 3, 4, 5, 6]
    assert c.match(prompt).matched == 0
    c.insert(prompt, [10, 11, -1, -1])
    # the identical prompt matches page-granularly but NEVER the whole
    # prompt — the engine must run the model once for the first token
    m = c.match(prompt)
    assert m.matched < len(prompt)
    assert m.matched == 4 and m.pages == [10] and m.cow_src == -1
    # a longer prompt with the same prefix takes full page + partial
    m = c.match(prompt + [7, 8])
    assert m.matched == 6 and m.pages == [10] and m.cow_src == 11


def test_radix_insert_claims_each_page_once():
    c = PrefixCache(page_len=4)
    prompt = [1, 2, 3, 4, 5, 6]
    assert c.insert(prompt, [10, 11, -1, -1]) == [10, 11]
    assert c.held_pages == 2 and sorted(c.pages()) == [10, 11]
    # same content at the same position claims nothing new
    assert c.insert(prompt, [20, 21, -1, -1]) == []
    assert c.held_pages == 2
    # a divergent suffix under the shared first page claims its own tail
    assert c.insert([1, 2, 3, 4, 9], [20, 22, -1, -1]) == [22]
    assert c.held_pages == 3


def test_radix_evicts_lru_leaves_first_and_respects_pins():
    c = PrefixCache(page_len=2)
    c.insert([1, 2, 3], [10, 11, -1])   # full 10, partial 11
    c.insert([5, 6, 7], [20, 21, -1])   # full 20, partial 21
    c.match([1, 2, 3])  # touch the first chain: second is now LRU
    assert c.evictable_pages(set()) == 4
    # a pinned leaf protects itself AND its parent (children first)
    assert c.evictable_pages({21}) == 2
    got = c.evict(2, {21})
    assert got == [11, 10]  # LRU-touched chain survives the pin? no:
    # 21 pinned -> 20 not fully evictable -> the first chain goes,
    # leaf (11) before its parent (10)
    assert c.held_pages == 2 and c.evictions == 2
    # re-inserting the evicted prefix claims fresh pages again
    assert c.insert([1, 2, 3], [30, 31, -1]) == [30, 31]


# ------------------------------------------- bitwise cached == cold


PREFIX = [11, 12, 13, 14, 15, 16]  # full page (4) + partial tail (2)


def test_prefix_cached_decode_matches_dense_across_cow_boundary(params):
    """The tentpole pin: a radix hit that shares one full page by
    reference AND copy-on-write duplicates the partial tail page
    reproduces the dense fp32 greedy decode bitwise, token for token."""
    reqs = [
        # cold: populates full node [11..14] + PARTIAL node [15,16]
        (PREFIX, 3),
        (PREFIX + [31, 32], 4),   # hit: ref page + COW the partial
        (PREFIX + [41, 42], 4),   # second hit (same COW source again)
    ]
    eng = make_engine(params)
    # warming the start-offset variants (the driver's off-the-clock
    # compile path) must not touch engine or pool state
    eng.warm_prefill_starts((4, len(PREFIX), 0, 99))
    assert bool(np.asarray(jax.device_get(eng.pool["free"])).all())
    assert eng.admitted == 0 and eng._prefills == 0
    got = serve_tokens(eng, reqs)
    for (prompt, max_new), tokens in zip(reqs, got):
        assert tokens == dense_greedy(params, prompt, max_new), prompt
    s = eng.prefix.stats()
    assert s["hits"] == 2 and s["lookups"] == 3
    assert s["hit_tokens"] == 2 * len(PREFIX)  # matched: page + partial
    # SAVED counts only the skipped scan positions — the page-aligned
    # floor (4 of the 6 matched tokens; the partial-page gap replays
    # with writes masked so the variant universe stays page-quantized)
    assert eng.prefill_tokens_saved == 2 * 4
    assert eng.prefill_flops_saved > 0
    assert eng.pool_ok_failures == 0
    assert_pool_invariants(eng)


def test_prefix_cache_survives_eviction_then_readmit(params):
    """LRU eviction is only ever a MISS: after page pressure evicts the
    cached prefix, readmitting the same prompt recomputes it bitwise
    (and re-caches it — the next request hits again)."""
    eng = make_engine(params, n_pages=6, max_slots=1)
    others = [
        ([51, 52, 53, 54, 55, 56], 2),
        ([61, 62, 63, 64, 65, 66], 2),
    ]
    reqs = (
        [(PREFIX, 2)] + others          # fill the cache: 6 pages held
        + [(PREFIX, 2), (PREFIX, 2)]    # evicted -> miss, then hit again
    )
    got = serve_tokens(eng, reqs)
    for (prompt, max_new), tokens in zip(reqs, got):
        assert tokens == dense_greedy(params, prompt, max_new), prompt
    s = eng.prefix.stats()
    assert s["evictions"] > 0
    # the readmitted prefix missed (no hit), the one after it hit
    assert s["hits"] >= 1
    assert eng.pool_ok_failures == 0
    assert_pool_invariants(eng)


@pytest.mark.parametrize("tp", [1, 2])
def test_refcount_pool_invariant_under_interleavings(params, tp):
    """Satellite: seeded property-style sweep.  Random shared-prefix
    traffic against a TIGHT pool (evictions, COW, backpressure, and
    mid-flight completions all interleave) keeps the refcount pool
    invariant exact at every scheduler step, and a full teardown frees
    every page (no leak, no double-free).  tp=2 (PR 18) runs the
    identical sweep on the head-dim-sharded pool: the sharing ops'
    refcount accounting is layout-oblivious, so the invariant holds
    bit-for-bit on the replicated accounting buffers."""
    for seed in (0, 1, 2):
        rng = np.random.RandomState(seed)
        eng = make_engine(
            params, n_pages=8, max_slots=2, prefill_batch=2, tp=tp,
        )
        prefixes = [
            [int(x) for x in rng.randint(1, CFG.vocab_size, size=6)]
            for _ in range(3)
        ]
        for _ in range(40):
            if rng.uniform() < 0.6:
                k = int(rng.randint(len(prefixes)))
                suffix = [int(x) for x in rng.randint(
                    1, CFG.vocab_size, size=2
                )]
                eng.submit(eng.make_request(
                    prefixes[k] + suffix, int(rng.randint(1, 4))
                ))
            eng.step()
            assert_pool_invariants(eng)
        drain(eng)
        eng.step()  # flush the final releases
        assert_pool_invariants(eng)
        # teardown: evict the whole cache; the pool must drain to empty
        evicted = eng.prefix.evict(eng.n_pages, set())
        if evicted:
            pages = np.full((eng.n_pages,), -1, np.int32)
            pages[: len(evicted)] = evicted
            eng.pool = kv_pages.unref_pages(eng.pool, jnp.asarray(pages))
        assert eng.prefix.held_pages == 0
        refcount = np.asarray(jax.device_get(eng.pool["refcount"]))
        assert (refcount == 0).all(), (seed, refcount.tolist())
        assert bool(np.asarray(jax.device_get(eng.pool["free"])).all())


def test_cached_engine_strictly_faster_on_the_virtual_clock(params):
    """The perf claim the A/B gates: identical shared-prefix trace,
    identical admission budget — the cached engine drains sooner on the
    virtual clock (prefill charged for the scan it actually ran) and
    emits the identical tokens."""
    spec = TrafficSpec(
        seed=0, duration_s=2.0, rate_rps=6.0, profile="shared",
        vocab_size=CFG.vocab_size,
    )
    trace = synth_trace(spec)
    assert len(trace) >= 4
    walls, streams = {}, {}
    for arm, on in (("cached", True), ("cold", False)):
        eng = make_engine(params, prefix_cache=on, prefill_batch=2)
        eng.run(trace, max_steps=5_000)
        m = eng.metrics()
        walls[arm] = m["wall_s"]
        streams[arm] = {r.rid: list(r.tokens) for r in eng.done}
        if on:
            assert m["prefix_hit_rate"] > 0
            assert m["prefill_tokens_saved"] > 0
            assert m["prefill_flops_saved"] > 0
        else:
            assert m["prefix_hit_rate"] is None
            assert m["prefill_tokens_saved"] == 0
    assert walls["cached"] < walls["cold"], walls
    common = set(streams["cached"]) & set(streams["cold"])
    assert common
    for rid in common:
        assert streams["cached"][rid] == streams["cold"][rid]


def test_driver_prefix_ab_gates_green(params):
    """driver.prefix_ab_compare on the seeded shared trace: skipped
    prefill work, a strict virtual-clock win, matching tokens — and
    tools/serve_report.check_prefix_ab passes the resulting cell."""
    from ddl25spring_tpu.serve import driver
    from tools import serve_report

    knobs = driver.engine_knobs(smoke=True)
    assert knobs["prefix_cache"] is True  # DDL25_SERVE_PREFIX default
    spec = TrafficSpec(
        seed=0, duration_s=2.0, rate_rps=6.0, profile="shared",
        vocab_size=CFG.vocab_size,
    )
    pab = driver.prefix_ab_compare(
        params, CFG, synth_trace(spec), knobs
    )
    assert pab["advantage_tokens"] > 0
    assert pab["tokens_match"] is True
    assert pab["cached"]["prefill_tokens_saved"] > 0
    assert (pab["cached"]["tokens_per_sec_per_chip"]
            > pab["cold"]["tokens_per_sec_per_chip"])
    row = {
        "key": {"profile": "shared"},
        "prefix_hit_rate": pab["cached"]["prefix_hit_rate"],
        "prefix_ab": driver._prefix_ab_cell(pab),
    }
    assert serve_report.check_prefix_ab([row]) == []
    # the full-doc shape (serve.json) judges identically
    doc = {"key": {"profile": "shared"},
           "ramp": {"prefix_hit_rate":
                    pab["cached"]["prefix_hit_rate"]},
           "prefix_ab": pab}
    assert serve_report.check_prefix_ab([doc]) == []


# --------------------------------------------------- report gates


def test_check_prefix_ab_fails_on_defects():
    from tools import serve_report

    assert serve_report.check_prefix_ab(
        [{"key": {"profile": "shared"}}]
    ) != []  # no cell at all
    bad = {
        "key": {"profile": "shared"},
        "prefix_hit_rate": 0.0,
        "prefix_ab": {
            "budget_s": 1.0,
            "cached_tokens_at_budget": 10,
            "cold_tokens_at_budget": 12,
            "advantage_tokens": -2,
            "tokens_match": False,
            "compared_requests": 3,
            "cached_tokens_per_sec_per_chip": 5.0,
            "cold_tokens_per_sec_per_chip": 6.0,
            "prefill_tokens_saved": 0,
        },
    }
    fails = serve_report.check_prefix_ab([bad])
    assert len(fails) == 5  # saved, tps, budget, match, hit-rate
    assert any("tokens_match" in f or "token-for-token" in f
               for f in fails)
    # tokens_match=True over ZERO compared requests is vacuous — the
    # gate must treat an empty comparison as a failure, not a pass
    vacuous = {
        "key": {"profile": "shared"},
        "prefix_hit_rate": 0.5,
        "prefix_ab": {
            **bad["prefix_ab"],
            "advantage_tokens": 2,
            "prefill_tokens_saved": 8,
            "cached_tokens_per_sec_per_chip": 7.0,
            "tokens_match": True,
            "compared_requests": 0,
        },
    }
    fails = serve_report.check_prefix_ab([vacuous])
    assert len(fails) == 1 and "compared request" in fails[0]


def test_check_group_gates_prefix_hit_rate_on_shared_runs():
    from tools import serve_report

    def row(hit):
        return {
            "key": {"profile": "shared"},
            "tokens_per_sec_per_chip": 10.0,
            "ttft_s_p95": 0.1,
            "prefix_hit_rate": hit,
        }

    assert serve_report.check_group([row(0.8), row(0.7)]) == []
    fails = serve_report.check_group([row(0.8), row(0.8), row(0.1)])
    assert any("prefix_hit_rate" in f for f in fails)
    # NOT gated off the shared profile (random prompts may simply miss)
    cold = [dict(r, key={"profile": "ramp"})
            for r in (row(0.8), row(0.8), row(0.0))]
    assert serve_report.check_group(cold) == []


# -------------------------------------------------------- traffic


def test_shared_profile_shape_and_determinism():
    spec = TrafficSpec(
        seed=5, duration_s=3.0, rate_rps=8.0, profile="shared",
    )
    trace = synth_trace(spec)
    assert len(trace) > 4
    plen = spec.shared_prefix_len + spec.shared_suffix_len
    assert all(len(r["prompt"]) == plen for r in trace)
    # every prompt starts with one of the K system prompts
    heads = {tuple(r["prompt"][: spec.shared_prefix_len]) for r in trace}
    assert 1 <= len(heads) <= spec.shared_prefixes
    assert synth_trace(spec) == trace
    assert synth_trace(TrafficSpec(
        seed=6, duration_s=3.0, rate_rps=8.0, profile="shared",
    )) != trace


def test_traffic_profiles_replay_across_process_restarts():
    """Satellite: every profile (flat/ramp/spike + shared) replays the
    IDENTICAL trace for the same seed in a fresh process — the A/B
    gates and the ledger trend depend on it."""
    specs = [
        {"seed": 3, "duration_s": 2.0, "rate_rps": 8.0, "profile": p}
        for p in PROFILES
    ]
    local = [synth_trace(TrafficSpec(**s)) for s in specs]
    code = (
        "import json, sys\n"
        "from ddl25spring_tpu.serve.traffic import TrafficSpec, "
        "synth_trace\n"
        "specs = json.loads(sys.argv[1])\n"
        "print(json.dumps([synth_trace(TrafficSpec(**s)) "
        "for s in specs]))\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code, json.dumps(specs)],
        capture_output=True, text=True, check=True,
    )
    assert json.loads(r.stdout) == local
