"""graft-goodput (``ddl25spring_tpu/obs/goodput.py`` + bench lineage
wiring + ``tools/goodput_report.py`` + the trace-export goodput gate):
the run-lineage goodput & SLO observatory.

The load-bearing pins:

- **decomposition sums to wall** — every bucket (including the
  ``other`` residual) sums to total wall within the pinned
  ``SUM_TOLERANCE``; the only way to fail is OVER-attribution (a
  double-billed window), and an over-billed meter does fail.
- **replayed steps = the manifest durable gap, exactly** — the replay
  window prices only resumable-phase dispatches; a secondary phase
  restarting its own step count never collides with it.  The
  ``slow``-marked chaos test proves it on a REAL ``sigterm@5`` lineage:
  same ``lineage_id`` across both attempts (retry JSONL, flight meta,
  timeline header), decomposition summing on the merged lineage axis.
- **SLO attainment is judged on the engine clock** — a seeded
  shared-profile drain on the virtual clock attains deterministically,
  and tightening the env-boundary SLO to zero flips every request to
  non-compliant without touching the token streams.
- **the falsification matrix** — each ``goodput_report --check`` /
  ``trace_export --check`` / ``obs_report`` gate trips on a seeded
  violation and passes on the near-miss variant.
- **zero cost when off** — metered ``timed_run`` losses are bitwise
  identical to unmetered ones, and with ``DDL25_OBS=0`` serve token
  streams (and hence the goodput cell computed from them) are bitwise
  identical to an instrumented run's.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.obs import goodput as gp
from ddl25spring_tpu.obs import state
from ddl25spring_tpu.serve.engine import ServeEngine
from ddl25spring_tpu.utils.config import LlamaConfig

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


def make_engine(params, **kw):
    # the test_serve smoke geometry (shared compiled-program cache)
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


# ------------------------------------------------------------- meter


def meter(**kw):
    kw.setdefault("t0_perf", 0.0)
    return gp.GoodputMeter("lintest000001", **kw)


def test_decomposition_sums_to_wall_with_other_residual():
    m = meter()
    m.add("warmup_compile", 0.0, 1.0)
    m.note_step(0, 1.0, 2.0)
    m.note_step(1, 2.0, 3.0)
    m.add("checkpoint_save", 3.0, 3.5)
    doc = m.finalize(total_wall_s=5.0)
    s = doc["seconds"]
    assert s["warmup_compile"] == 1.0
    assert s["useful_step"] == 2.0
    assert s["checkpoint_save"] == 0.5
    assert s["other"] == pytest.approx(1.5)  # the residual, reported
    assert sum(s.values()) == pytest.approx(doc["total_wall_s"])
    assert doc["sum_check"]["ok"] is True
    assert doc["fraction_useful"] == pytest.approx(2.0 / 5.0)
    assert set(s) == set(gp.BUCKETS)


def test_overbilled_meter_fails_the_sum_contract():
    m = meter()
    m.add("useful_step", 0.0, 10.0)
    doc = m.finalize(total_wall_s=5.0)
    assert doc["sum_check"]["ok"] is False
    assert doc["overrun_s"] == pytest.approx(5.0)
    # the near-miss: within tolerance stays ok
    m2 = meter()
    m2.add("useful_step", 0.0, 5.0 * (1 + gp.SUM_TOLERANCE) - 1e-4)
    assert m2.finalize(total_wall_s=5.0)["sum_check"]["ok"] is True


def test_unknown_bucket_refused():
    m = meter()
    with pytest.raises(ValueError):
        m.add("coffee_break", 0.0, 1.0)
    with pytest.raises(ValueError):
        m.add_seconds("coffee_break", 1.0)


def test_replay_window_prices_only_resumable_durable_gap_steps():
    m = meter()
    m.set_replay_window(4, 5)  # durable gap: steps 4..5 re-run
    for i in range(4, 8):
        m.note_step(i, float(i), float(i) + 1.0)
    # a secondary phase restarts its own count — indices 4..5 collide
    # numerically but are NOT on the resume axis
    for i in range(4, 6):
        m.note_step(i, 10.0 + i, 11.0 + i, resumable=False)
    doc = m.finalize(total_wall_s=20.0)
    assert doc["replayed_steps_count"] == 2  # == the manifest gap
    assert doc["seconds"]["replayed_steps"] == pytest.approx(2.0)
    assert doc["seconds"]["useful_step"] == pytest.approx(4.0)
    assert doc["steps"] == {"replayed_steps": 2, "useful_step": 4}


def test_stall_seconds_accumulate_without_windows():
    m = meter()
    m.add_seconds("stall", 0.75)
    doc = m.finalize(total_wall_s=2.0)
    assert doc["seconds"]["stall"] == 0.75
    assert all(w["bucket"] != "stall" for w in doc["windows"])


def test_window_cap_truncates_windows_but_not_seconds():
    m = meter()
    n = gp.MAX_WINDOWS + 7
    for i in range(n):
        m.add("useful_step", float(i), float(i) + 0.5)
    doc = m.finalize(total_wall_s=float(n))
    assert doc["seconds"]["useful_step"] == pytest.approx(0.5 * n)
    assert doc["windows_truncated"] == 7
    assert doc["sum_check"]["ok"] is True


def test_touching_same_bucket_windows_coalesce():
    m = meter()
    for i in range(5):
        m.note_step(i, float(i), float(i) + 1.0)
    m.add("checkpoint_save", 5.0, 5.2)
    doc = m.finalize(total_wall_s=6.0)
    useful = [w for w in doc["windows"] if w["bucket"] == "useful_step"]
    assert len(useful) == 1 and useful[0]["n"] == 5
    assert useful[0]["t0_s"] == 0.0 and useful[0]["t1_s"] == 5.0


# ---------------------------------------------------- lineage merge


def _flight_doc():
    return {
        "records": [
            {"kind": "step", "step": s, "wall_s": 1.0,
             "resumable": True}
            for s in range(6)
        ] + [
            # a secondary phase's record: no resumable marker, never
            # priced into the lineage
            {"kind": "step", "step": 0, "wall_s": 99.0},
        ]
    }


def test_failed_attempt_facts_split_on_the_durable_step():
    facts = gp.failed_attempt_facts(_flight_doc(), durable_step=3)
    assert facts["useful_steps"] == 4 and facts["lost_steps"] == 2
    assert facts["useful_wall_s"] == pytest.approx(4.0)
    assert facts["lost_wall_s"] == pytest.approx(2.0)
    # no durable checkpoint: the whole attempt is the lost tail
    none = gp.failed_attempt_facts(_flight_doc(), durable_step=None)
    assert none["useful_steps"] == 0 and none["lost_steps"] == 6


def test_merge_lineage_folds_attempts_onto_one_axis():
    final = meter()
    final.attempt = 2
    final.note_step(4, 0.0, 2.0)
    fdoc = final.finalize(
        total_wall_s=4.0, strategy="dp", mesh={"data": 2})
    failure = {
        "attempt": 1, "reason": "preempted", "wall_s": 10.0,
        "backoff_s": 1.0,
        "goodput": {"useful_wall_s": 4.0, "lost_wall_s": 2.0,
                    "useful_steps": 4, "lost_steps": 2,
                    "durable_step": 3},
    }
    doc = gp.merge_lineage(fdoc, [failure], lineage_id="lintest000001")
    assert doc["scope"] == "train_lineage"
    assert doc["attempts"] == 2
    assert doc["strategy"] == "dp" and doc["mesh"] == {"data": 2}
    s = doc["seconds"]
    # dead attempt: 4 s vouched useful, 2 s lost tail + 1 s backoff as
    # recovery, 4 s unattributed setup as other; final: 2 s useful + 2
    # s residual other on its own axis
    assert s["useful_step"] == pytest.approx(6.0)
    assert s["recovery"] == pytest.approx(3.0)
    assert s["other"] == pytest.approx(6.0)
    assert doc["total_wall_s"] == pytest.approx(15.0)
    assert doc["sum_check"]["ok"] is True
    # the final attempt's windows shifted past the dead attempt's span
    shifted = [w for w in doc["windows"] if w.get("step") == 4]
    assert shifted and shifted[0]["t0_s"] == pytest.approx(11.0)
    outcomes = [a["outcome"] for a in doc["attempts_detail"]]
    assert outcomes == ["failed", "succeeded"]


def test_merge_lineage_nothing_to_merge_is_none():
    assert gp.merge_lineage(None, []) is None


# -------------------------------------------------- serving goodput


def test_serve_slo_reads_the_env_boundary(monkeypatch):
    monkeypatch.setenv(gp.ENV_SLO_TTFT_MS, "123.5")
    monkeypatch.setenv(gp.ENV_SLO_TOK_MS, "7.25")
    assert gp.serve_slo() == {"ttft_ms": 123.5, "tok_ms": 7.25}
    monkeypatch.delenv(gp.ENV_SLO_TTFT_MS)
    monkeypatch.delenv(gp.ENV_SLO_TOK_MS)
    assert gp.serve_slo() == {
        "ttft_ms": gp.DEFAULT_SLO_TTFT_MS,
        "tok_ms": gp.DEFAULT_SLO_TOK_MS,
    }


def test_serve_goodput_cell_judges_each_request():
    slo = {"ttft_ms": 1000.0, "tok_ms": 100.0}
    done = [
        # compliant: ttft 0.5 s, per-token (1.0-0.5)/(6-1)=0.1 s
        {"arrival_t": 0.0, "first_token_t": 0.5, "done_t": 1.0,
         "tokens": [1] * 6},
        # TTFT miss
        {"arrival_t": 0.0, "first_token_t": 2.0, "done_t": 2.1,
         "tokens": [1] * 3},
        # per-token miss
        {"arrival_t": 0.0, "first_token_t": 0.1, "done_t": 3.0,
         "tokens": [1] * 3},
    ]
    cell = gp.serve_goodput_cell(
        done, clock="virtual", wall_s=2.0, n_chips=2, offered=10,
        rejected=2, completed=3, dropped=1, drain_demand=1, slo=slo,
    )
    assert cell["requests_evaluated"] == 3
    assert cell["slo_compliant"] == 1
    assert cell["slo_attainment"] == pytest.approx(1 / 3)
    assert cell["ttft_misses"] == 1 and cell["tok_latency_misses"] == 1
    assert cell["completed_tokens"] == 12
    assert cell["slo_compliant_tokens"] == 6
    # SLO-compliant tokens only, per second per chip
    assert cell["goodput_tokens_per_sec_per_chip"] == pytest.approx(
        6 / 2.0 / 2)
    # availability = 1 - (rejects + drops + drain demand) / offered
    assert cell["availability"] == pytest.approx(1 - 4 / 10)
    assert cell["slo"]["clock"] == "virtual"
    # nothing offered -> availability undefined, not 1.0
    empty = gp.serve_goodput_cell([], clock="wall", wall_s=None)
    assert empty["availability"] is None
    assert empty["slo_attainment"] is None
    assert empty["goodput_tokens_per_sec_per_chip"] is None


def test_seeded_virtual_clock_drain_attains_the_slo(params, monkeypatch):
    """A seeded shared-profile-shaped drain on the virtual clock: SLO
    attainment is deterministic (1.0 under the smoke defaults, 0.0
    under an impossible env-boundary SLO) and re-judging never touches
    the token streams."""
    eng = make_engine(params, prefill_batch=2)
    with state.scoped(False):
        reqs = [eng.make_request([5 + i, 9, 11, 3], 6) for i in range(4)]
        for r in reqs:
            assert eng.submit(r) is None
        drain(eng)
    tokens_before = [list(r.tokens) for r in reqs]
    cell = gp.serve_goodput_cell(
        eng.done, clock=eng.clock, wall_s=eng.now(), offered=4,
        completed=4, slo={"ttft_ms": 1e6, "tok_ms": 1e6},
    )
    assert cell["requests_evaluated"] == 4
    assert cell["slo_attainment"] == 1.0
    assert cell["availability"] == 1.0
    monkeypatch.setenv(gp.ENV_SLO_TTFT_MS, "0")
    monkeypatch.setenv(gp.ENV_SLO_TOK_MS, "0")
    strict = gp.serve_goodput_cell(
        eng.done, clock=eng.clock, wall_s=eng.now(), offered=4,
        completed=4,
    )
    assert strict["slo_attainment"] == 0.0
    assert strict["slo_compliant_tokens"] == 0
    assert [list(r.tokens) for r in reqs] == tokens_before


# --------------------------------------------- artifacts + ledger row


def test_goodput_json_round_trips(tmp_path):
    m = meter()
    m.note_step(0, 0.0, 1.0)
    doc = m.finalize(total_wall_s=2.0)
    path = gp.write_run_goodput(doc, str(tmp_path))
    assert os.path.basename(path) == gp.GOODPUT_BASENAME
    assert gp.read_run_goodput(str(tmp_path)) == json.loads(
        json.dumps(doc))
    assert gp.read_run_goodput(str(tmp_path / "nope")) is None


def test_goodput_cell_summarizes_without_windows():
    m = meter()
    m.note_step(0, 0.0, 1.0)
    cell = gp.goodput_cell(m.finalize(total_wall_s=2.0))
    assert "windows" not in cell
    assert cell["scope"] == "train_attempt"
    assert cell["sum_check"]["ok"] is True
    assert gp.goodput_cell(None) == {"enabled": False}


def test_ledger_row_keys_on_strategy_mesh_scope_not_lineage():
    m = meter()
    doc = m.finalize(total_wall_s=1.0)
    row = gp.ledger_row(doc, strategy="dp", mesh={"data": 2},
                        host="h/2cpu/cpu")
    assert row["record"] == "goodput"
    assert row["key"] == {"strategy": "dp", "mesh": {"data": 2},
                          "scope": "train_attempt"}
    assert "lineage_id" not in row["key"]  # identity, never the key
    assert row["lineage_id"] == "lintest000001"
    # serve rows carry the SLO cells, train rows don't
    assert "slo_attainment" not in row


# -------------------------------- goodput_report falsification matrix


def _trend_rows(led_path, fractions, scope="train_attempt", **serve):
    """A ledger of synthetic goodput rows sharing one trend key."""
    rows = []
    for i, f in enumerate(fractions):
        m = meter()
        m.note_step(0, 0.0, f * 10.0)
        doc = m.finalize(total_wall_s=10.0, scope=scope)
        doc["lineage_id"] = f"lin{i:09d}abc"  # unique per lineage
        if serve:
            doc.update(serve)
        row = gp.ledger_row(doc, strategy="dp", mesh={"data": 2},
                            host="h/2cpu/cpu")
        row["ts"] = 1_700_000_000 + i
        rows.append(row)
    with open(led_path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    return rows


def test_goodput_report_check_bands_fraction_useful(tmp_path, capsys):
    import tools.goodput_report as goodput_report

    led = str(tmp_path / "ledger.jsonl")
    # near-miss passes: latest 0.70 vs median-0.80 baseline is inside
    # the default 0.35 band
    _trend_rows(led, [0.8, 0.8, 0.8, 0.7])
    assert goodput_report.main(["--ledger", led, "--check"]) == 0
    # seeded violation trips: latest craters to 0.2
    _trend_rows(led, [0.8, 0.8, 0.8, 0.2])
    assert goodput_report.main(["--ledger", led, "--check"]) == 1
    out = capsys.readouterr()
    assert "fraction_useful" in out.err
    # a single record is a note, not a failure (no baseline yet)
    _trend_rows(led, [0.8])
    assert goodput_report.main(["--ledger", led, "--check"]) == 0
    # an empty ledger is its own exit code
    open(led, "w").close()
    assert goodput_report.main(["--ledger", led, "--check"]) == 2


def test_goodput_report_check_fails_broken_sum_contract(tmp_path):
    import tools.goodput_report as goodput_report

    led = str(tmp_path / "ledger.jsonl")
    _trend_rows(led, [0.8, 0.8])
    # corrupt the latest row's sum_check in place
    rows = [json.loads(ln) for ln in open(led)]
    rows[-1]["sum_check"]["ok"] = False
    with open(led, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    assert goodput_report.main(["--ledger", led, "--check"]) == 1


def test_goodput_report_slo_floor(tmp_path, capsys):
    import tools.goodput_report as goodput_report

    led = str(tmp_path / "ledger.jsonl")
    _trend_rows(led, [0.8, 0.8], scope="serve", slo_attainment=0.95,
                availability=1.0)
    assert goodput_report.main(
        ["--ledger", led, "--check", "--slo-floor", "0.9"]) == 0
    assert goodput_report.main(
        ["--ledger", led, "--check", "--slo-floor", "0.99"]) == 1
    # an engine that finished zero requests did not attain its SLO
    _trend_rows(led, [0.8], scope="serve", slo_attainment=None)
    assert goodput_report.main(
        ["--ledger", led, "--check", "--slo-floor", "0.5"]) == 1


def test_goodput_report_check_elastic_is_strict(tmp_path):
    import tools.goodput_report as goodput_report

    def write_doc(name, fraction):
        m = meter()
        m.note_step(0, 0.0, fraction * 10.0)
        d = str(tmp_path / name)
        gp.write_run_goodput(m.finalize(total_wall_s=10.0), d)
        return d

    el, rl = write_doc("elastic", 0.6), write_doc("relaunch", 0.5)
    assert goodput_report.main(["--check-elastic", el, rl]) == 0
    # a tie is NOT strictly higher — elastic must beat relaunch
    tie = write_doc("tie", 0.5)
    assert goodput_report.main(["--check-elastic", tie, rl]) == 1
    assert goodput_report.main(["--check-elastic", rl, el]) == 1
    assert goodput_report.main(
        ["--check-elastic", str(tmp_path / "missing"), rl]) == 2


def test_goodput_report_run_view_checks_the_artifact(tmp_path):
    import tools.goodput_report as goodput_report

    m = meter()
    m.add("useful_step", 0.0, 30.0)  # over-billed vs 10 s wall
    gp.write_run_goodput(m.finalize(total_wall_s=10.0), str(tmp_path))
    assert goodput_report.main(
        ["--run", str(tmp_path), "--check"]) == 1
    m2 = meter()
    m2.add("useful_step", 0.0, 8.0)
    gp.write_run_goodput(m2.finalize(total_wall_s=10.0), str(tmp_path))
    assert goodput_report.main(
        ["--run", str(tmp_path), "--check"]) == 0


# ------------------------------------ trace_export goodput gate


def _export_dir(tmp_path, doc):
    from ddl25spring_tpu.obs.timeline import timeline

    d = tmp_path / "run"
    timeline.configure(str(d))
    timeline.configure(None)  # header flushed; exporter needs only it
    gp.write_run_goodput(doc, str(d))
    return str(d)


def test_trace_export_renders_goodput_windows(tmp_path):
    import tools.trace_export as trace_export

    m = meter()
    m.add("warmup_compile", 0.0, 1.0)
    m.note_step(0, 1.0, 2.0)
    d = _export_dir(tmp_path, m.finalize(total_wall_s=3.0))
    assert trace_export.main([d, "--check"]) == 0
    merged = json.load(open(os.path.join(d, "trace_merged.json")))
    gp_evs = [e for e in merged["traceEvents"]
              if e.get("pid") == trace_export.PID_GOODPUT
              and e.get("ph") == "X"]
    assert {e["name"] for e in gp_evs} == {"warmup_compile",
                                           "useful_step"}


def test_trace_export_check_refuses_overlap_and_overrun(tmp_path):
    import tools.trace_export as trace_export

    # overlapping windows double-bill the interval
    m = meter()
    m.add("useful_step", 0.0, 2.0)
    m.add("warmup_compile", 1.0, 3.0)
    doc = m.finalize(total_wall_s=4.0)
    assert trace_export.check_goodput(doc)
    d = _export_dir(tmp_path, doc)
    assert trace_export.main([d, "--check"]) == 1
    # a window past total wall
    m2 = meter()
    m2.add("useful_step", 0.0, 9.0)
    doc2 = m2.finalize(total_wall_s=5.0)
    assert any("runs past total wall" in f
               for f in trace_export.check_goodput(doc2))
    # the clean near-miss: touching windows are not an overlap
    m3 = meter()
    m3.add("useful_step", 0.0, 2.0)
    m3.add("warmup_compile", 2.0, 3.0)
    assert trace_export.check_goodput(
        m3.finalize(total_wall_s=4.0)) == []


def test_obs_report_exit_5_on_goodput_violation(tmp_path):
    import tools.obs_report as obs_report

    run = tmp_path / "run"
    run.mkdir()
    with open(run / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"record": "header", "layout": "dp"}) + "\n")
    m = meter()
    m.add("useful_step", 0.0, 30.0)  # breaks the sum contract
    gp.write_run_goodput(m.finalize(total_wall_s=10.0), str(run))
    assert obs_report.main([str(run), "--check-health"]) == 5
    # healthy decomposition passes the same gate
    m2 = meter()
    m2.add("useful_step", 0.0, 8.0)
    gp.write_run_goodput(m2.finalize(total_wall_s=10.0), str(run))
    assert obs_report.main([str(run), "--check-health"]) == 0
    # serve SLO floor: exit 5 again
    m3 = meter()
    doc3 = m3.finalize(total_wall_s=1.0, scope="serve")
    doc3["slo_attainment"] = 0.4
    gp.write_run_goodput(doc3, str(run))
    assert obs_report.main(
        [str(run), "--check-health", "--slo-floor", "0.9"]) == 5


# ------------------------------------------------- zero cost when off


def test_metered_timed_run_is_bitwise_identical():
    from ddl25spring_tpu.benchmarks import timed_run

    @jax.jit
    def step(params, opt_state, batch):
        p = params - 1e-3 * jnp.sum(batch) * params
        return p, opt_state, jnp.sum(p * p)

    def run(meter_):
        params = jnp.ones((4,), jnp.float32)
        feed = lambda: jnp.ones((2,), jnp.float32)  # noqa: E731
        dt, p, _ = timed_run(
            step, params, 0, feed, steps=3, warmup=1, goodput=meter_,
        )
        return p

    base = run(None)
    m = meter()
    metered = run(m)
    assert jnp.array_equal(base, metered)
    # and the meter actually measured the run it rode along with
    assert m.seconds["useful_step"] > 0
    assert m.seconds["warmup_compile"] > 0


def test_disabled_obs_serve_tokens_identical_and_cell_matches(params):
    """DDL25_OBS=0: token streams are bitwise identical to an
    instrumented run, so the goodput cell computed post-hoc from the
    virtual clock matches field-for-field (modulo nothing)."""

    def run(on):
        eng = make_engine(params, prefill_batch=2)
        with state.scoped(on):
            reqs = [eng.make_request([5 + i, 9, 11, 3], 6)
                    for i in range(3)]
            for r in reqs:
                assert eng.submit(r) is None
            drain(eng)
        cell = gp.serve_goodput_cell(
            eng.done, clock=eng.clock, wall_s=eng.now(), offered=3,
            completed=3, slo={"ttft_ms": 1e6, "tok_ms": 1e6},
        )
        return [list(r.tokens) for r in reqs], cell

    off_tokens, off_cell = run(False)
    on_tokens, on_cell = run(True)
    assert on_tokens == off_tokens
    assert on_cell == off_cell


# ------------------------------------- the real chaos-resume lineage


@pytest.mark.slow
def test_sigterm_lineage_goodput_end_to_end(tmp_path):
    """The acceptance pin on a REAL ``sigterm@5`` lineage: the resumed
    child carries the SAME lineage_id (retry JSONL, flight meta,
    timeline header), the merged decomposition sums within tolerance on
    the lineage axis, and ``replayed_steps_count`` equals the manifest
    durable gap exactly."""
    obs_dir = str(tmp_path / "run")
    led = str(tmp_path / "ledger.jsonl")
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "DDL25_DONATE")
    }
    env.update(
        JAX_PLATFORMS="cpu", DDL25_BENCH_NTRAIN="256",
        DDL25_CHAOS="sigterm@5", DDL25_SENTINELS="1",
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--smoke",
         "--steps", "8", "--per-chip-batch", "16",
         "--obs-dir", obs_dir, "--perf-ledger", led],
        capture_output=True, text=True, timeout=900, env=env, cwd=root,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = [ln for ln in r.stdout.splitlines() if ln.strip()][-1]
    d = json.loads(line)
    tel = d["telemetry"]
    resume = tel["resume"]
    assert resume["resumes"] >= 1

    cell = tel["goodput"]
    assert cell["scope"] == "train_lineage"
    assert cell["attempts"] >= 2
    assert cell["sum_check"]["ok"] is True, cell["sum_check"]

    # one lineage id everywhere: the BENCH cell, every retry record,
    # the surviving child's flight meta and timeline header
    lineage = cell["lineage_id"]
    assert lineage
    for f in tel["retry_failures"]:
        assert f["lineage_id"] == lineage, f
    fl = json.load(open(os.path.join(obs_dir, "flight.json")))
    assert fl["meta"]["lineage_id"] == lineage
    assert fl["meta"]["attempt"] >= 2
    header = json.loads(
        [ln for ln in open(os.path.join(obs_dir, "timeline.jsonl"))
         if ln.strip()][0])
    assert header["lineage_id"] == lineage

    # replayed steps == the manifest durable gap, exactly: the steps
    # past the durable checkpoint the dead attempt lost are precisely
    # the ones the resumed child re-runs
    assert cell["replayed_steps_count"] == resume["steps_replayed"]
    lost = [f["goodput"]["lost_steps"] for f in tel["retry_failures"]
            if f.get("goodput")]
    assert resume["steps_replayed"] == sum(lost), (resume, lost)
    assert cell["seconds"]["replayed_steps"] > 0
    assert cell["seconds"]["recovery"] > 0  # dead tail + restore

    # the merged artifact is the lineage view, and every gate passes
    art = json.load(open(os.path.join(obs_dir, "goodput.json")))
    assert art["scope"] == "train_lineage"
    assert art["lineage_id"] == lineage
    assert art["attempts"] == cell["attempts"]

    import tools.goodput_report as goodput_report
    import tools.trace_export as trace_export

    assert goodput_report.main(["--ledger", led, "--check"]) == 0
    assert goodput_report.main(["--run", obs_dir, "--check"]) == 0
    assert trace_export.main([obs_dir, "--check"]) == 0
