"""LLaMA model unit tests: shapes, causality, determinism, stage splitting."""

import jax
import jax.numpy as jnp
import numpy as np

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.utils.config import LlamaConfig

CFG = LlamaConfig(
    vocab_size=64, dmodel=32, num_heads=2, n_layers=4, ctx_size=16, dtype="float32"
)


def test_forward_shapes():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 16), 0, 64)
    logits = llama.llama_forward(params, tokens, CFG)
    assert logits.shape == (3, 16, 64)
    assert logits.dtype == jnp.float32


def test_causality():
    """Changing a future token must not change past logits — the property
    the reference's causal attention provides implicitly via simplellm."""
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 64)
    logits_a = llama.llama_forward(params, tokens, CFG)
    tokens_b = tokens.at[0, 10].set((tokens[0, 10] + 1) % 64)
    logits_b = llama.llama_forward(params, tokens_b, CFG)
    np.testing.assert_allclose(
        logits_a[0, :10], logits_b[0, :10], atol=1e-5, rtol=1e-5
    )
    assert not np.allclose(logits_a[0, 10:], logits_b[0, 10:])


def test_stage_split_roundtrip_and_equivalence():
    params = llama.init_llama_params(jax.random.PRNGKey(0), CFG)
    staged = llama.split_blocks_for_stages(params, 2)
    assert jax.tree.leaves(staged["blocks"])[0].shape[:2] == (2, 2)
    merged = llama.merge_blocks_from_stages(staged)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b),
        params["blocks"],
        merged["blocks"],
    )
    # applying [S, L/S] stages sequentially == applying [L] blocks
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    x = llama.embed(params, tokens, CFG)
    full = llama.apply_blocks(params["blocks"], x, CFG)
    y = x
    for si in range(2):
        y = llama.apply_blocks(
            jax.tree.map(lambda p, si=si: p[si], staged["blocks"]), y, CFG
        )
    np.testing.assert_allclose(full, y, atol=1e-5, rtol=1e-5)


def test_rope_rotation_preserves_norm():
    cos, sin = llama.rope_angles(8, 4)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 4))
    r = llama.apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        jnp.linalg.norm(x, axis=-1), jnp.linalg.norm(r, axis=-1), rtol=1e-5
    )


# ---------------------------------------------------------------- MoE LLaMA


def test_moe_llama_forward_and_aux():
    """Switch-MoE blocks (cfg.n_experts > 0): logits well-formed, causality
    holds through capacity-bucketed dispatch, aux > 0 and ~1 for a fresh
    (roughly uniform) router."""
    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=16,
        dtype="float32", n_experts=4, capacity_factor=2.0,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    assert "moe" in params["blocks"] and "w_gate" not in params["blocks"]
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    logits, aux = llama.llama_forward_with_aux(params, tokens, cfg)
    assert logits.shape == (2, 16, 64)
    assert bool(jnp.isfinite(logits).all())
    # per-layer switch aux is ~1 at balanced routing; 2 layers -> ~2
    assert 0.5 < float(aux) < 8.0

    # causality survives the token-flattened dispatch: with
    # capacity_factor=2.0 nothing overflows, so examples are independent
    # (under overflow switch-style dispatch IS batch-coupled — drops
    # depend on slot competition; documented in block_forward)
    logits_b, _ = llama.llama_forward_with_aux(
        params, tokens.at[0, 10].set((tokens[0, 10] + 1) % 64), cfg
    )
    np.testing.assert_allclose(
        logits[0, :10], logits_b[0, :10], atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(logits[1], logits_b[1], atol=1e-5, rtol=1e-5)


def test_moe_llama_trains():
    """The full switch recipe: LM loss + weighted aux falls under Adam."""
    import optax

    from ddl25spring_tpu.ops.losses import causal_lm_loss

    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=2, ctx_size=16,
        dtype="float32", n_experts=4, capacity_factor=2.0,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    def loss_fn(p):
        logits, aux = llama.llama_forward_with_aux(p, tokens, cfg)
        return causal_lm_loss(logits, tokens) + cfg.moe_aux_weight * aux

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses[::10]
    assert all(np.isfinite(losses))

    # router grads actually flow (the dispatch is differentiable through
    # the gate weighting + aux loss)
    grads = jax.grad(loss_fn)(params)
    router_g = grads["blocks"]["moe"]["router"]
    assert float(jnp.abs(router_g).max()) > 0.0
