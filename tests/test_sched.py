"""graft-sched: the whole-program schedule verifier.

Three layers pinned here:

1. **Mechanics** — the instruction DAG, static FLOP accounting (dot
   contracting dims, fusion inlining, loop trip multiplication), and
   the three window models (async pair / committed schedule /
   dataflow) on synthetic HLO.
2. **Safety** — the per-participant stream expansion and each deadlock
   shape :func:`check_schedule_safety` proves absent (duplicate
   participant, channel-group mismatch, out-of-range device, divergent
   conditional branches, crossed async windows).
3. **Strategy pins** — every registered strategy carries a sched
   report, and each ``*-overlap`` strategy's ``static_overlap_bound``
   is STRICTLY greater than its sync twin's: the static proof of the
   PR-8 scheduling win that the noise-bound wall-clock A/B could not
   give.  These ride the shared lower-once compile cache
   (tests/conftest.py) — zero extra compiles.
"""

import pytest

from ddl25spring_tpu.analysis import sched
from ddl25spring_tpu.obs import xla_analytics as xa
from conftest import cached_strategy_report

# --------------------------------------------------------------- fixtures

_ADD = """\
%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}
"""

# a 4 MiB async all-reduce whose window holds one real matmul (2*512^3
# FLOPs — comfortably above 1% of the wire time on the reference chip)
PAIR_WITH_DOT = f"""\
HloModule pair_dot
{_ADD}
ENTRY %main (x: f32[1048576], a: f32[512,512], b: f32[512,512]) -> f32[1048576] {{
  %x = f32[1048576]{{0}} parameter(0)
  %a = f32[512,512]{{1,0}} parameter(1)
  %b = f32[512,512]{{1,0}} parameter(2)
  %ars = f32[1048576]{{0}} all-reduce-start(f32[1048576]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
  %d = f32[512,512]{{1,0}} dot(f32[512,512]{{1,0}} %a, f32[512,512]{{1,0}} %b), lhs_contracting_dims={{1}}, rhs_contracting_dims={{0}}
  %ard = f32[1048576]{{0}} all-reduce-done(f32[1048576]{{0}} %ars)
  %s = f32[] constant(0)
  ROOT %out = f32[1048576]{{0}} add(f32[1048576]{{0}} %ard, f32[1048576]{{0}} %ard)
}}
"""

# the cosmetic shape the motivation names: start immediately followed
# by done — the pair exists, the window is empty
PAIR_ZERO_SLACK = f"""\
HloModule pair_zero
{_ADD}
ENTRY %main (x: f32[1048576], a: f32[512,512], b: f32[512,512]) -> f32[1048576] {{
  %x = f32[1048576]{{0}} parameter(0)
  %a = f32[512,512]{{1,0}} parameter(1)
  %b = f32[512,512]{{1,0}} parameter(2)
  %ars = f32[1048576]{{0}} all-reduce-start(f32[1048576]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
  %ard = f32[1048576]{{0}} all-reduce-done(f32[1048576]{{0}} %ars)
  %d = f32[512,512]{{1,0}} dot(f32[512,512]{{1,0}} %a, f32[512,512]{{1,0}} %b), lhs_contracting_dims={{1}}, rhs_contracting_dims={{0}}
  ROOT %out = f32[1048576]{{0}} add(f32[1048576]{{0}} %ard, f32[1048576]{{0}} %ard)
}}
"""

# a sync collective: under the sync discipline its window is the
# committed schedule's [op, first use); under the overlap discipline it
# is the dataflow window (the dot is independent either way, but only
# the dataflow model may count it — it is scheduled after the use here)
SYNC_AR = f"""\
HloModule sync_ar
{_ADD}
ENTRY %main (x: f32[1048576], a: f32[512,512], b: f32[512,512]) -> f32[512,512] {{
  %x = f32[1048576]{{0}} parameter(0)
  %a = f32[512,512]{{1,0}} parameter(1)
  %b = f32[512,512]{{1,0}} parameter(2)
  %ar = f32[1048576]{{0}} all-reduce(f32[1048576]{{0}} %x), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
  %u = f32[1048576]{{0}} negate(f32[1048576]{{0}} %ar)
  ROOT %d = f32[512,512]{{1,0}} dot(f32[512,512]{{1,0}} %a, f32[512,512]{{1,0}} %b), lhs_contracting_dims={{1}}, rhs_contracting_dims={{0}}
}}
"""


# ------------------------------------------------------------- cost model


def test_dot_flops_use_contracting_dims():
    defs = xa.parse_op_defs(PAIR_WITH_DOT)
    d = defs["main"]["d"]
    assert sched.instruction_flops(defs, "main", d, {}) == 2 * 512**3


def test_fusion_flops_inline_the_called_computation():
    hlo = """\
HloModule fus
%fused (p0: f32[64,32], p1: f32[32,16]) -> f32[64,16] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %p1 = f32[32,16]{1,0} parameter(1)
  ROOT %d = f32[64,16]{1,0} dot(f32[64,32]{1,0} %p0, f32[32,16]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
ENTRY %main (a: f32[64,32], b: f32[32,16]) -> f32[64,16] {
  %a = f32[64,32]{1,0} parameter(0)
  %b = f32[32,16]{1,0} parameter(1)
  ROOT %f = f32[64,16]{1,0} fusion(f32[64,32]{1,0} %a, f32[32,16]{1,0} %b), kind=kOutput, calls=%fused
}
"""
    defs = xa.parse_op_defs(hlo)
    f = defs["main"]["f"]
    assert sched.instruction_flops(defs, "main", f, {}) == 2 * 64 * 16 * 32


def test_while_flops_multiply_by_known_trip_count():
    hlo = """\
HloModule wh
%body (p: (s32[], f32[8,8])) -> (s32[], f32[8,8]) {
  %p = (s32[], f32[8,8]{1,0}) parameter(0)
  %c = s32[] get-tuple-element((s32[], f32[8,8]{1,0}) %p), index=0
  %g = f32[8,8]{1,0} get-tuple-element((s32[], f32[8,8]{1,0}) %p), index=1
  %d = f32[8,8]{1,0} dot(f32[8,8]{1,0} %g, f32[8,8]{1,0} %g), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %t = (s32[], f32[8,8]{1,0}) tuple(%c, %d)
}
%cond (p: (s32[], f32[8,8])) -> pred[] {
  %p = (s32[], f32[8,8]{1,0}) parameter(0)
  ROOT %lt = pred[] constant(false)
}
ENTRY %main (x: f32[8,8]) -> f32[8,8] {
  %x = f32[8,8]{1,0} parameter(0)
  %c0 = s32[] constant(0)
  %t = (s32[], f32[8,8]{1,0}) tuple(%c0, %x)
  %w = (s32[], f32[8,8]{1,0}) while((s32[], f32[8,8]{1,0}) %t), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"5"}}
  ROOT %out = f32[8,8]{1,0} get-tuple-element((s32[], f32[8,8]{1,0}) %w), index=1
}
"""
    defs = xa.parse_op_defs(hlo)
    w = defs["main"]["w"]
    assert sched.instruction_flops(defs, "main", w, {}) == 5 * 2 * 8**3


def test_data_movement_costs_zero_flops():
    defs = xa.parse_op_defs(SYNC_AR)
    dag = sched.build_dag(defs, "main")
    for name in ("x", "a", "ar"):
        assert dag.flops[dag.index[name]] == 0.0


# ----------------------------------------------------------- window slack


def test_pair_window_counts_the_dot_between_start_and_done():
    defs = xa.parse_op_defs(PAIR_WITH_DOT)
    dag = sched.build_dag(defs, "main")
    rec = sched.window_slack(dag, "ars")
    assert rec["window"] == "pair"
    assert rec["slack_flops"] == 2 * 512**3
    assert rec["independent_instructions"] == 1


def test_zero_slack_pair_window_is_empty():
    defs = xa.parse_op_defs(PAIR_ZERO_SLACK)
    dag = sched.build_dag(defs, "main")
    rec = sched.window_slack(dag, "ars")
    assert rec["window"] == "pair"
    assert rec["slack_flops"] == 0.0


def test_pair_window_excludes_dependents_of_the_start():
    # the op between start and done CONSUMES the start: not slack
    hlo = PAIR_WITH_DOT.replace(
        "%d = f32[512,512]{1,0} dot(f32[512,512]{1,0} %a, "
        "f32[512,512]{1,0} %b), lhs_contracting_dims={1}, "
        "rhs_contracting_dims={0}",
        "%d = f32[1048576]{0} negate(f32[1048576]{0} %ars)",
    )
    defs = xa.parse_op_defs(hlo)
    dag = sched.build_dag(defs, "main")
    assert sched.window_slack(dag, "ars")["slack_flops"] == 0.0


def test_sync_vs_dataflow_window_disciplines():
    defs = xa.parse_op_defs(SYNC_AR)
    dag = sched.build_dag(defs, "main")
    # sync: the committed schedule puts the use right after the op
    assert sched.window_slack(dag, "ar", "sync")["slack_flops"] == 0.0
    # overlap: the dot is dataflow-independent, wherever it is scheduled
    rec = sched.window_slack(dag, "ar", "overlap")
    assert rec["window"] == "dataflow"
    assert rec["slack_flops"] == 2 * 512**3


def test_control_predecessors_count_as_dependencies():
    hlo = SYNC_AR.replace(
        "%u = f32[1048576]{0} negate(f32[1048576]{0} %ar)",
        "%u = f32[1048576]{0} negate(f32[1048576]{0} %x), "
        "control-predecessors={%ar}",
    )
    defs = xa.parse_op_defs(hlo)
    dag = sched.build_dag(defs, "main")
    i, j = dag.index["ar"], dag.index["u"]
    assert not dag.independent(i, j)


# --------------------------------------------------------- bound roll-up


def test_static_overlap_bound_ratio_and_scalar_exemption():
    r = sched.analyze_schedule(PAIR_WITH_DOT)
    assert r["async_pairs"] == 1
    (w,) = [s for s in r["slack"] if s["result_bytes"] > 64]
    assert w["t_wire_s"] > 0
    # bound = hideable/wire over the non-scalar windows only
    expect = min(w["t_wire_s"], w["t_slack_s"]) / w["t_wire_s"]
    assert r["static_overlap_bound"] == pytest.approx(expect)
    # a module with no non-scalar collectives has no bound at all
    scalar = PAIR_WITH_DOT.replace("1048576]", "4]")
    assert sched.analyze_schedule(scalar)["static_overlap_bound"] is None


def test_zero_slack_pair_bounds_at_zero():
    r = sched.analyze_schedule(PAIR_ZERO_SLACK)
    assert r["static_overlap_bound"] == 0.0


def test_discipline_of_reads_meta():
    assert sched.discipline_of(None) == "sync"
    assert sched.discipline_of({}) == "sync"
    assert sched.discipline_of({"overlap": True}) == "overlap"
    assert sched.discipline_of({"prefetch": True}) == "overlap"


# ------------------------------------------------------- stream safety


def _sites(hlo):
    ops = xa.parse_hlo_collectives(hlo)
    defs = xa.parse_op_defs(hlo)
    return defs, ops


def test_participant_streams_expand_groups():
    defs, ops = _sites(SYNC_AR)
    sites = [dict(o, groups=[[0, 1], [2, 3]]) for o in ops]
    streams = sched.participant_streams(sites)
    assert set(streams) == {0, 1, 2, 3}
    # every participant sees the same (site, kind, groups) sequence
    assert len({tuple(v) for v in streams.values()}) == 1


def test_safety_flags_duplicate_participant_in_group():
    hlo = SYNC_AR.replace(
        "replica_groups={{0,1,2,3}}", "replica_groups={{0,0,1,2}}"
    )
    defs, ops = _sites(hlo)
    hz = sched.check_schedule_safety(hlo, defs, _anchor(hlo, ops))
    assert any(h["check"] == "duplicate-participant" for h in hz)


def test_safety_flags_out_of_range_participant():
    hlo = SYNC_AR.replace(
        "HloModule sync_ar", "HloModule sync_ar, num_partitions=4"
    ).replace("replica_groups={{0,1,2,3}}", "replica_groups={{0,1,2,9}}")
    defs, ops = _sites(hlo)
    hz = sched.check_schedule_safety(hlo, defs, _anchor(hlo, ops))
    assert any(h["check"] == "participant-out-of-range" for h in hz)
    # in-range groups on the same module are quiet
    ok = SYNC_AR.replace(
        "HloModule sync_ar", "HloModule sync_ar, num_partitions=4"
    )
    defs, ops = _sites(ok)
    assert sched.check_schedule_safety(ok, defs, _anchor(ok, ops)) == []


def test_safety_range_uses_replica_times_partition_bound():
    """A pmap-lowered REPLICA-mode module (replica_count=8,
    num_partitions=1) groups over replica ids 0-7 — comparing them
    against num_partitions alone would false-fire on every valid
    replica-mode program.  The bound is replica_count x num_partitions
    (the flattened use_global_device_ids id space)."""
    rep = SYNC_AR.replace(
        "HloModule sync_ar",
        "HloModule sync_ar, replica_count=8, num_partitions=1",
    ).replace("replica_groups={{0,1,2,3}}",
              "replica_groups={{0,1,2,3,4,5,6,7}}")
    defs, ops = _sites(rep)
    assert sched.check_schedule_safety(rep, defs, _anchor(rep, ops)) == []
    # and id 8 is still out of the 8-device flattened space
    bad = rep.replace("{{0,1,2,3,4,5,6,7}}", "{{0,1,2,3,4,5,6,8}}")
    defs, ops = _sites(bad)
    hz = sched.check_schedule_safety(bad, defs, _anchor(bad, ops))
    assert any(h["check"] == "participant-out-of-range" for h in hz)


def _anchor(hlo, ops):
    """Re-anchor inventory records with their def line + groups (what
    analyze_schedule does internally)."""
    defs = xa.parse_op_defs(hlo)
    out = []
    for op in ops:
        d = defs.get(op.get("computation") or "", {}).get(op["name"])
        site = dict(op)
        site["line"] = d["line"] if d else ""
        site["groups"] = xa._parse_groups(site["line"]) if d else None
        out.append(site)
    return out


CHANNEL_MISMATCH = f"""\
HloModule chan, num_partitions=4
{_ADD}
ENTRY %main (x: f32[1024], y: f32[1024]) -> f32[1024] {{
  %x = f32[1024]{{0}} parameter(0)
  %y = f32[1024]{{0}} parameter(1)
  %ar1 = f32[1024]{{0}} all-reduce(f32[1024]{{0}} %x), channel_id=7, replica_groups={{{{0,1}},{{2,3}}}}, use_global_device_ids=true, to_apply=%add
  %ar2 = f32[1024]{{0}} all-reduce(f32[1024]{{0}} %y), channel_id=7, replica_groups={{{{0,2}},{{1,3}}}}, use_global_device_ids=true, to_apply=%add
  ROOT %s = f32[1024]{{0}} add(f32[1024]{{0}} %ar1, f32[1024]{{0}} %ar2)
}}
"""


def test_safety_flags_channel_reuse_with_different_groups():
    """The mismatched-participant deadlock H007 cannot catch: two sites
    share a channel (the rendezvous identity) but group the mesh
    differently — each participant waits for a peer set that never
    forms."""
    defs, ops = _sites(CHANNEL_MISMATCH)
    hz = sched.check_schedule_safety(
        CHANNEL_MISMATCH, defs, _anchor(CHANNEL_MISMATCH, ops)
    )
    assert any(h["check"] == "channel-group-mismatch" for h in hz)
    # same groups on both sites: distinct instances of one rendezvous
    # shape — quiet
    ok = CHANNEL_MISMATCH.replace("{{0,2},{1,3}}", "{{0,1},{2,3}}")
    defs, ops = _sites(ok)
    assert sched.check_schedule_safety(ok, defs, _anchor(ok, ops)) == []


DIVERGENT_BRANCHES = f"""\
HloModule cond
{_ADD}
%true_b (t: f32[256]) -> f32[256] {{
  %t = f32[256]{{0}} parameter(0)
  ROOT %ar = f32[256]{{0}} all-reduce(f32[256]{{0}} %t), replica_groups={{{{0,1,2,3}}}}, to_apply=%add
}}
%false_b (f: f32[256]) -> f32[256] {{
  %f = f32[256]{{0}} parameter(0)
  ROOT %n = f32[256]{{0}} negate(f32[256]{{0}} %f)
}}
ENTRY %main (p: pred[], x: f32[256]) -> f32[256] {{
  %p = pred[] parameter(0)
  %x = f32[256]{{0}} parameter(1)
  ROOT %c = f32[256]{{0}} conditional(pred[] %p, f32[256]{{0}} %x, f32[256]{{0}} %x), true_computation=%true_b, false_computation=%false_b
}}
"""


def test_safety_flags_divergent_conditional_branches():
    defs, ops = _sites(DIVERGENT_BRANCHES)
    hz = sched.check_schedule_safety(
        DIVERGENT_BRANCHES, defs, _anchor(DIVERGENT_BRANCHES, ops)
    )
    assert any(h["check"] == "divergent-branches" for h in hz)
    # both branches issuing the SAME sequence is safe
    ok = DIVERGENT_BRANCHES.replace(
        "ROOT %n = f32[256]{0} negate(f32[256]{0} %f)",
        "ROOT %n = f32[256]{0} all-reduce(f32[256]{0} %f), "
        "replica_groups={{0,1,2,3}}, to_apply=%add",
    )
    defs, ops = _sites(ok)
    assert sched.check_schedule_safety(ok, defs, _anchor(ok, ops)) == []


CROSSED_ASYNC = f"""\
HloModule crossed
{_ADD}
ENTRY %main (x: f32[1024], y: f32[1024]) -> f32[1024] {{
  %x = f32[1024]{{0}} parameter(0)
  %y = f32[1024]{{0}} parameter(1)
  %s1 = f32[1024]{{0}} all-reduce-start(f32[1024]{{0}} %x), replica_groups={{{{0,1}}}}, to_apply=%add
  %s2 = f32[1024]{{0}} all-reduce-start(f32[1024]{{0}} %y), replica_groups={{{{1,2}}}}, to_apply=%add
  %d1 = f32[1024]{{0}} all-reduce-done(f32[1024]{{0}} %s1)
  %d2 = f32[1024]{{0}} all-reduce-done(f32[1024]{{0}} %s2)
  ROOT %s = f32[1024]{{0}} add(f32[1024]{{0}} %d1, f32[1024]{{0}} %d2)
}}
"""


def test_safety_flags_crossed_async_windows_on_unequal_groups():
    defs, ops = _sites(CROSSED_ASYNC)
    dags = {"main": sched.build_dag(defs, "main")}
    hz = sched.check_schedule_safety(
        CROSSED_ASYNC, defs, _anchor(CROSSED_ASYNC, ops), dags
    )
    assert any(h["check"] == "crossed-async-windows" for h in hz)
    # equal participant sets serialize fine; nested windows too
    ok = CROSSED_ASYNC.replace("replica_groups={{1,2}}",
                               "replica_groups={{0,1}}")
    defs, ops = _sites(ok)
    dags = {"main": sched.build_dag(defs, "main")}
    assert sched.check_schedule_safety(ok, defs, _anchor(ok, ops), dags) == []


# --------------------------------------------------- measured-cost pricing


def test_slack_vs_measured_flags_underwater_windows():
    r = sched.analyze_schedule(PAIR_ZERO_SLACK)
    record = {
        "peak_flops_per_chip": 1e12,
        "micro": [{"op": "ars", "t_s": 1e-3}],
    }
    (hit,) = sched.slack_vs_measured(r, record)
    assert hit["op"] == "ars" and hit["t_slack_s"] == 0.0
    # a window whose compute covers the measured cost passes
    r2 = sched.analyze_schedule(PAIR_WITH_DOT)
    record2 = {
        "peak_flops_per_chip": 1e12,
        # 2*512^3 flops at 1e12 = ~268 us of cover; 100 us measured
        "micro": [{"op": "ars", "t_s": 100e-6}],
    }
    assert sched.slack_vs_measured(r2, record2) == []
    # no peak on the record: no claim
    assert sched.slack_vs_measured(r, {"micro": []}) == []


# ------------------------------------------------------- strategy pins


def test_every_registered_strategy_carries_a_sched_report():
    from ddl25spring_tpu.obs.compile_report import DEFAULT_STRATEGIES

    assert set(DEFAULT_STRATEGIES) == set(xa.STRATEGIES)
    # 14 training + 2 serving (PR 10) + the cached-prefill variant
    # (PR 11) + the 2 partition-rule-table strategies (PR 12) + the
    # speculative draft/verify pair (PR 13) + the TP serving trio
    # (PR 18: tp decode/prefill + zero3 weight streaming)
    assert len(DEFAULT_STRATEGIES) == 24
    for name in DEFAULT_STRATEGIES:
        r = cached_strategy_report(name)
        s = r.get("sched")
        assert s and "error" not in s, (name, s)
        assert s["discipline"] == (
            "overlap" if ("overlap" in name or "prefetch" in name) else "sync"
        )
        # schedule safety: ZERO deadlock hazards on every strategy
        assert s["hazards"] == [], (name, s["hazards"])


@pytest.mark.parametrize("overlap,sync", [
    ("dp-overlap", "dp"),
    ("zero1-overlap", "zero1"),
    ("zero2-overlap", "zero2"),
    ("zero3-overlap", "zero3"),
])
def test_overlap_strategies_prove_strictly_positive_slack(overlap, sync):
    """THE pin the tentpole exists for: each backward-overlapped
    strategy's static overlap bound is strictly above its sync twin's —
    the provable scheduling win PR 8's noise-bound wall-clock A/B could
    not show.  The sync twin's committed schedule leaves (next to)
    nothing in its windows; the overlapped twin's dataflow provably
    holds independent backward compute."""
    r_ov = cached_strategy_report(overlap)["sched"]
    r_sy = cached_strategy_report(sync)["sched"]
    assert r_ov["static_overlap_bound"] is not None
    assert r_sy["static_overlap_bound"] is not None
    assert r_ov["static_overlap_bound"] > r_sy["static_overlap_bound"]
    assert r_ov["static_overlap_bound"] > 0.0
    # the windows carry real FLOPs, not rounding dust
    ov_slack = sum(w["slack_flops"] for w in r_ov["slack"])
    assert ov_slack > 0


def test_zero3_prefetch_double_buffer_shows_positive_slack():
    """The scanned double-buffer gathers layer i+1 while layer i
    computes — dataflow-visible slack inside the loop body."""
    s = cached_strategy_report("zero3-prefetch")["sched"]
    assert s["static_overlap_bound"] is not None
    assert s["static_overlap_bound"] > 0.0


def test_multi_bucket_describe_default():
    """The overlap-vs-sync pins need the windows to exist: a
    single-bucket plan has nothing to overlap (its one collective
    depends on the entire backward), so the describe() workloads must
    plan >= 2 buckets by default."""
    for name in ("dp", "dp-overlap", "zero1", "zero2", "zero3"):
        assert cached_strategy_report(name)["meta"]["n_buckets"] >= 2, name


def test_perfscope_record_carries_static_overlap_bound():
    """The perfscope wiring: measured records ship the analytical bound
    next to the measured overlap_eff (the CI perf-smoke contract for
    *-overlap strategies), and the bench telemetry cell exposes it."""
    from ddl25spring_tpu.obs.perfscope import perf_cell

    rec = {"static_overlap_bound": 0.25, "overlap_eff": 0.1}
    cell = perf_cell(rec)
    assert cell["static_overlap_bound"] == 0.25


def test_comms_report_sched_cell():
    from tools.comms_report import _sched_cell

    assert _sched_cell({}) == "sched: not analyzed"
    assert "degraded" in _sched_cell({"sched": {"error": "boom"}})
    r = cached_strategy_report("dp-overlap")
    cell = _sched_cell(r)
    assert "static overlap bound" in cell and "overlap issue" in cell
