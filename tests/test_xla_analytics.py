"""Compile-time XLA analytics: HLO parsing units + per-strategy
collective-signature pins.

The signature pins are the comms-regression contract: each parallel
strategy's ``describe()`` declares the analytic collective signature its
compiled train step must show (DP = grad-bytes of all-reduce and nothing
else; ZeRO-3 = per-leaf all-gathers + reduce-scatters with NO param-sized
all-reduce; GPipe = ``M + S - 1`` collective-permutes per direction; ...),
and these tests assert the optimized HLO matches — on CPU, no
accelerator.  A refactor that silently adds a stray all-gather or breaks
fusion fails here before it ever reaches a TPU.

Strategies whose grad path needs VMA-typed shard_map lower forward-only
on this jax (``describe()`` handles the gating); the pins below compute
their expectations from ``meta``/``lowered`` so they are green on both
vintages.
"""

import jax
import jax.numpy as jnp
import pytest

from ddl25spring_tpu.obs import xla_analytics as xa
from ddl25spring_tpu.utils.compat import (
    HAS_VMA,
    compiled_cost_analysis,
    compiled_memory_stats,
)
from ddl25spring_tpu.utils.mesh import make_mesh

# ------------------------------------------------------------ parser units

SYNTHETIC_HLO = """\
HloModule synthetic, is_scheduled=true

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

%body (p: (s32[], f32[4,8])) -> (s32[], f32[4,8]) {
  %p = (s32[], f32[4,8]{1,0}) parameter(0)
  %g = f32[4,8]{1,0} get-tuple-element((s32[], f32[4,8]{1,0}) %p), index=1
  %cp = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %g), channel_id=1, source_target_pairs={{0,2},{2,0},{1,3},{3,1}}, metadata={op_name="ppermute" source_file="fake.py" source_line=7}
  ROOT %t = (s32[], f32[4,8]{1,0}) tuple(%g, %cp)
}

%cond (p: (s32[], f32[4,8])) -> pred[] {
  %p = (s32[], f32[4,8]{1,0}) parameter(0)
  ROOT %lt = pred[] constant(false)
}

%dead (x: f32[2]) -> f32[2] {
  %x = f32[2]{0} parameter(0)
  ROOT %agd = f32[2]{0} all-gather(f32[2]{0} %x), replica_groups={{0,1,2,3}}
}

ENTRY %main (x: f32[4,8]) -> f32[4,8] {
  %x = f32[4,8]{1,0} parameter(0)
  %ar = (f32[4,8]{1,0}, f32[2]{0}) all-reduce(f32[4,8]{1,0} %x, f32[2]{0} %x), channel_id=2, replica_groups={{0,1},{2,3}}, use_global_device_ids=true, to_apply=%add
  %t = (s32[], f32[4,8]{1,0}) tuple(%x, %x)
  %w = (s32[], f32[4,8]{1,0}) while((s32[], f32[4,8]{1,0}) %t), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"7"}}
  ROOT %out = f32[4,8]{1,0} get-tuple-element((s32[], f32[4,8]{1,0}) %w), index=1
}
"""


@pytest.fixture(scope="module")
def mesh22(devices8):
    return make_mesh(devices8[:4], outer=2, inner=2)


def test_parser_counts_and_trip_multipliers(mesh22):
    ops = xa.parse_hlo_collectives(SYNTHETIC_HLO, mesh22)
    kinds = {o["kind"]: o for o in ops}
    # the dead computation's all-gather is unreachable from ENTRY
    assert set(kinds) == {"all-reduce", "collective-permute"}
    ar = kinds["all-reduce"]
    # tuple-shaped fused all-reduce: f32[4,8] + f32[2] = 128 + 8 bytes
    assert ar["result_bytes"] == 136
    assert ar["count"] == 1 and ar["trip_known"]
    cp = kinds["collective-permute"]
    # one site inside a while with known_trip_count 7
    assert cp["count"] == 7 and cp["trip_known"]
    assert cp["result_bytes"] == 128
    assert cp["source"] == "fake.py:7"


def test_parser_axes_from_groups_and_pairs(mesh22):
    ops = xa.parse_hlo_collectives(SYNTHETIC_HLO, mesh22)
    by = {o["kind"]: o for o in ops}
    # groups {{0,1},{2,3}} vary the INNER coordinate of the 2x2 mesh
    assert by["all-reduce"]["axes"] == ["inner"]
    assert by["all-reduce"]["group_size"] == 2
    # pairs {0<->2, 1<->3} vary the OUTER coordinate
    assert by["collective-permute"]["axes"] == ["outer"]


def test_parser_iota_replica_groups(mesh22):
    txt = SYNTHETIC_HLO.replace(
        "replica_groups={{0,1},{2,3}}", "replica_groups=[2,2]<=[4]"
    )
    ops = xa.parse_hlo_collectives(txt, mesh22)
    ar = next(o for o in ops if o["kind"] == "all-reduce")
    # iota [2,2]<=[4] is {{0,1},{2,3}} — same inner-axis grouping
    assert ar["axes"] == ["inner"]


def test_totals_and_wire_accounting():
    ops = xa.parse_hlo_collectives(SYNTHETIC_HLO)
    totals = xa.collective_totals(ops)
    assert totals["collective-permute"]["count"] == 7
    assert totals["collective-permute"]["result_bytes"] == 7 * 128
    # permute wire = one payload per execution
    assert totals["collective-permute"]["wire_bytes"] == 7 * 128
    # ring all-reduce over groups of 2: 2 * (n-1)/n = 1x payload
    assert totals["all-reduce"]["wire_bytes"] == 136


def test_check_signature_catches_drift():
    ops = [
        {"kind": "all-reduce", "result_bytes": 1000, "count": 2,
         "trip_known": True, "axes": ["data"], "group_size": 4,
         "wire_bytes": 1500, "source": "x.py:1"},
        {"kind": "all-gather", "result_bytes": 500, "count": 1,
         "trip_known": True, "axes": ["stage"], "group_size": 2,
         "wire_bytes": 250, "source": "x.py:2"},
    ]
    report = {"collectives": {"ops": ops, "totals": xa.collective_totals(ops)}}
    ok = xa.check_signature(report, {
        "all-reduce": {"count": 2, "min_bytes": 2000, "axes": ["data"]},
    })
    assert ok == []
    viols = xa.check_signature(report, {
        "forbidden": ["all-gather"],
        "all-reduce": {"count": 1, "max_bytes": 100, "axes": ["model"]},
    })
    # stray kind + count drift + byte drift + wrong axis all reported
    assert len(viols) == 4


def test_strategy_mesh_folds_extra_dims():
    mesh = xa.strategy_mesh("zero3", (2, 4))
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"data": 8}
    mesh = xa.strategy_mesh("pipeline", (2,))
    assert mesh.axis_names == ("stage",)


def test_roofline_projection_bounds():
    # 1e12 flops on a 275e12-peak chip with negligible bytes: compute-bound
    p = xa.roofline_projection(1e12, 1e6, 0.0, chips=["TPU v4"])["TPU v4"]
    assert p["bound"] == "compute"
    assert p["projected_mfu"] == pytest.approx(1.0)
    # byte-dominated program: hbm-bound, low MFU
    p = xa.roofline_projection(1e9, 1e12, 0.0, chips=["TPU v4"])["TPU v4"]
    assert p["bound"] == "hbm" and p["projected_mfu"] < 0.01
    # collective-dominated: ici-bound
    p = xa.roofline_projection(1e9, 0.0, 1e12, chips=["TPU v4"])["TPU v4"]
    assert p["bound"] == "ici"


# ------------------------------------------------ compat fallbacks (0.4.x)


class _FakeMemStatsOld:
    """CompiledMemoryStats as jax 0.4.x ships it: no peak field."""

    argument_size_in_bytes = 1000
    output_size_in_bytes = 300
    temp_size_in_bytes = 700
    alias_size_in_bytes = 100
    generated_code_size_in_bytes = 50


class _FakeMemStatsNew(_FakeMemStatsOld):
    peak_memory_in_bytes = 4242


def test_memory_stats_fallback_assembles_peak():
    class C:
        def memory_analysis(self):
            return _FakeMemStatsOld()

    out = compiled_memory_stats(C())
    assert out["peak_hbm_bytes"] == 1000 + 300 + 700 + 50 - 100


def test_memory_stats_prefers_backend_peak():
    class C:
        def memory_analysis(self):
            return _FakeMemStatsNew()

    assert compiled_memory_stats(C())["peak_hbm_bytes"] == 4242


def test_memory_stats_absent_or_raising_is_none():
    class NoApi:
        pass

    class Raising:
        def memory_analysis(self):
            raise NotImplementedError("backend has no memory stats")

    class ReturnsNone:
        def memory_analysis(self):
            return None

    assert compiled_memory_stats(NoApi()) is None
    assert compiled_memory_stats(Raising()) is None
    assert compiled_memory_stats(ReturnsNone()) is None


def test_cost_analysis_per_module_list_and_failures():
    class ListShaped:
        def cost_analysis(self):
            return [{"flops": 7.0}, {"flops": 1.0}]

    class DictShaped:
        def cost_analysis(self):
            return {"flops": 9.0}

    class Raising:
        def cost_analysis(self):
            raise RuntimeError("no cost model")

    class Empty:
        def cost_analysis(self):
            return []

    assert compiled_cost_analysis(ListShaped()) == {"flops": 7.0}
    assert compiled_cost_analysis(DictShaped()) == {"flops": 9.0}
    assert compiled_cost_analysis(Raising()) is None
    assert compiled_cost_analysis(Empty()) is None


def test_compiled_flops_rides_the_shared_compat_path():
    from ddl25spring_tpu.utils.flops import compiled_flops

    @jax.jit
    def f(a):
        return (a @ a).sum()

    fl = compiled_flops(f, jnp.ones((32, 32)))
    assert fl is not None and fl >= 2 * 32**3


# ------------------------------------------------- strategy signature pins

# the compile-once cache moved to tests/conftest.py (PR 9): one
# compile_strategy() per strategy per SESSION, shared with
# test_hlo_lint's clean baselines and test_sched's overlap-bound pins
from conftest import cached_strategy_report as _report  # noqa: E402


def _count(r: dict, kind: str) -> int:
    return r["collectives"]["totals"].get(kind, {}).get("count", 0)


def _payload(r: dict, kind: str) -> int:
    return r["collectives"]["totals"].get(kind, {}).get("result_bytes", 0)


def test_dp_signature_exactly_one_fused_gradient_allreduce():
    r = _report("dp")
    assert r["signature_violations"] == []
    grad = r["meta"]["grad_bytes"]
    # all traffic is the gradient all-reduce (+ scalar loss reductions)
    assert grad <= _payload(r, "all-reduce") <= grad + 256
    # bucketed: the non-scalar launches == the plan's bucket count
    big = [
        o for o in r["collectives"]["ops"]
        if o["kind"] == "all-reduce" and o["result_bytes"] > 64
    ]
    assert sum(o["count"] for o in big) == r["meta"]["n_buckets"]
    for kind in ("all-gather", "reduce-scatter", "collective-permute",
                 "all-to-all"):
        assert _count(r, kind) == 0, f"plain DP grew a stray {kind}"
    assert all(
        o["axes"] == ["data"]
        for o in r["collectives"]["ops"] if o["result_bytes"] > 64
    )


def test_dp_overlap_signature_matches_dp_with_backward_issue():
    """The overlapped DP strategy is a scheduling restructure, not a
    traffic change: identical all-reduce payload, the same per-bucket
    launch ceiling, data-axis-only grouping, and the same forbidden
    kinds as sync dp — any drift here means the custom_vjp machinery
    changed what crosses the wire.  The meta declares the mode so every
    downstream consumer (perfscope records, comms tables) names it."""
    r = _report("dp-overlap")
    sync = _report("dp")
    assert r["signature_violations"] == []
    assert r["meta"]["overlap"] is True
    assert r["meta"]["bucket_bytes"] == sync["meta"]["bucket_bytes"]
    # same bytes on the wire as sync dp, same bucket-count launch shape
    assert _payload(r, "all-reduce") == _payload(sync, "all-reduce")
    big = [
        o for o in r["collectives"]["ops"]
        if o["kind"] == "all-reduce" and o["result_bytes"] > 64
    ]
    assert sum(o["count"] for o in big) == r["meta"]["n_buckets"]
    for kind in ("all-gather", "reduce-scatter", "collective-permute",
                 "all-to-all"):
        assert _count(r, kind) == 0, f"dp-overlap grew a stray {kind}"


def test_zero3_overlap_signature_matches_zero3():
    """zero3-overlap re-plans the row buckets in backward-readiness
    order — gather/scatter counts, payloads, and the no-param-all-reduce
    invariant pin identically to sync zero3."""
    r = _report("zero3-overlap")
    sync = _report("zero3")
    assert r["signature_violations"] == []
    assert r["meta"]["overlap"] is True
    for kind in ("all-gather", "reduce-scatter"):
        assert _count(r, kind) == _count(sync, kind)
        assert _payload(r, kind) == _payload(sync, kind)
    assert _payload(r, "all-reduce") <= 64


def test_zero3_signature_bucketed_gathers_and_scatters():
    r = _report("zero3")
    assert r["signature_violations"] == []
    n_buckets = r["meta"]["n_buckets"]
    padded = r["meta"]["padded_param_bytes"]
    n = r["mesh"]["data"]
    assert n_buckets < r["meta"]["n_param_leaves"]
    # forward gathers the full padded params, once per BUCKET (the
    # O(n_leaves) -> O(n_buckets) collapse; per-leaf counts are pinned
    # against this path in test_zero3_bucketing_collapses_llama_launches)
    assert _count(r, "all-gather") == n_buckets
    assert _payload(r, "all-gather") == padded
    # backward reduce-scatters the 1/n grad shards, once per bucket
    assert _count(r, "reduce-scatter") == n_buckets
    assert _payload(r, "reduce-scatter") == padded // n
    # NO param-sized all-reduce — that would be replicated DP again
    assert _payload(r, "all-reduce") <= 64


def test_zero_stage1_vs_stage2_collective_distinction():
    r1, r2 = _report("zero1"), _report("zero2")
    assert r1["signature_violations"] == []
    assert r2["signature_violations"] == []
    padded = r1["meta"]["padded_param_bytes"]
    # stage 1: full-grad all-reduce, NO reduce-scatter
    assert _payload(r1, "all-reduce") >= padded
    assert _count(r1, "reduce-scatter") == 0
    # stage 2: grads reduce-scatter straight to shards (one launch per
    # bucket), NO full all-reduce
    assert _count(r2, "reduce-scatter") == r2["meta"]["n_buckets"]
    assert _payload(r2, "all-reduce") <= 64
    # both all-gather the updated params back to replicas
    for r in (r1, r2):
        assert _payload(r, "all-gather") == padded
        assert _count(r, "all-gather") == r["meta"]["n_buckets"]


def test_zero3_bucketing_collapses_llama_launches():
    """The tentpole's machine-checkable core: on a param tree with a
    realistic leaf count (tiny LLaMA, 12 leaves), the bucketed ZeRO-3
    step launches O(n_buckets) collectives — strictly fewer than the
    per-leaf path's O(n_leaves) — while moving the same padded bytes."""
    bucketed = xa.compile_strategy("zero3", workload="llama")
    per_leaf = xa.compile_strategy(
        "zero3", workload="llama", bucketed=False
    )
    assert "error" not in bucketed and "error" not in per_leaf
    assert bucketed["signature_violations"] == []
    assert per_leaf["signature_violations"] == []
    n_leaves = per_leaf["meta"]["n_param_leaves"]
    n_buckets = bucketed["meta"]["n_buckets"]
    assert n_buckets < n_leaves
    for kind in ("all-gather", "reduce-scatter"):
        assert _count(per_leaf, kind) == n_leaves
        assert _count(bucketed, kind) == n_buckets
        assert _count(bucketed, kind) < _count(per_leaf, kind)
        # same padded payload rides fewer launches
        assert _payload(bucketed, kind) == _payload(per_leaf, kind)


def test_zero3_prefetch_gather_rides_the_layer_scan():
    """Leg-2 pin: the scanned-LLaMA prefetch step's parameter all-gather
    sits INSIDE the layer while-loop (trip count == n_layers, annotated
    by XLA) — one launch per layer-bucket per trip plus the initial
    double-buffer fill — instead of one up-front whole-tree gather."""
    r = _report("zero3-prefetch")
    assert r["signature_violations"] == []
    assert r["lowered"] == "train_step"
    L = r["meta"]["n_layers"]
    n_lb = r["meta"]["n_layer_buckets"]
    n_ob = r["meta"]["n_outer_buckets"]
    in_loop = [
        o for o in r["collectives"]["ops"]
        if o["kind"] == "all-gather" and o["count"] >= L - 1
    ]
    assert in_loop and all(o["trip_known"] for o in in_loop)
    # forward issues: L-1 in-scan (the peeled last layer prefetches
    # nothing) + 1 initial fill per layer-bucket, plus the outer
    # (embed/ln_f/unembed) gathers — exactly one gather per layer
    assert _count(r, "all-gather") == n_lb * L + n_ob
    # the backward reduce-scatters every layer's grads
    assert _count(r, "reduce-scatter") >= n_lb * (L - 1)
    assert _payload(r, "all-reduce") <= 64  # never collapses to DP


def test_strategy_reports_pin_memory_budgets_and_donation():
    """Satellite pins: every describe() that declares a peak-HBM budget
    or a donation floor is enforced through signature_violations (so an
    HBM regression fails tier-1 like a comms regression), and the
    donated builds alias a nonzero byte count on this backend."""
    for name in ("dp", "zero1", "zero2", "zero3", "zero3-prefetch", "ep"):
        r = _report(name)
        assert r["signature_violations"] == []
        assert "memory" in r["expected"], name
        assert "donation" in r["expected"], name
        assert r["memory"]["peak_hbm_bytes"] <= (
            r["expected"]["memory"]["max_peak_hbm_bytes"]
        )
        assert r["donation"]["hbm_saved_bytes"] >= (
            r["expected"]["donation"]["min_saved_bytes"]
        )
        assert r["donation"]["hbm_saved_bytes"] > 0


def test_pipeline_signature_ticks_times_permutes():
    r = _report("pipeline")
    assert r["signature_violations"] == []
    T = r["meta"]["ticks"]  # M + S - 1
    hops = _count(r, "collective-permute")
    if r["lowered"] == "loss":  # pre-VMA: forward schedule only
        assert hops == T, (
            f"GPipe forward must hop exactly microbatches+stages-1={T} "
            f"times, measured {hops}"
        )
    else:  # value_and_grad: the scan transpose replays the ring
        assert T * 2 <= hops <= T * 3
    assert all(
        o["axes"] == ["stage"]
        for o in r["collectives"]["ops"]
        if o["kind"] == "collective-permute"
    )
    # every boundary hop carries the [mb, L, d] activation
    assert _payload(r, "collective-permute") == hops * r["meta"]["boundary_bytes"]


def test_het_pipeline_signature():
    r = _report("het_pipeline")
    assert r["signature_violations"] == []
    T = r["meta"]["ticks"]
    hops = _count(r, "collective-permute")
    expect = T if r["lowered"] == "loss" else 2 * T
    assert hops == expect
    assert _payload(r, "collective-permute") == hops * r["meta"]["boundary_bytes"]
    assert _count(r, "all-gather") == 0


def test_tp_signature_allreduce_over_model_only():
    r = _report("tp")
    assert r["signature_violations"] == []
    # >= 2 row-parallel psums per block forward + backward mirrors
    assert _count(r, "all-reduce") >= 4 * r["meta"]["n_layers"]
    assert _count(r, "collective-permute") == 0
    # nothing may group outside the model axis (no data axis on this mesh)
    assert all(
        set(o["axes"]) <= {"model"}
        for o in r["collectives"]["ops"]
        if o["axes"] is not None and o["result_bytes"] > 64
    )


def test_sp_ring_signature_permutes_over_seq():
    r = _report("sp")
    assert r["signature_violations"] == []
    n = r["meta"]["seq_shards"]
    # at least one KV rotation per ring step per layer, plus boundary hops
    assert _count(r, "collective-permute") >= r["meta"]["n_layers"] * n
    assert _count(r, "all-to-all") == 0  # ring mode never all-to-alls
    assert all(
        o["axes"] == ["seq"]
        for o in r["collectives"]["ops"]
        if o["kind"] == "collective-permute"
    )


def test_ep_signature_alltoall_dispatch_combine():
    r = _report("ep")
    assert r["signature_violations"] == []
    # dispatch + combine forward; backward transposes may CSE
    assert 2 <= _count(r, "all-to-all") <= 4
    assert _count(r, "collective-permute") == 0
    assert _count(r, "reduce-scatter") == 0
    assert all(
        o["axes"] == ["expert"]
        for o in r["collectives"]["ops"] if o["kind"] == "all-to-all"
    )


def test_reports_carry_memory_and_flops():
    r = _report("dp")
    assert r["memory"]["peak_hbm_bytes"] > 0
    assert r["flops"] and r["flops"] > 0
    assert "TPU v4" in r["projection"]


@pytest.mark.skipif(
    not HAS_VMA,
    reason="pipeline grad-path signatures need VMA-typed shard_map "
    "(same gating as tests/test_pipeline.py); forward-only covered above",
)
def test_pipeline_grad_signature_doubles_the_ring():
    # on VMA jax the pipeline strategy lowers value_and_grad: the
    # transpose must replay the forward's M+S-1 hops in reverse
    r = _report("pipeline")
    assert r["lowered"] == "value_and_grad"
    assert _count(r, "collective-permute") >= 2 * r["meta"]["ticks"]


# ----------------------------------------------------- bench driver pieces


def test_attach_parent_telemetry_merges_into_bench_line():
    import bench

    rec = {"metric": "m", "value": 0.0, "error": "accelerator unreachable"}
    failures = [{"record": "bench_retry_failure", "attempt": 1,
                 "error": "timeout", "backoff_s": 60.0, "wall_s": 1.0,
                 "rc": None}]
    cr = {"record": "compile_report", "strategies": {}}
    out = bench.attach_parent_telemetry(rec, failures, cr)
    assert out["telemetry"]["retry_failures"] == failures
    assert out["telemetry"]["compile_report"] is cr
    # an existing telemetry dict is extended, not replaced
    rec2 = {"telemetry": {"enabled": True, "phases": {}}}
    out2 = bench.attach_parent_telemetry(rec2, failures, None)
    assert out2["telemetry"]["enabled"] is True
    assert out2["telemetry"]["retry_failures"] == failures


def test_compile_report_document_shape():
    from ddl25spring_tpu.obs.compile_report import build_compile_report

    doc = build_compile_report(["dp"])
    assert doc["record"] == "compile_report"
    assert "dp" in doc["strategies"]
    # reuse the cached strategy report for the deep checks
    assert doc["strategies"]["dp"]["collectives"]["totals"]
