"""Unit tests: FLOPs/MFU accounting and the notebook scrubber."""

import json

import jax
import numpy as np

from ddl25spring_tpu.utils.flops import chip_peak_flops, compiled_flops, mfu


def test_compiled_flops_counts_matmul():
    import jax.numpy as jnp

    @jax.jit
    def f(a, b):
        return (a @ b).sum()

    a = jnp.ones((128, 128))
    fl = compiled_flops(f, a, a)
    # 2*n^3 MACs-as-flops, plus the reduction; cost model may round
    assert fl is not None and fl >= 2 * 128**3


def test_chip_peak_prefix_match_prefers_longest():
    # device_kind "TPU v5 lite" must hit the v5e entry (197e12), not the
    # "TPU v5" (v5p) prefix
    class FakeDev:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    assert chip_peak_flops(FakeDev()) == 197e12

    class FakeV5p:
        platform = "tpu"
        device_kind = "TPU v5"

    assert chip_peak_flops(FakeV5p()) == 459e12


def test_chip_peak_on_cpu_calibrates_host_fallback():
    # datasheet-only callers still get None off-TPU ...
    assert chip_peak_flops(jax.devices("cpu")[0], allow_host=False) is None
    # ... but the default contract is now DEFINED on the CPU CI image:
    # the calibrated cpu-host pseudo-peak (perfscope's measured-MFU
    # denominator), so obs_report's MFU column stops reading n/a here
    peak = chip_peak_flops(jax.devices("cpu")[0])
    assert peak is not None and peak > 0


def test_host_peak_spec_cpu_host():
    from ddl25spring_tpu.utils.flops import (
        CHIP_SPECS,
        CPU_HOST_KIND,
        host_peak_spec,
    )

    kind, spec = host_peak_spec(jax.devices("cpu")[0])
    assert kind == CPU_HOST_KIND
    assert spec["peak_bf16_flops"] > 0
    # the calibrated peak replaces the placeholder; bandwidth terms
    # come from the static pseudo-spec
    assert spec["hbm_bytes_per_s"] == (
        CHIP_SPECS[CPU_HOST_KIND]["hbm_bytes_per_s"]
    )

    class FakeV4:
        platform = "tpu"
        device_kind = "TPU v4"

    kind, spec = host_peak_spec(FakeV4())
    assert kind == "TPU v4"
    assert spec == CHIP_SPECS["TPU v4"]


def test_roofline_projects_with_peak_only_spec():
    """A chip known only by its bf16 peak (TPU v2/v3/7x — in
    PEAK_BF16_FLOPS but without a full CHIP_SPECS entry, the shape
    host_peak_spec returns there) must still project: an unknown
    bandwidth just doesn't bound the step."""
    from ddl25spring_tpu.obs.xla_analytics import roofline_projection

    p = roofline_projection(
        1e12, 1e9, 1e6, chips=["TPU v2"],
        specs={"TPU v2": {"peak_bf16_flops": 45e12}},
    )["TPU v2"]
    assert p["bound"] == "compute"
    assert p["projected_mfu"] == 1.0


def test_calibration_failure_is_cached(monkeypatch):
    import jax as _jax

    from ddl25spring_tpu.utils import flops as fl

    monkeypatch.setattr(fl, "_HOST_PEAK", None)
    monkeypatch.setattr(fl, "_HOST_PEAK_TRIED", False)
    calls = []

    def broken_jit(*a, **k):
        calls.append(1)
        raise RuntimeError("broken backend")

    monkeypatch.setattr(_jax, "jit", broken_jit)
    assert fl.calibrated_host_peak_flops() is None
    assert fl.calibrated_host_peak_flops() is None
    # the failed attempt is cached: one timed-matmul attempt per
    # process, not one per peak lookup
    assert len(calls) == 1
    # and the placeholder peak never masquerades as a calibration:
    # spec is None, so perfscope nulls measured_mfu instead of faking
    # one against the 5e10 constant
    kind, spec = fl.host_peak_spec(jax.devices("cpu")[0])
    assert kind == fl.CPU_HOST_KIND and spec is None


def test_resnet_roofline_rides_shared_projection():
    """Drift pin for the PR-7 fold: tools/resnet_roofline.py must source
    its chip numbers from the one CHIP_SPECS table and compute each
    layer through xla_analytics.roofline_projection — re-deriving a
    layer independently must reproduce the tool's row exactly."""
    import pytest

    from ddl25spring_tpu.obs.xla_analytics import roofline_projection
    from ddl25spring_tpu.utils.flops import CHIP_SPECS
    from tools.resnet_roofline import CHIP, HBM_BW, PEAK_BF16, layer_rooflines

    assert PEAK_BF16 == CHIP_SPECS[CHIP]["peak_bf16_flops"]
    assert HBM_BW == CHIP_SPECS[CHIP]["hbm_bytes_per_s"]
    rows = layer_rooflines(256)
    assert len(rows) == 11
    for r in rows:
        # per-layer time = max(compute, bandwidth) * count — the
        # roofline contract, now via the shared helper
        assert r["t_s"] == pytest.approx(
            max(r["t_comp_s"], r["t_bw_s"]) * r["count"]
        )
    stem = rows[0]
    spec = CHIP_SPECS[CHIP]
    p = roofline_projection(
        3 * stem["flops_fwd"], 3 * stem["bytes_fwd"], 0.0, chips=[CHIP],
        specs={CHIP: {**spec, "peak_bf16_flops":
                      spec["peak_bf16_flops"] * stem["mxu_eff"]}},
    )[CHIP]
    assert stem["t_s"] == pytest.approx(
        p["projected_step_s"] * stem["count"]
    )
    # the stem's 3->64 conv cannot fill the 128-lane MXU
    assert stem["mxu_eff"] < 0.25


def test_mfu_math():
    class FakeDev:
        platform = "tpu"
        device_kind = "TPU v4"

    tf, frac = mfu(275e12, 1.0, n_chips=1, device=FakeDev())
    assert tf == 275.0
    np.testing.assert_allclose(frac, 1.0)
    assert mfu(None, 1.0) == (None, None)


def test_notebook_scrubber(tmp_path):
    import subprocess
    import sys

    nb = {
        "metadata": {"kernelspec": {"name": "python3"}, "widgets": {"x": 1}},
        "nbformat": 4, "nbformat_minor": 5,
        "cells": [{
            "cell_type": "code", "source": ["1+1"],
            "execution_count": 3, "metadata": {"scrolled": True},
            "outputs": [{"output_type": "execute_result", "data": {}}],
        }],
    }
    from pathlib import Path

    tool = Path(__file__).resolve().parent.parent / "tools/clear_notebook_metadata.py"
    p = tmp_path / "x.ipynb"
    p.write_text(json.dumps(nb))
    r = subprocess.run(
        [sys.executable, str(tool), str(tmp_path)],
        capture_output=True, text=True, check=True,
    )
    assert "1 notebook(s) changed" in r.stdout
    out = json.loads(p.read_text())
    cell = out["cells"][0]
    assert cell["outputs"] == [] and cell["execution_count"] is None
    assert cell["metadata"] == {}
    assert "widgets" not in out["metadata"]
    assert "kernelspec" in out["metadata"]
