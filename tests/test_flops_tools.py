"""Unit tests: FLOPs/MFU accounting and the notebook scrubber."""

import json

import jax
import numpy as np

from ddl25spring_tpu.utils.flops import chip_peak_flops, compiled_flops, mfu


def test_compiled_flops_counts_matmul():
    import jax.numpy as jnp

    @jax.jit
    def f(a, b):
        return (a @ b).sum()

    a = jnp.ones((128, 128))
    fl = compiled_flops(f, a, a)
    # 2*n^3 MACs-as-flops, plus the reduction; cost model may round
    assert fl is not None and fl >= 2 * 128**3


def test_chip_peak_prefix_match_prefers_longest():
    # device_kind "TPU v5 lite" must hit the v5e entry (197e12), not the
    # "TPU v5" (v5p) prefix
    class FakeDev:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    assert chip_peak_flops(FakeDev()) == 197e12

    class FakeV5p:
        platform = "tpu"
        device_kind = "TPU v5"

    assert chip_peak_flops(FakeV5p()) == 459e12


def test_chip_peak_none_on_cpu():
    assert chip_peak_flops(jax.devices("cpu")[0]) is None


def test_mfu_math():
    class FakeDev:
        platform = "tpu"
        device_kind = "TPU v4"

    tf, frac = mfu(275e12, 1.0, n_chips=1, device=FakeDev())
    assert tf == 275.0
    np.testing.assert_allclose(frac, 1.0)
    assert mfu(None, 1.0) == (None, None)


def test_notebook_scrubber(tmp_path):
    import subprocess
    import sys

    nb = {
        "metadata": {"kernelspec": {"name": "python3"}, "widgets": {"x": 1}},
        "nbformat": 4, "nbformat_minor": 5,
        "cells": [{
            "cell_type": "code", "source": ["1+1"],
            "execution_count": 3, "metadata": {"scrolled": True},
            "outputs": [{"output_type": "execute_result", "data": {}}],
        }],
    }
    from pathlib import Path

    tool = Path(__file__).resolve().parent.parent / "tools/clear_notebook_metadata.py"
    p = tmp_path / "x.ipynb"
    p.write_text(json.dumps(nb))
    r = subprocess.run(
        [sys.executable, str(tool), str(tmp_path)],
        capture_output=True, text=True, check=True,
    )
    assert "1 notebook(s) changed" in r.stdout
    out = json.loads(p.read_text())
    cell = out["cells"][0]
    assert cell["outputs"] == [] and cell["execution_count"] is None
    assert cell["metadata"] == {}
    assert "widgets" not in out["metadata"]
    assert "kernelspec" in out["metadata"]
