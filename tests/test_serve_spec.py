"""Speculative decoding (PR 13): the tiny-LLaMA drafter, the k-token
draft + single-verify round, jit-safe rollback, and the spec tooling.

The load-bearing pins:

- **spec == sequential, bitwise** — greedy speculative decode through
  the drafter + verify + truncate path reproduces the dense oracle
  token for token, across accept-all, reject-first (pinned with a
  random-weight drafter that never agrees), mid-draft rejection,
  EOS-inside-draft, and draft windows straddling page boundaries.
- **pool invariant under spec interleavings** — the seeded sweep
  (tests/test_serve_prefix.py pattern) holds ``refcount == table refs
  (+ cache claim)`` on BOTH pools at every step across draft / verify /
  reject / release / prefix-adopt interleavings, and teardown leaks
  nothing.
- **the win is deterministic** — spec-on vs spec-off on the virtual
  clock at equal admission budget shows a strictly positive advantage
  on the deep smoke config, with ``serve_report --check-spec-ab``
  passing the resulting cell (and failing defective ones).

Compile budget: every engine here shares the tiny 2-layer CFG with
test_serve.py (its tick/prefill/release programs come from the
module-level jit caches already paid for), the drafter programs are
shared across every spec engine (one draft cfg, one k), and the deep
strict-win A/B runs ONCE at module scope.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.models import decode as dm, llama
from ddl25spring_tpu.serve import kv_pages, spec as spec_mod
from ddl25spring_tpu.serve.engine import ServeEngine
from ddl25spring_tpu.serve.traffic import TrafficSpec, synth_trace
from ddl25spring_tpu.utils.config import LlamaConfig, replace

from conftest import cached_lowering

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)
DEEP_CFG = replace(CFG, n_layers=6)  # the tiny-deep serve model
K = 3  # one k for every test engine: the draft programs compile once


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


def dense_greedy(params, prompt: list[int], max_new: int) -> list[int]:
    """The dense-cache oracle, compiled once per (|prompt|, max_new)
    across the whole session (shared with test_serve/test_serve_prefix
    via the lower-once cache)."""

    def build():
        toks = dm.generate(
            params, jnp.asarray([prompt], jnp.int32), CFG,
            max_new_tokens=max_new, temperature=0.0,
        )
        return [int(t) for t in np.asarray(toks)[0]]

    return cached_lowering(("serve-dense", tuple(prompt), max_new), build)


def make_engine(params, **kw):
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    kw.setdefault("spec_k", K)
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


def assert_draft_pool_invariants(eng):
    """The drafter pool's half of the PR-11 contract: no cache ever
    claims drafter pages, so ``refcount[p]`` must equal the page-table
    reference count exactly, and ``free`` the zero-refcount set."""
    refcount = np.asarray(jax.device_get(eng.draft_pool["refcount"]))
    free = np.asarray(jax.device_get(eng.draft_pool["free"]))
    table = np.asarray(jax.device_get(eng.draft_pool["page_table"]))
    n_pages = free.shape[0]
    assert (free == (refcount == 0)).all()
    assert (refcount >= 0).all()
    table_refs = np.bincount(
        table[table >= 0].ravel(), minlength=n_pages
    )[:n_pages]
    assert (refcount == table_refs).all(), (
        refcount.tolist(), table_refs.tolist(),
    )


# ------------------------------------------------- truncate_to units


def test_truncate_to_frees_rolled_back_pages():
    pool = kv_pages.init_page_pool(
        CFG, n_pages=6, page_len=4, max_slots=2, pages_per_seq=4,
    )
    # slot 0 allocates entries 0..2 (positions 0, 4, 8)
    for pos in (0, 4, 8):
        pool, ok = kv_pages.reserve_pages(
            pool, jnp.asarray([0, 1]), jnp.asarray([pos, 0]),
            jnp.asarray([True, False]),
        )
        assert bool(ok)
    pool = {**pool, "seq_len": jnp.asarray([9, 0]),
            "active": jnp.asarray([True, False])}
    assert int(kv_pages.used_pages(pool)) == 3
    # roll back to 5 written positions: entry 2 (start 8) drops, entry
    # 1 (start 4, holds position 4) is the kept frontier page
    pool2 = kv_pages.truncate_to(
        pool, jnp.asarray([5, 0]), jnp.asarray([True, False])
    )
    assert int(kv_pages.used_pages(pool2)) == 2
    table = np.asarray(pool2["page_table"])
    assert table[0, 0] >= 0 and table[0, 1] >= 0 and table[0, 2] == -1
    assert int(pool2["seq_len"][0]) == 5
    # an unmasked slot is untouched even with new_len 0
    assert (np.asarray(pool2["page_table"])[1]
            == np.asarray(pool["page_table"])[1]).all()
    # a new_len at/above the frontier is a no-op (the drafter-pool case
    # on a fully-accepted round)
    pool3 = kv_pages.truncate_to(
        pool, jnp.asarray([12, 0]), jnp.asarray([True, False])
    )
    assert int(kv_pages.used_pages(pool3)) == 3
    assert int(pool3["seq_len"][0]) == 9  # min(9, 12): never grows


def test_truncate_to_decrements_shared_pages():
    """A truncated entry holding a SHARED page (refcount 2) drops one
    reference and survives — the same discipline as release_slots."""
    pool = kv_pages.init_page_pool(
        CFG, n_pages=4, page_len=4, max_slots=2, pages_per_seq=2,
    )
    pool, ok = kv_pages.reserve_pages(
        pool, jnp.asarray([0, 1]), jnp.asarray([0, 0]),
        jnp.asarray([True, False]),
    )
    page = int(np.asarray(pool["page_table"])[0, 0])
    pool = kv_pages.ref_pages(pool, jnp.asarray([page, -1]))  # cache ref
    pool = kv_pages.truncate_to(
        pool, jnp.asarray([0, 0]), jnp.asarray([True, False])
    )
    rc = np.asarray(pool["refcount"])
    assert rc[page] == 1  # the cache's reference survives the rollback
    assert not bool(np.asarray(pool["free"])[page])
    assert (np.asarray(pool["page_table"])[0] == -1).all()


# ------------------------------------------------- the drafter


def test_early_exit_drafter_shapes_and_ratio(params):
    dp, dcfg = spec_mod.early_exit_drafter(params, CFG, 1)
    assert dcfg.n_layers == 1 and dcfg.dmodel == CFG.dmodel
    assert jax.tree.leaves(dp["blocks"])[0].shape[0] == 1
    # shared leaves are views of the target's, not copies
    assert dp["embed"] is params["embed"]
    r = spec_mod.flop_ratio(dp, params)
    assert 0.0 < r < 1.0
    # a full-depth "drafter" costs exactly the target
    full, _ = spec_mod.early_exit_drafter(params, CFG, CFG.n_layers)
    assert spec_mod.flop_ratio(full, params) == pytest.approx(1.0)
    with pytest.raises(ValueError, match="draft_layers=0"):
        spec_mod.early_exit_drafter(params, CFG, 0)
    with pytest.raises(ValueError, match="draft_layers=3"):
        spec_mod.early_exit_drafter(params, CFG, 3)
    # the draft_dim knob slices every projection consistently
    dp8, dcfg8 = spec_mod.early_exit_drafter(params, CFG, 1, draft_dim=8)
    assert dcfg8.dmodel == 8 and dcfg8.ffn_dim == 32
    assert dp8["embed"].shape == (CFG.vocab_size, 8)
    assert dp8["blocks"]["wq"].shape == (1, 8, 8)
    assert dp8["blocks"]["w_down"].shape == (1, 32, 8)
    assert dp8["unembed"].shape == (8, CFG.vocab_size)
    assert spec_mod.flop_ratio(dp8, params) < r
    with pytest.raises(ValueError, match="head_dim"):
        spec_mod.early_exit_drafter(params, CFG, 1, draft_dim=6)


def test_spec_refuses_sampling(params):
    with pytest.raises(ValueError, match="greedy-only"):
        make_engine(params, temperature=0.7)


# --------------------------------------- bitwise spec == sequential


@pytest.fixture(scope="module")
def spec_engine_run(params):
    """One drained spec engine over prompts chosen to exercise the
    whole acceptance surface — shared by the bitwise/coverage/pool
    pins so the draft/verify/truncate programs compile once."""
    reqs = [
        ([5, 9, 11, 3], 9),   # crosses the page_len=4 boundary twice
        ([7, 2, 8], 6),
        ([1, 2], 4),
        ([3, 3, 3, 3, 3], 11),  # fills its last page exactly
    ]
    eng = make_engine(params)
    for prompt, max_new in reqs:
        r = eng.make_request(prompt, max_new)
        assert eng.submit(r) is None
        drain(eng)
    return eng, reqs


def test_spec_decode_is_bitwise_dense_across_page_boundaries(
    params, spec_engine_run
):
    """THE tentpole pin: greedy speculative decode — drafts proposed by
    the early-exit drafter, accepted against the verify pass's
    argmaxes, rejections rolled back through truncate_to — emits
    token-for-token the dense oracle's fp32 stream, with draft windows
    straddling page boundaries along the way."""
    eng, reqs = spec_engine_run
    for (prompt, max_new), req in zip(reqs, eng.done):
        assert req.tokens == dense_greedy(params, prompt, max_new), prompt
    assert eng.pool_ok_failures == 0
    # the draft window straddled a page boundary: some round wrote
    # across a page_len multiple (9 generated from prompt 4 must)
    assert eng.generated_tokens == sum(m for _, m in reqs)


def test_spec_round_coverage_and_counters(params, spec_engine_run):
    """The acceptance surface the bitwise pin exercised is not
    vacuous: the deterministic accept histogram covers reject-first
    (a=0), mid-draft rejection (0<a<k), and full acceptance (a=k) —
    and the proposed/accepted/rejected counters reconcile."""
    eng, _reqs = spec_engine_run
    counts = eng.spec_accept_counts
    assert counts.get(0, 0) > 0, counts          # reject-first
    assert any(0 < a < K for a in counts), counts  # mid-draft reject
    assert counts.get(K, 0) > 0, counts          # accept-all + bonus
    m = eng.metrics()
    assert m["acceptance_rate"] > 0
    assert (m["draft_tokens_accepted"] + m["draft_tokens_rejected"]
            == m["spec"]["draft_tokens_proposed"]
            == K * m["spec"]["rounds"])
    assert m["spec"]["enabled"] and m["spec"]["k"] == K
    assert 0.0 < m["spec"]["flop_ratio"] < 1.0
    assert m["config"]["spec_k"] == K


def test_spec_pools_drain_clean(params, spec_engine_run):
    eng, _ = spec_engine_run
    eng.step()  # flush the final releases
    assert int(jnp.sum(~eng.pool["free"])) == 0
    assert int(jnp.sum(~eng.draft_pool["free"])) == 0
    assert_draft_pool_invariants(eng)


def test_reject_first_path_with_a_random_drafter(params):
    """Bitwise equality must hold for ANY drafter — correctness never
    depends on agreement.  A drafter with independent random weights
    agrees ~1/vocab, so nearly every round rejects the FIRST draft
    (the pure-overhead path); the emitted stream must still be the
    dense oracle's, token for token."""
    dcfg = replace(CFG, n_layers=1)
    rand_draft = llama.init_llama_params(jax.random.PRNGKey(99), dcfg)
    eng = make_engine(
        params, draft_params=rand_draft, draft_cfg=dcfg,
    )
    prompt, max_new = [5, 9, 11, 3], 9
    r = eng.make_request(prompt, max_new)
    assert eng.submit(r) is None
    drain(eng)
    assert r.tokens == dense_greedy(params, prompt, max_new)
    counts = eng.spec_accept_counts
    assert counts.get(0, 0) > 0
    m = eng.metrics()
    assert m["acceptance_rate"] < 0.5  # mostly rejected, still correct
    assert eng.pool_ok_failures == 0


def test_eos_inside_draft_stops_and_frees(params):
    """EOS landing INSIDE an accepted draft window completes the
    request at the EOS token (later emissions in the same round are
    discarded) and the flush returns every page of both pools."""
    prompt = [5, 9, 11, 3]
    dense = dense_greedy(params, prompt, 9)
    eos = dense[3]  # 4th generated token — mid-stream, mid-window
    eng = make_engine(params, eos_id=eos)
    req = eng.make_request(prompt, 9)
    eng.submit(req)
    drain(eng)
    assert req.tokens == dense[:4]
    assert req.tokens[-1] == eos
    eng.step()  # flush the release
    assert int(jnp.sum(~eng.pool["free"])) == 0
    assert int(jnp.sum(~eng.draft_pool["free"])) == 0
    assert not any(eng.pool["active"].tolist())


def test_spec_mid_batch_admission_isolated(params):
    """Continuous batching under spec: a request admitted while
    another speculates emits exactly its own dense stream (the shared
    pools' cross-sequence isolation survives draft/verify/rollback)."""
    a_prompt, a_new = [5, 9, 11, 3], 9
    b_prompt, b_new = [7, 2, 8], 6
    eng = make_engine(params)
    ra = eng.make_request(a_prompt, a_new)
    assert eng.submit(ra) is None
    eng.step()
    eng.step()
    assert ra.done_t is None and len(ra.tokens) >= 2
    rb = eng.make_request(b_prompt, b_new)
    assert eng.submit(rb) is None
    eng.step()
    assert rb.admitted_t is not None
    drain(eng)
    assert ra.tokens == dense_greedy(params, a_prompt, a_new)
    assert rb.tokens == dense_greedy(params, b_prompt, b_new)
    assert eng.pool_ok_failures == 0


def test_spec_max_new_one_completes_in_prefill(params):
    """A request done at its FIRST token never reaches a spec round;
    its drafter-pool slot releases with the target's."""
    prompt = [7, 2]
    dense = dense_greedy(params, prompt, 1)
    eng = make_engine(params)
    r = eng.make_request(prompt, 1)
    assert eng.submit(r) is None
    eng.step()
    assert r.tokens == dense and r.done_t is not None
    eng.step()
    assert int(jnp.sum(~eng.draft_pool["free"])) == 0


def test_draft_writes_bounded_at_the_table_edge(params):
    """The draft scan honors the same per-row write limit as verify: a
    request sized to END exactly at the page table's last position
    (prompt + max_new == pages_per_seq * page_len) must never have the
    drafter open a page past the admission bill — an unmasked drafter
    write at the table edge fails the WHOLE batched reserve_pages call,
    dropping the OTHER slot's legitimate page and trash-routing its KV.
    Two such requests run concurrently so the all-or-nothing blast
    radius would be visible."""
    eng = make_engine(params, prefill_batch=2)
    ra = eng.make_request([9, 7, 5, 1], 12)   # 4 + 12 = 16 = table edge
    rb = eng.make_request([2, 4], 14)         # 2 + 14 = 16
    assert eng.submit(ra) is None and eng.submit(rb) is None
    drain(eng)
    assert ra.tokens == dense_greedy(params, [9, 7, 5, 1], 12)
    assert rb.tokens == dense_greedy(params, [2, 4], 14)
    assert eng.pool_ok_failures == 0


def test_spec_admission_covers_the_shareless_drafter_pool(params):
    """The prefix cache discounts matched pages from the TARGET bill,
    but the drafter pool shares nothing — spec-mode admission must
    bill the full worst case, or a tight pool with repeated prompts
    admits a request whose drafter-side reserve exhausts (observed as
    pool_ok_failures with silently corrupted proposals)."""
    eng = make_engine(
        params, n_pages=7, max_slots=2, prefill_batch=2,
        prefix_cache=True,
    )
    prompt = [11, 12, 13, 14, 15, 16, 17, 18]  # 2 full pages, cacheable
    for _ in range(2):  # identical prompt: the 2nd is a radix hit
        r = eng.make_request(prompt, 8)  # 8 + 8 = 16 -> 4 pages full
        assert eng.submit(r) is None
        drain(eng)
        assert r.tokens == dense_greedy(params, prompt, 8)
    assert eng.pool_ok_failures == 0
    assert eng.prefix.hits >= 1  # the discountless bill kept adoption
    assert_draft_pool_invariants(eng)


# ------------------------------------------ pool-invariant sweep


def test_pool_invariants_under_spec_interleavings(params):
    """The PR-13 satellite sweep: seeded shared-prefix traffic with
    per-request length jitter against TIGHT pools, speculation AND the
    radix prefix cache on — draft / verify / reject / COW-adopt /
    release / evict all interleave — holds the refcount invariant on
    BOTH pools at every scheduler step, and a full teardown frees
    every page (no leak, no double-free)."""
    from test_serve_prefix import assert_pool_invariants

    for seed in (0, 1):
        rng = np.random.RandomState(seed)
        eng = make_engine(
            params, n_pages=8, max_slots=2, prefill_batch=2,
            prefix_cache=True,
        )
        prefixes = [
            [int(x) for x in rng.randint(1, CFG.vocab_size, size=6)]
            for _ in range(3)
        ]
        for _ in range(40):
            if rng.uniform() < 0.6:
                kpfx = int(rng.randint(len(prefixes)))
                suffix = [int(x) for x in rng.randint(
                    1, CFG.vocab_size, size=2
                )]
                eng.submit(eng.make_request(
                    prefixes[kpfx] + suffix, int(rng.randint(1, 5))
                ))
            eng.step()
            assert_pool_invariants(eng)
            assert_draft_pool_invariants(eng)
        drain(eng)
        eng.step()
        assert_pool_invariants(eng)
        assert_draft_pool_invariants(eng)
        # teardown: evict the cache; both pools must drain to empty
        evicted = eng.prefix.evict(eng.n_pages, set())
        if evicted:
            pages = np.full((eng.n_pages,), -1, np.int32)
            pages[: len(evicted)] = evicted
            eng.pool = kv_pages.unref_pages(eng.pool, jnp.asarray(pages))
        assert bool(np.asarray(jax.device_get(eng.pool["free"])).all())
        assert bool(
            np.asarray(jax.device_get(eng.draft_pool["free"])).all()
        ), seed
        assert eng.pool_ok_failures == 0, seed


# ------------------------------------------------- the deterministic win


def test_spec_ab_strict_win_on_the_deep_config():
    """The perf claim the CI gate holds: on the tiny-deep smoke config
    (6-layer target, 1-layer early-exit drafter — FLOP ratio ~0.20)
    the spec arm strictly beats sequential decode on the virtual clock
    at equal admission budget, with bitwise-matching streams; and
    ``serve_report.check_spec_ab`` passes the resulting cell both in
    ledger-row and serve.json shape."""
    from ddl25spring_tpu.serve import driver
    from tools import serve_report

    deep_params = llama.init_llama_params(jax.random.PRNGKey(0), DEEP_CFG)
    knobs = dict(
        page_len=4, n_pages=16, max_slots=2, prefill_batch=2,
        max_prompt_len=8, max_queue=64, token_budget=None, eos_id=None,
        prefix_cache=False, spec_k=K, draft_layers=1,
    )
    spec = TrafficSpec(
        seed=0, duration_s=2.0, rate_rps=6.0, profile="shared",
        vocab_size=DEEP_CFG.vocab_size, max_new_jitter=2,
    )
    trace = synth_trace(spec)
    assert len(trace) >= 4
    sab = driver.spec_ab_compare(deep_params, DEEP_CFG, trace, knobs)
    assert sab["advantage_tokens"] > 0
    assert (sab["spec"]["tokens_per_sec_per_chip"]
            > sab["nospec"]["tokens_per_sec_per_chip"])
    assert sab["spec"]["drain_wall_s"] < sab["nospec"]["drain_wall_s"]
    assert sab["tokens_match"] is True
    assert sab["compared_requests"] > 0
    assert sab["spec"]["acceptance_rate"] > 0
    # the gate passes the honest cell in both shapes
    row = {"key": {"spec": True},
           "spec_ab": driver._spec_ab_cell(sab)}
    assert serve_report.check_spec_ab([row]) == []
    doc = {"key": {"spec": True}, "spec_ab": sab}
    assert serve_report.check_spec_ab([doc]) == []


# --------------------------------------------------- report gates


def test_check_spec_ab_fails_on_defects():
    from tools import serve_report

    assert serve_report.check_spec_ab(
        [{"key": {"spec": True}}]
    ) != []  # no cell at all
    bad = {
        "key": {"spec": True},
        "spec_ab": {
            "budget_s": 1.0,
            "spec_tokens_at_budget": 10,
            "nospec_tokens_at_budget": 12,
            "advantage_tokens": -2,
            "tokens_match": False,
            "compared_requests": 3,
            "spec_tokens_per_sec_per_chip": 5.0,
            "nospec_tokens_per_sec_per_chip": 6.0,
            "acceptance_rate": 0.0,
            "draft_tokens_accepted": 0,
        },
    }
    fails = serve_report.check_spec_ab([bad])
    assert len(fails) == 4  # accepted, tps, budget, match
    assert any("accepted" in f for f in fails)
    # tokens_match=True over ZERO compared requests is vacuous — the
    # same guard the prefix gate grew in PR 11
    vacuous = {
        "key": {"spec": True},
        "spec_ab": {
            **bad["spec_ab"],
            "advantage_tokens": 2,
            "draft_tokens_accepted": 9,
            "acceptance_rate": 0.5,
            "spec_tokens_per_sec_per_chip": 7.0,
            "tokens_match": True,
            "compared_requests": 0,
        },
    }
    fails = serve_report.check_spec_ab([vacuous])
    assert len(fails) == 1 and "compared request" in fails[0]


def test_check_group_gates_acceptance_rate_on_spec_runs():
    from tools import serve_report

    def row(acc):
        return {
            "key": {"spec": True, "profile": "shared"},
            "tokens_per_sec_per_chip": 10.0,
            "ttft_s_p95": 0.1,
            "prefix_hit_rate": 0.8,
            "acceptance_rate": acc,
        }

    assert serve_report.check_group([row(0.6), row(0.5)]) == []
    fails = serve_report.check_group([row(0.6), row(0.6), row(0.1)])
    assert any("acceptance_rate" in f for f in fails)
    # NOT gated off spec runs (the key carries no spec marker)
    cold = [
        {k: v for k, v in r.items() if k != "key"} | {"key": {}}
        for r in (row(0.6), row(0.6), row(0.0))
    ]
    assert serve_report.check_group(cold) == []


def test_ledger_and_cells_carry_the_spec_contract():
    """ledger_record / serve_cell / _spec_ab_cell thread the spec
    counters and the A/B verdict end to end (pure dict plumbing — no
    engine, no compile)."""
    from ddl25spring_tpu.serve import driver

    record = {
        "record": "serve", "ts": 1.0, "git_sha": "abc", "host": "h",
        "key": {"spec": True, "spec_k": K, "draft_layers": 1},
        "requests": 3,
        "ramp": {
            "tokens_per_sec_per_chip": 10.0,
            "acceptance_rate": 0.6,
            "draft_tokens_accepted": 12,
            "draft_tokens_rejected": 8,
            "spec": {"enabled": True, "k": K, "rounds": 7},
        },
        "spec_ab": {
            "budget_s": 2.0,
            "spec_tokens_at_budget": 30,
            "nospec_tokens_at_budget": 25,
            "advantage_tokens": 5,
            "advantage_frac": 0.2,
            "tokens_match": True,
            "compared_requests": 3,
            "spec": {"tokens_per_sec_per_chip": 12.0,
                     "acceptance_rate": 0.6,
                     "draft_tokens_accepted": 12,
                     "draft_tokens_rejected": 8},
            "nospec": {"tokens_per_sec_per_chip": 10.0},
        },
    }
    row = driver.ledger_record(record)
    assert row["acceptance_rate"] == 0.6
    assert row["draft_tokens_accepted"] == 12
    assert row["draft_tokens_rejected"] == 8
    assert row["spec_ab"]["advantage_tokens"] == 5
    assert row["spec_ab"]["spec_tokens_per_sec_per_chip"] == 12.0
    assert row["spec_ab"]["acceptance_rate"] == 0.6
    cell = driver.serve_cell(record)
    assert cell["acceptance_rate"] == 0.6
    assert cell["spec"]["enabled"] is True
    assert cell["spec_ab"]["tokens_match"] is True
    assert cell["spec_ab"]["compared_requests"] == 3


# -------------------------------------------------------- traffic


def test_shared_profile_max_new_jitter_is_seeded():
    base = TrafficSpec(
        seed=5, duration_s=3.0, rate_rps=8.0, profile="shared",
    )
    jit = TrafficSpec(
        seed=5, duration_s=3.0, rate_rps=8.0, profile="shared",
        max_new_jitter=2,
    )
    t0 = synth_trace(base)
    t1 = synth_trace(jit)
    assert len(t0) == len(t1) > 4
    # jitter=0 (the field default) replays the exact pre-knob stream
    assert synth_trace(base) == t0
    # the knob actually varies decode lengths, within +-jitter, >= 1
    assert {r["max_new"] for r in t1} != {r["max_new"] for r in t0}
    for a, b in zip(t0, t1):
        assert a["prompt"] == b["prompt"] and a["t"] == b["t"]
        assert abs(a["max_new"] - b["max_new"]) <= 2
        assert b["max_new"] >= 1
    # restart-deterministic like the rest of the profile
    assert synth_trace(jit) == t1


# ------------------------------------------------- compile signatures


@pytest.mark.parametrize("name,ar_count", [
    # draft: 2 psums/block x 1 drafter layer x (k+1 = 3) scan steps
    ("serve-draft", 2 * 1 * 3),
    # verify: 2 psums/block x 2 target layers x (k+1) positions — the
    # counts differing by exactly the depth ratio is the compile-time
    # half of the drafter's FLOP-ratio pricing
    ("serve-verify", 2 * 2 * 3),
])
def test_spec_signature_pins(strategy_report, name, ar_count):
    """Speculative TP serving traffic is the row-parallel all-reduce
    ONLY — pinned through the same registry gates as every strategy
    (lower-once session cache shared with graft-lint/graft-sched)."""
    r = strategy_report(name)
    assert r["signature_violations"] == []
    assert [f for f in r["findings"] if not f["waived"]] == []
    totals = r["collectives"]["totals"]
    assert set(totals) == {"all-reduce"}
    assert totals["all-reduce"]["count"] == ar_count
    assert r["sched"]["hazards"] == []
    assert r["lowered"] in ("draft_step", "verify_step")
    assert r["meta"]["kv_sharded_dim"] == 3
