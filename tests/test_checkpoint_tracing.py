"""Checkpoint/resume and tracing subsystems.

The key test is kill-and-resume equivalence: a run that checkpoints, "dies",
restores, and continues must land bitwise on the state of a run that never
died — the TPU-world recovery story the reference lacks (SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.models.mnist_cnn import MnistCnn
from ddl25spring_tpu.ops.losses import nll_loss
from ddl25spring_tpu.parallel.dp import make_dp_train_step
from ddl25spring_tpu.utils.checkpoint import Checkpointer
from ddl25spring_tpu.utils.mesh import make_mesh, replicated
from ddl25spring_tpu.utils.tracing import StepTimer, annotate


@pytest.fixture()
def train_setup():
    model = MnistCnn()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 10)
    params = model.init(jax.random.PRNGKey(2), x[:1])["params"]

    def loss_fn(p, batch, key):
        out = model.apply(
            {"params": p}, batch[0], train=True, rngs={"dropout": key}
        )
        return nll_loss(out, batch[1])

    tx = optax.adam(1e-3)
    return loss_fn, tx, params, (x, y)


def test_kill_and_resume_equivalence(tmp_path, train_setup, devices8):
    loss_fn, tx, params, batch = train_setup
    mesh = make_mesh(devices8[:2], data=2)
    step = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
    key = jax.random.PRNGKey(3)

    # uninterrupted run: 6 steps
    p_ref, o_ref = params, tx.init(params)
    for _ in range(6):
        p_ref, o_ref, _ = step(p_ref, o_ref, batch, key)

    # interrupted run: 3 steps, checkpoint, "crash", restore, 3 more
    ckpt = Checkpointer(tmp_path / "ckpt")
    p, o = params, tx.init(params)
    for _ in range(3):
        p, o, _ = step(p, o, batch, key)
    ckpt.save(2, {"params": p, "opt_state": o})
    ckpt.close()  # saves are async; the barrier stands in for process exit

    # the template pins device placement: restored slices land mesh-placed
    # (here replicated over the data axis, as the DP step expects)
    init_state = jax.device_put(
        {"params": params, "opt_state": tx.init(params)}, replicated(mesh)
    )
    restored, next_step = Checkpointer(tmp_path / "ckpt").restore_or_init(
        init_state
    )
    assert next_step == 3
    p, o = restored["params"], restored["opt_state"]
    for _ in range(3):
        p, o, _ = step(p, o, batch, key)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        p,
        p_ref,
    )


def test_zero_sharded_kill_and_resume(tmp_path, train_setup, devices8):
    """Kill-and-resume with ZeRO/FSDP-SHARDED state: the checkpoint holds
    [n, k] shard layouts, and the restore template (freshly re-sharded
    init state) pins each restored leaf back onto its NamedSharding(P
    ('data')) placement — the production resume path for sharded DP."""
    from ddl25spring_tpu.parallel.zero import (
        make_zero_dp_train_step, zero_shard_params,
    )

    loss_fn, tx, params, batch = train_setup
    mesh = make_mesh(devices8[:2], data=2)
    step = make_zero_dp_train_step(
        loss_fn, tx, mesh, params, per_shard_rng=False
    )
    key = jax.random.PRNGKey(4)

    # uninterrupted: 4 steps
    s_ref = zero_shard_params(params, mesh)
    o_ref = tx.init(s_ref)
    for _ in range(4):
        s_ref, o_ref, _ = step(s_ref, o_ref, batch, key)

    # interrupted: 2 steps, save, crash, restore via fresh template, 2 more
    ckpt = Checkpointer(tmp_path / "zckpt")
    s = zero_shard_params(params, mesh)
    o = tx.init(s)
    for _ in range(2):
        s, o, _ = step(s, o, batch, key)
    ckpt.save(1, {"shards": s, "opt_state": o})
    ckpt.close()

    from ddl25spring_tpu.utils.checkpoint import with_mesh_placement

    template = {"shards": zero_shard_params(params, mesh)}
    template["opt_state"] = tx.init(template["shards"])
    # opt-state scalars (Adam count) are born single-device; the template
    # must replicate them over the mesh or the resumed jit rejects the
    # mixed placement — the exact job of with_mesh_placement
    template = with_mesh_placement(template, mesh)
    restored, next_step = Checkpointer(tmp_path / "zckpt").restore_or_init(
        template
    )
    assert next_step == 2
    s2, o2 = restored["shards"], restored["opt_state"]
    # restored leaves carry the sharded placement, not single-device
    leaf = jax.tree.leaves(s2)[0]
    assert leaf.sharding.spec == jax.tree.leaves(template["shards"])[0].sharding.spec
    for _ in range(2):
        s2, o2, _ = step(s2, o2, batch, key)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        s2,
        s_ref,
    )


def test_restore_or_init_fresh_start(tmp_path, train_setup):
    _, tx, params, _ = train_setup
    ckpt = Checkpointer(tmp_path / "empty")
    state, next_step = ckpt.restore_or_init({"params": params})
    assert next_step == 0
    assert state["params"] is params


def test_max_to_keep_prunes(tmp_path):
    ckpt = Checkpointer(tmp_path / "ckpt", max_to_keep=2)
    state = {"w": jnp.arange(4.0)}
    for s in range(4):
        ckpt.save(s, state)
    assert ckpt.steps() == [2, 3]  # 0 and 1 pruned
    assert ckpt.latest_step() == 3
    restored = ckpt.restore(3)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(4.0))


def test_step_timer_discards_warmup():
    t = StepTimer(warmup=1)
    x = jnp.ones((8, 8))
    for _ in range(4):
        with annotate("matmul"):
            x = x @ x.T
        t.tick(x)
    assert len(t.times) == 2  # 3 intervals, 1 warmup discarded
    assert t.steps_per_sec() > 0
