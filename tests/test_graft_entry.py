"""Driver-contract tests for __graft_entry__.py."""

import jax

import __graft_entry__


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    loss = float(jax.jit(fn)(*args))
    assert loss == loss and loss > 0  # finite, positive


def test_dryrun_multichip_8():
    __graft_entry__.dryrun_multichip(8)
