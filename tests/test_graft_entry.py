"""Driver-contract tests for __graft_entry__.py."""

import os
import subprocess
import sys

import jax
import pytest

import __graft_entry__
from ddl25spring_tpu.utils.compat import HAS_VMA

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    loss = float(jax.jit(fn)(*args))
    assert loss == loss and loss > 0  # finite, positive


@pytest.mark.skipif(
    not HAS_VMA,
    reason="the dryrun's pipeline workloads need VMA-typed shard_map "
    "(lax.pcast) for their grad paths; this jax predates it",
)
def test_dryrun_multichip_fresh_subprocess():
    """Simulate the driver: run dryrun_multichip in a fresh interpreter
    WITHOUT conftest's platform forcing — dryrun_multichip itself must
    select the CPU platform (MULTICHIP_r01 failed exactly here).  This is
    a strict superset of an in-process dryrun call, which it replaces to
    keep the suite from paying the ~3-minute dryrun twice."""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    # 25 min head-room: the dryrun is ~17 workloads and takes ~13 min on a
    # cold compilation cache on this single-core image (minutes when the
    # persistent cache dryrun_multichip enables is warm)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py")],
        capture_output=True, text=True, timeout=1500, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"dryrun_multichip subprocess failed:\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "dryrun_multichip DPxPP OK" in proc.stdout
