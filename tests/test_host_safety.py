"""graft-race (``analysis/host_safety.py`` + ``analysis/host_sanitizer.py``
+ ``tools/graft_lint.py --host-safety``): the host-side concurrency &
signal-safety verifier.

The load-bearing pins:

- **one positive + one near-miss per rule S201–S205** — each synthetic
  source distills the real hazard the rule was built from (PR-5's
  signal-path self-deadlock, PR-6's shutdown wedge, PR-10/17's mirror
  drift) and its minimally-fixed twin stays quiet.
- **the static finding fires live** — a seeded S204 drift (device
  refcount bumped with no host billing) trips the runtime sanitizer's
  mirror assertion through the engine's own ``step()`` hook, and the
  lock-order proxy raises on a would-be self-deadlock / inversion
  *before* blocking.
- **zero cost when off** — with ``DDL25_SANITIZE=0`` token streams are
  bitwise identical and the decode tick lowers to byte-identical HLO;
  the sanitizer is host-side observation only.
- **the repo's own host surface is clean** — ``lint_repo`` over
  obs/ft/serve/bench/tools returns no findings (the PR-19 dogfood
  fixes hold), and the inventory sees the declared locks and entries.
"""

from __future__ import annotations

import textwrap
import threading
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from ddl25spring_tpu.analysis import host_safety, host_sanitizer
from ddl25spring_tpu.analysis.host_sanitizer import (
    OrderCheckedLock,
    SanitizerError,
    wrap_lock,
)
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.serve import kv_pages
from ddl25spring_tpu.serve.engine import ServeEngine
from ddl25spring_tpu.utils.config import LlamaConfig

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _clean_sanitizer():
    host_sanitizer.reset()
    yield
    host_sanitizer.reset()


def make_engine(params, **kw):
    # the test_serve smoke geometry — every compiled program rides the
    # session-wide program cache shared with tests/test_serve.py
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


def lint(src: str, relpath: str = "ddl25spring_tpu/obs/fake.py",
         mirrors=host_safety.MIRRORS):
    return host_safety.lint_source(
        textwrap.dedent(src), relpath, mirrors=mirrors
    )


# --------------------------------------------- S201: cross-context write


S201_BAD = """
    import threading

    class Watch:
        def __init__(self):
            self.fired = False
            self._t = threading.Thread(target=self._monitor, daemon=True)

        def beat(self):
            self.fired = False

        def _monitor(self):
            self.fired = True
"""


def test_s201_unlocked_cross_context_write_fires():
    findings = lint(S201_BAD)
    assert [f.rule for f in findings] == ["S201"]
    (f,) = findings
    assert f.op == "Watch.fired"
    assert "thread:Watch._monitor" in f.message and "main" in f.message


def test_s201_near_miss_shared_lock_stays_quiet():
    src = """
        import threading

        class Watch:
            def __init__(self):
                self.fired = False
                self._lock = threading.Lock()
                self._t = threading.Thread(
                    target=self._monitor, daemon=True)

            def beat(self):
                with self._lock:
                    self.fired = False

            def _monitor(self):
                with self._lock:
                    self.fired = True
    """
    assert lint(src) == []


def test_s201_init_writes_are_exempt():
    # __init__ publishes before the thread starts — construction
    # happens-before; only the thread writes after that
    src = """
        import threading

        class Watch:
            def __init__(self):
                self.fired = False
                self._t = threading.Thread(
                    target=self._monitor, daemon=True)

            def _monitor(self):
                self.fired = True
    """
    assert lint(src) == []


# ------------------------------------------- S202: lock-order inversion


S202_BAD = """
    import threading

    class Pair:
        def __init__(self):
            self._lock_a = threading.Lock()
            self._lock_b = threading.Lock()

        def forward(self):
            with self._lock_a:
                with self._lock_b:
                    self.n = 1

        def backward(self):
            with self._lock_b:
                with self._lock_a:
                    self.n = 2
"""


def test_s202_opposite_nesting_orders_fire():
    findings = lint(S202_BAD)
    assert [f.rule for f in findings] == ["S202"]
    (f,) = findings
    assert "Pair._lock_a" in f.op and "Pair._lock_b" in f.op


def test_s202_near_miss_consistent_order_stays_quiet():
    src = """
        import threading

        class Pair:
            def __init__(self):
                self._lock_a = threading.Lock()
                self._lock_b = threading.Lock()

            def forward(self):
                with self._lock_a:
                    with self._lock_b:
                        self.n = 1

            def backward(self):
                with self._lock_a:
                    with self._lock_b:
                        self.n = 2
    """
    assert lint(src) == []


# ----------------------------------- S203: signal-path non-reentrancy


S203_BAD = """
    import signal
    import threading

    class Reporter:
        def __init__(self):
            self._lock = threading.Lock()

        def install(self):
            signal.signal(signal.SIGTERM, self._on_term)

        def _on_term(self, signum, frame):
            self.dump()

        def dump(self):
            with self._lock:
                self.count = 1
"""


def test_s203_nonreentrant_lock_on_signal_path_fires():
    findings = lint(S203_BAD)
    assert [f.rule for f in findings] == ["S203"]
    (f,) = findings
    assert f.op == "Reporter.dump"
    assert "signal:Reporter._on_term" in f.message


def test_s203_near_miss_rlock_stays_quiet():
    # the PR-5 fix verbatim: the lock the handler path re-enters is
    # declared reentrant
    assert lint(S203_BAD.replace("threading.Lock()",
                                 "threading.RLock()")) == []


# --------------------------------------- S204: host<->device mirror drift


_S204_MIRRORS = (
    {
        "path": "ddl25spring_tpu/serve/fake_engine.py",
        "cls": "FakeEngine",
        "device_state": ("pool",),
        "device_ops": ("_ref",),
        "host_mirrors": ("_reserved",),
    },
)

S204_BAD = """
    class FakeEngine:
        def adopt(self, pages):
            self.pool = _ref(self.pool, pages)
"""


def test_s204_unmirrored_device_mutation_fires():
    findings = lint(S204_BAD, "ddl25spring_tpu/serve/fake_engine.py",
                    mirrors=_S204_MIRRORS)
    assert [f.rule for f in findings] == ["S204"]
    (f,) = findings
    assert f.op == "FakeEngine.adopt"
    assert "self.pool" in f.message and "_ref" in f.message


def test_s204_near_miss_same_method_mirror_write_stays_quiet():
    src = """
        class FakeEngine:
            def adopt(self, pages):
                self.pool = _ref(self.pool, pages)
                self._reserved += len(pages)
    """
    assert lint(src, "ddl25spring_tpu/serve/fake_engine.py",
                mirrors=_S204_MIRRORS) == []


# ------------------------------- S205: unbounded blocking on shutdown


S205_BAD = """
    import atexit

    class Saver:
        def install(self):
            atexit.register(self.close)

        def close(self):
            self.worker.join()
"""


def test_s205_unbounded_join_on_shutdown_path_fires():
    findings = lint(S205_BAD)
    assert [f.rule for f in findings] == ["S205"]
    (f,) = findings
    assert f.severity == "warn" and f.op == "Saver.close"
    assert "atexit:Saver.close" in f.message


def test_s205_near_miss_bounded_join_stays_quiet():
    assert lint(S205_BAD.replace(".join()", ".join(timeout=2.0)")) == []


# ----------------------------------- the repo's own host surface (gate)


def test_repo_host_surface_lints_clean():
    """The PR-19 dogfood state, pinned: after the watchdog/autosave/
    engine fixes the whole host scope passes with zero findings and
    zero waivers, and the inventory sees the machinery we know exists."""
    root = Path(__file__).resolve().parents[1]
    inv, findings = host_safety.lint_repo(str(root))
    assert findings == [], [
        f"{f.rule} {f.source} {f.op}" for f in findings
    ]
    s = inv.summary()
    assert s["files"] >= 30 and s["functions"] >= 300
    locks = s["locks"]
    assert locks[
        "ddl25spring_tpu/obs/recorder.py::FlightRecorder._lock"
    ] == "RLock"  # the PR-5 signal-path fix, still reentrant
    assert locks[
        "ddl25spring_tpu/obs/watchdog.py::StallWatchdog._state_lock"
    ] == "Lock"  # this PR's S201 fix: never held across dump
    assert locks[
        "ddl25spring_tpu/ft/autosave.py::AutoSaver._state_lock"
    ] == "RLock"  # this PR's S201 fix, reentrant because signal-reachable
    entries = s["entry_points"]
    assert entries.get("thread", 0) >= 1
    assert entries.get("signal", 0) >= 1
    assert entries.get("atexit", 0) >= 1
    assert s["mirror_contracts"] == 1


# ------------------------------------------ runtime: lock-order proxy


def test_sanitizer_self_deadlock_raises_before_blocking():
    lk = OrderCheckedLock("t.lock", threading.Lock())
    with lk:
        with pytest.raises(SanitizerError, match="self-deadlock"):
            lk.acquire()  # a plain Lock would hang here forever
    assert [v["kind"] for v in host_sanitizer.violations()] == [
        "self_deadlock"
    ]
    with lk:  # released cleanly; usable after the report
        pass


def test_sanitizer_rlock_reentry_is_fine():
    rl = OrderCheckedLock("t.rlock", threading.RLock())
    with rl:
        with rl:
            pass
    assert host_sanitizer.violations() == []


def test_sanitizer_lock_order_inversion_raises():
    a = OrderCheckedLock("t.a", threading.Lock())
    b = OrderCheckedLock("t.b", threading.Lock())
    with a:
        with b:  # records the edge a -> b
            pass
    with b:
        with pytest.raises(SanitizerError, match="inversion"):
            a.acquire()  # b -> a inverts the recorded order
    v = host_sanitizer.violations()
    assert [x["kind"] for x in v] == ["lock_order_inversion"]
    assert v[0]["held"] == "t.b" and v[0]["acquiring"] == "t.a"


def test_wrap_lock_resolves_flag_at_construction(monkeypatch):
    raw = threading.Lock()
    monkeypatch.setenv("DDL25_SANITIZE", "0")
    assert wrap_lock("t.x", raw) is raw
    monkeypatch.setenv("DDL25_SANITIZE", "1")
    wrapped = wrap_lock("t.x", raw)
    assert isinstance(wrapped, OrderCheckedLock)
    assert wrapped._inner is raw


# ----------------------------- runtime: the S204 mirror assertion, live


def test_sanitized_engine_drains_clean_then_catches_seeded_drift(
    params, monkeypatch
):
    """The dynamic half of S204: a real serve drain passes the mirror
    check at every step boundary, then a seeded drift — one device
    refcount bumped with no host billing, exactly the class the static
    rule flags — trips ``step()``'s own assertion."""
    import numpy as np

    monkeypatch.setenv("DDL25_SANITIZE", "1")
    eng = make_engine(params)
    assert eng._sanitize is True
    assert eng.submit(eng.make_request([5, 9, 11, 3], 4)) is None
    drain(eng)
    assert host_sanitizer.violations() == []

    free = np.asarray(jax.device_get(eng.pool["free"])).astype(bool)
    pid = int(np.argmax(free))
    assert free[pid], "no free page to corrupt"
    eng.pool = kv_pages.ref_pages(
        eng.pool, jnp.asarray([pid], jnp.int32)
    )
    with pytest.raises(SanitizerError, match="mirror drift"):
        eng.step()
    assert host_sanitizer.violations()[-1]["kind"] == "mirror_drift"


# --------------------------------------------------- zero cost when off


def test_tokens_bitwise_identical_with_sanitizer_toggled(
    params, monkeypatch
):
    """DDL25_SANITIZE on/off leaves token streams and the virtual clock
    bitwise unchanged — the mirror check observes, never steers."""

    def run(flag: str):
        monkeypatch.setenv("DDL25_SANITIZE", flag)
        host_sanitizer.reset()
        eng = make_engine(params, prefill_batch=2)
        reqs = [
            eng.make_request([5 + i, 9, 11, 3], 6) for i in range(3)
        ]
        for r in reqs:
            assert eng.submit(r) is None
        drain(eng)
        return [r.tokens for r in reqs], eng.now(), eng._vtime

    off_tokens, off_now, off_vt = run("0")
    on_tokens, on_now, on_vt = run("1")
    assert on_tokens == off_tokens
    assert on_now == off_now and on_vt == off_vt


def test_decode_tick_hlo_identical_with_sanitizer_toggled(
    params, monkeypatch
):
    """The sanitizer never enters a compiled program: the decode tick
    lowers to byte-identical HLO with the flag on or off."""
    from ddl25spring_tpu.serve.engine import make_decode_tick

    pool = kv_pages.init_page_pool(
        CFG, n_pages=16, page_len=4, max_slots=2, pages_per_seq=4,
    )
    args = (
        params, pool, jnp.zeros((2,), jnp.int32), jax.random.PRNGKey(0),
    )

    def lower(flag: str):
        monkeypatch.setenv("DDL25_SANITIZE", flag)
        tick = make_decode_tick(CFG, temperature=0.0, sentinel=False)
        return jax.jit(tick).lower(*args).as_text()

    assert lower("1") == lower("0")
