"""Mesh construction, including the multi-host hybrid builder's
single-process fallback."""

import jax
import pytest

from ddl25spring_tpu.utils.compat import HAS_VMA
from ddl25spring_tpu.utils.mesh import (
    make_hybrid_mesh,
    make_mesh,
    mesh_axis_sizes,
)


def test_make_mesh_infer_axis(devices8):
    mesh = make_mesh(devices8, data=-1, stage=2)
    assert mesh_axis_sizes(mesh) == {"data": 4, "stage": 2}


def test_make_mesh_too_many_devices_raises(devices8):
    with pytest.raises(ValueError):
        make_mesh(devices8[:2], data=4)


def test_hybrid_mesh_single_process_fallback(devices8):
    # one process (this test environment): DCN axes collapse into a flat
    # mesh with the same axis names/sizes, so code written for the hybrid
    # topology runs unchanged on a single host
    assert jax.process_count() == 1
    mesh = make_hybrid_mesh({"data": 2}, stage=2, model=2)
    assert mesh_axis_sizes(mesh) == {"data": 2, "stage": 2, "model": 2}
    assert tuple(mesh.axis_names) == ("data", "stage", "model")


def test_hybrid_mesh_forced_slices_layout(devices8):
    """force_slices simulates 2 slices of 4: the dcn axis must be
    OUTERMOST (each dcn index owns one contiguous slice block), so
    cross-slice collectives only ever ride the dcn axis."""
    mesh = make_hybrid_mesh({"data": 2}, force_slices=2, stage=4)
    assert mesh_axis_sizes(mesh) == {"data": 2, "stage": 4}
    devs = jax.devices()
    # row i of the mesh grid == simulated slice i (contiguous ids)
    for i in range(2):
        assert list(mesh.devices[i]) == devs[i * 4 : (i + 1) * 4]
    # partial ici footprint stays within its slice
    mesh_p = make_hybrid_mesh({"data": 2}, force_slices=2, stage=2)
    assert list(mesh_p.devices[1]) == devs[4:6]

    with pytest.raises(ValueError, match="simulated slices"):
        make_hybrid_mesh({"data": 3}, force_slices=3)


@pytest.mark.skipif(
    not HAS_VMA,
    reason="pipeline grad path needs VMA-typed shard_map (lax.pcast); "
    "this jax's experimental shard_map mis-transposes the schedule",
)
def test_hybrid_mesh_dp_over_dcn_pp_over_ici_trains(devices8):
    """One DP-over-DCN x PP-over-ICI train step on the simulated 2-slice
    mesh (VERDICT r3 #8): the flagship topology laid out so the gradient
    pmean is the only cross-slice collective while the per-tick ppermute
    stays inside a slice."""
    import jax.numpy as jnp
    import optax

    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_staged_params,
    )
    from ddl25spring_tpu.utils.config import LlamaConfig

    mesh = make_hybrid_mesh({"data": 2}, force_slices=2, stage=4)
    cfg = LlamaConfig(
        vocab_size=64, dmodel=32, num_heads=2, n_layers=4, ctx_size=16,
        dtype="float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    staged = shard_staged_params(
        llama.split_blocks_for_stages(params, 4), mesh
    )
    tx = optax.adam(1e-3)
    step = make_pipeline_train_step(
        cfg, tx, mesh, num_microbatches=2, data_axis="data"
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    _, _, loss = step(staged, tx.init(staged), tokens)
    assert float(loss) > 0 and jnp.isfinite(loss)
