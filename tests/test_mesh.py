"""Mesh construction, including the multi-host hybrid builder's
single-process fallback."""

import jax
import pytest

from ddl25spring_tpu.utils.mesh import (
    make_hybrid_mesh,
    make_mesh,
    mesh_axis_sizes,
)


def test_make_mesh_infer_axis(devices8):
    mesh = make_mesh(devices8, data=-1, stage=2)
    assert mesh_axis_sizes(mesh) == {"data": 4, "stage": 2}


def test_make_mesh_too_many_devices_raises(devices8):
    with pytest.raises(ValueError):
        make_mesh(devices8[:2], data=4)


def test_hybrid_mesh_single_process_fallback(devices8):
    # one process (this test environment): DCN axes collapse into a flat
    # mesh with the same axis names/sizes, so code written for the hybrid
    # topology runs unchanged on a single host
    assert jax.process_count() == 1
    mesh = make_hybrid_mesh({"data": 2}, stage=2, model=2)
    assert mesh_axis_sizes(mesh) == {"data": 2, "stage": 2, "model": 2}
    assert tuple(mesh.axis_names) == ("data", "stage", "model")
