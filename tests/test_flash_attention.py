"""Flash-attention kernel correctness vs the dense XLA path.

Runs the SAME Pallas kernels in interpreter mode on CPU (SURVEY §4: CPU
simulation is this repo's fake-cluster analogue) and checks outputs AND
gradients against ``llama.causal_attention``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops.flash_attention import flash_attention
from ddl25spring_tpu.utils.config import LlamaConfig


def dense(q, k, v):
    return llama.causal_attention(q, k, v, jnp.float32)


def test_choose_block():
    from ddl25spring_tpu.ops.flash_attention import _choose_block

    assert _choose_block(256, 128) == 128
    assert _choose_block(64, 128) == 64
    assert _choose_block(192, 128) == 96   # divides 192, multiple of 8
    assert _choose_block(100, 128) == 100  # fallback: whole axis
    for L in (96, 100, 192, 256, 384):
        b = _choose_block(L, 128)
        assert L % b == 0 and (b % 8 == 0 or b == L)


@pytest.mark.parametrize("L,block", [(128, 128), (256, 128), (256, 64), (192, 128)])
def test_flash_matches_dense(L, block):
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (2, L, 3, 32)  # [B, L, H, hd]
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    out = flash_attention(q, k, v, block_q=block, block_k=block, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense(q, k, v)), atol=2e-5
    )


@pytest.mark.parametrize("bq,bk", [(64, 128), (128, 64)])
def test_flash_unequal_blocks(bq, bk):
    """bq != bk exercises the diagonal-crossing live/finalize conditions of
    the 3-D-grid kernels (j_last = ((i+1)bq-1)//bk) in both directions."""
    key = jax.random.PRNGKey(2)
    kq, kk, kv, kt = jax.random.split(key, 4)
    shape = (1, 256, 2, 32)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    t = jax.random.normal(kt, shape, jnp.float32)

    out = flash_attention(q, k, v, block_q=bq, block_k=bk, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense(q, k, v)), atol=2e-5
    )

    def f_flash(q, k, v):
        return (flash_attention(
            q, k, v, block_q=bq, block_k=bk, interpret=True) * t).sum()

    def f_dense(q, k, v):
        return (dense(q, k, v) * t).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_flash_non_causal():
    """causal=False takes the other branch of every live/j_last condition."""
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (2, 128, 2, 32)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    out = flash_attention(
        q, k, v, causal=False, block_q=64, block_k=64, interpret=True
    )
    # dense non-causal reference
    B, L, H, hd = shape
    qt = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kt_ = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vt = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    s = jnp.einsum("bhld,bhmd->bhlm", qt, kt_) / (hd ** 0.5)
    ref = jnp.einsum(
        "bhlm,bhmd->bhld", jax.nn.softmax(s, axis=-1), vt
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grads_match_dense():
    key = jax.random.PRNGKey(1)
    kq, kk, kv, kt = jax.random.split(key, 4)
    shape = (1, 128, 2, 32)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    t = jax.random.normal(kt, shape, jnp.float32)  # random cotangent seed

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, interpret=True) * t).sum()

    def f_dense(q, k, v):
        return (dense(q, k, v) * t).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_with_lse_matches_dense(causal):
    """The (o, lse) variant: value AND the joint (do, dlse) backward —
    the ring-SP merge consumes lse, so its cotangent path (ds gains a
    ``p * dlse`` term, folded into delta) must match dense autodiff."""
    from ddl25spring_tpu.ops.flash_attention import flash_attention_with_lse
    from ddl25spring_tpu.parallel.sp import _dense_attention_with_lse

    key = jax.random.PRNGKey(5)
    kq, kk, kv, kt, ks = jax.random.split(key, 5)
    shape = (2, 128, 2, 32)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    t_o = jax.random.normal(kt, shape, jnp.float32)
    t_l = jax.random.normal(ks, (2, 2, 128), jnp.float32)

    o_f, lse_f = flash_attention_with_lse(
        q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
    )
    o_d, lse_d = _dense_attention_with_lse(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d), atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse_f), np.asarray(lse_d), atol=2e-5)

    # a loss mixing BOTH outputs (like the ring lse merge does)
    def f_flash(q, k, v):
        o, lse = flash_attention_with_lse(
            q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
        )
        return (o * t_o).sum() + (jnp.tanh(lse) * t_l).sum()

    def f_dense(q, k, v):
        o, lse = _dense_attention_with_lse(q, k, v, causal)
        return (o * t_o).sum() + (jnp.tanh(lse) * t_l).sum()

    g_f = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_llama_forward_with_flash_matches_dense_path():
    cfg_d = LlamaConfig(
        vocab_size=64, dmodel=64, num_heads=2, n_layers=2, ctx_size=128,
        dtype="float32",
    )
    cfg_f = LlamaConfig(
        vocab_size=64, dmodel=64, num_heads=2, n_layers=2, ctx_size=128,
        dtype="float32", use_flash=True,
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg_d)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
    out_d = llama.llama_forward(params, tokens, cfg_d)
    out_f = llama.llama_forward(params, tokens, cfg_f)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_d), atol=2e-4
    )
