"""TP-sharded serving + ZeRO-3 weight streaming (PR 18): the sharded
engine against the dense oracle, the per-chip residency shrink, and
the layout/signature contracts.

The load-bearing pins:

- **tp=2 == dense oracle, bitwise** — the whole paged engine under a
  2-chip ``model`` mesh (KV head dim split, Megatron params) emits the
  IDENTICAL token streams as ``models/decode.generate``, including the
  prefix-cache adopt/COW path and the speculative draft/verify loop.
- **residency divides, the wire does not** — ``mem_budget_bytes()``
  per chip strictly shrinks at tp=2 (global accounting unchanged), the
  ``-tp`` describes compile under a 64 KiB budget one chip cannot meet,
  and the all-reduce payload is byte-exact UNCHANGED by tp.
- **tp unset changes nothing** — the tp=1 engine holds the very same
  ``_PROGRAM_CACHE`` executables as before PR 18 (identity, hence
  byte-identical HLO), and ``DDL25_SERVE_TP`` defaults to 1.
- **the sharing ops are layout-oblivious** — adopt_prefix / ref_pages /
  unref_pages / truncate_to preserve the head-dim split on k/v and the
  replicated accounting, exactly as ``_tp_pool_specs`` declares.
"""

from __future__ import annotations

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.models import decode as dm, llama
from ddl25spring_tpu.serve import kv_pages
from ddl25spring_tpu.serve.engine import (
    KV_POOL_HEAD_DIM,
    ServeEngine,
    _compiled_programs,
)
from ddl25spring_tpu.utils.config import LlamaConfig

from conftest import cached_lowering

CFG = LlamaConfig(
    vocab_size=64, dmodel=16, num_heads=2, n_layers=2, ctx_size=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama_params(jax.random.PRNGKey(0), CFG)


def dense_greedy(params, prompt: list[int], max_new: int) -> list[int]:
    """The dense-cache oracle, compiled once per (|prompt|, max_new)."""

    def build():
        toks = dm.generate(
            params, jnp.asarray([prompt], jnp.int32), CFG,
            max_new_tokens=max_new, temperature=0.0,
        )
        return [int(t) for t in np.asarray(toks)[0]]

    return cached_lowering(("serve-dense", tuple(prompt), max_new), build)


def make_engine(params, **kw):
    kw.setdefault("page_len", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("max_slots", 2)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_batch", 1)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("clock", "virtual")
    return ServeEngine(params, CFG, **kw)


def drain(eng, max_steps: int = 500):
    steps = 0
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to drain"


def assert_tp_pool_layout(pool, tp: int = 2):
    """The H013 placement the engine committed: k/v split exactly on
    :data:`KV_POOL_HEAD_DIM` over ``model``, every accounting buffer
    replicated (the host scheduler reads them obliviously)."""
    for name in ("k", "v"):
        spec = pool[name].sharding.spec
        assert len(spec) > KV_POOL_HEAD_DIM and (
            spec[KV_POOL_HEAD_DIM] == "model"
        ), (name, spec)
        assert len(pool[name].sharding.device_set) == tp
    for name in ("page_table", "seq_len", "active", "free", "refcount"):
        assert pool[name].sharding.is_fully_replicated, name


# ------------------------------------------- bitwise oracle equivalence


def test_tp2_matches_dense_oracle_bitwise(params):
    """fp32 greedy decode through the head-split pool on a 2-chip model
    mesh == the dense single-chip cache, token for token — a page-
    boundary-crossing request plus one admitted mid-batch (the whole
    PR-18 correctness contract at once)."""
    a_prompt, a_new = [5, 9, 11, 3], 9
    b_prompt, b_new = [7, 2, 8], 6
    dense_a = dense_greedy(params, a_prompt, a_new)
    dense_b = dense_greedy(params, b_prompt, b_new)

    eng = make_engine(params, tp=2)
    assert eng.tp == 2 and eng.mesh is not None
    assert_tp_pool_layout(eng.pool)
    ra = eng.make_request(a_prompt, a_new)
    assert eng.submit(ra) is None
    eng.step()
    eng.step()
    rb = eng.make_request(b_prompt, b_new)
    assert eng.submit(rb) is None
    eng.step()  # admits B mid-flight while A stays resident
    drain(eng)
    assert ra.tokens == dense_a
    assert rb.tokens == dense_b
    assert eng.pool_ok_failures == 0
    assert_tp_pool_layout(eng.pool)
    m = eng.metrics()
    assert m["tp"] == 2 and m["weight_stream"] is False
    assert m["n_chips"] == 2
    # static residency telemetry: each chip holds strictly less than
    # the global pool/params (the quantity mem_report trends)
    pool_total = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(eng.pool)
    )
    param_total = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
    )
    assert 0 < m["pool_bytes_per_chip"] < pool_total
    assert 0 < m["param_bytes_per_chip"] < param_total


def test_tp2_weight_stream_matches_dense_oracle(params):
    """ZeRO-3 weight streaming inside the decode scan (double-buffered
    per-layer gather, TP slice, row-parallel block) is bit-identical to
    the resident-weights build — same dense-oracle token streams."""
    prompt, max_new = [5, 9, 11, 3], 9
    dense = dense_greedy(params, prompt, max_new)
    eng = make_engine(params, tp=2, weight_stream=True)
    assert eng.weight_stream is True
    req = eng.make_request(prompt, max_new)
    assert eng.submit(req) is None
    drain(eng)
    assert req.tokens == dense
    assert eng.pool_ok_failures == 0
    assert_tp_pool_layout(eng.pool)
    # the [L, n, k] row layout holds params/n per chip: strictly less
    # resident than even the Megatron split keeps
    resident = make_engine(params, tp=2)
    m_ws, m_tp = eng.metrics(), resident.metrics()
    assert m_ws["weight_stream"] is True
    assert m_ws["param_bytes_per_chip"] < m_tp["param_bytes_per_chip"]


def test_tp2_prefix_cache_hit_stays_bitwise(params):
    """The radix adopt/ref sharing path on the SHARDED pool: a repeated
    2-full-page prompt hits the cache (prefill work actually skipped)
    and still reproduces the dense oracle bitwise — adopt_prefix and
    ref_pages never disturb the head split they share pages under."""
    prompt = [5, 9, 11, 3, 7, 2, 8, 6]  # 2 full pages: a clean radix hit
    dense = dense_greedy(params, prompt, 6)
    eng = make_engine(params, tp=2, prefix_cache=True)
    for _ in range(2):
        r = eng.make_request(prompt, 6)
        assert eng.submit(r) is None
        drain(eng)
        assert r.tokens == dense
    assert eng.prefix.hits >= 1
    assert eng.prefill_tokens_saved > 0
    assert eng.pool_ok_failures == 0
    assert_tp_pool_layout(eng.pool)


def test_tp2_speculative_stays_bitwise(params):
    """The draft/verify loop on sharded pools: the tp=2 speculative
    engine (drafter sharded too, truncate_to rolling both pools back)
    emits the dense oracle's exact tokens with real acceptances."""
    prompt, max_new = [5, 9, 11, 3], 9
    dense = dense_greedy(params, prompt, max_new)
    eng = make_engine(params, tp=2, spec_k=2)
    req = eng.make_request(prompt, max_new)
    assert eng.submit(req) is None
    drain(eng)
    assert req.tokens == dense
    assert eng.draft_tokens_accepted > 0
    assert eng.pool_ok_failures == 0
    assert_tp_pool_layout(eng.pool)
    assert_tp_pool_layout(eng.draft_pool)


# ------------------------------------------------- layout obliviousness


def test_sharing_ops_preserve_the_head_split(params):
    """adopt_prefix / ref_pages / unref_pages / truncate_to run on the
    placed pool without re-laying it out: k/v keep the head-dim split,
    accounting stays replicated (layout-oblivious by construction —
    they only touch refcount/table state or copy whole head rows)."""
    eng = make_engine(params, tp=2)
    pool = eng.pool
    slots = jnp.arange(eng.max_slots, dtype=jnp.int32)
    pool, ok = kv_pages.reserve_pages(
        pool, slots[:1], jnp.zeros((1,), jnp.int32),
        jnp.asarray([True]),
    )
    assert bool(ok)
    assert_tp_pool_layout(pool)
    page0 = int(np.asarray(pool["page_table"])[0, 0])
    pool = kv_pages.ref_pages(pool, jnp.asarray([page0]))
    assert_tp_pool_layout(pool)
    # adopt by reference into slot 1 + a COW copy of the same page
    adopt = jnp.full((1, eng.pages_per_seq), -1, jnp.int32)
    pool, ok = kv_pages.adopt_prefix(
        pool, slots[1:2], adopt.at[0, 0].set(page0),
        jnp.asarray([page0]),
    )
    assert bool(ok)
    assert_tp_pool_layout(pool)
    pool = kv_pages.truncate_to(
        pool, jnp.zeros((eng.max_slots,), jnp.int32),
        jnp.asarray([True] * eng.max_slots),
    )
    assert_tp_pool_layout(pool)
    pool = kv_pages.unref_pages(pool, jnp.asarray([page0]))
    assert_tp_pool_layout(pool)


# ------------------------------------------------- tp=1 is untouched


def test_tp_unset_keeps_the_exact_single_device_build(params, monkeypatch):
    """The no-regression half of the tentpole: with ``DDL25_SERVE_TP``
    unset the driver knobs resolve to tp=1, and a tp=1 engine holds the
    IDENTICAL ``_PROGRAM_CACHE`` executables the pre-PR-18 build
    compiled — object identity, hence byte-identical decode HLO."""
    from ddl25spring_tpu.serve import driver

    monkeypatch.delenv("DDL25_SERVE_TP", raising=False)
    monkeypatch.delenv("DDL25_SERVE_WEIGHT_STREAM", raising=False)
    knobs = driver.engine_knobs(smoke=True)
    assert knobs["tp"] == 1 and knobs["weight_stream"] is False

    eng = make_engine(params)
    assert eng.tp == 1 and eng.mesh is None
    tick, prefill, release = _compiled_programs(
        CFG, max_prompt_len=8, temperature=0.0, sentinel=None,
        donate=True,
    )
    assert eng._tick is tick
    assert eng._prefill is prefill
    assert eng._release is release

    monkeypatch.setenv("DDL25_SERVE_TP", "2")
    monkeypatch.setenv("DDL25_SERVE_WEIGHT_STREAM", "1")
    knobs = driver.engine_knobs(smoke=True)
    assert knobs["tp"] == 2 and knobs["weight_stream"] is True


def test_tp_constructor_validation(params):
    with pytest.raises(ValueError, match="tp=0"):
        make_engine(params, tp=0)
    with pytest.raises(ValueError, match="requires tp > 1"):
        make_engine(params, tp=1, weight_stream=True)
    with pytest.raises(ValueError, match="spec_k"):
        make_engine(params, tp=2, weight_stream=True, spec_k=2)
    with pytest.raises(ValueError, match="not divisible"):
        make_engine(params, tp=4)  # 2 heads over 4 chips
    with pytest.raises(ValueError, match="devices"):
        make_engine(params, tp=16)  # conftest fakes only 8


# ------------------------------------------------- residency shrink


def test_tp_mem_budget_divides_per_chip_only(params):
    """``mem_budget_bytes()`` (per-chip, the default) strictly shrinks
    at tp=2 and again under weight streaming, while ``per_chip=False``
    — the GLOBAL logical accounting memscope bands against — is
    identical across all three builds (sharding moves bytes, it never
    creates or destroys them)."""
    dense = make_engine(params)
    tp2 = make_engine(params, tp=2)
    ws = make_engine(params, tp=2, weight_stream=True)
    assert tp2.mem_budget_bytes() < dense.mem_budget_bytes()
    assert ws.mem_budget_bytes() < dense.mem_budget_bytes()
    assert ws.mem_budget_bytes() < tp2.mem_budget_bytes()
    g = dense.mem_budget_bytes(per_chip=False)
    assert tp2.mem_budget_bytes(per_chip=False) == g
    assert ws.mem_budget_bytes(per_chip=False) == g


# ------------------------------------------------- compile signatures


@pytest.mark.parametrize("name,ar_count,ar_bytes,kinds", [
    # per-chip variants: same program as serve-decode/serve-prefill,
    # tighter screws — 64 KiB budget + byte-exact all-reduce payload
    ("serve-decode-tp", 2 * 2, 1024, {"all-reduce"}),
    ("serve-prefill-tp", 2 * 2 * 8, 4096, {"all-reduce"}),
    # streaming decode adds EXACTLY n_layers x n_buckets = 2 gathers
    ("serve-decode-zero3stream", 2 * 2, 1024, {"all-reduce", "all-gather"}),
])
def test_tp_signature_pins(strategy_report, name, ar_count, ar_bytes, kinds):
    """The PR-18 signatures: all-reduce count UNCHANGED from the dense
    pins (tp divides KV bytes and FLOPs, never the collective count),
    payload byte-exact (positions x dmodel x fp32 partial sums), and
    only the streaming entry may gather — count-pinned, not waived."""
    r = strategy_report(name)
    assert r["signature_violations"] == []
    assert [f for f in r["findings"] if not f["waived"]] == []
    totals = r["collectives"]["totals"]
    assert set(totals) == kinds
    assert totals["all-reduce"]["count"] == ar_count
    assert totals["all-reduce"]["result_bytes"] == ar_bytes
    if "all-gather" in kinds:
        n_layers, n_buckets = 2, r["meta"]["stream_buckets"]
        assert totals["all-gather"]["count"] == n_layers * n_buckets
    assert r["sched"]["hazards"] == []
    assert r["lowered"] in ("decode_step", "prefill_step")
    assert r["meta"]["kv_sharded_dim"] == KV_POOL_HEAD_DIM


@pytest.mark.parametrize("name", [
    "serve-decode-tp", "serve-prefill-tp", "serve-decode-zero3stream",
])
def test_tp_describe_budgets_shrink(strategy_report, name):
    """THE perf gate: the same program compiled on one chip vs two —
    compile-time peak HBM strictly shrinks, the tp=2 peak fits a budget
    the one-chip build measurably cannot (64 KiB vs ~83 KiB measured;
    128 KiB vs ~140 KiB streamed), and the declared per-chip pool/param
    residency divides (shard_shape math, deterministic)."""
    from ddl25spring_tpu.obs import xla_analytics as xa

    r2 = strategy_report(name)  # default mesh (2,)
    r1 = cached_lowering(
        ("tp-shrink", name),
        lambda: xa.compile_strategy(name, mesh_sizes=(1,)),
    )
    assert r1["signature_violations"] == []
    peak1 = r1["memory"]["peak_hbm_bytes"]
    peak2 = r2["memory"]["peak_hbm_bytes"]
    assert peak2 < peak1, (peak2, peak1)
    budget = r2["expected"]["memory"]["max_peak_hbm_bytes"]
    assert peak2 <= budget < peak1, (peak2, budget, peak1)
    # per-chip residency: pure shape math, pinned exact
    assert r1["meta"]["pool_bytes_per_chip"] == 17572
    assert r2["meta"]["pool_bytes_per_chip"] == 8868
    assert r1["meta"]["param_bytes_per_chip"] == 41280
    assert r2["meta"]["param_bytes_per_chip"] == (
        24768 if name == "serve-decode-zero3stream" else 24896
    )


def test_tp_entries_share_the_dense_programs_wire(strategy_report):
    """serve-decode-tp IS serve-decode compiled at (2,) — identical
    collective totals (the -tp registry entry changes the budget and
    the meta, never the program), so the per-chip shrink comes with the
    wire traffic pinned unchanged."""
    for dense, tp in (
        ("serve-decode", "serve-decode-tp"),
        ("serve-prefill", "serve-prefill-tp"),
    ):
        assert (strategy_report(dense)["collectives"]["totals"]
                == strategy_report(tp)["collectives"]["totals"])


def test_stream_rows_contract_catches_replicated_blocks(strategy_report):
    """The H013 stream-rows walk (analysis/shard_flow.py): green on the
    real compiled streaming program, and a report whose params['blocks']
    leaves lost their dim-1 row split raises findings (the check is not
    vacuous)."""
    from ddl25spring_tpu.analysis import shard_flow

    r = strategy_report("serve-decode-zero3stream")
    name = "serve-decode-zero3stream"
    assert shard_flow.stream_rows_findings({name: r}) == []
    bad = copy.deepcopy(r)
    broke = 0
    for p in bad["entry_params"]:
        if "blocks" in (p.get("arg") or ""):
            p["sharding"] = None
            broke += 1
    assert broke > 0
    findings = shard_flow.stream_rows_findings({name: bad})
    assert len(findings) == broke
    assert all(f.rule == "H013" for f in findings)


# ------------------------------------------------- driver + tooling


def test_driver_tp_ab_gates_green(params):
    """driver.tp_ab_compare on a seeded trace: bitwise token equality
    over every compared request, a strict per-chip residency shrink —
    and tools/serve_report.check_tp passes the cell (then trips on each
    falsified verdict, so the gate is not vacuous)."""
    from ddl25spring_tpu.serve import driver
    from ddl25spring_tpu.serve.traffic import TrafficSpec, synth_trace
    from tools import serve_report

    knobs = driver.engine_knobs(smoke=True)
    knobs["tp"] = 2
    spec = TrafficSpec(
        seed=0, duration_s=2.0, rate_rps=6.0, profile="ramp",
        vocab_size=CFG.vocab_size,
    )
    trace = synth_trace(spec)
    assert len(trace) >= 4
    tab = driver.tp_ab_compare(params, CFG, trace, knobs)
    assert tab["tp"] == 2
    assert tab["tokens_match"] is True
    assert tab["compared_requests"] > 0
    assert tab["budget_shrunk"] is True
    assert (tab["sharded"]["mem_budget_bytes_per_chip"]
            < tab["dense"]["mem_budget_bytes_per_chip"])
    # both arms drained the identical workload
    assert (tab["sharded"]["generated_tokens"]
            == tab["dense"]["generated_tokens"])

    rec = {"tp_ab": tab}
    assert serve_report.check_tp([rec]) == []
    # each verdict gates independently
    assert serve_report.check_tp([{}])  # no cell at all
    shallow = dict(tab, budget_shrunk=False)
    assert any("budget_shrunk" in f
               for f in serve_report.check_tp([{"tp_ab": shallow}]))
    mism = dict(tab, tokens_match=False)
    assert any("token-for-token" in f
               for f in serve_report.check_tp([{"tp_ab": mism}]))
    vac = dict(tab, compared_requests=0)
    assert any("token-for-token" in f
               for f in serve_report.check_tp([{"tp_ab": vac}]))
    grew = dict(
        tab,
        sharded=dict(tab["sharded"], mem_budget_bytes_per_chip=10**9),
    )
    assert any("did not shrink" in f
               for f in serve_report.check_tp([{"tp_ab": grew}]))


def test_obs_report_renders_the_tp_lines():
    """The Serving section prints per-chip pool/param bytes, the tp
    line, and the tp A/B verdict — from the raw serve.json shape (the
    arms nested under sharded/dense)."""
    from ddl25spring_tpu.obs.report import format_report

    summary = {
        "run_dir": "/tmp/x",
        "serve": {
            "key": {"model": "tiny", "tp": 2},
            "requests": {"submitted": 2, "admitted": 2, "rejected": 0,
                         "rejected_by_reason": {}, "completed": 2},
            "ramp": {
                "admitted": 2, "rejected": 0, "completed": 2,
                "tokens_per_sec_per_chip": 10.0,
                "page_pool_peak_pages": 4, "page_pool_pages": 16,
                "page_pool_peak_occupancy": 0.25,
                "pool_bytes_per_chip": 8868,
                "param_bytes_per_chip": 24896,
                "tp": 2, "weight_stream": False,
                "queue_depth_max": 1, "pool_ok_failures": 0,
            },
            "tp_ab": {
                "tp": 2, "budget_s": 1.0, "tokens_match": True,
                "tp_tokens_at_budget": 8, "dense_tokens_at_budget": 8,
                "budget_shrunk": True, "compared_requests": 2,
                "sharded": {"mem_budget_bytes_per_chip": 33722},
                "dense": {"mem_budget_bytes_per_chip": 58810},
            },
        },
    }
    text = format_report(summary)
    assert "8.7 KiB/chip" in text
    assert "tp 2" in text and "params 24.3 KiB/chip" in text
    assert "tp A/B (tp=2)" in text
    assert "shrunk True" in text
    assert "32.9 vs 57.4 KiB" in text
