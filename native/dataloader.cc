// Native host-side data loader / prefetcher for CIFAR-10 binary batches.
//
// Role: the framework's C++ runtime component for input pipelines.  The
// reference leans on native data machinery in its third-party deps
// (torchvision's C image decoders, SentencePiece's C++ tokenizer — SURVEY
// §2 "native components"); here the equivalent is in-tree: parsing,
// per-epoch shuffling, normalization, and batch assembly run in C++ worker
// threads that stay ahead of the TPU step loop, so host input work overlaps
// device compute instead of serializing with it.
//
// Pipeline: N worker threads pull batch indices from a ticket counter, each
// assembles one normalized float32 NHWC batch straight from the mmap-like
// in-memory byte store, and pushes it into a bounded queue (depth =
// prefetch_depth) consumed by dl_next().  Shuffling is a seeded
// Fisher-Yates permutation re-derived per epoch from (seed, epoch) so runs
// are deterministic; batches are emitted in epoch order regardless of which
// worker finishes first (per-slot reordering).
//
// C ABI (ctypes-consumed; see ddl25spring_tpu/data/native_loader.py):
//   dl_create(dir, batch, seed, depth, workers, normalize) -> handle (0 on error)
//   dl_num_samples(h), dl_error(h)
//   dl_next(h, void* x, int32* y) -> epoch of the batch (>=0), blocking
//     (x is float32 when normalize!=0, uint8 NHWC otherwise)
//   dl_destroy(h)
//
// CIFAR-10 record format: 1 label byte + 3072 channel-major pixel bytes
// (3x32x32 RGB); output is NHWC float32 normalized with the canonical
// train statistics — byte-identical semantics to the numpy path in
// ddl25spring_tpu/data/cifar10.py.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <filesystem>
#include <fstream>
#include <map>
#include <memory>
#include <mutex>
#include <numeric>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace fs = std::filesystem;

namespace {

constexpr int kH = 32, kW = 32, kC = 3;
constexpr int kRecordBytes = 1 + kH * kW * kC;
constexpr float kMean[kC] = {0.4914f, 0.4822f, 0.4465f};
constexpr float kStd[kC] = {0.2470f, 0.2435f, 0.2616f};

struct Batch {
  long index = 0;  // global batch counter (epoch * batches_per_epoch + i)
  std::vector<float> x;      // normalized mode
  std::vector<uint8_t> xb;   // raw mode (uint8 NHWC; device normalizes)
  std::vector<int32_t> y;
};

class Loader {
 public:
  Loader(const char* dir, int batch, uint64_t seed, int depth, int workers,
         bool normalize)
      : batch_(batch), seed_(seed), depth_(depth < 1 ? 1 : depth),
        normalize_(normalize) {
    for (int i = 1; i <= 6; ++i) {
      fs::path p = fs::path(dir) / ("data_batch_" + std::to_string(i) + ".bin");
      if (fs::exists(p)) Append(p);
    }
    if (records_ == 0) {
      fs::path p = fs::path(dir) / "train.bin";  // single-file layout
      if (fs::exists(p)) Append(p);
    }
    if (records_ < static_cast<size_t>(batch_)) {
      error_ = "no usable data_batch_*.bin under " + std::string(dir);
      return;
    }
    int n = workers < 1 ? 1 : workers;
    for (int i = 0; i < n; ++i)
      threads_.emplace_back([this] { Work(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_full_.notify_all();
    cv_empty_.notify_all();
    for (auto& t : threads_) t.join();
  }

  const char* error() const { return error_.empty() ? nullptr : error_.c_str(); }
  long num_samples() const { return static_cast<long>(records_); }
  long batches_per_epoch() const { return static_cast<long>(records_) / batch_; }

  // Blocking: copies the next in-order batch into caller buffers.
  // out_x is float32 in normalized mode, uint8 in raw mode.
  long Next(void* out_x, int32_t* out_y) {
    std::unique_lock<std::mutex> lk(mu_);
    long want = next_out_;
    cv_empty_.wait(lk, [&] { return stop_ || ready_.count(want); });
    if (stop_ && !ready_.count(want)) return -1;
    Batch b = std::move(ready_[want]);
    ready_.erase(want);
    ++next_out_;
    lk.unlock();
    cv_full_.notify_all();
    if (normalize_)
      std::memcpy(out_x, b.x.data(), b.x.size() * sizeof(float));
    else
      std::memcpy(out_x, b.xb.data(), b.xb.size());
    std::memcpy(out_y, b.y.data(), b.y.size() * sizeof(int32_t));
    return want / batches_per_epoch();  // epoch index
  }

 private:
  void Append(const fs::path& p) {
    std::ifstream f(p, std::ios::binary);
    std::vector<char> buf((std::istreambuf_iterator<char>(f)),
                          std::istreambuf_iterator<char>());
    size_t n = buf.size() / kRecordBytes;
    data_.insert(data_.end(), buf.begin(),
                 buf.begin() + static_cast<long>(n * kRecordBytes));
    records_ += n;
  }

  // Per-epoch deterministic permutation:
  // mt19937_64(seed + golden_ratio_odd * (epoch + 1)).
  std::vector<uint32_t> Perm(long epoch) const {
    std::vector<uint32_t> idx(records_);
    std::iota(idx.begin(), idx.end(), 0u);
    std::mt19937_64 rng(seed_ + 0x9e3779b97f4a7c15ULL * (epoch + 1));
    for (size_t i = records_ - 1; i > 0; --i) {
      std::uniform_int_distribution<size_t> d(0, i);
      std::swap(idx[i], idx[d(rng)]);
    }
    return idx;
  }

  void Assemble(long global_idx, Batch* out) const {
    long bpe = static_cast<long>(records_) / batch_;
    long epoch = global_idx / bpe, slot = global_idx % bpe;
    // Workers on the same epoch share the permutation via a small cache of
    // shared_ptrs — copying the pointer, not the 4*records_ byte vector.
    std::shared_ptr<const std::vector<uint32_t>> perm_p;
    {
      std::lock_guard<std::mutex> lk(perm_mu_);
      auto it = perm_cache_.find(epoch);
      if (it == perm_cache_.end()) {
        it = perm_cache_
                 .emplace(epoch, std::make_shared<const std::vector<uint32_t>>(
                                     Perm(epoch)))
                 .first;
        if (perm_cache_.size() > 4) perm_cache_.erase(perm_cache_.begin());
      }
      perm_p = it->second;
    }
    const std::vector<uint32_t>& perm = *perm_p;
    out->index = global_idx;
    if (normalize_)
      out->x.resize(static_cast<size_t>(batch_) * kH * kW * kC);
    else
      out->xb.resize(static_cast<size_t>(batch_) * kH * kW * kC);
    out->y.resize(batch_);
    for (int b = 0; b < batch_; ++b) {
      const unsigned char* rec = reinterpret_cast<const unsigned char*>(
          data_.data() +
          static_cast<size_t>(perm[slot * batch_ + b]) * kRecordBytes);
      out->y[b] = rec[0];
      const unsigned char* px = rec + 1;  // channel-major [3][32][32]
      if (normalize_) {
        float* dst = out->x.data() + static_cast<size_t>(b) * kH * kW * kC;
        for (int c = 0; c < kC; ++c) {
          const float inv = 1.0f / (255.0f * kStd[c]);
          const float off = kMean[c] / kStd[c];
          for (int hw = 0; hw < kH * kW; ++hw)
            dst[hw * kC + c] =
                static_cast<float>(px[c * kH * kW + hw]) * inv - off;
        }
      } else {
        // raw mode: transpose CHW->NHWC only; 4x less host->device traffic,
        // normalization fuses into the device step instead
        uint8_t* dst = out->xb.data() + static_cast<size_t>(b) * kH * kW * kC;
        for (int c = 0; c < kC; ++c)
          for (int hw = 0; hw < kH * kW; ++hw)
            dst[hw * kC + c] = px[c * kH * kW + hw];
      }
    }
  }

  void Work() {
    for (;;) {
      long ticket;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_full_.wait(lk, [&] {
          return stop_ || next_ticket_ < next_out_ + depth_;
        });
        if (stop_) return;
        ticket = next_ticket_++;
      }
      Batch b;
      Assemble(ticket, &b);
      {
        std::lock_guard<std::mutex> lk(mu_);
        ready_[ticket] = std::move(b);
      }
      cv_empty_.notify_all();
    }
  }

  const int batch_;
  const uint64_t seed_;
  const int depth_;
  const bool normalize_;
  std::string error_;
  std::vector<char> data_;
  size_t records_ = 0;

  mutable std::mutex perm_mu_;
  mutable std::map<long, std::shared_ptr<const std::vector<uint32_t>>>
      perm_cache_;

  std::mutex mu_;
  std::condition_variable cv_full_, cv_empty_;
  std::map<long, Batch> ready_;
  long next_ticket_ = 0;
  long next_out_ = 0;
  bool stop_ = false;
  std::vector<std::thread> threads_;
};

}  // namespace

extern "C" {

void* dl_create(const char* dir, int batch, uint64_t seed, int depth,
                int workers, int normalize) {
  auto* l = new Loader(dir, batch, seed, depth, workers, normalize != 0);
  return l;
}

const char* dl_error(void* h) { return static_cast<Loader*>(h)->error(); }

long dl_num_samples(void* h) {
  return static_cast<Loader*>(h)->num_samples();
}

long dl_next(void* h, void* x, int32_t* y) {
  return static_cast<Loader*>(h)->Next(x, y);
}

void dl_destroy(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
