// Native byte-level BPE encoder.
//
// Role: the framework's C++ runtime component for tokenization.  The
// reference's tokenizer IS native C++ — simplellm's SPTokenizer wraps
// SentencePiece (`lab/s01_b1_microbatches.py:6,31`; SURVEY §2 "native
// components") — so the in-tree equivalent keeps the hot encode loop
// native too: the greedy lowest-rank merge scan runs here, called through
// ctypes from ddl25spring_tpu/data/tokenizer.py (which transparently
// falls back to its pure-Python loop when the toolchain is absent).
//
// Semantics are BYTE-IDENTICAL to BpeTokenizer.encode:
//   - text is chunked by the Python regex `\s*\S+|\s+$` under Python-str
//     whitespace classification (the Unicode \s set below, enumerated from
//     CPython's re module), whitespace traveling with the following word;
//   - per chunk, ids start as byte+3 and the lowest-(rank, position)
//     adjacent pair is merged until no learnable pair remains — the exact
//     loop of BpeTokenizer._encode_chunk, including leftmost tie-break;
//   - id space: 0/1/2 pad/bos/eos, 3..258 bytes, 259+i = merge i.
//
// C ABI (ctypes-consumed):
//   bpe_create(const int32_t* merges /* [n*2] */, int n) -> handle
//   bpe_encode(h, const uint8_t* utf8, long len, int add_bos,
//              int32_t* out /* cap >= len+1 */) -> id count
//   bpe_destroy(h)

#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <vector>

using std::size_t;

namespace {

constexpr int kByte0 = 3;
constexpr int kBosId = 1;
constexpr int kFirstMergeId = 259;

// Python re `\s` for str (CPython 3.12): enumerated via
//   [i for i in range(0x110000) if re.match(r'\s', chr(i))]
bool IsPySpace(uint32_t cp) {
  switch (cp) {
    case 0x09: case 0x0a: case 0x0b: case 0x0c: case 0x0d:
    case 0x1c: case 0x1d: case 0x1e: case 0x1f: case 0x20:
    case 0x85: case 0xa0: case 0x1680:
    case 0x2000: case 0x2001: case 0x2002: case 0x2003: case 0x2004:
    case 0x2005: case 0x2006: case 0x2007: case 0x2008: case 0x2009:
    case 0x200a: case 0x2028: case 0x2029: case 0x202f: case 0x205f:
    case 0x3000:
      return true;
    default:
      return false;
  }
}

// Decode one UTF-8 codepoint at data[i]; advances i past it.  Invalid
// sequences consume one byte and yield a non-space sentinel — chunking
// then treats the raw byte as word content, matching how Python would
// have already replaced it before regex chunking (encode() receives str,
// so input bytes here are valid UTF-8 from Python; this is just safety).
uint32_t NextCodepoint(const uint8_t* data, long len, long& i) {
  uint8_t b = data[i];
  int extra = 0;
  uint32_t cp = b;
  if (b >= 0xf0) { extra = 3; cp = b & 0x07; }
  else if (b >= 0xe0) { extra = 2; cp = b & 0x0f; }
  else if (b >= 0xc0) { extra = 1; cp = b & 0x1f; }
  else if (b >= 0x80) { i += 1; return 0xFFFD; }  // bare continuation byte
  if (i + extra >= len) { i += 1; return 0xFFFD; }
  for (int k = 1; k <= extra; ++k) cp = (cp << 6) | (data[i + k] & 0x3f);
  i += 1 + extra;
  return cp;
}

struct PairHash {
  size_t operator()(const std::pair<int32_t, int32_t>& p) const {
    // widen to uint64_t before the 32-bit shift (UB on 32-bit size_t)
    return static_cast<size_t>(
        (static_cast<uint64_t>(static_cast<uint32_t>(p.first)) << 32) ^
        static_cast<uint32_t>(p.second));
  }
};

struct Bpe {
  std::unordered_map<std::pair<int32_t, int32_t>, int32_t, PairHash> rank;
  int n_merges = 0;
};

// The exact loop of BpeTokenizer._encode_chunk: repeatedly merge the
// lowest-(rank, position) adjacent pair.  Chunks are words, so the
// quadratic rescan is over short sequences; ids shrink in place.
void EncodeChunk(const Bpe& bpe, const uint8_t* data, long begin, long end,
                 std::vector<int32_t>& ids, std::vector<int32_t>& out) {
  ids.clear();
  for (long i = begin; i < end; ++i) ids.push_back(kByte0 + data[i]);
  while (ids.size() > 1) {
    int32_t best_rank = bpe.n_merges;
    size_t best_j = 0;
    for (size_t j = 0; j + 1 < ids.size(); ++j) {
      auto it = bpe.rank.find({ids[j], ids[j + 1]});
      if (it != bpe.rank.end() && it->second < best_rank) {
        best_rank = it->second;
        best_j = j;
      }
    }
    if (best_rank == bpe.n_merges) break;
    ids[best_j] = kFirstMergeId + best_rank;
    ids.erase(ids.begin() + best_j + 1);
  }
  out.insert(out.end(), ids.begin(), ids.end());
}

}  // namespace

extern "C" {

void* bpe_create(const int32_t* merges, int n) {
  Bpe* b = new Bpe();
  b->n_merges = n;
  b->rank.reserve(n * 2);
  for (int i = 0; i < n; ++i) {
    // assignment, not emplace: duplicate pairs keep the LAST rank, matching
    // the Python dict-comprehension in BpeTokenizer.__init__
    b->rank[std::make_pair(merges[2 * i], merges[2 * i + 1])] = i;
  }
  return b;
}

void bpe_destroy(void* h) { delete static_cast<Bpe*>(h); }

long bpe_encode(void* h, const uint8_t* utf8, long len, int add_bos,
                int32_t* out_buf) {
  const Bpe& bpe = *static_cast<Bpe*>(h);
  std::vector<int32_t> out;
  out.reserve(len + 1);
  if (add_bos) out.push_back(kBosId);

  // chunk by `\s*\S+|\s+$`: scan codepoints, emitting [ws-run][word] chunks;
  // a trailing pure-ws run is its own final chunk
  std::vector<int32_t> scratch;
  long i = 0;
  while (i < len) {
    long chunk_begin = i;
    // optional leading whitespace
    long j = i;
    while (j < len) {
      long k = j;
      if (!IsPySpace(NextCodepoint(utf8, len, k))) break;
      j = k;
    }
    if (j == len) {
      // trailing whitespace only: the `\s+$` alternative
      EncodeChunk(bpe, utf8, chunk_begin, len, scratch, out);
      break;
    }
    // the word: non-space codepoints
    while (j < len) {
      long k = j;
      if (IsPySpace(NextCodepoint(utf8, len, k))) break;
      j = k;
    }
    EncodeChunk(bpe, utf8, chunk_begin, j, scratch, out);
    i = j;
  }
  for (size_t k = 0; k < out.size(); ++k) out_buf[k] = out[k];
  return static_cast<long>(out.size());
}

}  // extern "C"
