"""Headline benchmark: the BASELINE.json north-star config.

North star (`BASELINE.json`): DP+PP ResNet-18/CIFAR-10 via the `run-b2.sh`
path at >= 5,000 samples/sec/chip.  The train step is built by
``ddl25spring_tpu.benchmarks.build_resnet_step`` — the same builder
`lab/s01_b2_dp_pp.py` uses, so the bench cannot drift from what run-b2.sh
runs.  Normalization happens device-side inside the jitted step.

**Primary input mode: HBM-resident dataset with on-device epoch shuffle**
(``DeviceDataset``) — the whole 147 MiB uint8 train split lives on device;
every timed step consumes a fresh, disjoint, epoch-permuted batch gathered
on device.  Real input semantics (unlike rounds 1-2's single re-fed batch),
zero steady-state host->device traffic (the TPU-native input design for
datasets that fit HBM).  Two secondary lines keep the bench honest:

- ``native-stream-uint8``: the C++ prefetcher pushes a fresh batch across
  the host->device link every step.  On this image that link is a network
  tunnel measured at ~10-20 MiB/s (vs multi-GiB/s PCIe on a real TPU VM),
  which bounds ANY host-streaming input at ~3-6k samples/s; the measured
  link bandwidth is emitted as ``h2d_mib_per_s`` so the number is
  self-describing.
- ``fixed-device-batch``: one device-resident batch re-fed (pure compute,
  the upper bound).

Topology: DP+PP (2-stage heterogeneous pipeline x DP) when >= 2 chips are
attached, pure DP on a single chip — the emitted JSON names the layout it
actually ran.

Driver contract: print ONE JSON line with at least
``{"metric", "value", "unit", "vs_baseline"}``.  Extra self-describing
fields: ``input``, ``data`` (real vs synthetic CIFAR), ``topology``,
``chip``, ``mfu``, ``achieved_tflops_per_chip``, ``secondary`` (list: the
streaming and fixed-batch runs).  If the TPU tunnel is unreachable the
device probe times out and ONE JSON line with an ``error`` field is printed
instead of hanging the driver.
"""

from __future__ import annotations

import argparse
import json
import os
import threading

import jax


def probe_devices(timeout_s: float):
    """jax.devices() with a timeout: backend init dials the TPU tunnel and
    can block forever when the relay is down — a daemon thread bounds it."""
    out: dict = {}

    def _probe():
        try:
            out["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — report, don't hang
            out["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in out:
        return out["devices"], None
    return None, out.get("error", f"device init timed out after {timeout_s:.0f}s")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local testing; the axon TPU "
                         "plugin is registered at interpreter start)")
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N",
                    help="simulate an N-device CPU mesh (implies --cpu)")
    ap.add_argument("--per-chip-batch", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--microbatches", type=int, default=2)
    ap.add_argument("--probe-timeout", type=float, default=240.0)
    args = ap.parse_args(argv)

    if args.force_cpu_devices:
        from ddl25spring_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(args.force_cpu_devices)
    elif args.cpu:
        jax.config.update("jax_platforms", "cpu")
    devices, err = probe_devices(args.probe_timeout)
    if devices is None:
        print(json.dumps({
            "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": f"accelerator unreachable: {err}",
        }))
        return

    import time

    from ddl25spring_tpu.benchmarks import (
        DeviceDataset,
        InputFeed,
        build_resnet_step,
        report_line,
        timed_run,
    )
    from ddl25spring_tpu.utils.flops import chip_peak_flops, compiled_flops, mfu

    n = len(devices)
    dp, S = (n // 2, 2) if n >= 2 else (1, 1)
    M = args.microbatches if S == 2 else 1
    batch = (args.per_chip_batch * dp * S) // (dp * M) * (dp * M)
    step, params, opt_state, meta = build_resnet_step(devices, dp, S, M, batch)
    n_chips = meta["n_chips"]

    ds = DeviceDataset(batch)

    # --- primary: HBM-resident dataset, on-device epoch shuffle ------------
    dt, params, opt_state = timed_run(
        step, params, opt_state, ds.feed, args.steps, args.warmup
    )
    sps_chip = args.steps * batch / dt / n_chips

    # --- secondary 1: host streaming through the native C++ loader ---------
    # Constructed only now, and warmed past the prefetch queue's capacity
    # (depth + in-flight workers), so the timed window starts with an empty
    # queue and measures steady-state producer-bound throughput — a queue
    # pre-filled during the primary run would hand the timed loop several
    # batches for free and inflate the number.
    workers = max(2, (os.cpu_count() or 4) // 2)
    depth = 6
    feed = InputFeed(batch, stream=True, workers=workers, prefetch_depth=depth)
    stream_warm = args.warmup + depth + workers
    dt_s, params, opt_state = timed_run(
        step, params, opt_state, feed.feed, args.steps, stream_warm
    )
    sps_chip_stream = args.steps * batch / dt_s / n_chips

    # --- secondary 2: one fixed device-resident batch (compute bound) ------
    dt2, params, opt_state = timed_run(
        step, params, opt_state, feed.feed_fixed, args.steps, args.warmup
    )
    sps_chip_fixed = args.steps * batch / dt2 / n_chips

    # measure the host->device link so the streaming line explains itself
    import numpy as np

    # median of 3 transfers: one TCP hiccup on the tunneled link must not
    # skew the self-describing bandwidth number
    buf = np.zeros(4 * 1024 * 1024, np.uint8)
    jax.device_put(buf[:1024], devices[0]).block_until_ready()
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(buf, devices[0]).block_until_ready()
        rates.append(4.0 / (time.perf_counter() - t0))
    h2d_mib_s = sorted(rates)[1]

    flops_step = compiled_flops(step, params, opt_state, feed.fixed)
    achieved_tf, frac = mfu(flops_step, dt / args.steps, n_chips, meta["device"])
    peak = chip_peak_flops(meta["device"])

    print(report_line(
        meta["layout"], sps_chip, ds.input_mode, frac, achieved_tf,
        data=ds.provenance,
        topology=meta["topology"],
        chip=f"{meta['device'].device_kind} x{n_chips}",
        flops_per_step=flops_step,
        peak_tflops_per_chip=peak / 1e12 if peak else None,
        h2d_mib_per_s=round(h2d_mib_s, 1),
        secondary=[
            {
                "input": feed.input_mode,
                "value": round(sps_chip_stream, 1),
                "unit": "samples/sec/chip",
                # only claim link-bound streaming when the native loader
                # actually streamed; on NativeLoaderUnavailable this run
                # degraded to the fixed batch and says so via input_mode
                **({"note": "bounded by the tunneled host->device link "
                            f"(~{h2d_mib_s:.0f} MiB/s here; GiB/s on a "
                            "TPU VM)"}
                   if feed.streaming else
                   {"note": "native loader unavailable; fell back to the "
                            "fixed device-resident batch"}),
            },
            {
                "input": "fixed-device-batch",
                "value": round(sps_chip_fixed, 1),
                "unit": "samples/sec/chip",
            },
        ],
    ))

    feed.close()


if __name__ == "__main__":
    main()
