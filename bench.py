"""Headline benchmark: the BASELINE.json north-star config.

North star (`BASELINE.json`): DP+PP ResNet-18/CIFAR-10 via the `run-b2.sh`
path at >= 5,000 samples/sec/chip.  The train step is built by
``ddl25spring_tpu.benchmarks.build_resnet_step`` — the same builder
`lab/s01_b2_dp_pp.py` uses, so the bench cannot drift from what run-b2.sh
runs.  Normalization happens device-side inside the jitted step.

**Primary input mode: HBM-resident dataset + on-device epoch shuffle,
K train steps fused per dispatch** (``build_resnet_scan_step``) — the
whole 147 MiB uint8 train split lives on device; the compiled program
draws K fresh, disjoint, epoch-permuted batches and runs K train steps
per Python dispatch (a ``lax.scan`` over the same inner step).  Real
input semantics (every sample once per epoch) with the ~4 ms/dispatch
tunnel round-trip amortized to noise — the idiomatic TPU input design:
data in HBM, input pipeline inside the program, host only ticks epochs.
Three secondary lines keep the bench honest:

- ``hbm-resident-shuffle``: the same input, ONE step per dispatch
  (rounds 1-3's primary; its delta vs the scan line is the measured
  dispatch overhead).

- ``native-stream-uint8``: the C++ prefetcher pushes a fresh batch across
  the host->device link every step.  On this image that link is a network
  tunnel measured at ~10-20 MiB/s (vs multi-GiB/s PCIe on a real TPU VM),
  which bounds ANY host-streaming input at ~3-6k samples/s; the measured
  link bandwidth is emitted as ``h2d_mib_per_s`` so the number is
  self-describing.
- ``fixed-device-batch``: one device-resident batch re-fed (pure compute,
  the upper bound).

Topology: DP+PP (2-stage heterogeneous pipeline x DP) when >= 2 chips are
attached, pure DP on a single chip — the emitted JSON names the layout it
actually ran.

A FedAvg round-time line rides in ``secondary`` too: one timed
``make_fedavg_round`` on the tutorial_1a workload (N=10, C=0.1, B=100,
E=1, lr=0.01, seed=10 — the reference's wall-time-accounted FedAvg round,
``lab/tutorial_1a/hfl_complete.py:294,373``), the second metric
BASELINE.json tracks.

Driver contract: print ONE JSON line with at least
``{"metric", "value", "unit", "vs_baseline"}``.  Extra self-describing
fields: ``input``, ``data`` (real vs synthetic CIFAR), ``topology``,
``chip``, ``mfu``, ``achieved_tflops_per_chip``, ``secondary`` (list: the
streaming, fixed-batch, and FedAvg runs).  If the TPU tunnel is
unreachable the device probe times out and ONE JSON line with an
``error`` field is printed instead of hanging the driver.

**Resilience**: a failed jax backend init is sticky in-process, and the
tunnel has flaked at capture time before (round 4 recorded ``value: 0.0``
for a run whose builder-side numbers were fine).  So the accelerator path
runs the whole bench in FRESH CHILD SUBPROCESSES with retries + backoff
(default 3 attempts, 60/120 s backoff — worst case ~15 min on a dead
tunnel): the parent re-execs this file with ``DDL25_BENCH_CHILD=1``,
forwards the child's stderr, and prints the first JSON line that carries
no ``error``.  Only after exhausting attempts does it emit the last error
line.  CPU runs (``--cpu`` / ``--force-cpu-devices``) skip the wrapper.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

import jax


# failure reason codes for per-attempt telemetry (satellite: classify
# retry failures instead of shipping a raw error string)
REASON_DEVICE = "device_unreachable"
REASON_COMPILE = "compile_error"
REASON_RUNTIME = "runtime_error"
REASON_STALLED = "stalled"

_DEVICE_MARKERS = (
    "accelerator unreachable", "device init timed out", "unavailable",
    "deadline_exceeded", "failed to connect", "connection", "tunnel",
    "no devices", "backend 'tpu' failed to initialize",
)
_COMPILE_MARKERS = (
    "compil", "lowering", "mosaic", "hlo", "xla_internal",
    "unimplemented",
)


def classify_failure(error: str | None) -> str:
    """Map an attempt's error string to a coarse reason code, so a
    BENCH_r*.json capture states *what kind* of death occurred without
    anyone grepping raw strings: ``device_unreachable`` (tunnel/backend
    init), ``stalled`` (watchdog/driver timeout killed a wedged run),
    ``compile_error`` (lowering/XLA compilation), ``runtime_error``
    (everything else)."""
    e = (error or "").lower()
    if "exceeded" in e and "killed" in e:
        return REASON_STALLED
    if any(m in e for m in _DEVICE_MARKERS):
        return REASON_DEVICE
    if any(m in e for m in _COMPILE_MARKERS):
        return REASON_COMPILE
    return REASON_RUNTIME


def probe_devices(timeout_s: float, flight_dir: str | None = None):
    """jax.devices() with a timeout: backend init dials the TPU tunnel and
    can block forever when the relay is down — a daemon thread bounds it,
    and a stall watchdog wraps the wait so the r01–r05 failure mode
    (bare ``device init timed out``) now produces a stack-attributed
    ``flight.json`` naming the frame the probe thread is wedged in.

    Returns ``(devices, error, flight_dump_path)``.

    Coverage note: the watchdog (like any Python thread) can only run
    while the probe's native call releases the GIL — true for the
    socket-blocked dead-tunnel case this targets, NOT for init paths
    that spin in native code holding the GIL (observed once with the
    TPU plugin's metadata retry loop, which freezes every thread in the
    process).  That mode is unkillable from inside; the parent driver's
    subprocess timeout reaps it and the retry record classifies it
    ``stalled``.
    """
    import time

    from ddl25spring_tpu.obs import StallWatchdog, flight

    out: dict = {}

    def _probe():
        try:
            out["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — report, don't hang
            out["error"] = f"{type(e).__name__}: {e}"

    flight.annotate(probe_timeout_s=timeout_s)
    t = threading.Thread(target=_probe, daemon=True, name="device-init-probe")
    # the watchdog deadline sits PAST the join timeout: an init that
    # succeeds just under the wire must never race the monitor into
    # recording a stall (which would fail --check-health on a healthy
    # run); on a real wedge the join times out first and the wait loop
    # below spans the margin
    margin = 2.0
    wd = StallWatchdog(
        deadline_s=timeout_s + margin, run_dir=flight_dir,
        name="device-init-probe", source="self",
    )
    with wd:
        t.start()
        t.join(timeout_s)
        if "devices" in out:
            return out["devices"], None, None
        if "error" not in out:
            # wedged, not raised: wait out the margin + a poll so the
            # watchdog takes its thread-stack dump
            deadline = time.perf_counter() + margin + 2 * wd.poll_s + 5.0
            while not wd.fired and time.perf_counter() < deadline:
                time.sleep(0.05)
    err = out.get(
        "error", f"device init timed out after {timeout_s:.0f}s"
    )
    return None, err, wd.dump_path


def attach_parent_telemetry(
    record: dict, failures: list | None, compile_report: dict | None
) -> dict:
    """Merge the retry driver's structured failure records and the
    pre-device compile report into a bench record's ``telemetry`` dict
    (creating it when the child ran without ``--obs-dir``).  The result
    is what makes a dead-device BENCH line machine-diagnosable: the
    errors that killed each attempt AND the compile-time perf facts that
    need no device at all."""
    tel = record.get("telemetry")
    if not isinstance(tel, dict):
        tel = {"enabled": False}
    if failures:
        tel["retry_failures"] = failures
    if compile_report is not None:
        tel["compile_report"] = compile_report
        tel["lint"] = lint_summary(compile_report)
    # runtime-health summary: when the record (or any attempt) carries a
    # flight dump, surface it at telemetry.health so a dead run's BENCH
    # line points straight at its post-mortem artifact
    health = tel.get("health") if isinstance(tel.get("health"), dict) else {}
    dump = record.get("flight_dump") or next(
        (f.get("flight_dump") for f in reversed(failures or [])
         if f.get("flight_dump")), None,
    )
    if dump and "flight_dump" not in health:
        health["flight_dump"] = dump
    if "error" in record:
        health.setdefault("reason", classify_failure(record["error"]))
    if health:
        tel["health"] = health
    record["telemetry"] = tel
    return record


def lint_summary(compile_report: dict) -> dict:
    """Condense the per-strategy hazard findings the compile report
    carries (``ddl25spring_tpu/analysis``) into the BENCH line's lint
    cell: total/unwaived counts, the worst unwaived severity, a count of
    strategies the linter could NOT judge (compile/lint errors — never
    conflated with "clean"), and a per-strategy breakdown — next to the
    compile report so a dead-TPU run still states the judgment, not
    just the inventory."""
    from ddl25spring_tpu.analysis.engine import summarize
    from ddl25spring_tpu.analysis.rules import severity_rank

    per: dict = {}
    worst = None
    total = unwaived = errors = 0
    for name, r in (compile_report.get("strategies") or {}).items():
        if "findings" not in r:
            # a strategy the linter never judged must not read as clean:
            # record WHY (compile error / lint crash) and count it
            err = r.get("lint_error") or r.get("error")
            if err is not None:
                errors += 1
                per[name] = {"error": str(err)}
            continue
        s = summarize(r["findings"])
        per[name] = {k: s[k] for k in ("findings", "unwaived", "worst")}
        total += s["findings"]
        unwaived += s["unwaived"]
        if severity_rank(s["worst"]) > severity_rank(worst):
            worst = s["worst"]
    return {
        "findings": total,
        "unwaived": unwaived,
        "worst": worst,
        "errors": errors,
        "per_strategy": per,
    }


def run_with_retries(
    argv,
    attempts: int,
    child_timeout_s: float,
    compile_report: dict | None = None,
) -> None:
    """Re-exec the bench in fresh subprocesses until one prints a JSON
    line without an ``error`` field.  Fresh processes because a failed
    jax TPU backend init is sticky: once ``jax.devices()`` has raised,
    every later call in the same interpreter raises immediately, so
    in-process retry can never recover from a transient tunnel outage.

    Every failed attempt emits one structured JSONL record to stderr
    (``{"record": "bench_retry_failure", attempt, error, reason,
    backoff_s, wall_s, rc}`` — ``reason`` is the coarse
    :func:`classify_failure` code, and ``flight_dump`` rides along when
    the child took a post-mortem dump) and the accumulated records ride
    the FINAL printed
    line's ``telemetry.retry_failures`` — so a BENCH_r*.json capture of a
    flaky/dead tunnel carries its own diagnosis instead of a bare 0.0
    (the r01–r05 failure mode).  ``compile_report`` (computed by the
    parent BEFORE any device contact) rides ``telemetry.compile_report``
    on the same line, success or failure."""
    import subprocess
    import time

    backoff = (60.0, 120.0)
    last: dict = {}
    failures: list[dict] = []
    for i in range(attempts):
        if i:
            delay = backoff[min(i - 1, len(backoff) - 1)]
            time.sleep(delay)
        env = dict(os.environ, DDL25_BENCH_CHILD="1")
        t0 = time.perf_counter()
        rc = None
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), *argv],
                env=env, capture_output=True, text=True,
                timeout=child_timeout_s,
            )
        except subprocess.TimeoutExpired as e:
            # probe passed but the run wedged (tunnel died mid-bench):
            # kill and retry — a hang must not take the driver with it
            sys.stderr.write((e.stderr or b"").decode("utf-8", "replace")
                             if isinstance(e.stderr, bytes)
                             else (e.stderr or ""))
            err = (f"attempt {i + 1}: bench subprocess exceeded "
                   f"{child_timeout_s:.0f}s and was killed")
            last = {
                "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
                "value": 0.0, "unit": "samples/sec/chip",
                "vs_baseline": 0.0,
                "error": err,
            }
            parsed = None
        else:
            rc = r.returncode
            sys.stderr.write(r.stderr)
            # only dict lines are bench records; a stray printable (bare
            # number, quoted string) must not crash the driver
            from ddl25spring_tpu.obs.compile_report import last_json_dict_line

            parsed = last_json_dict_line(r.stdout)
            if parsed is not None and "error" not in parsed:
                print(json.dumps(
                    attach_parent_telemetry(parsed, failures, compile_report)
                ))
                return
            last = parsed or {
                "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
                "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
                "error": f"attempt {i + 1}: bench subprocess exited "
                         f"rc={rc} with no JSON line",
            }
        # structured JSONL failure record (replaces the old bare print):
        # machine-diagnosable on stderr now, and carried in the final
        # line's telemetry below
        next_backoff = (
            backoff[min(i, len(backoff) - 1)] if i + 1 < attempts else 0.0
        )
        rec = {
            "record": "bench_retry_failure",
            "attempt": i + 1,
            "attempts_left": attempts - i - 1,
            "error": str(last.get("error", "unknown")),
            "reason": classify_failure(str(last.get("error", "unknown"))),
            "rc": rc,
            "wall_s": round(time.perf_counter() - t0, 3),
            "backoff_s": next_backoff,
            **(
                {"flight_dump": last["flight_dump"]}
                if isinstance(last, dict) and last.get("flight_dump")
                else {}
            ),
        }
        failures.append(rec)
        print(json.dumps(rec), file=sys.stderr)
    last.setdefault("error", "unknown")
    last["error"] = f"exhausted {attempts} attempts; last: {last['error']}"
    print(json.dumps(attach_parent_telemetry(last, failures, compile_report)))


def fedavg_secondary(n_rounds: int = 10) -> dict:
    """Timed FedAvg round on the tutorial_1a workload — the second metric
    BASELINE.json names (reference wall-time segmentation:
    ``lab/tutorial_1a/hfl_complete.py:294,373``).  N=10 C=0.1 B=100 E=1
    lr=0.01 seed=10, the solved-homework golden config
    (``lab/series01.ipynb`` cell 20).  One warmup round compiles the
    vmapped client program; the timed window is ``n_rounds`` full server
    rounds (host-side client sampling + device-side local epochs +
    weighted aggregation), reported as ms/round.

    ``DDL25_BENCH_NTRAIN`` shrinks the MNIST split for CPU smoke runs
    (the single-core XLA CPU backend takes minutes on the full 60k; the
    TPU headline always uses the full split).  Any failure here must not
    cost the already-measured primary metric: the caller degrades this
    entry to an error note instead of letting the exception escape (and
    burn the retry wrapper's attempts)."""
    import time

    from ddl25spring_tpu.data.mnist import load_mnist
    from ddl25spring_tpu.fl import FedAvgServer

    n_train = int(os.environ.get("DDL25_BENCH_NTRAIN", "0")) or 60_000
    server = FedAvgServer(
        nr_clients=10, client_fraction=0.1, batch_size=100,
        nr_local_epochs=1, lr=0.01, seed=10,
        data=load_mnist(n_train=n_train),
    )
    server.round(0)  # compile
    jax.block_until_ready(jax.tree.leaves(server.params))
    t0 = time.perf_counter()
    for r in range(1, n_rounds + 1):
        server.round(r)
    jax.block_until_ready(jax.tree.leaves(server.params))
    ms = (time.perf_counter() - t0) / n_rounds * 1e3
    return {
        "metric": "fedavg_round_ms",
        "value": round(ms, 2),
        "unit": "ms/round",
        "n_train": n_train,
        "note": "tutorial_1a FedAvg N=10 C=0.1 B=100 E=1; one vmapped "
                "server round incl. host-side sampling",
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local testing; the axon TPU "
                         "plugin is registered at interpreter start)")
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N",
                    help="simulate an N-device CPU mesh (implies --cpu)")
    ap.add_argument("--per-chip-batch", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--microbatches", type=int, default=2)
    ap.add_argument("--scan-steps", type=int, default=0, metavar="K",
                    help="train steps fused per dispatch in the primary "
                         "mode (0 = auto: largest divisor of "
                         "batches_per_epoch <= 16)")
    ap.add_argument("--probe-timeout", type=float, default=240.0)
    ap.add_argument("--attempts", type=int, default=3,
                    help="fresh-subprocess retries for the accelerator "
                         "path (the TPU tunnel can flake; backend-init "
                         "failure is sticky in-process)")
    ap.add_argument("--child-timeout", type=float, default=2400.0,
                    help="overall wall-clock bound per bench subprocess")
    ap.add_argument("--no-fedavg", action="store_true",
                    help="skip the FedAvg round-time secondary metric")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="enable run telemetry (ddl25spring_tpu.obs) and "
                         "write metrics.jsonl / counters.json / trace.json "
                         "there; summarize with tools/obs_report.py")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU smoke run with telemetry: single-device DP, "
                         "tiny dataset/steps, no FedAvg; writes "
                         "--obs-dir (default runs/bench_smoke)")
    ap.add_argument("--compile-report", action="store_true",
                    help="force the pre-device compile report on CPU runs "
                         "(the accelerator path always computes it; see "
                         "ddl25spring_tpu/obs/compile_report.py)")
    ap.add_argument("--no-compile-report", action="store_true",
                    help="skip the compile report on the accelerator path")
    args = ap.parse_args(argv)

    # 0/negative would skip the retry loop entirely and print a
    # contract-violating `last={}` line with only an `error` key
    if args.attempts < 1:
        print(f"clamping --attempts {args.attempts} -> 1", file=sys.stderr)
        args.attempts = 1

    if args.smoke:
        args.cpu = True
        args.no_fedavg = True
        args.per_chip_batch = min(args.per_chip_batch, 64)
        args.steps = min(args.steps, 8)
        args.warmup = min(args.warmup, 2)
        args.scan_steps = args.scan_steps or 1
        args.obs_dir = args.obs_dir or "runs/bench_smoke"
        os.environ.setdefault("DDL25_BENCH_NTRAIN", "512")

    on_cpu = args.cpu or args.force_cpu_devices
    is_child = os.environ.get("DDL25_BENCH_CHILD") == "1"

    # compile-time analytics BEFORE any device contact: lowered on a fake
    # CPU mesh in a fresh subprocess, so the report exists even when the
    # TPU tunnel is dead (the r01-r05 failure mode) and never pollutes
    # this process's backend state.  Parent path always; CPU runs opt in.
    compile_report = None
    # the child never recomputes: the parent did, once, and attaches it
    want_cr = not is_child and (
        args.compile_report or (not on_cpu and not args.no_compile_report)
    )
    if want_cr:
        from ddl25spring_tpu.obs.compile_report import (
            bench_compile_report_subprocess,
            write_compile_report,
        )

        compile_report = bench_compile_report_subprocess()
        if args.obs_dir:
            write_compile_report(args.obs_dir, compile_report)

    if not on_cpu and not is_child:
        run_with_retries(
            argv if argv is not None else sys.argv[1:],
            args.attempts, args.child_timeout,
            compile_report=compile_report,
        )
        return

    if args.force_cpu_devices:
        from ddl25spring_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(args.force_cpu_devices)
    elif args.cpu:
        jax.config.update("jax_platforms", "cpu")

    # arm the crash paths before any device contact: from here on an
    # unhandled exception, SIGTERM, or exit leaves a flight.json behind
    from ddl25spring_tpu.obs import flight

    flight.configure(run_dir=args.obs_dir)
    flight.install()
    flight.annotate(
        driver="bench",
        argv=list(argv if argv is not None else sys.argv[1:]),
    )

    devices, err, probe_dump = probe_devices(
        args.probe_timeout, flight_dir=args.obs_dir
    )
    if devices is None:
        record = {
            "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": f"accelerator unreachable: {err}",
            **({"flight_dump": probe_dump} if probe_dump else {}),
        }
        attach_parent_telemetry(record, None, compile_report)
        print(json.dumps(record), flush=True)
        sys.stderr.flush()
        # a wedged backend init leaves jax's atexit machinery deadlocked
        # on the half-initialized backend (observed on this image: the
        # TPU plugin's metadata retry loop), which would strand this
        # JSON line in a block buffer forever — the r01–r05 silent-child
        # mode.  Everything worth persisting is flushed; exit hard.
        if "timed out" in str(err):
            os._exit(0)
        return

    import time

    from ddl25spring_tpu import obs
    from ddl25spring_tpu.benchmarks import (
        DeviceDataset,
        InputFeed,
        build_resnet_scan_step,
        build_resnet_step,
        report_line,
        timed_run,
    )
    from ddl25spring_tpu.utils.flops import chip_peak_flops, compiled_flops, mfu

    lg = None
    if args.obs_dir:
        # enable BEFORE building the step so the on-device counters are
        # traced in (the flag is read at trace time — obs/state.py)
        obs.enable()
        obs.set_recorder(obs.SpanRecorder(process_name="bench"))
        obs.counters.reset()

    n = len(devices)
    dp, S = (n // 2, 2) if n >= 2 else (1, 1)
    M = args.microbatches if S == 2 else 1
    batch = (args.per_chip_batch * dp * S) // (dp * M) * (dp * M)

    # DDL25_BENCH_NTRAIN: shrink the HBM dataset for CPU smoke runs of the
    # full bench flow (the TPU headline always uses the full 50k split)
    n_train = int(os.environ.get("DDL25_BENCH_NTRAIN", "0")) or None
    ds = DeviceDataset(batch, n_train=n_train)
    # scan fusion is TPU-only by default: lax.scan over a conv body is
    # pathologically slow on the XLA CPU backend (measured 55x — see
    # build_resnet_scan_step's docstring), so CPU smoke runs take K=1
    on_tpu = devices[0].platform == "tpu"
    K = args.scan_steps or (
        max(k for k in range(1, 17) if ds.batches_per_epoch % k == 0)
        if on_tpu else 1
    )
    with obs.span("build_step", scan_steps=K):
        if K > 1:
            multi, step, params, opt_state, meta = build_resnet_scan_step(
                devices, dp, S, M, batch, K, ds.n
            )
        else:
            multi = None
            step, params, opt_state, meta = build_resnet_step(
                devices, dp, S, M, batch
            )
    n_chips = meta["n_chips"]
    flight.annotate(
        layout=meta["layout"], topology=meta["topology"],
        n_chips=n_chips, batch=batch, scan_steps=K,
        rng_seed=ds.seed,  # the DeviceDataset epoch-shuffle key
    )

    if args.obs_dir:
        lg = obs.MetricsLogger(
            args.obs_dir,
            meta=obs.run_metadata(
                mesh=meta["mesh"],
                layout=meta["layout"],
                topology=meta["topology"],
                n_chips=n_chips,
                batch=batch,
                num_stages=meta["num_stages"],
                num_microbatches=meta["num_microbatches"],
                scan_steps=K,
                input_mode=ds.input_mode,
            ),
        )

    # --- primary: HBM shuffle; K steps fused per dispatch on TPU -----------
    if multi is not None:
        def feed_scan():
            return (ds.x, ds.y) + ds.scan_window(K)

        def multi_packed(params, opt_state, packed):
            return multi(params, opt_state, *packed)

        # warmup MUST be >= 2 dispatches: the first call compiles, and the
        # SECOND recompiles once more (the first call's outputs come back
        # with TPU-chosen layouts that differ from the freshly-initialized
        # input arrays; the layout fix point is reached after one round).
        # With a 1-dispatch warmup that ~24 s recompile lands in the timed
        # window and craters the reported number ~25x (measured).
        n_disp = max(3, args.steps // K)
        dt, params, opt_state = timed_run(
            multi_packed, params, opt_state, feed_scan, n_disp,
            max(2, args.warmup // 2),
            logger=lg, label="hbm-scan", samples_per_step=batch,
            steps_per_call=K,
        )
        sps_chip = n_disp * K * batch / dt / n_chips
        dt_per_step = dt / (n_disp * K)

        # --- secondary 0: same input, one step per dispatch ----------------
        # reset the stream counter: scan_window and feed interpret it at
        # different granularities (K-windows vs single batches), so the
        # single-dispatch run starts a fresh epoch instead of interleaving
        ds._i = 0
        dt0, params, opt_state = timed_run(
            step, params, opt_state, ds.feed, args.steps, args.warmup,
            logger=lg, label="hbm-single", samples_per_step=batch,
        )
        sps_chip_single = args.steps * batch / dt0 / n_chips
    else:
        dt, params, opt_state = timed_run(
            step, params, opt_state, ds.feed, args.steps, args.warmup,
            logger=lg, label="hbm-single", samples_per_step=batch,
        )
        sps_chip = args.steps * batch / dt / n_chips
        dt_per_step = dt / args.steps
        sps_chip_single = None

    # --- secondary 1: host streaming through the native C++ loader ---------
    # Constructed only now, and warmed past the prefetch queue's capacity
    # (depth + in-flight workers), so the timed window starts with an empty
    # queue and measures steady-state producer-bound throughput — a queue
    # pre-filled during the primary run would hand the timed loop several
    # batches for free and inflate the number.
    workers = max(2, (os.cpu_count() or 4) // 2)
    depth = 6
    feed = InputFeed(batch, stream=True, workers=workers, prefetch_depth=depth)
    stream_warm = args.warmup + depth + workers
    dt_s, params, opt_state = timed_run(
        step, params, opt_state, feed.feed, args.steps, stream_warm,
        logger=lg, label="stream", samples_per_step=batch,
    )
    sps_chip_stream = args.steps * batch / dt_s / n_chips

    # --- secondary 2: one fixed device-resident batch (compute bound) ------
    dt2, params, opt_state = timed_run(
        step, params, opt_state, feed.feed_fixed, args.steps, args.warmup,
        logger=lg, label="fixed-batch", samples_per_step=batch,
    )
    sps_chip_fixed = args.steps * batch / dt2 / n_chips

    # measure the host->device link so the streaming line explains itself
    import numpy as np

    # median of 3 transfers: one TCP hiccup on the tunneled link must not
    # skew the self-describing bandwidth number
    buf = np.zeros(4 * 1024 * 1024, np.uint8)
    jax.device_put(buf[:1024], devices[0]).block_until_ready()
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(buf, devices[0]).block_until_ready()
        rates.append(4.0 / (time.perf_counter() - t0))
    h2d_mib_s = sorted(rates)[1]

    # --- secondary 3: FedAvg round time (BASELINE.json's second metric) ----
    # guarded: a FedAvg-side failure must degrade to an error note, not
    # discard the already-measured primary metric (and trigger retries)
    if args.no_fedavg:
        fedavg_line = []
    else:
        try:
            fedavg_line = [fedavg_secondary()]
        except Exception as e:  # noqa: BLE001 — keep the primary metric
            fedavg_line = [{
                "metric": "fedavg_round_ms", "value": None,
                "unit": "ms/round",
                "note": f"failed: {type(e).__name__}: {e}",
            }]

    flops_step = compiled_flops(step, params, opt_state, feed.fixed)
    achieved_tf, frac = mfu(flops_step, dt_per_step, n_chips, meta["device"])
    peak = chip_peak_flops(meta["device"])

    telemetry = {"enabled": False}
    if compile_report is not None:
        telemetry["compile_report"] = compile_report
    if lg is not None:
        # supplementary header: facts only known after the timed phases
        # (summarize_run merges header records in order)
        lg.log(
            record="header",
            flops_per_step=flops_step,
            peak_flops_per_chip=peak,
            h2d_mib_per_s=h2d_mib_s,
        )
        lg.close()
        obs.counters.save(args.obs_dir)
        obs.get_recorder().save(os.path.join(args.obs_dir, "trace.json"))
        from ddl25spring_tpu.obs.report import summarize_run

        s = summarize_run(args.obs_dir)
        telemetry = {
            "enabled": True,
            **(
                {"compile_report": compile_report}
                if compile_report is not None else {}
            ),
            "run_dir": args.obs_dir,
            "bubble_fraction": s.get("bubble_fraction"),
            "tick_interval_s_p50": s.get("tick_interval_s_p50"),
            "phases": {
                name: {
                    k: ph.get(k)
                    for k in (
                        "steps",
                        "step_s_p50",
                        "step_s_p95",
                        "samples_per_sec_per_chip_p50",
                        "mfu",
                    )
                    if ph.get(k) is not None
                }
                for name, ph in s.get("phases", {}).items()
            },
        }

    # runtime-health cell: sentinel state + flight-recorder facts, and a
    # flight.json in the run dir so obs_report's Health section (and any
    # post-mortem) reads the same artifact a crash would have left
    from ddl25spring_tpu.obs import sentinels as _sentinels

    _snap = obs.flight.snapshot()
    health = {
        "sentinels": _sentinels.enabled(),
        "policy": _sentinels.policy(),
        # cumulative counter, not a ring recount: a violation hundreds
        # of steps back must still show after the ring evicted it
        "violations": _snap["violations"],
        "stalls": _snap["stalls"],
        "flight_records": _snap["recorded"],
    }
    if args.obs_dir:
        health["flight_dump"] = obs.flight.dump(reason="end_of_run")
    telemetry["health"] = health

    primary_mode = (
        f"{ds.input_mode}-scan{K}" if multi is not None else ds.input_mode
    )
    single_line = [
        {
            "input": ds.input_mode,
            "value": round(sps_chip_single, 1),
            "unit": "samples/sec/chip",
            "note": "one step per dispatch; the delta vs the primary "
                    "is the measured per-dispatch tunnel overhead",
        },
    ] if sps_chip_single is not None else []
    print(report_line(
        meta["layout"], sps_chip, primary_mode, frac, achieved_tf,
        data=ds.provenance,
        topology=meta["topology"],
        chip=f"{meta['device'].device_kind} x{n_chips}",
        flops_per_step=flops_step,
        scan_steps=K,
        peak_tflops_per_chip=peak / 1e12 if peak else None,
        h2d_mib_per_s=round(h2d_mib_s, 1),
        telemetry=telemetry,
        secondary=single_line + [
            {
                "input": feed.input_mode,
                "value": round(sps_chip_stream, 1),
                "unit": "samples/sec/chip",
                # only claim link-bound streaming when the native loader
                # actually streamed; on NativeLoaderUnavailable this run
                # degraded to the fixed batch and says so via input_mode
                **({"note": "bounded by the tunneled host->device link "
                            f"(~{h2d_mib_s:.0f} MiB/s here; GiB/s on a "
                            "TPU VM)"}
                   if feed.streaming else
                   {"note": "native loader unavailable; fell back to the "
                            "fixed device-resident batch"}),
            },
            {
                "input": "fixed-device-batch",
                "value": round(sps_chip_fixed, 1),
                "unit": "samples/sec/chip",
            },
        ] + fedavg_line,
    ))

    feed.close()


if __name__ == "__main__":
    main()
