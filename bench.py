"""Headline benchmark: the BASELINE.json north-star config.

North star (`BASELINE.json`): DP+PP ResNet-18/CIFAR-10 via the `run-b2.sh`
path at >= 5,000 samples/sec/chip.  The train step is built by
``ddl25spring_tpu.benchmarks.build_resnet_step`` — the same builder
`lab/s01_b2_dp_pp.py` uses, so the bench cannot drift from what run-b2.sh
runs.  Normalization happens device-side inside the jitted step.

**Primary input mode: HBM-resident dataset + on-device epoch shuffle,
K train steps fused per dispatch** (``build_resnet_scan_step``) — the
whole 147 MiB uint8 train split lives on device; the compiled program
draws K fresh, disjoint, epoch-permuted batches and runs K train steps
per Python dispatch (a ``lax.scan`` over the same inner step).  Real
input semantics (every sample once per epoch) with the ~4 ms/dispatch
tunnel round-trip amortized to noise — the idiomatic TPU input design:
data in HBM, input pipeline inside the program, host only ticks epochs.
Three secondary lines keep the bench honest:

- ``hbm-resident-shuffle``: the same input, ONE step per dispatch
  (rounds 1-3's primary; its delta vs the scan line is the measured
  dispatch overhead).

- ``native-stream-uint8``: the C++ prefetcher pushes a fresh batch across
  the host->device link every step.  On this image that link is a network
  tunnel measured at ~10-20 MiB/s (vs multi-GiB/s PCIe on a real TPU VM),
  which bounds ANY host-streaming input at ~3-6k samples/s; the measured
  link bandwidth is emitted as ``h2d_mib_per_s`` so the number is
  self-describing.
- ``fixed-device-batch``: one device-resident batch re-fed (pure compute,
  the upper bound).

Topology: DP+PP (2-stage heterogeneous pipeline x DP) when >= 2 chips are
attached, pure DP on a single chip — the emitted JSON names the layout it
actually ran.

A FedAvg round-time line rides in ``secondary`` too: one timed
``make_fedavg_round`` on the tutorial_1a workload (N=10, C=0.1, B=100,
E=1, lr=0.01, seed=10 — the reference's wall-time-accounted FedAvg round,
``lab/tutorial_1a/hfl_complete.py:294,373``), the second metric
BASELINE.json tracks.

Driver contract: print ONE JSON line with at least
``{"metric", "value", "unit", "vs_baseline"}``.  Extra self-describing
fields: ``input``, ``data`` (real vs synthetic CIFAR), ``topology``,
``chip``, ``mfu``, ``achieved_tflops_per_chip``, ``secondary`` (list: the
streaming, fixed-batch, and FedAvg runs).  If the TPU tunnel is
unreachable the device probe times out and ONE JSON line with an
``error`` field is printed instead of hanging the driver.

**Resilience**: a failed jax backend init is sticky in-process, and the
tunnel has flaked at capture time before (round 4 recorded ``value: 0.0``
for a run whose builder-side numbers were fine).  So the accelerator path
runs the whole bench in FRESH CHILD SUBPROCESSES with retries + backoff
(default 3 attempts, 60/120 s backoff — worst case ~15 min on a dead
tunnel): the parent re-execs this file with ``DDL25_BENCH_CHILD=1``,
forwards the child's stderr, and prints the first JSON line that carries
no ``error``.  Only after exhausting attempts does it emit the last error
line.  CPU runs (``--cpu`` / ``--force-cpu-devices``) skip the wrapper.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

import jax


# failure reason codes for per-attempt telemetry (satellite: classify
# retry failures instead of shipping a raw error string)
REASON_DEVICE = "device_unreachable"
REASON_COMPILE = "compile_error"
REASON_RUNTIME = "runtime_error"
REASON_STALLED = "stalled"
REASON_PREEMPTED = "preempted"

_DEVICE_MARKERS = (
    "accelerator unreachable", "device init timed out", "unavailable",
    "deadline_exceeded", "failed to connect", "connection", "tunnel",
    "no devices", "backend 'tpu' failed to initialize", "device loss",
)
_COMPILE_MARKERS = (
    "compil", "lowering", "mosaic", "hlo", "xla_internal",
    "unimplemented",
)
# external-termination exit statuses: SIGTERM as the scheduler's
# preemption notice (subprocess reports -15, a shell-style wrapper 143)
# and SIGKILL as its hard deadline / the OOM killer (-9 / 137)
_PREEMPT_RCS = (143, -15, 137, -9)


def classify_failure(error: str | None, rc: int | None = None) -> str:
    """Map an attempt's error string (+ exit status) to a coarse reason
    code, so a BENCH_r*.json capture states *what kind* of death
    occurred without anyone grepping raw strings: ``preempted`` (killed
    from outside — SIGTERM/143, SIGKILL; the auto-resume path),
    ``device_unreachable`` (tunnel/backend init/device loss),
    ``stalled`` (watchdog/driver timeout killed a wedged run),
    ``compile_error`` (lowering/XLA compilation), ``runtime_error``
    (everything else)."""
    e = (error or "").lower()
    if rc in _PREEMPT_RCS or "preempt" in e or "sigterm" in e:
        return REASON_PREEMPTED
    if "exceeded" in e and "killed" in e:
        return REASON_STALLED
    if any(m in e for m in _DEVICE_MARKERS):
        return REASON_DEVICE
    if any(m in e for m in _COMPILE_MARKERS):
        return REASON_COMPILE
    return REASON_RUNTIME


def probe_devices(timeout_s: float, flight_dir: str | None = None):
    """jax.devices() with a timeout: backend init dials the TPU tunnel and
    can block forever when the relay is down — a daemon thread bounds it,
    and a stall watchdog wraps the wait so the r01–r05 failure mode
    (bare ``device init timed out``) now produces a stack-attributed
    ``flight.json`` naming the frame the probe thread is wedged in.

    Returns ``(devices, error, flight_dump_path)``.

    Coverage note: the watchdog (like any Python thread) can only run
    while the probe's native call releases the GIL — true for the
    socket-blocked dead-tunnel case this targets, NOT for init paths
    that spin in native code holding the GIL (observed once with the
    TPU plugin's metadata retry loop, which freezes every thread in the
    process).  That mode is unkillable from inside; the parent driver's
    subprocess timeout reaps it and the retry record classifies it
    ``stalled``.
    """
    import time

    from ddl25spring_tpu.obs import StallWatchdog, flight

    out: dict = {}

    def _probe():
        try:
            out["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — report, don't hang
            out["error"] = f"{type(e).__name__}: {e}"

    flight.annotate(probe_timeout_s=timeout_s)
    t = threading.Thread(target=_probe, daemon=True, name="device-init-probe")
    # the watchdog deadline sits PAST the join timeout: an init that
    # succeeds just under the wire must never race the monitor into
    # recording a stall (which would fail --check-health on a healthy
    # run); on a real wedge the join times out first and the wait loop
    # below spans the margin
    margin = 2.0
    wd = StallWatchdog(
        deadline_s=timeout_s + margin, run_dir=flight_dir,
        name="device-init-probe", source="self",
    )
    with wd:
        t.start()
        t.join(timeout_s)
        if "devices" in out:
            return out["devices"], None, None
        if "error" not in out:
            # wedged, not raised: wait out the margin + a poll so the
            # watchdog takes its thread-stack dump
            deadline = time.perf_counter() + margin + 2 * wd.poll_s + 5.0
            while not wd.fired and time.perf_counter() < deadline:
                time.sleep(0.05)
    err = out.get(
        "error", f"device init timed out after {timeout_s:.0f}s"
    )
    return None, err, wd.dump_path


def attach_parent_telemetry(
    record: dict, failures: list | None, compile_report: dict | None,
    resume: dict | None = None,
) -> dict:
    """Merge the retry driver's structured failure records and the
    pre-device compile report into a bench record's ``telemetry`` dict
    (creating it when the child ran without ``--obs-dir``).  The result
    is what makes a dead-device BENCH line machine-diagnosable: the
    errors that killed each attempt AND the compile-time perf facts that
    need no device at all.  ``resume`` (the retry driver's recovery
    summary — resume count, total steps lost to replay) merges into the
    child-reported ``telemetry.resume`` cell."""
    tel = record.get("telemetry")
    if not isinstance(tel, dict):
        tel = {"enabled": False}
    if failures:
        tel["retry_failures"] = failures
    if resume:
        child_resume = tel.get("resume")
        tel["resume"] = {
            **(child_resume if isinstance(child_resume, dict) else {}),
            **resume,
        }
    if compile_report is not None:
        # the child's measured perf cell prices the compile report's
        # H001 overlap complaints: the linter's "sync collective, no
        # overlap" findings on the bench workload gain the strategy's
        # measured exposed-comms time (ddl25spring_tpu/analysis/engine.
        # attach_measured_costs) before the report rides the line
        perf = tel.get("perf")
        if isinstance(perf, dict) and "error" not in perf:
            from ddl25spring_tpu.analysis.engine import (
                attach_measured_costs,
            )

            for name, r in (compile_report.get("strategies") or {}).items():
                if name.startswith("bench") and r.get("findings"):
                    attach_measured_costs(r["findings"], perf)
        tel["compile_report"] = compile_report
        tel["lint"] = lint_summary(compile_report)
    # runtime-health summary: when the record (or any attempt) carries a
    # flight dump, surface it at telemetry.health so a dead run's BENCH
    # line points straight at its post-mortem artifact
    health = tel.get("health") if isinstance(tel.get("health"), dict) else {}
    dump = record.get("flight_dump") or next(
        (f.get("flight_dump") for f in reversed(failures or [])
         if f.get("flight_dump")), None,
    )
    if dump and "flight_dump" not in health:
        health["flight_dump"] = dump
    if "error" in record:
        health.setdefault("reason", classify_failure(record["error"]))
    if health:
        tel["health"] = health
    record["telemetry"] = tel
    return record


def lint_summary(compile_report: dict) -> dict:
    """Condense the per-strategy hazard findings the compile report
    carries (``ddl25spring_tpu/analysis``) into the BENCH line's lint
    cell: total/unwaived counts, the worst unwaived severity, a count of
    strategies the linter could NOT judge (compile/lint errors — never
    conflated with "clean"), and a per-strategy breakdown — next to the
    compile report so a dead-TPU run still states the judgment, not
    just the inventory."""
    from ddl25spring_tpu.analysis.engine import summarize
    from ddl25spring_tpu.analysis.rules import severity_rank

    per: dict = {}
    worst = None
    total = unwaived = errors = 0
    for name, r in (compile_report.get("strategies") or {}).items():
        if "findings" not in r:
            # a strategy the linter never judged must not read as clean:
            # record WHY (compile error / lint crash) and count it
            err = r.get("lint_error") or r.get("error")
            if err is not None:
                errors += 1
                per[name] = {"error": str(err)}
            continue
        s = summarize(r["findings"])
        per[name] = {k: s[k] for k in ("findings", "unwaived", "worst")}
        total += s["findings"]
        unwaived += s["unwaived"]
        if severity_rank(s["worst"]) > severity_rank(worst):
            worst = s["worst"]
    return {
        "findings": total,
        "unwaived": unwaived,
        "worst": worst,
        "errors": errors,
        "per_strategy": per,
    }


def _flight_dump_facts(
    flight_dump: str | None,
) -> tuple[float | None, int | None]:
    """One parse of a dead child's flight.json -> ``(dumped_at_unix,
    last_resumable_step)`` — a single read so the staleness stamp and
    the step it vouches for can never come from two different dumps
    (the file is replaced by atomic rename between attempts).

    - the stamp is the retry driver's staleness check: a dump already
      billed for one death must not be billed again when a later
      attempt dies without managing a dump of its own;
    - the step is the highest CHECKPOINTABLE index recorded.  Only the
      checkpoint-hooked phase's dispatch records count (``timed_run``
      marks them ``resumable``): their indices share units with the
      durable checkpoint steps, while secondary phases re-count from 0
      in single-step units and the sentinel callbacks' per-process
      counter includes warmup — either would corrupt the arithmetic."""
    if not flight_dump:
        return None, None
    try:
        with open(flight_dump) as f:
            doc = json.load(f)
        steps = [
            r["step"] for r in doc.get("records", [])
            if r.get("kind") == "step" and r.get("resumable")
            and isinstance(r.get("step"), int)
        ]
        return doc.get("dumped_at_unix"), max(steps) if steps else None
    except (OSError, ValueError, KeyError):
        return None, None


def _flight_last_step(flight_dump: str | None) -> int | None:
    """See :func:`_flight_dump_facts` (the resumed child's
    steps-replayed annotation needs only the step half)."""
    return _flight_dump_facts(flight_dump)[1]


def run_with_retries(
    argv,
    attempts: int,
    child_timeout_s: float,
    compile_report: dict | None = None,
    ckpt_dir: str | None = None,
    flight_path: str | None = None,
    ledger_path: str | None = None,
) -> None:
    """Re-exec the bench in fresh subprocesses until one prints a JSON
    line without an ``error`` field.  Fresh processes because a failed
    jax TPU backend init is sticky: once ``jax.devices()`` has raised,
    every later call in the same interpreter raises immediately, so
    in-process retry can never recover from a transient tunnel outage.

    **Auto-resume** (``ckpt_dir``): when a failed attempt left a durable
    checkpoint behind (the ft/ autosave layer commits steps by atomic
    rename — a truncated save is invisible), the next attempt is
    relaunched with ``--resume-from <ckpt_dir>`` instead of restarting
    from scratch: the child restores params/opt-state/data-cursor/rng
    and continues from the step after the durable one.  Preempted
    attempts (SIGTERM/SIGKILL — chaos or a real scheduler) skip the
    backoff entirely: the device was never the problem.

    Every failed attempt emits one structured JSONL record to stderr
    (``{"record": "bench_retry_failure", attempt, error, reason,
    backoff_s, wall_s, rc}`` — ``reason`` is the coarse
    :func:`classify_failure` code; ``flight_dump`` rides along when the
    child took a post-mortem dump, ``resumed_from_step`` when the
    attempt itself was a resume, and ``chaos`` when ``DDL25_CHAOS`` is
    armed) and the accumulated records ride the FINAL printed line's
    ``telemetry.retry_failures`` — so a BENCH_r*.json capture of a
    flaky/dead tunnel carries its own diagnosis instead of a bare 0.0
    (the r01–r05 failure mode).  ``telemetry.resume`` totals the
    recovery story: resume count and steps lost to replay (the gap
    between each death's last flight-recorded step and the durable
    checkpoint it restarted from).  ``compile_report`` (computed by the
    parent BEFORE any device contact) rides ``telemetry.compile_report``
    on the same line, success or failure.

    **Run lineage** (graft-goodput, PR 20): the parent mints ONE
    ``lineage_id`` here and hands it to every attempt through the
    sanctioned env boundary (``DDL25_LINEAGE`` / ``DDL25_ATTEMPT``) —
    all attempts of one retry loop, resumed or fresh, are the same
    lineage, and each stamps it into its flight meta and timeline
    header.  Each failure record carries the lineage id plus the dead
    attempt's goodput facts priced off its flight dump (the next
    attempt overwrites the file, so failure time is the only chance);
    after the loop, :func:`ddl25spring_tpu.obs.goodput.merge_lineage`
    folds every attempt onto one wall axis, rewrites the run's
    ``goodput.json`` with the lineage view, appends the
    ``record:"goodput"`` ledger row, and rides ``telemetry.goodput``
    on the final line."""
    import subprocess
    import time

    from ddl25spring_tpu.ft.manifest import latest_durable_step
    from ddl25spring_tpu.obs import goodput as goodput_mod

    backoff = (60.0, 120.0)
    chaos_spec = os.environ.get("DDL25_CHAOS")
    lineage_id = goodput_mod.mint_lineage_id()
    run_dir = os.path.dirname(flight_path) if flight_path else None

    def _finish(record: dict) -> dict:
        """Fold the lineage goodput view into the final line (and the
        run dir's goodput.json / the ledger) — best-effort: goodput
        accounting must never cost the bench line itself."""
        try:
            final = (
                goodput_mod.read_run_goodput(run_dir) if run_dir else None
            )
            if isinstance(final, dict) and final.get("scope") != (
                "train_attempt"
            ):
                final = None  # stale serve/lineage doc, not this child's
            merged = goodput_mod.merge_lineage(
                final, failures, lineage_id=lineage_id
            )
            if merged is None:
                return record
            if run_dir:
                goodput_mod.write_run_goodput(merged, run_dir)
            tel = record.setdefault("telemetry", {"enabled": False})
            if isinstance(tel, dict):
                tel["goodput"] = goodput_mod.goodput_cell(merged)
            if final is not None and merged.get("strategy"):
                from ddl25spring_tpu.obs import perfscope

                perfscope.append_ledger(
                    goodput_mod.ledger_row(
                        merged,
                        strategy=merged["strategy"],
                        mesh=merged.get("mesh"),
                        host=perfscope.host_fingerprint(),
                    ),
                    ledger_path or perfscope.DEFAULT_LEDGER,
                )
        except Exception as e:  # noqa: BLE001 — observability only
            print(f"lineage goodput merge failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
        return record

    last: dict = {}
    failures: list[dict] = []
    resume_step: int | None = None  # durable step the NEXT attempt resumes from
    resume_count = 0
    steps_lost = 0
    seen_dump_stamp: float | None = None
    delay = 0.0
    for i in range(attempts):
        if i and delay:
            time.sleep(delay)
        child_argv = list(argv)
        if resume_step is not None:
            child_argv += ["--resume-from", ckpt_dir]
            resume_count += 1
        env = dict(
            os.environ,
            DDL25_BENCH_CHILD="1",
            **{
                goodput_mod.ENV_LINEAGE: lineage_id,
                goodput_mod.ENV_ATTEMPT: str(i + 1),
            },
        )
        t0 = time.perf_counter()
        rc = None
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), *child_argv],
                env=env, capture_output=True, text=True,
                timeout=child_timeout_s,
            )
        except subprocess.TimeoutExpired as e:
            # probe passed but the run wedged (tunnel died mid-bench):
            # kill and retry — a hang must not take the driver with it
            sys.stderr.write((e.stderr or b"").decode("utf-8", "replace")
                             if isinstance(e.stderr, bytes)
                             else (e.stderr or ""))
            err = (f"attempt {i + 1}: bench subprocess exceeded "
                   f"{child_timeout_s:.0f}s and was killed")
            last = {
                "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
                "value": 0.0, "unit": "samples/sec/chip",
                "vs_baseline": 0.0,
                "error": err,
            }
            parsed = None
        else:
            rc = r.returncode
            sys.stderr.write(r.stderr)
            # only dict lines are bench records; a stray printable (bare
            # number, quoted string) must not crash the driver
            from ddl25spring_tpu.obs.compile_report import last_json_dict_line

            parsed = last_json_dict_line(r.stdout)
            if parsed is not None and "error" not in parsed:
                resume = (
                    {"resumes": resume_count, "total_steps_lost": steps_lost}
                    if resume_count else None
                )
                print(json.dumps(_finish(attach_parent_telemetry(
                    parsed, failures, compile_report, resume=resume
                ))))
                return
            last = parsed or {
                "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
                "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
                "error": f"attempt {i + 1}: bench subprocess exited "
                         f"rc={rc} with no JSON line"
                         + (" (killed by signal"
                            f" {-rc})" if rc is not None and rc < 0 else ""),
            }
        # structured JSONL failure record (replaces the old bare print):
        # machine-diagnosable on stderr now, and carried in the final
        # line's telemetry below
        err_s = str(last.get("error", "unknown"))
        reason = classify_failure(err_s, rc=rc)
        # a SIGTERM'd/SIGKILL'd child prints no JSON line, but its
        # crash handler (or last end_of_run) dumped into the obs dir —
        # the known flight_path covers the records-only death
        flight_dump = (
            last.get("flight_dump") if isinstance(last, dict) else None
        ) or (
            flight_path
            if flight_path and os.path.exists(flight_path) else None
        )
        prev_resume = resume_step
        # a durable checkpoint turns the next retry into a resume; the
        # replay cost is the gap between where the child died (its last
        # flight-recorded step) and where the next one restarts.  A dump
        # carrying the stamp of one we already billed is a STALE file (a
        # later attempt died before dumping) — don't bill it twice.
        resume_step = latest_durable_step(ckpt_dir) if ckpt_dir else None
        stamp, died_at = _flight_dump_facts(flight_dump)
        dump_fresh = stamp is None or stamp != seen_dump_stamp
        if stamp is not None and dump_fresh:
            seen_dump_stamp = stamp
        if resume_step is not None and dump_fresh and died_at is not None:
            steps_lost += max(0, died_at - resume_step)
        # price the dead attempt for the lineage goodput merge NOW —
        # the relaunched child truncates this exact file.  Same
        # staleness rule as steps_lost: a dump we already billed must
        # not vouch for a second death's useful work.
        attempt_goodput = None
        if flight_dump and dump_fresh:
            try:
                with open(flight_dump) as f:
                    attempt_goodput = goodput_mod.failed_attempt_facts(
                        json.load(f), resume_step
                    )
            except (OSError, ValueError):
                attempt_goodput = None
        # preemption skips the backoff: the accelerator is healthy, the
        # process was just told to die — relaunch (and resume) now.
        # Armed chaos skips it too: every chaos death is SIMULATED (the
        # device never actually went away), so waiting out a tunnel
        # backoff would bill fake recovery time to the relaunch path —
        # exactly the number the elastic-vs-relaunch A/B compares.
        delay = (
            0.0 if reason == REASON_PREEMPTED or chaos_spec
            else backoff[min(i, len(backoff) - 1)]
        ) if i + 1 < attempts else 0.0
        rec = {
            "record": "bench_retry_failure",
            "lineage_id": lineage_id,
            "attempt": i + 1,
            "attempts_left": attempts - i - 1,
            "error": err_s,
            "reason": reason,
            "rc": rc,
            "wall_s": round(time.perf_counter() - t0, 3),
            "backoff_s": delay,
            **({"flight_dump": flight_dump} if flight_dump else {}),
            **({"goodput": attempt_goodput} if attempt_goodput else {}),
            **(
                {"resumed_from_step": prev_resume}
                if prev_resume is not None else {}
            ),
            **({"chaos": chaos_spec} if chaos_spec else {}),
        }
        failures.append(rec)
        print(json.dumps(rec), file=sys.stderr)
    last.setdefault("error", "unknown")
    last["error"] = f"exhausted {attempts} attempts; last: {last['error']}"
    resume = (
        {"resumes": resume_count, "total_steps_lost": steps_lost}
        if resume_count else None
    )
    print(json.dumps(_finish(attach_parent_telemetry(
        last, failures, compile_report, resume=resume
    ))))


def fedavg_secondary(n_rounds: int = 10) -> dict:
    """Timed FedAvg round on the tutorial_1a workload — the second metric
    BASELINE.json names (reference wall-time segmentation:
    ``lab/tutorial_1a/hfl_complete.py:294,373``).  N=10 C=0.1 B=100 E=1
    lr=0.01 seed=10, the solved-homework golden config
    (``lab/series01.ipynb`` cell 20).  One warmup round compiles the
    vmapped client program; the timed window is ``n_rounds`` full server
    rounds (host-side client sampling + device-side local epochs +
    weighted aggregation), reported as ms/round.

    ``DDL25_BENCH_NTRAIN`` shrinks the MNIST split for CPU smoke runs
    (the single-core XLA CPU backend takes minutes on the full 60k; the
    TPU headline always uses the full split).  Any failure here must not
    cost the already-measured primary metric: the caller degrades this
    entry to an error note instead of letting the exception escape (and
    burn the retry wrapper's attempts)."""
    import time

    from ddl25spring_tpu.data.mnist import load_mnist
    from ddl25spring_tpu.fl import FedAvgServer

    n_train = int(os.environ.get("DDL25_BENCH_NTRAIN", "0")) or 60_000
    server = FedAvgServer(
        nr_clients=10, client_fraction=0.1, batch_size=100,
        nr_local_epochs=1, lr=0.01, seed=10,
        data=load_mnist(n_train=n_train),
    )
    server.round(0)  # compile
    jax.block_until_ready(jax.tree.leaves(server.params))
    t0 = time.perf_counter()
    for r in range(1, n_rounds + 1):
        server.round(r)
    jax.block_until_ready(jax.tree.leaves(server.params))
    ms = (time.perf_counter() - t0) / n_rounds * 1e3
    return {
        "metric": "fedavg_round_ms",
        "value": round(ms, 2),
        "unit": "ms/round",
        "n_train": n_train,
        "note": "tutorial_1a FedAvg N=10 C=0.1 B=100 E=1; one vmapped "
                "server round incl. host-side sampling",
    }


def main(argv=None) -> None:
    import time as _time

    # anchor for recovery_wall_s: how long a relaunched child takes from
    # process entry to "training again" — the checkpoint-relaunch side
    # of the elastic-vs-relaunch recovery A/B (the elastic side measures
    # its in-process reshape against the same clock kind)
    t_main0 = _time.perf_counter()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local testing; the axon TPU "
                         "plugin is registered at interpreter start)")
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N",
                    help="simulate an N-device CPU mesh (implies --cpu)")
    ap.add_argument("--per-chip-batch", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--microbatches", type=int, default=2)
    ap.add_argument("--stages", type=int, default=0, metavar="S",
                    help="force the pipeline stage count (0 = auto: "
                         "2 stages when >= 2 chips, else pure DP; "
                         "--stages 1 forces pure DP on any chip count "
                         "— how the perf ledger gets multi-chip "
                         "bench-dp records)")
    ap.add_argument("--overlap", action="store_true",
                    help="backward-overlapped grad-bucket collectives "
                         "(parallel/dp.py overlap mode; implies pure "
                         "DP): the BENCH line and perf-ledger records "
                         "carry layout dp-overlap so before/after "
                         "measurements never mix")
    ap.add_argument("--scan-steps", type=int, default=0, metavar="K",
                    help="train steps fused per dispatch in the primary "
                         "mode (0 = auto: largest divisor of "
                         "batches_per_epoch <= 16)")
    ap.add_argument("--probe-timeout", type=float, default=240.0)
    ap.add_argument("--attempts", type=int, default=3,
                    help="fresh-subprocess retries for the accelerator "
                         "path (the TPU tunnel can flake; backend-init "
                         "failure is sticky in-process)")
    ap.add_argument("--child-timeout", type=float, default=2400.0,
                    help="overall wall-clock bound per bench subprocess")
    ap.add_argument("--no-fedavg", action="store_true",
                    help="skip the FedAvg round-time secondary metric")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="enable run telemetry (ddl25spring_tpu.obs) and "
                         "write metrics.jsonl / counters.json / trace.json "
                         "there; summarize with tools/obs_report.py")
    ap.add_argument("--save-every", type=int, default=0, metavar="N",
                    help="checkpoint the primary phase every N train "
                         "steps (ddl25spring_tpu.ft autosave: async, "
                         "sentinel-gated, atomic manifest); 0 disables. "
                         "Defaults to 2 when DDL25_CHAOS is armed")
    ap.add_argument("--ckpt-dir", default=None, metavar="DIR",
                    help="checkpoint directory (default: <obs-dir>/ckpt, "
                         "or runs/bench_ckpt)")
    ap.add_argument("--resume-from", default=None, metavar="CKPT_DIR",
                    help="restore params/opt-state/data-cursor/rng from "
                         "the latest durable checkpoint and continue the "
                         "primary phase from the next step (the retry "
                         "driver passes this automatically on relaunch)")
    ap.add_argument("--elastic", action="store_true",
                    help="survive device_loss / capacity_change chaos "
                         "IN-PROCESS by reshaping onto the surviving "
                         "mesh (ddl25spring_tpu.ft.elastic): live state "
                         "re-lands device-to-device, the step re-lowers "
                         "on the survivor mesh, the run continues from "
                         "the data cursor — no relaunch, no checkpoint "
                         "round-trip.  Implies pure DP at single-step "
                         "dispatch granularity; with --smoke a 2-device "
                         "CPU mesh so a loss is survivable.  A "
                         "capacity_change target that does not divide "
                         "the global batch is lowered to the largest "
                         "device count that does")
    ap.add_argument("--perf-reps", type=int, default=8, metavar="K",
                    help="barriered step reps for the measured perf "
                         "record (ddl25spring_tpu.obs.perfscope: "
                         "measured MFU, overlap efficiency, exposed "
                         "comms on the BENCH line's telemetry.perf); "
                         "0 disables the measurement")
    ap.add_argument("--perf-ledger", default=None, metavar="JSONL",
                    help="append the measured perf record here "
                         "(default runs/perf_ledger.jsonl; gate trends "
                         "with tools/perf_report.py --check)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU smoke run with telemetry: single-device DP, "
                         "tiny dataset/steps, no FedAvg; writes "
                         "--obs-dir (default runs/bench_smoke)")
    # --- serving mode (ddl25spring_tpu/serve): the inference bench -----
    ap.add_argument("--serve", action="store_true",
                    help="run the continuous-batching LLaMA serving bench "
                         "instead of the training bench: seeded open-loop "
                         "traffic through the paged-KV decode engine, "
                         "BENCH line with telemetry.serve (tokens/sec/"
                         "chip, TTFT + per-token p50/p95, admission "
                         "counters, pool occupancy) and a continuous-vs-"
                         "static A/B in the perf ledger; with --smoke: "
                         "tiny fp32 model, CPU, obs-dir runs/serve_smoke. "
                         "Engine knobs via DDL25_SERVE_* (see README)")
    ap.add_argument("--serve-duration", type=float, default=None,
                    metavar="S", help="traffic trace duration (seconds of "
                                      "arrival clock)")
    ap.add_argument("--serve-rate", type=float, default=None, metavar="RPS",
                    help="peak arrival rate (requests/sec)")
    ap.add_argument("--serve-profile", default=None,
                    choices=("flat", "ramp", "spike", "shared"),
                    help="arrival-rate shape (default ramp; 'shared' = "
                         "K seeded system prompts x Poisson arrivals — "
                         "the radix-prefix-cache workload)")
    ap.add_argument("--serve-seed", type=int, default=None,
                    help="traffic trace seed (two runs on the same seed "
                         "replay the identical workload)")
    ap.add_argument("--serve-budget", type=float, default=None, metavar="S",
                    help="wall-clock bound on the ramp phase (default: "
                         "run to drain)")
    ap.add_argument("--serve-model", default=None,
                    choices=("tiny", "tiny-deep", "ref"),
                    help="model to serve (default: tiny under --smoke, "
                         "else the reference LLaMA constants; tiny-deep "
                         "= 6-layer tiny, the speculative-decoding "
                         "smoke target whose 1-layer drafter is "
                         "genuinely cheap)")
    ap.add_argument("--no-serve-ab", action="store_true",
                    help="skip the continuous-vs-static A/B phase")
    ap.add_argument("--no-serve-prefix-ab", action="store_true",
                    help="skip the cached-vs-cold prefix-cache A/B "
                         "phase (it also never runs with "
                         "DDL25_SERVE_PREFIX=0)")
    ap.add_argument("--no-serve-spec-ab", action="store_true",
                    help="skip the speculative spec-on-vs-off A/B "
                         "phase (it also never runs without "
                         "DDL25_SERVE_SPEC=1)")
    ap.add_argument("--serve-tp", type=int, default=None, metavar="N",
                    help="TP-shard the serving engine N ways over a "
                         "1-D model mesh (KV head dim + Megatron "
                         "params divided per chip; overrides "
                         "DDL25_SERVE_TP).  N>1 also runs the "
                         "sharded-vs-dense A/B serve_report "
                         "--check-tp gates")
    ap.add_argument("--no-serve-tp-ab", action="store_true",
                    help="skip the tp-sharded-vs-dense A/B phase (it "
                         "also never runs at tp=1)")
    ap.add_argument("--compile-report", action="store_true",
                    help="force the pre-device compile report on CPU runs "
                         "(the accelerator path always computes it; see "
                         "ddl25spring_tpu/obs/compile_report.py)")
    ap.add_argument("--no-compile-report", action="store_true",
                    help="skip the compile report on the accelerator path")
    args = ap.parse_args(argv)

    # 0/negative would skip the retry loop entirely and print a
    # contract-violating `last={}` line with only an `error` key
    if args.attempts < 1:
        print(f"clamping --attempts {args.attempts} -> 1", file=sys.stderr)
        args.attempts = 1

    if args.serve and args.smoke:
        # the serving smoke gets its own obs dir so a bench smoke and a
        # serve smoke in one CI run never clobber each other's artifacts
        args.obs_dir = args.obs_dir or os.path.join("runs", "serve_smoke")
    if args.smoke:
        args.cpu = True
        args.no_fedavg = True
        args.per_chip_batch = min(args.per_chip_batch, 64)
        args.steps = min(args.steps, 8)
        args.warmup = min(args.warmup, 2)
        args.scan_steps = args.scan_steps or 1
        args.obs_dir = args.obs_dir or "runs/bench_smoke"
        os.environ.setdefault("DDL25_BENCH_NTRAIN", "512")
    if args.elastic:
        # the reshape boundary is a dispatch boundary: elastic runs at
        # single-step granularity (a K-fused scan dispatch would make
        # "the in-flight step" K steps wide) and in pure DP — the
        # layout whose re-lower the reshape path covers today
        if args.scan_steps not in (0, 1):
            print("--elastic forces --scan-steps 1 (reshape operates at "
                  "single-dispatch granularity)", file=sys.stderr)
        args.scan_steps = 1
        if args.smoke and not args.force_cpu_devices:
            # a 1-device smoke has nothing to lose; fake two CPU
            # devices so device_loss@k has a survivor to reshape onto
            args.force_cpu_devices = 2

    on_cpu = args.cpu or args.force_cpu_devices
    is_child = os.environ.get("DDL25_BENCH_CHILD") == "1"

    # fault-tolerance wiring (ddl25spring_tpu/ft): armed chaos implies
    # autosave (a kill with nothing durable proves nothing), and chaos
    # on a CPU run still needs the subprocess wrapper — the relaunch IS
    # the recovery mechanism the chaos exists to exercise
    chaos_spec = os.environ.get("DDL25_CHAOS")
    if chaos_spec and not args.save_every and not args.serve:
        # serve mode has no checkpoint loop: its chaos kinds drive the
        # elastic replica reshaping inside the serve driver instead
        args.save_every = 2
    resilient = bool(args.save_every or args.resume_from)
    ckpt_dir = args.ckpt_dir or args.resume_from or (
        os.path.join(args.obs_dir, "ckpt") if args.obs_dir
        else os.path.join("runs", "bench_ckpt")
    )
    # fresh-start hygiene happens at the TOP of the run, never on a
    # retry: only the first process (parent, or the in-process CPU
    # path) wipes the stale checkpoint dir and the previous run's
    # flight.json.  A relaunched child must keep both — the chaos
    # one-shot journal lives in the ckpt dir (wiping it on a
    # nothing-durable-yet restart would re-fire the fault forever),
    # and a stale dump would corrupt the steps-lost accounting.
    if args.resume_from and args.ckpt_dir and (
        os.path.abspath(args.resume_from) != os.path.abspath(args.ckpt_dir)
    ):
        # silently saving into one dir while "resuming" from another
        # would restart from scratch behind the user's back
        print("--resume-from and --ckpt-dir point at different "
              "directories; pass one (the resume source is also where "
              "new checkpoints land)", file=sys.stderr)
        sys.exit(2)
    if resilient and not args.resume_from and not is_child and (
        os.path.isdir(ckpt_dir)
    ):
        import shutil

        # wipe ONLY something that is recognizably ours: the autosave
        # manifest, a chaos journal, or orbax step dirs.  A typo'd
        # --ckpt-dir pointing at user data must refuse, not recurse.
        ours = {"manifest.json", "chaos_fired.jsonl"}
        entries = os.listdir(ckpt_dir)
        if not entries or any(e in ours for e in entries) or all(
            os.path.isdir(os.path.join(ckpt_dir, e))
            and (e.isdigit() or ".orbax-checkpoint-tmp" in e)
            for e in entries
        ):
            shutil.rmtree(ckpt_dir)
        else:
            print(f"refusing to wipe {ckpt_dir}: it does not look like "
                  "a bench checkpoint dir (no manifest.json / chaos "
                  "journal / orbax step dirs); clear it yourself or "
                  "pass --resume-from to continue from it",
                  file=sys.stderr)
            sys.exit(2)
    if not is_child and not args.resume_from and args.obs_dir:
        stale_flight = os.path.join(args.obs_dir, "flight.json")
        if os.path.exists(stale_flight):
            os.remove(stale_flight)

    # compile-time analytics BEFORE any device contact: lowered on a fake
    # CPU mesh in a fresh subprocess, so the report exists even when the
    # TPU tunnel is dead (the r01-r05 failure mode) and never pollutes
    # this process's backend state.  Parent path always; CPU runs opt in.
    compile_report = None
    # the child never recomputes: the parent did, once, and attaches it
    want_cr = not is_child and (
        args.compile_report or (not on_cpu and not args.no_compile_report)
    )
    if want_cr:
        from ddl25spring_tpu.obs.compile_report import (
            bench_compile_report_subprocess,
            write_compile_report,
        )

        compile_report = bench_compile_report_subprocess()
        if args.obs_dir:
            write_compile_report(args.obs_dir, compile_report)

    if (not on_cpu or chaos_spec) and not is_child:
        run_with_retries(
            argv if argv is not None else sys.argv[1:],
            args.attempts, args.child_timeout,
            compile_report=compile_report,
            ckpt_dir=ckpt_dir if resilient else None,
            flight_path=(
                os.path.join(args.obs_dir, "flight.json")
                if args.obs_dir else None
            ),
            ledger_path=args.perf_ledger,
        )
        return

    if args.force_cpu_devices:
        from ddl25spring_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(args.force_cpu_devices)
    elif args.cpu:
        jax.config.update("jax_platforms", "cpu")

    # arm the crash paths before any device contact: from here on an
    # unhandled exception, SIGTERM, or exit leaves a flight.json behind
    from ddl25spring_tpu.obs import flight

    flight.configure(run_dir=args.obs_dir)
    flight.install()
    flight.annotate(
        driver="bench",
        argv=list(argv if argv is not None else sys.argv[1:]),
    )

    # graft-goodput (PR 20): this process's place in its run lineage.
    # A retry child inherits the parent's id through the env boundary
    # (so a resumed attempt carries the SAME lineage_id); an in-process
    # run (plain CPU smoke, serve) is its own one-attempt lineage.
    from ddl25spring_tpu.obs import goodput as goodput_mod

    lineage_id, attempt = goodput_mod.lineage_from_env()
    own_lineage = lineage_id is None  # nobody upstream will merge for us
    if own_lineage:
        lineage_id = goodput_mod.mint_lineage_id()
    flight.annotate(lineage_id=lineage_id, attempt=attempt)
    lineage_meta = {"lineage_id": lineage_id, "attempt": attempt}
    gp_meter = goodput_mod.GoodputMeter(
        lineage_id, attempt, t0_perf=t_main0
    )

    devices, err, probe_dump = probe_devices(
        args.probe_timeout, flight_dir=args.obs_dir
    )
    if devices is None:
        record = {
            "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": f"accelerator unreachable: {err}",
            **({"flight_dump": probe_dump} if probe_dump else {}),
        }
        attach_parent_telemetry(record, None, compile_report)
        print(json.dumps(record), flush=True)
        sys.stderr.flush()
        # a wedged backend init leaves jax's atexit machinery deadlocked
        # on the half-initialized backend (observed on this image: the
        # TPU plugin's metadata retry loop), which would strand this
        # JSON line in a block buffer forever — the r01–r05 silent-child
        # mode.  Everything worth persisting is flushed; exit hard.
        if "timed out" in str(err):
            os._exit(0)
        return

    # --- serving mode: traffic -> paged-KV engine -> telemetry.serve ---
    # (the training phases below never run; the serve driver owns the
    # ramp, the continuous-vs-static A/B, serve.json, and the ledger row)
    if args.serve:
        from ddl25spring_tpu import obs
        from ddl25spring_tpu.obs import sentinels as _sentinels
        from ddl25spring_tpu.obs.timeline import timeline
        from ddl25spring_tpu.serve.driver import run_serve_bench, serve_cell

        if args.obs_dir:
            # graft-trace (PR 16): enable BEFORE the engines build so
            # the serve spans + request timeline record (the flag is
            # read at emission time; everything here is host-side, so
            # the compiled serve programs are byte-identical either
            # way — pinned in tests/test_timeline.py)
            obs.enable()
            obs.set_recorder(obs.SpanRecorder(process_name="serve"))
            timeline.configure(run_dir=args.obs_dir, meta=lineage_meta)

        record = run_serve_bench(
            smoke=args.smoke,
            model=args.serve_model,
            obs_dir=args.obs_dir,
            duration_s=args.serve_duration,
            rate_rps=args.serve_rate,
            profile=args.serve_profile,
            seed=args.serve_seed,
            budget_s=args.serve_budget,
            ledger_path=args.perf_ledger or "runs/perf_ledger.jsonl",
            skip_ab=args.no_serve_ab,
            skip_prefix_ab=args.no_serve_prefix_ab,
            skip_spec_ab=args.no_serve_spec_ab,
            skip_tp_ab=args.no_serve_tp_ab,
            serve_tp=args.serve_tp,
            lineage=lineage_meta,
        )
        telemetry: dict = {
            "enabled": bool(args.obs_dir),
            "serve": serve_cell(record),
        }
        # graft-goodput: the SLO-denominated serving goodput cell the
        # driver computed (attainment, goodput tokens/sec/chip,
        # availability) — lineage identity rides along so serve lines
        # group like training lines in the ledger
        if record.get("goodput"):
            telemetry["goodput"] = {
                **lineage_meta, **goodput_mod.goodput_cell(
                    record["goodput"]
                ),
            }
        # graft-mem (PR 17): the runtime memory cell — measured
        # live-bytes high-water vs the engine's static bill, pool
        # telemetry, drain-time leak verdict (tools/mem_report.py)
        from ddl25spring_tpu.obs import memscope

        telemetry["mem"] = (
            memscope.mem_cell(record["mem"]) if record.get("mem")
            else {"enabled": False}
        )
        if record.get("mem_json"):
            telemetry["mem"]["mem_json"] = record["mem_json"]
        if compile_report is not None:
            telemetry["compile_report"] = compile_report
            telemetry["lint"] = lint_summary(compile_report)
        snap = flight.snapshot()
        health = {
            "sentinels": _sentinels.enabled(),
            "policy": _sentinels.policy(),
            "violations": snap["violations"],
            "stalls": snap["stalls"],
            "flight_records": snap["recorded"],
        }
        if args.obs_dir:
            health["flight_dump"] = flight.dump(reason="end_of_run")
            # the other two thirds of the merged trace: host spans
            # (trace.json) + the request timeline — what
            # tools/trace_export.py folds into one Perfetto view
            telemetry["trace"] = obs.get_recorder().save(
                os.path.join(args.obs_dir, "trace.json")
            )
            timeline.flush()
            telemetry["timeline"] = timeline.path
            telemetry["timeline_events"] = timeline.snapshot()["emitted"]
        telemetry["health"] = health
        ramp = record["ramp"]
        print(json.dumps({
            "metric": "serve_tokens_per_sec_per_chip",
            "value": ramp.get("tokens_per_sec_per_chip"),
            "unit": "tokens/sec/chip",
            # no committed serving baseline yet: the perf ledger trend
            # (tools/serve_report.py --check) is the regression gate
            "vs_baseline": None,
            "model": record["key"]["model"],
            "profile": record["key"]["profile"],
            "chip": f"{devices[0].device_kind} x{ramp.get('n_chips', 1)}",
            "telemetry": telemetry,
        }), flush=True)
        return

    import time

    from ddl25spring_tpu import obs
    from ddl25spring_tpu.benchmarks import (
        DeviceDataset,
        InputFeed,
        build_resnet_scan_step,
        build_resnet_step,
        report_line,
        timed_run,
    )
    from ddl25spring_tpu.utils.flops import chip_peak_flops, compiled_flops, mfu

    lg = None
    if args.obs_dir:
        # enable BEFORE building the step so the on-device counters are
        # traced in (the flag is read at trace time — obs/state.py)
        obs.enable()
        obs.set_recorder(obs.SpanRecorder(process_name="bench"))
        obs.counters.reset()
        # graft-goodput: the training run gets the unified timeline too
        # (serve always had one) — its header names the lineage, and
        # the flight tap mirrors save/restore/stall/chaos events in,
        # so one artifact correlates every attempt of a retry lineage
        from ddl25spring_tpu.obs.timeline import timeline

        timeline.configure(run_dir=args.obs_dir, meta=lineage_meta)

    n = len(devices)
    if args.stages:
        S = args.stages
        dp = max(n // S, 1)
    elif args.overlap or args.elastic:
        # overlap restructures the DP gradient path; elastic reshapes
        # it — both pin the pure-DP layout
        dp, S = n, 1
    else:
        dp, S = (n // 2, 2) if n >= 2 else (1, 1)
    # any pipelined layout takes the microbatch arg (S was only ever 1
    # or 2 before --stages existed; an S=3/4 run must not silently
    # degrade to the full-bubble M=1 schedule)
    M = args.microbatches if S >= 2 else 1
    batch = (args.per_chip_batch * dp * S) // (dp * M) * (dp * M)

    # DDL25_BENCH_NTRAIN: shrink the HBM dataset for CPU smoke runs of the
    # full bench flow (the TPU headline always uses the full 50k split)
    n_train = int(os.environ.get("DDL25_BENCH_NTRAIN", "0")) or None
    ds = DeviceDataset(batch, n_train=n_train)
    # scan fusion is TPU-only by default: lax.scan over a conv body is
    # pathologically slow on the XLA CPU backend (measured 55x — see
    # build_resnet_scan_step's docstring), so CPU smoke runs take K=1
    on_tpu = devices[0].platform == "tpu"
    K = args.scan_steps or (
        max(k for k in range(1, 17) if ds.batches_per_epoch % k == 0)
        if on_tpu else 1
    )
    with obs.span("build_step", scan_steps=K):
        if K > 1:
            multi, step, params, opt_state, meta = build_resnet_scan_step(
                devices, dp, S, M, batch, K, ds.n, overlap=args.overlap
            )
        else:
            multi = None
            step, params, opt_state, meta = build_resnet_step(
                devices, dp, S, M, batch, overlap=args.overlap
            )
    n_chips = meta["n_chips"]
    gp_meter.chips = n_chips  # windows before a reshape bill this width
    flight.annotate(
        layout=meta["layout"], topology=meta["topology"],
        n_chips=n_chips, batch=batch, scan_steps=K,
        rng_seed=ds.seed,  # the DeviceDataset epoch-shuffle key
    )

    # --- fault tolerance (ddl25spring_tpu/ft): restore + chaos + autosave --
    # the primary phase becomes resumable: periodic sentinel-gated async
    # checkpoints of the FULL resume state (params, opt state, data
    # cursor, rng seed), chaos faults armed from DDL25_CHAOS, and — when
    # the retry driver relaunched us with --resume-from — restoration of
    # the latest durable step instead of a restart from scratch.
    saver = None
    chaos = None
    chaos_exc: tuple = ()
    start_step = 0
    replayed = None
    recovery_wall_s = None
    # chaos kinds an elastic run CLAIMS at segment boundaries via
    # chaos.take (ft/elastic.py): on_step must not execute their
    # default raise-and-die action out from under the reshape path
    elastic_skip = (
        ("device_loss", "capacity_change") if args.elastic else ()
    )
    reshape_events: list = []
    if resilient or chaos_spec:
        from ddl25spring_tpu.ft import (
            AutoSaver,
            ChaosInjector,
            DeviceLossError,
            resume_bundle,
        )
        from ddl25spring_tpu.utils.checkpoint import with_mesh_placement

        if resilient:
            saver = AutoSaver(
                ckpt_dir, save_every=args.save_every,
                meta={"driver": "bench", "layout": meta["layout"]},
            )
        chaos = ChaosInjector.from_env(state_dir=ckpt_dir)
        chaos_exc = (DeviceLossError,)
        if chaos.pending("nan_grad"):
            print("chaos: nan_grad does not reach the bench's uint8 input "
                  "path; exercise it via ft/demo.py or the ft tests",
                  file=sys.stderr)
        if args.resume_from and saver is not None:
            # the template pins placement: restored leaves land exactly
            # where a fresh build put them (mesh-replicated here)
            init = with_mesh_placement(
                resume_bundle(params, opt_state,
                              data_cursor=ds.cursor, rng_seed=ds.seed),
                meta["mesh"],
            )
            state, start_step = saver.restore_or_init(init)
            # the relaunch path's recovery bill: process entry ->
            # restored and ready to train (imports, backend dial, and
            # the checkpoint read all inside); the elastic path's
            # reshape wall is the in-process counterpart
            recovery_wall_s = round(_time.perf_counter() - t_main0, 3)
            # goodput: everything from process entry to "restored" is
            # the relaunch path's recovery bill — one window on the
            # meter's axis (which is anchored at the same t_main0)
            gp_meter.add(
                "recovery", 0.0, gp_meter.now(), reason="relaunch_restore"
            )
            if start_step:
                params, opt_state = state["params"], state["opt_state"]
                ds.cursor = int(state["data_cursor"])
                # steps replayed = the gap between the dead attempt's
                # last flight-recorded step (its dump is still in the
                # obs dir — we haven't overwritten it yet) and our
                # restart point
                prev_last = _flight_last_step(
                    os.path.join(args.obs_dir, "flight.json")
                    if args.obs_dir else None
                )
                if prev_last is not None:
                    replayed = max(0, prev_last + 1 - start_step)
                    flight.annotate(steps_replayed=replayed)
                    # the durable-gap steps re-run now: timed_run bills
                    # their dispatch walls `replayed_steps`, not useful
                    gp_meter.set_replay_window(start_step, prev_last)

        def ft_on_step(i, p, o, lval):
            """timed_run's per-step hook: kill-type chaos first (a fault
            at step i fires BEFORE step i's state can become durable —
            maximum honest replay), then the gated autosave."""
            if chaos is not None:
                chaos.on_step(i, skip=elastic_skip)
            if saver is not None:
                # goodput: the save's host-blocking enqueue wall (the
                # async write itself overlaps training) — billed only
                # when the cadence gate actually fired
                t0_save = gp_meter.now()
                if saver.maybe_save(
                    i,
                    resume_bundle(p, o, data_cursor=ds.cursor,
                                  rng_seed=ds.seed),
                    loss=lval,
                ):
                    gp_meter.add(
                        "checkpoint_save", t0_save, gp_meter.now(), step=i
                    )
    else:
        ft_on_step = None

    # graft-mem (PR 17): the training-loop memory observatory — live
    # bytes + host RSS sampled once per step through the same on_step
    # hook the ft machinery rides, with the windowed monotone-growth
    # detector watching the host side (a growing Python-side resource
    # fires a flight ``kind="mem"`` violation).  All of it is host
    # observation: with DDL25_MEMSCOPE=0 (or obs off) the hook reduces
    # to the ft chain and the compiled step is untouched.
    from ddl25spring_tpu.obs import memscope

    mem_scope = memscope.MemScope(label="train")
    if memscope.enabled():
        _ft_chain = ft_on_step

        def ft_on_step(i, p, o, lval):  # noqa: F811 — deliberate wrap
            mem_scope.sample(i)
            if _ft_chain is not None:
                _ft_chain(i, p, o, lval)

    if args.obs_dir:
        lg = obs.MetricsLogger(
            args.obs_dir,
            meta=obs.run_metadata(
                mesh=meta["mesh"],
                layout=meta["layout"],
                topology=meta["topology"],
                n_chips=n_chips,
                batch=batch,
                num_stages=meta["num_stages"],
                num_microbatches=meta["num_microbatches"],
                scan_steps=K,
                input_mode=ds.input_mode,
            ),
        )

    # --- primary: HBM shuffle; K steps fused per dispatch on TPU -----------
    # A chaos-simulated device loss mid-phase degrades to the standard
    # error line (classified ``device_unreachable``) so the retry driver
    # relaunches — with --resume-from, since the autosave left a durable
    # step behind.  Chaos/checkpoint step indices count DISPATCHES on
    # the scan path (each dispatch = K fused steps); a resumed attempt
    # runs only the remaining steps (warmup still re-runs — compilation
    # is per-process — so the resumed data cursor drifts by the warmup
    # batches, which a throughput bench tolerates and the pinned
    # equivalence tests in tests/test_ft.py avoid by construction).
    # the budget anchor is the FIRST sampled step (memscope auto-
    # baselines): steady-state live bytes on the actual placement —
    # a post-build probe undercounts DP replication, which only
    # materializes on the first dispatch
    try:
        if multi is not None:
            def feed_scan():
                return (ds.x, ds.y) + ds.scan_window(K)

            def multi_packed(params, opt_state, packed):
                return multi(params, opt_state, *packed)

            # warmup MUST be >= 2 dispatches: the first call compiles,
            # and the SECOND recompiles once more (the first call's
            # outputs come back with TPU-chosen layouts that differ from
            # the freshly-initialized input arrays; the layout fix point
            # is reached after one round).  With a 1-dispatch warmup that
            # ~24 s recompile lands in the timed window and craters the
            # reported number ~25x (measured).
            resumed_past_end = start_step >= max(3, args.steps // K)
            n_disp = max(max(3, args.steps // K) - start_step, 1)
            dt, params, opt_state = timed_run(
                multi_packed, params, opt_state, feed_scan, n_disp,
                max(2, args.warmup // 2),
                logger=lg, label="hbm-scan", samples_per_step=batch,
                steps_per_call=K, on_step=ft_on_step,
                step_offset=start_step, goodput=gp_meter,
            )
            sps_chip = n_disp * K * batch / dt / n_chips
            dt_per_step = dt / (n_disp * K)

            # --- secondary 0: same input, one step per dispatch ------------
            # reset the stream counter: scan_window and feed interpret it
            # at different granularities (K-windows vs single batches), so
            # the single-dispatch run starts a fresh epoch instead of
            # interleaving
            ds._i = 0
            dt0, params, opt_state = timed_run(
                step, params, opt_state, ds.feed, args.steps, args.warmup,
                logger=lg, label="hbm-single", samples_per_step=batch,
                goodput=gp_meter,
            )
            sps_chip_single = args.steps * batch / dt0 / n_chips
        else:
            resumed_past_end = start_step >= args.steps
            steps_run = max(args.steps - start_step, 1)
            end_step = start_step + steps_run
            # the elastic plan: armed device_loss / capacity_change
            # faults inside this run's step window become SEGMENT
            # boundaries — each segment is an ordinary timed_run, and
            # between segments the taken fault is answered with an
            # in-process reshape instead of a death (ft/elastic.py).
            # Chaos fires post-step by contract, so the boundary split
            # is observationally identical to an in-loop fault: step k
            # completes, THEN the mesh changes.
            elastic_plan = sorted(
                (
                    f for f in (chaos.pending() if chaos else ())
                    if f.kind in elastic_skip
                    and start_step <= f.step < end_step
                ),
                key=lambda f: f.step,
            ) if args.elastic else []
            dt = 0.0
            chip_s = 0.0  # chip-seconds: each segment billed at ITS width
            seg_start = start_step
            mesh_now = meta["mesh"]
            for fault in [*elastic_plan, None]:
                seg_end = end_step if fault is None else fault.step + 1
                if seg_end > seg_start:
                    dt_i, params, opt_state = timed_run(
                        step, params, opt_state, ds.feed,
                        seg_end - seg_start,
                        # the continuation segment must not burn feed
                        # batches (and mutate params) on re-warmup; the
                        # rebuilt step compiles on its first timed
                        # dispatch — that compile IS part of the
                        # recovery story and stays in the measurement
                        args.warmup if seg_start == start_step else 0,
                        logger=lg, label="hbm-single",
                        samples_per_step=batch,
                        on_step=ft_on_step, step_offset=seg_start,
                        goodput=gp_meter,
                    )
                    dt += dt_i
                    chip_s += dt_i * n_chips
                    seg_start = seg_end
                if fault is None:
                    break
                if not chaos.take(fault.step, kinds=(fault.kind,)):
                    continue  # journaled in a previous life: one-shot
                from ddl25spring_tpu.ft import elastic

                t0r = time.perf_counter()
                g0r = gp_meter.now()
                # graft-mem: the survivor-mesh memory step — live bytes
                # before the reshard vs after the old-mesh state is
                # dropped rides the reshape record (mem_report gates
                # its presence on the elastic smoke)
                mem_before = (
                    memscope.live_total_bytes()
                    if memscope.enabled() else None
                )
                n_now = meta["n_chips"]
                target = (
                    fault.arg if fault.kind == "capacity_change"
                    and fault.arg else max(1, n_now // 2)
                )
                if target > len(devices):
                    # a capacity grant beyond the attached devices
                    # lowers to what exists — growing is best-effort,
                    # only shrinking is forced on us
                    print(f"elastic: capacity_change target {target} "
                          f"exceeds {len(devices)} attached device(s); "
                          "lowering", file=sys.stderr)
                    target = len(devices)
                while batch % target:  # keep the global batch exact
                    target -= 1
                new_devs = elastic.surviving_devices(
                    devices, size=target
                )
                step, p_t, o_t, meta = build_resnet_step(
                    new_devs, target, 1, 1, batch, overlap=args.overlap
                )
                state = elastic.reshape_state(
                    {"params": params, "opt_state": opt_state},
                    with_mesh_placement(
                        {"params": p_t, "opt_state": o_t}, meta["mesh"]
                    ),
                )
                params, opt_state = state["params"], state["opt_state"]
                # the freshly-initialized template state from the
                # rebuild is only a placement donor — holding it for
                # the rest of the run doubles the survivor mesh's
                # live bytes (found by the graft-mem step-down gate)
                del state, p_t, o_t
                wall = time.perf_counter() - t0r
                gp_meter.add(
                    "reshape_window", g0r, g0r + wall,
                    step=fault.step, reason=fault.kind,
                )
                # the faulted step completed and its loss synced before
                # the post-step fault fired — nothing was in flight, so
                # steps_lost is 0 by construction (vs the relaunch
                # path's died_at - durable gap)
                reshape_events.append(elastic.record_reshape(
                    old=mesh_now, new=meta["mesh"], wall_s=wall,
                    steps_lost=0, reason=fault.kind, step=fault.step,
                    **({
                        "live_bytes_before": mem_before,
                        "live_bytes_after": memscope.live_total_bytes(),
                    } if mem_before is not None else {}),
                ))
                if saver is not None:
                    saver.note_reshape(
                        old=reshape_events[-1]["old"],
                        new=reshape_events[-1]["new"],
                        step=fault.step,
                    )
                mesh_now = meta["mesh"]
                n_chips = meta["n_chips"]
                gp_meter.chips = n_chips  # later windows bill survivor width
                flight.annotate(
                    layout=meta["layout"], topology=meta["topology"],
                    n_chips=n_chips,
                )
            # per-chip throughput over chip-seconds: a mid-run reshape
            # means segments ran at DIFFERENT widths — dividing the
            # whole wall by the final width would overstate the number
            sps_chip = steps_run * batch / chip_s
            dt_per_step = dt / steps_run
            sps_chip_single = None
    except chaos_exc as e:
        if saver is not None:
            saver.close()  # the relaunch resumes from what we drained
        import contextlib

        dump = None
        with contextlib.suppress(Exception):  # the error line must print
            dump = flight.dump(reason="device_loss")
        record = {
            "metric": "cifar10_resnet18_dppp_samples_per_sec_per_chip",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": str(e),
            **({"flight_dump": dump} if dump else {}),
        }
        print(json.dumps(record), flush=True)
        return

    # --- secondary 1: host streaming through the native C++ loader ---------
    # Constructed only now, and warmed past the prefetch queue's capacity
    # (depth + in-flight workers), so the timed window starts with an empty
    # queue and measures steady-state producer-bound throughput — a queue
    # pre-filled during the primary run would hand the timed loop several
    # batches for free and inflate the number.
    workers = max(2, (os.cpu_count() or 4) // 2)
    depth = 6
    feed = InputFeed(batch, stream=True, workers=workers, prefetch_depth=depth)
    stream_warm = args.warmup + depth + workers
    dt_s, params, opt_state = timed_run(
        step, params, opt_state, feed.feed, args.steps, stream_warm,
        logger=lg, label="stream", samples_per_step=batch,
        goodput=gp_meter,
    )
    sps_chip_stream = args.steps * batch / dt_s / n_chips

    # --- secondary 2: one fixed device-resident batch (compute bound) ------
    dt2, params, opt_state = timed_run(
        step, params, opt_state, feed.feed_fixed, args.steps, args.warmup,
        logger=lg, label="fixed-batch", samples_per_step=batch,
        goodput=gp_meter,
    )
    sps_chip_fixed = args.steps * batch / dt2 / n_chips

    # measure the host->device link so the streaming line explains itself
    import numpy as np

    # median of 3 transfers: one TCP hiccup on the tunneled link must not
    # skew the self-describing bandwidth number
    buf = np.zeros(4 * 1024 * 1024, np.uint8)
    jax.device_put(buf[:1024], devices[0]).block_until_ready()
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(buf, devices[0]).block_until_ready()
        rates.append(4.0 / (time.perf_counter() - t0))
    h2d_mib_s = sorted(rates)[1]

    # --- secondary 3: FedAvg round time (BASELINE.json's second metric) ----
    # guarded: a FedAvg-side failure must degrade to an error note, not
    # discard the already-measured primary metric (and trigger retries)
    if args.no_fedavg:
        fedavg_line = []
    else:
        try:
            fedavg_line = [fedavg_secondary()]
        except Exception as e:  # noqa: BLE001 — keep the primary metric
            fedavg_line = [{
                "metric": "fedavg_round_ms", "value": None,
                "unit": "ms/round",
                "note": f"failed: {type(e).__name__}: {e}",
            }]

    # measured perf record (ddl25spring_tpu/obs/perfscope.py): re-lowers
    # the per-batch step once (the cost the old FLOPs-only pass already
    # paid), times it barriered, times the 1-device compute-only
    # counterfactual, micro-costs the live collective inventory, and
    # derives measured MFU / overlap efficiency / exposed comms.  Any
    # perf-side failure degrades to the bare FLOPs count — measurement
    # must never cost the bench line.
    perf_record = None
    flops_step = None
    if args.perf_reps > 0:
        try:
            from ddl25spring_tpu.obs import perfscope

            perf_record, params, opt_state = perfscope.measure_bench_step(
                step, params, opt_state, feed.fixed, meta, devices,
                reps=args.perf_reps, per_chip_batch=args.per_chip_batch,
            )
            flops_step = perf_record.get("flops")
        except Exception as e:  # noqa: BLE001 — keep the bench metric
            print(f"perfscope measurement failed ({type(e).__name__}: "
                  f"{e}); falling back to FLOPs-only accounting",
                  file=sys.stderr)
            perf_record = {"error": f"{type(e).__name__}: {e}"}
    if flops_step is None:
        flops_step = compiled_flops(step, params, opt_state, feed.fixed)
    achieved_tf, frac = mfu(flops_step, dt_per_step, n_chips, meta["device"])
    peak = chip_peak_flops(meta["device"])

    telemetry = {"enabled": False}
    if compile_report is not None:
        telemetry["compile_report"] = compile_report
    if lg is not None:
        # supplementary header: facts only known after the timed phases
        # (summarize_run merges header records in order)
        lg.log(
            record="header",
            flops_per_step=flops_step,
            peak_flops_per_chip=peak,
            h2d_mib_per_s=h2d_mib_s,
        )
        lg.close()
        obs.counters.save(args.obs_dir)
        obs.get_recorder().save(os.path.join(args.obs_dir, "trace.json"))
        from ddl25spring_tpu.obs.report import summarize_run

        s = summarize_run(args.obs_dir)
        telemetry = {
            "enabled": True,
            **(
                {"compile_report": compile_report}
                if compile_report is not None else {}
            ),
            "run_dir": args.obs_dir,
            "bubble_fraction": s.get("bubble_fraction"),
            "tick_interval_s_p50": s.get("tick_interval_s_p50"),
            "phases": {
                name: {
                    k: ph.get(k)
                    for k in (
                        "steps",
                        "step_s_p50",
                        "step_s_p95",
                        "samples_per_sec_per_chip_p50",
                        "mfu",
                    )
                    if ph.get(k) is not None
                }
                for name, ph in s.get("phases", {}).items()
            },
        }

    # the measured-perf cell + artifacts: perf.json in the run dir for
    # obs_report's "performance" section, and a ledger append so this
    # run becomes one point on the cross-run trend that
    # tools/perf_report.py --check gates
    if perf_record is not None:
        if "error" in perf_record:
            telemetry["perf"] = {"error": perf_record["error"]}
        else:
            from ddl25spring_tpu.obs import perfscope

            telemetry["perf"] = perfscope.perf_cell(perf_record)
            try:
                telemetry["perf"]["ledger"] = perfscope.append_ledger(
                    perf_record,
                    args.perf_ledger or perfscope.DEFAULT_LEDGER,
                )
                if args.obs_dir:
                    perfscope.write_run_perf(perf_record, args.obs_dir)
            except OSError as e:  # a read-only FS must not kill the line
                telemetry["perf"]["ledger_error"] = str(e)

    # the runtime-memory cell + artifacts (graft-mem, PR 17): mem.json
    # in the run dir for obs_report's Memory section, a record:"mem"
    # ledger row for tools/mem_report.py --check, and the reshape
    # memory step-downs for the elastic gate
    telemetry["mem"] = {"enabled": False}
    if memscope.enabled():
        try:
            mesh_axes = {
                str(ax): int(s) for ax, s in zip(
                    meta["mesh"].axis_names, meta["mesh"].devices.shape
                )
            }
        except Exception:  # noqa: BLE001 — identity only
            mesh_axes = {}
        mem_steps = [
            {
                "scope": "train",
                "reason": ev.get("reason"),
                "step": ev.get("step"),
                "live_bytes_before": ev["live_bytes_before"],
                "live_bytes_after": ev["live_bytes_after"],
                "step_down_bytes": (
                    ev["live_bytes_before"] - ev["live_bytes_after"]
                ),
            }
            for ev in reshape_events
            if ev.get("live_bytes_before") is not None
        ]
        mem_record = memscope.mem_record(
            strategy=meta["layout"],
            mesh=mesh_axes,
            scope_cell=mem_scope.cell(),
            budget=memscope.budget_cell(
                mem_scope.live_bytes_peak,
                mem_scope.live_bytes_baseline,
                source="first_sample_live_bytes",
            ),
            reshape_steps=mem_steps or None,
        )
        telemetry["mem"] = memscope.mem_cell(mem_record)
        try:
            from ddl25spring_tpu.obs import perfscope

            telemetry["mem"]["ledger"] = perfscope.append_ledger(
                mem_record, args.perf_ledger or perfscope.DEFAULT_LEDGER
            )
            if args.obs_dir:
                telemetry["mem"]["mem_json"] = memscope.write_run_mem(
                    mem_record, args.obs_dir
                )
        except OSError as e:  # a read-only FS must not kill the line
            telemetry["mem"]["ledger_error"] = str(e)

    # drain the last async checkpoint and finalize the manifest BEFORE
    # the end-of-run flight dump, so the dump's meta names the final
    # durable step (close is idempotent — the shutdown chain would have
    # run it anyway on a crash)
    if saver is not None:
        saver.close()
        telemetry["resume"] = {
            "start_step": start_step,
            **({"resumed_from_step": start_step - 1} if start_step else {}),
            **({"steps_replayed": replayed} if replayed is not None else {}),
            # honesty flag: the run was already done when it resumed —
            # the floor re-ran a minimal window just to print a metric
            **({"resumed_past_end": True} if resumed_past_end else {}),
            "save_every": args.save_every,
            "ckpt_dir": ckpt_dir,
            "saves": saver.saves,
            "saves_skipped": saver.skipped,
            # the elastic-vs-relaunch A/B facts (ft/elastic.py): the
            # in-process reshape count + walls on the elastic side, the
            # entry->restored wall on the relaunch side — steps lost
            # ride total_steps_lost either way (0 for a reshape, the
            # died_at - durable gap for a relaunch, merged by the retry
            # parent)
            **({
                "reshapes": len(reshape_events),
                "reshape": reshape_events,
                "reshape_wall_s": round(
                    sum(e["wall_s"] for e in reshape_events), 3
                ),
                "recovery_wall_s": round(
                    sum(e["wall_s"] for e in reshape_events), 3
                ),
                "total_steps_lost": sum(
                    e["steps_lost"] for e in reshape_events
                ),
            } if reshape_events else {}),
            **({
                "recovery_wall_s": recovery_wall_s,
            } if recovery_wall_s is not None and not reshape_events
              else {}),
        }

    # runtime-health cell: sentinel state + flight-recorder facts, and a
    # flight.json in the run dir so obs_report's Health section (and any
    # post-mortem) reads the same artifact a crash would have left
    from ddl25spring_tpu.obs import sentinels as _sentinels

    _snap = obs.flight.snapshot()
    health = {
        "sentinels": _sentinels.enabled(),
        "policy": _sentinels.policy(),
        # cumulative counter, not a ring recount: a violation hundreds
        # of steps back must still show after the ring evicted it
        "violations": _snap["violations"],
        "stalls": _snap["stalls"],
        "flight_records": _snap["recorded"],
    }
    if args.obs_dir:
        health["flight_dump"] = obs.flight.dump(reason="end_of_run")
    telemetry["health"] = health

    # graft-goodput (PR 20): close this attempt's badput decomposition.
    # Watchdog stall idle rides as seconds-only (its span overlaps the
    # step that eventually completed); everything never measured
    # (imports, FedAvg, the h2d probe, perfscope) is the honest
    # ``other`` residual.  A retry child's doc is the attempt view the
    # parent merges into the lineage view; an in-process run (plain CPU
    # smoke) is its own one-attempt lineage and appends its own ledger
    # row.
    for _r in obs.flight.last():
        if _r.get("kind") == "stall" and isinstance(
            _r.get("idle_s"), (int, float)
        ):
            gp_meter.add_seconds("stall", _r["idle_s"])
    try:
        gp_mesh = {
            str(ax): int(s) for ax, s in zip(
                meta["mesh"].axis_names, meta["mesh"].devices.shape
            )
        }
    except Exception:  # noqa: BLE001 — identity only
        gp_mesh = {}
    attempt_goodput = gp_meter.finalize(
        scope="train_attempt", strategy=meta["layout"], mesh=gp_mesh,
    )
    telemetry["goodput"] = goodput_mod.goodput_cell(attempt_goodput)
    if args.obs_dir:
        goodput_mod.write_run_goodput(attempt_goodput, args.obs_dir)
    if own_lineage:
        try:
            from ddl25spring_tpu.obs import perfscope

            telemetry["goodput"]["ledger"] = perfscope.append_ledger(
                goodput_mod.ledger_row(
                    attempt_goodput, strategy=meta["layout"],
                    mesh=gp_mesh, host=perfscope.host_fingerprint(),
                ),
                args.perf_ledger or perfscope.DEFAULT_LEDGER,
            )
        except OSError as e:  # a read-only FS must not kill the line
            telemetry["goodput"]["ledger_error"] = str(e)

    primary_mode = (
        f"{ds.input_mode}-scan{K}" if multi is not None else ds.input_mode
    )
    single_line = [
        {
            "input": ds.input_mode,
            "value": round(sps_chip_single, 1),
            "unit": "samples/sec/chip",
            "note": "one step per dispatch; the delta vs the primary "
                    "is the measured per-dispatch tunnel overhead",
        },
    ] if sps_chip_single is not None else []
    print(report_line(
        meta["layout"], sps_chip, primary_mode, frac, achieved_tf,
        data=ds.provenance,
        topology=meta["topology"],
        chip=f"{meta['device'].device_kind} x{n_chips}",
        flops_per_step=flops_step,
        scan_steps=K,
        peak_tflops_per_chip=peak / 1e12 if peak else None,
        h2d_mib_per_s=round(h2d_mib_s, 1),
        # the effective grad-bucket threshold (DDL25_BUCKET_BYTES-aware)
        # so sweep results compare like-for-like across runs
        bucket_bytes=meta.get("bucket_bytes"),
        telemetry=telemetry,
        secondary=single_line + [
            {
                "input": feed.input_mode,
                "value": round(sps_chip_stream, 1),
                "unit": "samples/sec/chip",
                # only claim link-bound streaming when the native loader
                # actually streamed; on NativeLoaderUnavailable this run
                # degraded to the fixed batch and says so via input_mode
                **({"note": "bounded by the tunneled host->device link "
                            f"(~{h2d_mib_s:.0f} MiB/s here; GiB/s on a "
                            "TPU VM)"}
                   if feed.streaming else
                   {"note": "native loader unavailable; fell back to the "
                            "fixed device-resident batch"}),
            },
            {
                "input": "fixed-device-batch",
                "value": round(sps_chip_fixed, 1),
                "unit": "samples/sec/chip",
            },
        ] + fedavg_line,
    ))

    feed.close()


if __name__ == "__main__":
    main()
