"""Headline benchmark: CIFAR-10 ResNet-18 training throughput per chip.

Driver contract: print ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.
Baseline: BASELINE.json north star, >= 5,000 samples/sec/chip for DP(+PP)
ResNet-18/CIFAR-10.

Runs the DP train step over all available devices (on this image: the one
real TPU chip; the metric is per-chip so the number is mesh-size invariant).
bf16 compute, fp32 params/loss — the MXU-native configuration.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import optax

from ddl25spring_tpu.data.cifar10 import load_cifar10
from ddl25spring_tpu.models.resnet import ResNet18
from ddl25spring_tpu.ops.losses import cross_entropy_logits
from ddl25spring_tpu.parallel.dp import make_dp_train_step
from ddl25spring_tpu.utils.mesh import make_mesh

BASELINE_SAMPLES_PER_SEC_PER_CHIP = 5_000.0


def main(per_chip_batch: int = 1024, steps: int = 20, warmup: int = 3) -> None:
    devices = jax.devices()
    n = len(devices)
    mesh = make_mesh(devices, data=n)
    batch_size = per_chip_batch * n

    model = ResNet18(norm="group", dtype=jnp.bfloat16)
    data = load_cifar10(n_train=batch_size, n_test=8)
    # real CIFAR-10 caps at 50k rows; clamp to what loaded, divisible by n
    batch_size = (min(batch_size, len(data["x_train"])) // n) * n
    x = jnp.asarray(data["x_train"][:batch_size])
    y = jnp.asarray(data["y_train"][:batch_size])

    params = model.init(jax.random.PRNGKey(0), x[:8])["params"]

    def loss_fn(p, batch, key):
        xb, yb = batch
        logits = model.apply({"params": p}, xb.astype(jnp.bfloat16), train=True)
        return cross_entropy_logits(logits, yb)

    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)
    step = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)

    key = jax.random.PRNGKey(1)
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, (x, y), key)
    # force completion via host transfer: on this image's tunneled TPU
    # platform block_until_ready does not actually block
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, (x, y), key)
    float(loss)  # the step chain is data-dependent through params
    dt = time.perf_counter() - t0

    sps_per_chip = steps * batch_size / dt / n
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet18_dp_samples_per_sec_per_chip",
                "value": round(sps_per_chip, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(
                    sps_per_chip / BASELINE_SAMPLES_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
