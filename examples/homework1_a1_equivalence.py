#!/usr/bin/env python
"""Homework 1, part A1 — FedSGD-with-gradients == FedSGD-with-weights.

The reference's strongest correctness idea (SURVEY §4): running FedSGD by
shipping *gradients* must match running it by shipping *weights* — i.e.
``FedAvgServer`` with full-batch clients and one local epoch — to within
0.02% test accuracy per round (``lab/series01.ipynb`` cells 9-12; blank
assignment ``lab/homework-1.ipynb`` cell 9).

Both servers here are vmapped-client TPU implementations; the equivalence
holds because one full-batch SGD step followed by weighted weight-averaging
is linear in the gradients.  Run: ``python examples/homework1_a1_equivalence.py
[--rounds 10] [--clients 10]``.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from ddl25spring_tpu.fl import FedAvgServer, FedSgdGradientServer  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--clients", type=int, default=10)
    ap.add_argument("--fraction", type=float, default=0.1)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=10)  # homework-mandated seed
    ap.add_argument("--n-train", type=int, default=0,
                    help="subsample the train set (0 = full 60k); the "
                         "equivalence holds at any size")
    ap.add_argument("--force-cpu-devices", type=int, default=0,
                    metavar="N", help="simulate an N-device CPU mesh")
    args = ap.parse_args(argv)

    from ddl25spring_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.force_cpu_devices)

    data = None
    if args.n_train:
        from ddl25spring_tpu.data.mnist import load_mnist

        data = load_mnist(n_train=args.n_train, n_test=2000)
        print(f"# reduced dataset: n_train={args.n_train}, n_test=2000")

    common = dict(
        nr_clients=args.clients,
        client_fraction=args.fraction,
        lr=args.lr,
        seed=args.seed,
        data=data,
    )
    # scenario per series01.ipynb cell 12: weights variant = FedAvg with
    # batch_size=len(data) (B=-1) and E=1
    grad_server = FedSgdGradientServer(
        batch_size=-1, nr_local_epochs=1, **common
    )
    weight_server = FedAvgServer(batch_size=-1, nr_local_epochs=1, **common)

    print(f"{'round':>5} {'grad acc':>9} {'weight acc':>10} {'|delta|':>8}")
    worst = 0.0
    for r in range(args.rounds):
        grad_server.round(r)
        weight_server.round(r)
        ga = grad_server.test_accuracy()
        wa = weight_server.test_accuracy()
        worst = max(worst, abs(ga - wa))
        print(f"{r:>5} {ga:>9.4f} {wa:>10.4f} {abs(ga - wa):>8.5f}")

    tol = 2e-4  # the homework's 0.02%
    verdict = "PASS" if worst <= tol else "FAIL"
    print(f"max |delta| = {worst:.6f} (tolerance {tol}) -> {verdict}")
    return 0 if worst <= tol else 1


if __name__ == "__main__":
    raise SystemExit(main())
