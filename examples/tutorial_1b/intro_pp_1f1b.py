#!/usr/bin/env python
"""Tutorial 1b PP — 1F1B single-batch pipeline, TPU-native.

The reference (``lab/tutorial_1b/PP/1F1B/intro_PP_1F1B.py:27-95``) chains
three OS processes: rank0 ``embed -> send``, rank1 ``recv -> fwd -> send``,
rank2 ``fwd -> loss -> backward``, with boundary grads flowing back through
``send(inp.grad)`` / ``out.backward(recv)``.  Here the same 3-stage
single-batch (M=1) schedule is ONE jitted program:
:func:`ddl25spring_tpu.parallel.pipeline.make_pipeline_train_step` with
``schedule="1f1b"`` — the hand-rolled backward walks the cotangent across
stages via a reverse ``ppermute``, exactly the reference's grad chain, with
the activation stash bounded at ``2S-1`` stage inputs.

Run: ``python examples/tutorial_1b/intro_pp_1f1b.py --force-cpu-devices 3``
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=3)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--lr", type=float, default=8e-4)
    ap.add_argument("--microbatches", type=int, default=1,
                    help="1 = the reference's single-batch chain; raise it "
                         "for the steady-state interleaved schedule")
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N")
    args = ap.parse_args(argv)

    from ddl25spring_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.force_cpu_devices)

    import jax
    import jax.numpy as jnp
    import optax

    from ddl25spring_tpu.data.tinystories import TinyStories
    from ddl25spring_tpu.data.tokenizer import get_tokenizer
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_staged_params,
    )
    from ddl25spring_tpu.utils.config import LlamaConfig
    from ddl25spring_tpu.utils.mesh import make_mesh

    devices = jax.devices()
    tok = get_tokenizer()
    cfg = LlamaConfig(
        vocab_size=tok.vocab_size, dmodel=288, num_heads=6, n_layers=6,
        ctx_size=args.seq_len,
        dtype="bfloat16" if devices[0].platform == "tpu" else "float32",
    )
    S = max(s for s in (3, 2, 1)
            if s <= len(devices) and cfg.n_layers % s == 0)
    mesh = make_mesh(devices[:S], stage=S)
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    staged = shard_staged_params(llama.split_blocks_for_stages(params, S), mesh)
    tx = optax.adam(args.lr)
    opt_state = tx.init(staged)
    step = make_pipeline_train_step(
        cfg, tx, mesh, args.microbatches, schedule="1f1b"
    )
    ds = iter(TinyStories(tok, batch_size=args.batch, seq_l=args.seq_len))
    print(f"1F1B pipeline: {S} stages, M={args.microbatches} "
          f"(reference: 3 ranks, single batch)")
    for it in range(args.iters):
        staged, opt_state, loss = step(staged, opt_state, jnp.asarray(next(ds)))
        print(f"iter {it:3d}  loss {float(loss):.4f}", flush=True)


if __name__ == "__main__":
    main()
