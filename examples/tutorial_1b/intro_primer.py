#!/usr/bin/env python
"""Tutorial 1b primer — centralized LLaMA training, TPU-native.

The reference primer (``lab/tutorial_1b/primer/intro.py:23-33``) is the
minimal train loop: ``next(iter_ds) -> net(x) -> causalLLMLoss -> backward
-> Adam.step`` on one device.  Here that is one jitted step from
:func:`ddl25spring_tpu.parallel.dp.make_train_step` over the in-tree LLaMA
at the workload constants (dmodel=288, 6 heads, 6 layers, ctx 256 —
``lab/s01_b1_microbatches.py:21-24``).

Run: ``python examples/tutorial_1b/intro_primer.py [--force-cpu-devices 1]``
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=3)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--lr", type=float, default=8e-4)
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N")
    args = ap.parse_args(argv)

    from ddl25spring_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.force_cpu_devices)

    import jax
    import jax.numpy as jnp
    import optax

    from ddl25spring_tpu.data.tinystories import TinyStories
    from ddl25spring_tpu.data.tokenizer import get_tokenizer
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.ops.losses import causal_lm_loss
    from ddl25spring_tpu.parallel.dp import make_train_step
    from ddl25spring_tpu.utils.config import LlamaConfig

    tok = get_tokenizer()
    cfg = LlamaConfig(
        vocab_size=tok.vocab_size, dmodel=288, num_heads=6, n_layers=6,
        ctx_size=args.seq_len,
        dtype="bfloat16" if jax.devices()[0].platform == "tpu" else "float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    def loss_fn(p, tokens, key):
        return causal_lm_loss(llama.llama_forward(p, tokens, cfg), tokens)

    step = make_train_step(loss_fn, tx)
    ds = iter(TinyStories(tok, batch_size=args.batch, seq_l=args.seq_len))
    for it in range(args.iters):
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(next(ds)), jax.random.PRNGKey(it)
        )
        print(f"iter {it:3d}  loss {float(loss):.4f}", flush=True)


if __name__ == "__main__":
    main()
