#!/usr/bin/env python
"""Tutorial 1b DP — gradient aggregation, TPU-native.

The reference (``lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:41-68``)
runs one process per rank: after ``backward()`` it flattens all grads,
``all_reduce(SUM)`` over gloo, unflattens, divides by world size, steps.
Here the whole world is ONE jitted SPMD program from
:func:`ddl25spring_tpu.parallel.dp.make_dp_train_step`: the batch is
sharded over the mesh ``data`` axis and the grad ``pmean`` inside the step
IS the all_reduce+divide, riding ICI instead of gloo (no flattening — XLA
fuses the collective over the pytree).

Run: ``python examples/tutorial_1b/intro_dp_ga.py --force-cpu-devices 2``
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--per-replica-batch", type=int, default=3)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--lr", type=float, default=8e-4)
    ap.add_argument("--force-cpu-devices", type=int, default=0, metavar="N")
    args = ap.parse_args(argv)

    from ddl25spring_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.force_cpu_devices)

    import jax
    import jax.numpy as jnp
    import optax

    from ddl25spring_tpu.data.tinystories import TinyStories
    from ddl25spring_tpu.data.tokenizer import get_tokenizer
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.ops.losses import causal_lm_loss
    from ddl25spring_tpu.parallel.dp import make_dp_train_step
    from ddl25spring_tpu.utils.config import LlamaConfig
    from ddl25spring_tpu.utils.mesh import make_mesh

    devices = jax.devices()
    n = len(devices)
    mesh = make_mesh(devices, data=n)
    tok = get_tokenizer()
    cfg = LlamaConfig(
        vocab_size=tok.vocab_size, dmodel=288, num_heads=6, n_layers=6,
        ctx_size=args.seq_len,
        dtype="bfloat16" if devices[0].platform == "tpu" else "float32",
    )
    params = llama.init_llama_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    def loss_fn(p, tokens, key):
        return causal_lm_loss(llama.llama_forward(p, tokens, cfg), tokens)

    step = make_dp_train_step(loss_fn, tx, mesh, per_shard_rng=False)
    # one global stream sharded by the step's in_spec — the mesh analogue of
    # the reference's disjoint skip=rank*N streams (intro_DP_GA.py:29)
    batch = args.per_replica_batch * n
    ds = iter(TinyStories(tok, batch_size=batch, seq_l=args.seq_len))
    print(f"DP gradient aggregation over mesh(data={n})")
    for it in range(args.iters):
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(next(ds)), jax.random.PRNGKey(it)
        )
        print(f"iter {it:3d}  loss {float(loss):.4f}", flush=True)


if __name__ == "__main__":
    main()
